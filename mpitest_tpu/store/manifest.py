"""Journaled spill manifests — the durability anchor of the external sort.

A killed process used to lose the whole external sort: completed runs
sat on disk, but nothing durable said *which* files were finished runs
of *which* dataset, so a restart could only re-sort from scratch (and
the orphaned files leaked forever).  This module is the missing record:
one append-only JSONL **journal** per external sort, keyed by the
caller's dataset id, living beside the runs it describes
(``<spill_dir>/<dataset>.mfst``).

Commit protocol (the classic write-ahead discipline):

1. the run's files are made durable first — the streaming writer
   (``store/runs.py``, ``durable=True``) writes ``*.tmp`` names,
   ``fsync``\\ s them, publishes with ``os.replace`` and ``fsync``\\ s
   the directory, so a run is either fully present or invisible;
2. only then does :meth:`ManifestWriter.commit_run` append one JSON
   line (chunk index, path, count, fingerprint, ``format_version``)
   and ``flush + fsync`` the journal.

A crash therefore leaves at most one torn tail line; everything before
it names runs that provably hit disk.  Replay (:func:`load`) skips
torn/garbage lines **loudly** (a warning + ``skipped_lines``), treats
duplicate chunk entries last-wins (a resumed sort re-commits corrected
runs), and raises the typed
:class:`~mpitest_tpu.store.runs.RunFormatError` — naming both versions
— when the journal was written by a ``format_version`` this build
cannot read: an upgraded binary must never silently mis-parse an old
store dataset.

The journal itself is created atomically (write-temp → fsync →
``os.replace`` → fsync(dir)), so a half-written *new* journal can never
shadow a complete old one.  sortlint SL014 fences ``.mfst`` opens into
this module the same way run-file opens are fenced into
``store/runs.py``.
"""

from __future__ import annotations

import json
import os
import warnings
from dataclasses import dataclass, field

from mpitest_tpu import faults
from mpitest_tpu.models.verify import Fingerprint
from mpitest_tpu.store import runs as runlib

#: Journal schema tag (first field of every line).
MANIFEST_SCHEMA = "sortmfst1"

#: Journal filename suffix (``<dataset>.mfst`` in the spill dir).
MANIFEST_SUFFIX = ".mfst"


def manifest_path(spill_dir: str, dataset: str) -> str:
    """The journal path for ``dataset`` under ``spill_dir``."""
    return os.path.join(spill_dir, f"{dataset}{MANIFEST_SUFFIX}")


@dataclass(frozen=True)
class ManifestRun:
    """One committed run as recorded in the journal."""

    chunk: int                # source chunk index behind the run
    path: str                 # the .run key file
    n: int
    payload_width: int
    fingerprint: Fingerprint
    disk_bytes: int
    format_version: int


@dataclass
class Manifest:
    """Replayed journal state: the begin record + every committed run
    that survived replay (torn/garbage lines skipped loudly)."""

    path: str
    dataset: str
    dtype: str
    n: int | None             # total records (None = unknown at begin)
    payload_width: int
    format_version: int
    chunk_elems: int          # partition chunking the runs were cut at
    algorithm: str
    budget: int
    fanin: int
    runs: list[ManifestRun] = field(default_factory=list)
    #: torn / unparseable journal lines skipped during replay — the
    #: loud part of "skipped loudly" (also a warning per line).
    skipped_lines: int = 0


def _fp_fields(fp: Fingerprint) -> dict:
    return {"count": fp.count, "xors": list(fp.xors),
            "sums": list(fp.sums)}


def _fp_from(obj: dict) -> Fingerprint:
    return Fingerprint(int(obj["count"]),
                       tuple(int(v) for v in obj["xors"]),
                       tuple(int(v) for v in obj["sums"]))


def _check_version(ver: object, path: str) -> int:
    ver = int(ver) if isinstance(ver, (int, float)) else -1
    if ver not in runlib.COMPAT_FORMAT_VERSIONS:
        raise runlib.RunVersionError(
            f"spill manifest {path!r} was written at format_version "
            f"{ver}; this build reads "
            f"{runlib.COMPAT_FORMAT_VERSIONS} and writes "
            f"{runlib.RUN_FORMAT_VERSION}")
    return ver


def load(path: str) -> Manifest | None:
    """Replay a journal.  Returns ``None`` when no journal exists or it
    holds no readable ``begin`` record; raises the typed
    :class:`~mpitest_tpu.store.runs.RunVersionError` (naming both
    versions) when the journal's ``format_version`` is unreadable.
    Torn / garbage lines are skipped loudly, duplicates last-wins."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return None
    head: Manifest | None = None
    by_chunk: dict[int, ManifestRun] = {}
    skipped = 0
    lines = raw.split(b"\n")
    #: a non-empty final segment has no newline — a torn tail write
    torn_tail = lines[-1] != b""
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        is_tail = i == len(lines) - 1 and torn_tail
        try:
            obj = json.loads(line.decode("utf-8"))
            if not isinstance(obj, dict) or \
                    obj.get("v") != MANIFEST_SCHEMA:
                raise ValueError(f"bad schema tag {obj!r:.64}")
            kind = obj.get("kind")
            if kind == "begin":
                ver = _check_version(obj.get("format_version"), path)
                head = Manifest(
                    path=path, dataset=str(obj["dataset"]),
                    dtype=str(obj["dtype"]),
                    n=(int(obj["n"]) if obj.get("n") is not None
                       else None),
                    payload_width=int(obj["payload_width"]),
                    format_version=ver,
                    chunk_elems=int(obj["chunk_elems"]),
                    algorithm=str(obj.get("algorithm", "radix")),
                    budget=int(obj.get("budget", 0)),
                    fanin=int(obj.get("fanin", 0)))
            elif kind == "run":
                ver = _check_version(obj.get("format_version"), path)
                mr = ManifestRun(
                    chunk=int(obj["chunk"]), path=str(obj["path"]),
                    n=int(obj["n"]),
                    payload_width=int(obj["payload_width"]),
                    fingerprint=_fp_from(obj),
                    disk_bytes=int(obj.get("disk_bytes", 0)),
                    format_version=ver)
                by_chunk[mr.chunk] = mr
            else:
                raise ValueError(f"unknown record kind {kind!r}")
        except runlib.RunVersionError:
            raise
        except (ValueError, KeyError, TypeError, UnicodeDecodeError) as e:
            skipped += 1
            warnings.warn(
                f"spill manifest {path!r}: skipping "
                f"{'torn tail' if is_tail else 'garbage'} journal "
                f"line {i + 1} ({e})", RuntimeWarning, stacklevel=2)
    if head is None:
        if skipped:
            warnings.warn(
                f"spill manifest {path!r}: no readable begin record "
                f"({skipped} line(s) skipped) — ignoring the journal",
                RuntimeWarning, stacklevel=2)
        return None
    head.runs = [by_chunk[c] for c in sorted(by_chunk)]
    head.skipped_lines = skipped
    return head


def live_manifests(spill_dir: str) -> list[Manifest]:
    """Every replayable journal under ``spill_dir`` — the GC sweep's
    notion of *live*: any run a journal names must not be reclaimed.
    Unreadable journals are skipped (they stay subject to the age-gated
    sweep themselves)."""
    out: list[Manifest] = []
    try:
        names = os.listdir(spill_dir)
    except OSError:
        return out
    for fn in sorted(names):
        if not fn.endswith(MANIFEST_SUFFIX):
            continue
        try:
            m = load(os.path.join(spill_dir, fn))
        except (runlib.RunFormatError, OSError):
            continue
        if m is not None:
            out.append(m)
    return out


def run_record(chunk: int, info: "runlib.RunInfo") -> dict:
    """The journal line (as a dict) for one committed run."""
    rec = {"v": MANIFEST_SCHEMA, "kind": "run", "chunk": int(chunk),
           "path": info.path, "n": info.n,
           "payload_width": info.payload_width,
           "disk_bytes": info.disk_bytes,
           "format_version": runlib.RUN_FORMAT_VERSION}
    rec.update(_fp_fields(info.fingerprint))
    return rec


class ManifestWriter:
    """The append side of the journal.  Construction atomically
    replaces any prior journal for the dataset with a fresh ``begin``
    record (plus one ``run`` line per already-validated resumed run —
    a resumed sort's journal is self-contained, never a diff against
    the old one); :meth:`commit_run` appends + ``fsync``\\ s one line
    per newly committed run.

    The ``manifest_torn`` fault site fires in :meth:`commit_run`: the
    line's tail bytes never reach the journal (the crashed-mid-append
    shape replay must skip loudly)."""

    def __init__(self, spill_dir: str, dataset: str, *, dtype: str,
                 n: int | None, payload_width: int, algorithm: str,
                 chunk_elems: int, budget: int, fanin: int,
                 resumed: "list[ManifestRun] | None" = None) -> None:
        os.makedirs(spill_dir, exist_ok=True)
        self.dataset = dataset
        self.path = manifest_path(spill_dir, dataset)
        self._dir = spill_dir
        begin = {"v": MANIFEST_SCHEMA, "kind": "begin",
                 "dataset": dataset, "dtype": dtype, "n": n,
                 "payload_width": int(payload_width),
                 "algorithm": algorithm,
                 "chunk_elems": int(chunk_elems), "budget": int(budget),
                 "fanin": int(fanin),
                 "format_version": runlib.RUN_FORMAT_VERSION}
        lines = [json.dumps(begin, separators=(",", ":"))]
        for mr in resumed or ():
            rec = {"v": MANIFEST_SCHEMA, "kind": "run",
                   "chunk": mr.chunk, "path": mr.path, "n": mr.n,
                   "payload_width": mr.payload_width,
                   "disk_bytes": mr.disk_bytes,
                   "format_version": mr.format_version}
            rec.update(_fp_fields(mr.fingerprint))
            lines.append(json.dumps(rec, separators=(",", ":")))
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(("\n".join(lines) + "\n").encode("utf-8"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        runlib.fsync_dir(self._dir)
        self._f = open(self.path, "ab")
        #: a fired manifest_torn left the journal without its newline —
        #: the next commit restores line framing first (the drill keeps
        #: exactly one bad line; a real crash's torn line is the last)
        self._torn = False

    def commit_run(self, chunk: int, info: "runlib.RunInfo") -> None:
        """Durably append one committed run's journal line.  MUST be
        called only after the run's own files are durable (the writer's
        ``durable=True`` commit) — the journal is the promise that the
        named files are complete."""
        line = json.dumps(run_record(chunk, info),
                          separators=(",", ":")).encode("utf-8")
        cut = faults.manifest_tear_cut(len(line))
        prefix = b"\n" if self._torn else b""
        if cut:
            self._f.write(prefix + line[:len(line) - cut])
            self._torn = True
        else:
            self._f.write(prefix + line + b"\n")
            self._torn = False
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass

    def delete(self) -> None:
        """Retire the journal (the sort finished — verified success or
        a typed failure whose runs were already deleted).  Only a crash
        leaves a journal behind, which is exactly the resume signal."""
        self.close()
        try:
            os.unlink(self.path)
        except OSError:
            pass
        runlib.fsync_dir(self._dir)
