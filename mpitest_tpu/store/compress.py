"""Spill-run block codec: order-preserving delta + bitpack compression.

Spill runs are SORTED key words — the best-case delta-coding input:
consecutive encoded keys differ by small non-negative amounts, so a
block of 64-bit "wide" values (the codec's msw/lsw uint32 planes
combined; lexicographic word order == numeric uint64 order) packs into
``bit_length(max delta)`` bits per key instead of 32/64.  This module
is the per-block codec behind the SORTRUN2 framing in store/runs.py:
pack one block -> (packed bytes, first value, delta width, checksum);
unpack is the exact mirror.  Deltas wrap mod 2^64, so ANY input block
round-trips — unsorted (corrupted-upstream) data costs width, never
correctness.

Two engines, bit-identical byte for byte (the fuzz leg of
``make sanitize-selftest`` and tests/test_store.py both hold them to
that):

* native — ``native/libspillz.so`` via ctypes (GIL released, so the
  read-ahead/write-behind threads of store/aio.py get real
  parallelism); built by ``make -C bench libspillz``;
* python — the numpy fallback below, the parity oracle and the
  always-available path.

Whether runs compress AT ALL is the registered knob
``SORT_SPILL_COMPRESS``: ``auto`` (default) compresses only when the
native library loads (never slow the spill path down on a box without
the .so), ``on`` forces compression (python codec if the library is
missing), ``off`` writes raw SORTBIN1-framed runs.  The engine in use
never changes bytes on disk — only who computes them.

The block checksum is a 32-bit fold of the VALUES (not the packed
bytes): each uint64 is avalanche-mixed (murmur3 finalizer) before an
XOR + wrapping-sum accumulate, halves mixed down at the end.  The
pre-mix matters — raw XOR+sum is blind to a 2^63 shift applied to an
even-length suffix (exactly what one high packed-bit flip produces);
the fuzzer found that, so both kernels mix first.
"""

from __future__ import annotations

import ctypes
import subprocess
import threading
from pathlib import Path

import numpy as np

from mpitest_tpu.utils import knobs

_REPO = Path(__file__).resolve().parents[2]
LIB_PATH = _REPO / "native" / "libspillz.so"

#: Must match SPZ_ABI_VERSION in native/spillz.h — a stale .so is
#: refused at load, never called into.
ABI_VERSION = 1

# status codes (native/spillz.h)
_SPZ_OK = 0
_SPZ_EBOUNDS = -1
_SPZ_EWIDTH = -2

#: Keys per compressed block (the SORTRUN2 header stamps the value the
#: writer used, so readers never depend on this constant matching).
#: 4096 keeps the per-block header overhead under 0.1% while every
#: block still decodes independently — the read-ahead granularity.
DEFAULT_BLOCK_ELEMS = 4096


_LOADED = False
_LIB: ctypes.CDLL | None = None
_LIB_ERR: str | None = None
#: guards the one-time load: concurrent first resolutions (parallel
#: spill writers, or a read-ahead thread racing the merge driver) must
#: both see the COMPLETED verdict, never a half-written pair.
_LOAD_LOCK = threading.Lock()


def _bind(lib: ctypes.CDLL) -> None:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.spz_abi_version.restype = ctypes.c_int
    lib.spz_abi_version.argtypes = []
    lib.spz_pack_block.restype = ctypes.c_longlong
    lib.spz_pack_block.argtypes = [
        u64p, ctypes.c_size_t, u8p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_uint32)]
    lib.spz_unpack_block.restype = ctypes.c_longlong
    lib.spz_unpack_block.argtypes = [
        u8p, ctypes.c_size_t, ctypes.c_size_t, ctypes.c_uint64,
        ctypes.c_int, u64p, ctypes.POINTER(ctypes.c_uint32)]


def _load() -> ctypes.CDLL | None:
    """Load (once) and ABI-check the codec library; None + a recorded
    reason on any failure — ``auto`` degrades to raw runs, the python
    engine stays available for reading existing compressed runs."""
    global _LOADED, _LIB, _LIB_ERR
    if _LOADED:
        return _LIB
    with _LOAD_LOCK:
        if _LOADED:  # another thread completed the load while we waited
            return _LIB
        lib: ctypes.CDLL | None = None
        err: str | None = None
        if not LIB_PATH.exists():
            err = f"{LIB_PATH} not built (run `make -C bench libspillz`)"
        else:
            try:
                lib = ctypes.CDLL(str(LIB_PATH))
                _bind(lib)
                got = int(lib.spz_abi_version())
                if got != ABI_VERSION:
                    err = (f"{LIB_PATH} has ABI v{got}, shim expects "
                           f"v{ABI_VERSION} (rebuild: `make -C bench "
                           "libspillz`)")
                    lib = None
            except (OSError, AttributeError) as e:
                # AttributeError: a stale .so missing a symbol dies
                # inside _bind() before the ABI stamp can be read —
                # same verdict (unusable library).
                err = (f"{LIB_PATH} failed to load: {e} "
                       "(rebuild: `make -C bench libspillz`)")
                lib = None
        _LIB, _LIB_ERR = lib, err
        _LOADED = True  # published LAST: readers never see a half-load
    return _LIB


def available() -> bool:
    """True iff the native library is present, loadable and ABI-matched."""
    return _load() is not None


def unavailable_reason() -> str | None:
    _load()
    return _LIB_ERR


def engine() -> str:
    """The codec engine for this process: ``"native"`` when the library
    loads, ``"python"`` otherwise.  Unlike the encode engine this is
    NOT knob-selected — ``SORT_SPILL_COMPRESS`` decides whether runs
    compress at all (see :func:`resolve_compress`); bytes on disk are
    engine-independent, so which engine computes them is pure speed."""
    return "native" if available() else "python"


def resolve_compress(mode: str | None = None) -> bool:
    """Resolve ``SORT_SPILL_COMPRESS`` (or an explicit ``mode``) to the
    writer's decision: True == write SORTRUN2 compressed runs."""
    if mode is None:
        mode = knobs.get("SORT_SPILL_COMPRESS")
    if mode == "off":
        return False
    if mode == "on":
        return True
    return available()  # auto: only when the fast engine is present


def build(quiet: bool = True) -> bool:
    """Best-effort build of the codec library (`make -C bench libspillz`)
    — the test suite's fixture hook; selftests go through the Makefile."""
    global _LOADED, _LIB, _LIB_ERR
    r = subprocess.run(
        ["make", "-C", str(_REPO / "bench"), "libspillz"],
        capture_output=quiet, text=True)
    with _LOAD_LOCK:  # a racing _load() must not republish a stale handle
        _LOADED, _LIB, _LIB_ERR = False, None, None  # force a re-probe
    return r.returncode == 0 and available()


# --------------------------------------------------------- wide <-> words

def words_to_wide(words: tuple[np.ndarray, ...]) -> np.ndarray:
    """Codec word planes (msw first) -> one uint64 "wide" array whose
    numeric order equals the planes' lexicographic order."""
    if len(words) == 1:
        return words[0].astype(np.uint64)
    return ((words[0].astype(np.uint64) << np.uint64(32))
            | words[1].astype(np.uint64))


def wide_to_words(wide: np.ndarray, n_words: int) -> tuple[np.ndarray, ...]:
    """Inverse of :func:`words_to_wide` (msw first)."""
    if n_words == 1:
        return (wide.astype(np.uint32),)
    return ((wide >> np.uint64(32)).astype(np.uint32),
            wide.astype(np.uint32))


# ------------------------------------------------------------ value fold

def _mix64(z: np.ndarray) -> np.ndarray:
    """Vectorized murmur3 finalizer (wrapping uint64 arithmetic)."""
    z = z.astype(np.uint64, copy=True)
    z ^= z >> np.uint64(33)
    z *= np.uint64(0xFF51AFD7ED558CCD)
    z ^= z >> np.uint64(33)
    z *= np.uint64(0xC4CEB9FE1A85EC53)
    z ^= z >> np.uint64(33)
    return z


def _fold(vals: np.ndarray) -> int:
    """The spz_fold rule of native/spillz.c, elementwise-vectorized:
    m = mix64(vals); x = XOR(m); s = sum(m) mod 2^64; halves mixed."""
    if vals.size == 0:
        return 0
    m = _mix64(vals)
    x = int(np.bitwise_xor.reduce(m))
    s = int(np.sum(m, dtype=np.uint64))
    v = x ^ (x >> 32) ^ s ^ (s >> 32)
    return v & 0xFFFFFFFF


def checksum_bytes(data: bytes) -> int:
    """32-bit fold of a raw byte block (payload blocks): zero-pad to a
    multiple of 8, view little-endian uint64, same value fold as keys."""
    if not data:
        return 0
    pad = (-len(data)) % 8
    if pad:
        data = data + b"\x00" * pad
    return _fold(np.frombuffer(data, dtype="<u8"))


# ------------------------------------------------------------ block codec

def pack_block(vals: np.ndarray,
               eng: str | None = None) -> tuple[bytes, int, int, int]:
    """Pack one block of wide (uint64) values.  Returns
    ``(packed, first, width, checksum)`` where ``packed`` holds the
    (n-1) wrapping deltas at ``width`` bits each, LSB-first, zero-padded
    to whole bytes — exactly ``ceil((n-1)*width/8)`` bytes.  Both
    engines return identical bytes on every input."""
    vals = np.ascontiguousarray(vals, dtype=np.uint64)
    n = int(vals.size)
    if n == 0:
        raise ValueError("pack_block: empty block (the run framing "
                         "never writes one)")
    if eng is None:
        eng = engine()
    if eng != "native":
        return _pack_python(vals)
    lib = _load()
    assert lib is not None, "engine() guards this path"
    cap = n * 8 + 8
    out = np.empty(cap, np.uint8)
    first = ctypes.c_uint64()
    width = ctypes.c_int()
    chk = ctypes.c_uint32()
    rc = int(lib.spz_pack_block(
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), n,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), cap,
        ctypes.byref(first), ctypes.byref(width), ctypes.byref(chk)))
    if rc < 0:  # unreachable with the cap above; refuse to write garbage
        raise ValueError(f"spz_pack_block failed: status {rc}")
    return (out[:rc].tobytes(), int(first.value), int(width.value),
            int(chk.value))


def _pack_python(vals: np.ndarray) -> tuple[bytes, int, int, int]:
    n = int(vals.size)
    first = int(vals[0])
    chk = _fold(vals)
    if n == 1:
        return b"", first, 0, chk
    deltas = vals[1:] - vals[:-1]  # uint64 wrapping, like the C kernel
    width = int(deltas.max()).bit_length()
    if width == 0:
        return b"", first, 0, chk
    shifts = np.arange(width, dtype=np.uint64)
    bits = ((deltas[:, None] >> shifts) & np.uint64(1)).astype(np.uint8)
    packed = np.packbits(bits.reshape(-1), bitorder="little")
    return packed.tobytes(), first, width, chk


def unpack_block(data: bytes, n: int, first: int, width: int,
                 eng: str | None = None) -> tuple[np.ndarray, int]:
    """Unpack one block: ``(values, checksum)`` reconstructed from the
    packed bytes and the block header's (n, first, width).  Raises
    ValueError on ANY framing inconsistency (width outside 0..64,
    ``len(data) != ceil((n-1)*width/8)``) from either engine — the
    caller types it as block corruption.  The returned checksum is
    folded from the RECONSTRUCTED values; the caller compares it
    against the stored one."""
    if n <= 0:
        raise ValueError(f"unpack_block: bad element count {n}")
    if eng is None:
        eng = engine()
    if eng != "native":
        return _unpack_python(data, n, first, width)
    lib = _load()
    assert lib is not None, "engine() guards this path"
    buf = np.frombuffer(data, np.uint8) if data else np.zeros(1, np.uint8)
    vals = np.empty(n, np.uint64)
    chk = ctypes.c_uint32()
    rc = int(lib.spz_unpack_block(
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(data), n,
        ctypes.c_uint64(first & 0xFFFFFFFFFFFFFFFF), width,
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        ctypes.byref(chk)))
    if rc == _SPZ_EWIDTH:
        raise ValueError(f"unpack_block: delta width {width} outside 0..64")
    if rc < 0:
        raise ValueError(
            f"unpack_block: {len(data)} packed bytes disagree with "
            f"(n={n}, width={width})")
    return vals, int(chk.value)


def _unpack_python(data: bytes, n: int, first: int,
                   width: int) -> tuple[np.ndarray, int]:
    if width < 0 or width > 64:
        raise ValueError(f"unpack_block: delta width {width} outside 0..64")
    need = ((n - 1) * width + 7) // 8
    if len(data) != need:
        raise ValueError(
            f"unpack_block: {len(data)} packed bytes disagree with "
            f"(n={n}, width={width})")
    f64 = np.uint64(first & 0xFFFFFFFFFFFFFFFF)
    vals = np.empty(n, np.uint64)
    vals[0] = f64
    if n > 1:
        if width == 0:
            vals[1:] = f64
        else:
            nbits = (n - 1) * width
            raw = np.frombuffer(data, np.uint8)
            bits = np.unpackbits(raw, count=nbits,
                                 bitorder="little").reshape(n - 1, width)
            deltas = np.zeros(n - 1, np.uint64)
            for j in range(width):
                deltas |= bits[:, j].astype(np.uint64) << np.uint64(j)
            vals[1:] = f64 + np.cumsum(deltas, dtype=np.uint64)
    return vals, _fold(vals)
