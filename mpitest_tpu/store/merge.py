"""Streamed k-way merge of sorted spill runs with bounded host memory.

The classic heap merge moves one record per Python-level comparison —
three orders of magnitude too slow for a memory-bound pipeline.  This
merge is **vectorized**: each run keeps a bounded read-ahead buffer of
encoded key words (+ payload words), and each round computes a *safe
boundary* — the lexicographic minimum, over every run with unread file
data, of the last key already buffered.  Any buffered key strictly
below that boundary is globally safe to emit (every unread key of run
``r`` is ≥ the last buffered key of ``r``, which is ≥ the boundary), so
the round concatenates those prefixes, sorts them once with
``np.lexsort`` (keyed by the key words plus ``(run, pos)`` tiebreaks —
the merge is **stable** across runs, matching the in-memory stable sort
bit for bit for records) and yields the result as one chunk.  Keys
*equal* to the boundary are streamed per run in ascending run order
(``_drain_equal``), refilling as needed, so a dup-heavy input — every
run one long plateau of the same key — merges in run order with the
same bounded buffers instead of forcing one buffer to swallow the whole
plateau.

Integrity: every chunk read back from disk is folded
(:func:`store.runs.run_fingerprint`); at run exhaustion the
accumulated fold must equal the run's sidecar — a mismatch (bad disk,
the injected ``spill_corrupt``) raises the typed
:class:`RunIntegrityError` naming the run, which the external driver
catches to re-spill that slice from source.  The ``merge_drop`` fault
site consumes whole output chunks BEFORE the caller sees (or folds)
them, modeling silent merge truncation — the external driver's
count/fingerprint comparison against the combined sidecars goes loud.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator

import numpy as np

from mpitest_tpu import faults
from mpitest_tpu.models.supervisor import SortIntegrityError
from mpitest_tpu.ops.keys import codec_for
from mpitest_tpu.store import runs as runlib


class RunIntegrityError(SortIntegrityError):
    """A run's read-back fold disagreed with its fingerprint sidecar.
    Carries the offending :class:`~mpitest_tpu.store.runs.RunInfo` so
    the external driver can blame and re-spill exactly that slice."""

    def __init__(self, info: "runlib.RunInfo", detail: str) -> None:
        super().__init__(detail)
        self.info = info


@dataclass
class _Cursor:
    """Read-ahead state of one run inside a merge."""

    info: runlib.RunInfo
    run_id: int
    chunks: Iterator
    #: buffered encoded key words (tuple of uint32 arrays, msw first)
    kw: tuple = ()
    #: buffered payload words (tuple of uint32 arrays; () = keys only)
    pw: tuple = ()
    #: global position (within the run) of the buffer's first element —
    #: the stable-merge `pos` tiebreak
    base: int = 0
    consumed_from_file: int = 0
    file_done: bool = False
    fold: "runlib.Fingerprint | None" = None
    _codec: object = None

    def __post_init__(self) -> None:
        self._codec = codec_for(self.info.dtype)

    @property
    def buffered(self) -> int:
        return int(self.kw[0].size) if self.kw else 0

    def refill(self) -> bool:
        """Append one more disk chunk to the buffer (folding it into
        the run's read-back fingerprint).  Returns False at EOF — and
        at EOF compares the accumulated fold against the sidecar,
        raising :class:`RunIntegrityError` on mismatch."""
        if self.file_done:
            return False
        try:
            keys, pay = next(self.chunks)
        except runlib.BlockIntegrityError as e:
            # a compressed block failed its framing/checksum mid-read
            # (ISSUE 20): surface it as the SAME typed blame the fold
            # mismatch raises, so the external driver's re-spill
            # recovery covers compressed corruption too
            raise RunIntegrityError(self.info, str(e)) from None
        except StopIteration:
            self.file_done = True
            fp = self.fold
            want = self.info.fingerprint
            if fp is None:
                ok = want.count == 0
            else:
                ok = fp == want
            if not ok:
                raise RunIntegrityError(
                    self.info,
                    f"run {self.info.path!r} read-back fingerprint "
                    "disagrees with its sidecar (disk corruption "
                    "between spill and merge)") from None
            return False
        from mpitest_tpu.models.records import payload_to_words

        arr = np.array(keys)
        kw = self._codec.encode(arr)
        pw = (payload_to_words(np.array(pay))
              if pay is not None else ())
        cfp = runlib.run_fingerprint(kw, pw)
        self.fold = cfp if self.fold is None else self.fold.combine(cfp)
        self.consumed_from_file += arr.size
        if self.kw:
            self.kw = tuple(np.concatenate([a, b])
                            for a, b in zip(self.kw, kw))
            self.pw = tuple(np.concatenate([a, b])
                            for a, b in zip(self.pw, pw))
        else:
            self.kw, self.pw = kw, pw
        return True

    def pop(self, m: int) -> tuple[tuple, tuple, np.ndarray]:
        """Remove the first ``m`` buffered records; returns their key
        words, payload words and global in-run positions."""
        pos = np.arange(self.base, self.base + m, dtype=np.uint32)
        kw = tuple(w[:m] for w in self.kw)
        pw = tuple(w[:m] for w in self.pw)
        self.kw = tuple(w[m:] for w in self.kw)
        self.pw = tuple(w[m:] for w in self.pw)
        self.base += m
        return kw, pw, pos


def _order_for(kws: tuple, rid: np.ndarray,
               pos: np.ndarray) -> np.ndarray:
    """Sort order of one merge round: lexicographic over the key words
    (msw first) with the stable ``(run, pos)`` tiebreaks.

    Host ``np.lexsort`` by default.  Under the fused local-sort engine
    (``SORT_LOCAL_ENGINE=radix_pallas*``, ISSUE 17) the round's inner
    loop runs on device instead — the rank-by-comparison kernel
    ``ops/radix_pallas.merge_order`` over the same planes, bit-identical
    because the (kws, rid, pos) key is unique per record.  The bounded
    read-ahead / safe-boundary logic stays up in :func:`merge_runs`
    either way; only the order computation moves.  Rounds above the
    kernel's O(n^2) envelope, and any device failure (loudly counted as
    a degrade), fall back to the host path — the merge must survive a
    dead backend exactly like the sort ladder's host rung.
    """
    from mpitest_tpu.utils import knobs

    eng = knobs.get("SORT_LOCAL_ENGINE")
    n = int(rid.size)
    if eng.startswith("radix_pallas") and 1 < n:
        from mpitest_tpu.ops import radix_pallas as rp

        if n <= rp.MERGE_MAX_ELEMS:
            try:
                import jax

                interpret = (eng == "radix_pallas_interpret"
                             or jax.default_backend() != "tpu")
                return np.asarray(rp.merge_order(
                    tuple(kws) + (rid, pos), interpret=interpret))
            except Exception as e:  # pragma: no cover - device loss
                import warnings

                warnings.warn(
                    "device merge-order kernel failed "
                    f"({type(e).__name__}: {e}); degrading this merge "
                    "to the host lexsort", RuntimeWarning)
    # np.lexsort: LAST key is primary -> (pos, rid, lsw..msw)
    return np.lexsort((pos, rid) + tuple(reversed(kws)))


def _lex_below(words: tuple, bound: tuple[int, ...],
               inclusive: bool) -> int:
    """Count of the buffer's prefix lexicographically < ``bound``
    (or <= with ``inclusive``).  The buffer is sorted, so the boolean
    mask is a prefix and its popcount is the split point."""
    n = int(words[0].size)
    if n == 0:
        return 0
    lt = np.zeros(n, bool)
    eq = np.ones(n, bool)
    for w, b in zip(words, bound):
        lt |= eq & (w < np.uint32(b))
        eq &= w == np.uint32(b)
    mask = (lt | eq) if inclusive else lt
    return int(np.count_nonzero(mask))


def _last_key(cur: _Cursor) -> tuple[int, ...]:
    return tuple(int(w[-1]) for w in cur.kw)


def _first_key(cur: _Cursor) -> tuple[int, ...]:
    return tuple(int(w[0]) for w in cur.kw)


def merge_runs(infos: list["runlib.RunInfo"], chunk_elems: int,
               io=None) -> Iterator[tuple[tuple, tuple]]:
    """Merge sorted runs, yielding ``(key_words, payload_words)``
    chunks in globally sorted (stable: key, then run, then in-run
    position) order.  Host memory is bounded by roughly
    ``len(infos) * chunk_elems`` records of buffer plus one output
    round.  Callers wanting a multi-pass (fan-in-limited) merge drive
    this through :func:`store.external` — this function merges every
    run it is handed in one pass.

    ``io`` (ISSUE 20) is an optional :class:`store.aio.MergeIO`: when
    given, each cursor's chunk stream comes from ``io.source(info,
    chunk_elems)`` — a read-ahead thread that decodes the NEXT disk
    block while this loop consumes the current one — instead of the
    synchronous :func:`store.runs.read_run_chunks`.  The chunk
    contents are identical either way; only the overlap changes."""
    if not infos:
        return
    chunk_elems = max(1, int(chunk_elems))
    cursors = [
        _Cursor(info=ri, run_id=i,
                chunks=(io.source(ri, chunk_elems) if io is not None
                        else runlib.read_run_chunks(ri, chunk_elems)))
        for i, ri in enumerate(infos)
    ]
    try:
        yield from _merge_cursors(cursors)
    finally:
        # close every chunk source (sync generators AND read-ahead
        # threads) even when the consumer abandons the merge mid-way
        for c in cursors:
            close = getattr(c.chunks, "close", None)
            if close is not None:
                close()


def _merge_cursors(cursors: list[_Cursor],
                   ) -> Iterator[tuple[tuple, tuple]]:
    for c in cursors:
        c.refill()
    out_idx = 0
    while True:
        for c in cursors:
            if not c.buffered and not c.file_done:
                c.refill()
        live = [c for c in cursors if c.buffered]
        if not live:
            return
        # safe boundary: lex-min of last-buffered keys over runs whose
        # FILE still has unread data (a fully-buffered run constrains
        # nothing — all its keys are visible)
        bounded = [c for c in live if not c.file_done]
        if not bounded:
            boundary = None            # everything visible: drain all
        else:
            boundary = min(_last_key(c) for c in bounded)
        pieces_kw: list[tuple] = []
        pieces_pw: list[tuple] = []
        pieces_rid: list[np.ndarray] = []
        pieces_pos: list[np.ndarray] = []
        total = 0
        for c in live:
            m = (c.buffered if boundary is None
                 else _lex_below(c.kw, boundary, inclusive=False))
            if m:
                kw, pw, pos = c.pop(m)
                pieces_kw.append(kw)
                pieces_pw.append(pw)
                pieces_rid.append(np.full(m, c.run_id, np.uint32))
                pieces_pos.append(pos)
                total += m
        if total:
            n_kw = len(pieces_kw[0])
            kws = tuple(np.concatenate([p[i] for p in pieces_kw])
                        for i in range(n_kw))
            n_pw = len(pieces_pw[0])
            pws = tuple(np.concatenate([p[i] for p in pieces_pw])
                        for i in range(n_pw))
            rid = np.concatenate(pieces_rid)
            pos = np.concatenate(pieces_pos)
            order = _order_for(kws, rid, pos)
            kws = tuple(w[order] for w in kws)
            pws = tuple(w[order] for w in pws)
            if not faults.should_drop_merge_chunk(out_idx, total):
                yield kws, pws
            out_idx += 1
            continue
        if boundary is None:
            continue  # drained everything visible; loop refills
        # plateau: every safe-emittable key equals the boundary.
        # Stream the == boundary records per run in ascending run id
        # (the stable tie order), refilling inside each drain so the
        # buffers stay bounded even when one run is a single plateau.
        emitted_any = False
        for c in sorted(live, key=lambda c: c.run_id):
            while True:
                m = _lex_below(c.kw, boundary, inclusive=True)
                if m:
                    emitted_any = True
                    kw, pw, _pos = c.pop(m)
                    if not faults.should_drop_merge_chunk(out_idx, m):
                        yield kw, pw
                    out_idx += 1
                # keep draining while the run may still hold == keys:
                # buffer exhausted with file data left, or the buffer
                # now starts above the boundary
                if c.buffered == 0:
                    if not c.refill():
                        break
                    continue
                if _first_key(c) > boundary:
                    break
                # buffered head == boundary still (m was limited by a
                # previous pop edge) — loop again
                if m == 0:
                    break
        if not emitted_any:
            # defensive: boundary came from a bounded run whose == keys
            # are all unread; force progress by refilling the min run
            for c in bounded:
                if _last_key(c) == boundary:
                    c.refill()
                    break
