"""Async spill IO for the merge phase: read-ahead + write-behind.

A synchronous external-sort merge alternates strictly between disk and
compute: read a block, decode it, merge it, encode the output, write
it, fsync — the disk idles while numpy runs and numpy idles while the
disk runs.  On the disk-bound configurations the spillperf gate models
(ISSUE 20), that alternation roughly doubles the wall clock.

This module overlaps the two, with the same bounded double-buffering
discipline as the streamed-ingest pipeline (``models/ingest.py``):

* :class:`ReadAhead` — one daemon thread per input run decodes the
  NEXT chunk (disk read + block decompression, both GIL-releasing in
  the native engine) while the merge consumes the current one, through
  a ``Queue(maxsize=2)``.  The thread puts a terminal ``None`` at EOF
  and the exception object itself on failure, so typed run-corruption
  errors (:class:`~mpitest_tpu.store.runs.BlockIntegrityError`)
  surface in the consumer exactly as the synchronous path raises them.
* :class:`WriteBehind` — one daemon thread drains output chunks into a
  :class:`~mpitest_tpu.store.runs.RunStreamWriter` (compression +
  throttle + fsync all behind the emit loop); writer errors are
  re-raised at the next ``append_words``/``close``.
* :class:`MergeIO` — owns the read-ahead threads of one merge (plus an
  optional write-behind), aggregates their disk-busy and consumer-
  stall intervals, and computes the **disk overlap** fraction the
  timeline/doctor layers surface: how much of the disk's busy time ran
  concurrently with merge compute.

Every thread here is registered in ``utils/thread_registry.py``
(roots ``spill-readahead`` / ``spill-writebehind``, ``jax_ok=False``)
and every lock carries a rank — threadlint walks this module like any
other.  Shutdown follows the ingest idiom: an abort event, bounded
``put(timeout=...)`` polls against it, and ``close()`` drains + joins
so an abandoned merge never leaks a wedged producer.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Iterator

from mpitest_tpu.store import runs as runlib
from mpitest_tpu.utils.spans import merge_intervals, overlap_seconds

#: Bounded hand-off depth: one chunk in flight + one buffered is what
#: makes this double (not unbounded) buffering — memory stays at
#: O(queue depth × chunk) per run, same as the synchronous path's
#: single chunk up to a small constant.
QUEUE_DEPTH = 2

#: Poll granularity of abortable queue puts (the ingest idiom: block
#: in small slices so an abort is honored within ~50 ms).
_PUT_POLL_S = 0.05

#: Joins are bounded — a wedged thread is reported, never waited on
#: forever (the drill-friendly failure mode is loud, not hung).
_JOIN_TIMEOUT_S = 10.0

#: Stalls shorter than this are queue bookkeeping, not waiting.
_STALL_FLOOR_S = 1e-6


def subtract_intervals(span: tuple[float, float],
                       busy: list[tuple[float, float]],
                       ) -> list[tuple[float, float]]:
    """``[span] - busy``: the parts of one interval NOT covered by a
    MERGED (sorted, disjoint) interval list — how the merge's compute
    time is derived from its wall span minus its consumer stalls."""
    t0, t1 = span
    out: list[tuple[float, float]] = []
    cur = t0
    for a, b in busy:
        if b <= cur:
            continue
        if a >= t1:
            break
        if a > cur:
            out.append((cur, min(a, t1)))
        cur = max(cur, b)
        if cur >= t1:
            return out
    if cur < t1:
        out.append((cur, t1))
    return out


class ReadAhead:
    """Iterator over one run's chunks, decoded one chunk ahead.

    Drop-in for :func:`store.runs.read_run_chunks` — same items, same
    exceptions — plus ``close()`` (idempotent; also invoked by
    ``merge_runs``'s cursor cleanup) and stall/IO interval stats."""

    def __init__(self, info: "runlib.RunInfo", chunk_elems: int) -> None:
        self.info = info
        self.chunk_elems = int(chunk_elems)
        #: (t0, t1) spans the worker spent in disk read + decode
        self.io_intervals: list[tuple[float, float]] = []
        #: (t0, t1) spans the CONSUMER waited on an empty queue
        self.stall_intervals: list[tuple[float, float]] = []
        self._lock = threading.Lock()
        self._q: queue.Queue = queue.Queue(maxsize=QUEUE_DEPTH)
        self._abort = threading.Event()
        self._closed = False
        self._thread = threading.Thread(
            target=self._worker, name="spill-readahead", daemon=True)
        self._thread.start()

    # -- producer side -------------------------------------------------

    def _put(self, item: object) -> bool:
        while not self._abort.is_set():
            try:
                self._q.put(item, timeout=_PUT_POLL_S)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self) -> None:
        try:
            chunks = runlib.read_run_chunks(self.info, self.chunk_elems)
            while True:
                t0 = time.perf_counter()
                try:
                    item = next(chunks)
                except StopIteration:
                    break
                t1 = time.perf_counter()
                with self._lock:
                    self.io_intervals.append((t0, t1))
                # the put-wait is NOT disk time: it is the consumer
                # lagging, excluded so overlap math sees real IO only
                if not self._put(item):
                    return
            self._put(None)
        except BaseException as e:  # re-raised at the consumer's next()
            self._put(e)

    # -- consumer side -------------------------------------------------

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        t0 = time.perf_counter()
        item = self._q.get()
        t1 = time.perf_counter()
        if t1 - t0 > _STALL_FLOOR_S:
            with self._lock:
                self.stall_intervals.append((t0, t1))
        if item is None:
            raise StopIteration
        if isinstance(item, BaseException):
            raise item
        return item

    def close(self) -> None:
        """Stop the worker, drain the queue, join — idempotent."""
        if self._closed:
            return
        self._closed = True
        self._abort.set()
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=_JOIN_TIMEOUT_S)

    def snapshot(self) -> tuple[list, list]:
        with self._lock:
            return list(self.io_intervals), list(self.stall_intervals)


class WriteBehind:
    """Run-writer facade that moves the disk work off the emit loop.

    ``append_words`` enqueues the chunk and returns immediately; the
    worker thread performs the real ``RunStreamWriter.append_words``
    (encode + compress + throttle + write).  A writer failure parks the
    exception and aborts the queue; it re-raises — with the original
    type — at the caller's next ``append_words`` or ``close``."""

    def __init__(self, writer: "runlib.RunStreamWriter") -> None:
        self.writer = writer
        self.io_intervals: list[tuple[float, float]] = []
        self.stall_intervals: list[tuple[float, float]] = []
        self._lock = threading.Lock()
        self._q: queue.Queue = queue.Queue(maxsize=QUEUE_DEPTH)
        self._abort = threading.Event()
        self._err: BaseException | None = None
        self._thread = threading.Thread(
            target=self._worker, name="spill-writebehind", daemon=True)
        self._thread.start()

    def _worker(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            kind, a, b = item
            t0 = time.perf_counter()
            try:
                if kind == "words":
                    self.writer.append_words(a, b)
                else:
                    self.writer.append(a, b)
            except BaseException as e:
                with self._lock:
                    self._err = e
                # unblock any producer stuck on a full queue
                self._abort.set()
                return
            t1 = time.perf_counter()
            with self._lock:
                self.io_intervals.append((t0, t1))

    def _raise_pending(self) -> None:
        with self._lock:
            err = self._err
            self._err = None
        if err is not None:
            raise err

    def _enqueue(self, item: tuple) -> None:
        self._raise_pending()
        t0 = time.perf_counter()
        while not self._abort.is_set():
            try:
                self._q.put(item, timeout=_PUT_POLL_S)
                t1 = time.perf_counter()
                if t1 - t0 > _STALL_FLOOR_S:
                    with self._lock:
                        self.stall_intervals.append((t0, t1))
                return
            except queue.Full:
                continue
        # abort set: the worker died — surface why
        self._raise_pending()
        raise RuntimeError("write-behind worker stopped")

    def append_words(self, key_words: tuple, payload_words: tuple,
                     ) -> None:
        self._enqueue(("words", key_words, payload_words))

    def append(self, keys, payload=None) -> None:
        self._enqueue(("rows", keys, payload))

    def close(self) -> "runlib.RunInfo":
        """Flush the queue, stop the worker, close the writer (final
        block flush + fsync/publish run on the CALLER, timed as disk
        work) and return the published :class:`RunInfo`."""
        self._raise_pending()
        while not self._abort.is_set():
            try:
                self._q.put(None, timeout=_PUT_POLL_S)
                break
            except queue.Full:
                continue
        self._thread.join(timeout=_JOIN_TIMEOUT_S)
        self._raise_pending()
        if self._thread.is_alive():  # pragma: no cover - wedge guard
            raise RuntimeError("write-behind worker failed to drain")
        t0 = time.perf_counter()
        info = self.writer.close()
        with self._lock:
            self.io_intervals.append((t0, time.perf_counter()))
        return info

    def abort(self) -> None:
        """Failed-merge cleanup: stop the worker, delete the partial."""
        self._abort.set()
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=_JOIN_TIMEOUT_S)
        self.writer.abort()

    def snapshot(self) -> tuple[list, list]:
        with self._lock:
            return list(self.io_intervals), list(self.stall_intervals)


class MergeIO:
    """The async-IO engine of ONE merge: read-ahead sources for its
    input runs, an optional write-behind sink, and the aggregated
    overlap stats the external driver stamps on its merge span."""

    def __init__(self) -> None:
        self.readers: list[ReadAhead] = []
        self.writers: list[WriteBehind] = []

    def source(self, info: "runlib.RunInfo", chunk_elems: int,
               ) -> ReadAhead:
        """Chunk iterator for ``merge_runs(..., io=self)``."""
        ra = ReadAhead(info, chunk_elems)
        self.readers.append(ra)
        return ra

    def wrap_writer(self, writer: "runlib.RunStreamWriter",
                    ) -> WriteBehind:
        wb = WriteBehind(writer)
        self.writers.append(wb)
        return wb

    def close(self) -> None:
        for ra in self.readers:
            ra.close()

    def stats(self, t0: float, t1: float) -> dict[str, float]:
        """Overlap accounting over the merge wall span ``[t0, t1]``.

        *disk* = union of every reader/writer IO interval.  *compute*
        = the wall span minus the union of consumer-side stalls (queue
        waits are neither disk nor compute).  ``disk_overlap`` is the
        concurrency fraction ``overlap / min(disk, compute)`` — 1.0
        means the scarcer activity was fully hidden behind the other,
        ~0 means the merge alternated (synchronous behavior)."""
        self.close()
        io_iv: list[tuple[float, float]] = []
        stall_iv: list[tuple[float, float]] = []
        for src in (*self.readers, *self.writers):
            io, stall = src.snapshot()
            io_iv.extend(io)
            stall_iv.extend(stall)
        disk = merge_intervals([(a, b) for a, b in io_iv if b > a])
        stalls = merge_intervals(
            [(a, b) for a, b in stall_iv if b > a])
        compute = subtract_intervals((t0, t1), stalls)
        total_disk = sum(b - a for a, b in disk)
        total_compute = sum(b - a for a, b in compute)
        ov = overlap_seconds(disk, compute)
        denom = min(total_disk, total_compute)
        frac = ov / denom if denom > 1e-9 else 0.0
        return {
            "disk_busy_s": total_disk,
            "overlap_s": ov,
            "disk_overlap": min(1.0, frac),
        }
