"""Out-of-core sorted-run store (ISSUE 15): spill runs, k-way merge,
and the external-sort driver that turns dataset size from an HBM limit
into a disk limit.

Exports are PEP 562 lazy (like ``serve/``): importing the package costs
nothing until a symbol is touched, so the client-side and lint surfaces
never pull jax.
"""

from __future__ import annotations

from typing import Any

_EXPORTS = {
    "RunFormatError": "mpitest_tpu.store.runs",
    "RunInfo": "mpitest_tpu.store.runs",
    "open_run": "mpitest_tpu.store.runs",
    "read_run_chunks": "mpitest_tpu.store.runs",
    "verify_run": "mpitest_tpu.store.runs",
    "write_run": "mpitest_tpu.store.runs",
    "merge_runs": "mpitest_tpu.store.merge",
    "external_sort": "mpitest_tpu.store.external",
    "external_sort_file": "mpitest_tpu.store.external",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str) -> Any:
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod), name)
