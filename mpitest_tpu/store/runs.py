"""Spill-run files: SORTBIN1-framed sorted runs + fingerprint sidecars.

One **run** is a sorted slice of a dataset persisted to disk so the
external sort (``store/external.py``) can exceed device/host memory:

* ``<name>.run`` — the sorted keys as an ordinary SORTBIN1 file (the
  exact framing ``utils/io.py`` writes and the native encode engine
  validates), so every existing reader — ``open_keys_mmap`` zero-copy
  slicing, the engine-dispatched header check, the CLI — works on a run
  unchanged.
* ``<name>.pay`` — the per-record payload bytes (record sorts only):
  a 16-byte ``SORTPAY1`` header carrying the payload width, then
  ``n * width`` raw bytes in key order.
* ``<name>.fpr.json`` — the fingerprint **sidecar**: record count,
  per-word XOR/sum folds (key words + payload words + the binding mix
  word, :func:`models.verify.fingerprint_records`) computed from the
  sorted host words BEFORE the bytes hit disk.  The sidecar is the
  run's integrity anchor: the merge folds every chunk it reads back and
  compares at run exhaustion, so bad disk bytes (or the injected
  ``spill_corrupt`` fault) are caught before they can ship.

Compressed runs (ISSUE 20) swap the framing, not the contract: a
``<name>.runz`` key file is ``SORTRUN2`` — the sorted keys' encoded
words delta-coded and bitpacked in fixed-size independently-decodable
blocks (``store/compress.py``), each with its own 24-byte header
(count, delta width, first value, packed length, checksum); the
payload section becomes ``SORTPAY2`` (same raw bytes, 8-byte per-block
headers).  The fingerprint sidecar STILL folds the decompressed words,
so integrity blame names the run identically, and a block whose
framing or checksum disagrees raises the typed
:class:`BlockIntegrityError` naming run + block — never silently-wrong
keys.  Whether new runs compress is the ``SORT_SPILL_COMPRESS`` knob;
readers dispatch on the file magic, so raw and compressed runs mix
freely in one merge.

This module is the ONE place run files are opened — sortlint rule
SL014 fences ad-hoc ``open()`` of spill paths everywhere else, so the
framing/sidecar contract cannot be quietly bypassed.

Typed errors: :class:`RunFormatError` (``ValueError``) for structural
garbage — bad magic, truncated payload, sidecar/key-count mismatch;
integrity (fingerprint) failures surface from the merge/external layer
as ``SortIntegrityError`` so the CLI's exit-code contract (exit 3)
holds for spilled sorts too.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass

import numpy as np

from mpitest_tpu import faults
from mpitest_tpu.models.verify import (Fingerprint, fingerprint_host,
                                       fingerprint_records)
from mpitest_tpu.ops.keys import codec_for
from mpitest_tpu.store import compress as blockz
from mpitest_tpu.utils import io as kio
from mpitest_tpu.utils import knobs

#: Payload-section magic (the key section reuses ``kio.BIN_MAGIC``).
PAY_MAGIC = b"SORTPAY1"
PAY_HEADER_LEN = 16

#: Compressed-run framing (ISSUE 20).  SORTRUN2 key header (16 bytes,
#: same length as SORTBIN1 so the version/kind offsets line up):
#: magic[8] | kind[1] | itemsize[1] | format_version[1] | n_words[1] |
#: block_elems u32 LE[4].  Each block: n u32 | width u8 | reserved[3] |
#: first u64 | packed_len u32 | checksum u32, then the packed bytes.
RUNZ_MAGIC = b"SORTRUN2"
RUNZ_HEADER_LEN = 16
RUNZ_BLOCK_HEADER_LEN = 24

#: Compressed payload section: magic[8] | width u32 LE | version[1] |
#: zeros[3]; blocks 1:1 with key blocks, each ``n u32 | checksum u32``
#: then ``n * width`` raw payload bytes.
PAY2_MAGIC = b"SORTPAY2"

#: Sidecar schema tag.
FP_SCHEMA = "sortfp1"

#: Run-framing format version (ISSUE 18), stamped into reserved byte 10
#: of the SORTBIN1 header and byte 12 of the SORTPAY1 header (both
#: engines validate only magic + kind + itemsize, so versioned runs
#: stay readable by every existing SORTBIN1 consumer), plus the sidecar
#: and the spill manifest.  Version 0 is the pre-versioning framing
#: (reserved bytes all zero) — still readable.
#: Version 2 (ISSUE 20) introduces the compressed SORTRUN2/SORTPAY2
#: framing; RAW runs also stamp 2 (the version names the writer
#: generation, the magic names the framing) and versions 0/1 stay
#: readable.
RUN_FORMAT_VERSION = 2
COMPAT_FORMAT_VERSIONS = (0, 1, 2)

#: Byte offsets of the version stamp inside the two 16-byte headers.
BIN_VERSION_OFF = 10
PAY_VERSION_OFF = 12


class RunFormatError(ValueError):
    """A run file (or its payload/sidecar) is structurally invalid —
    bad magic, truncation, or a count that disagrees with the sidecar.
    Always names the offending path."""


class RunVersionError(RunFormatError):
    """A run file / sidecar / manifest carries a ``format_version``
    this build cannot read.  Always names BOTH versions — the file's
    and ours — so an upgrade mismatch is diagnosable from the message
    alone.  A distinct type so crash-resume can re-sort around disk
    *damage* while still surfacing version skew typed: damage is
    recoverable from source, silent cross-version misreads are not."""


class BlockIntegrityError(RunFormatError):
    """One compressed block of a SORTRUN2/SORTPAY2 run is undecodable
    or fails its checksum — garbage framing fields, a torn body, or
    bytes that no longer fold to the stored block checksum.  Always
    names the run path AND the block index, so the merge's blame ladder
    (:class:`store.merge.RunIntegrityError`) can re-spill exactly the
    damaged run."""

    def __init__(self, path: str, block: int, detail: str) -> None:
        self.path = str(path)
        self.block = int(block)
        super().__init__(
            f"run file {path!r}: compressed block {block}: {detail}")


# --------------------------------------------------------- disk throttle
#
# SORT_SPILL_THROTTLE_MBPS simulates ONE disk of bounded bandwidth for
# the whole process: a module-level token bucket every spill read/write
# charges actual bytes moved against.  Shared state is the point — the
# read-ahead threads of store/aio.py each stream a different run, and
# per-thread throttles would multiply the simulated bandwidth by the
# merge fanin.  The sleep happens OUTSIDE the lock (threadlint TL003):
# the lock only computes this transfer's reservation window.

_THROTTLE_LOCK = threading.Lock()
_throttle_next = 0.0


def throttle_disk(nbytes: int) -> None:
    """Charge ``nbytes`` against the simulated spill-disk bandwidth
    (no-op when ``SORT_SPILL_THROTTLE_MBPS`` is 0, the default)."""
    global _throttle_next
    mbps = float(knobs.get("SORT_SPILL_THROTTLE_MBPS"))
    if mbps <= 0.0 or nbytes <= 0:
        return
    cost = nbytes / (mbps * 1e6)
    with _THROTTLE_LOCK:
        now = time.monotonic()
        start = _throttle_next if _throttle_next > now else now
        _throttle_next = start + cost
        wait = _throttle_next - now
    if wait > 0:
        time.sleep(wait)


def fsync_dir(path: str) -> None:
    """Durably commit a directory's entries (the rename half of the
    write-temp → fsync → ``os.replace`` → fsync(dir) protocol).
    Best-effort: filesystems without directory fsync just no-op."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _check_format_version(ver: int, path: str) -> None:
    if ver not in COMPAT_FORMAT_VERSIONS:
        raise RunVersionError(
            f"run file {path!r} is format_version {ver}; this build "
            f"reads {COMPAT_FORMAT_VERSIONS} and writes "
            f"{RUN_FORMAT_VERSION}")


def _run_bin_header(dtype: np.dtype) -> bytes:
    """The SORTBIN1 header with the run format version stamped into
    reserved byte 10 (``kio._bin_header`` zeroes all six reserved
    bytes, so pre-versioning files read back as version 0)."""
    h = bytearray(kio._bin_header(dtype))
    h[BIN_VERSION_OFF] = RUN_FORMAT_VERSION
    return bytes(h)


def _pay_header(width: int) -> bytes:
    h = bytearray(PAY_MAGIC + int(width).to_bytes(4, "little")
                  + b"\0" * 4)
    h[PAY_VERSION_OFF] = RUN_FORMAT_VERSION
    return bytes(h)


def _runz_header(dtype: np.dtype, n_words: int, block_elems: int) -> bytes:
    h = bytearray(RUNZ_MAGIC)
    h.append(ord(dtype.kind))
    h.append(dtype.itemsize)
    h.append(RUN_FORMAT_VERSION)
    h.append(n_words)
    h += int(block_elems).to_bytes(4, "little")
    return bytes(h)


def _pay2_header(width: int) -> bytes:
    h = bytearray(PAY2_MAGIC + int(width).to_bytes(4, "little")
                  + b"\0" * 4)
    h[PAY_VERSION_OFF] = RUN_FORMAT_VERSION
    return bytes(h)


def _runz_block_header(n: int, width: int, first: int, packed_len: int,
                       checksum: int) -> bytes:
    return (int(n).to_bytes(4, "little") + bytes([width]) + b"\0" * 3
            + int(first).to_bytes(8, "little")
            + int(packed_len).to_bytes(4, "little")
            + int(checksum).to_bytes(4, "little"))


def _runz_pay_blocks(n: int, block_elems: int) -> int:
    """Number of payload/key blocks a compressed run of ``n`` records
    holds (the writer flushes full blocks plus one remainder)."""
    return (n + block_elems - 1) // block_elems if n else 0


@dataclass(frozen=True)
class RunInfo:
    """One opened (or freshly written) spill run."""

    path: str                 # the .run (raw) / .runz (compressed) key file
    n: int                    # records in the run
    dtype: np.dtype
    payload_width: int        # bytes per record payload (0 = keys only)
    fingerprint: Fingerprint  # sidecar fold (sorted words, pre-disk)
    disk_bytes: int           # total bytes on disk (keys + payload)
    compressed: bool = False  # SORTRUN2 block-compressed framing

    @property
    def pay_path(self) -> str:
        return self.path + ".pay"

    @property
    def sidecar_path(self) -> str:
        return self.path + ".fpr.json"


def run_fingerprint(key_words: tuple[np.ndarray, ...],
                    payload_words: tuple[np.ndarray, ...],
                    ) -> Fingerprint:
    """The ONE fold rule for runs: plain per-word fingerprint for bare
    keys, the record (binding-mix) fingerprint once a payload rides."""
    if payload_words:
        return fingerprint_records(key_words, payload_words)
    return fingerprint_host(key_words)


def _take_pending(bufs: list[np.ndarray], take: int) -> np.ndarray:
    """Pop exactly ``take`` leading rows from a list of buffered arrays
    (1-D keys or (m, width) payload), splitting the boundary array in
    place — the compressed writer's block former."""
    out: list[np.ndarray] = []
    got = 0
    while got < take:
        a = bufs[0]
        need = take - got
        if len(a) <= need:
            out.append(a)
            got += len(a)
            bufs.pop(0)
        else:
            out.append(a[:need])
            bufs[0] = a[need:]
            got = take
    return out[0] if len(out) == 1 else np.concatenate(out)


class RunStreamWriter:
    """Incremental run writer: append already-sorted chunks, fold the
    fingerprint as they arrive, seal the sidecar at :meth:`close`.
    The intermediate-merge path writes through this so a merge pass
    never materializes its output run in host memory;
    :func:`write_run` is the one-shot convenience on top.

    The ``spill_corrupt`` fault site fires on the FIRST appended chunk
    (after its fold, before its write) — deterministic placement, same
    contract as ``faults.maybe_poison_chunk``.

    ``durable=True`` (the manifest-journaled path, ISSUE 18) writes
    ``*.tmp`` names and commits at :meth:`close` via fsync(file) →
    ``os.replace`` → fsync(dir), per file (keys, payload, sidecar) —
    a crash leaves either a complete published run or invisible temp
    files the startup GC reclaims, never a half-run under a final
    name."""

    def __init__(self, spill_dir: str, name: str, dtype: np.dtype,
                 payload_width: int = 0, durable: bool = False,
                 compress: bool | None = None,
                 block_elems: int = blockz.DEFAULT_BLOCK_ELEMS) -> None:
        os.makedirs(spill_dir, exist_ok=True)
        if compress is None:
            compress = blockz.resolve_compress()
        self.compressed = bool(compress)
        ext = ".runz" if self.compressed else ".run"
        self.path = os.path.join(spill_dir, f"{name}{ext}")
        self.durable = bool(durable)
        self._dir = spill_dir
        self._suffix = ".tmp" if self.durable else ""
        self.dtype = np.dtype(dtype)
        self.codec = codec_for(self.dtype)
        self.payload_width = int(payload_width)
        self.block_elems = max(1, int(block_elems))
        self.n = 0
        self.disk_bytes = 0
        self._fp: Fingerprint | None = None
        self._chunks = 0
        self._key_body = 0  # key bytes written after the 16-byte header
        self._blocks: list[tuple[int, int]] = []  # (offset, len) per block
        self._pend_keys: list[np.ndarray] = []
        self._pend_pay: list[np.ndarray] = []
        self._pend_n = 0
        self._kf = open(self.path + self._suffix, "wb")
        if self.compressed:
            self._kf.write(_runz_header(self.dtype, self.codec.n_words,
                                        self.block_elems))
            self.disk_bytes += RUNZ_HEADER_LEN
        else:
            self._kf.write(_run_bin_header(self.dtype))
            self.disk_bytes += kio.BIN_HEADER_LEN
        self._pf = None
        if self.payload_width:
            self._pf = open(self.path + ".pay" + self._suffix, "wb")
            self._pf.write(_pay2_header(self.payload_width)
                           if self.compressed
                           else _pay_header(self.payload_width))
            self.disk_bytes += PAY_HEADER_LEN

    def append(self, keys_sorted: np.ndarray,
               payload_sorted: np.ndarray | None = None) -> None:
        from mpitest_tpu.models.records import payload_to_words

        keys_sorted = np.ascontiguousarray(
            np.asarray(keys_sorted, self.dtype).reshape(-1))
        m = int(keys_sorted.size)
        if m == 0:
            return
        kw = self.codec.encode(keys_sorted)
        pw: tuple = ()
        pay = None
        if self.payload_width:
            if payload_sorted is None:
                raise ValueError(
                    "run declared a payload width but a chunk arrived "
                    "without payload")
            pay = np.ascontiguousarray(
                np.asarray(payload_sorted, np.uint8)).reshape(
                m, self.payload_width)
            pw = payload_to_words(pay)
        cfp = run_fingerprint(kw, pw)
        self._fp = cfp if self._fp is None else self._fp.combine(cfp)
        key_bytes = keys_sorted.tobytes()
        if self._chunks == 0:
            key_bytes = faults.maybe_corrupt_spill(key_bytes)
        self._chunks += 1
        faults.maybe_spill_enospc(len(key_bytes))
        if self.compressed:
            # reconstruct from the (possibly drill-corrupted) disk
            # bytes: the block codec must compress exactly what a raw
            # run would have persisted, so every block's checksum is
            # self-consistent and ONLY the sidecar fold can catch the
            # spill_corrupt shape — same detection story as raw runs
            self._pend_keys.append(np.frombuffer(key_bytes, self.dtype))
            if pay is not None:
                self._pend_pay.append(pay)
            self._pend_n += m
            self._flush_blocks(final=False)
        else:
            throttle_disk(len(key_bytes))
            self._kf.write(key_bytes)
            self.disk_bytes += len(key_bytes)
            self._key_body += len(key_bytes)
            if pay is not None:
                throttle_disk(pay.nbytes)
                self._pf.write(pay.tobytes())
                self.disk_bytes += pay.nbytes
        self.n += m

    def _flush_blocks(self, final: bool) -> None:
        """Compress+write full buffered blocks (every block except the
        run's last holds exactly ``block_elems`` records; ``final``
        drains the remainder at close)."""
        while self._pend_n >= self.block_elems or (final and
                                                   self._pend_n > 0):
            take = min(self.block_elems, self._pend_n)
            keys = _take_pending(self._pend_keys, take)
            wide = blockz.words_to_wide(self.codec.encode(keys))
            packed, first, width, chk = blockz.pack_block(wide)
            bh = _runz_block_header(take, width, first, len(packed), chk)
            off = RUNZ_HEADER_LEN + self._key_body
            throttle_disk(len(bh) + len(packed))
            self._kf.write(bh)
            self._kf.write(packed)
            blen = RUNZ_BLOCK_HEADER_LEN + len(packed)
            self._blocks.append((off, blen))
            self._key_body += blen
            self.disk_bytes += blen
            if self._pf is not None:
                pay_bytes = _take_pending(self._pend_pay, take).tobytes()
                pbh = (int(take).to_bytes(4, "little")
                       + int(blockz.checksum_bytes(pay_bytes)).to_bytes(
                           4, "little"))
                throttle_disk(len(pbh) + len(pay_bytes))
                self._pf.write(pbh)
                self._pf.write(pay_bytes)
                self.disk_bytes += len(pbh) + len(pay_bytes)
            self._pend_n -= take

    def append_words(self, key_words: tuple[np.ndarray, ...],
                     payload_words: tuple[np.ndarray, ...]) -> None:
        """Append a chunk already in encoded-word form (the merge's
        native currency) — decoded once here for the disk framing."""
        from mpitest_tpu.models.records import words_to_payload

        keys = self.codec.decode(key_words)
        pay = None
        if self.payload_width:
            pay = words_to_payload(payload_words, int(keys.size),
                                   self.payload_width)
        self.append(keys, pay)

    def abort(self) -> None:
        """Close + delete everything this writer may have produced
        (both temp and published names) — the ENOSPC / failed-merge
        cleanup path: a dead attempt must not leak dataset-sized
        partials under either naming."""
        for f in (self._kf, self._pf):
            try:
                if f is not None:
                    f.close()
            except OSError:
                pass
        for base in (self.path, self.path + ".pay",
                     self.path + ".fpr.json"):
            for p in ((base, base + ".tmp") if self.durable
                      else (base,)):
                try:
                    os.unlink(p)
                except OSError:
                    pass

    def close(self) -> RunInfo:
        if self.compressed:
            self._flush_blocks(final=True)
        if self.durable:
            for f in (self._kf, self._pf):
                if f is not None:
                    f.flush()
                    os.fsync(f.fileno())
        self._kf.close()
        if self._pf is not None:
            self._pf.close()
        fp = self._fp if self._fp is not None else run_fingerprint(
            tuple(np.empty(0, np.uint32)
                  for _ in range(self.codec.n_words)),
            ())
        sc_path = self.path + ".fpr.json"
        with open(sc_path + self._suffix, "w") as f:
            json.dump({"v": FP_SCHEMA, "n": self.n,
                       "dtype": self.dtype.name,
                       "payload_width": self.payload_width,
                       "format_version": RUN_FORMAT_VERSION,
                       "count": fp.count,
                       "xors": list(fp.xors), "sums": list(fp.sums)}, f)
            if self.durable:
                f.flush()
                os.fsync(f.fileno())
        if self.durable:
            # publish: fsync'd temp → final name → directory entry.
            # order keys/payload before sidecar — a sidecar must never
            # describe files that do not exist yet
            os.replace(self.path + ".tmp", self.path)
            if self.payload_width:
                os.replace(self.path + ".pay.tmp", self.path + ".pay")
            os.replace(sc_path + ".tmp", sc_path)
            fsync_dir(self._dir)
        # disk-fault drills (ISSUE 18 + ISSUE 20), applied to the
        # PUBLISHED file: a torn tail (bytes that never really hit the
        # platter), post-commit bit rot, and — compressed runs only —
        # a scrambled block header; all leave the sidecar/manifest
        # promising bytes the disk no longer honestly holds
        body = self._key_body
        cut = faults.spill_tear_bytes(body)
        if cut:
            os.truncate(self.path,
                        kio.BIN_HEADER_LEN + max(0, body - cut))
        rot = faults.spill_bitrot_word()
        if rot is not None and body > 0:
            off = kio.BIN_HEADER_LEN + body // 2
            with open(self.path, "r+b") as f:
                f.seek(off)
                b = f.read(1)
                if b:
                    f.seek(off)
                    f.write(bytes([b[0] ^ ((rot & 0xFF) or 0x5A)]))
        gw = faults.spill_block_garbage_word()
        if gw is not None and self.compressed and self._blocks:
            # scramble the MIDDLE block's header payload (first value,
            # packed length, checksum — bytes 8..24): the reader must
            # fail the framing or checksum check for that exact block
            off, blen = self._blocks[len(self._blocks) // 2]
            span = min(16, blen - 8)
            with open(self.path, "r+b") as f:
                f.seek(off + 8)
                cur = f.read(span)
                f.seek(off + 8)
                f.write(bytes(b ^ 0xA5 for b in cur))
        return RunInfo(self.path, self.n, self.dtype,
                       self.payload_width, fp, self.disk_bytes,
                       compressed=self.compressed)


def write_run(spill_dir: str, name: str, keys_sorted: np.ndarray,
              payload_sorted: np.ndarray | None = None,
              durable: bool = False,
              compress: bool | None = None) -> RunInfo:
    """Persist one sorted run: keys as SORTBIN1, payload (optional) as
    SORTPAY1, fingerprint sidecar folded from the HOST words before any
    byte reaches disk.  ``payload_sorted`` is a ``(n, width)`` uint8
    matrix already permuted into key order (``models/records.py``).

    The ``spill_corrupt`` fault site fires here — after the sidecar
    fold, before the disk write — so an armed drill produces exactly
    the bad-disk shape the merge's read-back fold must catch."""
    keys_sorted = np.asarray(keys_sorted).reshape(-1)
    width = 0
    if payload_sorted is not None:
        pay = np.asarray(payload_sorted, np.uint8)
        if pay.ndim != 2 or pay.shape[0] != int(keys_sorted.size):
            raise ValueError(
                f"payload must be (n, width) uint8; got {pay.shape} for "
                f"{int(keys_sorted.size)} records")
        width = int(pay.shape[1])
    w = RunStreamWriter(spill_dir, name, keys_sorted.dtype, width,
                        durable=durable, compress=compress)
    try:
        w.append(keys_sorted, payload_sorted if width else None)
        return w.close()
    except OSError:
        # ENOSPC mid-write (real or injected): never leak the partial
        w.abort()
        raise


def _load_sidecar(path: str) -> tuple[dict, Fingerprint]:
    sc_path = path + ".fpr.json"
    try:
        with open(sc_path) as f:
            sc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise RunFormatError(
            f"run sidecar {sc_path!r} unreadable: {e}") from None
    if not isinstance(sc, dict) or sc.get("v") != FP_SCHEMA:
        raise RunFormatError(
            f"run sidecar {sc_path!r}: bad schema tag {sc.get('v')!r} "
            f"(want {FP_SCHEMA!r})")
    try:
        fp = Fingerprint(int(sc["count"]),
                         tuple(int(v) for v in sc["xors"]),
                         tuple(int(v) for v in sc["sums"]))
    except (KeyError, TypeError, ValueError) as e:
        raise RunFormatError(
            f"run sidecar {sc_path!r}: malformed fingerprint: {e}"
        ) from None
    _check_format_version(int(sc.get("format_version", 0)), sc_path)
    return sc, fp


def open_run(path: str) -> RunInfo:
    """Open an existing run: validate the SORTBIN1 framing (via the
    engine-dispatched header check — the native encode engine's
    read-back path), the payload section, and the sidecar.  Raises
    :class:`RunFormatError` on any structural problem; fingerprint
    verification happens at read time (the merge) or via
    :func:`verify_run`."""
    sc, fp = _load_sidecar(path)
    dtype = np.dtype(str(sc.get("dtype", "int32")))
    try:
        st = os.stat(path)
    except OSError as e:
        raise RunFormatError(f"run file {path!r} unreadable: {e}") from None
    n = int(sc["n"])
    with open(path, "rb") as f:
        head = f.read(kio.BIN_HEADER_LEN)
    compressed = head[:8] == RUNZ_MAGIC
    if compressed:
        if len(head) < RUNZ_HEADER_LEN:
            raise RunFormatError(
                f"run file {path!r}: truncated SORTRUN2 header")
        if (chr(head[8]), head[9]) != (dtype.kind, dtype.itemsize):
            raise RunFormatError(
                f"run file {path!r} holds {chr(head[8])}{head[9] * 8} "
                f"keys, not {dtype.name}")
        _check_format_version(head[BIN_VERSION_OFF], path)
        codec = codec_for(dtype)
        if head[11] != codec.n_words:
            raise RunFormatError(
                f"run file {path!r}: {head[11]} key words in the "
                f"header, codec says {codec.n_words}")
        block_elems = int.from_bytes(head[12:16], "little")
        if block_elems < 1:
            raise RunFormatError(
                f"run file {path!r}: bad block_elems {block_elems}")
        # no fixed key-body size for compressed runs — each block
        # declares its own length; framing damage surfaces as a typed
        # BlockIntegrityError at read time instead
    else:
        body = st.st_size - kio.BIN_HEADER_LEN
        if body != n * dtype.itemsize:
            raise RunFormatError(
                f"run file {path!r}: {body} key bytes on disk but the "
                f"sidecar says {n} x {dtype.itemsize}-byte records "
                "(truncated or torn write)")
        if head[:8] != kio.BIN_MAGIC:
            raise RunFormatError(
                f"run file {path!r} is not SORTBIN1-framed")
        kio._check_bin_header(head, path, dtype)
        _check_format_version(head[BIN_VERSION_OFF], path)
        block_elems = 0
    width = int(sc.get("payload_width", 0))
    disk = st.st_size
    if width:
        pp = path + ".pay"
        try:
            pst = os.stat(pp)
        except OSError as e:
            raise RunFormatError(
                f"run payload {pp!r} unreadable: {e}") from None
        want_pay = PAY_HEADER_LEN + n * width
        if compressed:
            want_pay += 8 * _runz_pay_blocks(n, block_elems)
        if pst.st_size != want_pay:
            raise RunFormatError(
                f"run payload {pp!r}: {pst.st_size} bytes on disk, "
                f"expected {want_pay} "
                f"({n} x {width}-byte payloads)")
        with open(pp, "rb") as f:
            phead = f.read(PAY_HEADER_LEN)
        want_magic = PAY2_MAGIC if compressed else PAY_MAGIC
        if phead[:8] != want_magic or \
                int.from_bytes(phead[8:12], "little") != width:
            raise RunFormatError(
                f"run payload {pp!r}: bad "
                f"{want_magic.decode('ascii')} header")
        _check_format_version(phead[PAY_VERSION_OFF], pp)
        disk += pst.st_size
    return RunInfo(path, n, dtype, width, fp, disk,
                   compressed=compressed)


def read_run_chunks(info: RunInfo, chunk_elems: int):
    """Yield ``(keys_chunk, payload_chunk | None)`` slices of a run in
    order.  Raw runs: keys as zero-copy mmap slices
    (``kio.open_keys_mmap``, the PR 2 page-in path), payload as
    mmap-backed ``(m, width)`` views.  Compressed runs: sequential
    block reads + decode (:mod:`store.compress`), any in-block
    inconsistency raising the typed :class:`BlockIntegrityError`.
    Bounded memory at any run size."""
    if info.compressed:
        yield from _read_runz_chunks(info, chunk_elems)
        return
    try:
        mm = kio.open_keys_mmap(info.path, info.dtype)
    except ValueError as e:
        # a torn tail leaves a byte count that is not a whole number of
        # keys — np.memmap raises a bare ValueError; type it so the
        # merge blame ladder can re-spill this run
        raise RunFormatError(
            f"run file {info.path!r}: torn/unmappable keys body "
            f"({e})") from None
    if int(mm.size) != info.n:
        raise RunFormatError(
            f"run file {info.path!r}: {int(mm.size)} keys on disk, "
            f"sidecar says {info.n}")
    pm = None
    if info.payload_width:
        try:
            pm = np.memmap(info.pay_path, dtype=np.uint8, mode="r",
                           offset=PAY_HEADER_LEN)
            pm = pm.reshape(info.n, info.payload_width)
        except ValueError as e:
            raise RunFormatError(
                f"run payload {info.pay_path!r}: torn/unmappable body "
                f"({e})") from None
    if info.n == 0:
        return
    chunk_elems = max(1, int(chunk_elems))
    for i in range(0, info.n, chunk_elems):
        k = mm[i:i + chunk_elems]
        throttle_disk(k.nbytes)
        p = pm[i:i + chunk_elems] if pm is not None else None
        if p is not None:
            throttle_disk(p.nbytes)
        yield k, p


def _read_runz_chunks(info: RunInfo, chunk_elems: int):
    """The compressed (SORTRUN2) half of :func:`read_run_chunks`:
    stream block headers + bodies sequentially, validate EVERY framing
    field against the sidecar's totals before trusting it, decode
    (native engine when loadable), and compare the stored block
    checksum against one folded from the reconstructed values.  Any
    disagreement is a :class:`BlockIntegrityError` naming run + block
    — the merge types it as run damage and re-spills."""
    codec = codec_for(info.dtype)
    chunk_elems = max(1, int(chunk_elems))
    kf = open(info.path, "rb")
    pf = open(info.pay_path, "rb") if info.payload_width else None
    try:
        head = kf.read(RUNZ_HEADER_LEN)
        if len(head) < RUNZ_HEADER_LEN or head[:8] != RUNZ_MAGIC:
            raise RunFormatError(
                f"run file {info.path!r} is not SORTRUN2-framed")
        block_elems = max(1, int.from_bytes(head[12:16], "little"))
        if pf is not None:
            pf.seek(PAY_HEADER_LEN)
        remaining = info.n
        bidx = 0
        while remaining > 0:
            bh = kf.read(RUNZ_BLOCK_HEADER_LEN)
            if len(bh) != RUNZ_BLOCK_HEADER_LEN:
                raise BlockIntegrityError(
                    info.path, bidx, "truncated block header "
                    f"({len(bh)} of {RUNZ_BLOCK_HEADER_LEN} bytes)")
            bn = int.from_bytes(bh[0:4], "little")
            bwidth = bh[4]
            first = int.from_bytes(bh[8:16], "little")
            plen = int.from_bytes(bh[16:20], "little")
            stored = int.from_bytes(bh[20:24], "little")
            if bn == 0 or bn > block_elems or bn > remaining:
                raise BlockIntegrityError(
                    info.path, bidx,
                    f"element count {bn} outside 1..{min(block_elems, remaining)}")
            if bwidth > 64:
                raise BlockIntegrityError(
                    info.path, bidx, f"delta width {bwidth} outside 0..64")
            want = ((bn - 1) * bwidth + 7) // 8
            if plen != want:
                raise BlockIntegrityError(
                    info.path, bidx,
                    f"packed length {plen} disagrees with "
                    f"(n={bn}, width={bwidth}) -> {want}")
            packed = kf.read(plen)
            if len(packed) != plen:
                raise BlockIntegrityError(
                    info.path, bidx, "truncated block body "
                    f"({len(packed)} of {plen} bytes)")
            throttle_disk(RUNZ_BLOCK_HEADER_LEN + plen)
            try:
                wide, chk = blockz.unpack_block(packed, bn, first, bwidth)
            except ValueError as e:
                raise BlockIntegrityError(info.path, bidx, str(e)) from None
            if chk != stored:
                raise BlockIntegrityError(
                    info.path, bidx,
                    f"checksum mismatch (stored {stored:#010x}, "
                    f"re-folded {chk:#010x})")
            keys = codec.decode(blockz.wide_to_words(wide, codec.n_words))
            pay = None
            if pf is not None:
                pbh = pf.read(8)
                if len(pbh) != 8:
                    raise BlockIntegrityError(
                        info.path, bidx, "truncated payload block header")
                pn = int.from_bytes(pbh[0:4], "little")
                pstored = int.from_bytes(pbh[4:8], "little")
                if pn != bn:
                    raise BlockIntegrityError(
                        info.path, bidx,
                        f"payload block holds {pn} records, key block {bn}")
                pay_bytes = pf.read(bn * info.payload_width)
                if len(pay_bytes) != bn * info.payload_width:
                    raise BlockIntegrityError(
                        info.path, bidx, "truncated payload block body")
                throttle_disk(8 + len(pay_bytes))
                if blockz.checksum_bytes(pay_bytes) != pstored:
                    raise BlockIntegrityError(
                        info.path, bidx, "payload block checksum mismatch")
                pay = np.frombuffer(pay_bytes, np.uint8).reshape(
                    bn, info.payload_width)
            for i in range(0, bn, chunk_elems):
                yield (keys[i:i + chunk_elems],
                       pay[i:i + chunk_elems] if pay is not None else None)
            remaining -= bn
            bidx += 1
    finally:
        kf.close()
        if pf is not None:
            pf.close()


class InputStage:
    """Wire→disk staging for the serve spill tier (ISSUE 15): an
    over-budget request's key/payload bytes stream straight from the
    socket into spill-dir files — host memory never holds the request —
    and come back as memmap views the external sort pages in
    chunk-by-chunk.  Lives here so every spill-path ``open()`` stays
    inside this module (sortlint SL014)."""

    def __init__(self, spill_dir: str, name: str, dtype: np.dtype,
                 n: int, payload_width: int = 0) -> None:
        os.makedirs(spill_dir, exist_ok=True)
        self.path = os.path.join(spill_dir, f"{name}.spill")
        self.dtype = np.dtype(dtype)
        self.n = int(n)
        self.payload_width = int(payload_width)
        self._kf = open(self.path, "wb")
        self._kf.write(kio._bin_header(self.dtype))
        self._pf = None
        if self.payload_width:
            self._pf = open(self.path + ".pay", "wb")
            self._pf.write(_pay_header(self.payload_width))

    def key_sink(self, chunk: bytes) -> None:
        self._kf.write(chunk)

    def pay_sink(self, chunk: bytes) -> None:
        assert self._pf is not None
        self._pf.write(chunk)

    def abort(self) -> None:
        """Close + delete the staged files (the request died before
        dispatch — short read, timeout, rejection)."""
        self._kf.close()
        if self._pf is not None:
            self._pf.close()
        for p in (self.path, self.path + ".pay"):
            try:
                os.unlink(p)
            except OSError:
                pass

    def finish(self) -> tuple[np.ndarray, np.ndarray | None]:
        """Seal the staged files and return memmap views (keys 1-D,
        payload ``(n, width)``).  The files are unlinked immediately —
        the mmaps keep them alive exactly as long as the dispatch needs
        them, and nothing can leak on any later exit path."""
        self._kf.close()
        got = os.path.getsize(self.path) - kio.BIN_HEADER_LEN
        want = self.n * self.dtype.itemsize
        if got != want:
            self.abort()
            raise RunFormatError(
                f"staged input {self.path!r}: {got} key bytes, "
                f"expected {want}")
        keys = np.memmap(self.path, dtype=self.dtype, mode="r",
                         offset=kio.BIN_HEADER_LEN)
        pay = None
        if self._pf is not None:
            self._pf.close()
            pgot = os.path.getsize(self.path + ".pay") - PAY_HEADER_LEN
            if pgot != self.n * self.payload_width:
                self.abort()
                raise RunFormatError(
                    f"staged payload {self.path + '.pay'!r}: {pgot} "
                    f"bytes, expected {self.n * self.payload_width}")
            pay = np.memmap(self.path + ".pay", dtype=np.uint8,
                            mode="r", offset=PAY_HEADER_LEN)
            pay = pay.reshape(self.n, self.payload_width)
        for p in (self.path, self.path + ".pay"):
            try:
                os.unlink(p)
            except OSError:
                pass
        return keys, pay


def remove_run(info: RunInfo) -> None:
    """Best-effort deletion of a run's files (keys, payload, sidecar)
    — the external driver's cleanup: partition and intermediate runs
    are dataset-sized and must not outlive the sort that made them."""
    for p in (info.path, info.pay_path, info.sidecar_path):
        try:
            os.unlink(p)
        except OSError:
            pass


def remove_run_paths(path: str) -> None:
    """Best-effort deletion by the KEY path alone — cleanup of a run
    whose metadata never loaded (a torn/damaged resume candidate the
    manifest names but :func:`open_run` rejects)."""
    for p in (path, path + ".pay", path + ".fpr.json"):
        try:
            os.unlink(p)
        except OSError:
            pass


def run_body_views(info: RunInfo,
                   unlink: bool = False) -> list[memoryview]:
    """Zero-copy memoryviews of a run's key body (and payload body) —
    the spill tier's reply source: the wire layer sends these straight
    to the socket without materializing the merged result.  With
    ``unlink`` the files are deleted now; the mmaps keep the bytes
    reachable until the views are dropped."""
    if info.compressed:
        # defensive: final/output runs are ALWAYS written raw (the wire
        # layer serves their bodies verbatim) — a compressed run here
        # means a routing bug upstream, not a servable reply
        raise RunFormatError(
            f"run file {info.path!r} is SORTRUN2-compressed; only raw "
            "runs can serve zero-copy body views")
    mm = np.memmap(info.path, dtype=np.uint8, mode="r",
                   offset=kio.BIN_HEADER_LEN)
    views = [memoryview(mm)]
    if info.payload_width:
        pm = np.memmap(info.pay_path, dtype=np.uint8, mode="r",
                       offset=PAY_HEADER_LEN)
        views.append(memoryview(pm))
    if unlink:
        for p in (info.path, info.pay_path, info.sidecar_path):
            try:
                os.unlink(p)
            except OSError:
                pass
    return views


def verify_run(info: RunInfo, chunk_elems: int = 1 << 20) -> bool:
    """Full integrity scan of one run: re-fold the on-disk bytes
    chunk-by-chunk and compare against the sidecar, plus a sortedness
    sweep across chunk boundaries.  The external driver's blame step —
    when the merged output disagrees with the combined sidecars, this
    names the bad run(s)."""
    from mpitest_tpu.models.records import payload_to_words
    from mpitest_tpu.models.segmented import lex_sorted_host

    codec = codec_for(info.dtype)
    fp = None
    prev_last: np.ndarray | None = None
    for keys, pay in read_run_chunks(info, chunk_elems):
        arr = np.array(keys)  # fault the pages in
        kw = codec.encode(arr)
        pw = payload_to_words(np.array(pay)) if pay is not None else ()
        cfp = run_fingerprint(kw, pw)
        fp = cfp if fp is None else fp.combine(cfp)
        if arr.size:
            # boundary-inclusive sortedness: prepend the previous
            # chunk's last key so a violation across the seam trips too
            both = (np.concatenate([prev_last, arr])
                    if prev_last is not None else arr)
            if not lex_sorted_host(codec.encode(both)):
                return False
            prev_last = arr[-1:]
    if fp is None:  # 0-record run: nothing to fold, nothing to corrupt
        return info.fingerprint.count == 0
    return fp == info.fingerprint
