"""Sort doctor: automated pathology diagnosis over the telemetry fold.

The stack emits rich raw telemetry — spans with trace ids, plan
provenance + regret, /metrics, a flight recorder — but until ISSUE 16
nothing *interpreted* it: finding the straggler or the mis-set knob
meant hand-correlating ``exchange_balance`` byte lists, regrow
counters, cache misses, and breaker events across JSONL.  This module
is the interpreter: a REGISTERED vocabulary of known pathologies
(:data:`DOCTOR_RULES`), each a pure function over one evidence
snapshot (timeline fold + span census + serve stats + plan attrs)
returning a typed :class:`Finding` — severity, the span/metric
citations that justify it, and the knob to turn with a direction.

Consumed three ways:

* ``report.py --doctor [trace|trace-id]`` renders findings post-hoc;
* ``SortPlan.digest()`` embeds a compact ``doctor`` block (plan-shaped
  rules only) so mis-planned runs self-describe;
* ``serve/sentinel.py`` emits live ``serve.alert`` spans whose rule
  names come from THIS vocabulary (sortlint SL007 enforces that, the
  same way SL005/SL006 police plan decisions/policies).

Import contract (same as models/plan.py): stdlib-only at module
import, loadable standalone by file path — sortlint loads it with no
package context, so ``DOCTOR_RULES`` must resolve without jax, numpy,
or the mpitest_tpu package.  Span names consumed here are string
literals matched against the registered schema; they are read, never
emitted, so SL003 does not apply.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

DOCTOR_SCHEMA = "doctor.v1"

#: Severity ladder, mildest first (findings sort critical-first).
SEVERITIES = ("info", "warn", "critical")

#: The registered pathology vocabulary.  sortlint SL007 loads this
#: dict by file path and rejects any literal rule name in doctor /
#: sentinel calls or ``serve.alert`` emissions that is not a key here
#: (the SL005/SL006 pattern for plan decisions/policies).  Add the
#: rule function with :func:`_rule` in the same change — a key without
#: a diagnosis function fails the vocabulary test.
DOCTOR_RULES: dict[str, str] = {
    "skew_imbalance":
        "one rank exchanges far more bytes than the median — a "
        "straggler serializes every barrier behind it",
    "cap_thrash":
        "the negotiated exchange capacity repeatedly regrew mid-sort "
        "— each regrow is a recompile + retry of the exchange",
    "compile_storm":
        "persistent jit-cache misses in steady state — the shape mix "
        "is not covered by the serve bucket ladder",
    "window_misfit":
        "the serve batch window pads lanes it cannot fill (high "
        "padded-lane waste) or never packs more than one segment",
    "spill_bound":
        "external-sort wall time is dominated by disk spill/merge "
        "reads rather than compute",
    "verify_overhead_regression":
        "post-sort verification consumes an outsized share of the "
        "run wall time",
    "breaker_flap":
        "the serve circuit breaker trips repeatedly — capacity is "
        "oscillating instead of recovering",
    "deadline_burn":
        "the serve SLO budget is burning: errors/expired deadlines "
        "or drifting p99 exceed the error-budget burn-rate allowance",
    "local_sort_lax":
        "the local sort dominates the critical path while the engine "
        "resolved to generic lax.sort on a TPU backend — the fused "
        "radix engine is one knob away",
    "spill_churn":
        "the external sort keeps re-spilling or crash-resuming — "
        "repeated integrity recoveries / manifest replays in one "
        "trace point at a failing spill volume",
}

# diagnosis thresholds — module constants so tests cite them and the
# sentinel reuses the same gates for its rolling windows
SKEW_FACTOR_WARN = 1.5
SKEW_FACTOR_CRITICAL = 3.0
CAP_REGROW_GATE = 2
COMPILE_MISS_MIN = 4
WINDOW_WASTE_GATE = 0.5
WINDOW_OCCUPANCY_MIN_BATCHES = 4
SPILL_FRACTION_GATE = 0.5
VERIFY_RATIO_GATE = 0.25
# absolute floor: tiny/cold runs legitimately spend most of their wall
# in verify (the verifier's first-call compile lands in phase:verify),
# and sub-second overhead is not worth a knob suggestion either way
VERIFY_MIN_SECONDS = 0.5
BREAKER_TRIP_GATE = 2
BURN_RATE_GATE = 1.0
BURN_MIN_REQUESTS = 8
DEFAULT_SLO_TARGET_PCT = 99.9
# local_sort_lax (ISSUE 17): the sort phase must both be the critical
# path's dominant phase AND carry at least this fraction of the phase
# wall before a lax-on-TPU local engine is worth a knob suggestion
LOCAL_SORT_PHASE_GATE = 0.4
# spill_churn (ISSUE 18): integrity recoveries + manifest resumes in
# one trace before the spill volume itself is the suspect (one of
# either is normal operation: a single blamed run, a single restart)
SPILL_CHURN_GATE = 2


@dataclass
class Finding:
    """One diagnosed pathology: what, how bad, why (citations into the
    span/metric evidence), and which knob to turn which way."""
    rule: str
    severity: str              # one of SEVERITIES
    summary: str
    evidence: list[str] = field(default_factory=list)
    knob: str | None = None    # registered SORT_* knob to adjust
    direction: str | None = None   # "raise" / "lower" / "set ..."
    value: float | None = None     # the measured signal
    threshold: float | None = None  # the gate it crossed

    def __post_init__(self) -> None:
        if self.rule not in DOCTOR_RULES:
            raise KeyError(f"unregistered doctor rule: {self.rule!r}")
        if self.severity not in SEVERITIES:
            raise ValueError(f"bad severity: {self.severity!r}")

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {"v": DOCTOR_SCHEMA, "rule": self.rule,
                               "severity": self.severity,
                               "summary": self.summary,
                               "evidence": list(self.evidence)}
        if self.knob:
            out["knob"] = self.knob
            out["direction"] = self.direction
        if self.value is not None:
            out["value"] = self.value
        if self.threshold is not None:
            out["threshold"] = self.threshold
        return out


_RULES: dict[str, Callable[[dict], "Finding | None"]] = {}


def _rule(name: str) -> Callable:
    """Register a diagnosis function under a vocabulary key."""
    if name not in DOCTOR_RULES:
        raise KeyError(f"unregistered doctor rule: {name!r}")

    def deco(fn: Callable[[dict], "Finding | None"]) -> Callable:
        _RULES[name] = fn
        return fn
    return deco


# -- evidence fold ----------------------------------------------------

def empty_evidence() -> dict[str, Any]:
    return {"timeline": {}, "spans": {}, "serve": {}, "plans": [],
            "watchdog": {}, "slo_target_pct": DEFAULT_SLO_TARGET_PCT}


def evidence_from_rows(rows: list[dict],
                       timeline: dict | None = None) -> dict[str, Any]:
    """Fold span-dict rows (report.py rows, flight-recorder snapshots,
    raw ``to_dict()`` output) into the evidence snapshot the rules
    consume.  ``timeline`` is the :func:`utils.timeline.build_timeline`
    fold when the caller already has it — the doctor itself stays
    import-light and never computes one."""
    ev = empty_evidence()
    ev["timeline"] = timeline or {}
    spans: dict[str, int] = ev["spans"]
    serve: dict[str, Any] = ev["serve"]
    serve.update(requests=0, ok=0, errors={}, deadline_expired=0,
                 cache_hits=0, cache_misses=0, batches=0,
                 batch_segments=0, latencies_ms=[])
    watchdog: dict[str, int] = ev["watchdog"]
    for r in rows:
        if not isinstance(r, dict):
            continue
        name = str(r.get("name", "?"))
        attrs = r.get("attrs") or {}
        spans[name] = spans.get(name, 0) + 1
        if name == "serve.request":
            serve["requests"] += 1
            status = str(attrs.get("status", "?"))
            if status == "ok":
                serve["ok"] += 1
                dt = float(r.get("dt", 0.0) or 0.0)
                serve["latencies_ms"].append(dt * 1e3)
            else:
                errs = serve["errors"]
                errs[status] = errs.get(status, 0) + 1
        elif name == "serve.deadline":
            serve["deadline_expired"] += 1
        elif name == "serve.compile_cache":
            if attrs.get("hit"):
                serve["cache_hits"] += 1
            else:
                serve["cache_misses"] += 1
        elif name == "serve.batch":
            serve["batches"] += 1
            segs = attrs.get("segments")
            if isinstance(segs, (int, float)):
                serve["batch_segments"] += int(segs)
        elif name == "serve.watchdog":
            ev_kind = str(attrs.get("event", "?"))
            watchdog[ev_kind] = watchdog.get(ev_kind, 0) + 1
        elif name == "sort.plan":
            if isinstance(attrs, dict) and attrs:
                ev["plans"].append(attrs)
    return ev


# -- the rules --------------------------------------------------------

@_rule("skew_imbalance")
def _r_skew(ev: dict) -> Finding | None:
    tl = ev.get("timeline") or {}
    f = tl.get("straggler_factor")
    if not isinstance(f, (int, float)) or f < SKEW_FACTOR_WARN:
        return None
    worst = None
    for p in tl.get("passes") or []:
        if p.get("straggler") == f:
            worst = p
            break
    cites = [f"exchange_balance: straggler factor {f:g}x "
             f"(max/median rank bytes)"]
    if worst is not None and worst.get("rank_bytes"):
        rb = worst["rank_bytes"]
        cites.append(f"exchange_balance[seq={worst['seq']}]: rank "
                     f"bytes max={max(rb):g} median-normalized over "
                     f"{len(rb)} ranks")
    sev = "critical" if f >= SKEW_FACTOR_CRITICAL else "warn"
    return Finding("skew_imbalance", sev,
                   f"rank data skew: the slowest rank carries {f:g}x "
                   f"the median exchange bytes",
                   evidence=cites, knob="SORT_RESTAGE",
                   direction="set auto (re-stage the skewed input)",
                   value=float(f), threshold=SKEW_FACTOR_WARN)


@_rule("cap_thrash")
def _r_cap_thrash(ev: dict) -> Finding | None:
    regrows = 0
    per_plan: list[str] = []
    for attrs in ev.get("plans") or []:
        cap = (attrs.get("decisions") or {}).get("cap") \
            if isinstance(attrs.get("decisions"), dict) else None
        actual = cap.get("actual") if isinstance(cap, dict) else None
        n = actual.get("regrows") if isinstance(actual, dict) else None
        if isinstance(n, (int, float)) and n > 0:
            regrows += int(n)
            per_plan.append(
                f"sort.plan: decisions.cap.actual.regrows={int(n)}"
                + (f" (negotiated cap {cap.get('chosen')})"
                   if isinstance(cap, dict) and "chosen" in cap else ""))
    if regrows < CAP_REGROW_GATE:
        return None
    return Finding("cap_thrash", "warn",
                   f"exchange capacity regrew {regrows}x — the "
                   f"negotiated cap is too tight for the real "
                   f"distribution",
                   evidence=per_plan or
                   [f"sort.plan: {regrows} cap regrow(s)"],
                   knob="SORT_CAP_FACTOR",
                   direction="raise (leave headroom over the probe)",
                   value=float(regrows), threshold=float(CAP_REGROW_GATE))


@_rule("compile_storm")
def _r_compile_storm(ev: dict) -> Finding | None:
    s = ev.get("serve") or {}
    hits = int(s.get("cache_hits", 0))
    misses = int(s.get("cache_misses", 0))
    if misses < COMPILE_MISS_MIN or misses <= hits:
        return None
    return Finding("compile_storm", "warn",
                   f"jit cache missing in steady state: {misses} "
                   f"miss(es) vs {hits} hit(s)",
                   evidence=[f"serve.compile_cache: hit=False x"
                             f"{misses}, hit=True x{hits}"],
                   knob="SORT_SERVE_SHAPE_BUCKETS",
                   direction="widen (cover the live shape mix)",
                   value=float(misses), threshold=float(COMPILE_MISS_MIN))


@_rule("window_misfit")
def _r_window_misfit(ev: dict) -> Finding | None:
    wastes: list[float] = []
    for attrs in ev.get("plans") or []:
        batch = (attrs.get("decisions") or {}).get("batch") \
            if isinstance(attrs.get("decisions"), dict) else None
        actual = batch.get("actual") if isinstance(batch, dict) else None
        w = actual.get("waste") if isinstance(actual, dict) else None
        if isinstance(w, (int, float)):
            wastes.append(float(w))
    if wastes:
        mean_waste = sum(wastes) / len(wastes)
        if mean_waste >= WINDOW_WASTE_GATE:
            return Finding(
                "window_misfit", "warn",
                f"batch window pads {100 * mean_waste:.0f}% of the "
                f"lane it packs",
                evidence=[f"sort.plan: decisions.batch.actual.waste "
                          f"mean {mean_waste:.2f} over "
                          f"{len(wastes)} plan(s)"],
                knob="SORT_SERVE_BATCH_WINDOW_MS",
                direction="lower (stop packing mismatched shapes)",
                value=round(mean_waste, 4),
                threshold=WINDOW_WASTE_GATE)
    s = ev.get("serve") or {}
    batches = int(s.get("batches", 0))
    segs = int(s.get("batch_segments", 0))
    if batches >= WINDOW_OCCUPANCY_MIN_BATCHES and segs <= batches:
        occ = segs / batches if batches else 0.0
        return Finding(
            "window_misfit", "info",
            f"batch window never packs: {segs} segment(s) over "
            f"{batches} batch(es) (occupancy {occ:.2f})",
            evidence=[f"serve.batch: {batches} batches, "
                      f"{segs} segments"],
            knob="SORT_SERVE_BATCH_WINDOW_MS",
            direction="raise (let arrivals coalesce)",
            value=round(occ, 4), threshold=1.0)
    return None


@_rule("spill_bound")
def _r_spill_bound(ev: dict) -> Finding | None:
    tl = ev.get("timeline") or {}
    ov = tl.get("overlap") or {}
    disk = float(ov.get("disk_s", 0.0) or 0.0)
    comp = float(ov.get("compute_s", 0.0) or 0.0)
    total = disk + comp
    if disk <= 0 or total <= 0:
        return None
    frac = disk / total
    if frac < SPILL_FRACTION_GATE:
        return None
    evidence = [f"external.run/external.merge: {disk:.3f}s "
                f"disk vs {comp:.3f}s compute "
                f"(overlap {ov.get('compute_disk_pct', 0)}%)"]
    # traces from the async-merge era (ISSUE 20) carry the measured
    # read-ahead/write-behind concurrency; surface it when present so
    # the operator can tell "disk-bound AND synchronous" (fixable by
    # the IO engine) from "disk-bound at full overlap" (buy compression
    # or a faster disk) — older traces lack the key, behavior unchanged
    spill_ov = ov.get("spill_disk_overlap")
    if isinstance(spill_ov, (int, float)):
        evidence.append(
            f"final merge read-ahead/write-behind overlap "
            f"{100 * float(spill_ov):.0f}% "
            "(SORT_SPILL_COMPRESS shrinks the disk traffic itself)")
    return Finding("spill_bound", "warn",
                   f"disk spill/merge IO is {100 * frac:.0f}% of the "
                   f"compute+IO wall",
                   evidence=evidence,
                   knob="SORT_MERGE_FANIN",
                   direction="raise (fewer merge passes over the runs)",
                   value=round(frac, 4), threshold=SPILL_FRACTION_GATE)


@_rule("verify_overhead_regression")
def _r_verify(ev: dict) -> Finding | None:
    tl = ev.get("timeline") or {}
    phases = tl.get("phases") or {}
    verify = float(phases.get("verify", 0.0) or 0.0)
    total = sum(float(v) for v in phases.values())
    if verify < VERIFY_MIN_SECONDS or total <= 0:
        return None
    ratio = verify / total
    if ratio < VERIFY_RATIO_GATE:
        return None
    return Finding("verify_overhead_regression", "warn",
                   f"phase:verify is {100 * ratio:.0f}% of phase wall "
                   f"time",
                   evidence=[f"phase:verify {verify:.3f}s of "
                             f"{total:.3f}s total phase time"],
                   knob="SORT_VERIFY",
                   direction="lower (sampled or off once the fallback "
                             "ladder is trusted)",
                   value=round(ratio, 4), threshold=VERIFY_RATIO_GATE)


@_rule("local_sort_lax")
def _r_local_sort_lax(ev: dict) -> Finding | None:
    tl = ev.get("timeline") or {}
    if tl.get("critical_path_phase") != "sort":
        return None
    phases = tl.get("phases") or {}
    sort_s = float(phases.get("sort", 0.0) or 0.0)
    total = sum(float(v) for v in phases.values())
    if total <= 0:
        return None
    frac = sort_s / total
    if frac < LOCAL_SORT_PHASE_GATE:
        return None
    hits: list[str] = []
    for attrs in ev.get("plans") or []:
        eng = (attrs.get("decisions") or {}).get("engine") \
            if isinstance(attrs.get("decisions"), dict) else None
        actual = eng.get("actual") if isinstance(eng, dict) else None
        if not isinstance(actual, dict):
            continue
        if (actual.get("local_engine") == "lax"
                and actual.get("backend") == "tpu"):
            hits.append("sort.plan: decisions.engine.actual"
                        ".local_engine=lax backend=tpu")
    if not hits:
        return None
    return Finding(
        "local_sort_lax", "warn",
        f"local sort is the critical-path phase ({100 * frac:.0f}% of "
        "phase wall) and lowered through generic lax.sort on a TPU "
        "backend",
        evidence=[f"timeline: critical_path_phase=sort "
                  f"({sort_s:.3f}s of {total:.3f}s)"] + hits[:3],
        knob="SORT_LOCAL_ENGINE",
        direction="set radix_pallas (fused per-pass local radix "
                  "kernel; re-baseline on first TPU use)",
        value=round(frac, 4), threshold=LOCAL_SORT_PHASE_GATE)


@_rule("spill_churn")
def _r_spill_churn(ev: dict) -> Finding | None:
    spans = ev.get("spans") or {}
    recovers = int(spans.get("external.recover", 0))
    resumes = int(spans.get("external.resume", 0))
    churn = recovers + resumes
    if churn < SPILL_CHURN_GATE:
        return None
    cites = []
    if recovers:
        cites.append(f"external.recover: {recovers} integrity "
                     "recovery(ies) — runs re-spilled from source")
    if resumes:
        cites.append(f"external.resume: {resumes} manifest replay(s) "
                     "— sorts re-entered at the merge phase")
    sev = "critical" if recovers >= SPILL_CHURN_GATE else "warn"
    return Finding("spill_churn", sev,
                   f"spill tier churning: {recovers} recovery(ies) + "
                   f"{resumes} crash resume(s) in one trace",
                   evidence=cites, knob="SORT_SPILL_DIR",
                   direction="set (move spill staging to a healthier "
                             "volume; check dmesg for media errors)",
                   value=float(churn), threshold=float(SPILL_CHURN_GATE))


@_rule("breaker_flap")
def _r_breaker_flap(ev: dict) -> Finding | None:
    wd = ev.get("watchdog") or {}
    trips = int(wd.get("trip", 0))
    if trips < BREAKER_TRIP_GATE:
        return None
    cites = [f"serve.watchdog: event=trip x{trips}"]
    for kind in ("recovered", "probe"):
        if wd.get(kind):
            cites.append(f"serve.watchdog: event={kind} x{wd[kind]}")
    return Finding("breaker_flap", "critical",
                   f"circuit breaker flapping: {trips} trip(s) in one "
                   f"trace — capacity oscillates instead of recovering",
                   evidence=cites,
                   knob="SORT_SERVE_DISPATCH_TIMEOUT_S",
                   direction="raise (or lower SORT_SERVE_MAX_INFLIGHT "
                             "to shed load before the breaker does)",
                   value=float(trips), threshold=float(BREAKER_TRIP_GATE))


@_rule("deadline_burn")
def _r_deadline_burn(ev: dict) -> Finding | None:
    s = ev.get("serve") or {}
    n = int(s.get("requests", 0))
    if n < BURN_MIN_REQUESTS:
        return None
    errors = sum(int(v) for v in (s.get("errors") or {}).values())
    if errors <= 0:
        return None
    target = float(ev.get("slo_target_pct", DEFAULT_SLO_TARGET_PCT))
    rate = 100.0 * errors / n
    allowance = max(100.0 - target, 1e-9)
    burn = rate / allowance
    if burn < BURN_RATE_GATE:
        return None
    expired = int(s.get("deadline_expired", 0))
    cites = [f"serve.request: {errors}/{n} non-ok "
             f"({rate:.2f}% vs {allowance:g}% allowance = "
             f"{burn:.1f}x burn)"]
    if expired:
        cites.append(f"serve.deadline: {expired} expired deadline(s)")
    by_status = ", ".join(f"{k}={v}" for k, v in
                          sorted((s.get("errors") or {}).items()))
    if by_status:
        cites.append(f"sort_requests_total status breakdown: {by_status}")
    sev = "critical" if burn >= 2 * BURN_RATE_GATE else "warn"
    return Finding("deadline_burn", sev,
                   f"error budget burning at {burn:.1f}x allowance "
                   f"({errors} error(s) in {n} request(s))",
                   evidence=cites, knob="SORT_SERVE_MAX_INFLIGHT",
                   direction="lower (shed load before deadlines expire)",
                   value=round(burn, 4), threshold=BURN_RATE_GATE)


# -- entry points -----------------------------------------------------

def run_rule(name: str, evidence: dict) -> Finding | None:
    """Run ONE registered rule (KeyError on a name outside
    :data:`DOCTOR_RULES` — sortlint SL007 catches literal misuse at
    lint time, this catches computed names at run time)."""
    return _RULES[name](evidence)


def diagnose(evidence: dict) -> list[Finding]:
    """Run every registered rule over one evidence snapshot; findings
    sorted critical-first, then by rule name for determinism."""
    found = []
    for name in sorted(DOCTOR_RULES):
        f = _RULES[name](evidence)
        if f is not None:
            found.append(f)
    order = {s: i for i, s in enumerate(SEVERITIES)}
    found.sort(key=lambda f: (-order[f.severity], f.rule))
    return found


def plan_findings(plan_attrs: dict) -> list[dict]:
    """Compact doctor block for ``SortPlan.digest()``: only the
    plan-shaped rules (cap_thrash, window_misfit) evaluated over one
    plan's attrs — a mis-planned run self-describes in its digest."""
    ev = empty_evidence()
    ev["plans"] = [plan_attrs] if isinstance(plan_attrs, dict) else []
    out = []
    for name in ("cap_thrash", "window_misfit"):
        f = _RULES[name](ev)
        if f is not None:
            out.append({"rule": f.rule, "severity": f.severity,
                        "summary": f.summary})
    return out


def render(findings: list[Finding]) -> str:
    """Human-readable findings report (the ``report.py --doctor``
    output)."""
    if not findings:
        return "doctor: no findings — all registered pathology rules " \
               "are quiet"
    lines = [f"doctor: {len(findings)} finding(s)"]
    for f in findings:
        lines.append(f"\n[{f.severity.upper()}] {f.rule}: {f.summary}")
        for cite in f.evidence:
            lines.append(f"    evidence: {cite}")
        if f.knob:
            lines.append(f"    suggest : {f.knob} -> {f.direction}")
    return "\n".join(lines)
