"""Streaming ingest/egress: chunked, double-buffered host↔device transfer.

Why this layer exists (ISSUE 2): the on-device sort runs at hundreds of
Mkeys/s, but the host path around it used to be fully serial — read the
whole file, ``codec.encode`` the whole array on one thread, then push it
through a single monolithic ``jax.device_put``.  End-to-end throughput
collapsed to a third of the device sort.  This module replaces that
serial staircase with a three-stage pipeline over fixed-size chunks:

* **parse** (producer thread): materialize chunk k — an mmap page-in for
  SORTBIN1 slices, a slice view for in-memory arrays — and hand it to a
  bounded queue (depth 2: double buffering, not unbounded buffering).
* **encode** (``SORT_INGEST_THREADS`` pool): encode chunk k into uint32
  key words while chunk k-1 is still transferring; also folds the
  chunk's per-word min/max (the radix pass planner's input), the
  running native max key (the padding value) and the verifier
  fingerprint, so the sort needs NO extra host pass over the data
  afterwards.  The stage is engine-dispatched (ISSUE 6,
  ``SORT_NATIVE_ENCODE``): the native C kernel
  (:mod:`mpitest_tpu.utils.native_encode`) does all of that in ONE
  GIL-released pass — for mmap'd SORTBIN1 it reads the pages in place,
  so the host path is zero-copy (mmap → fold → staging words → DMA);
  the Python engine is the original numpy multi-pass path, preserved
  bit-for-bit as fallback and parity oracle.
* **transfer** (one dedicated thread, in order): split the encoded chunk
  at shard boundaries (``parallel.mesh.shard_bounds``), ``device_put``
  each piece onto its owning device, and block until that chunk's DMA
  completes.  One thread keeps per-device piece lists ordered; being a
  *separate* thread is what makes the DMA of chunk k genuinely overlap
  the encode of chunk k+1 on the wall clock.

Each stage records its own ``ingest.*`` span (thread-safe
``SpanLog.record``), so ``python -m mpitest_tpu.report`` can show the
overlapped timeline and compute overlap efficiency from the same run.

The pipeline ends by gluing the per-device pieces (plus max-key padding)
into one key-sharded global array via
``jax.make_array_from_single_device_arrays`` — no host-side concatenate,
no second copy.  The result travels as a :class:`StagedIngest`, which
``models.api.sort`` accepts in place of raw keys (skipping its own
encode/pad), and whose word buffers the sort dispatch may *donate* back
to XLA so device memory is reused rather than doubled.

Egress is the mirror image (:func:`stream_result_to_numpy`): a fetch
thread pulls shard k+1 device→host while the decode of shard k runs,
emitting ``egress.*`` spans.  Decode is elementwise (the codec is an
order-preserving bijection), so per-shard decode is exact.

Host-memory bound: at most ~(``SORT_INGEST_THREADS`` + 4) chunks live at
once (2 queued parses, up to ``threads`` encodes in flight, 2 transfers
buffered) — a 2^30-key SORTBIN1 file streams through tens of MiB of
host memory instead of 8 GiB (mmap slices page in per chunk).  Text
inputs materialize once on read — shard bounds need the total key count
before the first DMA — and then pipeline from the in-memory array.
"""

from __future__ import annotations

import math
import queue
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import TYPE_CHECKING

import jax
import numpy as np

from mpitest_tpu import faults
from mpitest_tpu.models.supervisor import verify_enabled
from mpitest_tpu.models.verify import Fingerprint
from mpitest_tpu.ops.keys import codec_for
from mpitest_tpu.parallel.mesh import assemble_sharded, shard_bounds
from mpitest_tpu.utils import io as kio
from mpitest_tpu.utils import native_encode
from mpitest_tpu.utils.spans import (SpanLog, merge_intervals,
                                     overlap_seconds)

if TYPE_CHECKING:
    from jax.sharding import Mesh

    from mpitest_tpu.utils.trace import Tracer

#: ``SORT_INGEST=auto`` streams only above this many key *bytes* — below
#: it the monolithic path's single device_put beats the pipeline's
#: thread machinery (measured crossover is ~10 MiB; 32 MiB is safely
#: past it and keeps tiny test inputs on the legacy path unless forced).
STREAM_MIN_BYTES = 1 << 25

#: ``auto`` egress streaming threshold (result bytes per the same logic).
EGRESS_MIN_BYTES = 1 << 22


def checked_device_put(x: "np.ndarray | jax.Array",
                       target: "jax.sharding.Sharding | jax.Device",
                       ) -> jax.Array:
    """``jax.device_put`` with a dtype-preservation guard: raises on ANY
    host→device dtype change instead of JAX's silent downcast.  Without
    x64, ``device_put`` of an int64/uint64/float64 host array silently
    lands a 32-bit shadow — a wrong *sort input*, not an error (the
    bench.py:171 hazard, observed producing a wrong float64 sort).  The
    ingest path routes every host→device transfer through here."""
    out = jax.device_put(x, target)
    src = np.dtype(x.dtype)
    if np.dtype(out.dtype) != src:
        raise TypeError(
            f"jax.device_put changed dtype {src} -> {out.dtype}: 64-bit "
            "host keys need jax_enable_x64 (the silent downcast would "
            "corrupt the sort input, not just its precision)"
        )
    return out


def use_stream(n_bytes: int) -> bool:
    """Resolve the SORT_INGEST mode against the input size."""
    mode = kio.ingest_mode()
    if mode == "stream":
        return True
    if mode == "mono":
        return False
    return n_bytes >= STREAM_MIN_BYTES


@dataclass
class IngestStats:
    """Wall/stage accounting of one streamed ingest — the source of the
    bench sub-metrics (parse/encode/transfer seconds, overlap)."""

    n: int = 0
    chunks: int = 0
    host_bytes: int = 0       # native key bytes read
    device_bytes: int = 0     # encoded word bytes shipped (pads included)
    parse_s: float = 0.0
    encode_s: float = 0.0
    transfer_s: float = 0.0
    wall_s: float = 0.0
    #: encode engine the run actually used ("native" | "python") — the
    #: observable half of the SORT_NATIVE_ENCODE=auto contract: a
    #: degraded fallback shows up here, in spans, and in bench rows.
    encode_engine: str = "python"
    host_iv: list = field(default_factory=list)  # (t0, t1) parse/encode
    xfer_iv: list = field(default_factory=list)  # (t0, t1) transfers

    def overlap_efficiency(self) -> float:
        """Fraction of transfer wall time hidden under host parse/encode
        work — interval intersection on one perf_counter timeline, the
        exact quantity ``report.py --require-ingest-overlap`` gates on.
        (A sum-of-stage-seconds formula would double-count concurrent
        encode workers and report fake overlap for a pipeline whose DMA
        never ran alongside host work.)"""
        xm = merge_intervals(self.xfer_iv)
        xfer = sum(b - a for a, b in xm)
        if xfer <= 0:
            return 0.0
        return overlap_seconds(merge_intervals(self.host_iv), xm) / xfer


@dataclass
class StagedIngest:
    """Encoded, padded, mesh-sharded key words plus everything the sort
    needs to plan without another pass over the data.  ``models.api.sort``
    accepts this in place of raw keys."""

    words: tuple                     # sharded [P*n] uint32 arrays, msw first
    n_valid: int                     # real keys (excludes padding)
    dtype: np.dtype
    word_diffs: tuple                # per-word max^min (pass-planner input)
    mesh: object
    stats: IngestStats
    #: host source for donation-retry rebuilds (sort may donate `words`
    #: to the SPMD program; an exchange-overflow retry then re-streams
    #: from here).  None ⇒ the caller keeps no source and the sort must
    #: not donate.
    source: np.ndarray | None = None
    #: pipeline configuration of the run that produced this — a rebuild
    #: must replay the SAME tracer/chunking, not silently fall back to
    #: env defaults (spans would vanish from the overlap tables).
    tracer: object | None = None
    chunk_elems: int | None = None
    threads: int | None = None
    #: set by a DONATED sort dispatch: the word buffers were handed to
    #: XLA and are dead.  A staged object is single-use under donation —
    #: sort() raises on reuse instead of dispatching on deleted arrays
    #: (use :meth:`rebuild` for another sort).
    consumed: bool = False
    #: input-side multiset fingerprint (models/verify.py), folded
    #: chunk-by-chunk by the encode workers — the half the always-on
    #: output verifier compares against; None only when verification
    #: was disabled during staging.
    fingerprint: "Fingerprint | None" = None

    @property
    def size(self) -> int:
        """Key count — mirrors ndarray.size so telemetry and callers can
        treat staged input like an array."""
        return self.n_valid

    def rebuild(self) -> "StagedIngest":
        if self.source is None:
            raise ValueError("StagedIngest has no source to re-stream from")
        return stream_to_mesh(self.source, self.mesh, tracer=self.tracer,
                              chunk_elems=self.chunk_elems,
                              threads=self.threads)


class _StreamState:
    """Cross-thread accumulator for stats and planner inputs."""

    def __init__(self, n_words: int, fold_fp: bool = True) -> None:
        self.lock = threading.Lock()
        self.word_min = [None] * n_words
        self.word_max = [None] * n_words
        self.native_max = None
        self.stats = IngestStats()
        #: running input fingerprint (models/verify.py): XOR + wrapping
        #: sum + count per word, folded chunk-by-chunk so the output
        #: verifier needs no second pass over the data.  ``fold_fp=False``
        #: (SORT_VERIFY=0) skips the per-chunk scans entirely — the A/B
        #: baseline must not silently pay verification cost.
        self.fold_fp = fold_fp
        self.fp = Fingerprint.empty(n_words) if fold_fp else None

    def apply_fold(self, los: list, his: list, m: object,
                   chunk_fp: "Fingerprint | None",
                   t0: float, dt_s: float) -> None:
        """Merge one chunk's already-computed reductions (engine output,
        utils/native_encode.encode_and_fold — the expensive scans ran
        OUTSIDE the lock, on the encode worker) into the running state;
        only these scalar folds need mutual exclusion."""
        with self.lock:
            self.stats.encode_s += dt_s
            self.stats.host_iv.append((t0, t0 + dt_s))
            if chunk_fp is not None:
                self.fp = self.fp.combine(chunk_fp)
            for i, (lo, hi) in enumerate(zip(los, his)):
                if self.word_min[i] is None or lo < self.word_min[i]:
                    self.word_min[i] = lo
                if self.word_max[i] is None or hi > self.word_max[i]:
                    self.word_max[i] = hi
            if m is not None and (self.native_max is None
                                  or m > self.native_max):
                self.native_max = m

    def word_diffs(self, n_words: int) -> tuple:
        return tuple(
            (self.word_max[i] ^ self.word_min[i])
            if self.word_min[i] is not None else 0
            for i in range(n_words)
        )


def _spans_of(tracer: "Tracer | None") -> "SpanLog | None":
    return tracer.spans if tracer is not None else None


def stream_to_mesh(x: np.ndarray, mesh: "Mesh",
                   tracer: "Tracer | None" = None,
                   chunk_elems: int | None = None,
                   threads: int | None = None) -> StagedIngest:
    """Run the full parse→encode→DMA pipeline over host keys ``x`` (a
    numpy array — possibly mmap-backed, in which case chunks page in
    lazily) and return the :class:`StagedIngest` the sort consumes.

    Deterministic by construction: chunk boundaries are fixed arithmetic,
    encode is elementwise, and the single transfer thread lands pieces in
    chunk order — the resulting sharded words are bit-identical to the
    monolithic path's.
    """
    t_wall = time.perf_counter()
    x = np.asarray(x).reshape(-1)
    dtype = np.dtype(x.dtype)
    codec = codec_for(dtype)
    N = int(x.size)
    if N == 0:
        raise ValueError("cannot stream an empty key array")
    chunk_elems = chunk_elems or kio.ingest_chunk_elems()
    threads = threads or kio.ingest_threads()
    # engine resolved ONCE per run (SORT_NATIVE_ENCODE=on raises here,
    # before any thread starts, if the library is missing)
    eng = native_encode.engine()
    n_ranks = int(mesh.devices.size)
    n = max(1, math.ceil(N / n_ranks))
    total = n_ranks * n
    bounds = shard_bounds(mesh, n)
    spans = _spans_of(tracer)
    state = _StreamState(codec.n_words, fold_fp=verify_enabled())
    state.stats.n = N
    state.stats.encode_engine = eng
    # chunk k's pieces per device, appended in chunk order by the single
    # transfer thread: per_dev[d] = [piece0_words, piece1_words, ...]
    per_dev: list[list[tuple]] = [[] for _ in bounds]
    # mmap-backed sources: with the PYTHON engine the parse stage
    # materializes the slice (the page-in IS the parse); the NATIVE
    # engine skips that copy entirely — the C kernel reads the mmap
    # pages in-place during its single encode pass, so SORTBIN1 ingest
    # is zero-copy on the host (mmap -> fold -> staging words, ISSUE 6).
    # Walk the full base chain — asarray/reshape wrap the memmap in
    # plain views.
    materialize = False
    if eng != "native":
        _b = x
        while _b is not None:
            if isinstance(_b, np.memmap):
                materialize = True
                break
            _b = getattr(_b, "base", None)

    abort = threading.Event()

    def _put(q: queue.Queue, item) -> bool:
        """Bounded put that gives up when the consumer aborted — the
        producer must never block forever on a full queue nobody will
        drain (that would leak the thread AND pin ``x`` for process
        lifetime)."""
        while not abort.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def parse_chunks(q: queue.Queue):
        try:
            off = 0
            k = 0
            while off < N:
                t0 = time.perf_counter()
                c = x[off:off + chunk_elems]
                if materialize:
                    c = np.array(c)   # fault the pages in, off-thread
                dt = time.perf_counter() - t0
                with state.lock:
                    state.stats.parse_s += dt
                    state.stats.host_iv.append((t0, t0 + dt))
                    state.stats.chunks += 1
                    state.stats.host_bytes += c.nbytes
                if spans is not None:
                    spans.record("ingest.parse", t0, dt, chunk=k,
                                 n=int(c.size), bytes=int(c.nbytes))
                if not _put(q, (k, off, c)):
                    return
                off += c.size
                k += 1
            _put(q, None)
        except BaseException as e:  # surface parse failures to the consumer
            _put(q, e)

    def encode_one(k: int, chunk):
        # engine-dispatched one-call encode stage: words + per-word
        # min/max + pad key + fingerprint in one pass (native: a single
        # GIL-released C sweep that also faults the mmap pages in).
        # The timed interval covers the WHOLE stage for both engines,
        # so encode_s / encode_gb_per_s compare like for like.
        t0 = time.perf_counter()
        words, los, his, m, chunk_fp = native_encode.encode_and_fold(
            chunk, codec, state.fold_fp, eng)
        dt = time.perf_counter() - t0
        state.apply_fold(los, his, m, chunk_fp, t0, dt)
        # fault injection (SORT_FAULTS=ingest_poison): corrupt AFTER the
        # fingerprint fold — the device receives bytes the fingerprint
        # never saw, which the output verifier must flag.
        words = faults.maybe_poison_chunk(words, k)
        if spans is not None:
            spans.record("ingest.encode", t0, dt, chunk=k,
                         n=int(chunk.size), engine=eng,
                         bytes=int(sum(w.nbytes for w in words)))
        return words

    def transfer_one(k: int, off: int, words, pad: bool = False):
        t0 = time.perf_counter()
        clen = words[0].size
        # issue EVERY per-device put before blocking on any: a chunk
        # spanning k shard boundaries then runs its k DMAs concurrently
        # instead of serializing device-by-device
        placed = []
        for d, (dev, start, stop) in enumerate(bounds):
            a = max(off, start)
            b = min(off + clen, stop)
            if a >= b:
                continue
            placed.append((d, tuple(
                checked_device_put(w[a - off:b - off], dev) for w in words
            )))
        nbytes = 0
        for d, piece in placed:
            for p in piece:
                p.block_until_ready()
                nbytes += p.nbytes
            per_dev[d].append(piece)
        dt = time.perf_counter() - t0
        with state.lock:
            state.stats.transfer_s += dt
            state.stats.xfer_iv.append((t0, t0 + dt))
            state.stats.device_bytes += nbytes
        if spans is not None:
            attrs = {"chunk": k, "bytes": int(nbytes)}
            if pad:
                attrs["pad"] = True
            spans.record("ingest.transfer", t0, dt, **attrs)

    q: queue.Queue = queue.Queue(maxsize=2)
    # threadlint TL010: named like its registered root (ingest-parse)
    producer = threading.Thread(target=parse_chunks, args=(q,),
                                name="ingest-parse", daemon=True)
    producer.start()
    enc_pool = ThreadPoolExecutor(threads, thread_name_prefix="ingest-enc")
    xfer_pool = ThreadPoolExecutor(1, thread_name_prefix="ingest-xfer")
    try:
        encodes: deque = deque()   # (k, off, future) in chunk order
        xfers: deque = deque()     # transfer futures in chunk order

        def drain_encode_front():
            k0, off0, ef = encodes.popleft()
            xfers.append(xfer_pool.submit(transfer_one, k0, off0, ef.result()))
            while len(xfers) > 2:   # double buffer: ≤2 chunk DMAs buffered
                xfers.popleft().result()

        while True:
            item = q.get()
            if item is None:
                break
            if isinstance(item, BaseException):
                raise item
            k, off, chunk = item
            encodes.append((k, off, enc_pool.submit(encode_one, k, chunk)))
            # hand finished encodes to the transfer thread eagerly (the
            # DMA of chunk k starts the moment it is encoded), but let
            # up to `threads` encodes run concurrently before blocking
            # on the oldest — SORT_INGEST_THREADS>2 buys real encode
            # parallelism instead of being a silent no-op.
            while encodes and (encodes[0][2].done()
                               or len(encodes) > threads):
                drain_encode_front()
        while encodes:
            drain_encode_front()
        while xfers:
            xfers.popleft().result()
        producer.join()

        # padding: replicate the maximum real key (float codecs use the
        # totalOrder sentinel) — same contract as the monolithic path.
        # The pad rides transfer_one as a synthetic tail chunk at offset
        # N, so placement/accounting/spans stay in one place (total-N is
        # always < n_ranks: ceil division leaves less than one shard).
        if total > N:
            if codec.sentinel_pad:
                pad_words = codec.max_sentinel()
            else:
                pad_words = tuple(
                    int(w[0]) for w in codec.encode(
                        np.asarray([state.native_max], dtype))
                )
            transfer_one(-1, N, tuple(
                np.full(total - N, pw, np.uint32) for pw in pad_words
            ), pad=True)
    finally:
        # unblock + reap the producer FIRST (it may be parked on a full
        # queue); a leaked producer would pin x for process lifetime
        abort.set()
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                break
        producer.join(timeout=5.0)
        enc_pool.shutdown(wait=True)
        xfer_pool.shutdown(wait=True)

    # per-device shard assembly: single piece passes through untouched;
    # multi-piece shards concatenate ON the owning device (the pieces are
    # committed there, so eager concatenate never touches the host)
    import jax.numpy as jnp

    words_global = []
    for wi in range(codec.n_words):
        shards = []
        for d in range(len(bounds)):
            pieces = [p[wi] for p in per_dev[d]]
            shards.append(pieces[0] if len(pieces) == 1
                          else jnp.concatenate(pieces))
        words_global.append(assemble_sharded(mesh, shards, total))
    state.stats.wall_s = time.perf_counter() - t_wall
    if spans is not None:
        spans.record("ingest.pipeline", t_wall, state.stats.wall_s,
                     n=N, chunks=state.stats.chunks,
                     encode_engine=eng,
                     parse_s=round(state.stats.parse_s, 6),
                     encode_s=round(state.stats.encode_s, 6),
                     transfer_s=round(state.stats.transfer_s, 6),
                     overlap_efficiency=round(
                         state.stats.overlap_efficiency(), 4))
    return StagedIngest(
        words=tuple(words_global), n_valid=N, dtype=dtype,
        word_diffs=state.word_diffs(codec.n_words), mesh=mesh,
        stats=state.stats, source=x,
        tracer=tracer, chunk_elems=chunk_elems, threads=threads,
        fingerprint=state.fp,
    )


def stream_result_to_numpy(words: tuple[jax.Array, ...], n_valid: int,
                           dtype: "np.dtype | str",
                           tracer: "Tracer | None" = None) -> np.ndarray:
    """Streamed egress for contiguous (non-ragged) sorted results: fetch
    shard k+1 device→host on a dedicated thread while shard k decodes —
    the mirror image of the ingest pipeline, with ``egress.*`` spans.

    Per-shard decode is exact because the codec is elementwise; shard
    boundaries come from the arrays' own ``addressable_shards`` indices,
    so any 1-D block layout (including the last shard's pad tail) is
    handled by intersection with ``[0, n_valid)``.
    """
    codec = codec_for(np.dtype(dtype))
    # multi-host meshes: this process only sees its own shards, so the
    # streamed decode would leave remote-shard ranges of `out` as
    # uninitialized memory — refuse loudly (the legacy gather path
    # raises on non-addressable arrays; silence would be wrong data).
    if not getattr(words[0], "is_fully_addressable", True):
        raise ValueError(
            "streamed egress requires fully addressable result shards; "
            "on a multi-process mesh gather per-process results instead")
    spans = _spans_of(tracer)
    out = np.empty(n_valid, np.dtype(dtype))
    shard_lists = [w.addressable_shards for w in words]
    n_shards = len(shard_lists[0])

    def fetch(i: int):
        t0 = time.perf_counter()
        sl = shard_lists[0][i].index[0]
        host = tuple(np.asarray(sl_w[i].data) for sl_w in shard_lists)
        dt = time.perf_counter() - t0
        if spans is not None:
            spans.record("egress.fetch", t0, dt, shard=i,
                         bytes=int(sum(h.nbytes for h in host)))
        return sl, host

    def decode(i: int, sl, host):
        a = sl.start or 0
        b = min(sl.stop if sl.stop is not None else n_valid, n_valid)
        if a >= b:
            return
        t0 = time.perf_counter()
        out[a:b] = codec.decode(tuple(h[: b - a] for h in host))
        dt = time.perf_counter() - t0
        if spans is not None:
            spans.record("egress.decode", t0, dt, shard=i,
                         n=int(b - a),
                         bytes=int((b - a) * out.itemsize))

    with ThreadPoolExecutor(1, thread_name_prefix="egress-fetch") as pool:
        nxt = pool.submit(fetch, 0)
        for i in range(n_shards):
            sl, host = nxt.result()
            if i + 1 < n_shards:
                nxt = pool.submit(fetch, i + 1)
            decode(i, sl, host)
    return out
