"""Plan provenance — the typed record of every runtime decision a sort makes.

The reference programs decide nothing at runtime: algorithm, radix and
buffer sizes are compile-time constants (``mpi_radix_sort.c`` bakes the
digit width; ``mpi_sample_sort.c:140`` hard-codes ``1.5*size_bucket``).
mpitest_tpu makes dozens of consequential decisions per request — algo
reroute, capacity negotiation, skew re-stage, engine selection, pass
count, fallback-ladder rung, serve batching/bucketing — and PR 8's
telemetry records *what executed*, not *what was decided or why*.  This
module is the missing record: a :class:`SortPlan` minted at the
decision chokepoints (``models/api.py``, ``models/supervisor.py``,
``serve/server.py`` + ``serve/batching.py``), each decision carrying
the **predicted** quantity at decision time and the **actual** one
stamped at completion, folded into a ``regret`` scalar per decision —
so a mis-sized cap, a wasted re-stage or a wrong reroute is a number in
``/metrics`` and one line in ``report.py --explain``, not an anecdote.

The decision vocabulary is REGISTERED here (:data:`PLAN_DECISIONS`),
exactly like span names in ``utils/span_schema.py`` and metric names in
``utils/metrics_live.py``: ``report.py --explain`` and the ``/varz``
decision snapshot key on these strings, and sortlint rule ``SL005``
fails the lint gate on any literal decision name outside the registry.

Regret semantics (the ONE definition, unit-tested in
``tests/test_plan.py``): regret is a unitless scalar >= 0 per decision.
0 means the prediction matched reality and the decision cost nothing it
did not have to; each avoidable full re-dispatch (overflow regrow,
wasted re-stage, late reroute, ladder descent) costs 1.0; sizing
decisions add their relative prediction error ``|predicted - actual| /
max(actual, 1)``.  The plan's total regret is the sum over decisions.

This module is import-light on purpose (stdlib only at import time —
numpy loads lazily inside the profiler functions): sortlint loads it by
file path with no package context, like ``span_schema.py``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

#: Version tag of the plan record (the ``sort.plan`` span's ``plan_v``).
PLAN_SCHEMA = "plan.v1"

#: Registered decision vocabulary: name -> one-line doc of what was
#: decided and who decides it.  sortlint SL005 fails the gate on any
#: literal ``plan.decide(...)`` / ``plan.actual(...)`` name outside
#: this dict (same loader pattern as SL003 spans / SL004 metrics).
PLAN_DECISIONS: dict[str, str] = {
    "algo": ("sort algorithm actually run vs requested (skew reroutes: "
             "sniff / probe-estimate / reactive cap-exceeded)"),
    "cap": ("exchange capacity: negotiation mode (exact/estimate/off), "
            "chosen cap vs probe-predicted need vs measured need; "
            "overflow regrows stamped by the supervisor"),
    "restage": ("skew-aware re-stage verdict + trigger (probe/overflow); "
                "predicted vs post-restage peer ratio"),
    "engine": ("exchange-pack and local-sort engine selection "
               "(xla/pallas pack, lax/bitonic/radix_pallas local); a "
               "local-engine degrade (trigger=pallas_fault for "
               "dispatch faults, verify_failure for failed "
               "verification) is this decision's regret, beside the "
               "pair-residual fallbacks"),
    "exchange_engine": ("inter-device exchange engine (ISSUE 13): "
                        "lax collective vs pallas remote-DMA + fused "
                        "pass; a degrade to lax (trigger=pallas_fault "
                        "for dispatch faults, verify_failure for "
                        "failed verification) is this decision's "
                        "regret"),
    "passes": ("radix pass plan: digit width + pass count from the "
               "word-diff planner vs passes actually dispatched"),
    "ladder": ("fallback-ladder rung the result came from; descents "
               "and supervisor dispatch retries are its regret"),
    "batch": ("serve batching: window close reason, members packed, "
              "bucket chosen; predicted vs actual padded-lane waste"),
    "planner": ("self-tuning planner verdict (ISSUE 14): the scored "
                "policy (models/planner.py PLANNER_POLICIES, SL006), "
                "its profile trigger, whether it was applied (on) or "
                "only logged (shadow), the learned margin evidence; a "
                "passthrough miss (the strided profile lied and the "
                "verify pass was wasted) is this decision's regret"),
    "external": ("out-of-core tier verdict (ISSUE 15): the request "
                 "spilled to sorted runs + k-way merge under "
                 "SORT_MEM_BUDGET (predicted budget/fan-in vs actual "
                 "runs/disk bytes/merge passes); each integrity "
                 "recovery — a re-spilled run + re-merge — is this "
                 "decision's regret"),
}

#: Registered input-distribution profile fields (the probe-riding
#: profiler's vocabulary — recorded on the plan and the sort.plan span).
PLAN_PROFILE_FIELDS: tuple[str, ...] = (
    "sortedness", "run_len", "dup_ratio", "bin_entropy", "skew_factor",
    "key_width")


def relative_regret(predicted: float, actual: float) -> float:
    """The sizing-regret rule: relative prediction error, floored so a
    tiny actual cannot blow the ratio up (``|p - a| / max(|a|, 1)``)."""
    return abs(float(predicted) - float(actual)) / max(abs(float(actual)),
                                                       1.0)


def _scalar(v: Any) -> Any:
    """JSON-safe scalar: numpy ints/floats/bools degrade to Python ones
    (span attrs stream as JSON; an int64 leaking in would crash the
    JSONL append mid-sort)."""
    if isinstance(v, bool) or v is None or isinstance(v, (int, str)):
        return v
    if isinstance(v, float):
        return round(v, 6)
    item = getattr(v, "item", None)
    if callable(item):
        try:
            return _scalar(item())
        except Exception:  # noqa: BLE001 — telemetry must not raise
            return str(v)
    return str(v)


def _clean(d: dict[str, Any]) -> dict[str, Any]:
    return {k: _scalar(v) for k, v in d.items() if v is not None}


@dataclass
class Decision:
    """One recorded decision: what was chosen (vs requested), why
    (``trigger``), what was predicted at decision time, and what
    actually happened — with the folded ``regret`` scalar."""

    name: str
    chosen: Any = None
    requested: Any = None
    trigger: str | None = None
    predicted: dict[str, Any] = field(default_factory=dict)
    actual: dict[str, Any] = field(default_factory=dict)
    regret: float | None = None

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {"chosen": _scalar(self.chosen)}
        if self.requested is not None:
            out["requested"] = _scalar(self.requested)
        if self.trigger is not None:
            out["trigger"] = self.trigger
        if self.predicted:
            out["predicted"] = _clean(self.predicted)
        if self.actual:
            out["actual"] = _clean(self.actual)
        if self.regret is not None:
            out["regret"] = round(float(self.regret), 6)
        return out


class SortPlan:
    """The per-run decision record.  Minted once per sort (or per
    packed serve dispatch), carried on the run's ``Tracer``; decisions
    accumulate through :meth:`decide` / :meth:`actual`, and
    :meth:`finalize` folds the per-decision regrets.  All methods are
    no-fail by contract — provenance must never take down the sort it
    describes."""

    def __init__(self, algo: str | None = None, n: int | None = None,
                 dtype: str | None = None, ranks: int | None = None,
                 ) -> None:
        self.algo = algo
        self.n = n
        self.dtype = dtype
        self.ranks = ranks
        self.decisions: dict[str, Decision] = {}
        self.profile: dict[str, float] = {}
        self.finalized = False
        #: snapshot of a cumulative tracer counter at mint time — the
        #: minting layer records it so completion stamps per-run deltas
        #: (a reused serve tracer accumulates across requests).
        self.fallbacks_base = 0

    # -- recording ----------------------------------------------------
    def decide(self, name: str, chosen: Any, requested: Any = None,
               trigger: str | None = None, **predicted: Any) -> Decision:
        """Record (or re-record — a reroute overwrites ``chosen`` /
        ``trigger`` while keeping earlier predictions) one decision.
        ``name`` must come from :data:`PLAN_DECISIONS` (SL005)."""
        d = self.decisions.get(name)
        if d is None:
            d = self.decisions[name] = Decision(name)
        d.chosen = chosen
        if requested is not None:
            d.requested = requested
        if trigger is not None:
            d.trigger = trigger
        d.predicted.update(predicted)
        if name == "algo" and isinstance(chosen, str):
            # the plan's headline algo is the one that RAN: a reroute
            # must update the span head / digest / by-algo census, not
            # just the decision row (the requested algo stays on it)
            self.algo = chosen
        return d

    def actual(self, name: str, **measured: Any) -> None:
        """Stamp measured outcomes onto a decision at completion (merge
        semantics; later stamps win per key).  Stamping a decision that
        was never made records the measurement alone — the explain view
        shows it as an outcome without a recorded choice, which is
        itself a provenance finding."""
        d = self.decisions.get(name)
        if d is None:
            d = self.decisions[name] = Decision(name)
        d.actual.update(measured)

    def bump(self, name: str, key: str, amount: float = 1.0) -> None:
        """Accumulate a counter-like actual (e.g. supervisor regrows /
        retries) — merge-overwrite semantics would lose earlier
        increments."""
        d = self.decisions.get(name)
        if d is None:
            d = self.decisions[name] = Decision(name)
        d.actual[key] = float(d.actual.get(key, 0)) + amount

    # -- regret folding ----------------------------------------------
    def _regret_of(self, d: Decision) -> float:
        """The per-decision regret rule (see module docstring)."""
        p, a = d.predicted, d.actual
        if d.name == "cap":
            # sizing error vs the measured need + one unit per overflow
            # regrow (each is a full discarded exchange dispatch).  With
            # negotiation OFF the cap machinery could neither see nor
            # fix the exchange imbalance, so the whole need-above-fair
            # overhead is this decision's regret too — that is exactly
            # the term SORT_NEGOTIATE=off raises on a skewed input
            # (when a probe ran, the imbalance is the restage
            # decision's to answer for, and cap regret is pure sizing).
            regrows = float(a.get("regrows", 0) or 0)
            cap = p.get("cap")
            need = a.get("need", p.get("need"))
            r = regrows
            if cap is not None and need is not None:
                r += relative_regret(float(cap), float(need))
            if d.trigger == "off":
                fair = p.get("fair")
                if fair and need is not None:
                    r += max(0.0, float(need) / float(fair) - 1.0)
            return r
        if d.name == "restage":
            if d.chosen:
                # a re-stage that did not improve the peer ratio was a
                # wasted full resharding pass
                before = p.get("peer_ratio")
                after = a.get("peer_ratio")
                if before is not None and after is not None \
                        and float(after) >= float(before):
                    return 1.0
                return 0.0
            # not restaged: the overflow cost is already charged to the
            # cap decision (regrows) — no double count here
            return 0.0
        if d.name == "algo":
            # a LATE reroute paid a doomed full exchange before
            # switching; an up-front one (sniff/probe) costs nothing
            return 1.0 if a.get("late_reroute") else 0.0
        if d.name == "passes":
            planned = p.get("passes", d.chosen)
            ran = a.get("passes")
            if planned is not None and ran is not None:
                return relative_regret(float(planned), float(ran))
            return 0.0
        if d.name == "ladder":
            return (float(a.get("rungs_descended", 0) or 0)
                    + float(a.get("dispatch_retries", 0) or 0))
        if d.name == "batch":
            # padded lanes are pure overhead; the prediction error on
            # top shows a window that closed on stale information
            waste = float(a.get("waste", p.get("waste", 0.0)) or 0.0)
            pred = p.get("waste")
            extra = (relative_regret(float(pred), waste)
                     if pred is not None and "waste" in a else 0.0)
            return waste + extra
        if d.name == "engine":
            # an engine whose residual fallback ran paid both engines;
            # a local-engine ladder degrade (fused radix -> lax, same
            # trigger classes as exchange_engine) paid every dispatch
            # up to the switch on top
            return (float(a.get("fallbacks", 0) or 0)
                    + (1.0 if d.trigger in ("pallas_fault",
                                            "verify_failure") else 0.0))
        if d.name == "planner":
            # the planner's own cost: each passthrough miss paid one
            # verify dispatch that proved nothing (the strided profile
            # hid a descent) before the ladder sorted for real.  A
            # shadow decision (applied False) changed nothing and can
            # regret nothing.
            return float(a.get("misses", 0) or 0)
        if d.name == "external":
            # each recovery paid one blamed-run re-spill + a full
            # re-merge before the verified result
            return float(a.get("recoveries", 0) or 0)
        if d.name == "exchange_engine":
            # either degrade cause paid every dispatch up to the switch
            # before the lax rung re-ran the whole algorithm; the
            # trigger names the cause class (kernel fault vs failed
            # verification, which may equally implicate the data)
            return 1.0 if d.trigger in ("pallas_fault",
                                        "verify_failure") else 0.0
        return 0.0

    def finalize(self) -> float:
        """Fold per-decision regrets; returns the plan's total regret.
        Idempotent (re-finalizing re-folds from the current stamps)."""
        total = 0.0
        for d in self.decisions.values():
            try:
                d.regret = round(self._regret_of(d), 6)
            except (TypeError, ValueError):
                d.regret = 0.0
            total += d.regret
        self.total_regret = round(total, 6)
        self.finalized = True
        return self.total_regret

    # -- export -------------------------------------------------------
    def to_attrs(self) -> dict[str, Any]:
        """The ``sort.plan`` span's attrs: everything, JSON-safe."""
        if not self.finalized:
            self.finalize()
        return {
            "plan_v": PLAN_SCHEMA,
            "algo": self.algo,
            "n": _scalar(self.n),
            "dtype": self.dtype,
            "ranks": _scalar(self.ranks),
            "regret": getattr(self, "total_regret", 0.0),
            "decisions": {k: d.to_dict()
                          for k, d in sorted(self.decisions.items())},
            "profile": _clean(self.profile),
        }

    def digest(self) -> dict[str, Any]:
        """Compact wire digest (the ``sortserve.v1`` response header's
        ``plan`` field): algo, negotiated cap, restage verdict, total
        regret — enough for a client to notice decision drift without
        shipping the whole record."""
        if not self.finalized:
            self.finalize()
        cap = self.decisions.get("cap")
        restage = self.decisions.get("restage")
        out: dict[str, Any] = {
            "algo": self.algo,
            "regret": getattr(self, "total_regret", 0.0),
        }
        if cap is not None:
            out["negotiated_cap"] = _scalar(cap.predicted.get("cap"))
            out["cap_regret"] = cap.regret
        if restage is not None:
            out["restaged"] = bool(restage.chosen)
        xeng = self.decisions.get("exchange_engine")
        if xeng is not None:
            out["exchange_engine"] = _scalar(xeng.chosen)
        batch = self.decisions.get("batch")
        if batch is not None:
            out["bucket"] = _scalar(batch.chosen)
        pl = self.decisions.get("planner")
        if pl is not None:
            # the planner's verdict rides the wire digest so clients
            # (and the serve_load plan fold) see policy drift directly
            out["planner"] = _scalar(pl.chosen)
            out["planner_regret"] = pl.regret
        ext = self.decisions.get("external")
        if ext is not None:
            # ISSUE 15: the typed evidence an over-budget request was
            # served by the spill tier, not rejected
            out["spilled"] = True
            out["spill_runs"] = _scalar(ext.actual.get("runs"))
            # ISSUE 18: a retried request that warm-resumed from a
            # journaled spill manifest says so in its reply digest
            if _scalar(ext.actual.get("resumed")):
                out["resumed"] = True
        # ISSUE 16: the doctor's plan-shaped verdicts (cap_thrash,
        # window_misfit) ride the digest so a mis-planned run
        # self-describes.  Lazy + best-effort: this module must stay
        # stdlib-only at import (sortlint loads it standalone), and a
        # digest never fails because diagnosis did.
        try:
            from mpitest_tpu.doctor import plan_findings
            df = plan_findings(self.to_attrs())
            if df:
                out["doctor"] = df
        except Exception:
            pass
        return out


def fold_decision_stats(plan_attrs: "list[dict]") -> dict[str, dict]:
    """Per-decision ``{count, regret_sum, regret_max}`` over a list of
    ``sort.plan`` span attr dicts — the ONE fold behind the ``/varz``
    decision snapshot and ``report.py --explain``'s aggregate table
    (two consumers of the same record must not re-implement and
    silently diverge)."""
    out: dict[str, dict] = {}
    for attrs in plan_attrs:
        decisions = (attrs or {}).get("decisions")
        if not isinstance(decisions, dict):
            continue
        for name, d in decisions.items():
            if not isinstance(d, dict):
                continue
            row = out.setdefault(name, {"count": 0, "regret_sum": 0.0,
                                        "regret_max": 0.0})
            row["count"] += 1
            try:
                r = float(d.get("regret", 0.0) or 0.0)
            except (TypeError, ValueError):
                r = 0.0
            row["regret_sum"] += r
            row["regret_max"] = max(row["regret_max"], r)
    return out


# ------------------------------------------------- input-distribution profile

#: Sample size of the host-side profile (the same ~1k strided sample
#: idiom as the skew sniffs — O(s log s), no key movement).
PROFILE_SAMPLE = 1024


def profile_host_array(x: Any, n_profile_sample: int = PROFILE_SAMPLE,
                       ) -> dict[str, float]:
    """Sortedness / run-length / duplicate-ratio estimates from an
    evenly-strided ~1k sample of the host keys — zero extra key
    movement (the values are about to be encoded anyway, and native
    value order IS the sort order for every supported dtype).
    Invariants (pinned in tests/test_plan.py): sorted input →
    sortedness == 1; constant input → dup_ratio == 1; reverse-sorted →
    sortedness ≈ 0.  NaN comparisons are False, so NaN-heavy float
    input reads as unsorted — conservative, never wrong-sided."""
    import numpy as np

    a = np.asarray(x).reshape(-1)
    n = int(a.size)
    if n == 0:
        return {}
    s = int(min(n_profile_sample, n))
    idx = np.linspace(0, n - 1, s).astype(np.int64)
    samp = a[idx]
    nondec = 1.0 if s < 2 else float(np.mean(samp[:-1] <= samp[1:]))
    descents = 0 if s < 2 else int(np.sum(~(samp[:-1] <= samp[1:])))
    # duplicate ratio over the sorted sample, normalized so a constant
    # input is exactly 1.0 and an all-distinct one exactly 0.0
    if s < 2:
        dup = 0.0
    else:
        ss = np.sort(samp)
        dup = float(np.sum(ss[:-1] == ss[1:])) / (s - 1)
    out = {
        "sortedness": round(nondec, 4),
        "run_len": round(s / (descents + 1), 2),
        "dup_ratio": round(dup, 4),
    }
    if np.issubdtype(samp.dtype, np.integer):
        # significant-bit width of the SAMPLED value range (ISSUE 17) —
        # the radix_compact policy's trigger.  A strided sample can
        # miss the true extremes, so this may under-read: the planner's
        # predicted pass count is scored against the pass count the
        # full-range diff planner actually runs ("passes" regret = the
        # lying-profile cost).
        spread = int(samp.max()) - int(samp.min())
        out["key_width"] = int(spread).bit_length()
    return out


def profile_from_counts(cnts: Any, fair: int) -> dict[str, float]:
    """Skew factor and per-bin entropy from the ALREADY-MATERIALIZED
    [P, P] count-probe matrix (the PR 6 negotiation probe — zero extra
    key movement).  ``bin_entropy`` is the normalized Shannon entropy of
    the destination mass (1.0 = perfectly balanced exchange, 0.0 = all
    keys to one peer); ``skew_factor`` is the max single-peer segment
    over the fair share — exactly the quantity that drives capacity."""
    import numpy as np

    c = np.asarray(cnts, dtype=np.float64)
    total = float(c.sum())
    out: dict[str, float] = {
        "skew_factor": round(float(c.max()) / max(int(fair), 1), 4),
    }
    if total > 0 and c.shape[-1] > 1:
        dest = c.sum(axis=0) / total
        nz = dest[dest > 0]
        ent = float(-(nz * np.log(nz)).sum()) / float(np.log(len(dest)))
        out["bin_entropy"] = round(ent, 4)
    return out


def enabled() -> bool:
    """``SORT_PLAN`` gate (on by default): plan provenance is minted,
    emitted as the ``sort.plan`` span and exported through the regret
    metrics; ``off`` restores the PR 8 behavior byte-for-byte."""
    from mpitest_tpu.utils import knobs

    return knobs.get("SORT_PLAN") != "off"
