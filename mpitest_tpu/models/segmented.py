"""Segmented (multi-tenant) batched sort — the pack/dispatch/split core.

The sort server's batching layer (ISSUE 8) packs many concurrent small
requests into ONE device dispatch: per-request dispatch overhead (host
→device staging, program launch, the result sync) dominates small-sort
latency, and a persistent server seeing heavy small-request traffic
amortizes it by sorting many tenants' keys in a single fused program.

Mechanism — segment-ID-prefixed keys: request ``i``'s keys encode
through the ordinary order-preserving codec (``ops/keys.py``) into
uint32 words, and a constant extra word holding the segment id ``i`` is
prepended as the MOST significant word.  A lexicographic sort of the
``(seg, *key_words)`` tuples therefore orders first by segment, then by
key — i.e. it sorts every segment independently in one pass, and each
segment's slice of the output is **bit-identical** to sorting that
request alone (same codec, same comparison; the tests pin this parity
against :func:`mpitest_tpu.models.api.sort`).  Pad lanes carry segment
id ``PAD_SEG`` (the uint32 maximum, above any real id) so they sort to
the global tail past every tenant.

Shapes are power-of-two **buckets** (:func:`bucket_for`): the packed
program is compiled per (word count, bucket), so any mix of request
sizes whose total lands in the same bucket reuses one executable — the
executor cache (``serve/executor_cache.py``) AOT-compiles and memoizes
exactly these.

Verification is per segment, host-side (batches are small by
construction — ``SORT_SERVE_BATCH_KEYS`` caps the packed size): each
segment must be lexicographically sorted AND reproduce the input-side
multiset fingerprint folded at pack time.  A segment that fails (e.g. a
poisoned request, or an injected result fault) is re-run solo under the
PR 3 supervisor by the server — the other tenants' results are already
proven good, so one bad request can never poison its batchmates.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache
from typing import Any, Callable, Sequence

import numpy as np

from mpitest_tpu.models.verify import Fingerprint, fingerprint_host
from mpitest_tpu.ops.keys import KeyCodec, codec_for

#: Segment id of pad lanes — the uint32 maximum, strictly above any real
#: segment id (the batcher caps segments per batch far below it), so
#: pads sort to the global tail past every tenant's keys.
PAD_SEG = 0xFFFFFFFF

#: Smallest bucket: below this the compile zoo costs more than the
#: padding wastes (a 1024-lane uint32 word is 4 KiB).
MIN_BUCKET = 1 << 10


def bucket_for(n: int, min_bucket: int = MIN_BUCKET) -> int:
    """Power-of-two shape bucket for ``n`` packed lanes: the smallest
    power of two >= max(n, min_bucket).  Bucketing is what turns an
    unbounded family of request shapes into a handful of compiled
    executables — warm traffic never compiles."""
    if n < 0:
        raise ValueError(f"bucket_for: negative size {n}")
    b = max(int(min_bucket), 1)
    # next power of two >= max(n, min_bucket)
    target = max(n, b)
    return 1 << (target - 1).bit_length() if target > 1 else 1


@dataclass(frozen=True)
class PackedBatch:
    """One packed multi-tenant batch, host-side: the ``(seg, *words)``
    uint32 arrays (padded to ``bucket``), per-segment geometry, and the
    per-segment input fingerprints the post-sort verification compares
    against."""

    words: tuple[np.ndarray, ...]      # (1 + n_words) uint32, len bucket
    sizes: tuple[int, ...]             # per-segment key counts
    offsets: tuple[int, ...]           # per-segment start lane
    fps: tuple[Fingerprint, ...]       # per-segment input fold (key words)
    dtype: np.dtype
    bucket: int

    @property
    def n_valid(self) -> int:
        return int(sum(self.sizes))

    @property
    def n_segments(self) -> int:
        return len(self.sizes)


def pack_segments(arrays: Sequence[np.ndarray], dtype: np.dtype,
                  bucket: int | None = None) -> PackedBatch:
    """Encode + pack request key arrays into one segment-prefixed word
    tuple padded to a shape bucket.  All arrays must share ``dtype``;
    the segment order is the argument order (and the split order)."""
    codec: KeyCodec = codec_for(dtype)
    if len(arrays) >= PAD_SEG:
        raise ValueError(f"too many segments ({len(arrays)})")
    sizes = tuple(int(a.size) for a in arrays)
    total = sum(sizes)
    if bucket is None:
        bucket = bucket_for(total)
    if total > bucket:
        raise ValueError(f"segments hold {total} keys > bucket {bucket}")
    offsets = tuple(int(v) for v in np.cumsum((0,) + sizes)[:-1])

    seg = np.full(bucket, PAD_SEG, np.uint32)
    key_words = tuple(np.zeros(bucket, np.uint32)
                      for _ in range(codec.n_words))
    fps = []
    for i, a in enumerate(arrays):
        flat = np.asarray(a, dtype=dtype).reshape(-1)
        w = codec.encode(flat)
        lo, hi = offsets[i], offsets[i] + sizes[i]
        seg[lo:hi] = np.uint32(i)
        for dst, src in zip(key_words, w):
            dst[lo:hi] = src
        fps.append(fingerprint_host(w))
    return PackedBatch((seg,) + key_words, sizes, offsets, tuple(fps),
                       np.dtype(dtype), bucket)


@lru_cache(maxsize=64)
def compile_packed_sort(n_words_total: int,
                        bucket: int) -> Callable[..., Any]:
    """AOT-compile the packed-batch program: one fused lexicographic
    sort of ``n_words_total`` uint32 word arrays of length ``bucket``.
    Returns the compiled executable (``jit(...).lower(...).compile()``),
    so a warm call never touches the compiler.  lru-cached
    process-wide; the server's
    :class:`~mpitest_tpu.serve.executor_cache.ExecutorCache` layers
    per-server hit/miss telemetry and prewarm on top.

    Two lowerings, same bytes out:

    * ``n_words_total == 2`` (segment word + a 1-word codec — the int32
      /uint32/f32 small-request common case): the two words fuse into
      ONE uint64 ``(seg << 32) | key`` and sort as a single key —
      XLA:CPU's multi-operand sort runs a per-pair comparator call and
      measured 2-4x slower than the single-key form at batch sizes
      (28.4 vs 7.5 ms at 2^16 lanes); the u64 order is identical to the
      lexicographic (seg, key) order by construction.  The program is
      *lowered* under a scoped ``enable_x64`` (u64 is otherwise
      unavailable); inputs and outputs stay uint32, so callers never
      see the flag.
    * wider keys: the variadic ``ops/kernels.local_sort`` (the segment
      word is just the most significant key word).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from mpitest_tpu import compat
    from mpitest_tpu.ops import kernels

    specs = tuple(jax.ShapeDtypeStruct((bucket,), jnp.uint32)
                  for _ in range(n_words_total))
    if n_words_total == 2:
        def f2(seg: Any, key: Any) -> Any:
            u = ((seg.astype(jnp.uint64) << np.uint64(32))
                 | key.astype(jnp.uint64))
            s = lax.sort([u], num_keys=1, is_stable=False)[0]
            return ((s >> np.uint64(32)).astype(jnp.uint32),
                    s.astype(jnp.uint32))

        with compat.enable_x64(True):
            return jax.jit(f2).lower(*specs).compile()

    def f(*words: Any) -> Any:
        return kernels.local_sort(words)

    return jax.jit(f).lower(*specs).compile()


def executable_stats(exe: Any) -> dict[str, float]:
    """XLA cost/compile statistics of an AOT-compiled executable —
    flops, bytes accessed, generated code size — recorded into the
    ``serve.compile_cache`` miss event at compile time (ISSUE 10
    device profiling hook).  Every probe is best-effort: the
    cost-analysis surface varies across jax versions and backends, and
    telemetry must never fail a compile that succeeded."""
    out: dict[str, float] = {}
    try:
        ca = exe.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            for src, dst in (("flops", "flops"),
                             ("bytes accessed", "bytes_accessed")):
                v = ca.get(src)
                if v is not None and float(v) >= 0:
                    out[dst] = float(v)
    except Exception:  # noqa: BLE001 — version-dependent surface
        pass
    try:
        ma = exe.memory_analysis()
        v = getattr(ma, "generated_code_size_in_bytes", None)
        if v is not None:
            out["code_bytes"] = float(v)
    except Exception:  # noqa: BLE001
        pass
    return out


def run_packed(batch: PackedBatch,
               executable: Callable[..., Any] | None = None,
               ) -> tuple[np.ndarray, ...]:
    """Dispatch the packed batch (through ``executable`` when the caller
    holds a cache entry, else the shared compiled program) and return
    the sorted words on the host."""
    fn = executable if executable is not None else \
        compile_packed_sort(len(batch.words), batch.bucket)
    out = fn(*batch.words)
    return tuple(np.asarray(w) for w in out)


def lex_sorted_host(words: Sequence[np.ndarray]) -> bool:
    """Host-side lexicographic non-decreasing check over word arrays
    (msw first) — the batch verifier's sortedness half."""
    n = int(words[0].size)
    if n < 2:
        return True
    lt = np.zeros(n - 1, bool)
    eq = np.ones(n - 1, bool)
    for w in words:
        a, b = w[:-1], w[1:]
        lt |= eq & (a < b)
        eq &= a == b
    return bool(np.all(lt | eq))


def split_segments(batch: PackedBatch,
                   sorted_words: tuple[np.ndarray, ...],
                   ) -> list[np.ndarray]:
    """Decode each segment's slice of the sorted packed words back to
    its tenant's native-dtype sorted array.  Segment ``i`` occupies
    lanes ``[offsets[i], offsets[i] + sizes[i])`` — the sort is keyed on
    the segment word first, so every segment's keys land contiguously in
    segment-id order, sizes unchanged."""
    codec = codec_for(batch.dtype)
    out = []
    for lo, size in zip(batch.offsets, batch.sizes):
        segs = tuple(w[lo:lo + size] for w in sorted_words[1:])
        out.append(codec.decode(segs))
    return out


def verify_segments(batch: PackedBatch,
                    sorted_words: tuple[np.ndarray, ...],
                    ) -> list[bool]:
    """Per-segment verification of a sorted packed batch: the segment
    word must be exactly the packed segment layout (ids in order, pads
    at the tail), each segment's key words lexicographically sorted, and
    each segment's multiset fingerprint equal to its input-side fold.
    Returns one verdict per segment — a poisoned tenant flags ONLY its
    own segment."""
    seg_out = sorted_words[0]
    verdicts = []
    for i, (lo, size) in enumerate(zip(batch.offsets, batch.sizes)):
        ok = bool(np.all(seg_out[lo:lo + size] == np.uint32(i)))
        key_segs = tuple(w[lo:lo + size] for w in sorted_words[1:])
        ok = ok and lex_sorted_host(key_segs)
        ok = ok and fingerprint_host(key_segs) == batch.fps[i]
        verdicts.append(ok)
    return verdicts
