"""SortSupervisor — retries, cap regrow, and graceful degradation.

Before this layer, ``models/api.py`` had two ad-hoc overflow-retry
loops (sample and radix each re-deriving "grow the cap, rebuild donated
words, count the retry") and NO policy for a dispatch that throws: a
transient ``JaxRuntimeError`` — a preempted device, a fleeting OOM —
killed the whole sort.  The reference is worse still: its failure
"policy" is silent truncation and stranded peers (SURVEY §7.4).

The supervisor centralizes all of it:

* :meth:`dispatch` — every SPMD program launch goes through one bounded
  retry loop with exponential backoff (``SORT_MAX_RETRIES`` /
  ``SORT_RETRY_BACKOFF``).  Each failed attempt emits a
  ``supervisor_retry`` span; donated input words are rebuilt before the
  re-launch (a failed donated dispatch may have consumed them).  The
  fault registry's ``dispatch_error`` / ``dispatch_oom`` sites inject
  here, so the retry path is exercised without a flaky device.
* :meth:`exchange_loop` — THE cap-regrow loop, shared by both
  algorithms: run an attempt at the current cap, grow to the reported
  need on overflow, rebuild donated words, and surface a typed
  :class:`ExchangeCapExceeded` when the need crosses the caller's O(n)
  bound (the sample→radix skew reroute keeps its policy in api.py; the
  mechanics live here, once).
* **Degradation ladder** (driven by ``_sort_impl``): exchange engine
  pallas → lax (ISSUE 13: a Pallas kernel failure re-runs the SAME
  algorithm on the XLA collective before anything else moves), then
  requested algorithm → the other algorithm → host ``np.lexsort`` —
  taken only on persistent dispatch failure or repeated verification
  failure, and every rung's result still faces the same fingerprint
  verification.
  The ladder ends in a *verified* result or a typed error
  (:class:`SortIntegrityError` / :class:`SortRetryExhausted`), never a
  silent wrong answer.  ``SORT_FALLBACK=0`` pins the requested
  algorithm (benchmarks, parity tests).

The CLI maps the two terminal errors to distinct exit codes
(``drivers/sort_cli.py``), and every retry / fault / verification event
lands in the span stream the report CLI aggregates — robustness is
observable, not just present.
"""

from __future__ import annotations

import time
from typing import TYPE_CHECKING, Callable

from mpitest_tpu import faults as flt
from mpitest_tpu.utils import knobs

if TYPE_CHECKING:
    from mpitest_tpu.models.plan import SortPlan
    from mpitest_tpu.utils.trace import Tracer


class SortFaultError(RuntimeError):
    """Base of the supervisor's typed terminal errors."""


class SortIntegrityError(SortFaultError):
    """Every recovery rung was exhausted without producing a result that
    passes the sortedness + fingerprint verification — the caller must
    treat the sort as failed (never as approximately right)."""


class SortRetryExhausted(SortFaultError):
    """Dispatch kept failing past the retry budget (and fallback was
    disabled or also failed); the underlying error is ``__cause__``."""


class ExchangeCapExceeded(Exception):
    """Internal control flow of :meth:`SortSupervisor.exchange_loop`:
    the exchange needs a cap beyond the caller's bound."""

    def __init__(self, need: int, limit: int) -> None:
        super().__init__(f"exchange needs cap {need} > bound {limit}")
        self.need = need
        self.limit = limit


def max_retries() -> int:
    """``SORT_MAX_RETRIES`` (default 2): the dispatch retry budget."""
    return knobs.get("SORT_MAX_RETRIES")


def retry_backoff() -> float:
    """``SORT_RETRY_BACKOFF`` (default 0.05): base backoff seconds."""
    return knobs.get("SORT_RETRY_BACKOFF")


def fallback_enabled() -> bool:
    """``SORT_FALLBACK`` (default on): the degradation ladder switch."""
    return knobs.get("SORT_FALLBACK")


def exchange_engine_knob() -> str:
    """``SORT_EXCHANGE_ENGINE`` (default auto): the exchange engine the
    ladder's first rung runs — resolution to a concrete impl (auto →
    pallas on TPU backends, lax elsewhere) lives in ``models/api.py``,
    which knows the backend; the pallas → lax rung below it is this
    module's ladder contract."""
    return knobs.get("SORT_EXCHANGE_ENGINE")


def local_engine_knob() -> str:
    """``SORT_LOCAL_ENGINE`` (default auto): the local-sort engine the
    ladder's first rung runs — resolution to a concrete impl (auto →
    bitonic on TPU backends; the radix_pallas family → real Mosaic on
    TPU, the interpreter elsewhere, lax outside its size/width
    envelope) lives in ``models/api.py``, which knows the backend; the
    fused-family radix_pallas → lax rung below it is this module's
    ladder contract, mirroring :func:`exchange_engine_knob`."""
    return knobs.get("SORT_LOCAL_ENGINE")


def verify_enabled() -> bool:
    """``SORT_VERIFY`` (default on): the always-on output verifier."""
    return knobs.get("SORT_VERIFY")


def wire_registry(reg: flt.FaultRegistry | None,
                  tracer: "Tracer") -> None:
    """Point a fault registry's ``on_fire`` at a tracer: every injected
    fault becomes a ``fault`` span event + a ``faults_injected`` count.
    Wired as early as possible in a run — the ingest-poison site fires
    inside the streaming pipeline, long before the dispatch supervisor
    exists."""
    if reg is None:
        return

    def _on_fault(site: str, detail: dict) -> None:
        tracer.count("faults_injected", 1)
        tracer.spans.record("fault", time.perf_counter(), 0.0,
                            site=site, **{k: v for k, v in detail.items()
                                          if k != "word"})
        # ISSUE 10: a firing fault site is an incident trigger — dump
        # the flight-recorder ring (rate-limited per site, so a chaos
        # grid documents each site once, not once per cell).
        from mpitest_tpu.utils import flight_recorder

        flight_recorder.dump_on_error(f"fault_{site}")

    reg.on_fire = _on_fault


class SortSupervisor:
    """Per-run supervisor: owns the retry budget, the fault registry
    hookup, and the shared cap-regrow loop.  One instance per sort()."""

    def __init__(self, tracer: "Tracer",
                 registry: "flt.FaultRegistry | None" = None,
                 plan: "SortPlan | None" = None) -> None:
        self.tracer = tracer
        self.registry = registry
        #: decision record (ISSUE 12): the supervisor is the layer that
        #: KNOWS how wrong a sizing decision was — overflow regrows and
        #: dispatch retries stamp their counts onto the plan here.
        self.plan = plan
        self.max_retries = max_retries()
        self.backoff = retry_backoff()
        wire_registry(registry, tracer)

    # -- fault arming -------------------------------------------------
    def squeeze_cap(self, cap: int, floor: int) -> int:
        """``cap_squeeze`` site: collapse the initial exchange cap to the
        alignment floor so the overflow-retry path runs for real."""
        if self.registry is not None and self.registry.fire(
                "cap_squeeze", cap=cap, floor=floor):
            return floor
        return cap

    def arm_exchange(self) -> str:
        """Compile token for the trace-time exchange faults ('' = clean,
        cache-shared compile)."""
        return flt.arm_exchange(self.registry)

    def _inject_dispatch_fault(self) -> None:
        import jax

        reg = self.registry
        if reg is None:
            return
        if reg.would_fire("dispatch_stall"):
            stall_ms = knobs.get("SORT_FAULT_STALL_MS")
            if reg.fire("dispatch_stall", stall_ms=stall_ms):
                # models the known wedge (the TPU-compiler tunnel hang):
                # the SINGLE dispatch thread blocks here, which is
                # exactly what the serving watchdog exists to detect —
                # the sort itself still completes correctly afterwards
                time.sleep(stall_ms / 1e3)
        if reg.fire("dispatch_oom"):
            raise jax.errors.JaxRuntimeError(
                "RESOURCE_EXHAUSTED: injected fault (SORT_FAULTS=dispatch_oom)")
        if reg.fire("dispatch_error"):
            raise jax.errors.JaxRuntimeError(
                "INTERNAL: injected fault (SORT_FAULTS=dispatch_error)")

    # -- dispatch with bounded retry + backoff ------------------------
    def dispatch(self, label: str, fn: Callable[..., object],
                 args_fn: Callable[[], tuple[object, ...]],
                 on_retry: Callable[[], None] | None = None,
                 **attrs: object) -> object:
        """Run ``fn(*args_fn())`` under the retry budget.  ``args_fn`` is
        re-evaluated per attempt (donated buffers must be re-staged
        after a failed attempt — ``on_retry`` marks them dead so the
        caller's rebuild kicks in)."""
        import jax

        from mpitest_tpu.models.api import _traced_call

        attempt = 0
        while True:
            try:
                self._inject_dispatch_fault()
                return _traced_call(self.tracer, label, fn, *args_fn(),
                                    **attrs)
            except jax.errors.JaxRuntimeError as e:
                # an exchange fault armed for THIS dispatch may not have
                # been consumed (the program never traced) — drop it so
                # it cannot leak into a later clean compile.  It was
                # counted as injected at arm time but never touched
                # data: faults_dropped keeps the ledger honest.
                dropped = flt.drop_pending()
                if dropped:
                    self.tracer.count("faults_dropped", dropped)
                if attempt >= self.max_retries:
                    raise SortRetryExhausted(
                        f"{label} failed {attempt + 1} time(s); retry "
                        f"budget exhausted: {e}") from e
                delay = min(self.backoff * (2 ** attempt), 2.0)
                self.tracer.verbose(
                    f"{label} dispatch failed ({type(e).__name__}); "
                    f"retry {attempt + 1}/{self.max_retries} in {delay:.2f}s")
                self.tracer.count("sort_retries", 1)
                if self.plan is not None:
                    self.plan.bump("ladder", "dispatch_retries")
                self.tracer.spans.record(
                    "supervisor_retry", time.perf_counter(), 0.0,
                    label=label, attempt=attempt + 1,
                    error=type(e).__name__)
                if on_retry is not None:
                    on_retry()
                if delay:
                    time.sleep(delay)
                attempt += 1

    # -- the ONE cap-regrow loop --------------------------------------
    def exchange_loop(self, label: str,
                      attempt: "Callable[[int], tuple[object, int]]",
                      cap: int, align: int,
                      round_cap: Callable[[int, int], int],
                      cap_limit: int | None = None,
                      on_overflow: Callable[[], None] | None = None,
                      re_stage: Callable[[], None] | None = None,
                      ) -> tuple[object, int]:
        """Run ``attempt(cap) -> (payload, max_cnt)`` until the exchange
        fits; grow the cap to the reported need otherwise.  The cap only
        ever grows (bounded by the shard size), so the loop terminates.
        ``cap_limit``: raise :class:`ExchangeCapExceeded` when the need
        crosses it (the sample path's O(n) recv-memory bound).
        ``on_overflow``: invalidate donated input words before any
        rerun.  ``re_stage``: skew-aware rebalance hook (ISSUE 7) —
        invoked ONCE when the loop detects *persistent* imbalance (a
        second overflow regrow means the input arrangement, not a
        one-off estimate, is driving the cap); the callback interleaves
        the shards so per-peer counts collapse toward the fair share,
        and the already-grown cap is guaranteed to fit the rebalanced
        exchange."""
        regrows = 0
        while True:
            payload, max_cnt = attempt(cap)
            if max_cnt <= cap:
                return payload, cap
            need = round_cap(max_cnt, align)
            if on_overflow is not None:
                on_overflow()
            if cap_limit is not None and need > cap_limit:
                raise ExchangeCapExceeded(max_cnt, cap_limit)
            regrows += 1
            if self.plan is not None:
                # each regrow is a full discarded exchange dispatch —
                # the unit of cap-regret the explain view reports
                self.plan.bump("cap", "regrows")
            if re_stage is not None and regrows >= 2:
                self.tracer.verbose(
                    f"{label} exchange overflowed {regrows} times "
                    "(persistent imbalance); re-staging shards")
                if self.plan is not None:
                    self.plan.decide("restage", chosen=True,
                                     trigger="overflow")
                re_stage()
                re_stage = None  # once per run
            self.tracer.verbose(
                f"{label} exchange overflow (need {max_cnt} > cap {cap}); "
                "retrying")
            self.tracer.count("exchange_retries", 1)
            cap = need
