"""Record sorts: key+payload sorting with device-side payload permutation.

The stack so far sorts bare keys — a production shuffle engine sorts
*records*: each key drags an opaque payload (a row id, a serialized
tuple, a pointer-sized handle) that must land next to its key in the
output.  This module generalizes the sort to ``(key, payload)`` pairs
without teaching the SPMD kernels anything about payloads:

1. **argsort via the codec** — the keys encode through the ordinary
   order-preserving multi-word codec (``ops/keys.py``) and a uint32
   **lane-index word** is appended as the LEAST significant sort word
   (the mirror image of ``models/segmented.py``'s most-significant
   segment prefix).  One lexicographic sort of ``(*key_words, idx)``
   then yields both the sorted keys and — in the index word's output —
   the exact permutation that sorted them.  The index tiebreak makes
   the sort **stable by construction**: equal keys keep their input
   order, so the result is bit-identical to a host
   ``np.argsort(kind="stable")`` gather at any duplication level.
2. **device-side payload gather** — the payload bytes are packed into
   uint32 word columns (zero-padded to a 4-byte multiple) and permuted
   ON DEVICE by ``jnp.take(word, perm)`` inside the same fused program;
   the payload never round-trips through a host-side gather.
3. **1-word fusion** — for 1-word codecs (int32/uint32/float32, the
   common case) the ``(key, idx)`` pair fuses into ONE uint64
   ``(key << 32) | idx`` single-key sort, lowered under a scoped
   ``compat.enable_x64`` exactly like the segmented (seg,key) fusion
   (XLA:CPU's multi-operand comparator sort measured 2-4x slower than
   the single-key form); inputs and outputs stay uint32.

Verification is always-on and record-aware: the multiset fingerprint
(:func:`models.verify.fingerprint_records`) folds every key AND payload
word plus a per-record binding mix word, so a payload gathered against
the wrong key — both multisets individually intact — still trips the
check.  A failed verification re-dispatches once (transient corruption)
and then raises the typed :class:`SortIntegrityError`.

Payload transfers ride the PR 2 staging contract: every host→device
move goes through ``checked_device_put`` (the dtype-preservation
guard), and the external-sort path (``store/external.py``) stages
payload chunks through the same spill framing as the keys.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Any, Callable

import numpy as np

from mpitest_tpu.models import verify as vfy
from mpitest_tpu.models.ingest import checked_device_put
from mpitest_tpu.models.segmented import lex_sorted_host
from mpitest_tpu.models.supervisor import SortIntegrityError, verify_enabled
from mpitest_tpu.ops.keys import KeyCodec, codec_for

#: Hard bound on records per sort: the lane-index word is uint32 and
#: the (key<<32|idx) fusion gives the index the low 32 bits.
MAX_RECORDS = 1 << 31

#: Payload bytes pack into this many-byte words (uint32 columns).
_WORD_BYTES = 4


def payload_width_words(width: int) -> int:
    """uint32 words per record for a ``width``-byte payload."""
    return (int(width) + _WORD_BYTES - 1) // _WORD_BYTES


def as_payload_matrix(payload: Any, n: int) -> np.ndarray:
    """Canonicalize a payload argument to a ``(n, width)`` uint8 matrix.

    Accepts ``bytes`` / 1-D uint8 of ``n * width`` bytes (width
    inferred), a ``(n, width)`` uint8 matrix, or any fixed-itemsize
    array of ``n`` elements (viewed as its raw little-endian bytes —
    a uint64 row-id array is a valid 8-byte payload as-is)."""
    if isinstance(payload, (bytes, bytearray, memoryview)):
        payload = np.frombuffer(bytes(payload), np.uint8)
    arr = np.asarray(payload)
    if arr.dtype != np.uint8:
        if arr.ndim != 1 or arr.shape[0] != n:
            raise ValueError(
                f"payload array must be 1-D with one element per record "
                f"(got shape {arr.shape} for {n} records)")
        arr = np.ascontiguousarray(arr).view(np.uint8).reshape(n, -1)
    if arr.ndim == 1:
        if n == 0:
            return arr.reshape(0, 0)
        if arr.size % n:
            raise ValueError(
                f"payload of {arr.size} bytes is not a multiple of the "
                f"record count {n}")
        arr = arr.reshape(n, arr.size // n)
    if arr.ndim != 2 or arr.shape[0] != n:
        raise ValueError(
            f"payload must be (n, width) bytes; got shape {arr.shape} "
            f"for {n} records")
    return np.ascontiguousarray(arr)


def payload_to_words(payload: np.ndarray) -> tuple[np.ndarray, ...]:
    """``(n, width)`` uint8 payload -> per-record uint32 word columns
    (little-endian, zero-padded to a word multiple).  Zero columns for
    a zero-width payload."""
    n, width = payload.shape
    pw = payload_width_words(width)
    if pw == 0:
        return ()
    padded = payload
    if width % _WORD_BYTES:
        padded = np.zeros((n, pw * _WORD_BYTES), np.uint8)
        padded[:, :width] = payload
    cols = padded.reshape(n, pw, _WORD_BYTES).view(np.uint32)[..., 0]
    return tuple(np.ascontiguousarray(cols[:, j]) for j in range(pw))


def words_to_payload(words: tuple[np.ndarray, ...], n: int,
                     width: int) -> np.ndarray:
    """Inverse of :func:`payload_to_words`: word columns -> ``(n,
    width)`` uint8 payload (the zero pad is dropped)."""
    pw = payload_width_words(width)
    if pw == 0:
        return np.zeros((n, 0), np.uint8)
    mat = np.empty((n, pw), np.uint32)
    for j, w in enumerate(words):
        mat[:, j] = w
    return mat.view(np.uint8).reshape(n, pw * _WORD_BYTES)[:, :width].copy()


@lru_cache(maxsize=32)
def _compile_record_sort(n_key_words: int, n_payload_words: int,
                         n: int) -> Callable[..., Any]:
    """AOT-compile the fused record program for one shape: sort
    ``(*key_words, idx)`` lexicographically (idx = appended uint32 lane
    index, the stability tiebreak AND the permutation), then gather
    every payload word by the sorted index — one dispatch, no host
    round-trip between argsort and gather.

    ``n`` is always a power-of-two shape bucket
    (:func:`models.segmented.bucket_for` — callers pad, see
    :func:`_dispatch`), so a serve mix of assorted record sizes reuses
    a handful of executables instead of paying an XLA compile per
    distinct request size on the dispatch thread.

    1-word keys fuse ``(key << 32) | idx`` into a single uint64 sort
    key, LOWERED under a scoped ``enable_x64`` (the segmented.py
    pattern — u32 in/out, callers never see the flag)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from mpitest_tpu import compat

    specs = tuple(jax.ShapeDtypeStruct((n,), jnp.uint32)
                  for _ in range(n_key_words + n_payload_words))

    def gather(perm: Any, payload: tuple[Any, ...]) -> tuple[Any, ...]:
        return tuple(jnp.take(w, perm) for w in payload)

    if n_key_words == 1:
        def f1(*arrs: Any) -> Any:
            key, payload = arrs[0], arrs[1:]
            idx = lax.iota(jnp.uint32, n)
            u = ((key.astype(jnp.uint64) << np.uint64(32))
                 | idx.astype(jnp.uint64))
            s = lax.sort([u], num_keys=1, is_stable=False)[0]
            perm = s.astype(jnp.uint32)
            return ((s >> np.uint64(32)).astype(jnp.uint32),), \
                gather(perm, payload), perm

        with compat.enable_x64(True):
            return jax.jit(f1).lower(*specs).compile()

    def f(*arrs: Any) -> Any:
        kw, payload = arrs[:n_key_words], arrs[n_key_words:]
        idx = lax.iota(jnp.uint32, n)
        out = lax.sort(list(kw) + [idx], num_keys=n_key_words + 1,
                       is_stable=False)
        perm = out[-1]
        return tuple(out[:n_key_words]), gather(perm, payload), perm

    return jax.jit(f).lower(*specs).compile()


def _dispatch(codec: KeyCodec, key_words: tuple[np.ndarray, ...],
              payload_words: tuple[np.ndarray, ...], n: int,
              device: Any) -> tuple[tuple[np.ndarray, ...],
                                    tuple[np.ndarray, ...]]:
    """One staged record dispatch: pad to the power-of-two shape
    bucket, device_put (guarded), run the fused program, fetch and
    slice the sorted words back on the host.

    Pad lanes carry all-ones key words (the lexicographic maximum) and
    lane indices >= n, so they sort strictly after every real record —
    a real all-ones key still wins its tie by index — and the first
    ``n`` output lanes are exactly the sorted real records.  Bucketing
    (the ``segmented.bucket_for`` rule) is what keeps the executable
    zoo bounded under a serve mix of assorted record sizes."""
    from mpitest_tpu.models.segmented import bucket_for

    bucket = bucket_for(n)
    if bucket > n:
        pad = bucket - n
        key_words = tuple(
            np.concatenate([w, np.full(pad, 0xFFFFFFFF, np.uint32)])
            for w in key_words)
        payload_words = tuple(
            np.concatenate([w, np.zeros(pad, np.uint32)])
            for w in payload_words)
    exe = _compile_record_sort(codec.n_words, len(payload_words),
                               bucket)
    dev_args = tuple(checked_device_put(w, device)
                     for w in key_words + payload_words)
    out_kw, out_pw, _perm = exe(*dev_args)
    return (tuple(np.asarray(w)[:n] for w in out_kw),
            tuple(np.asarray(w)[:n] for w in out_pw))


def sort_records(keys: np.ndarray, payload: Any,
                 mesh: Any = None, tracer: Any = None,
                 ) -> tuple[np.ndarray, np.ndarray]:
    """Sort ``keys`` with their per-record ``payload`` permuted along
    (stable by key; see the module docstring).  Returns ``(sorted_keys,
    sorted_payload)`` where the payload comes back as a ``(n, width)``
    uint8 matrix.

    Always verified: the output must be lexicographically sorted AND
    reproduce the record fingerprint (key+payload+binding mix) folded
    from the input — one transient-corruption retry, then a typed
    :class:`SortIntegrityError`."""
    keys = np.asarray(keys).reshape(-1)
    n = int(keys.size)
    if n >= MAX_RECORDS:
        raise ValueError(f"record sort supports < 2^31 records, got {n}")
    dtype = np.dtype(keys.dtype)
    codec = codec_for(dtype)
    pay = as_payload_matrix(payload, n)
    width = int(pay.shape[1])
    if n == 0:
        return np.empty(0, dtype), pay.reshape(0, width)

    if mesh is None:
        from mpitest_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(1)
    device = mesh.devices.flat[0]

    verify_on = verify_enabled()
    key_words = codec.encode(keys)
    payload_words = payload_to_words(pay)
    fp_in = (vfy.fingerprint_records(key_words, payload_words)
             if verify_on else None)

    spans = tracer.spans if tracer is not None else None
    for attempt in range(2 if verify_on else 1):
        out_kw, out_pw = _dispatch(codec, key_words, payload_words, n,
                                   device)
        if not verify_on:
            break
        sorted_ok = lex_sorted_host(out_kw)
        fp_ok = vfy.fingerprint_records(out_kw, out_pw) == fp_in
        ok = sorted_ok and fp_ok
        if spans is not None:
            spans.event("verify", ok=bool(ok),
                        sorted_ok=bool(sorted_ok),
                        fp_ok=bool(fp_ok), n=n)
        if tracer is not None:
            tracer.count("verify_runs", 1)
        if ok:
            break
        if tracer is not None:
            tracer.count("verify_failures", 1)
        if attempt:
            raise SortIntegrityError(
                "record sort failed fingerprint verification twice "
                "(keys, payload, or their pairing corrupted)")
    return codec.decode(out_kw), words_to_payload(out_pw, n, width)
