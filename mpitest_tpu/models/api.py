"""Public sort API: dispatch, sharding, overflow-retry, host assembly.

This is the driver layer of the framework (the reference's ``main()`` +
``sort()`` scaffolding, ``mpi_sample_sort.c:28-82,220-241``, redesigned):
it owns everything that is *not* SPMD — dtype encoding, padding to static
shapes, placing shards on the mesh, compiling the shard_map program,
reacting to exchange overflow, and decoding results back to the host.

Static-shape contract: inputs pad to ``P·n`` with copies of the *maximum
real key* (SURVEY.md §7.4 "Scatter overflow" fix — padding also makes P∤N
inputs correct, which the reference gets wrong).  Pads tie with genuine
max keys and sort to the global tail, so slicing the first N elements
recovers the exact multiset — bit-identical output — and, unlike an
all-ones sentinel, pads never widen the key range seen by the radix
pass planner.

Overflow-retry contract: the SPMD programs return the global max per-peer
segment length.  If it exceeded the static cap, lanes were dropped and the
result is discarded; the host recompiles with that length as the new cap
and reruns.  For single-exchange sample sort the reported value is exact,
so one retry suffices; for multi-pass radix an overflowed early pass
corrupts what later passes see, so the reported max can understate a later
pass's need — the cap still grows strictly monotonically (bounded by the
shard size), so the loop terminates, possibly after more than one
recompile.  This replaces the reference's silent bucket overflow
(``mpi_sample_sort.c:140-144``) and its "no enough sample" abort
(``:96-99``) with a clean, always-correct path.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import lru_cache
from typing import Any, Callable, Iterator

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from mpitest_tpu import compat, faults
from mpitest_tpu.models import plan as plan_mod
from mpitest_tpu.models import planner as planner_mod
from mpitest_tpu.models import radix_sort, sample_sort
from mpitest_tpu.models import supervisor as supervision
from mpitest_tpu.models import verify as vfy
from mpitest_tpu.models.supervisor import (  # re-exported: public errors
    ExchangeCapExceeded,
    SortFaultError,
    SortIntegrityError,
    SortRetryExhausted,
    SortSupervisor,
)
from mpitest_tpu.models.ingest import (
    EGRESS_MIN_BYTES as _EGRESS_MIN_BYTES,
    StagedIngest,
    checked_device_put,
    stream_result_to_numpy,
    stream_to_mesh,
    use_stream,
)
from mpitest_tpu.ops import bitonic, kernels, radix_pallas
from mpitest_tpu.ops.keys import KeyCodec, codec_for
from mpitest_tpu.parallel.mesh import AXIS, key_sharding, make_mesh
from mpitest_tpu.utils import io as kio
from mpitest_tpu.utils import knobs
from mpitest_tpu.utils.trace import Tracer


#: jit callables that have executed at least once — the compile-vs-
#: execute split of the span layer: a callable's FIRST invocation pays
#: tracing + XLA compile and is recorded as ``jit_compile_execute``;
#: warm calls are ``jit_execute``.  Keyed by id(); the lru_caches above
#: keep the callables alive, so collisions need an eviction first (and
#: cost only a mislabeled span, never a wrong result).
_warm_jits: set[int] = set()


def _traced_call(tracer: Tracer, label: str, fn: Callable[..., Any],
                 *args: Any, **attrs: object) -> Any:
    """Call a jit program under a span that separates first-call (compile
    included) from warm-call wall time — the split ISSUE/SURVEY §5 needs
    to attribute 'slow run' to compile vs execute."""
    first = id(fn) not in _warm_jits
    name = "jit_compile_execute" if first else "jit_execute"
    # sortlint: disable=SL003 -- both branches above are registered schema names
    with tracer.spans.span(name, label=label, **attrs):
        out = fn(*args)
    if first:
        _warm_jits.add(id(fn))
        tracer.count("jit_first_calls", 1)
    return out


@dataclass
class DistributedSortResult:
    """Device-resident sorted output (sharded); decode lazily on demand."""

    words: tuple[jax.Array, ...]     # sharded [P*n] (radix) or [P*(P*cap)] (sample)
    n_valid: int                     # total real keys (excludes padding)
    dtype: np.dtype
    counts: np.ndarray | None = None  # per-shard valid counts (ragged layouts)
    shard_slots: int | None = None    # slots per shard for ragged layouts

    def to_numpy(self, tracer: "Tracer | None" = None) -> np.ndarray:
        if self.n_valid == 0:
            return np.empty(0, self.dtype)
        codec = codec_for(self.dtype)
        if self.counts is None:
            # Streamed egress (models/ingest.py): decode shard k while
            # shard k+1 is still fetching — on by default above the
            # auto threshold, forced by SORT_INGEST=stream, disabled by
            # =mono.  Ragged (sample) results keep the legacy gather.
            try:
                # multi-host arrays only expose local shards here; the
                # streamed decode cannot cover the rest, so those fall
                # through to the legacy gather (which raises loudly).
                n_shards = (len(self.words[0].addressable_shards)
                            if self.words[0].is_fully_addressable else 1)
            except Exception:
                n_shards = 1
            mode = kio.ingest_mode()
            nbytes = self.n_valid * np.dtype(self.dtype).itemsize
            if n_shards > 1 and (
                mode == "stream"
                or (mode == "auto" and nbytes >= _EGRESS_MIN_BYTES)
            ):
                return stream_result_to_numpy(
                    self.words, self.n_valid, self.dtype, tracer=tracer)
            host = tuple(np.asarray(w) for w in self.words)
            return codec.decode(tuple(w[: self.n_valid] for w in host))
        host = tuple(np.asarray(w) for w in self.words)
        # ragged: concatenate the valid prefix of each shard's slot range,
        # then drop the padding sentinels (global max ⇒ they sit at the tail)
        parts = []
        for w in host:
            segs = [
                w[i * self.shard_slots : i * self.shard_slots + c]
                for i, c in enumerate(self.counts)
            ]
            parts.append((np.concatenate(segs) if segs else w[:0])[: self.n_valid])
        return codec.decode(tuple(parts))

    def median_probe_raw(self) -> Any:
        """The (n/2)-th sorted element as a native-dtype scalar (exact
        bits — float probes must compare bit patterns, since distinct
        float medians can collide under int truncation)."""
        idx = self.n_valid // 2 - 1
        if idx < 0:
            raise ValueError("median probe undefined for < 2 keys")
        codec = codec_for(self.dtype)
        # Slice on device, THEN materialize: one element crosses the
        # host boundary, not the full multi-GB result.
        if self.counts is None:
            return codec.decode(tuple(np.asarray(w[idx : idx + 1]) for w in self.words))[0]
        cum = np.concatenate([[0], np.cumsum(self.counts)])
        shard = int(np.searchsorted(cum, idx, side="right")) - 1
        off = idx - cum[shard]
        s = self.shard_slots
        return codec.decode(
            tuple(np.asarray(w[shard * s + off : shard * s + off + 1]) for w in self.words)
        )[0]

    def median_probe(self) -> int:
        """The reference's correctness probe: the (n/2)-th sorted element
        (``int_buf[size_input / 2 - 1]``, mpi_sample_sort.c:205)."""
        return int(self.median_probe_raw())


def _round_cap(c: int, align: int = 128) -> int:
    """Round caps up to a lane-friendly multiple: 128 (TPU minor dim) for
    the XLA pack, 1024 (the DMA chunk) for the Pallas pack."""
    return max(align, ((c + align - 1) // align) * align)


_PACK_IMPLS = ("xla", "pallas", "pallas_interpret")


def _resolve_pack(pack: str | None) -> str:
    """Exchange-pack implementation: Pallas DMA pack on real TPU (4.7×
    the XLA scatter spread at 2^26 on v5e), XLA elsewhere."""
    if pack is None:
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    if pack not in _PACK_IMPLS:
        raise ValueError(f"unknown pack {pack!r}; use one of {_PACK_IMPLS}")
    return pack


def _cap_align(pack: str) -> int:
    from mpitest_tpu.ops.pallas_kernels import CHUNK

    return CHUNK if pack.startswith("pallas") else 128


def _resolve_exchange_engine(engine: str | None) -> str:
    """Concrete exchange-engine impl (ISSUE 13): ``None`` reads the
    ``SORT_EXCHANGE_ENGINE`` knob.  ``auto`` = the remote-DMA Pallas
    engine on real TPU backends, the XLA collective elsewhere; a forced
    ``pallas`` without a TPU runs the engine's interpreter form (same
    convention as the bitonic local engine, :func:`_bitonic_impl`) —
    the remote-copy hop itself then rides the bit-identical
    ``lax.all_to_all``, see ``ops/exchange.py``."""
    from mpitest_tpu.ops import exchange as xeng

    v = engine if engine is not None else supervision.exchange_engine_knob()
    if v == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "lax"
    if v not in xeng.ENGINES:
        raise ValueError(
            f"unknown exchange engine {v!r}; use one of "
            f"{('auto',) + xeng.ENGINES}")
    if v == "pallas" and jax.default_backend() != "tpu":
        return "pallas_interpret"
    return v


def _engine_pack(pack_impl: str, engine: str) -> tuple[str, int]:
    """(effective pack impl, cap alignment) for one ladder rung: the
    pallas exchange engine owns its pack (the fused multi-word kernel,
    CHUNK-aligned caps); the lax engine keeps the resolved ``pack``."""
    if engine.startswith("pallas"):
        return engine, _cap_align(engine)
    return pack_impl, _cap_align(pack_impl)


def _passes_from_diffs(diffs: tuple[int, ...], digit_bits: int) -> int:
    """Number of LSD passes actually required, from per-word ``max ^ min``
    diffs (msw first) — the one canonical pass planner, shared by the host
    path (diffs from :func:`_word_diffs`) and the device path (diffs from
    one scalar min/max sync per word).  Digits above the highest
    globally-differing bit are identical everywhere and can be skipped —
    the principled version of the reference's ``number_digits`` pre-pass
    (``mpi_radix_sort.c:100``).

    Digit alignment restarts at every 32-bit word boundary (the pass loop
    in :func:`radix_sort_spmd` walks ``per_word`` digits per word), so the
    count is ``per_word``-per-full-word plus the digits covering the
    differing bits of the first non-constant word — NOT a contiguous
    bit-count over the whole key, which would undercount whenever
    ``digit_bits`` does not divide 32.
    """
    n_words = len(diffs)
    per_word = (32 + digit_bits - 1) // digit_bits
    for wi, x in enumerate(diffs):  # msw first
        if x:
            full_words_below = n_words - 1 - wi
            return min(
                full_words_below * per_word + math.ceil(x.bit_length() / digit_bits),
                per_word * n_words,
            )
    return 0


def _word_diffs(words: tuple[np.ndarray, ...]) -> tuple[int, ...]:
    """Per-word ``max ^ min`` of host key words (msw first) — the one
    canonical input to pass planning; empty input has no differing bits."""
    if words[0].size == 0:
        return (0,) * len(words)
    return tuple(int(w.max()) ^ int(w.min()) for w in words)


@lru_cache(maxsize=8)
def _compile_word_range(dtype_name: str) -> Callable[..., Any]:
    """Per-word min/max of the encoded key words (msw first) — feeds the
    pass planner for device-resident input (one tiny reduction + scalar
    sync instead of abandoning pass skipping)."""
    codec = codec_for(np.dtype(dtype_name))

    def f(x):
        words = codec.encode_jax(x)
        return tuple((jnp.min(w), jnp.max(w)) for w in words)

    return jax.jit(f)


#: Memo: platforms whose device-side float64 encode failed to lower
#: (XLA x64-rewrite gap, see sort() docstring) — later calls on the SAME
#: platform route f64 device input straight to the host fallback instead
#: of re-attempting a doomed (and slow) XLA compile every time.  Keyed
#: per platform so one broken backend never degrades another (e.g. a CPU
#: mesh in the same process, whose lowering is fine).
_f64_encode_broken_platforms: set[str] = set()


def _device_platform(x: jax.Array) -> str:
    """Platform string of the device(s) holding ``x`` — the memo key for
    the single-device path, whose encode compiles where ``x`` lives."""
    try:
        return next(iter(x.devices())).platform
    except Exception:
        return jax.default_backend()


def _mesh_platform(mesh: Mesh) -> str:
    """Platform the mesh compiles for — the memo key for the sharded
    path: the failing compile is ``_compile_encode_pad(..., mesh)``, so
    keying on the *input's* platform would both poison a healthy backend
    (CPU input, broken TPU mesh) and miss the memo (TPU input, same
    broken mesh)."""
    return mesh.devices.flat[0].platform

#: Error-text markers of the known f64 lowering gap ("While rewriting
#: computation to not contain X64 element types ... %bitcast-convert").
#: Anything else (OOM, preemption) must re-raise, not masquerade as it.
_F64_GAP_MARKERS = ("bitcast-convert", "X64 element types")


def _f64_gap_applies(dtype: np.dtype, codec: KeyCodec) -> bool:
    return dtype.kind == "f" and codec.n_words == 2


def _is_f64_lowering_gap(e: Exception, dtype: np.dtype, codec: KeyCodec,
                         platform: str) -> bool:
    """True iff ``e`` is the known f64 device-encode lowering gap for a
    2-word float dtype; memoizes the verdict for later calls on the same
    platform.  The markers are fragments of ONE message and must all be
    present — a different x64-rewrite failure or an unrelated bitcast
    error is not this gap and must re-raise."""
    if not _f64_gap_applies(dtype, codec):
        return False
    msg = str(e)
    if not all(m in msg for m in _F64_GAP_MARKERS):
        return False
    _f64_encode_broken_platforms.add(platform)
    return True


def _f64_known_broken(platform: str, dtype: np.dtype,
                      codec: KeyCodec) -> bool:
    """Memoized verdict: ``platform`` already tripped the f64 gap."""
    return (_f64_gap_applies(dtype, codec)
            and platform in _f64_encode_broken_platforms)


def _f64_host_input(x: jax.Array, tracer: Tracer) -> np.ndarray:
    """Engage the documented f64 host fallback: tracer breadcrumbs plus
    the host copy of the device array."""
    tracer.verbose(
        "device-side float64 encode unsupported by this backend; "
        "falling back to one host round-trip"
    )
    tracer.count("f64_host_fallback", 1)
    return np.asarray(x)


def _host_hi_dup_sniff(hi: np.ndarray) -> bool:
    """Host twin of the hi-duplication sniff (same ~1024-key sample)."""
    n = hi.size
    s = min(1024, n)
    idx = np.linspace(0, n - 1, s).astype(np.int64)
    samp = np.sort(hi[idx])
    return bool(np.any(samp[1:] == samp[:-1]))


@lru_cache(maxsize=4)
def _compile_pair_sort(impl: str) -> Callable[..., Any]:
    interpret = impl == "bitonic_interpret"

    def f(hi, lo):
        return kernels.sort_two_words_bitonic(hi, lo, interpret=interpret)

    return jax.jit(f)


#: Engine codes returned by the fused device program (scalar, one fetch).
_PAIR_CODES = {0: "constant", 1: "bitonic_1w1", 2: "bitonic_1w0",
               3: "lax", 4: "bitonic_pair", 5: "bitonic_pair+lax_fallback"}


@lru_cache(maxsize=8)
def _compile_pair_fused(dtype_name: str,
                        impl: str) -> Callable[..., Any]:
    """ONE-dispatch device program for 2-word device-resident local
    sorts: encode + range/dup planning + a ``lax.cond`` tree selecting
    constant-word 1-word engine / variadic ``lax.sort`` / pair engine
    (with its residual fallback folded in as a nested cond) — every
    branch returns the same shapes, so the whole adaptive decision runs
    on device.  Rationale: each extra dispatch costs ~0.15-0.2 s over
    this image's tunnel, which is larger than the pair engine's entire
    kernel-level win at 2^27 — the host-orchestrated version measured
    SLOWER end-to-end than the single-jit lax path despite a 1.4x
    faster device sort."""
    from jax import lax as jlax

    codec = codec_for(np.dtype(dtype_name))
    interpret = impl == "bitonic_interpret"

    def lax2w(hi, lo):
        out = jlax.sort([hi, lo], num_keys=2, is_stable=False)
        return out[0], out[1]

    def one_w(w):
        return kernels.local_sort((w,), engine=impl)[0]

    def f(x):
        hi, lo = codec.encode_jax(x.reshape(-1))
        d0 = jnp.min(hi) ^ jnp.max(hi)
        d1 = jnp.min(lo) ^ jnp.max(lo)
        n = hi.shape[0]
        s = min(1024, n)
        if s > 1:
            stride = -(-(n - 1) // (s - 1))  # ceil: sample stays <= s picks
            s_eff = (n - 1) // stride + 1
            start = (n - 1) - (s_eff - 1) * stride
            samp = jlax.sort(
                [jlax.slice(hi, (start,),
                            (start + (s_eff - 1) * stride + 1,), (stride,))],
                num_keys=1, is_stable=False)[0]
            dup = jnp.any(samp[1:] == samp[:-1])
        else:
            dup = jnp.zeros((), bool)

        def b_both(h, l):   # both words constant: already sorted
            return h, l, jnp.int32(0)

        def b_hic(h, l):    # hi constant: 1-word engine on lo
            return h, one_w(l), jnp.int32(1)

        def b_loc(h, l):    # lo constant: 1-word engine on hi
            return one_w(h), l, jnp.int32(2)

        def b_lax(h, l):    # sniffed hi duplication: straight to lax
            hs, ls = lax2w(h, l)
            return hs, ls, jnp.int32(3)

        def b_pair(h, l):
            hs, ls, bad = kernels.sort_two_words_bitonic(
                h, l, interpret=interpret)
            hs, ls = jlax.cond(bad, lax2w, lambda a, b: (hs, ls), h, l)
            return hs, ls, jnp.where(bad, jnp.int32(5), jnp.int32(4))

        def b_var(h, l):    # both words vary: sniff decides
            return jlax.cond(dup, b_lax, b_pair, h, l)

        return jlax.cond(
            d0 == jnp.uint32(0),
            lambda a, b: jlax.cond(d1 == jnp.uint32(0), b_both, b_hic, a, b),
            lambda a, b: jlax.cond(d1 == jnp.uint32(0), b_loc, b_var, a, b),
            hi, lo)

    return jax.jit(f)


def _local_pair_sort(x: Any, is_device: bool, codec: KeyCodec,
                     dtype: np.dtype, mesh: Mesh, tracer: Tracer,
                     words_np: tuple[np.ndarray, ...] | None = None,
                     ) -> tuple[jax.Array, ...]:
    """Single-device 64-bit sort orchestration — the MSD-hybrid structure
    (VERDICT r3 #1), adaptive like the skew fallback:

    1. constant-word shortcut: a word with zero range never needs
       sorting — narrow-range int64 (values inside one 32-bit window,
       common in practice) collapses to the plain 1-word bitonic engine
       on the other word, ~2x faster again than the pair engine.
    2. hi-duplication sniff: heavy duplication would leave equal-hi runs
       longer than the pair engine's fixed run fix-up depth — route to
       the variadic ``lax.sort`` up front (no wasted phase).
    3. pair engine (``kernels.sort_two_words_bitonic``): key+payload
       bitonic by hi + segmented odd-even run fix-up.  The residual flag
       (runs the sniff missed) falls back to ``lax.sort`` — correctness
       never depends on the sniff.

    Returns the sorted device word tuple.
    """
    engine = _local_engine()
    impl = _bitonic_impl()
    if is_device and _f64_known_broken(_device_platform(x), dtype, codec):
        x, is_device = _f64_host_input(x, tracer), False
    if is_device:
        # Device-resident input: the whole adaptive tree runs in ONE
        # fused dispatch (see _compile_pair_fused) — host-side branching
        # would cost a tunnel round-trip per decision.
        try:
            with tracer.phase("sort"):
                hi_s, lo_s, code = _traced_call(
                    tracer, "pair_fused", _compile_pair_fused(dtype.name, impl), x)
                code = int(code)
        except jax.errors.JaxRuntimeError as e:
            if not _is_f64_lowering_gap(e, dtype, codec, _device_platform(x)):
                raise
            x, is_device = _f64_host_input(x, tracer), False
        else:
            tracer.counters["local_engine"] = _PAIR_CODES[code]
            if code == 3:
                tracer.count("pair_dup_reroute", 1)
            elif code == 5:
                tracer.verbose(
                    "pair engine left residual runs (hi duplication the "
                    "sniff missed); lax fallback ran on device")
                tracer.count("pair_residual_fallback", 1)
            return (hi_s, lo_s)
    if not is_device:
        with tracer.phase("encode"):
            # caller may have encoded already (the verification
            # fingerprint needs the words too — don't pay O(n) twice)
            if words_np is None:
                words_np = codec.encode(np.asarray(x).reshape(-1))
            rng = np.array([words_np[0].min(), words_np[0].max(),
                            words_np[1].min(), words_np[1].max()])
            dup = _host_hi_dup_sniff(words_np[0])
        with tracer.phase("device_put"):
            dev = mesh.devices.flat[0]
            words = tuple(checked_device_put(w, dev) for w in words_np)
    diffs = (int(rng[0]) ^ int(rng[1]), int(rng[2]) ^ int(rng[3]))
    if diffs == (0, 0):  # all keys identical: already sorted
        tracer.counters["local_engine"] = "constant"
        return words
    for const_w, sort_w in ((0, 1), (1, 0)):
        if diffs[const_w] == 0:
            # the constant word never moves; 1-word engine on the other
            tracer.counters["local_engine"] = f"bitonic_1w{sort_w}"
            with tracer.phase("sort"):
                s_out = _traced_call(
                    tracer, "local_1w", _compile_local(1, engine), words[sort_w])[0]
            return (words[0], s_out) if sort_w == 1 else (s_out, words[1])
    if dup:
        tracer.counters["local_engine"] = "lax"
        tracer.count("pair_dup_reroute", 1)
        with tracer.phase("sort"):
            return _traced_call(tracer, "local_2w_lax",
                                _compile_local(2, "lax"), *words)
    tracer.counters["local_engine"] = "bitonic_pair"
    with tracer.phase("sort"):
        hi_s, lo_s, bad = _traced_call(tracer, "pair_sort",
                                       _compile_pair_sort(impl), *words)
        bad = bool(bad)
    if bad:
        tracer.verbose(
            "pair engine left residual runs (hi duplication the sniff "
            "missed); falling back to lax.sort")
        tracer.count("pair_residual_fallback", 1)
        with tracer.phase("sort"):
            return _traced_call(tracer, "local_2w_lax",
                                _compile_local(2, "lax"), *words)
    return (hi_s, lo_s)


def _local_engine() -> str:
    """Local (single-device) sort engine: the Pallas bitonic kernel
    (``ops/bitonic.py``) on real TPU backends for large one-word keys —
    measured 2.0-4.2x ``lax.sort`` at 2^26 on v5e post-relayout (r5) —
    ``lax.sort`` otherwise.  ``SORT_LOCAL_ENGINE={auto,bitonic,lax,
    radix_pallas,radix_pallas_interpret}`` overrides; the fused radix
    family (``ops/radix_pallas.py``) is never chosen by ``auto`` until
    the first real-TPU re-baseline (the kernels have only ever run
    under interpret)."""
    return supervision.local_engine_knob()


def _use_bitonic(engine: str, n_words: int, n: int) -> bool:
    if n_words > 2:
        return False  # wider keys keep the variadic lax.sort
    if engine == "bitonic":
        return True
    return engine == "auto" and jax.default_backend() == "tpu" and (
        n >= (1 << bitonic.MIN_SORT_LOG2)
    )


def _bitonic_impl() -> str:
    """Execution form of the bitonic engine: real Mosaic kernels on TPU
    backends, the Pallas interpreter elsewhere (CPU-mesh tests / forced
    ``SORT_LOCAL_ENGINE=bitonic`` without a TPU)."""
    return "bitonic" if jax.default_backend() == "tpu" else "bitonic_interpret"


def _use_fused(engine: str, n_words: int, n: int) -> bool:
    """True when the fused radix family can take this dispatch: the
    knob asked for it AND the key/size fit the kernel's VMEM-resident
    envelope.  Never True for ``auto`` — the fused kernels have only
    ever run under interpret, so auto stays bitonic-on-TPU until the
    first real-TPU re-baseline."""
    return (engine.startswith("radix_pallas")
            and n_words <= radix_pallas.FUSED_MAX_WORDS
            and n <= radix_pallas.FUSED_MAX_ELEMS)


def _resolve_local_engine(engine: str, n_words: int, n: int) -> str:
    """Concrete local-sort engine for one dispatch: the fused radix
    family resolves to real Mosaic on TPU backends and the Pallas
    interpreter elsewhere (and to ``lax`` when the dispatch falls
    outside its envelope); the bitonic family keeps its PR 5 rules;
    everything else is ``lax``."""
    if engine.startswith("radix_pallas"):
        if not _use_fused(engine, n_words, n):
            return "lax"
        if engine == "radix_pallas_interpret" or \
                jax.default_backend() != "tpu":
            return "radix_pallas_interpret"
        return "radix_pallas"
    if _use_bitonic(engine, n_words, n):
        return _bitonic_impl()
    return "lax"


@lru_cache(maxsize=8)
def _compile_local_device(dtype_name: str,
                          engine: str = "auto") -> Callable[..., Any]:
    """1-device program for device-resident input: fused encode + sort."""
    codec = codec_for(np.dtype(dtype_name))

    def f(x):
        words = codec.encode_jax(x)
        eng = _resolve_local_engine(engine, len(words), x.size)
        return kernels.local_sort(words, engine=eng)

    return jax.jit(f)


@lru_cache(maxsize=16)
def _compile_encode_pad(dtype_name: str, total: int,
                        mesh: Mesh | None) -> Callable[..., Any]:
    """Device-side encode + pad-to-``total``-with-max.  With a mesh, the
    output is sharded on the key axis; with ``mesh=None`` the program runs
    wherever the input lives (used for non-divisible N, whose *input*
    cannot be evenly sharded — the padded output can, and is landed on the
    mesh by the caller).  Keeps device-resident keys off the host."""
    codec = codec_for(np.dtype(dtype_name))

    def f(x):
        words = codec.encode_jax(x)
        pad = total - x.shape[0]
        if pad:
            # Pad with the maximum real key in the *native* order (encode
            # is order-preserving, so its word tuple is lexicographically
            # max) — never a per-word max, which for multi-word keys could
            # fabricate a key larger than any real one.  Float codecs pad
            # with the all-ones sentinel instead: jnp.max is NaN-poisoned
            # and a NaN "max" need not be the totalOrder maximum.
            if codec.sentinel_pad:
                mx_words = tuple(jnp.full((1,), mw, jnp.uint32)
                                 for mw in codec.max_sentinel())
            else:
                mx_words = codec.encode_jax(jnp.max(x)[None])
            words = tuple(
                jnp.concatenate([w, jnp.broadcast_to(mw[0], (pad,))])
                for w, mw in zip(words, mx_words)
            )
        return words

    if mesh is None:
        return jax.jit(f)
    return jax.jit(f, out_shardings=key_sharding(mesh))


@lru_cache(maxsize=16)
def _compile_local(n_words: int,
                   engine: str = "auto",
                   widths: tuple[int, ...] | None = None,
                   ) -> Callable[..., Any]:
    """The 1-device specialization: both distributed algorithms degenerate
    to the local kernel when the mesh has a single device (no exchange, no
    splitters, no digit passes) — one fused local sort (the Pallas
    bitonic engine for large 1-word keys on TPU, else ``lax.sort``).
    The reference run with ``-np 1`` still pays its full protocol; here
    the program specializes to what the hardware actually needs.

    ``widths`` (per-word significant-bit widths, msw first) compacts the
    fused radix engine's pass plan for range-narrow inputs; quantizing
    the host-measured diffs to bit widths keeps this cache's key
    vocabulary small (<= 33 values per word)."""
    def f(*words):
        eng = _resolve_local_engine(engine, len(words), words[0].size)
        diffs = None
        if widths is not None and eng.startswith("radix_pallas"):
            diffs = tuple((1 << w) - 1 for w in widths)
        return kernels.local_sort(words, engine=eng, diffs=diffs)

    return jax.jit(f)


@lru_cache(maxsize=64)
def _compile_radix(mesh: Mesh, n_words: int, n: int, digit_bits: int,
                   cap: int, passes: int, pack: str, donate: bool = False,
                   fault_token: str = "",
                   exchange_engine: str = "lax",
                   local_engine: str = "lax") -> Callable[..., Any]:
    # fault_token: unique per armed exchange fault (mpitest_tpu.faults) —
    # a poisoned trace gets its own cache entry and can never be served
    # to a clean dispatch.  "" = the shared clean compile.
    n_ranks = mesh.devices.size

    def f(*words):
        out, max_cnt = radix_sort.radix_sort_spmd(
            words, n_words, digit_bits, n_ranks, cap, passes, pack=pack,
            exchange_engine=exchange_engine, local_engine=local_engine,
        )
        return out, max_cnt

    return jax.jit(
        compat.shard_map(
            f,
            mesh=mesh,
            in_specs=(P(AXIS),) * n_words,
            out_specs=((P(AXIS),) * n_words, P()),
            # pallas_call's internal ops mix varying/unvarying operands in
            # ways the vma checker rejects; out_specs are explicit here.
            # The engine conjunct matters only for DIRECT compiles (sort()
            # forces pack to the engine's impl via _engine_pack, but e.g.
            # radix_pass_states-style callers can pass pack="xla" with a
            # pallas engine, whose transport still runs pallas kernels).
            check_vma=(pack == "xla" and exchange_engine == "lax"
                       and local_engine == "lax"),
        ),
        # Donation: the input word shards alias the output word shards
        # (same shape/dtype/sharding), so HBM holds ONE copy of the keys
        # during the sort instead of two — the streamed-ingest memory
        # contract.  Callers rebuild words before any overflow retry
        # (the donated buffers are dead after the call).
        donate_argnums=tuple(range(n_words)) if donate else (),
    )


@lru_cache(maxsize=64)
def _compile_sample(mesh: Mesh, n_words: int, n: int, cap: int,
                    oversample: int, pack: str, engine: str = "lax",
                    donate: bool = False,
                    fault_token: str = "",
                    exchange_engine: str = "lax") -> Callable[..., Any]:
    # fault_token: see _compile_radix.
    n_ranks = mesh.devices.size

    def f(*words):
        out, count, max_cnt = sample_sort.sample_sort_spmd(
            words, n_words, n_ranks, cap, oversample, pack=pack,
            engine=engine, exchange_engine=exchange_engine,
        )
        return out, count[None], max_cnt

    return jax.jit(
        compat.shard_map(
            f,
            mesh=mesh,
            in_specs=(P(AXIS),) * n_words,
            out_specs=((P(AXIS),) * n_words, P(AXIS), P()),
            # pallas_call internals (exchange pack, bitonic engine) mix
            # varying/unvarying operands in ways the vma checker rejects.
            check_vma=(pack == "xla" and engine == "lax"
                       and exchange_engine == "lax"),
        ),
        # see _compile_radix: input/output word aliasing under donation
        # ([P*(P*cap)] outputs differ in shape from [P*n] inputs, so XLA
        # may only reuse rather than alias — still a net HBM win).
        donate_argnums=tuple(range(n_words)) if donate else (),
    )


@lru_cache(maxsize=32)
def _compile_radix_probe(mesh: Mesh, n_words: int, n: int,
                         digit_bits: int) -> Callable[..., Any]:
    """Capacity-negotiation probe (ISSUE 7): the exact pass-1 per-peer
    send-count matrix, no key movement (radix_sort.radix_probe_spmd)."""
    n_ranks = mesh.devices.size

    def f(*words: jax.Array) -> jax.Array:
        return radix_sort.radix_probe_spmd(words, digit_bits, n_ranks)

    return jax.jit(
        compat.shard_map(
            f, mesh=mesh, in_specs=(P(AXIS),) * n_words, out_specs=P(),
            # the [P, P] matrix is replicated by construction (it comes
            # out of an all_gather) but the vma checker cannot prove it
            check_vma=False,
        )
    )


@lru_cache(maxsize=32)
def _compile_sample_probe(mesh: Mesh, n_words: int, n: int,
                          oversample: int) -> Callable[..., Any]:
    """Estimated splitter-repartition count matrix (sample_probe_spmd)."""
    n_ranks = mesh.devices.size

    def f(*words: jax.Array) -> jax.Array:
        return sample_sort.sample_probe_spmd(words, n_ranks, oversample)

    return jax.jit(
        compat.shard_map(
            f, mesh=mesh, in_specs=(P(AXIS),) * n_words, out_specs=P(),
            check_vma=False,  # see _compile_radix_probe
        )
    )


@lru_cache(maxsize=16)
def _compile_interleave(mesh: Mesh, n_words: int,
                        n: int) -> Callable[..., Any]:
    """Skew-aware re-stage program (ISSUE 7): deal the global key array
    round-robin across shards — ``new[j*n + i] = old[i*P + j]`` — so a
    clustered arrangement (sorted/reverse-sorted input, the cap-blowing
    case) turns into one where every shard holds a representative
    stride of the whole distribution and per-peer exchange counts
    collapse toward the fair share.  A pure permutation: the sorted
    output (and the multiset fingerprint the verifier checks) is
    bit-identical.  Costs one resharding pass over the words — paid
    only when the measured imbalance says the exchange would otherwise
    need a near-worst-case capacity."""
    P_ = int(mesh.devices.size)

    def f(*words: jax.Array) -> tuple[jax.Array, ...]:
        return tuple(w.reshape(n, P_).T.reshape(-1) for w in words)

    return jax.jit(f, out_shardings=key_sharding(mesh))


#: Safety margin on the sample probe's ESTIMATED per-peer counts (its
#: splitters are sampled, the real run's are exact local quantiles —
#: see sample_sort.sample_probe_spmd); the radix probe is exact and
#: needs none.
SAMPLE_NEG_MARGIN = 1.25


def _negotiation_enabled(n_ranks: int) -> bool:
    """``SORT_NEGOTIATE``: capacity negotiation runs the count probe
    before compiling the exchange (auto/on = whenever the mesh is
    actually distributed; a 1-device mesh has no exchange to size)."""
    return knobs.get("SORT_NEGOTIATE") != "off" and n_ranks > 1


def _restage_enabled(n_ranks: int) -> bool:
    """``SORT_RESTAGE``: the skew-aware re-stage is armed (P>1 only)."""
    return knobs.get("SORT_RESTAGE") != "off" and n_ranks > 1


def _donation_enabled() -> bool:
    """Buffer donation on the sort dispatch: ``SORT_DONATE`` ∈
    {auto, 1, 0} (validated in one place, ``utils.io.donate_setting``).
    ``auto`` donates on real TPU backends only — that is where the
    aliasing saves HBM; CPU donation saves nothing and (on some jaxlib
    versions) emits an unusable-donation warning on every compile."""
    v = kio.donate_setting()
    if v == "auto":
        return jax.default_backend() == "tpu"
    return v == "1"


#: Recv-memory bound for the sample-sort exchange, in units of the fair
#: per-peer share ceil(n/P).  The [P, cap] recv buffer is then at most
#: 8·n words per device — O(n), never O(N) — and inputs needing more
#: (heavy duplication: every copy of a hot key routes to one splitter
#: interval) fall back to radix, whose destination = exact global
#: position is skew-immune by construction (SURVEY.md §7.3 Zipf config).
SAMPLE_CAP_LIMIT_FACTOR = 8


def _sample_skew_sniff(words_np: tuple[np.ndarray, ...], n_ranks: int) -> bool:
    """Cheap host-side skew detector: would quantile splitters degenerate?

    Takes an evenly-strided ~32·P-key sample, sorts it, and picks the same
    P-1 quantiles the SPMD program would.  Two *equal adjacent* splitters
    mean at least 2/P of the sample mass sits on one key value — every
    copy would route to a single destination and the exchange cap would
    blow through the O(n) bound, so route to radix up front instead of
    discovering it via a failed exchange round.  (Duplication below that
    threshold keeps splitters distinct and the cap bounded; the reactive
    in-loop bound still catches anything the sample misses.)
    """
    n_total = words_np[0].size
    s = min(n_total, max(64, 32 * n_ranks))
    idx = np.linspace(0, n_total - 1, s).astype(np.int64)
    # lexsort: last key is primary → feed words lsw-first.
    order = np.lexsort(tuple(w[idx] for w in reversed(words_np)))
    qpos = (np.arange(1, n_ranks) * s) // n_ranks
    picks = [tuple(int(w[idx[order[q]]]) for w in words_np) for q in qpos]
    return any(a == b for a, b in zip(picks, picks[1:]))


@lru_cache(maxsize=32)
def _compile_skew_sniff(mesh: Mesh, n_words: int, n_valid: int,
                        n_ranks: int) -> Callable[..., Any]:
    """Device-side twin of :func:`_sample_skew_sniff` for device-resident
    input (VERDICT r2 #4): the same evenly-strided sample, quantile picks
    and adjacent-equality verdict, computed on the mesh — one tiny
    compile + one scalar sync instead of discovering degeneracy through a
    failed full exchange round + recompile.  Samples index [0, n_valid)
    only, so pad slots (appended after the real keys) never join.

    The sample is a *static* strided ``lax.slice`` (start/stride/limit
    are Python ints baked into the program) rather than a gather: gather
    indices carry a dtype, and int32 ones silently wrap for
    n_valid ≥ 2^31 (ADVICE r3 #1) — a strided slice has no index array
    to overflow, at any scale.  The slice is anchored so its LAST pick
    is exactly index n_valid-1 (like the host twin's linspace endpoint):
    anchoring at 0 instead would leave up to ~n_valid/2 tail keys — and
    the global max — outside the sample."""
    s = min(n_valid, max(64, 32 * n_ranks))
    if s > 1:
        # Ceil, not floor: floor division made stride 1 whenever
        # n_valid < 2s, inflating the "sample" to nearly the whole shard
        # (ADVICE r4 #3).  Ceil keeps the pick count <= the requested s.
        stride = -(-(n_valid - 1) // (s - 1))
        s = (n_valid - 1) // stride + 1   # picks that fit the range
        start = (n_valid - 1) - (s - 1) * stride  # last pick = n_valid-1
    else:
        stride, start = 1, 0
    qpos = (np.arange(1, n_ranks) * s) // n_ranks

    def f(*words):
        # msw first = lexicographic order
        picks = [jax.lax.slice(w, (start,), (start + (s - 1) * stride + 1,),
                               (stride,))
                 for w in words]
        sp = jax.lax.sort(picks, num_keys=len(picks), is_stable=False)
        sp = sp if isinstance(sp, (list, tuple)) else (sp,)
        if qpos.size < 2:
            return jnp.zeros((), bool)
        eq = jnp.ones((qpos.size - 1,), bool)
        for p in sp:
            q = p[qpos]
            eq &= q[:-1] == q[1:]
        return jnp.any(eq)

    return jax.jit(f)


def _host_pad_words(codec: KeyCodec, flat: np.ndarray, dtype: np.dtype,
                    total: int) -> tuple[int, ...] | None:
    """Pad-word tuple for host input shorter than ``total``: the maximum
    real key (encode is order-preserving, so encoding the host max yields
    the lexicographically-max word tuple), or the all-ones sentinel for
    float codecs — ``np.max`` is NaN-poisoned and a NaN "max" need not be
    the totalOrder maximum.  None when no padding is needed (skips the
    host max() scan)."""
    if flat.size >= total:
        return None
    if codec.sentinel_pad:
        return codec.max_sentinel()
    return tuple(int(w[0]) for w in codec.encode(np.asarray([flat.max()], dtype)))


def _auto_digit_bits(diffs: tuple[int, ...]) -> int:
    """Auto digit width: a pass costs one full fused sort regardless of
    digit width (BASELINE.md roofline), so wider digits that cut the pass
    count win outright; 16-bit digits halve full-range int32 to 2 passes.
    The histogram / exscan metadata grows to [P, 65536] int32 — 256 KiB
    per device per pass, noise next to the shard itself."""
    return 16 if _passes_from_diffs(diffs, 16) < _passes_from_diffs(diffs, 8) else 8


def _shard_input(words_np: tuple[np.ndarray, ...], mesh: Mesh, n: int,
                 pad_words: tuple[int, ...] | None = None,
                 ) -> tuple[jax.Array, ...]:
    P_ = mesh.devices.size
    sharding = key_sharding(mesh)
    out = []
    for i, w in enumerate(words_np):
        if w.size < P_ * n:
            w = np.concatenate([w, np.full(P_ * n - w.size, pad_words[i], np.uint32)])
        out.append(checked_device_put(w, sharding))
    return tuple(out)


def radix_pass_states(
    x: Any, mesh: Mesh | None = None, digit_bits: int | None = None,
    cap_factor: float = 2.0, pack: str | None = None,
) -> Iterator[tuple[int, np.ndarray, np.ndarray]]:
    """Debug observability: the globally digit-sorted array after each LSD
    pass — the TPU twin of the reference's per-pass intermediate dump
    (``DUMP: LOOP %u RADIX %u = %u``, ``mpi_radix_sort.c:175-178``) and of
    the native core's debug>2 contract (``native/radix_core.h``).

    The fused SPMD program runs all passes inside one jit, so intermediate
    states are not observable in a production run; this helper *re-runs*
    the program with ``passes`` limited to 1..P — the LSD invariant makes
    the pass-``k`` output exactly the state after pass ``k`` (input stably
    sorted by its low ``k`` digits).  O(passes²) total work, debug-only.

    Yields ``(pass_index_1based, shard_size, full_padded_array)`` where the
    array is the decoded ``[P·shard]`` result (pads included — they are
    copies of the maximum real key and, by stability, the LAST occurrences
    of that value in every pass state; callers attributing keys to ranks
    drop exactly those trailing occurrences).
    """
    x = np.asarray(x)
    dtype = np.dtype(x.dtype)
    codec = codec_for(dtype)
    N = int(x.size)
    if N == 0:
        return
    if mesh is None:
        mesh = make_mesh()
    n_ranks = int(mesh.devices.size)
    n = max(1, math.ceil(N / n_ranks))
    flat = x.reshape(-1)
    words_np = codec.encode(flat)
    pad = _host_pad_words(codec, flat, dtype, n_ranks * n)
    words = _shard_input(words_np, mesh, n, pad)
    diffs = _word_diffs(words_np)
    if digit_bits is None:
        digit_bits = _auto_digit_bits(diffs)
    passes = _passes_from_diffs(diffs, digit_bits)
    pack_impl = _resolve_pack(pack)
    align = _cap_align(pack_impl)
    # cap only ever grows: an overflow discovered at pass prefix k would
    # recur at every k' > k, so keep the grown value across the loop.
    cap = _round_cap(int(n / n_ranks * cap_factor) + 1, align)
    for k in range(1, passes + 1):
        while True:
            fn = _compile_radix(mesh, codec.n_words, n, digit_bits, cap, k,
                                pack_impl)
            out, max_cnt = fn(*words)
            if int(max_cnt) <= cap:
                break
            cap = _round_cap(int(max_cnt), align)
        full = codec.decode(tuple(np.asarray(w) for w in out))
        yield k, n, full


def device_mem_peak(mesh: Mesh | None) -> int:
    """Peak HBM high-water across the mesh devices where the backend
    exposes ``memory_stats()`` (real TPU; CPU returns 0).  Best-effort
    telemetry — never raises.  The serve layer attaches this per packed
    batch (ISSUE 10); :func:`sort` attaches it to its umbrella span."""
    try:
        devs = list(mesh.devices.flat) if mesh is not None else jax.devices()
        peak = 0
        for d in devs:
            stats = d.memory_stats() if hasattr(d, "memory_stats") else None
            if stats:
                peak = max(peak, int(stats.get("peak_bytes_in_use", 0)))
        return peak
    except Exception:
        return 0


def _device_mem_high_water(span: Any, mesh: Mesh | None) -> None:
    """Attach :func:`device_mem_peak` to ``span`` when nonzero."""
    peak = device_mem_peak(mesh)
    if peak:
        span.attrs["device_mem_peak_bytes"] = peak


def _finish_plan(tracer: Tracer, plan: "plan_mod.SortPlan | None") -> None:
    """Seal and emit the run's decision record (ISSUE 12): default the
    engine/restage decisions from what the counters already know, fold
    the per-decision regrets, stamp the scalars into ``tracer.counters``
    (the bench rows read them), and emit the registered ``sort.plan``
    point event — the record ``report.py --explain``, the live regret
    metrics and the ``/varz`` decision snapshot all consume."""
    if plan is None:
        return
    c = tracer.counters
    engine = c.get("local_engine")
    if engine is not None:
        if "engine" in plan.decisions:
            plan.actual("engine", local_engine=str(engine))
        else:
            plan.decide("engine", chosen=str(engine))
        # backend rides the engine actual so the doctor's local-sort
        # rule can tell "lax on TPU" (a knob away from the fused
        # engine) from "lax on CPU" (nothing to suggest)
        plan.actual("engine", backend=str(jax.default_backend()))
    fallbacks = (int(c.get("pair_residual_fallback", 0))
                 - int(getattr(plan, "fallbacks_base", 0)))
    if fallbacks > 0:
        plan.actual("engine", fallbacks=fallbacks)
    if plan.ranks and plan.ranks > 1 and "restage" not in plan.decisions:
        # never restaged: its regret is every overflow regrow a
        # re-stage would have prevented (stamped by the supervisor)
        cap_d = plan.decisions.get("cap")
        plan.decide("restage", chosen=False)
        plan.actual("restage",
                    regrows=(cap_d.actual.get("regrows", 0)
                             if cap_d is not None else 0))
    total = plan.finalize()
    tracer.counters["plan_regret"] = total
    cap_d = plan.decisions.get("cap")
    if cap_d is not None and cap_d.regret is not None:
        tracer.counters["plan_cap_regret"] = cap_d.regret
    tracer.spans.event("sort.plan", **plan.to_attrs())
    tracer.plan = plan


def ingest_to_mesh(
    x: Any,
    mesh: Mesh | None = None,
    tracer: Tracer | None = None,
    chunk_elems: int | None = None,
    threads: int | None = None,
) -> StagedIngest:
    """Public streaming-ingest entry: run the chunked, double-buffered
    parse→encode→DMA pipeline (:mod:`mpitest_tpu.models.ingest`) over
    host keys ``x`` and return the :class:`StagedIngest` that
    :func:`sort` accepts in place of raw keys (skipping its own
    encode/pad/device_put entirely).  Every host→device transfer goes
    through the dtype-preservation guard (:func:`checked_device_put`).

    ``SORT_TRACE`` streaming applies here exactly as in :func:`sort`, so
    the ``ingest.*`` stage spans land in the same JSONL the report CLI
    aggregates."""
    if mesh is None:
        mesh = make_mesh()
    tracer = tracer or Tracer()
    trace_path = knobs.get("SORT_TRACE")
    if trace_path and tracer.spans.stream_path is None:
        tracer.spans.stream_path = trace_path
    reg = faults.for_run()
    supervision.wire_registry(reg, tracer)
    with tracer.spans.span("ingest", n=int(np.asarray(x).size),
                           dtype=str(np.asarray(x).dtype)), \
            faults.active(reg):
        return stream_to_mesh(x, mesh, tracer=tracer,
                              chunk_elems=chunk_elems, threads=threads)


def sort(
    x: Any,
    algorithm: str = "radix",
    mesh: Mesh | None = None,
    digit_bits: int | None = None,
    cap_factor: float = 2.0,
    oversample: int | None = None,
    tracer: Tracer | None = None,
    return_result: bool = False,
    pack: str | None = None,   # exchange pack impl; None = auto by backend
    exchange_engine: str | None = None,  # None = SORT_EXCHANGE_ENGINE knob
    payload: Any = None,       # per-record payload bytes -> record sort
) -> Any:
    """Sort integer keys on the mesh; returns a sorted numpy array
    (or the device-resident :class:`DistributedSortResult`).

    ``payload`` (ISSUE 15) turns the call into a **record sort**: each
    key drags an opaque per-record payload (bytes / any fixed-itemsize
    array, see :func:`models.records.as_payload_matrix`) that is
    permuted alongside the keys by a device-side argsort-gather —
    stable by key, verified end-to-end by the record fingerprint.  The
    return value is then the ``(sorted_keys, sorted_payload)`` pair
    (payload as an ``(n, width)`` uint8 matrix); ``return_result`` /
    ``exchange_engine`` do not apply — records ride the fused local
    program in :mod:`mpitest_tpu.models.records`.

    ``exchange_engine`` (ISSUE 13) selects the inter-device exchange
    path — ``lax`` (XLA collective) or ``pallas`` (remote-DMA kernel +
    fused pass, ``ops/exchange.py``; ``pallas_interpret`` is its
    interpreter form); ``None`` reads ``SORT_EXCHANGE_ENGINE`` (auto =
    pallas on TPU backends).  A pallas failure degrades to the lax
    engine on the supervisor ladder, fingerprint-verified and recorded
    as a plan decision.

    ``x`` may be a host array, a device-resident ``jax.Array``, or a
    :class:`StagedIngest` from :func:`ingest_to_mesh` (pre-encoded,
    pre-sharded words — the streaming pipeline's output).  Large host
    arrays automatically ride the same pipeline (``SORT_INGEST`` knob:
    auto/stream/mono); on TPU the staged word buffers are donated to the
    SPMD program so device memory holds one copy of the keys, not two.

    Telemetry: the run accumulates a structured span log on
    ``tracer.spans`` (:mod:`mpitest_tpu.utils.spans`) — nested phases,
    jit compile-vs-execute splits, one trace-time span per radix pass /
    splitter round / collective with byte counts, and the device memory
    high-water where ``memory_stats()`` exists.  ``SORT_TRACE=<path>``
    streams it as JSONL; ``tracer.spans.to_chrome_trace()`` exports the
    same run for Perfetto.  See the module docstring of utils/spans.py
    for the device-side granularity contract.
    """
    if algorithm not in ("radix", "sample"):
        raise ValueError(f"unknown algorithm {algorithm!r}")
    tracer = tracer or Tracer()
    trace_path = knobs.get("SORT_TRACE")
    if trace_path and tracer.spans.stream_path is None:
        tracer.spans.stream_path = trace_path
    if payload is not None:
        # record sort (ISSUE 15): key+payload through the fused
        # argsort-gather program — models/records.py owns the path,
        # including its always-on record-fingerprint verification.
        # The record path mints no plan record: clear any PREVIOUS
        # run's plan from a reused tracer (the serve dispatch thread),
        # or the reply digest would carry a stranger's decisions.
        from mpitest_tpu.models import records

        tracer.plan = None
        arr = np.asarray(x)
        with tracer.spans.span("sort", algorithm="records",
                               n=int(arr.size), dtype=str(arr.dtype)):
            return records.sort_records(arr, payload, mesh=mesh,
                                        tracer=tracer)
    size = getattr(x, "size", None)
    # Fault registry for THIS run (SORT_FAULTS env or an installed test
    # registry) — active for the whole run so the ingest/exchange hooks
    # see it; None in production is a no-op.
    reg = faults.for_run()
    # Plan provenance (ISSUE 12): ONE decision record per run, minted
    # here and carried on the tracer so every chokepoint below — algo
    # reroutes, negotiation, re-stage, the supervisor's regrow loop,
    # the fallback ladder — stamps into the same object.  SORT_PLAN=off
    # restores the PR 8 behavior.
    plan = (plan_mod.SortPlan(algo=algorithm,
                              dtype=str(getattr(x, "dtype", "")) or None)
            if plan_mod.enabled() else None)
    if plan is not None:
        # tracer counters accumulate across runs on a reused Tracer
        # (the serve dispatch thread): snapshot the fallback tally so
        # _finish_plan stamps THIS run's delta, not server-lifetime sums
        plan.fallbacks_base = int(
            tracer.counters.get("pair_residual_fallback", 0))
    tracer.plan = plan
    with tracer.spans.span(
        "sort", algorithm=algorithm,
        n=int(size) if size is not None else None,
        dtype=str(getattr(x, "dtype", "")) or None,
    ) as sp, faults.active(reg):
        try:
            out = _sort_impl(x, algorithm, mesh, digit_bits, cap_factor,
                             oversample, tracer, return_result, pack, reg,
                             exchange_engine)
            _finish_plan(tracer, plan)
        except supervision.SortFaultError as e:
            # ISSUE 10: a typed terminal error leaves an artifact — the
            # flight recorder's last-N spans (this run's retries, fault
            # events and failed verifications included) dumped where
            # SORT_FLIGHT_RECORDER_DIR points, rate-limited per reason.
            from mpitest_tpu.utils import flight_recorder

            flight_recorder.dump_on_error(type(e).__name__)
            raise
        _device_mem_high_water(sp, mesh)
    return out


def _sort_impl(
    x: Any,
    algorithm: str,
    mesh: Mesh | None,
    digit_bits: int | None,
    cap_factor: float,
    oversample: int | None,
    tracer: Tracer,
    return_result: bool,
    pack: str | None,
    reg: "faults.FaultRegistry | None" = None,
    exchange_engine: str | None = None,
) -> Any:
    """The sort() body (see the public wrapper's docstring — this layer
    assumes a validated algorithm and a live tracer/span log).

    Robustness contract (ISSUE 3): every result is verified before it is
    returned — on-device sortedness plus a multiset fingerprint compared
    against the input-side fingerprint folded during ingest/encode
    (:mod:`mpitest_tpu.models.verify`) — and the distributed dispatch
    runs under a :class:`SortSupervisor`: bounded retry with exponential
    backoff on transient ``JaxRuntimeError``, ONE shared cap-regrow loop
    for both algorithms, and a graceful-degradation ladder (requested
    algorithm → the other algorithm → host lexsort) on persistent
    failure.  The outcome is always a verified result or a typed
    :class:`SortIntegrityError` / :class:`SortRetryExhausted` — never a
    silent wrong answer.  Knobs: ``SORT_VERIFY``, ``SORT_MAX_RETRIES``,
    ``SORT_RETRY_BACKOFF``, ``SORT_FALLBACK``, ``SORT_FAULTS`` (fault
    injection, :mod:`mpitest_tpu.faults`).

    ``algorithm``: ``"radix"`` (flagship: perfectly load-balanced, fixed
    pass count) or ``"sample"`` (one exchange round; cap-sensitive under
    skew).  Both produce identical bytes — sorted output is canonical.

    Skew fallback (SURVEY.md §7.3): ``"sample"`` inputs whose quantile
    splitters would degenerate (heavy duplication — the Zipf stress
    config) route to radix automatically, either up front (host-side
    sniff, :func:`_sample_skew_sniff`) or reactively when the exchange
    cap would exceed the O(n)-per-device bound
    (:data:`SAMPLE_CAP_LIMIT_FACTOR`); ``tracer.counters
    ["sample_skew_fallback"]`` records the reroute.

    ``x`` may be a host array OR a device-resident ``jax.Array`` (any
    supported dtype — 64-bit device arrays exist only under
    ``jax_enable_x64`` and split into uint32 words on-device): the device
    path encodes/pads on-device and never round-trips the keys through
    the host — the framework's steady-state contract (keys live sharded
    on the mesh; SURVEY.md §5 long-context row).

    Device-resident ``float64`` caveats (measured on v5e, round 3): TPU
    stacks without a native f64→u32 bitcast lowering degrade to ONE
    documented host round-trip (``tracer.counters["f64_host_fallback"]``)
    instead of an internal compiler error; and on such stacks the
    *device array itself* is approximate (f64 held via f32-pair
    emulation — ~2e-15 relative error introduced by ``device_put``,
    before this function is called).  The sort is always bit-exact with
    respect to the bits actually resident on the device; host-input
    float64 is bit-exact, full stop.
    """
    staged = x if isinstance(x, StagedIngest) else None
    if staged is not None:
        if staged.consumed:
            raise ValueError(
                "StagedIngest was already consumed by a donated sort "
                "dispatch (its word buffers now belong to XLA); call "
                ".rebuild() or ingest_to_mesh() again for another sort")
        is_device = False
        dtype = staged.dtype
        codec = codec_for(dtype)
        N = staged.n_valid
        if mesh is None:
            mesh = staged.mesh
        elif mesh != staged.mesh:  # equality, not identity: make_mesh()
            raise ValueError(      # builds equal-but-distinct Mesh objects
                "StagedIngest was streamed onto a different mesh")
    else:
        is_device = isinstance(x, jax.Array)
        if not is_device:
            x = np.asarray(x)
        dtype = np.dtype(x.dtype)
        codec = codec_for(dtype)
        N = int(x.size)
    if N == 0:
        out = np.empty(0, dtype)
        return out if not return_result else DistributedSortResult((), 0, dtype)
    if mesh is None:
        mesh = make_mesh()
    n_ranks = int(mesh.devices.size)
    n = max(1, math.ceil(N / n_ranks))

    # ---- exchange engine (ISSUE 13): resolved once per run ----------
    # The ladder may later degrade it (pallas → lax); _eng is the ONE
    # mutable engine state every compile below reads.  Recorded in the
    # counters even for exchange-free (1-device) runs so bench rows
    # always carry the engine column.
    eng0 = _resolve_exchange_engine(exchange_engine)
    _eng = {"v": eng0}
    tracer.counters["exchange_engine"] = eng0
    # ---- local-sort engine (ISSUE 17): same ONE-mutable-state shape.
    # _leng holds the KNOB-level value ("radix_pallas" family / bitonic
    # / auto / lax); each dispatch resolves it per key-width and size
    # via _resolve_local_engine.  The ladder may degrade the fused
    # family to lax without touching the exchange engine.
    leng0 = _local_engine()
    _leng = {"v": leng0}

    # ---- plan provenance (ISSUE 12): the run's decision record ------
    plan = tracer.plan if isinstance(tracer.plan, plan_mod.SortPlan) \
        else None
    if plan is not None:
        plan.n = N
        plan.ranks = n_ranks
        plan.decide("algo", chosen=algorithm, requested=algorithm)
        if staged is None and not is_device:
            # host input: sortedness / run-length / duplicate profile
            # from a ~1k strided sample (no extra key movement; the
            # probe adds entropy/skew once the histogram materializes)
            plan.profile.update(plan_mod.profile_host_array(x))

    verify_on = supervision.verify_enabled()
    # Wire fault telemetry BEFORE any word staging: the ingest_poison
    # site fires inside the streaming pipeline, long before the
    # supervisor object exists below.
    supervision.wire_registry(reg, tracer)

    # ---- self-tuning planner (ISSUE 14): the policy layer -----------
    # off: nothing below runs — the hand-set defaults byte-for-byte.
    # shadow: every policy is scored and logged as the registered
    # `planner` plan decision (applied=False) while the output path
    # stays untouched.  on: the algo policy may override `algorithm`
    # and the learned margin replaces SAMPLE_NEG_MARGIN.  The planner
    # rides the plan record, so SORT_PLAN=off also disables it.
    planner_mode = planner_mod.mode()
    pchoice: "planner_mod.PolicyChoice | None" = None
    neg_margin = SAMPLE_NEG_MARGIN
    if planner_mode != "off" and plan is not None:
        pchoice = planner_mod.choose(plan.profile, algorithm,
                                     verify_on=verify_on)
        # the margin only steers the sample negotiation: requests bound
        # for radix, 1-rank runs (no exchange) and negotiate-off runs
        # skip the flight-ring scan entirely — and never record
        # cap_margin as an applied policy they cannot act on (a
        # passthrough miss over a sample request still falls into the
        # sample path, so those keep it)
        if ((pchoice.algo or algorithm) == "sample"
                and _negotiation_enabled(n_ranks)):
            margin, margin_ev = planner_mod.learned_margin(
                SAMPLE_NEG_MARGIN)
        else:
            margin, margin_ev = SAMPLE_NEG_MARGIN, {}
        # the RECORDED policy: when the algo scorer chose nothing but
        # the margin policy learned, the margin IS the planner's move
        name = pchoice.policy
        if name == "static" and margin_ev.get("margin_learned"):
            name = "cap_margin"
        planner_mod.policy(name)  # runtime twin of SL006: loud KeyError
        plan.decide("planner", chosen=name, requested="static",
                    trigger=pchoice.trigger,
                    applied=(planner_mode == "on"),
                    algo=pchoice.algo, margin=round(margin, 4),
                    **dict(pchoice.predicted, **margin_ev))
        tracer.counters["planner"] = planner_mode
        tracer.counters["planner_policy"] = name
        if planner_mode == "on":
            neg_margin = margin
            if pchoice.algo is not None and pchoice.algo != algorithm:
                # the scored reroute: recorded exactly like the sniff/
                # probe reroutes, so plan_regret now measures the
                # planner itself (a wrong choice shows up as algo/cap
                # regret on a planner-triggered decision)
                plan.decide("algo", chosen=pchoice.algo,
                            trigger="planner")
                algorithm = pchoice.algo
            if (pchoice.policy == "radix_compact"
                    and "passes" in pchoice.predicted):
                # key-width compaction (ISSUE 17): the profile's min/max
                # promise a narrow key, so pre-record the predicted pass
                # count.  run_radix keeps this prediction when it plans
                # for real — the "passes" regret then prices a lying
                # profile (sampled min/max missed the range, more passes
                # ran than the planner promised).
                plan.decide("passes",
                            chosen=int(pchoice.predicted["passes"]),
                            trigger="planner",
                            passes=int(pchoice.predicted["passes"]))

    def _check_result(res_v, fp_v) -> bool:
        """Run the on-device verifier on a result; True = verified.
        Emits the ``verify`` span event (ok / sorted_ok / fp_ok) the
        report CLI's robustness table aggregates."""
        with tracer.phase("verify"):
            sorted_ok, fp_ok = vfy.verify_result(res_v, fp_v)
        tracer.count("verify_runs", 1)
        tracer.spans.event("verify", ok=bool(sorted_ok and fp_ok),
                           sorted_ok=bool(sorted_ok), fp_ok=bool(fp_ok),
                           n=N)
        if not (sorted_ok and fp_ok):
            tracer.verbose(
                f"output verification FAILED (sorted={bool(sorted_ok)}, "
                f"fingerprint={bool(fp_ok)})")
        return bool(sorted_ok and fp_ok)

    def _local_device_fp():
        """Input fingerprint for device-resident single-device input:
        one tiny fused encode+reduce dispatch.  The known f64 encode
        lowering gap degrades to sortedness-only verification (fp None)
        rather than breaking the sort."""
        try:
            return vfy.fingerprint_device_input(x.reshape(-1), dtype)
        except jax.errors.JaxRuntimeError:
            tracer.verbose("input fingerprint unavailable on this backend; "
                           "verifying sortedness only")
            return None

    def _finish_local(res_l, fp_l):
        """Verify-and-return for the single-device paths.  No ladder
        here (the degradation machinery targets the distributed
        dispatch); a verification failure is a typed error."""
        if plan is not None:
            # single-device runs have no distributed ladder: the rung
            # is the fused local path itself (the engine decision is
            # defaulted from the counters at _finish_plan time)
            plan.decide("ladder", chosen="local")
        if verify_on and not _check_result(res_l, fp_l):
            raise SortIntegrityError(
                "single-device sort result failed verification")
        if return_result:
            return res_l
        with tracer.phase("decode"):
            return res_l.to_numpy(tracer=tracer)

    if staged is not None and n_ranks == 1:
        # 1-device mesh with pre-staged words: one fused local sort of
        # the padded shard (pads replicate the max key, so they sort to
        # the tail past n_valid — same contract as the host local path).
        # The streamed ingest already folded per-word diffs, so the
        # fused radix engine gets its compacted pass plan for free.
        s_widths = (tuple(int(d).bit_length() for d in staged.word_diffs)
                    if leng0.startswith("radix_pallas")
                    and staged.word_diffs is not None else None)
        with tracer.phase("sort"):
            out = _traced_call(
                tracer, "local",
                _compile_local(codec.n_words, leng0, s_widths),
                *staged.words)
        return _finish_local(DistributedSortResult(out, N, dtype),
                             staged.fingerprint if verify_on else None)

    if staged is None and n_ranks == 1 and algorithm in ("radix", "sample"):
        engine = leng0
        if (codec.n_words == 2 and engine != "lax"
                and N >= (1 << bitonic.MIN_SORT_LOG2)
                and (engine == "bitonic" or jax.default_backend() == "tpu")):
            # 64-bit local path: the adaptive pair-engine orchestration
            # (constant-word shortcut / dup sniff / pair bitonic + run
            # fix-up / lax fallback) — see _local_pair_sort.
            fp_in = None
            pair_words = None
            if not is_device:
                # encode ONCE: the fingerprint and the pair sort share
                # the words (a second O(n) encode pass would bill the
                # verifier for work the sort needs anyway)
                with tracer.phase("encode"):
                    pair_words = codec.encode(np.asarray(x).reshape(-1))
                if verify_on:
                    with tracer.phase("verify"):
                        fp_in = vfy.fingerprint_host(pair_words)
            elif verify_on:
                fp_in = _local_device_fp()
            out = _local_pair_sort(x, is_device, codec, dtype, mesh, tracer,
                                   words_np=pair_words)
            return _finish_local(DistributedSortResult(out, N, dtype), fp_in)
        tracer.counters["local_engine"] = _resolve_local_engine(
            engine, codec.n_words, N)
        if is_device and _f64_known_broken(_device_platform(x), dtype, codec):
            x, is_device = _f64_host_input(x, tracer), False
        fp_in = None
        if is_device:
            if verify_on:
                fp_in = _local_device_fp()
            try:
                with tracer.phase("sort"):
                    out = _traced_call(
                        tracer, "local_device",
                        _compile_local_device(dtype.name, engine),
                        x.reshape(-1))
            except jax.errors.JaxRuntimeError as e:
                # float64 device-side encode needs a f64->u32 bitcast some
                # TPU stacks cannot lower (XLA's x64-rewrite pass lacks the
                # rule; int64 works).  Degrade to one documented host
                # round-trip instead of an internal compiler error; every
                # other runtime failure re-raises untouched.
                if not _is_f64_lowering_gap(e, dtype, codec,
                                            _device_platform(x)):
                    raise
                x, is_device = _f64_host_input(x, tracer), False
        if not is_device:
            with tracer.phase("encode"):
                words_np = codec.encode(x.reshape(-1))
            if verify_on:
                with tracer.phase("verify"):
                    fp_in = vfy.fingerprint_host(words_np)
            with tracer.phase("device_put"):
                words = tuple(
                    checked_device_put(w, mesh.devices.flat[0])
                    for w in words_np
                )
            # planner rung zero, local edition (ISSUE 14): same contract
            # as the distributed rung below — the profile read fully
            # sorted, so the encoded input words ARE a sort candidate;
            # one verify dispatch replaces the local sort when it
            # passes, and a miss costs exactly the verify (typed as the
            # planner decision's regret) before the sort runs.  This is
            # the only 1-rank site the policy can reach: device-resident
            # and staged inputs take no host profile, so the scorer
            # already chose `static` for them.
            if (pchoice is not None and planner_mode == "on" and verify_on
                    and pchoice.policy == "verify_passthrough"
                    and fp_in is not None):
                cand = DistributedSortResult(words, N, dtype)
                if _check_result(cand, fp_in):
                    tracer.count("planner_passthrough", 1)
                    if plan is not None:
                        plan.decide("ladder", chosen="passthrough")
                    if return_result:
                        return cand
                    with tracer.phase("decode"):
                        return cand.to_numpy(tracer=tracer)
                tracer.count("planner_passthrough_miss", 1)
                if plan is not None:
                    plan.actual("planner", misses=1)
            # fused-engine pass compaction: the host words are in hand,
            # so one cheap max/min pass quantizes the per-word spread
            # into the compile key's width vocabulary.
            l_widths = (tuple(int(d).bit_length()
                              for d in _word_diffs(words_np))
                        if engine.startswith("radix_pallas") else None)
            with tracer.phase("sort"):
                out = _traced_call(tracer, "local",
                                   _compile_local(codec.n_words, engine,
                                                  l_widths), *words)
        return _finish_local(DistributedSortResult(out, N, dtype), fp_in)

    #: per-word max^min already known without touching the data again
    #: (streamed ingest folds it chunk-by-chunk); None = plan from
    #: words_np or a device reduction as before.
    plan_diffs: tuple[int, ...] | None = None
    #: re-create the sharded input words after a *donated* dispatch
    #: consumed them (overflow retry / skew reroute); None disables
    #: donation for this input.
    rebuild_words = None
    #: input fingerprint folded by an in-sort streamed ingest (the
    #: device words may already carry an injected ingest fault, so the
    #: fingerprint must come from the HOST-side chunk folds).
    stream_fp = None

    if staged is not None:
        words = staged.words
        words_np = None
        plan_diffs = staged.word_diffs
        if staged.source is not None:
            rebuild_words = lambda: staged.rebuild().words  # noqa: E731
    if staged is None and is_device and _f64_known_broken(
            _mesh_platform(mesh), dtype, codec):
        x, is_device = _f64_host_input(x, tracer), False
    if staged is None and is_device:
        words_np = None

        def _device_encode_words():
            x_flat = x.reshape(-1)
            if N == n_ranks * n:
                # Land the input on the mesh first (no-op when already
                # sharded there); a committed single-device array would
                # otherwise conflict with the jit's mesh-wide
                # out_shardings.
                x_flat = checked_device_put(x_flat, key_sharding(mesh))
                return _traced_call(
                    tracer, "encode_pad",
                    _compile_encode_pad(dtype.name, N, mesh), x_flat)
            # Uneven N cannot be mesh-sharded directly; encode+pad
            # wherever the input lives, then land the even result.
            ws = _traced_call(
                tracer, "encode_pad",
                _compile_encode_pad(dtype.name, n_ranks * n, None),
                x_flat)
            return tuple(checked_device_put(w, key_sharding(mesh))
                         for w in ws)

        try:
            with tracer.phase("encode"):
                words = _device_encode_words()
            rebuild_words = _device_encode_words
        except jax.errors.JaxRuntimeError as e:
            # see the single-device branch: f64->u32 bitcast gap on some
            # TPU stacks — degrade to one documented host round-trip.
            # Memo key = the MESH's platform (the compile that failed),
            # not the input's.
            if not _is_f64_lowering_gap(e, dtype, codec, _mesh_platform(mesh)):
                raise
            x, is_device = _f64_host_input(x, tracer), False
    if staged is None and not is_device:
        flat = x.reshape(-1)
        if use_stream(flat.nbytes):
            # Streaming ingest (models/ingest.py): chunked parse/encode
            # overlapped with per-shard DMA, bounded host memory, and
            # the pass-planner diffs folded in flight — no second host
            # pass over the keys.
            with tracer.phase("ingest"):
                st = stream_to_mesh(flat, mesh, tracer=tracer)
            words = st.words
            words_np = None
            plan_diffs = st.word_diffs
            stream_fp = st.fingerprint
            rebuild_words = lambda: stream_to_mesh(  # noqa: E731
                flat, mesh, tracer=tracer).words
        else:
            with tracer.phase("encode"):
                words_np = codec.encode(flat)
                pad = _host_pad_words(codec, flat, dtype, n_ranks * n)

            with tracer.phase("device_put"):
                words = _shard_input(words_np, mesh, n, pad)
            rebuild_words = lambda: _shard_input(  # noqa: E731
                words_np, mesh, n, pad)

    pack_impl = _resolve_pack(pack)
    # cap alignment follows the FIRST rung's engine: the pallas engine's
    # fused pack needs CHUNK-aligned caps, and a CHUNK-aligned cap stays
    # valid (just 128-aligned too) if the ladder later degrades to lax.
    _, align = _engine_pack(pack_impl, eng0)
    if plan is not None:
        # the pack that will actually run: the pallas exchange engine
        # owns its fused pack regardless of the resolved pack impl
        plan.decide("engine", chosen=_engine_pack(pack_impl, eng0)[0])
        plan.decide("exchange_engine", chosen=eng0)
    # Donate the input word buffers to the SPMD program where the
    # backend profits (HBM aliasing) and the input can be rebuilt for
    # overflow retries (a donated buffer is dead after the dispatch).
    donate = _donation_enabled() and rebuild_words is not None
    if donate and staged is not None:
        # the first dispatch hands the staged buffers to XLA; flag the
        # object now so a reuse fails with a clear error instead of
        # dispatching on deleted arrays
        staged.consumed = True

    # ---- robustness layer (ISSUE 3): supervisor + input fingerprint --
    sup = SortSupervisor(tracer, registry=reg, plan=plan)
    input_fp = None
    if verify_on:
        with tracer.phase("verify"):
            if staged is not None:
                # folded chunk-by-chunk during streamed ingest — free
                input_fp = staged.fingerprint
            elif stream_fp is not None:
                input_fp = stream_fp  # in-sort streamed ingest, same fold
            elif words_np is not None:
                input_fp = vfy.fingerprint_host(words_np)
            else:
                # device-resident padded words: one tiny fused reduction
                input_fp = vfy.fingerprint_device(words, N)

    #: fair per-peer share of a shard — the ONE definition behind the
    #: sample cap bound, the skew-reroute cap, and every scale-out
    #: imbalance ratio (ISSUE 7).
    fair = max(1, -(-n // n_ranks))
    base_cap = _round_cap(int(n / n_ranks * cap_factor) + 1, align)
    # Radix cap for skew reroutes: duplication that degenerates splitters
    # also concentrates a radix pass's send runs, so start at the same
    # O(n)-per-device bound the sample path enforces instead of paying
    # overflow-retry recompiles to grow there.
    skew_cap = _round_cap(min(n, SAMPLE_CAP_LIMIT_FACTOR * fair), align)
    if oversample is None:
        oversample = max(2 * n_ranks - 1, 8)
    # Upper clamp: splitter quality saturates far below this, the
    # [P, oversample] sample gather replicates to every device, and
    # evenly_spaced_samples' int32 index math needs d^2 < 2^31.
    oversample = min(oversample, n, 16_384)

    # ---- scale-out layer (ISSUE 7): negotiation + skew re-stage -----
    negotiate = _negotiation_enabled(n_ranks)
    restage_on = _restage_enabled(n_ranks)
    restage_ratio = knobs.get("SORT_RESTAGE_RATIO")
    _restaged = {"done": False}

    # Live/dead tracking of the (possibly donated) input word buffers —
    # the ONE place that knows whether the next dispatch must re-stage.
    # Every dispatch of a donated program hands the words to XLA, so any
    # rerun (overflow regrow, transient retry, verification retry,
    # degradation rung) rebuilds through here.
    _wstate = {"words": words, "dead": False}

    def _interleave(ws: tuple) -> tuple:
        return _traced_call(
            tracer, "interleave",
            _compile_interleave(mesh, codec.n_words, n), *ws)

    def live_words():
        if _wstate["dead"]:
            w = rebuild_words()
            if _restaged["done"]:
                # the run committed to the rebalanced arrangement; a
                # rebuild (donation retry / verify re-stage) must land
                # back on it, or the negotiated cap no longer fits
                w = _interleave(w)
            _wstate["words"] = w
            _wstate["dead"] = False
        return _wstate["words"]

    def do_restage() -> None:
        """Skew-aware re-stage: interleave the shards so per-peer
        exchange counts collapse toward the fair share (see
        _compile_interleave).  Idempotent — triggered proactively by
        the count probe or reactively by the supervisor's regrow loop,
        whichever detects the imbalance first."""
        if _restaged["done"]:
            return
        with tracer.spans.span("restage", ranks=n_ranks, n=n):
            _wstate["words"] = _interleave(live_words())
            _wstate["dead"] = False
        _restaged["done"] = True
        tracer.count("skew_restage", 1)
        tracer.verbose(
            "skew re-stage: interleaved shards to rebalance the exchange")

    def mark_dead():
        if donate:
            _wstate["dead"] = True

    def force_restage():
        """After a verification failure the staged words themselves are
        suspect (e.g. an ingest fault corrupted them after the
        fingerprint fold) — re-stage from the source even when donation
        is off, so the retry runs on freshly ingested data."""
        if rebuild_words is not None:
            _wstate["dead"] = True

    _plan: dict = {}

    def radix_plan():
        if not _plan:
            with tracer.phase("plan"):
                if plan_diffs is not None:
                    # Streamed ingest already folded per-word max^min
                    # chunk-by-chunk — planning is free.
                    diffs = plan_diffs
                elif words_np is None:
                    # Device-resident input: one scalar min/max sync per
                    # word plans the pass count (pads replicate the max
                    # key — range unchanged).
                    ranges = _compile_word_range(dtype.name)(x.reshape(-1))
                    diffs = tuple(int(lo) ^ int(hi) for lo, hi in ranges)
                else:
                    diffs = _word_diffs(words_np)
                db = digit_bits if digit_bits is not None \
                    else _auto_digit_bits(diffs)
                _plan["p"] = (db, _passes_from_diffs(diffs, db))
        return _plan["p"]

    def _balance_event(cnts: np.ndarray, algo_label: str, exact: bool,
                       negotiated: int, restaged: bool) -> None:
        """Fold a measured [P, P] count matrix into telemetry: the
        ``exchange_balance`` event (per-rank send/recv byte lists + the
        ratios) and the counters the bench/report scale-out tables
        read.  ``recv`` imbalance is the classic per-rank exchange-byte
        skew (radix is 1.0 by construction — destination blocks are
        n-sized); ``peer_ratio`` (max single-peer segment over the fair
        share) is what actually drives the capacity."""
        wpb = 4 * codec.n_words
        send = cnts.sum(axis=1) * wpb
        recv = cnts.sum(axis=0) * wpb
        rmean = float(recv.mean())
        recv_ratio = float(recv.max()) / rmean if rmean > 0 else 1.0
        peer_ratio = float(cnts.max()) / fair
        tracer.spans.event(
            "exchange_balance", algorithm=algo_label, ranks=n_ranks,
            exact=exact, peer_max=int(cnts.max()), fair=fair,
            negotiated_cap=negotiated, worst_cap=n,
            send_bytes=[int(v) for v in send],
            recv_bytes=[int(v) for v in recv],
            recv_ratio=round(recv_ratio, 4),
            peer_ratio=round(peer_ratio, 4), restaged=restaged,
            exchange_engine=_eng["v"])
        tracer.counters["negotiated_cap"] = negotiated
        tracer.counters["worst_cap"] = n
        tracer.counters["exchange_balance_ratio"] = round(recv_ratio, 4)
        tracer.counters["exchange_peer_ratio"] = round(peer_ratio, 4)

    def _probe(kind: str, db: int | None = None) -> np.ndarray:
        fn = (_compile_radix_probe(mesh, codec.n_words, n, db)
              if kind == "radix" else
              _compile_sample_probe(mesh, codec.n_words, n, oversample))
        with tracer.phase("plan"):
            return np.asarray(_traced_call(
                tracer, f"{kind}_probe", fn, *live_words()))

    def _negotiate(kind: str, db: int | None = None) -> np.ndarray:
        """Run the count probe; re-stage once (and re-probe) when the
        measured per-peer imbalance crosses the re-stage ratio.  Returns
        the count matrix describing the arrangement the sort will
        actually exchange."""
        cnts = _probe(kind, db)
        if plan is not None:
            # the probe's [P, P] histogram is already materialized —
            # the input-distribution profile rides it for free
            plan.profile.update(plan_mod.profile_from_counts(cnts, fair))
        ratio = float(cnts.max()) / fair
        if (restage_on and not _restaged["done"]
                and ratio >= restage_ratio):
            tracer.verbose(
                f"{kind} probe: per-peer need {int(cnts.max())} >= "
                f"{restage_ratio:g}x fair share {fair}; re-staging")
            if plan is not None:
                plan.decide("restage", chosen=True, trigger="probe",
                            peer_ratio=round(ratio, 4))
            do_restage()
            cnts = _probe(kind, db)
            if plan is not None:
                plan.actual("restage",
                            peer_ratio=round(float(cnts.max()) / fair, 4))
        return cnts

    def run_radix(cap0: int) -> DistributedSortResult:
        db, passes = radix_plan()
        eng = _eng["v"]
        eff_pack, eff_align = _engine_pack(pack_impl, eng)
        tracer.counters["exchange_engine"] = eng
        # Local engine inside the radix shards: only the fused family
        # applies (the first pass's stable digit sort is a counting
        # sort the fused kernel replaces 1:1); bitonic has no slot in
        # the digit passes, so everything else stays lax.
        leng = _resolve_local_engine(_leng["v"], codec.n_words, n)
        radix_leng = leng if leng.startswith("radix_pallas") else "lax"
        tracer.counters["local_engine"] = radix_leng
        if plan is not None:
            # keep a planner-predicted pass count (radix_compact) as
            # the prediction this decision is scored against; the
            # chosen/ran side comes from the real plan below.
            d_passes = plan.decisions.get("passes")
            keep = (d_passes is not None
                    and "passes" in d_passes.predicted)
            plan.decide("passes", chosen=passes, digit_bits=db,
                        **({} if keep else {"passes": passes}))
        if negotiate and passes > 0:
            cnts = _negotiate("radix", db)
            need = _round_cap(int(cnts.max()), eff_align)
            # pass 1's need is EXACT; later passes depend on the post-
            # exchange arrangement, so multi-pass runs keep the
            # cap_factor floor and the regrow loop as backstop instead
            # of risking a full re-run to undercut it.
            cap0 = need if passes == 1 else max(need, cap0)
            if plan is not None:
                plan.decide("cap", chosen=cap0, trigger="exact",
                            cap=cap0, need=int(cnts.max()), fair=fair)
            _balance_event(cnts, "radix", True, cap0, _restaged["done"])
        elif plan is not None:
            plan.decide("cap", chosen=cap0, trigger="off", cap=cap0,
                        fair=fair)
        last_need = {"v": None}

        def attempt(c: int):
            fn = _compile_radix(mesh, codec.n_words, n, db, c, passes,
                                eff_pack, donate, sup.arm_exchange(),
                                exchange_engine=eng,
                                local_engine=radix_leng)
            with tracer.phase("sort"):
                out, max_cnt = sup.dispatch(
                    "radix_spmd", fn, live_words, on_retry=mark_dead,
                    n=n, cap=c, passes=passes, digit_bits=db, ranks=n_ranks)
                mark_dead()
                max_cnt = int(max_cnt)
            last_need["v"] = max_cnt
            # Exchange accounting (SURVEY.md §5 metrics row), counted per
            # attempt so discarded overflow retries — whose all_to_all
            # traffic really crossed the links — are included: the padded
            # exchange ships full [P, cap] word blocks; wire bytes
            # exclude the self-block, which never leaves the device.
            tracer.count(
                "exchange_bytes",
                passes * n_ranks * (n_ranks - 1) * c * 4 * codec.n_words,
            )
            return out, max_cnt

        out, cap = sup.exchange_loop(
            "radix", attempt, sup.squeeze_cap(cap0, eff_align), eff_align,
            _round_cap, on_overflow=mark_dead,
            re_stage=do_restage if restage_on else None)
        tracer.count("exchange_passes", passes)
        tracer.counters["exchange_cap"] = cap  # last cap, not accumulated
        tracer.counters["digit_bits"] = db     # auto-resolved width
        if plan is not None:
            # actual side of the cap decision: the measured per-peer
            # need and its wire-byte size (vs the probe's prediction)
            plan.actual("cap", cap=cap, need=last_need["v"],
                        peer_recv_bytes=(last_need["v"] or 0)
                        * 4 * codec.n_words)
            plan.actual("passes", passes=passes)
        return DistributedSortResult(out, N, dtype)

    def run_sample() -> DistributedSortResult:
        eng = _eng["v"]
        eff_pack, eff_align = _engine_pack(pack_impl, eng)
        tracer.counters["exchange_engine"] = eng
        if words_np is not None:
            degenerate = _sample_skew_sniff(words_np, n_ranks)
        else:
            # Device-resident input: same sniff on the mesh — a tiny
            # strided sample + quantile check, one scalar sync.  Without
            # it, skewed device inputs would only discover degeneracy via
            # a failed exchange round + recompile (VERDICT r2 #4).
            degenerate = bool(
                _compile_skew_sniff(mesh, codec.n_words, N, n_ranks)(
                    *live_words())
            )
        if degenerate:
            tracer.verbose(
                "sample: quantile splitters degenerate (heavy duplication); "
                "routing to radix (skew-immune)"
            )
            tracer.count("sample_skew_fallback", 1)
            if plan is not None:
                plan.decide("algo", chosen="radix", trigger="skew_sniff")
            return run_radix(skew_cap)
        cap_limit = _round_cap(SAMPLE_CAP_LIMIT_FACTOR * fair, eff_align)
        cap_start = base_cap
        if negotiate:
            cnts = _negotiate("sample")
            # the sample probe is an ESTIMATE (sampled splitters) —
            # margin on top, and the regrow loop stays as backstop.
            # neg_margin is SAMPLE_NEG_MARGIN unless the planner is ON
            # and learned a tighter one from the flight ring's observed
            # estimate-error quantiles (ISSUE 14 cap/margin policy).
            need = _round_cap(
                int(float(cnts.max()) * neg_margin) + 1, eff_align)
            if need > cap_limit:
                # the estimate already busts the O(n) recv bound: route
                # to radix NOW instead of paying a doomed full exchange
                # to find out (the reactive ExchangeCapExceeded path
                # below stays for what the estimate misses)
                tracer.verbose(
                    f"sample probe estimates cap {need} > O(n) bound "
                    f"{cap_limit}; routing to radix (skew-immune)")
                tracer.count("sample_skew_fallback", 1)
                if plan is not None:
                    plan.decide("algo", chosen="radix",
                                trigger="probe_estimate")
                return run_radix(skew_cap)
            cap_start = need
            if plan is not None:
                plan.decide("cap", chosen=cap_start, trigger="estimate",
                            cap=cap_start, need=int(cnts.max()), fair=fair,
                            margin=round(neg_margin, 4))
            _balance_event(cnts, "sample", False, cap_start,
                           _restaged["done"])
        elif plan is not None:
            plan.decide("cap", chosen=cap_start, trigger="off",
                        cap=cap_start, fair=fair)
        spmd_engine = _resolve_local_engine(_leng["v"], codec.n_words, n)
        tracer.counters["local_engine"] = spmd_engine

        last_need = {"v": None}

        def attempt(c: int):
            fn = _compile_sample(mesh, codec.n_words, n, c, oversample,
                                 eff_pack, spmd_engine, donate,
                                 sup.arm_exchange(),
                                 exchange_engine=eng)
            with tracer.phase("sort"):
                out, counts, max_cnt = sup.dispatch(
                    "sample_spmd", fn, live_words, on_retry=mark_dead,
                    n=n, cap=c, ranks=n_ranks)
                mark_dead()
                max_cnt = int(max_cnt)
            last_need["v"] = max_cnt
            tracer.count(
                "exchange_bytes",
                n_ranks * (n_ranks - 1) * c * 4 * codec.n_words,
            )
            return (out, counts), max_cnt

        try:
            (out, counts), cap = sup.exchange_loop(
                "sample", attempt, sup.squeeze_cap(cap_start, eff_align),
                eff_align, _round_cap, cap_limit=cap_limit,
                on_overflow=mark_dead,
                re_stage=do_restage if restage_on else None)
        except ExchangeCapExceeded as e:
            tracer.verbose(
                f"sample exchange needs cap {e.need} > O(n) bound "
                f"{e.limit}; routing to radix (skew-immune)"
            )
            tracer.count("sample_skew_fallback", 1)
            if plan is not None:
                # the LATE reroute: a full exchange ran and busted the
                # bound before the switch — the regret the up-front
                # sniff/probe reroutes exist to avoid
                plan.decide("algo", chosen="radix", trigger="cap_exceeded")
                plan.actual("algo", late_reroute=True)
            return run_radix(skew_cap)
        tracer.count("exchange_passes", 1)
        tracer.counters["exchange_cap"] = cap
        if plan is not None:
            plan.actual("cap", cap=cap, need=last_need["v"],
                        peer_recv_bytes=(last_need["v"] or 0)
                        * 4 * codec.n_words)
        return DistributedSortResult(
            out, N, dtype, counts=np.asarray(counts),
            shard_slots=n_ranks * cap
        )

    def run_host() -> tuple:
        """Last degradation rung: host lexsort over the encoded words —
        no device dispatch at all, so it survives a dead backend.  The
        result is fingerprint-verified on the host before anyone sees
        it."""
        tracer.verbose("graceful degradation: host lexsort fallback")
        if staged is not None:
            if staged.source is None:
                raise SortIntegrityError(
                    "host fallback impossible: StagedIngest kept no source")
            arr = np.asarray(staged.source).reshape(-1)
        else:
            arr = np.asarray(x).reshape(-1)
        with tracer.phase("sort"):
            w = codec.encode(arr)
            # np.lexsort: last key is primary -> feed words lsw-first
            order = np.lexsort(tuple(reversed(w)))
            sorted_w = tuple(wi[order] for wi in w)
        if verify_on and input_fp is not None:
            with tracer.phase("verify"):
                out_fp = vfy.fingerprint_host(sorted_w)
            tracer.count("verify_runs", 1)
            if out_fp != input_fp:
                raise SortIntegrityError(
                    "host fallback result failed fingerprint verification "
                    "(input changed between ingest and fallback?)")
        return sorted_w

    # ---- degradation ladder: pallas exchange engine -> lax engine
    # (same algorithm, ISSUE 13), then requested algorithm -> the other
    # one -> host lexsort.  Each rung gets one verification retry (a
    # transient corruption re-dispatches clean); persistent dispatch
    # failure or repeated verification failure moves down.  The ladder
    # ends in a VERIFIED result or a typed error — never a silent wrong
    # answer.
    fused_local = leng0.startswith("radix_pallas")
    #: lower-rung local engine: the fused family degrades to lax with
    #: the rest of the rung; the bitonic/auto/lax values ride every
    #: rung unchanged (their fallback story predates this ladder).
    lower_leng = "lax" if fused_local else leng0
    rungs: list[tuple[str, str, str]] = [(algorithm, eng0, leng0)]
    if supervision.fallback_enabled():
        if fused_local:
            # the LOCAL engine rung (ISSUE 17): a broken fused radix
            # kernel must not cost the exchange engine or the
            # algorithm — re-run the same rung on lax local sorts
            rungs.append((algorithm, eng0, "lax"))
        if eng0 != "lax":
            # the engine rung: a broken pallas kernel must not cost the
            # requested ALGORITHM — re-run it on the XLA collective
            rungs.append((algorithm, "lax", lower_leng))
        rungs.append(("sample" if algorithm == "radix" else "radix",
                      "lax", lower_leng))
        rungs.append(("host", "lax", "lax"))
    if plan is not None:
        plan.decide("ladder", chosen=rungs[0][0])

    res = None
    host_words = None
    last_err: Exception | None = None
    #: why the previous rung ended: "dispatch" (supervised dispatch /
    #: device error) vs "verify" (fingerprint/sortedness failures) —
    #: the engine-degrade decision must blame the ACTUAL cause, not
    #: stamp every descent off a pallas rung as a kernel fault.
    last_fail = "dispatch"
    level = rungs[0][0]

    # ---- planner rung zero (ISSUE 14): verify-passthrough -----------
    # The profile's strided sample read fully sorted, so the staged
    # input words ARE a sort candidate: one O(n) verify dispatch (the
    # same always-on gate every ladder rung faces) replaces the whole
    # sort when it passes.  A miss — the sample hid a descent — costs
    # exactly the verify pass (the planner decision's regret) and the
    # ordinary ladder below sorts for real.  Only in `on` mode and only
    # with the verifier armed: without it the profile is a guess, and a
    # guess must not skip the sort.
    if (pchoice is not None and planner_mode == "on" and verify_on
            and pchoice.policy == "verify_passthrough"
            and input_fp is not None):
        cand = DistributedSortResult(live_words(), N, dtype)
        if _check_result(cand, input_fp):
            tracer.count("planner_passthrough", 1)
            if plan is not None:
                plan.decide("ladder", chosen="passthrough")
            res = cand
        else:
            tracer.count("planner_passthrough_miss", 1)
            if plan is not None:
                plan.actual("planner", misses=1)

    for level, rung_eng, rung_leng in (() if res is not None else rungs):
        if rung_leng != _leng["v"]:
            tracer.verbose(
                f"degrading local-sort engine {_leng['v']} -> {rung_leng}")
            tracer.count("local_engine_degraded", 1)
            _leng["v"] = rung_leng
            if plan is not None:
                # the engine decision keeps its chosen (the pack that
                # runs); the degrade stamps its trigger, and the regret
                # rule prices the descent exactly like exchange_engine
                eng_d = plan.decisions.get("engine")
                plan.decide(
                    "engine",
                    chosen=(eng_d.chosen if eng_d is not None
                            else rung_leng),
                    trigger=("pallas_fault" if last_fail == "dispatch"
                             else "verify_failure"))
        if rung_eng != _eng["v"]:
            tracer.verbose(
                f"degrading exchange engine {_eng['v']} -> {rung_eng}")
            tracer.count("exchange_engine_degraded", 1)
            _eng["v"] = rung_eng
            if plan is not None:
                plan.decide(
                    "exchange_engine", chosen=rung_eng,
                    trigger=("pallas_fault" if last_fail == "dispatch"
                             else "verify_failure"))
        if level != rungs[0][0]:
            tracer.verbose(f"degrading to the {level} path")
            if plan is not None:
                plan.decide("ladder", chosen=level)
                plan.bump("ladder", "rungs_descended")
        done = False
        for ver_try in range(2 if verify_on else 1):
            try:
                if level == "host":
                    host_words = run_host()
                    done = True
                    break
                cand = run_sample() if level == "sample" else \
                    run_radix(base_cap)
                cand = faults.maybe_corrupt_result(reg, cand)
                ok = not verify_on or _check_result(cand, input_fp)
            except SortRetryExhausted as e:
                last_err = e
                last_fail = "dispatch"
                tracer.verbose(f"{level} path failed persistently: {e}")
                break
            except jax.errors.JaxRuntimeError as e:
                # A dead device can also surface OUTSIDE the supervised
                # sort dispatch — the skew sniff, the pass-planner
                # reduction, the verifier program.  The ladder exists
                # for exactly this: degrade instead of leaking an
                # untyped error past the typed-error contract (the host
                # rung needs no device at all).
                last_err = SortRetryExhausted(
                    f"{level} path failed outside the sort dispatch: "
                    f"{e}")
                last_err.__cause__ = e
                last_fail = "dispatch"
                tracer.count("sort_retries", 1)
                tracer.verbose(f"{level} path device failure: "
                               f"{type(e).__name__}; degrading")
                break
            if ok:
                res = cand
                done = True
                break
            tracer.count("verify_failures", 1)
            last_fail = "verify"
            force_restage()  # the input words themselves are suspect
        if done:
            break
    if res is None and host_words is None:
        if last_err is not None:
            raise last_err
        raise SortIntegrityError(
            "no sort path produced a verified result (verify_failures="
            f"{int(tracer.counters.get('verify_failures', 0))})")

    if host_words is not None:
        tracer.counters["degraded_to"] = "host"
        out_np = codec.decode(host_words)
        if not return_result:
            return out_np
        # best-effort re-stage of the host-sorted words onto the mesh
        # (already globally sorted; pads = max copies keep the contract).
        # If the device is GENUINELY dead — the scenario this rung
        # survives — the re-stage fails too: return a host-backed result
        # instead of leaking an untyped JaxRuntimeError past the typed-
        # error contract (DistributedSortResult's decode/probe paths are
        # plain array ops, so numpy words work throughout).
        try:
            pad = _host_pad_words(codec, out_np, dtype, n_ranks * n)
            return DistributedSortResult(
                _shard_input(host_words, mesh, n, pad), N, dtype)
        except jax.errors.JaxRuntimeError:
            tracer.verbose("device unavailable for re-staging the host "
                           "fallback result; returning host-backed words")
            return DistributedSortResult(host_words, N, dtype)

    if level != rungs[0][0]:
        tracer.counters["degraded_to"] = level
    if return_result:
        return res
    with tracer.phase("decode"):
        out_np = res.to_numpy(tracer=tracer)
    return out_np
