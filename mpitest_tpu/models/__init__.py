from mpitest_tpu.models.api import sort, DistributedSortResult  # noqa: F401
from mpitest_tpu.models import radix_sort, sample_sort  # noqa: F401
