"""Distributed sample sort — splitter-based repartitioning, TPU-native.

Reference algorithm (``mpi_sample_sort.c:28-218``): local sort → each rank
sends 2P-1 evenly spaced samples to rank 0 → rank 0 sorts P·(2P-1) samples,
picks P-1 splitters, broadcasts → per-key linear bucket scan → hand-rolled
Alltoallv (tag = length) → local sort → Gatherv to root.

TPU redesign:

* **Splitters are computed replicated**, not on a root: samples ride one
  ``all_gather`` (tiny: P·s words) and every device sorts them and picks
  identical splitters — the Isend-per-sample / tag-as-index protocol
  (``mpi_sample_sort.c:101,112``) has no reason to exist on a mesh.
* **Bucketing is one vectorized lexicographic searchsorted**
  (:func:`mpitest_tpu.ops.kernels.searchsorted_words`), not an O(P)-per-key
  scan (``mpi_sample_sort.c:148-155``).  Keys are already locally sorted,
  so bucket ids are monotone ⇒ per-destination segments are contiguous ⇒
  the shared ragged exchange applies.
* **The bucket cap is honest.**  The reference fixes capacity at
  1.5·(N/P)·2 and silently overflows under skew
  (``mpi_sample_sort.c:140-144``).  Here the cap is static for XLA but
  overflow is *detected* (returned max_send_cnt) and the host retries with
  the exact cap — the Zipf stress config's failure mode becomes a
  recompile, not a corruption.

Output stays sharded and ragged: each device holds ``P·cap`` slots of
which the first ``count`` (after the final local sort, with max-sentinel
fill) are valid.  Gather-to-root happens only at the host boundary for
verification/output, mirroring what SURVEY.md §2.3 prescribes for
``MPI_Gatherv``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from mpitest_tpu.ops import kernels, keys
from mpitest_tpu.parallel import collectives as coll
from mpitest_tpu.parallel.mesh import AXIS
from mpitest_tpu.utils import spans

Words = tuple[jax.Array, ...]


def select_splitters(sorted_words: Words, n_ranks: int, oversample: int,
                     axis: str = AXIS) -> Words:
    """Evenly spaced local samples → all_gather → replicated splitters.

    ``oversample`` is the per-rank sample count (the reference uses 2P-1,
    ``mpi_sample_sort.c:89``); larger values tighten splitter balance at
    negligible cost (P·oversample words total)."""
    # Trace-time span (utils/spans.py): the sample all_gather nests
    # under the splitter round in the SORT_TRACE stream.
    with spans.maybe_span("splitter_round", ranks=n_ranks,
                          oversample=oversample, trace_time=True,
                          sample_bytes=(n_ranks * oversample * 4
                                        * len(sorted_words))):
        samples = kernels.evenly_spaced_samples(sorted_words, oversample)
        gathered = tuple(coll.all_gather(s, axis).reshape(-1) for s in samples)  # [P*s]
        gsorted = kernels.local_sort(gathered)
        m = n_ranks * oversample
        idx = (jnp.arange(1, n_ranks, dtype=jnp.int32) * m) // n_ranks       # P-1 picks
        return tuple(w[idx] for w in gsorted)


def sample_probe_spmd(
    words: Words,
    n_ranks: int,
    oversample: int,
    axis: str = AXIS,
) -> jax.Array:
    """Capacity-negotiation count probe (ISSUE 7): ESTIMATED per-peer
    send counts of the splitter repartition, without sorting the shard.

    Splitters are picked from a sorted evenly-strided sample of the
    (unsorted) shard — statistically the same quantile estimate the real
    program derives from its fully-sorted shard, at a tiny fraction of
    the cost — then one vectorized ``searchsorted`` + histogram counts
    each destination.  Because the real run's splitters are exact local
    quantiles and these are sampled ones, the counts are an *estimate*:
    the caller adds a margin and keeps the supervisor's regrow loop as
    the backstop (the radix probe, by contrast, is exact).

    The strided sample is a static ``lax.slice`` (no gather index array
    to overflow at scale), anchored so the last pick is index n-1 —
    the same construction as the device skew sniff in models/api.py.

    Returns int32[P, P], replicated: row r = estimated counts rank r
    sends to each peer.
    """
    n = words[0].shape[0]
    s = min(n, max(64, 32 * n_ranks))
    if s > 1:
        stride = -(-(n - 1) // (s - 1))     # ceil: picks stay <= s
        s = (n - 1) // stride + 1
        start = (n - 1) - (s - 1) * stride  # last pick = n-1
    else:
        stride, start = 1, 0
    with spans.maybe_span("negotiate_probe", algorithm="sample",
                          ranks=n_ranks, n=n, trace_time=True):
        samp = tuple(
            lax.slice(w, (start,), (start + (s - 1) * stride + 1,),
                      (stride,))
            for w in words
        )
        splitters = select_splitters(kernels.local_sort(samp), n_ranks,
                                     min(oversample, s), axis)
        dest = kernels.searchsorted_words(splitters, words)
        h = kernels.histogram(dest, n_ranks)
        return coll.all_gather(h, axis)


def sample_sort_spmd(
    words: Words,
    n_words: int,
    n_ranks: int,
    cap: int,
    oversample: int,
    axis: str = AXIS,
    pack: str = "xla",
    engine: str = "lax",
    exchange_engine: str = "lax",
) -> tuple[Words, jax.Array, jax.Array]:
    """Full sample sort of the shard. SPMD; call under shard_map.

    Returns ``(out_words, count, max_send_cnt)`` where ``out_words`` are
    [P*cap] per-device buffers whose first ``count`` slots are the valid
    globally-sorted run for this shard position.

    ``engine`` selects the per-shard sort for the two big local sorts
    (the pre-split shard sort and the post-exchange merge): ``"bitonic"``
    = the Pallas engine of ``ops/bitonic.py`` (one-word keys), ``"lax"``
    = the fused ``lax.sort``.  The tiny splitter-sample sort always uses
    ``lax.sort``.

    ``exchange_engine`` selects the one splitter-repartition exchange's
    transport (ISSUE 13): ``"pallas"``/``"pallas_interpret"`` route the
    negotiated per-peer buckets through the fused pack + remote-DMA
    engine (``ops/exchange.py``); ``"lax"`` keeps the XLA collective.
    Output is bit-identical either way (the sentinel-fill contract is
    the same); sample sort has a single exchange, so the multi-pass
    overlap loop is radix-only.
    """
    sorted_words = kernels.local_sort(words, engine=engine)
    splitters = select_splitters(sorted_words, n_ranks, oversample, axis)

    # dest[i] = number of splitters < key[i]  ∈ [0, P-1]; monotone since sorted.
    dest = kernels.searchsorted_words(splitters, sorted_words)

    n = words[0].shape[0]
    h = kernels.histogram(dest, n_ranks)
    send_start = coll.exclusive_cumsum(h)
    send_cnt = h

    sentinel = (keys.MAX_WORD,) * n_words
    recv, recv_cnt, max_cnt = coll.ragged_all_to_all(
        sorted_words, send_start, send_cnt, cap, n_ranks, axis,
        fill=sentinel, pack=pack, engine=exchange_engine,
    )
    # Invalid lanes are max-sentinel filled → they sort to the tail; the
    # first `count` slots after sorting are exactly the valid multiset
    # (canonical-output argument, SURVEY.md §7.3).
    flat = tuple(r.reshape(-1) for r in recv)
    out = kernels.local_sort(flat, engine=engine)
    count = jnp.minimum(recv_cnt, cap).sum().astype(jnp.int32)
    return out, count, max_cnt
