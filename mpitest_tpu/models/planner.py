"""Self-tuning planner — the policy that closes the telemetry loop.

Everything before this module *measured*: the input-distribution
profiler (``models/plan.py``), the capacity probes (PR 6), the
predicted-vs-actual regret telemetry (PR 10), the flight-recorder ring
(PR 8), the batcher's queue waits and padded-lane waste.  But the 76
knobs that steer the hot path were still hand-set constants: a sorted
input paid every radix pass, the sample-negotiation margin was a fixed
×1.25, and a bursty small-request mix ran a fixed batching window.
This module is the missing actuator: per-request **policies** that turn
those measurements into the config the telemetry says is fastest —

* **algo policy** (:func:`choose`): score the host input profile
  (sortedness / duplicate ratio, the same ~1k strided sample the plan
  profiler already takes) into a registered policy — sorted input short-
  circuits through the always-on verifier (one O(n) verify dispatch IS
  the sort when it passes, and the ladder sorts for real when the
  strided sample lied), near-sorted input takes the one-exchange sample
  path, duplicate-heavy input routes to radix up front (the planner's
  scored twin of the reactive ``skew_sniff``);
* **cap/margin policy** (:func:`learned_margin`): size the sample
  probe's safety margin from the OBSERVED estimate-error distribution —
  the ``actual need / predicted need`` ratios of recent ``negotiate``
  decisions in the flight-recorder ring — instead of the hand-set
  ``SAMPLE_NEG_MARGIN`` constant; a well-behaved estimator earns a
  tight cap (lower cap regret), a drifting one a wide one (no regrows);
* **serve auto-tuning** (:class:`ServeTuner`): the batching window and
  prewarm shape buckets re-sized from the rolling request mix
  (inter-arrival gaps, size quantiles) with two-phase hysteresis so an
  oscillating mix can never thrash the window.

Modes (``SORT_PLANNER``): ``off`` — nothing runs, byte-identical to the
pre-planner stack; ``shadow`` — every policy is computed and logged as
a registered ``planner`` plan decision (would-have-been choice, applied
``False``) while the output path stays byte-identical; ``on`` — the
policies act.  Every decision rides the PR 10 provenance machinery
(``sort.plan`` spans → ``/metrics`` regret gauges → ``report.py
--explain``), the always-on verifier and the supervisor ladder make any
bad choice recoverable, and ``bench/planner_selftest.py`` is the gate:
planner-on must measurably beat planner-off on an adversarial mix.

Policy names are REGISTERED here (:data:`PLANNER_POLICIES`), exactly
like plan decisions in ``models/plan.py``: sortlint rule ``SL006``
fails the lint gate on any literal policy name outside the registry.

This module is import-light on purpose (stdlib only at import time —
knobs/flight-recorder load lazily inside functions): sortlint loads it
by file path with no package context, like ``plan.py``.
"""

from __future__ import annotations

import collections
import math
import statistics
import threading
from dataclasses import dataclass, field
from typing import Any

#: Version tag of the planner record (stamped into decision attrs).
PLANNER_SCHEMA = "planner.v1"

#: Registered policy vocabulary: name -> one-line doc of what the
#: policy does and when the scorer picks it.  sortlint SL006 fails the
#: gate on any literal policy name outside this dict (same loader
#: pattern as SL005 plan decisions).
PLANNER_POLICIES: dict[str, str] = {
    "static": ("the hand-set defaults unchanged — the scorer found no "
               "profile signal worth acting on (uniform input, or no "
               "host profile available)"),
    "verify_passthrough": ("profile says the input is already sorted: "
                           "run the always-on verifier on the staged "
                           "input words — one O(n) verify dispatch IS "
                           "the sort when it passes; a miss (the "
                           "strided sample hid a descent) falls "
                           "through to the ordinary ladder"),
    "merge_sample": ("near-sorted input: quantile splitters over "
                     "sorted-ish data are near-perfect, so the single-"
                     "exchange sample path beats the multi-pass radix "
                     "default"),
    "radix_narrow": ("duplicate-heavy / low-entropy input: splitters "
                     "would degenerate, and the measured effective key "
                     "width already cuts the radix pass count — route "
                     "to radix up front (the scored twin of the "
                     "reactive skew_sniff)"),
    "radix_compact": ("range-narrow input (ISSUE 17): the profile's "
                      "sampled min/max promise few significant key "
                      "bits, so the radix route with its diff-driven "
                      "pass planner (and the fused local engine's "
                      "compacted pass plan) sorts in fewer, narrower "
                      "passes than any comparison path — extends "
                      "radix_narrow from dup-heavy to range-narrow"),
    "cap_margin": ("sample-negotiation margin sized from the observed "
                   "estimate-error quantiles in the flight ring "
                   "instead of the fixed x1.25 constant — the recorded "
                   "policy when the margin learned but the algo scorer "
                   "chose nothing (profile was uniform)"),
    "window_auto": ("serve batching window re-sized from the rolling "
                    "inter-arrival mix (two-phase hysteresis: two "
                    "consecutive agreeing evaluations commit, an "
                    "oscillating mix never flips twice in a row)"),
    "buckets_auto": ("executor-cache prewarm buckets extended from the "
                     "observed request size/dtype mix so the mix's "
                     "shapes compile off the request path (per dtype — "
                     "packed executables are keyed by it)"),
}


def policy(name: str) -> str:
    """Registered-policy lookup: returns the policy's doc line, raises
    ``KeyError`` for unregistered names — the runtime twin of sortlint
    SL006 (a policy name that is not in the vocabulary is a bug, not a
    new feature)."""
    return PLANNER_POLICIES[name]


# ----------------------------------------------------------- mode / knobs

def mode() -> str:
    """``SORT_PLANNER`` ∈ {off, shadow, on} (default off — the
    pre-planner stack byte-for-byte).  ``shadow`` computes and logs
    every policy choice without acting; ``on`` acts.

    The planner RIDES the plan-provenance layer (its decisions are
    plan decisions, its margin policy reads plan records from the
    flight ring), so ``SORT_PLAN=off`` disables the planner everywhere
    — this resolver is the one chokepoint: library hook and serve
    tuner both read the same effective mode, and the ``SORT_PLAN=off``
    contract ("no sort.plan spans") can never be violated by a
    planner half-running."""
    from mpitest_tpu.models import plan as plan_mod
    from mpitest_tpu.utils import knobs

    v = knobs.get("SORT_PLANNER")
    if v != "off" and not plan_mod.enabled():
        return "off"
    return v


def window() -> int:
    """``SORT_PLANNER_WINDOW``: how many recent records/observations
    the learning policies look back over (flight-ring plan records for
    the margin policy, request arrivals for the serve tuner)."""
    from mpitest_tpu.utils import knobs

    return knobs.get("SORT_PLANNER_WINDOW")


def hysteresis() -> float:
    """``SORT_PLANNER_HYSTERESIS``: minimum ratio a serve-tuner
    recommendation must differ from the current value by before it may
    be applied (> 1; applied symmetrically up/down)."""
    from mpitest_tpu.utils import knobs

    return knobs.get("SORT_PLANNER_HYSTERESIS")


# ------------------------------------------------------------ algo policy

#: Profile thresholds of the algo scorer (unit-tested in
#: tests/test_planner.py).  The strided profile's sortedness is the
#: fraction of non-decreasing adjacent sample pairs; dup_ratio the
#: fraction of equal adjacent pairs in the sorted sample.
SORTED_SORTEDNESS = 1.0      # every sampled pair non-decreasing
NEAR_SORTED_SORTEDNESS = 0.9
DUP_RATIO_HEAVY = 0.25
#: Max sampled key width (significant bits of max-min) that counts as
#: range-narrow: 20 bits in an int64 is the canonical ISSUE 17 case —
#: 3 radix passes instead of 8.  Mirrors the digit math: width/8 passes.
NARROW_KEY_WIDTH_BITS = 20
#: Digit widths the pass prediction considers — the radix default and
#: the wide digit models/api.py's _auto_digit_bits switches to when it
#: cuts the pass count; the prediction mirrors that rule (min passes
#: over both widths) so an honest profile predicts the pass count that
#: actually runs.
NARROW_DIGIT_BITS = 8
NARROW_WIDE_DIGIT_BITS = 16


@dataclass
class PolicyChoice:
    """One scored algo-policy verdict: the registered policy name, the
    profile class that fired (``trigger``), the algorithm override
    (None = keep the requested one), and the predicted quantities the
    plan decision records."""

    policy: str
    trigger: str
    algo: str | None = None
    predicted: dict[str, Any] = field(default_factory=dict)


def choose(profile: dict, requested: str,
           verify_on: bool) -> PolicyChoice:
    """Score the input profile into a registered policy.  Pure function
    of its inputs (unit-testable); empty profiles (device-resident /
    staged input — no host sample was taken) choose ``static``.
    ``requested`` is the algo the caller asked for: a policy whose
    target already equals it returns ``algo=None`` (the policy is
    still recorded, the reroute is a no-op).

    Ordering: fully-sorted first (the passthrough beats everything and
    needs the verifier as its proof), then duplicate-heavy (a near-
    sorted but dup-heavy input would degenerate sample splitters — the
    radix route wins even when sortedness is high), then near-sorted,
    then range-narrow (a near-sorted narrow input still wants the
    single-exchange sample path; compaction only pays on inputs the
    multi-pass radix would run anyway).
    """
    sortedness = profile.get("sortedness")
    dup = profile.get("dup_ratio", 0.0)
    if sortedness is None:
        return PolicyChoice("static", "no_profile")
    if sortedness >= SORTED_SORTEDNESS and verify_on:
        # the verifier is the proof — without it the "sorted" sample is
        # just a guess, and a guess must not skip the sort
        return PolicyChoice("verify_passthrough", "sorted",
                            predicted={"sortedness": sortedness})
    if dup >= DUP_RATIO_HEAVY:
        return PolicyChoice(
            "radix_narrow", "dup_heavy",
            algo=None if requested == "radix" else "radix",
            predicted={"dup_ratio": dup})
    if sortedness >= NEAR_SORTED_SORTEDNESS:
        return PolicyChoice(
            "merge_sample", "near_sorted",
            algo=None if requested == "sample" else "sample",
            predicted={"sortedness": sortedness})
    width = profile.get("key_width")
    if width is not None and 0 < int(width) <= NARROW_KEY_WIDTH_BITS:
        # key-width compaction (ISSUE 17): predicted passes are what
        # the diff planner will run IF the sampled range held; the
        # "passes" plan decision scores that promise against the pass
        # count actually dispatched (lying-profile regret)
        w = int(width)
        passes = min(-(-w // NARROW_DIGIT_BITS),
                     -(-w // NARROW_WIDE_DIGIT_BITS))
        return PolicyChoice(
            "radix_compact", "range_narrow",
            algo=None if requested == "radix" else "radix",
            predicted={"key_width": w, "passes": passes})
    return PolicyChoice("static", "uniform")


# ------------------------------------------------------ cap/margin policy

#: Bounds of the learned sample-negotiation margin: never below a 2%
#: safety pad (the regrow loop is the backstop, but a regrow costs a
#: full discarded exchange), never above the old worst-case constant
#: territory (an estimator THAT wrong should pay regrows visibly, not
#: hide behind an unbounded margin).
MARGIN_MIN = 1.02
MARGIN_MAX = 2.0

#: Multiplicative pad on the observed q95 error ratio (the 5% tail the
#: quantile did not see still has to fit more often than not).
MARGIN_PAD = 1.03

#: Below this many observed negotiate decisions the margin policy
#: declines to learn and returns the hand-set default.
MARGIN_MIN_SAMPLES = 6

#: Recompute the learned margin only after the flight ring grew by
#: this many spans (the quantile can't move faster than the ring
#: fills) — amortizes the ring scan off the per-request path.
MARGIN_REFRESH = 24

#: Memo of the last computation: (recorder instance, its recorded
#: count at compute time, learned margin or None, evidence).  The
#: identity check recomputes when tests swap the recorder; the count
#: check recomputes after :data:`MARGIN_REFRESH` new spans.
_margin_memo: "tuple[Any, int, float | None, dict[str, Any]] | None" \
    = None


def learned_margin(default: float, last_n: int | None = None,
                   ) -> tuple[float, dict[str, Any]]:
    """The cap/margin policy: the sample probe's safety margin sized
    from the observed estimate-error distribution — the ``actual need /
    predicted need`` ratios of recent estimate-mode ``cap`` decisions
    in the flight-recorder ring (``sort.plan`` spans; the predicted
    side is the raw probe count, so the ratio measures the ESTIMATOR,
    independent of whatever margin past runs applied).  Returns
    ``(margin, evidence)`` where evidence is stamped into the planner
    decision's predicted attrs; with fewer than
    :data:`MARGIN_MIN_SAMPLES` observations the hand-set ``default``
    comes back unchanged (``margin_learned`` False).

    Memoized per :data:`MARGIN_REFRESH` ring growth: at serve QPS the
    ring scan + record decode would otherwise repeat per request for a
    value that only moves as new negotiate decisions accumulate."""
    global _margin_memo
    from mpitest_tpu.utils import flight_recorder

    rec = flight_recorder.get()
    memo = _margin_memo
    if (memo is not None and memo[0] is rec
            and 0 <= rec.recorded - memo[1] < MARGIN_REFRESH):
        return (default if memo[2] is None else memo[2]), dict(memo[3])
    if last_n is None:
        last_n = window()
    rows = rec.snapshot(last_n=last_n, kinds=("sort.plan",))
    ratios: list[float] = []
    for r in rows:
        decs = (r.get("attrs") or {}).get("decisions")
        if not isinstance(decs, dict):
            continue
        cap = decs.get("cap")
        if not isinstance(cap, dict) or cap.get("trigger") != "estimate":
            continue
        pred = (cap.get("predicted") or {}).get("need")
        act = (cap.get("actual") or {}).get("need")
        try:
            if pred and act and float(pred) > 0:
                ratios.append(float(act) / float(pred))
        except (TypeError, ValueError):
            continue
    if len(ratios) < MARGIN_MIN_SAMPLES:
        ev = {"margin_samples": len(ratios), "margin_learned": False}
        _margin_memo = (rec, rec.recorded, None, ev)
        return default, dict(ev)
    ratios.sort()
    q95 = ratios[min(len(ratios) - 1,
                     max(0, math.ceil(0.95 * len(ratios)) - 1))]
    m = min(max(q95 * MARGIN_PAD, MARGIN_MIN), MARGIN_MAX)
    ev = {"margin_samples": len(ratios), "margin_learned": True,
          "margin_q95": round(q95, 4)}
    _margin_memo = (rec, rec.recorded, m, ev)
    return m, dict(ev)


# ------------------------------------------------------- serve auto-tuner

#: Evaluate the mix every this many observations (the tuner's cost is
#: one median over the rolling window, amortized far off the hot path).
RETUNE_EVERY = 24

#: Below this many observations the tuner declines to recommend.
MIN_OBSERVATIONS = 16

#: The window recommendation: enough to collect ~this many arrivals
#: at the observed median gap (a closed-loop burst packs into one
#: dispatch; sparse traffic earns a short window and low latency).
WINDOW_GAIN = 4.0

#: Clamp of the recommended batching window, seconds.  The floor keeps
#: latency sane on pathological gap estimates; the ceiling keeps the
#: tuner from ever holding a request longer than a human-visible blink.
MIN_WINDOW_S = 1e-3
MAX_WINDOW_S = 16e-3

#: Gaps above this are idle pauses, not traffic cadence — clipped so
#: one quiet minute cannot drag the median into absurdity.
MAX_GAP_S = 1.0


class ServeTuner:
    """Rolling-mix observer + two-phase hysteresis for the serve layer.

    Handler threads call :meth:`observe` per admitted request (one
    deque append under a lock); every :data:`RETUNE_EVERY` observations
    the caller runs :meth:`evaluate`, which recommends a batching
    window from the observed inter-arrival gaps and size quantiles and
    decides — under the two-phase hysteresis contract — whether to
    commit it:

    * a recommendation inside the hysteresis band of the current value
      is a ``hold`` (and clears any pending direction);
    * the FIRST out-of-band recommendation in a direction is a ``hold``
      that arms that direction;
    * the SECOND consecutive out-of-band recommendation in the SAME
      direction commits (``retune``) and clears the armed state.

    Corollary (regression-tested): an oscillating mix whose successive
    evaluations disagree in direction never commits at all, and no two
    consecutive evaluations can both commit — the window cannot thrash.

    The tuner tracks its own committed window (``window_s``) so shadow
    mode can log would-have-been retunes over time without ever
    touching the live batcher; the serve layer applies ``window_s`` to
    the batcher only in ``on`` mode.
    """

    def __init__(self, window: int, hysteresis: float,
                 batch_keys: int, initial_window_s: float) -> None:
        self.capacity = max(int(window), MIN_OBSERVATIONS)
        self.hysteresis = float(hysteresis)
        self.batch_keys = int(batch_keys)
        self.window_s = float(initial_window_s)
        self._arrivals: "collections.deque[float]" = collections.deque(
            maxlen=self.capacity)
        self._sizes: "collections.deque[int]" = collections.deque(
            maxlen=self.capacity)
        self._dtypes: "collections.deque[str]" = collections.deque(
            maxlen=self.capacity)
        self._lock = threading.Lock()
        self._since_eval = 0
        self._pending_dir: str | None = None
        self.evals = 0
        self.retunes = 0
        self.last: dict[str, Any] = {}

    def observe(self, t_arrival: float, n: int,
                dtype_name: str = "int32") -> bool:
        """Record one admitted request; True when an evaluation is due
        (every :data:`RETUNE_EVERY` observations).  ``dtype_name``
        feeds the prewarm recommendation — a packed executable is
        keyed per dtype, so an int32 build never covers a uint64 mix."""
        with self._lock:
            self._arrivals.append(float(t_arrival))
            self._sizes.append(int(n))
            self._dtypes.append(str(dtype_name))
            self._since_eval += 1
            if self._since_eval >= RETUNE_EVERY:
                self._since_eval = 0
                return True
        return False

    def _recommend_locked(self) -> dict[str, Any] | None:
        if len(self._arrivals) < MIN_OBSERVATIONS:
            return None
        arr = list(self._arrivals)
        gaps = [min(b - a, MAX_GAP_S)
                for a, b in zip(arr, arr[1:]) if b >= a]
        if not gaps:
            return None
        p50_gap = statistics.median(gaps)
        desired = min(max(WINDOW_GAIN * p50_gap, MIN_WINDOW_S),
                      MAX_WINDOW_S)
        sizes = sorted(self._sizes)
        # clamp to the batch bound: over-batch_keys requests dispatch
        # solo and never touch a packed executable, so their sizes must
        # not steer the prewarm toward buckets no batch can ever use
        p99_n = min(sizes[min(len(sizes) - 1,
                              max(0, math.ceil(0.99 * len(sizes)) - 1))],
                    self.batch_keys)
        # the packed total a full window would plausibly collect: the
        # p99 request times the arrivals one window spans, capped at
        # the batch-keys bound — the bucket this mix actually needs
        expect = min(self.batch_keys,
                     int(p99_n * max(1.0, desired / max(p50_gap, 1e-6))))
        return {"window_s": round(desired, 6),
                "p50_gap_s": round(p50_gap, 6),
                "p99_n": int(p99_n),
                "expected_batch_keys": int(expect),
                "dtypes": tuple(sorted(set(self._dtypes)))}

    def evaluate(self) -> tuple[str, dict[str, Any]] | None:
        """Recommend-and-maybe-commit (see class docstring).  Returns
        ``None`` (not enough data), ``("hold", rec)`` or
        ``("retune", rec)`` — on retune, ``self.window_s`` already
        carries the committed value."""
        with self._lock:
            rec = self._recommend_locked()
            if rec is None:
                return None
            self.evals += 1
            self.last = rec
            desired = float(rec["window_s"])
            cur = self.window_s
            ratio = desired / cur if cur > 0 else math.inf
            if 1.0 / self.hysteresis < ratio < self.hysteresis:
                self._pending_dir = None
                return ("hold", rec)
            direction = "up" if desired > cur else "down"
            if self._pending_dir != direction:
                # phase one: arm the direction, commit nothing yet
                self._pending_dir = direction
                return ("hold", rec)
            # phase two: the second consecutive agreeing evaluation
            self._pending_dir = None
            self.window_s = desired
            self.retunes += 1
            return ("retune", rec)

    def snapshot(self) -> dict[str, Any]:
        """Consistent point-in-time state for ``/varz``."""
        with self._lock:
            return {"window_s": self.window_s,
                    "observations": len(self._arrivals),
                    "evals": self.evals,
                    "retunes": self.retunes,
                    "pending_dir": self._pending_dir,
                    "hysteresis": self.hysteresis,
                    "last": dict(self.last)}
