"""Always-on output verification: sortedness + multiset fingerprint.

The reference's only correctness signal is the (n/2)-th-element probe —
a single value that silent truncation, duplication or corruption can
easily leave intact.  Here every ``sort()`` proves its own result:

1. **On-device sortedness**: one fused check that the result words are
   lexicographically non-decreasing (contiguous layouts check the whole
   padded array — pads are the maximum key, so they extend the order;
   ragged layouts check within-shard adjacency plus a lex-cummax chain
   across shard boundaries that is robust to empty shards).
2. **Multiset fingerprint**: per encoded word, the XOR and the wrapping
   uint32 SUM over the *valid* keys, plus the exact count.  The input
   side is folded where the keys are first touched — chunk-by-chunk
   during streamed ingest, during the host encode otherwise, or by one
   tiny on-device reduction for device-resident input — so no extra
   pass over the data ever happens.  The output side is computed by the
   same reduction over the result and compared host-side.  Truncation
   moves the count, duplication moves the sum, corruption moves the
   XOR: each of the reference's silent failure classes trips at least
   one component.

Cost: the fingerprint is a pair of O(n) elementwise reductions fused
into one small program — measured well under the 5%-of-sort-wall budget
(bench.py records ``verify_overhead_s`` per run).  ``SORT_VERIFY=0``
disables it (benchmark A/B), but the default is ON: a production sorter
that cannot prove its result is the reference's failure mode with extra
steps.

Why not compare against ``np.sort``?  That is O(n log n) host work per
run — the verifier is O(n) device work, and the fingerprint equality +
sortedness of a multiset TOGETHER imply the result *is* the sorted
input (sortedness fixes the permutation; the fingerprint ties the
multiset with collision probability ~2^-64 per word against random
corruption).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache
from typing import TYPE_CHECKING, Callable

import numpy as np

if TYPE_CHECKING:
    import jax

    from mpitest_tpu.models.api import DistributedSortResult

_U32 = 0xFFFFFFFF


@dataclass(frozen=True)
class Fingerprint:
    """Order-independent digest of a key-word multiset."""

    count: int
    xors: tuple            # per word, uint32
    sums: tuple            # per word, uint32 (wrapping)

    def combine(self, other: "Fingerprint") -> "Fingerprint":
        return Fingerprint(
            self.count + other.count,
            tuple((a ^ b) & _U32 for a, b in zip(self.xors, other.xors)),
            tuple((a + b) & _U32 for a, b in zip(self.sums, other.sums)),
        )

    @staticmethod
    def empty(n_words: int) -> "Fingerprint":
        return Fingerprint(0, (0,) * n_words, (0,) * n_words)


def fingerprint_host(words: "tuple[np.ndarray, ...]") -> Fingerprint:
    """Fold host uint32 word arrays (one numpy pass, memory-bound)."""
    words = tuple(np.asarray(w, dtype=np.uint32) for w in words)
    return Fingerprint(
        int(words[0].size),
        tuple(int(np.bitwise_xor.reduce(w)) if w.size else 0 for w in words),
        tuple(int(w.sum(dtype=np.uint64)) & _U32 for w in words),
    )


# ---------------------------------------------------------------- records

#: Per-word-index odd multipliers for the record binding word — each is
#: a bijection on uint32 (odd ⇒ invertible mod 2^32), distinct per word
#: position so transposing two words of one record moves the mix.
def _mix_mult(i: int) -> np.uint32:
    return np.uint32((0x9E3779B1 * (2 * i + 3)) & _U32 | 1)


def record_mix(words: "tuple[np.ndarray, ...]") -> np.ndarray:
    """Per-record binding word over a record's key+payload words: the
    XOR of each word scaled by a position-distinct odd constant.  The
    per-word XOR/sum folds alone cannot see a *pairing* error — a sort
    that permutes keys correctly but gathers the wrong payload lanes
    preserves both multisets individually — while the mix multiset
    moves unless every (key, payload) pair survives intact."""
    mix = np.zeros(words[0].shape, np.uint32)
    for i, w in enumerate(words):
        mix ^= np.asarray(w, np.uint32) * _mix_mult(i)
    return mix


def fingerprint_records(key_words: "tuple[np.ndarray, ...]",
                        payload_words: "tuple[np.ndarray, ...]",
                        ) -> Fingerprint:
    """Multiset fingerprint of key+payload records: the ordinary
    per-word fold over every key AND payload word, plus one extra
    folded word — :func:`record_mix` — that binds each key to its own
    payload.  Input side folds at pack/spill time, output side after
    the permuted gather; equality (with sorted keys) means the output
    is the key-sorted permutation of exactly the input records."""
    words = tuple(key_words) + tuple(payload_words)
    return fingerprint_host(words + (record_mix(words),))


# ------------------------------------------------------------------ device

def _xor_reduce_1d(w: "jax.Array") -> "jax.Array":
    """XOR-reduce a 1-D uint32 array with a trace-time halving fold —
    XLA's SPMD partitioner only understands the standard reduction
    kinds (a custom xor ``lax.reduce`` is UNIMPLEMENTED on sharded
    operands), so the fold uses nothing but slices and elementwise xor.
    O(n) total work, O(log n) ops."""
    import jax.numpy as jnp

    if w.shape[0] == 0:
        return jnp.uint32(0)
    while w.shape[0] > 1:
        n = w.shape[0]
        tail = w[n - 1:] if n % 2 else None
        half = (n - (n % 2)) // 2
        w = w[:half] ^ w[half:half * 2]
        if tail is not None:
            w = jnp.concatenate([w, tail]) if half else tail
    return w[0]


@lru_cache(maxsize=64)
def _compile_contig(n_words: int, n_valid: int, total: int,
                    check_sorted: bool) -> "Callable[..., object]":
    """Fingerprint (+ optional sortedness) of a contiguous layout: real
    keys occupy [0, n_valid), pads (max key / sentinel) the tail.  The
    valid-region reduction is pad-region subtraction — two static
    slices, no index arrays, so there is nothing to overflow at any
    scale (the int32-iota hazard of ADVICE r3 #1 never arises)."""
    import jax
    import jax.numpy as jnp

    def f(*words):
        xors, sums = [], []
        for w in words:
            pad = w[n_valid:total]
            xors.append(_xor_reduce_1d(w) ^ _xor_reduce_1d(pad))
            sums.append(jnp.sum(w, dtype=jnp.uint32)
                        - jnp.sum(pad, dtype=jnp.uint32))
        if not check_sorted:
            return jnp.ones((), bool), tuple(xors), tuple(sums)
        # lexicographic adjacency over the full array (pads = max extend
        # the order, so they never mask a violation among real keys):
        # pair ok iff the first differing word (msw first) increases,
        # or all words tie.
        lt = jnp.zeros((max(total - 1, 0),), bool)
        eq = jnp.ones_like(lt)
        for w in words:
            a, b = w[:-1], w[1:]
            lt = lt | (eq & (a < b))
            eq = eq & (a == b)
        ok = jnp.all(lt | eq)
        return ok, tuple(xors), tuple(sums)

    return jax.jit(f)


@lru_cache(maxsize=64)
def _compile_ragged(n_words: int, n_valid: int, slots: int,
                    n_ranks: int) -> "Callable[..., object]":
    """Fingerprint + sortedness of the ragged (sample) layout: shard r
    owns slots [r·S, (r+1)·S), of which the first counts[r] are valid,
    sentinel fill sorted to the shard tail.  Valid lanes below global
    position ``n_valid`` (counts-exclusive-scan order) are fingerprinted
    — that excludes exactly the pad copies, which sort to the global
    tail.  Returns (ok, fp_count, xors, sums)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from mpitest_tpu.ops import kernels

    total = n_ranks * slots

    def lex_lt(a, b):
        lt = jnp.zeros(a[0].shape, bool)
        eq = jnp.ones(a[0].shape, bool)
        for aw, bw in zip(a, b):
            lt = lt | (eq & (aw < bw))
            eq = eq & (aw == bw)
        return lt

    def f(counts, *words):
        counts = counts.astype(jnp.int32)
        starts = lax.iota(jnp.int32, n_ranks) * slots
        # per-lane shard metadata, gather-free (kernels.piecewise_fill)
        cnt_at = kernels.piecewise_fill(starts, counts, total)
        base = jnp.cumsum(counts) - counts           # exclusive scan
        base_at = kernels.piecewise_fill(starts, base, total)
        start_at = kernels.piecewise_fill(starts, starts, total)
        pos = lax.iota(jnp.int32, total) - start_at  # slot within shard
        gpos = base_at + pos                         # global sorted position
        valid = (pos < cnt_at) & (gpos < n_valid)

        xors, sums = [], []
        for w in words:
            wm = jnp.where(valid, w, jnp.uint32(0))
            xors.append(_xor_reduce_1d(wm))
            sums.append(jnp.sum(wm, dtype=jnp.uint32))
        n_in = jnp.sum(valid.astype(jnp.int32))

        # within-shard adjacency (the sentinel tail is all-ones = max,
        # so whole-buffer adjacency holds for a correct shard)
        lt = jnp.zeros((max(total - 1, 0),), bool)
        eq = jnp.ones_like(lt)
        for w in words:
            a, b = w[:-1], w[1:]
            lt = lt | (eq & (a < b))
            eq = eq & (a == b)
        within = jnp.all(lt | eq | (pos[1:] == 0))   # skip shard seams

        # cross-shard: running lex-max of last-valid keys must not
        # exceed the next nonempty shard's first key (empty shards are
        # skipped by giving them MIN last / MAX first).
        first = tuple(lax.slice(w, (0,), ((n_ranks - 1) * slots + 1,),
                                (slots,)) for w in words)
        last_idx = starts + jnp.maximum(counts - 1, 0)
        last = tuple(jnp.take(w, last_idx) for w in words)
        empty = counts == 0
        first = tuple(jnp.where(empty, jnp.uint32(_U32), fw) for fw in first)
        last = tuple(jnp.where(empty, jnp.uint32(0), lw) for lw in last)

        def lex_max(a, b):
            keep_b = lex_lt(a, b)
            return tuple(jnp.where(keep_b, bw, aw) for aw, bw in zip(a, b))

        run = lax.associative_scan(lex_max, last)
        prev = tuple(r[:-1] for r in run)
        nxt = tuple(fw[1:] for fw in first)
        cross = jnp.all(~lex_lt(nxt, prev) | empty[1:])
        return within & cross, n_in, tuple(xors), tuple(sums)

    return jax.jit(f)


@lru_cache(maxsize=16)
def _compile_encode_fp(dtype_name: str) -> "Callable[..., object]":
    """Fused device-side encode + fingerprint for raw (unencoded)
    device-resident input — the single-device local paths, whose sort
    programs fuse their own encode and never expose the words."""
    import jax
    import jax.numpy as jnp

    from mpitest_tpu.ops.keys import codec_for

    codec = codec_for(np.dtype(dtype_name))

    def f(x):
        words = codec.encode_jax(x)
        xors = tuple(_xor_reduce_1d(w) for w in words)
        sums = tuple(jnp.sum(w, dtype=jnp.uint32) for w in words)
        return xors, sums

    return jax.jit(f)


def fingerprint_device_input(x: "jax.Array",
                             dtype: "np.dtype | str") -> Fingerprint:
    """Fingerprint of raw device-resident keys (encode fused in)."""
    xors, sums = _compile_encode_fp(np.dtype(dtype).name)(x)
    return Fingerprint(int(x.size),
                       tuple(int(v) for v in xors),
                       tuple(int(s) for s in sums))


def fingerprint_device(words: "tuple[jax.Array, ...]",
                       n_valid: int) -> Fingerprint:
    """Input-side device fingerprint over a contiguous padded layout
    (one tiny fused reduction, one scalar sync)."""
    n_words = len(words)
    total = int(words[0].shape[0])
    _, xors, sums = _compile_contig(n_words, n_valid, total, False)(*words)
    return Fingerprint(n_valid,
                       tuple(int(x) for x in xors),
                       tuple(int(s) for s in sums))


def verify_result(res: "DistributedSortResult",
                  input_fp: Fingerprint | None) -> tuple[bool, bool]:
    """Verify a DistributedSortResult on device: returns
    ``(sorted_ok, fp_ok)``.  ``fp_ok`` is True when no input fingerprint
    is available (nothing to compare — sortedness still gates)."""
    n_words = len(res.words)
    if res.counts is None:
        total = int(res.words[0].shape[0])
        ok, xors, sums = _compile_contig(
            n_words, min(res.n_valid, total), total, True)(*res.words)
        out_fp = Fingerprint(res.n_valid,
                             tuple(int(x) for x in xors),
                             tuple(int(s) for s in sums))
    else:
        n_ranks = len(res.counts)
        ok, n_in, xors, sums = _compile_ragged(
            n_words, res.n_valid, res.shard_slots, n_ranks)(
            np.asarray(res.counts, np.int32), *res.words)
        out_fp = Fingerprint(int(n_in),
                             tuple(int(x) for x in xors),
                             tuple(int(s) for s in sums))
    fp_ok = input_fp is None or out_fp == input_fp
    return bool(ok), fp_ok
