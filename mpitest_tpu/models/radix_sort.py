"""Distributed LSD radix sort — device-resident, root-free, load-balanced.

The reference (``mpi_radix_sort.c:60-205``) runs, per digit: root scatters
the whole array, every rank buckets by digit value, buckets travel to the
rank *owning that digit* (rank = digit, radix = P), then everything gathers
back to root — O(N) bytes through rank 0 every pass, and digit ownership
means skewed data piles onto one rank.

The TPU design is different in three load-bearing ways:

1. **Keys never leave the mesh.**  The array stays sharded [P, n] across
   all passes; only 256-entry histograms are globally replicated
   (``all_gather``).  This removes the root bandwidth bottleneck
   (SURVEY.md §5 "long-context" row).

2. **Destination = global sorted position, not digit owner.**  Each pass
   computes, for every key, its exact global index in the digit-stable
   order:

       dest(key i, digit d) = digit_base[d] + rank_base[r, d] + occ_i

   where ``digit_base`` is the exclusive scan of global digit totals,
   ``rank_base`` the exclusive scan over ranks (the MPI_Exscan analogue),
   and ``occ_i`` the key's stable occurrence number locally.  Keys then
   move to ``dest // n`` — so every device ends every pass with *exactly*
   ``n`` keys, regardless of skew.  (The reference's per-pass root
   round-trip is what re-balances its shards; here balance is intrinsic.)

3. **8-bit digits, integer math.**  Digit width decouples from mesh size
   (the reference couples radix to P, ``mpi_radix_sort.c:64``) and digits
   are shift/mask, not ``pow()`` (``mpi_radix_sort.c:54-58``).

**One sort per pass.**  A naive receiver re-sorts the [P, cap] exchange
buffer by digit to merge it (a second full comparison sort every pass —
round-1 design, flagged by its review).  Here the receiver instead
*computes* each incoming lane's exact slot from information it already
has replicated — sender s's segment start toward me and the (base − lo)
step function of s's digit runs, all derived from the H matrix (see
:func:`_lane_slots`; everything is K-element scatters, row cumsums and
``searchsorted``, no per-element gathers) — and the next pass's single
``lax.sort`` keyed on ``(next_digit, slot)`` performs the pending merge
and the new digit grouping in one fused pass.  The pending merge of the
*last* pass is materialized by one 1-key sort on ``slot``.

Stability across ranks matches the reference's in-rank-order Recv loop
(``mpi_radix_sort.c:168-173``): ``slot`` IS the exact global position,
so output is bit-identical run to run — arrival order never matters.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

import jax
import jax.numpy as jnp
from jax import lax

from mpitest_tpu.ops import kernels, radix_pallas
from mpitest_tpu.parallel import collectives as coll
from mpitest_tpu.parallel.mesh import AXIS
from mpitest_tpu.utils import spans

if TYPE_CHECKING:
    import contextlib

Words = tuple[jax.Array, ...]


def _pass_span(
    k: int, w_idx: int, shift: int, digit_bits: int, n: int, cap: int,
) -> "contextlib.AbstractContextManager[spans.Span | None]":
    """Trace-time span for one radix pass (utils/spans.py granularity
    contract): the collectives traced inside the pass body nest under
    it, so the SORT_TRACE stream shows pass → {all_gather, exchange}
    structure.  ``trace_time`` marks the dt as host tracing wall, not
    device execution (the fused program is one dispatch)."""
    return spans.maybe_span("radix_pass", pass_index=k, word=w_idx,
                            shift=shift, digit_bits=digit_bits, n=n,
                            cap=cap, trace_time=True)


def _lane_slots(recv_cnt: jax.Array, H: jax.Array, digit_base: jax.Array,
                rank_base: jax.Array, n: int, cap: int,
                axis: str) -> jax.Array:
    """Local output slot of every received lane, from replicated state.

    Lane (s, c) of the exchange buffer holds element ``j = j0[s] + c`` of
    sender s's digit-sorted shard, where ``j0[s]`` is the start of s's
    segment toward me.  Its pass destination is

        dest = base[s, d] + (j - lo[s, d]),   d = digit of the key,

    with ``base[s, d] = digit_base[d] + rank_base[s, d]`` (s's global run
    start for digit d) and ``lo[s, d]`` the run start *within* s's shard
    — both functions of the replicated H matrix, so nothing extra rides
    the wire.  Since lanes within a row arrive digit-sorted, the gather
    ``(base - lo)[s, d(c)]`` is a per-row step function whose run
    boundaries in lane space are ``lo[s, ·] - j0[s]`` — the digit values
    themselves are never touched: K-element scatter + cumsum
    (:func:`kernels.piecewise_fill`), never a per-element gather
    (10-40x a sort's cost on v5e; see ops/kernels.py).

    Returns int32 [P, cap]: local slot in [0, n) for valid lanes, ``n``
    for invalid ones.  Valid slots tile [0, n) exactly once — dest
    partitions the global key space and my block receives exactly n.
    """
    me = lax.axis_index(axis)
    n_ranks = H.shape[0]
    base = digit_base[None, :] + rank_base          # [P, bins]
    lo = coll.exclusive_cumsum(H, axis=1)           # [P, bins]
    # j0[s] = #{keys of s with dest < me*n} = sum_d clip(me*n - base, 0, H)
    j0 = jnp.clip(me * n - base, 0, H).sum(axis=1).astype(jnp.int32)  # [P]

    # Per-row step function of (base - lo) over the lane axis: run of
    # digit d occupies lanes [lo[s,d] - j0[s], lo[s,d+1] - j0[s]).
    starts = jnp.clip(lo - j0[:, None], 0, cap).astype(jnp.int32)     # [P, bins]
    values = (base - lo).astype(jnp.int32)                            # [P, bins]
    fill = jax.vmap(kernels.piecewise_fill, in_axes=(0, 0, None))(
        starts, values, cap
    )                                                                 # [P, cap]

    c = lax.iota(jnp.int32, cap)[None, :]
    slot = fill + j0[:, None] + c - me * n
    valid = c < recv_cnt[:, None]
    return jnp.where(valid, slot, n).astype(jnp.int32)


def _send_segments(sorted_dest: jax.Array, n: int,
                   n_ranks: int) -> tuple[jax.Array, jax.Array]:
    """Contiguous per-destination-device segments of the dest-monotone
    shard (dest strictly increasing ⇒ one segment per device)."""
    bounds = lax.iota(jnp.int32, n_ranks) * n
    send_start = jnp.searchsorted(sorted_dest, bounds, side="left").astype(jnp.int32)
    seg_end = jnp.concatenate([send_start[1:], jnp.asarray([n], jnp.int32)])
    return send_start, seg_end - send_start


def radix_probe_spmd(
    words: Words,
    digit_bits: int,
    n_ranks: int,
    axis: str = AXIS,
) -> jax.Array:
    """Capacity-negotiation count probe (ISSUE 7): the EXACT per-peer
    send counts of the first radix exchange, with zero key movement.

    Pass 1 always works on the least-significant digit of the
    least-significant word (the plan loop below), and its destination is
    the exact global digit-stable position — fully determined by the
    ``[P, bins]`` histogram matrix ``H``.  So one local digit histogram
    plus the same tiny histogram ``all_gather`` the real pass pays
    anyway yields, via :func:`collectives.block_send_counts`, the
    precise capacity the ``[P, cap]`` exchange buffer needs — before any
    buffer is allocated or any worst-case cap guessed.  (Later passes
    depend on the post-exchange arrangement; the supervisor's regrow
    loop remains the backstop for them.)

    The histogram rides a sort + binary search rather than a scatter-add
    (``kernels.histogram_sorted`` — scatter lowers to serialized updates
    on TPU, ~40x slower at scale).

    Returns int32[P, P], replicated: row r = counts rank r sends to each
    peer (self included — the self block occupies exchange lanes too).
    """
    n = words[0].shape[0]
    n_bins = 1 << digit_bits
    with spans.maybe_span("negotiate_probe", algorithm="radix",
                          ranks=n_ranks, n=n, trace_time=True):
        d = kernels.digit_at(words[-1], 0, digit_bits)
        h, _ = kernels.histogram_sorted(jnp.sort(d), n_bins)
        H = coll.all_gather(h, axis)                  # [P, bins]
        mine = coll.block_send_counts(H, n, axis)     # [P]
        return coll.all_gather(mine, axis)            # [P, P]


def radix_sort_spmd(
    words: Words,
    n_words: int,
    digit_bits: int,
    n_ranks: int,
    cap: int,
    passes: int | None = None,
    axis: str = AXIS,
    pack: str = "xla",
    exchange_engine: str = "lax",
    local_engine: str = "lax",
) -> tuple[Words, jax.Array]:
    """Full multi-pass radix sort of the shard. SPMD; call under shard_map.

    ``passes`` limits the number of digit passes (host may have computed
    that high words are all-equal — the reference's ``number_digits``
    optimization, ``mpi_radix_sort.c:100``, done right).  Passes run from
    the least-significant digit of the least-significant word upward.

    ``exchange_engine`` (ISSUE 13) selects the per-pass exchange path:

    * ``"lax"`` — the original pass: after the fused sort, the n-element
      ``dest`` plane materializes (piecewise_fill + iota), segments come
      from ``searchsorted(dest)``, and the pack/transport ride
      :func:`collectives.ragged_all_to_all` with the ``pack`` impl.
    * ``"pallas"`` / ``"pallas_interpret"`` — the fused pass: segments
      come straight from the histogram's clip-arithmetic
      (:func:`collectives.block_send_segments` — histogram → exclusive
      scan → segments is [bins]-sized math, the dest plane and its two
      extra n-element HBM round-trips never exist), all key words pack
      in ONE fused kernel sweep, the transport is the remote-DMA kernel
      (``ops/exchange.py``), and the **overlap loop** double-buffers:
      pass k+1's lane-slot (scatter) plane is computed via the
      ``pre_exchange`` hook while pass k's bucket sends are still in
      flight — it depends only on the tiny count exchange + replicated
      H state, never on the payload DMAs.  Both engines are
      bit-identical by construction (same sorts, same segment values,
      same fill contract); the parity gates pin it.

    ``local_engine`` (ISSUE 17) selects the FIRST pass's stable digit
    sort: ``"radix_pallas"`` / ``"radix_pallas_interpret"`` replace the
    ``lax.sort`` counting sort with the fused per-pass kernel
    (``ops/radix_pallas.py``) carrying the key words as payload planes
    — bit-identical, both are stable sorts by the same digit.  Later
    passes keep ``lax.sort``: their (digit, slot) key merges the
    exchange buffer, which is a scatter rather than a sort, and moving
    it into the kernel is flagged TPU follow-up work.

    Returns ``(sorted_words, max_send_cnt_over_passes)`` — the second value
    > cap means an exchange overflowed and the host must retry with at
    least that cap (an overflowed pass corrupts later passes, so the
    reported value is a lower bound; the host loop grows the cap
    monotonically until no pass overflows).
    """
    from mpitest_tpu.ops import exchange as xeng

    n = words[0].shape[0]
    n_bins = 1 << digit_bits
    my = lax.axis_index(axis)
    per_word = (32 + digit_bits - 1) // digit_bits
    total = per_word * n_words if passes is None else passes
    max_cnt = jnp.zeros((), jnp.int32)
    fused = xeng.is_pallas(exchange_engine)

    plan = []  # (word_idx, shift), lsw first
    for w_idx in range(n_words - 1, -1, -1):
        for p in range(per_word):
            if len(plan) < total:
                plan.append((w_idx, p * digit_bits))

    if not plan:
        return words, max_cnt

    # recv-buffer state between exchanges; None before the first pass.
    recv: Words | None = None
    recv_cnt = None
    prev = None  # lax engine: (H, digit_base, rank_base) of the pending exchange
    slot_carry = None  # pallas engine: the overlapped lane-slot plane

    for k, (w_idx, shift) in enumerate(plan):
        with _pass_span(k + 1, w_idx, shift, digit_bits, n, cap):
            if recv is None:
                # First pass: the flat shard is trivially "merged"; one
                # stable 1-key sort groups by digit (stability = position
                # order, exactly the (digit, slot) key of later passes).
                d = kernels.digit_at(words[w_idx], shift, digit_bits)
                if local_engine.startswith("radix_pallas"):
                    # Fused local engine: the stable 1-key digit sort IS
                    # a counting sort — one kernel launch, the words
                    # ride as payload planes (diff 0 = never a sort key)
                    fps = radix_pallas.fused_radix_sort(
                        (d.astype(jnp.uint32),) + tuple(words),
                        diffs=(n_bins - 1,) + (0,) * n_words,
                        interpret=(
                            local_engine == "radix_pallas_interpret"))
                    sd = fps[0].astype(jnp.int32)
                    sorted_words = tuple(fps[1:])
                else:
                    ops = lax.sort([d] + list(words), num_keys=1,
                                   is_stable=True)
                    sd, sorted_words = ops[0], tuple(ops[1:])
            else:
                # Fused pass: merge the pending exchange buffer AND group by
                # the new digit with ONE sort keyed on (digit, slot) — the
                # pair is unique per valid lane, so no stability needed.
                # Under the pallas engine the slot plane was already
                # computed while the previous exchange's DMAs were in
                # flight (the pre_exchange hook below).
                slot = slot_carry if fused else \
                    _lane_slots(recv_cnt, *prev, n, cap, axis)
                d = kernels.digit_at(recv[w_idx], shift, digit_bits)
                c = lax.iota(jnp.int32, cap)[None, :]
                d = jnp.where(c < recv_cnt[:, None], d, n_bins)
                ops = lax.sort(
                    [d.reshape(-1), slot.reshape(-1)] + [r.reshape(-1) for r in recv],
                    num_keys=2, is_stable=False,
                )
                # Valid lanes total exactly n and sort to the front (invalid
                # carry the n_bins sentinel digit).
                sd = ops[0][:n]
                sorted_words = tuple(o[:n] for o in ops[2:])

            # Histogram + first-occurrence offsets from the sorted digits.
            h, lo_local = kernels.histogram_sorted(sd, n_bins)
            H, tot, rank_base = coll.exscan_counts(h, axis)
            digit_base = coll.exclusive_cumsum(tot)
            base = digit_base + rank_base[my]      # [bins] my global run starts

            if fused:
                # Fused pass (ISSUE 13): segments from [bins]-sized clip
                # arithmetic — no n-element dest plane — and the next
                # pass's scatter half precomputed during the DMA window.
                send_start, send_cnt = coll.block_send_segments(
                    h, base, n, n_ranks)

                def _pre(rc: jax.Array, H: jax.Array = H,
                         db: jax.Array = digit_base,
                         rb: jax.Array = rank_base) -> jax.Array:
                    return _lane_slots(rc, H, db, rb, n, cap, axis)

                recv, recv_cnt, mc, slot_carry = coll.ragged_all_to_all(
                    sorted_words, send_start, send_cnt, cap, n_ranks,
                    axis, pack=pack, engine=exchange_engine,
                    pre_exchange=_pre,
                )
            else:
                # dest[j] = base[sd[j]] + (j - lo[sd[j]]) — gather-free
                # step fn.
                dest = kernels.piecewise_fill(
                    lo_local, base - lo_local, n) + lax.iota(jnp.int32, n)
                send_start, send_cnt = _send_segments(dest, n, n_ranks)

                recv, recv_cnt, mc = coll.ragged_all_to_all(
                    sorted_words, send_start, send_cnt, cap, n_ranks,
                    axis, pack=pack,
                )
                prev = (H, digit_base, rank_base)
            max_cnt = jnp.maximum(max_cnt, mc)

    # Materialize the last pass's pending merge: one 1-key sort on slot.
    slot = slot_carry if fused else _lane_slots(recv_cnt, *prev, n, cap, axis)
    flat = lax.sort(
        [slot.reshape(-1)] + [r.reshape(-1) for r in recv],
        num_keys=1, is_stable=False,
    )
    out_words = tuple(o[:n] for o in flat[1:])
    return out_words, max_cnt
