"""Distributed LSD radix sort — device-resident, root-free, load-balanced.

The reference (``mpi_radix_sort.c:60-205``) runs, per digit: root scatters
the whole array, every rank buckets by digit value, buckets travel to the
rank *owning that digit* (rank = digit, radix = P), then everything gathers
back to root — O(N) bytes through rank 0 every pass, and digit ownership
means skewed data piles onto one rank.

The TPU design is different in three load-bearing ways:

1. **Keys never leave the mesh.**  The array stays sharded [P, n] across
   all passes; only 256-entry histograms are globally replicated
   (``all_gather``).  This removes the root bandwidth bottleneck
   (SURVEY.md §5 "long-context" row).

2. **Destination = global sorted position, not digit owner.**  Each pass
   computes, for every key, its exact global index in the digit-stable
   order:

       dest(key i, digit d) = digit_base[d] + rank_base[r, d] + occ_i

   where ``digit_base`` is the exclusive scan of global digit totals,
   ``rank_base`` the exclusive scan over ranks (the MPI_Exscan analogue),
   and ``occ_i`` the key's stable occurrence number locally.  Keys then
   move to ``dest // n`` — so every device ends every pass with *exactly*
   ``n`` keys, regardless of skew.  (The reference's per-pass root
   round-trip is what re-balances its shards; here balance is intrinsic.)

3. **8-bit digits, integer math.**  Digit width decouples from mesh size
   (the reference couples radix to P, ``mpi_radix_sort.c:64``) and digits
   are shift/mask, not ``pow()`` (``mpi_radix_sort.c:54-58``).

Monotonicity property used by the exchange: after the local stable sort by
digit, ``dest`` is strictly increasing, so each destination device's keys
form one contiguous segment — exactly what
:func:`~mpitest_tpu.parallel.collectives.ragged_all_to_all` ships.

Stability across ranks matches the reference's in-rank-order Recv loop
(``mpi_radix_sort.c:168-173``); the scatter at the receiver is
deterministic (every key lands at its computed offset), so output is
bit-identical run to run — arrival order never matters.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from mpitest_tpu.ops import kernels
from mpitest_tpu.parallel import collectives as coll
from mpitest_tpu.parallel.mesh import AXIS

Words = tuple[jax.Array, ...]


def _one_pass(words: Words, word_idx: int, shift: int, digit_bits: int,
              n_ranks: int, cap: int, axis: str,
              pack: str = "xla") -> tuple[Words, jax.Array]:
    """One LSD pass, built only from TPU-fast primitives: fused multi-
    operand ``lax.sort``, ``searchsorted`` over sorted data, cumsum, and
    K-element scatters (K = bins or ranks).  Per-element gathers/scatters
    — the straightforward translation of the reference's bucket loops —
    measured 10-40× slower than a sort at 2^26 on v5e, so none appear on
    the per-key path."""
    n = words[0].shape[0]
    n_bins = 1 << digit_bits
    my = lax.axis_index(axis)

    # Group keys by digit: ONE fused stable sort carries all key words.
    d = kernels.digit_at(words[word_idx], shift, digit_bits)
    ops = lax.sort([d] + list(words), num_keys=1, is_stable=True)
    sd, sorted_words = ops[0], tuple(ops[1:])

    # Histogram + first-occurrence offsets from the sorted digits (no scatter).
    h, lo = kernels.histogram_sorted(sd, n_bins)

    _, tot, rank_base = coll.exscan_counts(h, axis)
    digit_base = coll.exclusive_cumsum(tot)
    base = digit_base + rank_base[my]          # [bins] my global run starts

    # dest[j] = base[sd[j]] + (j - lo[sd[j]]): the step function
    # (base - lo)[sd[j]] materialized gather-free, plus iota.
    dest = kernels.piecewise_fill(lo, base - lo, n) + lax.iota(jnp.int32, n)

    bounds = lax.iota(jnp.int32, n_ranks) * n
    send_start = jnp.searchsorted(dest, bounds, side="left").astype(jnp.int32)
    seg_end = jnp.concatenate([send_start[1:], jnp.asarray([n], jnp.int32)])
    send_cnt = seg_end - send_start

    # Keys only on the wire — the receiver recomputes digits from the key
    # words, so no index payload rides the exchange.
    recv, recv_cnt, max_cnt = coll.ragged_all_to_all(
        sorted_words, send_start, send_cnt, cap, n_ranks, axis, pack=pack
    )

    # Receiver-side placement is a P-way merge by (digit, sender, arrival):
    # flatten sender-major and stable-sort by digit.  Globally, my n slots
    # are filled exactly once (dest partitions [0, P·n)), so the valid
    # lanes sort to a length-n prefix; invalid lanes get digit = n_bins.
    # This replaces the reference's rank-ordered Recv loop
    # (mpi_radix_sort.c:168-173) and needs no per-element scatter.
    rd = kernels.digit_at(recv[word_idx], shift, digit_bits)
    c = lax.iota(jnp.int32, cap)
    valid = c[None, :] < recv_cnt[:, None]                           # [P, cap]
    rd = jnp.where(valid, rd, n_bins)
    flat = lax.sort(
        [rd.reshape(-1)] + [r.reshape(-1) for r in recv],
        num_keys=1, is_stable=True,
    )
    out_words = tuple(o[:n] for o in flat[1:])
    return out_words, max_cnt


def radix_sort_spmd(
    words: Words,
    n_words: int,
    digit_bits: int,
    n_ranks: int,
    cap: int,
    passes: int | None = None,
    axis: str = AXIS,
    pack: str = "xla",
) -> tuple[Words, jax.Array]:
    """Full multi-pass radix sort of the shard. SPMD; call under shard_map.

    ``passes`` limits the number of digit passes (host may have computed
    that high words are all-equal — the reference's ``number_digits``
    optimization, ``mpi_radix_sort.c:100``, done right).  Passes run from
    the least-significant digit of the least-significant word upward.

    Returns ``(sorted_words, max_send_cnt_over_passes)`` — the second value
    > cap means an exchange overflowed and the host must retry with at
    least that cap (an overflowed pass corrupts later passes, so the
    reported value is a lower bound; the host loop grows the cap
    monotonically until no pass overflows).
    """
    per_word = (32 + digit_bits - 1) // digit_bits
    total = per_word * n_words if passes is None else passes
    max_cnt = jnp.zeros((), jnp.int32)
    done = 0
    for w_idx in range(n_words - 1, -1, -1):          # lsw first
        for p in range(per_word):
            if done >= total:
                break
            words, mc = _one_pass(
                words, w_idx, p * digit_bits, digit_bits, n_ranks, cap, axis,
                pack=pack,
            )
            max_cnt = jnp.maximum(max_cnt, mc)
            done += 1
    return words, max_cnt
