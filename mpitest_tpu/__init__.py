"""mpitest_tpu — a TPU-native distributed sorting framework.

A ground-up re-design of the capabilities of the reference MPI teaching repo
(``acgrid/mpi-test``: revised sample sort + LSD radix sort of integer keys,
SPMD over P workers) for TPU hardware:

* keys live **device-resident and sharded** over a 1-D ``jax.sharding.Mesh``
  (the reference round-trips through rank 0 every radix pass,
  ``mpi_radix_sort.c:139,192`` — the TPU design removes the root entirely);
* every communication step is an XLA collective over ICI
  (``all_gather`` / ``psum`` / padded ``all_to_all``) issued from inside a
  single ``jit``-compiled ``shard_map`` program per phase;
* local kernels are XLA ops (``lax.sort``, scatter-add histograms), with
  Pallas escalation hooks where XLA is the bottleneck;
* multi-word key codecs make signed / 64-bit keys *correct* (the reference
  sorts negatives by magnitude, ``mpi_radix_sort.c:50,56``).

Layer map (mirrors SURVEY.md §7):

* :mod:`mpitest_tpu.parallel` — mesh construction + the collective/"comm"
  layer (the Python twin of the native ``comm/comm.h`` shim).
* :mod:`mpitest_tpu.ops` — local kernels and key codecs.
* :mod:`mpitest_tpu.models` — the two distributed sort algorithms
  ("model families"): sample sort and radix sort.
* :mod:`mpitest_tpu.utils` — I/O (reference text format), generators,
  tracing/debug-log contract, metrics.
"""

from mpitest_tpu.models.api import (  # noqa: F401
    DistributedSortResult,
    SortFaultError,
    SortIntegrityError,
    SortRetryExhausted,
    sort,
)
from mpitest_tpu.parallel.mesh import make_mesh  # noqa: F401

__version__ = "0.1.0"

__all__ = ["sort", "DistributedSortResult", "make_mesh",
           "SortFaultError", "SortIntegrityError", "SortRetryExhausted",
           "__version__"]
