"""Admission control: bounded in-flight work with typed backpressure.

A server without admission control has a failure mode worse than
rejection: the queue grows until host memory (or the batch window's
latency SLO) dies.  This module bounds BOTH axes the sort server cares
about — concurrent request count (``SORT_SERVE_MAX_INFLIGHT``) and
total in-flight payload bytes (``SORT_SERVE_MAX_BYTES``) — and turns an
over-limit arrival into a :class:`AdmissionReject` whose ``reason`` is
machine-readable, so clients can tell "back off" (``inflight`` /
``bytes``) from "the server is going away" (``draining``).

The protocol maps a rejection to one typed error response; nothing
about an over-limit request ever reaches the device."""

from __future__ import annotations

import threading


class AdmissionReject(RuntimeError):
    """Typed backpressure rejection.  ``reason`` ∈ {"inflight",
    "bytes", "draining", "breaker"}; the wire protocol forwards it
    verbatim.  ("breaker" is raised by ``ServerCore._admit`` when the
    dispatch watchdog's circuit breaker is open — ISSUE 11 — and is
    tallied here via :meth:`AdmissionControl.note_reject` so the
    rejected count covers breaker-open incidents too.)"""

    def __init__(self, reason: str, detail: str) -> None:
        super().__init__(detail)
        self.reason = reason


class AdmissionControl:
    """Counting admission gate.  ``admit(nbytes)`` either reserves
    capacity or raises :class:`AdmissionReject`; ``release(nbytes)``
    returns it (call exactly once per successful admit — the server's
    request handler does both in one try/finally)."""

    def __init__(self, max_inflight: int, max_bytes: int) -> None:
        self.max_inflight = int(max_inflight)
        self.max_bytes = int(max_bytes)
        self.inflight = 0
        self.inflight_bytes = 0
        self.rejected = 0
        self.admitted = 0
        self.draining = False
        #: observer called with (inflight, inflight_bytes) UNDER the
        #: admission lock on every admit/release — the server points it
        #: at the live in-flight gauges (ISSUE 10).  Publishing inside
        #: the lock means gauge writes are ordered exactly like the
        #: state changes; a read-then-set outside it could leave a
        #: phantom in-flight count exported forever on an idle server.
        self.on_change = None
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)

    def _changed(self) -> None:
        if self.on_change is not None:
            self.on_change(self.inflight, self.inflight_bytes)

    def admit(self, nbytes: int) -> None:
        with self._lock:
            if self.draining:
                self.rejected += 1
                raise AdmissionReject(
                    "draining", "server is draining (SIGTERM received); "
                    "not accepting new work")
            if self.inflight + 1 > self.max_inflight:
                self.rejected += 1
                raise AdmissionReject(
                    "inflight",
                    f"in-flight request limit reached "
                    f"({self.max_inflight}); retry with backoff")
            if self.inflight_bytes + nbytes > self.max_bytes:
                self.rejected += 1
                raise AdmissionReject(
                    "bytes",
                    f"in-flight byte limit reached ({self.max_bytes}); "
                    "retry with backoff")
            self.inflight += 1
            self.inflight_bytes += nbytes
            self.admitted += 1
            self._changed()

    def release(self, nbytes: int) -> None:
        with self._lock:
            self.inflight -= 1
            self.inflight_bytes -= nbytes
            self._changed()
            if self.inflight == 0:
                self._idle.notify_all()

    def note_reject(self) -> None:
        """Count a rejection decided OUTSIDE this gate (the circuit
        breaker's fast path) so ``rejected`` stays the one total the
        driver's exit line and /varz report."""
        with self._lock:
            self.rejected += 1

    def snapshot(self) -> dict:
        """Point-in-time state for the live /varz endpoint (ISSUE 10)."""
        with self._lock:
            return {"inflight": self.inflight,
                    "inflight_bytes": self.inflight_bytes,
                    "max_inflight": self.max_inflight,
                    "max_bytes": self.max_bytes,
                    "admitted": self.admitted,
                    "rejected": self.rejected,
                    "draining": self.draining}

    def start_drain(self) -> None:
        """Flip to draining: every subsequent admit is a typed
        rejection; in-flight work is unaffected."""
        with self._lock:
            self.draining = True

    def wait_idle(self, timeout: float | None = None) -> bool:
        """Block until no requests are in flight (the SIGTERM drain
        barrier).  Returns False on timeout."""
        with self._lock:
            return self._idle.wait_for(lambda: self.inflight == 0,
                                       timeout=timeout)
