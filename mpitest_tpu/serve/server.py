"""The sort server: protocol, orchestration, per-request supervision.

Wire protocol (``sortserve.v1``, one TCP connection may carry many
requests back to back):

* request: one JSON header line (utf-8, ``\\n``-terminated) —
  ``{"v": "sortserve.v1", "dtype": "int32", "n": 4096}`` with optional
  ``"algo"`` (radix | sample; solo dispatches only), ``"trace_id"``
  (1-64 chars of ``[A-Za-z0-9_-]``; minted server-side when absent and
  echoed in the response — the end-to-end request-trace key, ISSUE 10),
  ``"payload_bytes"`` (ISSUE 15: per-record payload width — the keys
  become records; ``n * payload_bytes`` raw payload bytes follow the
  key bytes and come back permuted into key order)
  and ``"faults"`` (a ``SORT_FAULTS`` spec, honored only when the
  server runs with ``SORT_SERVE_ALLOW_FAULTS=1``) — followed by exactly
  ``n * itemsize`` raw little-endian key bytes (then the payload
  section, when declared).  A request whose total bytes exceed
  ``SORT_SERVE_MAX_BYTES`` routes to the out-of-core **spill tier**
  (``SORT_SERVE_SPILL``, ISSUE 15): the bytes stream straight to disk,
  the external sort serves them under ``SORT_MEM_BUDGET``, and the ok
  response carries ``"spilled": true`` (+ the plan digest's
  ``spilled``) instead of the old typed ``bytes`` rejection.
* response: one JSON header line — ``{"ok": true, "n": ..., "batched":
  ..., "bucket": ..., "trace_id": ..., "batch_id": ..., "plan": ...}``
  (``plan`` is the compact decision digest of ISSUE 12 — algo,
  negotiated cap, restage verdict, regret — present when ``SORT_PLAN``
  is on) followed by the sorted key bytes, or ``{"ok": false, "error":
  <code>, "detail": ..., "trace_id": ...}`` with no payload.  Error codes are TYPED and stable: ``bad_request`` (the
  header/payload is malformed), ``backpressure`` (admission bounds hit
  or the circuit breaker is open — retry with backoff), ``draining``
  (SIGTERM received), ``deadline_exceeded`` (the request's optional
  ``deadline_ms`` budget expired before dispatch — the sort was never
  run), ``integrity`` (no path produced a verified result for THIS
  request), ``retries`` (dispatch kept failing past the retry budget),
  ``internal`` (anything else — still one request's problem, never the
  server's).

Request lifecycle bounds (ISSUE 11): the header read is bounded by the
connection idle timeout, payload reads / rejected-payload drains /
response writes by one total ``SORT_SERVE_READ_TIMEOUT_S`` budget
(admission bytes are provably released on every wire exit path), the
dispatch wait by ``SORT_SERVE_COMPLETION_TIMEOUT_S``, and the dispatch
itself by the watchdog (``serve/watchdog.py``): a wedge trips a
circuit breaker — ``/healthz`` 503, fast typed rejections, automatic
half-open probe recovery — instead of silently pinning the server.

Failure semantics: every dispatch runs under the PR 3 robustness layer.
Solo requests go through the supervised ``models.api.sort`` (bounded
retry, degradation ladder, always-on verification); batched requests
are verified PER SEGMENT (``models/segmented.verify_segments``) and a
failing segment is re-run solo under the supervisor while its
batchmates' verified results return normally.  A poisoned request —
injected via ``SORT_FAULTS`` on the server or a per-request ``faults``
spec in test mode — therefore yields a typed per-request error, never
server death and never a batchmate's corruption.

Telemetry: every request records a ``serve.request`` span (n, dtype,
status, batched, bucket) whose duration feeds the report CLI's p50/p99
SLO table; every packed dispatch records ``serve.batch``; every
executor-cache lookup records ``serve.compile_cache``.  All ride the
ordinary ``SORT_TRACE`` stream.
"""

from __future__ import annotations

import errno
import json
import math
import os
import re
import socket
import socketserver
import threading
import time
from typing import TYPE_CHECKING, Any, BinaryIO

import numpy as np

from mpitest_tpu import faults
from mpitest_tpu.models import plan as plan_mod
from mpitest_tpu.models import planner as planner_mod
from mpitest_tpu.models import segmented
from mpitest_tpu.models import supervisor as supervision
from mpitest_tpu.serve.admission import AdmissionControl, AdmissionReject
from mpitest_tpu.serve.batching import ERR_DEADLINE, Batcher, ServeRequest
from mpitest_tpu.serve.executor_cache import ExecutorCache
from mpitest_tpu.serve.telemetry import ProfileHook
from mpitest_tpu.serve.watchdog import CircuitBreaker, DispatchWatchdog
from mpitest_tpu.utils import flight_recorder, knobs
from mpitest_tpu.utils import spans as spanlib
from mpitest_tpu.utils.metrics_live import LiveMetrics, SpanMetricsBridge

if TYPE_CHECKING:
    from jax.sharding import Mesh

    from mpitest_tpu.utils.trace import Tracer

#: Protocol version tag (header "v" of every request and response).
WIRE_SCHEMA = "sortserve.v1"

#: Typed error codes (stable wire vocabulary; see module docstring).
ERR_BAD_REQUEST = "bad_request"
ERR_BACKPRESSURE = "backpressure"
ERR_DRAINING = "draining"
ERR_INTEGRITY = "integrity"
ERR_RETRIES = "retries"
ERR_INTERNAL = "internal"
#: ISSUE 11: the request's deadline_ms expired before dispatch — the
#: sort was never run, the admission bytes were released.  Defined by
#: the dispatch layer (serve/batching.py), re-exported as wire vocab.
ERR_DEADLINE_EXCEEDED = ERR_DEADLINE

#: Sanity cap on a single request's key count (the admission byte bound
#: is the real limit; this just stops a hostile header from asking the
#: server to read exabytes to keep framing).
MAX_REQUEST_KEYS = 1 << 31

#: Sanity cap on the per-record payload width (ISSUE 15): 64 KiB per
#: record is far past any key-attached handle; bigger payloads belong
#: in an object store keyed by a payload-resident id.
MAX_PAYLOAD_WIDTH = 1 << 16

#: Wire-supplied trace ids: short, log/filename-safe tokens.  Anything
#: else is a typed bad_request — trace ids land in span attrs, file
#: names and report output, so the grammar is closed.
_TRACE_ID_RE = re.compile(r"[A-Za-z0-9_\-]{1,64}")


def mint_trace_id() -> str:
    """Server-side trace id for requests that arrived without one (the
    wire/client layer normally mints it — serve/client.py)."""
    return os.urandom(8).hex()


def _maybe_corrupt_packed(reg: "faults.FaultRegistry | None",
                          words: tuple,
                          n_valid: int) -> tuple:
    """Batch-path twin of ``faults.maybe_corrupt_result``: apply the
    ``result_swap`` / ``result_dup`` sites to the packed host words so
    server-level ``SORT_FAULTS`` chaos drills reach the batched
    dispatch too.  The per-segment verifier must then flag (only) the
    touched segments."""
    if reg is None or n_valid < 2:
        return words
    for site in ("result_swap", "result_dup"):
        if not reg.would_fire(site):
            continue
        if not reg.fire(site):
            continue
        out = []
        for w in words:
            h = w.copy()
            if site == "result_swap":
                h[0], h[n_valid - 1] = h[n_valid - 1].copy(), h[0].copy()
            else:
                h[1] = h[0]
            out.append(h)
        return tuple(out)
    return words


class ServerCore:
    """Transport-independent server core: admission → batcher →
    executor cache → supervised dispatch → typed result.  The TCP layer
    (:class:`SortServer`) and the in-process tests both drive this."""

    def __init__(self, mesh: "Mesh | None" = None,
                 tracer: "Tracer | None" = None) -> None:
        from mpitest_tpu.parallel.mesh import make_mesh
        from mpitest_tpu.utils.trace import Tracer as _Tracer

        self.mesh = mesh if mesh is not None else make_mesh()
        self.tracer = tracer or _Tracer()
        trace_path = knobs.get("SORT_TRACE")
        if trace_path and self.tracer.spans.stream_path is None:
            self.tracer.spans.stream_path = trace_path
        self.default_algo = knobs.get("SORT_ALGO")
        self.allow_faults = knobs.get("SORT_SERVE_ALLOW_FAULTS")
        self.batch_keys = knobs.get("SORT_SERVE_BATCH_KEYS")
        window_ms = knobs.get("SORT_SERVE_BATCH_WINDOW_MS")
        # request-lifecycle bounds (ISSUE 11): every wire interaction
        # and every dispatch wait is time-bounded
        self.idle_timeout_s = knobs.get("SORT_SERVE_IDLE_TIMEOUT_S")
        self.read_timeout_s = knobs.get("SORT_SERVE_READ_TIMEOUT_S")
        self.completion_timeout_s = knobs.get(
            "SORT_SERVE_COMPLETION_TIMEOUT_S")
        #: out-of-core spill tier (ISSUE 15): requests larger than the
        #: admission byte bound spill to disk and ride the external
        #: sort instead of a typed 'bytes' rejection.  The tier's
        #: memory budget is SORT_MEM_BUDGET when set, else the
        #: admission bound itself (the byte bound IS the host-memory
        #: statement the operator already made).
        self.spill_enabled = knobs.get("SORT_SERVE_SPILL") != "off"
        self.spill_budget = (knobs.get("SORT_MEM_BUDGET")
                             or knobs.get("SORT_SERVE_MAX_BYTES"))
        self.cache = ExecutorCache(self.tracer.spans)
        self.admission = AdmissionControl(
            knobs.get("SORT_SERVE_MAX_INFLIGHT"),
            knobs.get("SORT_SERVE_MAX_BYTES"))
        #: live metrics (ISSUE 10): the registry the /metrics endpoint
        #: renders.  Span-derived metrics ride the span-close bridge;
        #: only the admission gauges are written directly.
        self.metrics = LiveMetrics()
        self.tracer.spans.observers.append(SpanMetricsBridge(self.metrics))
        #: streaming SLO sentinel (ISSUE 16): a second span-close
        #: observer, appended right after the bridge so its serve.alert
        #: emissions are bridged into sort_alerts_total on the same
        #: flush.  None when SORT_SENTINEL=off; /alerts snapshots it.
        self.sentinel = None
        if knobs.get("SORT_SENTINEL") != "off":
            from mpitest_tpu.serve.sentinel import SortSentinel
            self.sentinel = SortSentinel(
                self.metrics, self.tracer.spans,
                window_s=knobs.get("SORT_SENTINEL_WINDOW_S"),
                burn_rate=knobs.get("SORT_ALERT_BURN_RATE"))
            self.tracer.spans.observers.append(self.sentinel)
        #: on-demand jax.profiler captures around dispatches (ISSUE 10).
        self.profiler = ProfileHook(self.tracer.spans)
        # gauge publication rides the admission lock (see
        # AdmissionControl.on_change) so exported in-flight counts can
        # never be left stale by interleaved handler threads
        self.admission.on_change = self._publish_admission
        self.started = time.time()
        self._batch_seq = 0
        self.batcher = Batcher(self._run_batch, self._run_solo,
                               window_ms / 1e3, self.batch_keys)
        # seed the gauge with the configured window so a scrape can
        # always tell "initial value" from "metric missing" (retunes
        # overwrite it)
        self.metrics.gauge("sort_serve_batch_window_ms").set(window_ms)
        #: serve-side auto-tuning (ISSUE 14): rolling request-mix
        #: observer + two-phase hysteresis re-sizing the batching
        #: window and the prewarm buckets.  `shadow` computes and logs
        #: every recommendation without touching the batcher; `on`
        #: acts.  None when SORT_PLANNER=off — and when the operator
        #: set window 0 (solo dispatch): there is no batching window to
        #: tune, and the tuner's clamp floor (MIN_WINDOW_S) could only
        #: ever override that explicit config, never restore it.
        self.planner_mode = planner_mod.mode()
        self.tuner: "planner_mod.ServeTuner | None" = None
        if self.planner_mode != "off" and window_ms > 0:
            self.tuner = planner_mod.ServeTuner(
                window=knobs.get("SORT_PLANNER_WINDOW"),
                hysteresis=knobs.get("SORT_PLANNER_HYSTERESIS"),
                batch_keys=self.batch_keys,
                initial_window_s=window_ms / 1e3)
        #: circuit breaker + dispatch watchdog (ISSUE 11).  The breaker
        #: is always consulted by admission; the watchdog THREAD only
        #: runs when start_watchdog() is called (the server driver does;
        #: in-process test cores stay thread-clean unless they opt in).
        self.breaker = CircuitBreaker(
            knobs.get("SORT_SERVE_BREAKER_BACKOFF_S"))
        self.watchdog = DispatchWatchdog(
            self, knobs.get("SORT_SERVE_DISPATCH_TIMEOUT_S"),
            self.breaker)
        self.requests_ok = 0
        self.requests_err = 0
        #: guards the two tallies above — _finish runs on concurrent
        #: TCP handler threads, and a bare += loses increments.
        self._tally_lock = threading.Lock()
        #: in-flight dispatched requests by trace_id (ISSUE 11): the
        #: drain-timeout path names exactly who was stuck.
        self._inflight_reqs: dict[str, ServeRequest] = {}
        self._inflight_lock = threading.Lock()

    #: Disk headroom a spill request must fit under: staged input +
    #: merged output + merge intermediates.  Without this check the
    #: spill tier would convert the old memory-protection rejection
    #: into a disk-exhaustion vector (huge declared n, or a full
    #: volume surfacing as an untyped OSError mid-stage).
    SPILL_DISK_FACTOR = 3

    def spill_disk_ok(self, nbytes: int) -> bool:
        """True when the spill volume has room for a request of
        ``nbytes`` (input + output + intermediates); False degrades to
        the ordinary typed ``bytes`` rejection."""
        import shutil

        from mpitest_tpu.store import external

        try:
            free = shutil.disk_usage(
                external.resolve_spill_dir(None)).free
        except OSError:
            return False
        return free >= self.SPILL_DISK_FACTOR * nbytes

    def start_watchdog(self) -> None:
        """Start the dispatch-watchdog thread (no-op when
        ``SORT_SERVE_DISPATCH_TIMEOUT_S=0``)."""
        self.watchdog.start()

    def _publish_admission(self, inflight: int, nbytes: int) -> None:
        self.metrics.gauge("sort_serve_inflight").set(inflight)
        self.metrics.gauge("sort_serve_inflight_bytes").set(nbytes)

    # -- startup ------------------------------------------------------
    def prewarm(self, log: Any = None) -> int:
        """AOT-prewarm the executor cache (``SORT_SERVE_PREWARM`` /
        ``SORT_SERVE_SHAPE_BUCKETS``); returns executables ensured."""
        if knobs.get("SORT_SERVE_PREWARM") == "off":
            return 0
        log = log or (lambda m: None)
        buckets = tuple(1 << int(b)
                        for b in knobs.get("SORT_SERVE_SHAPE_BUCKETS"))
        return self.cache.prewarm(buckets, ("int32",), log)

    # -- dispatch executors (dispatch thread only) --------------------
    def _run_solo(self, req: ServeRequest) -> None:
        """One supervised sort for one request.  A per-request fault
        spec (test mode) installs a scoped registry — the dispatch
        thread is single, so install/clear cannot race another sort.
        Runs under the request's trace context: every span the sort
        emits (phases, retries, faults, verify) carries its trace_id."""
        from mpitest_tpu.models import api

        req.picked_up()
        if req.expired():
            # final pre-executor deadline gate (stage "dispatch"): the
            # device never sees work nobody is waiting for
            req.fail_deadline("dispatch")
            self.batcher.deadline_cancelled += 1
            return
        reg = None
        if req.faults is not None:
            reg = faults.FaultRegistry(req.faults, seed=faults.faults_seed())
        try:
            with spanlib.trace_context(trace_id=req.trace_id), \
                    self.profiler.maybe_capture():
                if reg is not None:
                    faults.install(reg)
                try:
                    if req.spill:
                        # out-of-core tier (ISSUE 15): external sort
                        # over the disk-staged input, merged output
                        # streamed into one run the reply reads from
                        from mpitest_tpu.store import external

                        # out_name: server-minted nonce, NOT the
                        # client trace_id (see _spill_wire — a reused
                        # id must never collide one request's reply
                        # stream with another's dispatch)
                        res = external.external_sort(
                            req.arr, req.payload
                            if req.payload_width else None,
                            algorithm=req.algo, mesh=self.mesh,
                            tracer=self.tracer,
                            budget=self.spill_budget,
                            sink="file",
                            out_name=f"out_{mint_trace_id()}",
                            dataset=req.dataset)
                        out, out_pay, out_run = None, None, res.out_run
                    elif req.payload_width:
                        # record sort (ISSUE 15): key+payload through
                        # the fused argsort-gather
                        out, out_pay = api.sort(
                            req.arr, algorithm=req.algo, mesh=self.mesh,
                            tracer=self.tracer, payload=req.payload)
                        out_run = None
                    else:
                        out = api.sort(req.arr, algorithm=req.algo,
                                       mesh=self.mesh, tracer=self.tracer)
                        out_pay = out_run = None
                finally:
                    if reg is not None:
                        faults.install(None)
            # plan digest (ISSUE 12): sort() left its finished decision
            # record on the tracer (single dispatch thread — last write
            # is this request's); the compact digest rides the response
            # header so clients can watch decision drift
            p = self.tracer.plan
            req.complete(out, batched=False, bucket=None,
                         plan=p.digest() if isinstance(
                             p, plan_mod.SortPlan) else None,
                         payload=out_pay, run=out_run)
        except supervision.SortIntegrityError as e:
            req.fail(ERR_INTEGRITY, str(e))
        except supervision.SortRetryExhausted as e:
            req.fail(ERR_RETRIES, str(e))
        except (ValueError, TypeError, OverflowError) as e:
            from mpitest_tpu.store.runs import RunFormatError

            # a structurally-bad SPILL artifact is the server's disk
            # problem, never the client's request
            req.fail(ERR_INTERNAL if isinstance(e, RunFormatError)
                     else ERR_BAD_REQUEST, str(e))
        except OSError as e:
            # mid-merge disk-full (ISSUE 18): the external sort already
            # deleted its partials; the client sees the same retryable
            # rejection vocabulary as admission backpressure, never an
            # untyped 500
            if e.errno == errno.ENOSPC:
                req.fail(ERR_BACKPRESSURE, str(e))
            else:
                flight_recorder.dump_on_error("serve_internal")
                req.fail(ERR_INTERNAL, f"{type(e).__name__}: {e}")
        except Exception as e:  # noqa: BLE001 — one request's problem,
            # never the server's; an UNtyped failure is an incident the
            # flight recorder must document (api.sort dumps the typed
            # ones itself at their raise chokepoint)
            flight_recorder.dump_on_error("serve_internal")
            req.fail(ERR_INTERNAL, f"{type(e).__name__}: {e}")

    def _run_batch(self, reqs: "list[ServeRequest]") -> None:
        """One packed multi-tenant dispatch.  Per-segment verification
        isolates a bad segment: it re-runs solo under the supervisor,
        its batchmates' verified results return normally.  The whole
        dispatch runs under a ``batch_id`` trace context, and the
        ``serve.batch`` span lists every member's ``trace_id`` — one
        request is reconstructable even when it shared a device sort
        with strangers (ISSUE 10)."""
        from mpitest_tpu.models import api

        t0 = time.perf_counter()
        for r in list(reqs):
            r.picked_up()
            if r.expired():
                # a member that expired while the window packed around
                # it is cancelled here; its batchmates dispatch normally
                r.fail_deadline("dispatch")
                self.batcher.deadline_cancelled += 1
                reqs.remove(r)
        if not reqs:
            return
        dtype = reqs[0].dtype
        self._batch_seq += 1
        batch_id = f"b{os.getpid():x}-{self._batch_seq:06x}"
        with spanlib.trace_context(batch_id=batch_id):
            try:
                with self.profiler.maybe_capture():
                    batch = segmented.pack_segments(
                        [r.arr for r in reqs], dtype)
                    exe = self.cache.get_packed(batch.bucket, dtype.name,
                                                len(batch.words))
                    sorted_words = segmented.run_packed(batch, exe)
                reg = faults.for_run()
                supervision.wire_registry(reg, self.tracer)
                sorted_words = _maybe_corrupt_packed(reg, sorted_words,
                                                     batch.n_valid)
                verdicts = segmented.verify_segments(batch, sorted_words)
                outs = segmented.split_segments(batch, sorted_words)
            except Exception as e:  # noqa: BLE001 — pack/dispatch died:
                # nothing was verified; every tenant falls back to its
                # own supervised solo run (typed per-request outcome)
                self.tracer.count("serve_batch_fallbacks", 1)
                self.metrics.counter(
                    "sort_serve_batch_fallbacks_total").inc(1)
                flight_recorder.dump_on_error("serve_batch_fallback")
                self.tracer.verbose(f"batch dispatch failed "
                                    f"({type(e).__name__}: {e}); "
                                    "re-running each request solo")
                for r in reqs:
                    self._run_solo(r)
                return
            attrs: dict = {"segments": len(reqs), "keys": batch.n_valid,
                           "bucket": batch.bucket, "dtype": dtype.name,
                           "trace_ids": [r.trace_id for r in reqs]}
            peak = api.device_mem_peak(self.mesh)
            if peak:
                attrs["device_mem_peak_bytes"] = peak
            self.tracer.spans.record(
                "serve.batch", t0, time.perf_counter() - t0, **attrs)
            # batch plan (ISSUE 12): the batching-window + bucket
            # decision as a first-class plan record — predicted waste
            # at window close vs the padded lanes actually shipped
            digest = None
            if plan_mod.enabled():
                plan = plan_mod.SortPlan(algo="packed", n=batch.n_valid,
                                         dtype=dtype.name, ranks=1)
                w = next((r.window for r in reqs if r.window), None) or {}
                keys_close = int(w.get("keys", batch.n_valid))
                pred_bucket = segmented.bucket_for(keys_close)
                plan.decide(
                    "batch", chosen=batch.bucket,
                    trigger=str(w.get("closed_by", "?")),
                    members=int(w.get("members", len(reqs))),
                    bucket=pred_bucket,
                    waste=round(1.0 - keys_close / pred_bucket, 4))
                plan.actual(
                    "batch", keys=batch.n_valid,
                    waste=round(1.0 - batch.n_valid / batch.bucket, 4))
                plan.finalize()
                self.tracer.spans.event("sort.plan", **plan.to_attrs())
                digest = plan.digest()
            for r, ok, out in zip(reqs, verdicts, outs):
                if ok:
                    r.complete(out, batched=True, bucket=batch.bucket,
                               batch_id=batch_id, plan=digest)
                else:
                    self.tracer.count("serve_segment_requeues", 1)
                    self.metrics.counter(
                        "sort_serve_segment_requeues_total").inc(1)
                    self.tracer.verbose(
                        "batched segment failed verification; re-running "
                        "that request solo under the supervisor")
                    self._run_solo(r)

    # -- request execution (any handler thread) -----------------------
    def _finish(self, t0: float, attrs: dict, status: str,
                payload: Any) -> tuple[str, Any, dict]:
        """Record the ``serve.request`` span — the SLO unit — and the
        served/errored tallies; every request path ends here exactly
        once."""
        attrs["status"] = status
        self.tracer.spans.record("serve.request", t0,
                                 time.perf_counter() - t0, **attrs)
        with self._tally_lock:
            if status == "ok":
                self.requests_ok += 1
            else:
                self.requests_err += 1
        return status, payload, attrs

    @staticmethod
    def reject_code(e: AdmissionReject) -> str:
        return ERR_DRAINING if e.reason == "draining" else ERR_BACKPRESSURE

    def _deadline_event(self, stage: str, trace_id: str) -> None:
        """Record the registered ``serve.deadline`` point event — the
        audit trail (and live counter, via the span bridge) of work
        cancelled before it ever reached the device."""
        self.tracer.spans.record("serve.deadline", time.perf_counter(),
                                 0.0, stage=stage, trace_id=trace_id)

    def _dispatch_admitted(self, t0: float, attrs: dict, arr: np.ndarray,
                           algo: str | None, faults_spec: str | None,
                           trace_id: str, deadline: float | None = None,
                           payload: np.ndarray | None = None,
                           spill: bool = False,
                           dataset: str | None = None,
                           ) -> tuple[str, Any, dict]:
        """Dispatch an ALREADY-ADMITTED request and wait for completion.
        The caller owns the admission release.  ``payload`` (ISSUE 15)
        routes through the record sort; ``spill`` through the
        out-of-core tier — both solo by construction (the packed path
        is keys-only and in-memory).  ``dataset`` (ISSUE 18) keys the
        spill tier's journaled manifest for crash/retry resume."""
        width = int(payload.shape[1]) if payload is not None else 0
        req = ServeRequest(
            arr=arr, dtype=np.dtype(arr.dtype),
            algo=algo or self.default_algo,
            batchable=(faults_spec is None and not spill and width == 0
                       and int(arr.size) <= self.batch_keys),
            faults=faults_spec, trace_id=trace_id, deadline=deadline,
            payload=payload, payload_width=width, spill=spill,
            dataset=dataset)
        # serve auto-tuning (ISSUE 14): every admitted request feeds
        # the rolling mix the window/bucket policies learn from
        self._tuner_observe(int(arr.size), req.dtype.name)
        if req.expired():
            # stage "admission": the deadline died while the payload
            # was read/admitted — never enqueued, never dispatched
            req.fail_deadline("admission")
            attrs["deadline_stage"] = "admission"
            self._deadline_event("admission", trace_id)
            return self._finish(t0, attrs, req.error[0], req.error[1])
        with self._inflight_lock:
            self._inflight_reqs[trace_id] = req
        try:
            self.batcher.submit(req)
            if not req.done.wait(self.completion_timeout_s):
                return self._finish(t0, attrs, ERR_INTERNAL,
                                    "dispatch timed out")
        finally:
            with self._inflight_lock:
                self._inflight_reqs.pop(trace_id, None)
        attrs["batched"] = req.batched
        if req.bucket is not None:
            attrs["bucket"] = req.bucket
        if req.batch_id is not None:
            attrs["batch_id"] = req.batch_id
        if req.plan is not None:
            attrs["plan"] = req.plan
        if req.queue_s is not None:
            attrs["queue_s"] = round(req.queue_s, 6)
        if req.error is not None:
            if req.error[0] == ERR_DEADLINE_EXCEEDED:
                attrs["deadline_stage"] = req.deadline_stage
                self._deadline_event(req.deadline_stage or "queue",
                                     trace_id)
            return self._finish(t0, attrs, req.error[0], req.error[1])
        if req.result_run is not None:            # spill tier (ISSUE 15)
            attrs["spilled"] = True
            return self._finish(t0, attrs, "ok", req.result_run)
        if req.payload_width:                     # record sort
            return self._finish(t0, attrs, "ok",
                                (req.result, req.result_payload))
        return self._finish(t0, attrs, "ok", req.result)

    def _tuner_observe(self, n: int, dtype_name: str = "int32") -> None:
        """Feed the serve tuner one admitted request (ISSUE 14) and,
        every RETUNE_EVERY observations, evaluate the mix.  A committed
        recommendation re-sizes the live batching window (`on` mode
        only — `shadow` logs the would-have-been retune and changes
        nothing) and background-prewarms any (bucket, dtype) pair the
        observed size/dtype mix says it needs.  Every commit is a
        registered `planner` plan decision in the span stream, so
        window drift is explainable from the same record as everything
        else."""
        tuner = self.tuner
        if tuner is None:
            return
        if not tuner.observe(time.monotonic(), n, dtype_name):
            return
        verdict = tuner.evaluate()
        if verdict is None or verdict[0] != "retune":
            return
        _action, rec = verdict
        applied = self.planner_mode == "on"
        want = tuple(sorted({
            segmented.bucket_for(int(rec["p99_n"])),
            segmented.bucket_for(int(rec["expected_batch_keys"]))}))
        dtypes = tuple(rec.get("dtypes") or ("int32",))
        missing = self.cache.missing_packed(want, dtypes)
        if applied:
            self.batcher.set_window(rec["window_s"])
            self.metrics.counter(
                "sort_serve_window_retunes_total").inc(1)
            self.metrics.gauge("sort_serve_batch_window_ms").set(
                rec["window_s"] * 1e3)
            if missing:
                # compile OFF the request path: a daemon thread pays
                # the build (detached — see _build_detached: a racing
                # cold-key get_packed may also compile, first insert
                # wins, the dispatch thread never waits on prewarm)
                def _prewarm(cache=self.cache, pairs=missing):
                    for dn in sorted({d for _b, d in pairs}):
                        cache.prewarm(tuple(sorted(
                            b for b, d in pairs if d == dn)), (dn,))
                threading.Thread(target=_prewarm,
                                 name="serve-tuner-prewarm",
                                 daemon=True).start()
        if missing:
            # its own plan event: a SortPlan keys decisions by name, so
            # the bucket verdict cannot ride the window_auto record —
            # and shadow logs the would-have-been prewarm too
            bplan = plan_mod.SortPlan(algo="serve_tuner")
            bplan.decide("planner", chosen="buckets_auto",
                         trigger="mix_shift", applied=applied,
                         buckets=sorted({int(b) for b, _d in missing}),
                         dtypes=sorted({d for _b, d in missing}))
            bplan.finalize()
            self.tracer.spans.event("sort.plan", **bplan.to_attrs())
        plan = plan_mod.SortPlan(algo="serve_tuner")
        plan.decide("planner", chosen="window_auto",
                    trigger="mix_shift", applied=applied,
                    window_ms=round(rec["window_s"] * 1e3, 3),
                    p50_gap_ms=round(rec["p50_gap_s"] * 1e3, 3),
                    p99_n=rec["p99_n"],
                    expected_batch_keys=rec["expected_batch_keys"])
        plan.finalize()
        self.tracer.spans.event("sort.plan", **plan.to_attrs())

    def stuck_trace_ids(self) -> list[str]:
        """Trace ids of requests admitted+dispatched but not yet
        complete — what the drain-timeout incident artifact names."""
        with self._inflight_lock:
            return sorted(self._inflight_reqs)

    def _admit(self, nbytes: int) -> None:
        """Admission with the circuit breaker consulted FIRST (ISSUE
        11): while the breaker is open a request is rejected in
        microseconds — clients back off instead of queueing behind a
        wedged dispatch."""
        if self.breaker.engaged():
            self.admission.note_reject()
            raise AdmissionReject(
                "breaker",
                "circuit breaker open (dispatch watchdog tripped); "
                "retry with backoff")
        self.admission.admit(nbytes)

    def execute(self, arr: np.ndarray, algo: str | None = None,
                faults_spec: str | None = None,
                trace_id: str | None = None,
                deadline_ms: float | None = None,
                payload: np.ndarray | None = None,
                ) -> tuple[str, Any, dict]:
        """Admit, dispatch and complete one request (the in-process
        entry; the wire path admits BEFORE materializing the payload —
        see :meth:`handle_wire`).  Returns ``(status, payload, attrs)``
        where status ``"ok"`` carries the sorted array (a
        ``(keys, payload)`` pair for record requests) and any error
        status carries the detail string.  ``trace_id`` is minted when
        the caller supplies none; it lands in ``attrs`` and on every
        span the request touches.  ``deadline_ms`` (optional) is the
        caller's remaining latency budget: once it expires the request
        is cancelled typed ``deadline_exceeded`` at whatever lifecycle
        stage it had reached — never dispatched late.

        A request whose bytes exceed ``SORT_SERVE_MAX_BYTES`` outright
        routes to the spill tier (ISSUE 15) instead of the typed
        ``bytes`` rejection — unless ``SORT_SERVE_SPILL=off``."""
        t0 = time.perf_counter()
        tid = trace_id or mint_trace_id()
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        pay = None
        if payload is not None:
            from mpitest_tpu.models.records import as_payload_matrix

            pay = as_payload_matrix(payload, int(arr.size))
        nbytes = int(arr.nbytes) + (int(pay.nbytes) if pay is not None
                                    else 0)
        attrs: dict = {"n": int(arr.size), "dtype": str(arr.dtype),
                       "trace_id": tid}
        spill = False
        if (nbytes > self.admission.max_bytes and self.spill_enabled
                and faults_spec is None and self.spill_disk_ok(nbytes)):
            # the request can NEVER fit the byte bound — the spill
            # tier serves it from disk under count-only admission
            # (no disk headroom ⇒ fall through to the byte rejection)
            spill = True
            attrs["spilled"] = True
            nbytes = 0
        try:
            self._admit(nbytes)
        except AdmissionReject as e:
            attrs["reject"] = e.reason
            return self._finish(t0, attrs, self.reject_code(e), str(e))
        if spill:
            self.metrics.counter(
                "sort_external_spilled_requests_total").inc(1)
        try:
            status, result, attrs = self._dispatch_admitted(
                t0, attrs, arr, algo, faults_spec, tid, deadline,
                payload=pay, spill=spill)
        finally:
            self.admission.release(nbytes)
        if status == "ok" and spill:
            # in-process callers want arrays, not the output run: read
            # it back (the run files are unlinked once viewed)
            from mpitest_tpu.store import runs as runlib

            views = runlib.run_body_views(result, unlink=True)
            keys = np.frombuffer(views[0], dtype=arr.dtype).copy()
            if pay is not None:
                out_pay = np.frombuffer(
                    views[1], np.uint8).reshape(pay.shape).copy()
                return status, (keys, out_pay), attrs
            return status, keys, attrs
        return status, result, attrs

    # -- wire handling ------------------------------------------------
    def wire_timeout(self, kind: str) -> None:
        """Tally one enforced wire timeout (kind: idle|read|write) —
        the live evidence a slow-loris is being shed, not served."""
        self.metrics.counter("sort_serve_timeouts_total").inc(
            1, kind=kind)

    def _read_wire(self, rfile: BinaryIO, nbytes: int,
                   conn: "socket.socket | None",
                   keep: bool = True,
                   sink: Any = None) -> tuple[bytes, str]:
        """Read exactly ``nbytes`` under ONE total wall budget
        (``SORT_SERVE_READ_TIMEOUT_S``).  On a socket the loop uses
        ``read1`` — AT MOST ONE underlying ``recv`` per call — with
        the timeout re-armed to the remaining budget before each, so
        the deadline is re-checked per recv: neither a dead stall nor
        a slow drip (whose every tiny chunk "makes progress" and so
        never trips a per-recv timeout) can hold the thread past the
        budget (ISSUE 11).  Returns ``(data, outcome)`` with outcome
        ``"ok"``, ``"short"`` (EOF / reset) or ``"timeout"``;
        ``keep=False`` drops the bytes (the discard path) instead of
        accumulating them, and ``sink`` (a callable taking one bytes
        chunk — the spill tier's disk stage) consumes them without
        accumulation either way.  ``conn`` None (in-process callers
        reading from a BytesIO) reads unbounded — there is no socket to
        stall."""
        chunks: list[bytes] = []
        got = 0
        deadline = (time.monotonic() + self.read_timeout_s
                    if conn is not None else None)
        read1 = getattr(rfile, "read1", None)
        while got < nbytes:
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return b"".join(chunks), "timeout"
                try:
                    conn.settimeout(remaining)
                except OSError:
                    return b"".join(chunks), "short"
            want = min(nbytes - got, 1 << 20)
            try:
                # read1 never blocks across multiple recvs; a plain
                # buffered read(N) would recv in a loop internally,
                # giving EVERY recv the full remaining budget and
                # stretching the total far past the deadline
                chunk = (read1(want) if read1 is not None
                         else rfile.read(want))
            except TimeoutError:
                return b"".join(chunks), "timeout"
            except OSError:
                return b"".join(chunks), "short"
            if not chunk:
                return b"".join(chunks), "short"
            if sink is not None:
                sink(chunk)
            elif keep:
                chunks.append(chunk)
            got += len(chunk)
        return b"".join(chunks), "ok"

    def _read_header_line(self, rfile: BinaryIO,
                          conn: "socket.socket",
                          ) -> tuple[bytes, str]:
        """Read one header line under two TOTAL budgets: the idle
        timeout bounds the wait for the FIRST byte (a keep-alive
        connection sitting between requests), the read timeout bounds
        the rest of the line (a header dripped byte-by-byte must not
        reset the clock per recv — a plain ``readline`` would).  Uses
        ``read1(1)``: at most one raw recv per call, and anything the
        recv buffered past the requested byte stays in the
        BufferedReader for the payload reads.  Returns ``(line,
        outcome)`` with outcome ``ok`` | ``idle`` | ``read`` (the
        timeout kinds) | ``closed`` (EOF / reset / over-long)."""
        line = bytearray()
        read1 = rfile.read1
        deadline = time.monotonic() + self.idle_timeout_s
        phase = "idle"
        while len(line) < (1 << 16):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return bytes(line), phase
            try:
                conn.settimeout(remaining)
                b = read1(1)
            except TimeoutError:
                return bytes(line), phase
            except OSError:
                return bytes(line), "closed"
            if not b:
                return bytes(line), "closed"
            line += b
            if phase == "idle":
                # first byte landed: this is now a request read, on
                # the request-read budget
                phase = "read"
                deadline = time.monotonic() + self.read_timeout_s
            if b == b"\n":
                return bytes(line), "ok"
        return bytes(line), "closed"

    def write_wire(self, conn: "socket.socket",
                   blob: "bytes | list") -> str:
        """Send a response under ONE total wall budget (the read
        timeout): per-``send`` socket timeouts reset on any progress,
        so a client reading one byte per interval could otherwise pin
        the handler thread for hours on a large payload.  ``blob`` may
        be a list of byte-like segments (the spill tier's zero-copy
        run views) — all segments share the one budget.  Returns
        ``"ok"``, ``"timeout"`` or ``"closed"``."""
        segments = blob if isinstance(blob, list) else [blob]
        deadline = time.monotonic() + self.read_timeout_s
        for seg in segments:
            view = memoryview(seg)
            off = 0
            while off < len(view):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self.wire_timeout("write")
                    return "timeout"
                try:
                    conn.settimeout(remaining)
                    off += conn.send(view[off:off + (1 << 20)])
                except TimeoutError:
                    self.wire_timeout("write")
                    return "timeout"
                except OSError:
                    return "closed"
        return "ok"

    def _discard(self, rfile: BinaryIO, nbytes: int,
                 conn: "socket.socket | None" = None) -> bool:
        """Read and drop ``nbytes`` of payload — keeps the
        connection's framing after a semantic rejection WITHOUT ever
        buffering the rejected payload (the admission byte bound must
        bound memory, not just dispatch).  Same bounded reader, same
        total time budget.  Returns False on a short read or timeout
        (framing lost)."""
        _data, outcome = self._read_wire(rfile, nbytes, conn, keep=False)
        if outcome == "timeout":
            self.wire_timeout("read")
        return outcome == "ok"

    def handle_wire(self, header_line: bytes, rfile: BinaryIO,
                    conn: "socket.socket | None" = None,
                    ) -> tuple[dict, bytes, bool]:
        """One request from the wire: parse the header, ADMIT (the
        payload only enters host memory after the admission byte/count
        bounds said yes), read the payload, execute, build the
        response.  Returns ``(response header, response payload,
        keep_alive)`` — ``keep_alive`` False means framing is lost
        (unreadable header / short payload / read timeout) and the
        connection must close.  ``conn`` (the TCP layer passes its
        socket) arms the total read budget; in-process callers reading
        from a BytesIO pass None and read unbounded."""
        tid: str | None = None   # echoed in every response once known

        def err(code: str, detail: str, keep: bool = True,
                ) -> tuple[dict, bytes, bool]:
            h = {"v": WIRE_SCHEMA, "ok": False, "error": code,
                 "detail": detail}
            if tid is not None:
                h["trace_id"] = tid
            return (h, b"", keep)

        try:
            hdr = json.loads(header_line.decode("utf-8"))
            if not isinstance(hdr, dict):
                raise ValueError("header is not an object")
        except (UnicodeDecodeError, ValueError) as e:
            return err(ERR_BAD_REQUEST, f"unreadable header: {e}",
                       keep=False)
        if hdr.get("v") != WIRE_SCHEMA:
            return err(ERR_BAD_REQUEST,
                       f"unknown protocol version {hdr.get('v')!r} "
                       f"(want {WIRE_SCHEMA!r})", keep=False)
        # trace context (ISSUE 10), parsed FIRST among the fields so
        # every later typed error echoes it — a client correlating
        # failures by its minted id must never lose one to a bad dtype.
        raw_tid = hdr.get("trace_id")
        if raw_tid is not None and (
                not isinstance(raw_tid, str)
                or not _TRACE_ID_RE.fullmatch(raw_tid)):
            return err(ERR_BAD_REQUEST,
                       f"bad trace_id {raw_tid!r} (1-64 chars of "
                       "[A-Za-z0-9_-])", keep=False)
        tid = raw_tid or mint_trace_id()
        # dataset_id (ISSUE 18): client-chosen stable id keying the
        # spill tier's journaled manifest — a retried request with the
        # same id warm-resumes at the merge phase.  Same grammar as
        # trace_id (it becomes a spill-dir filename stem).
        dataset_id = hdr.get("dataset_id")
        if dataset_id is not None and (
                not isinstance(dataset_id, str)
                or not _TRACE_ID_RE.fullmatch(dataset_id)):
            return err(ERR_BAD_REQUEST,
                       f"bad dataset_id {dataset_id!r} (1-64 chars of "
                       "[A-Za-z0-9_-])", keep=False)
        try:
            dtype = np.dtype(str(hdr.get("dtype", "int32")))
            from mpitest_tpu.ops.keys import codec_for

            codec_for(dtype)  # rejects valid-but-unsupported dtypes
        except Exception as e:  # noqa: BLE001 — typed wire error
            return err(ERR_BAD_REQUEST, f"bad dtype: {e}", keep=False)
        n = hdr.get("n")
        if not isinstance(n, int) or not 1 <= n <= MAX_REQUEST_KEYS:
            return err(ERR_BAD_REQUEST,
                       f"bad n={n!r} (integer in [1, {MAX_REQUEST_KEYS}])",
                       keep=False)
        # payload_bytes (ISSUE 15): per-record payload width.  The
        # payload section (n * payload_bytes raw bytes) follows the key
        # bytes; the reply mirrors the framing with the payload
        # permuted into key order.
        width = hdr.get("payload_bytes", 0)
        if not isinstance(width, int) or isinstance(width, bool) or \
                not 0 <= width <= MAX_PAYLOAD_WIDTH:
            return err(ERR_BAD_REQUEST,
                       f"bad payload_bytes={width!r} (integer in "
                       f"[0, {MAX_PAYLOAD_WIDTH}])", keep=False)
        nbytes = n * (dtype.itemsize + width)
        # deadline_ms (ISSUE 11): the client's remaining latency budget
        # becomes an ABSOLUTE monotonic deadline right here, carried
        # through admission -> queue -> dispatch; expired work is
        # cancelled typed, never dispatched.
        deadline_ms = hdr.get("deadline_ms")
        deadline: float | None = None
        if deadline_ms is not None:
            ok_num = (isinstance(deadline_ms, (int, float))
                      and not isinstance(deadline_ms, bool)
                      and math.isfinite(float(deadline_ms))
                      and float(deadline_ms) > 0)
            if not ok_num:
                keep = self._discard(rfile, nbytes, conn)
                return err(ERR_BAD_REQUEST,
                           f"bad deadline_ms {deadline_ms!r} (a finite "
                           "number of milliseconds > 0)", keep=keep)
            deadline = time.monotonic() + float(deadline_ms) / 1e3
        algo = hdr.get("algo")
        if algo is not None and algo not in ("radix", "sample"):
            # payload not read yet: framing is recoverable only by
            # draining it (bounded chunks) before responding
            keep = self._discard(rfile, nbytes, conn)
            return err(ERR_BAD_REQUEST,
                       f"bad algo {algo!r} (radix | sample)", keep=keep)
        faults_spec = hdr.get("faults")
        if faults_spec is not None:
            if not self.allow_faults:
                keep = self._discard(rfile, nbytes, conn)
                return err(ERR_BAD_REQUEST,
                           "per-request fault injection requires "
                           "SORT_SERVE_ALLOW_FAULTS=1 on the server",
                           keep=keep)
            try:
                faults.FaultRegistry(str(faults_spec))
            except ValueError as e:
                keep = self._discard(rfile, nbytes, conn)
                return err(ERR_BAD_REQUEST, str(e), keep=keep)
        # Admission BEFORE the payload is materialized: a rejected
        # request is drained in bounded chunks, so the in-flight byte
        # bound really bounds host memory, not just dispatch.
        t0 = time.perf_counter()
        attrs: dict = {"n": n, "dtype": dtype.name, "trace_id": tid}
        if width:
            attrs["payload_bytes"] = width
        if (nbytes > self.admission.max_bytes and self.spill_enabled
                and faults_spec is None and self.spill_disk_ok(nbytes)):
            # spill tier (ISSUE 15): the request can NEVER fit the
            # byte bound — stream it to disk and serve it out-of-core
            # instead of the old typed 'bytes' rejection.  No disk
            # headroom (3x the request) ⇒ the ordinary typed rejection
            # below, never an untyped OSError mid-stage.
            return self._spill_wire(t0, attrs, rfile, conn, n, dtype,
                                    width, algo, tid, deadline, err,
                                    dataset_id)
        try:
            self._admit(nbytes)
        except AdmissionReject as e:
            attrs["reject"] = e.reason
            code, detail, _ = self._finish(t0, attrs,
                                           self.reject_code(e), str(e))
            keep = self._discard(rfile, nbytes, conn)
            return err(code, str(detail), keep=keep)
        try:
            # the TOTAL read budget (SORT_SERVE_READ_TIMEOUT_S) starts
            # here: a client that stalls mid-payload — or drips one
            # byte per second — is disconnected at the budget, and the
            # finally below provably reclaims its admission bytes on
            # THIS exit path like every other (ISSUE 11 satellite).
            payload, outcome = self._read_wire(rfile, nbytes, conn)
            if outcome != "ok":
                if outcome == "timeout":
                    self.wire_timeout("read")
                detail = (f"payload read "
                          f"{'timed out' if outcome == 'timeout' else 'short'}"
                          f" ({len(payload)}/{nbytes} bytes)")
                # post-admission outcome like any other: it must land
                # in the serve.request span stream / error tally too
                self._finish(t0, attrs, ERR_BAD_REQUEST, detail)
                return err(ERR_BAD_REQUEST, detail, keep=False)
            key_bytes = n * dtype.itemsize
            arr = np.frombuffer(payload[:key_bytes], dtype=dtype).copy()
            pay = None
            if width:
                pay = np.frombuffer(
                    payload[key_bytes:], np.uint8).reshape(n,
                                                           width).copy()
            del payload
            status, result, attrs = self._dispatch_admitted(
                t0, attrs, arr, algo,
                str(faults_spec) if faults_spec is not None else None,
                tid, deadline, payload=pay)
        finally:
            self.admission.release(nbytes)
        if status != "ok":
            return err(status, str(result))
        return self._ok_response(n, dtype, width, attrs, tid, result)

    def _ok_response(self, n: int, dtype: np.dtype, width: int,
                     attrs: dict, tid: str, result: Any,
                     ) -> tuple[dict, Any, bool]:
        """Build the ok wire response.  ``result`` is the sorted array,
        a ``(keys, payload)`` pair (records) or a
        :class:`~mpitest_tpu.store.runs.RunInfo` (spill tier — the
        reply streams zero-copy memoryviews of the output run)."""
        resp = {"v": WIRE_SCHEMA, "ok": True, "n": n,
                "dtype": dtype.name,
                "batched": bool(attrs.get("batched")),
                "bucket": attrs.get("bucket"),
                "trace_id": tid}
        if width:
            resp["payload_bytes"] = width
        if attrs.get("spilled"):
            resp["spilled"] = True
        if attrs.get("batch_id") is not None:
            resp["batch_id"] = attrs["batch_id"]
        if attrs.get("plan") is not None:
            # compact decision digest (ISSUE 12): algo, negotiated cap,
            # restage verdict, regret — decision drift is observable
            # from the client side without the span stream
            resp["plan"] = attrs["plan"]
        if attrs.get("spilled"):
            from mpitest_tpu.store import runs as runlib

            return resp, runlib.run_body_views(result, unlink=True), True
        if width:
            keys, pay = result
            return resp, (np.ascontiguousarray(keys).tobytes()
                          + np.ascontiguousarray(pay).tobytes()), True
        return resp, np.ascontiguousarray(result).tobytes(), True

    def _spill_wire(self, t0: float, attrs: dict, rfile: BinaryIO,
                    conn: "socket.socket | None", n: int,
                    dtype: np.dtype, width: int, algo: str | None,
                    tid: str, deadline: float | None,
                    err: Any,
                    dataset_id: str | None = None) -> tuple[dict, Any, bool]:
        """The wire spill tier: stream the over-budget request's bytes
        straight from the socket into spill-dir staging files (host
        memory never holds them), dispatch the external sort over the
        staged memmaps, and reply from the merged output run.  Admitted
        under the COUNT bound only (bytes live on disk); the staged
        and output files are unlinked as soon as they are mapped, so no
        exit path can leak them."""
        from mpitest_tpu.store import external
        from mpitest_tpu.store import runs as runlib

        attrs["spilled"] = True
        try:
            self._admit(0)
        except AdmissionReject as e:
            attrs["reject"] = e.reason
            code, detail, _ = self._finish(t0, attrs,
                                           self.reject_code(e), str(e))
            keep = self._discard(rfile, n * (dtype.itemsize + width),
                                 conn)
            return err(code, str(detail), keep=keep)
        self.metrics.counter(
            "sort_external_spilled_requests_total").inc(1)
        # staging/output names carry a SERVER-minted nonce, never the
        # client-supplied trace_id: two concurrent requests reusing one
        # trace_id (only grammar-checked) must not share disk paths —
        # interleaved staged bytes would be folded as-is and VERIFY
        # cleanly while carrying the other client's data.
        nonce = mint_trace_id()
        try:
            stage = runlib.InputStage(
                external.resolve_spill_dir(None), f"in_{nonce}", dtype,
                n, width)
            try:
                _, outcome = self._read_wire(rfile, n * dtype.itemsize,
                                             conn, sink=stage.key_sink)
                if outcome == "ok" and width:
                    _, outcome = self._read_wire(rfile, n * width, conn,
                                                 sink=stage.pay_sink)
                if outcome != "ok":
                    if outcome == "timeout":
                        self.wire_timeout("read")
                    stage.abort()
                    detail = (f"payload read "
                              f"{'timed out' if outcome == 'timeout' else 'short'}"
                              " (spill tier)")
                    self._finish(t0, attrs, ERR_BAD_REQUEST, detail)
                    return err(ERR_BAD_REQUEST, detail, keep=False)
                arr, pay = stage.finish()
            except runlib.RunFormatError as e:
                self._finish(t0, attrs, ERR_INTERNAL, str(e))
                return err(ERR_INTERNAL, str(e), keep=False)
            except OSError as e:
                # ENOSPC while staging (ISSUE 18): typed retryable
                # rejection, partial staging files already unlinked
                stage.abort()
                if e.errno != errno.ENOSPC:
                    raise
                self._finish(t0, attrs, ERR_BACKPRESSURE, str(e))
                return err(ERR_BACKPRESSURE, str(e), keep=False)
            status, result, attrs = self._dispatch_admitted(
                t0, attrs, arr, algo, None, tid, deadline, payload=pay,
                spill=True, dataset=dataset_id)
        finally:
            self.admission.release(0)
        if status != "ok":
            return err(status, str(result))
        return self._ok_response(n, dtype, width, attrs, tid, result)

    # -- lifecycle ----------------------------------------------------
    def start_drain(self) -> None:
        self.admission.start_drain()

    def drain_and_stop(self, timeout: float = 60.0) -> bool:
        """SIGTERM semantics: reject new work (typed ``draining``), let
        in-flight requests complete, stop the dispatch thread and the
        watchdog.  Returns True ONLY when everything drained AND the
        dispatch thread actually exited inside ``timeout`` — a wedged
        dispatch is a dirty exit, not a quiet one (ISSUE 11: the
        join() outcome used to be silently discarded here)."""
        self.start_drain()
        idle = self.admission.wait_idle(timeout)
        stopped = self.batcher.stop(timeout=10.0)
        self.watchdog.stop()
        return idle and stopped


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        core: ServerCore = self.server.core  # type: ignore[attr-defined]
        while True:
            # idle bound for the wait, read bound for the line itself
            # (ISSUE 11): both TOTAL budgets, not per-recv timeouts
            line, outcome = core._read_header_line(self.rfile,
                                                   self.connection)
            if outcome in ("idle", "read"):
                core.wire_timeout(outcome)
                return
            if outcome != "ok" or not line.strip():
                return
            resp, payload, keep = core.handle_wire(line, self.rfile,
                                                   self.connection)
            # response writes share the wire budget: a client that
            # stops (or trickles) reading cannot pin this thread on a
            # full send buffer.  A list payload (the spill tier's
            # zero-copy run views) streams segment by segment.
            header = json.dumps(resp).encode("utf-8") + b"\n"
            blob = ([header] + payload if isinstance(payload, list)
                    else header + payload)
            if core.write_wire(self.connection, blob) != "ok":
                return
            if not keep:
                return


class SortServer(socketserver.ThreadingTCPServer):
    """TCP front end over a :class:`ServerCore`.  Handler threads only
    parse/frame and block on completion events; all device work happens
    on the core's single dispatch thread."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, core: ServerCore, host: str, port: int) -> None:
        super().__init__((host, port), _Handler)
        self.core = core

    @property
    def bound_port(self) -> int:
        return int(self.server_address[1])
