"""Multi-tenant batching: pack concurrent small requests into one dispatch.

One dispatch of the packed program costs roughly the same wall time as a
dispatch for a single small request — the fixed per-launch overhead
(host staging, program launch, result sync) dominates at small N.  The
batcher therefore runs ONE dispatch thread that, on picking up a
batchable request, keeps collecting compatible requests for at most
``SORT_SERVE_BATCH_WINDOW_MS`` (or until ``SORT_SERVE_BATCH_KEYS`` keys
are packed) and hands the group to the server's batch runner — under
closed-loop small-request load, K tenants share one device launch
instead of paying K.

Compatibility is dtype equality (segments share one packed word
layout).  Requests that are too large, carry a per-request fault spec,
or arrive with batching disabled (window 0) dispatch alone, in arrival
order, on the same thread — a single dispatcher also serializes device
access, so batched and solo work never contend for the mesh.

The dispatch thread is the only thread that touches JAX; request
handler threads only enqueue and wait on per-request completion events.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Callable

import numpy as np

if TYPE_CHECKING:
    pass

#: Sentinel that tells the dispatch thread to finish the queue and exit.
_STOP = object()

#: Typed error code of a request whose deadline expired before dispatch
#: (ISSUE 11).  Defined here (not in server.py) because the dispatch
#: loop is the layer that cancels expired work; the wire protocol
#: re-exports it as part of the stable error vocabulary.
ERR_DEADLINE = "deadline_exceeded"


@dataclass
class ServeRequest:
    """One admitted request riding the dispatch queue.  The handler
    thread blocks on ``done``; the dispatch thread fills exactly one of
    ``result`` / ``error`` and sets it."""

    arr: np.ndarray
    dtype: np.dtype
    algo: str
    batchable: bool
    faults: str | None = None
    #: per-record payload bytes (ISSUE 15): a ``(n, width)`` uint8
    #: matrix riding the keys through the record sort.  Payload
    #: requests dispatch solo (the packed path is keys-only).
    payload: np.ndarray | None = None
    payload_width: int = 0
    #: out-of-core spill-tier request (ISSUE 15): ``arr``/``payload``
    #: are disk-backed memmaps of the staged input and the dispatch
    #: runs the external sort; solo by construction.
    spill: bool = False
    #: client-chosen dataset id (ISSUE 18): keys the spill tier's
    #: journaled manifest, so a retried request of the same dataset
    #: warm-resumes at the merge phase instead of re-sorting.
    dataset: str | None = None
    #: wire/client-minted request trace id (ISSUE 10) — stamped on every
    #: span this request touches via ``spans.trace_context``.
    trace_id: str = ""
    t_enq: float = field(default_factory=time.perf_counter)
    done: threading.Event = field(default_factory=threading.Event)
    result: np.ndarray | None = None
    #: record requests: the permuted payload, (n, width) uint8.
    result_payload: np.ndarray | None = None
    #: spill requests: the merged output run the reply streams from.
    result_run: object | None = None
    error: tuple[str, str] | None = None    # (code, detail)
    batched: bool = False
    bucket: int | None = None
    #: packed-dispatch identity this request shared (None for solo).
    batch_id: str | None = None
    #: seconds between enqueue and dispatch pickup (the queue wait the
    #: serve.request span + live histogram report).
    queue_s: float | None = None
    #: absolute monotonic deadline (ISSUE 11): work whose deadline
    #: expires before dispatch is cancelled typed, never dispatched.
    deadline: float | None = None
    #: lifecycle stage the deadline expired at (admission|queue|
    #: dispatch) — set by fail_deadline, read by the serve.deadline
    #: span emission.
    deadline_stage: str | None = None
    #: batching-window decision record (ISSUE 12): why the window
    #: closed, members collected, keys at close — the predicted side of
    #: the batch/bucket decision the server's plan stamps actuals onto.
    window: dict | None = None
    #: compact plan digest of the dispatch that served this request
    #: (models/plan.py SortPlan.digest()) — echoed in the wire response.
    plan: dict | None = None

    @property
    def n(self) -> int:
        return int(self.arr.size)

    def expired(self, now: float | None = None) -> bool:
        """True once the request's deadline has passed (False when no
        deadline was set)."""
        if self.deadline is None:
            return False
        return (time.monotonic() if now is None else now) >= self.deadline

    def fail_deadline(self, stage: str) -> None:
        """Cancel this request typed ``deadline_exceeded`` — it was
        never dispatched; the admitting handler releases its bytes."""
        self.deadline_stage = stage
        self.fail(ERR_DEADLINE,
                  f"deadline expired before dispatch (at stage "
                  f"{stage!r}); the sort was never run")

    def picked_up(self) -> None:
        """Dispatch-thread pickup marker: fixes the queue wait."""
        if self.queue_s is None:
            self.queue_s = time.perf_counter() - self.t_enq

    def complete(self, out: np.ndarray, batched: bool,
                 bucket: int | None, batch_id: str | None = None,
                 plan: dict | None = None,
                 payload: np.ndarray | None = None,
                 run: object | None = None) -> None:
        self.result = out
        self.result_payload = payload
        self.result_run = run
        self.batched = batched
        self.bucket = bucket
        self.batch_id = batch_id
        if plan is not None:
            self.plan = plan
        self.done.set()

    def fail(self, code: str, detail: str) -> None:
        self.error = (code, detail)
        self.done.set()


class Batcher:
    """The dispatch loop.  ``run_batch(requests)`` / ``run_solo(request)``
    are the server's executors; both must complete/fail every request
    they are handed (the loop itself never touches results)."""

    def __init__(self, run_batch: Callable[[list[ServeRequest]], None],
                 run_solo: Callable[[ServeRequest], None],
                 window_s: float, batch_keys: int) -> None:
        self.run_batch = run_batch
        self.run_solo = run_solo
        self.window_s = float(window_s)
        self.batch_keys = int(batch_keys)
        self._q: "queue.Queue[object]" = queue.Queue()
        self._pending: list[ServeRequest] = []  # incompatibles set aside
        self._pending_lock = threading.Lock()
        self._stopping = False
        self.batches = 0
        self.batched_requests = 0
        self.solo_requests = 0
        self.deadline_cancelled = 0
        #: live window re-sizes applied via set_window (ISSUE 14).
        self.window_retunes = 0
        #: dispatch heartbeat (ISSUE 11): (monotonic start, kind,
        #: trace_ids) while an executor call is live, None otherwise —
        #: the watchdog's only evidence, so it is set/cleared under a
        #: lock around EVERY executor call.
        self._hb_lock = threading.Lock()
        self._hb: "tuple[float, str, list[str]] | None" = None
        self._thread = threading.Thread(target=self._loop,
                                        name="serve-dispatch", daemon=True)
        self._thread.start()

    def submit(self, req: ServeRequest) -> None:
        self._q.put(req)

    def set_window(self, window_s: float) -> None:
        """Re-size the batching window live (ISSUE 14: the serve
        tuner's actuator).  A single float attribute swap — GIL-atomic
        against the dispatch loop, which re-reads ``window_s`` at every
        pack open, so the new value governs the NEXT window and never
        tears one already collecting.  Callers own the hysteresis; this
        method just applies."""
        self.window_s = float(window_s)
        self.window_retunes += 1

    # -- watchdog surface (ISSUE 11) ----------------------------------
    def inflight_dispatch(self) -> "tuple[float, str, list[str]] | None":
        """Snapshot of the live executor call: ``(age_s, kind,
        trace_ids)`` — None when the dispatch thread is between
        dispatches.  The watchdog polls this."""
        with self._hb_lock:
            hb = self._hb
        if hb is None:
            return None
        started, kind, tids = hb
        return (time.monotonic() - started, kind, tids)

    def fail_queued(self, code: str, detail: str) -> int:
        """Fail every request still waiting in the queue (typed) —
        called by the watchdog when the dispatch thread is wedged, so
        queued callers stop burning their completion timeout on work
        that will never start.  Returns the number failed."""
        failed = 0
        drained: list[object] = []
        while True:
            try:
                drained.append(self._q.get_nowait())
            except queue.Empty:
                break
        for item in drained:
            if isinstance(item, ServeRequest):
                if not item.done.is_set():
                    item.fail(code, detail)
                    failed += 1
            else:
                self._q.put(item)        # _STOP survives the purge
        with self._pending_lock:
            pending, self._pending = self._pending, []
        for req in pending:
            if not req.done.is_set():
                req.fail(code, detail)
                failed += 1
        return failed

    def _guarded(self, thunk: "Callable[[], None]",
                 reqs: list[ServeRequest], kind: str) -> None:
        """Run an executor under a blanket guard: the dispatch thread
        must survive ANY executor failure (the executors are typed
        internally, but e.g. a span-stream disk-full OSError escaping
        would otherwise kill the only thread that completes requests,
        wedging every future request for the full completion timeout).
        Requests the executor never completed fail typed instead.  The
        heartbeat brackets the call so the watchdog can age it."""
        with self._hb_lock:
            self._hb = (time.monotonic(), kind,
                        [r.trace_id for r in reqs])
        try:
            thunk()
        except BaseException as e:  # noqa: BLE001 — thread survival
            for r in reqs:
                if not r.done.is_set():
                    r.fail("internal",
                           f"dispatcher error: {type(e).__name__}: {e}")
        finally:
            with self._hb_lock:
                self._hb = None

    def stop(self, timeout: float = 60.0) -> bool:
        """Finish everything already enqueued, then stop the dispatch
        thread (the drain path: admission already rejects new work).
        Returns True iff the thread actually exited inside ``timeout``
        — a False here means a dispatch is wedged, and the caller
        (``ServerCore.drain_and_stop``) must NOT report a clean drain
        (the silently-discarded join() outcome, ISSUE 11)."""
        self._q.put(_STOP)
        self._thread.join(timeout)
        return not self._thread.is_alive()

    # -- dispatch loop ------------------------------------------------
    def _next(self, timeout: float | None) -> object | None:
        with self._pending_lock:
            if self._pending:
                return self._pending.pop(0)
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    @staticmethod
    def _deadline_close(req: ServeRequest, now: float) -> float:
        """Window-close instant for a deadline-carrying member: 90% of
        its remaining budget (>= 2 ms headroom), so the dispatch still
        happens inside the deadline instead of the pack consuming it
        to the last tick."""
        assert req.deadline is not None
        return req.deadline - max(0.002, 0.1 * (req.deadline - now))

    def _cancel_if_expired(self, req: ServeRequest, stage: str) -> bool:
        """Deadline gate at queue pickup (ISSUE 11): expired work is
        cancelled typed and NEVER dispatched — the device's time goes
        to requests someone is still waiting for.  (The executors run
        a final stage="dispatch" check at entry; this one catches the
        queue wait.)"""
        if req.expired():
            req.fail_deadline(stage)
            self.deadline_cancelled += 1
            return True
        return False

    def _loop(self) -> None:
        while True:
            item = self._next(timeout=0.1 if self._stopping else None)
            if item is None:
                if self._stopping and self._q.empty():
                    return
                continue
            if item is _STOP:
                self._stopping = True
                continue
            req = item  # type: ignore[assignment]
            if not isinstance(req, ServeRequest):
                continue
            if self._cancel_if_expired(req, "queue"):
                continue
            if not req.batchable or req.faults is not None:
                self.solo_requests += 1
                # kind "spill" lets the watchdog age the (legitimately
                # long) out-of-core dispatch against the completion
                # bound instead of the per-dispatch one
                self._guarded(lambda r=req: self.run_solo(r), [req],
                              "spill" if req.spill else "solo")
                continue
            batch = [req]
            total = req.n
            closed_by = "keys" if total >= self.batch_keys else (
                "solo" if self.window_s <= 0 else "window")
            if self.window_s > 0:
                # the window closes at the EARLIEST member deadline,
                # less dispatch headroom (10% of the member's remaining
                # budget, >= 2 ms): holding a deadline-carrying request
                # open for the full window could expire it in the pack,
                # and closing exactly AT the deadline would hand the
                # dispatch a request already dead on arrival
                now = time.monotonic()
                close = now + self.window_s
                #: True once a member deadline shortened the window —
                #: a time-based close is then a "deadline" close, not a
                #: full "window" (the plan's trigger must say which)
                deadline_clamped = False
                if req.deadline is not None:
                    dc = self._deadline_close(req, now)
                    if dc < close:
                        close, deadline_clamped = dc, True
                while total < self.batch_keys:
                    slack = close - time.monotonic()
                    if slack <= 0:
                        if deadline_clamped:
                            closed_by = "deadline"
                        break
                    try:
                        nxt = self._q.get(timeout=slack)
                    except queue.Empty:
                        if deadline_clamped:
                            closed_by = "deadline"
                        break
                    if nxt is _STOP:
                        self._stopping = True
                        continue
                    cand = nxt  # type: ignore[assignment]
                    if not isinstance(cand, ServeRequest):
                        continue
                    if self._cancel_if_expired(cand, "queue"):
                        continue
                    if (cand.batchable and cand.faults is None
                            and cand.dtype == req.dtype
                            and total + cand.n <= self.batch_keys):
                        batch.append(cand)
                        total += cand.n
                        if total >= self.batch_keys:
                            closed_by = "keys"
                        if cand.deadline is not None:
                            dc = self._deadline_close(cand,
                                                      time.monotonic())
                            if dc < close:
                                close, deadline_clamped = dc, True
                    else:
                        # incompatible (dtype mix, solo-only, or the
                        # batch would overflow): set it aside for the
                        # next iteration and close this batch — simple
                        # FIFO fairness beats clever repacking at a
                        # 2 ms window
                        with self._pending_lock:
                            self._pending.append(cand)
                        # a same-dtype batchable candidate can only be
                        # deferred by the capacity bound — that is a
                        # "keys" (full) close, not an incompatibility
                        closed_by = ("keys" if (cand.batchable
                                                and cand.faults is None
                                                and cand.dtype == req.dtype)
                                     else "incompatible")
                        break
            # final deadline sweep AFTER the window: members that
            # expired while the pack collected are cancelled here, so
            # the batches/batched_requests tallies below count only
            # work actually handed to the executor (they must
            # reconcile with the serve.batch span stream)
            batch = [r for r in batch
                     if not self._cancel_if_expired(r, "dispatch")]
            if not batch:
                continue
            # window decision record (ISSUE 12): why this pack closed
            # and what it will actually dispatch — keys recounted AFTER
            # the deadline sweep above, or the batch plan's predicted
            # bucket/waste would be computed from members that were
            # cancelled and never shipped
            window = {"members": len(batch),
                      "keys": sum(r.n for r in batch),
                      "closed_by": closed_by}
            for r in batch:
                r.window = window
            # window 0 degenerates to per-request dispatch — still
            # through the packed path, so the executor cache serves the
            # sequential mode warm too (the A/B the selftest measures)
            self.batches += 1
            self.batched_requests += len(batch)
            self._guarded(lambda b=batch: self.run_batch(b), batch,
                          "batch")
