"""Multi-tenant batching: pack concurrent small requests into one dispatch.

One dispatch of the packed program costs roughly the same wall time as a
dispatch for a single small request — the fixed per-launch overhead
(host staging, program launch, result sync) dominates at small N.  The
batcher therefore runs ONE dispatch thread that, on picking up a
batchable request, keeps collecting compatible requests for at most
``SORT_SERVE_BATCH_WINDOW_MS`` (or until ``SORT_SERVE_BATCH_KEYS`` keys
are packed) and hands the group to the server's batch runner — under
closed-loop small-request load, K tenants share one device launch
instead of paying K.

Compatibility is dtype equality (segments share one packed word
layout).  Requests that are too large, carry a per-request fault spec,
or arrive with batching disabled (window 0) dispatch alone, in arrival
order, on the same thread — a single dispatcher also serializes device
access, so batched and solo work never contend for the mesh.

The dispatch thread is the only thread that touches JAX; request
handler threads only enqueue and wait on per-request completion events.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Callable

import numpy as np

if TYPE_CHECKING:
    pass

#: Sentinel that tells the dispatch thread to finish the queue and exit.
_STOP = object()


@dataclass
class ServeRequest:
    """One admitted request riding the dispatch queue.  The handler
    thread blocks on ``done``; the dispatch thread fills exactly one of
    ``result`` / ``error`` and sets it."""

    arr: np.ndarray
    dtype: np.dtype
    algo: str
    batchable: bool
    faults: str | None = None
    #: wire/client-minted request trace id (ISSUE 10) — stamped on every
    #: span this request touches via ``spans.trace_context``.
    trace_id: str = ""
    t_enq: float = field(default_factory=time.perf_counter)
    done: threading.Event = field(default_factory=threading.Event)
    result: np.ndarray | None = None
    error: tuple[str, str] | None = None    # (code, detail)
    batched: bool = False
    bucket: int | None = None
    #: packed-dispatch identity this request shared (None for solo).
    batch_id: str | None = None
    #: seconds between enqueue and dispatch pickup (the queue wait the
    #: serve.request span + live histogram report).
    queue_s: float | None = None

    @property
    def n(self) -> int:
        return int(self.arr.size)

    def picked_up(self) -> None:
        """Dispatch-thread pickup marker: fixes the queue wait."""
        if self.queue_s is None:
            self.queue_s = time.perf_counter() - self.t_enq

    def complete(self, out: np.ndarray, batched: bool,
                 bucket: int | None, batch_id: str | None = None) -> None:
        self.result = out
        self.batched = batched
        self.bucket = bucket
        self.batch_id = batch_id
        self.done.set()

    def fail(self, code: str, detail: str) -> None:
        self.error = (code, detail)
        self.done.set()


class Batcher:
    """The dispatch loop.  ``run_batch(requests)`` / ``run_solo(request)``
    are the server's executors; both must complete/fail every request
    they are handed (the loop itself never touches results)."""

    def __init__(self, run_batch: Callable[[list[ServeRequest]], None],
                 run_solo: Callable[[ServeRequest], None],
                 window_s: float, batch_keys: int) -> None:
        self.run_batch = run_batch
        self.run_solo = run_solo
        self.window_s = float(window_s)
        self.batch_keys = int(batch_keys)
        self._q: "queue.Queue[object]" = queue.Queue()
        self._pending: list[ServeRequest] = []  # incompatibles set aside
        self._stopping = False
        self.batches = 0
        self.batched_requests = 0
        self.solo_requests = 0
        self._thread = threading.Thread(target=self._loop,
                                        name="serve-dispatch", daemon=True)
        self._thread.start()

    def submit(self, req: ServeRequest) -> None:
        self._q.put(req)

    def _guarded(self, thunk: "Callable[[], None]",
                 reqs: list[ServeRequest]) -> None:
        """Run an executor under a blanket guard: the dispatch thread
        must survive ANY executor failure (the executors are typed
        internally, but e.g. a span-stream disk-full OSError escaping
        would otherwise kill the only thread that completes requests,
        wedging every future request for the full completion timeout).
        Requests the executor never completed fail typed instead."""
        try:
            thunk()
        except BaseException as e:  # noqa: BLE001 — thread survival
            for r in reqs:
                if not r.done.is_set():
                    r.fail("internal",
                           f"dispatcher error: {type(e).__name__}: {e}")

    def stop(self, timeout: float = 60.0) -> None:
        """Finish everything already enqueued, then stop the dispatch
        thread (the drain path: admission already rejects new work)."""
        self._q.put(_STOP)
        self._thread.join(timeout)

    # -- dispatch loop ------------------------------------------------
    def _next(self, timeout: float | None) -> object | None:
        if self._pending:
            return self._pending.pop(0)
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def _loop(self) -> None:
        while True:
            item = self._next(timeout=0.1 if self._stopping else None)
            if item is None:
                if self._stopping and self._q.empty():
                    return
                continue
            if item is _STOP:
                self._stopping = True
                continue
            req = item  # type: ignore[assignment]
            if not isinstance(req, ServeRequest):
                continue
            if not req.batchable or req.faults is not None:
                self.solo_requests += 1
                self._guarded(lambda r=req: self.run_solo(r), [req])
                continue
            batch = [req]
            total = req.n
            if self.window_s > 0:
                deadline = time.monotonic() + self.window_s
                while total < self.batch_keys:
                    slack = deadline - time.monotonic()
                    if slack <= 0:
                        break
                    try:
                        nxt = self._q.get(timeout=slack)
                    except queue.Empty:
                        break
                    if nxt is _STOP:
                        self._stopping = True
                        continue
                    cand = nxt  # type: ignore[assignment]
                    if (isinstance(cand, ServeRequest) and cand.batchable
                            and cand.faults is None
                            and cand.dtype == req.dtype
                            and total + cand.n <= self.batch_keys):
                        batch.append(cand)
                        total += cand.n
                    else:
                        # incompatible (dtype mix, solo-only, or the
                        # batch would overflow): set it aside for the
                        # next iteration and close this batch — simple
                        # FIFO fairness beats clever repacking at a
                        # 2 ms window
                        self._pending.append(cand)  # type: ignore[arg-type]
                        break
            # window 0 degenerates to per-request dispatch — still
            # through the packed path, so the executor cache serves the
            # sequential mode warm too (the A/B the selftest measures)
            self.batches += 1
            self.batched_requests += len(batch)
            self._guarded(lambda b=batch: self.run_batch(b), batch)
