"""Sort-as-a-service (ISSUE 8): the persistent serving layer.

"Millions of users" means the process must outlive one sort.  The CLI
pays process start + mesh setup + jit compile on EVERY invocation —
milliseconds of device work under seconds of fixed overhead for small
requests.  This package is the layer that amortizes all of it:

* :mod:`~mpitest_tpu.serve.executor_cache` — AOT-compiles and memoizes
  executables per (shape-bucket, dtype, word-count, mesh) with
  power-of-two shape bucketing, so warm requests never touch the
  compiler; startup prewarm degrades to jit-on-first-use behind the
  bounded topology probe (``utils/topology_probe.py``) instead of
  wedging on a tunnel-less TPU image.
* :mod:`~mpitest_tpu.serve.admission` — bounds in-flight requests and
  payload bytes; over-limit requests get a TYPED backpressure
  rejection, never a queue that grows until the process dies.
* :mod:`~mpitest_tpu.serve.batching` — packs concurrent small requests
  into one segmented device dispatch
  (:mod:`mpitest_tpu.models.segmented`) within a bounded window and
  splits the result per tenant.
* :mod:`~mpitest_tpu.serve.server` — the transport + orchestration:
  a newline-JSON-header/raw-payload TCP protocol, per-request
  supervision (a poisoned request yields a typed per-request error,
  never server death), ``serve.*`` spans for the report CLI's p50/p99
  SLO tables, and graceful SIGTERM drain.
* :mod:`~mpitest_tpu.serve.client` — the matching client used by
  ``bench/serve_load.py``, the tests, and anything else that talks to
  the server.

Entry point: ``drivers/sort_server.py``.  Load generator / regression
gate: ``bench/serve_load.py`` via ``make serve-selftest``.
"""

__all__ = [
    "AdmissionControl", "AdmissionReject", "ExecutorCache", "ServerCore",
    "SortServer", "bucket_for",
]

#: Lazy exports (PEP 562): ``serve.client`` must stay importable
#: without dragging in the server stack (jax, the models layer) —
#: load generators and remote clients import only the wire protocol.
_EXPORTS = {
    "AdmissionControl": "mpitest_tpu.serve.admission",
    "AdmissionReject": "mpitest_tpu.serve.admission",
    "ExecutorCache": "mpitest_tpu.serve.executor_cache",
    "bucket_for": "mpitest_tpu.serve.executor_cache",
    "ServerCore": "mpitest_tpu.serve.server",
    "SortServer": "mpitest_tpu.serve.server",
}


def __getattr__(name: str):
    if name in _EXPORTS:
        import importlib

        return getattr(importlib.import_module(_EXPORTS[name]), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
