"""Live telemetry endpoints + device profiling hook for the sort server.

The serving process must be observable WHILE it serves (ISSUE 10): a
side-port HTTP server (``SORT_METRICS_PORT``) exposes

* ``GET /metrics`` — Prometheus text exposition of the live registry
  (``utils/metrics_live.py``): request/error/latency, queue wait, batch
  occupancy, executor-cache hit/miss, verify overhead, retry/fault
  counters, per-rank exchange-balance gauges;
* ``GET /healthz`` — liveness JSON; HTTP 200 while serving, 503 once
  draining (load balancers stop routing before SIGTERM finishes);
* ``GET /varz`` — configuration + internal state: every explicitly-set
  knob, the mesh, executor-cache/admission/batcher/flight-recorder
  state;
* ``GET /flightrecorder`` — the in-memory span ring as span-schema
  JSONL (``?dump=1`` also writes a timestamped artifact to
  ``SORT_FLIGHT_RECORDER_DIR`` and reports its path);
* ``GET /profile?n=K`` — arm a ``jax.profiler`` capture for the next K
  dispatches (Perfetto/TensorBoard-compatible trace into
  ``SORT_PROFILE``, else ``<flight dir>/profile``).

The handler threads only read shared state (one lock-cheap registry
render, one deque snapshot) — a scrape can never block a dispatch.

:class:`ProfileHook` is the dispatch-side half: endpoint-armed or
every-Nth (``SORT_PROFILE_EVERY``) capture around exactly one dispatch,
recorded as a ``serve.profile`` span event so captures are visible in
the same stream as everything else.  jax.profiler failures degrade to a
logged no-op — profiling must never fail a request.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import TYPE_CHECKING, Any, Iterator
from urllib.parse import parse_qs, urlparse

from mpitest_tpu.utils import flight_recorder, knobs
from mpitest_tpu.utils.metrics_live import PROM_CONTENT_TYPE

if TYPE_CHECKING:
    from mpitest_tpu.serve.server import ServerCore
    from mpitest_tpu.utils.spans import SpanLog


class ProfileHook:
    """Decides, per dispatch, whether to wrap it in a jax.profiler
    capture; owns the armed-count (endpoint) and every-Nth
    (``SORT_PROFILE_EVERY``) triggers."""

    def __init__(self, spans: "SpanLog") -> None:
        self.spans = spans
        self.every = knobs.get("SORT_PROFILE_EVERY")
        self.logdir = knobs.get("SORT_PROFILE") or os.path.join(
            knobs.get("SORT_FLIGHT_RECORDER_DIR"), "profile")
        self.captures = 0
        self.failed = 0
        self._armed = 0
        self._seq = 0
        self._lock = threading.Lock()

    def arm(self, n: int = 1) -> int:
        """Endpoint trigger: capture the next ``n`` dispatches."""
        with self._lock:
            self._armed += max(0, int(n))
            return self._armed

    def _should_capture(self) -> str | None:
        with self._lock:
            self._seq += 1
            if self._armed > 0:
                self._armed -= 1
                return "endpoint"
            if self.every and self._seq % self.every == 0:
                return "every"
        return None

    @contextlib.contextmanager
    def maybe_capture(self) -> Iterator[bool]:
        """Wrap one dispatch; yields True when a capture is live."""
        trigger = self._should_capture()
        if trigger is None:
            yield False
            return
        logdir = os.path.join(self.logdir, f"capture-{self._seq:05d}")
        try:
            import jax

            os.makedirs(logdir, exist_ok=True)
            jax.profiler.start_trace(logdir)
        except Exception:  # noqa: BLE001 — profiling never fails a request
            self.failed += 1
            yield False
            return
        t0 = time.perf_counter()
        try:
            yield True
        finally:
            try:
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001
                self.failed += 1
            self.captures += 1
            self.spans.record("serve.profile", t0,
                              time.perf_counter() - t0,
                              logdir=logdir, trigger=trigger,
                              seq=self._seq)

    def state(self) -> dict:
        with self._lock:
            return {"every": self.every, "logdir": self.logdir,
                    "armed": self._armed, "captures": self.captures,
                    "failed": self.failed}


def plan_snapshot(rows: "list[dict]") -> dict:
    """Rolling decision snapshot (ISSUE 12) from the flight-recorder
    ring: fold the ring's recent ``sort.plan`` spans into plan counts
    per algorithm, mean/max regret per decision, and the latest plan's
    compact view — the traffic profile the ROADMAP item-5 planner will
    consume, already shaped for ``/varz``."""
    from mpitest_tpu.models.plan import fold_decision_stats

    plans = [r for r in rows if r.get("name") == "sort.plan"]
    by_algo: dict[str, int] = {}
    total_regret = 0.0
    for p in plans:
        attrs = p.get("attrs") or {}
        algo = str(attrs.get("algo", "?"))
        by_algo[algo] = by_algo.get(algo, 0) + 1
        total_regret += float(attrs.get("regret", 0.0) or 0.0)
    dec = fold_decision_stats([p.get("attrs") or {} for p in plans])
    out: dict = {
        "plans": len(plans),
        "by_algo": by_algo,
        "mean_regret": (round(total_regret / len(plans), 6)
                        if plans else 0.0),
        "decisions": {
            name: {"count": row["count"],
                   "mean_regret": round(row["regret_sum"] / row["count"],
                                        6),
                   "max_regret": round(row["regret_max"], 6)}
            for name, row in sorted(dec.items())},
    }
    if plans:
        last = plans[-1].get("attrs") or {}
        out["last"] = {"algo": last.get("algo"),
                       "regret": last.get("regret"),
                       "profile": last.get("profile")}
    return out


def _set_knobs() -> dict[str, str]:
    """Every registered knob explicitly set in this process's
    environment (raw values) — the /varz configuration view.  Defaults
    are documented in README; varz shows what this server was told."""
    out = {}
    for k in knobs.iter_knobs():
        raw = knobs.get_raw(k.name)
        if raw is not None:
            out[k.name] = raw
    return out


class _Handler(BaseHTTPRequestHandler):
    server: "TelemetryServer"  # type: ignore[assignment]

    #: silence the default per-request stderr logging (a scrape every
    #: few seconds would swamp the server log)
    def log_message(self, format: str, *args: Any) -> None:  # noqa: A002
        pass

    def _reply(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, code: int, obj: object) -> None:
        self._reply(code, (json.dumps(obj, indent=1) + "\n").encode(),
                    "application/json")

    def do_GET(self) -> None:  # noqa: N802 — http.server contract
        try:
            url = urlparse(self.path)
            route = url.path.rstrip("/") or "/"
            if route == "/metrics":
                body = self.server.core.metrics.render_prom().encode()
                self._reply(200, body, PROM_CONTENT_TYPE)
            elif route == "/healthz":
                self._healthz()
            elif route == "/varz":
                self._varz()
            elif route == "/flightrecorder":
                self._flightrecorder(parse_qs(url.query))
            elif route == "/profile":
                self._profile(parse_qs(url.query))
            elif route == "/alerts":
                self._alerts()
            else:
                self._json(404, {"error": f"unknown path {route!r}",
                                 "routes": ["/metrics", "/healthz",
                                            "/varz", "/flightrecorder",
                                            "/profile", "/alerts"]})
        except BrokenPipeError:
            pass
        except Exception as e:  # noqa: BLE001 — a scrape bug must not kill
            try:                # the handler thread pool
                self._json(500, {"error": f"{type(e).__name__}: {e}"})
            except OSError:
                pass

    def _healthz(self) -> None:
        core = self.server.core
        draining = core.admission.draining
        breaker = core.breaker.state
        # 503 while draining OR while the breaker is open/half-open
        # (ISSUE 11): load balancers must stop routing to a server
        # whose dispatch is wedged, not just one that is shutting down.
        healthy = not draining and breaker == "closed"
        self._json(200 if healthy else 503, {
            "ok": healthy,
            "draining": draining,
            "breaker": breaker,
            "uptime_s": round(time.time() - core.started, 3),
            "inflight": core.admission.inflight,
            "requests_ok": core.requests_ok,
            "requests_err": core.requests_err,
            # live thread census: the chaos gate asserts handler
            # threads are reclaimed after every wire-fault cell
            "threads": threading.active_count(),
            "pid": os.getpid(),
        })

    def _varz(self) -> None:
        core = self.server.core
        mesh_devs = list(core.mesh.devices.flat)
        rec = flight_recorder.get()
        self._json(200, {
            "knobs_set": _set_knobs(),
            "mesh": {"devices": len(mesh_devs),
                     "platform": mesh_devs[0].platform if mesh_devs
                     else "?"},
            "cache": core.cache.snapshot(),
            "admission": core.admission.snapshot(),
            "batcher": {"batches": core.batcher.batches,
                        "batched_requests": core.batcher.batched_requests,
                        "solo_requests": core.batcher.solo_requests,
                        "deadline_cancelled":
                            core.batcher.deadline_cancelled,
                        "window_s": core.batcher.window_s,
                        "batch_keys": core.batcher.batch_keys},
            "watchdog": core.watchdog.snapshot(),
            "flight_recorder": {"capacity": rec.capacity,
                                "recorded": rec.recorded,
                                "dumps": rec.dumps,
                                "dir": rec.directory},
            # rolling decision snapshot (ISSUE 12), fed from the ring —
            # the traffic profile the self-tuning planner consumes
            "plans": plan_snapshot(rec.snapshot()),
            # self-tuning planner state (ISSUE 14): mode + the serve
            # tuner's rolling-mix view and retune history
            "planner": {
                "mode": core.planner_mode,
                "tuner": (core.tuner.snapshot()
                          if core.tuner is not None else None),
                "window_retunes": core.batcher.window_retunes,
            },
            "profiler": core.profiler.state(),
            "requests": {"ok": core.requests_ok,
                         "err": core.requests_err},
            "uptime_s": round(time.time() - core.started, 3),
        })

    def _alerts(self) -> None:
        # streaming-sentinel snapshot (ISSUE 16): alert history + the
        # rolling-window series state; a router balances on this
        sentinel = getattr(self.server.core, "sentinel", None)
        if sentinel is None:
            self._json(200, {"enabled": False, "alerts": [],
                             "alerts_total": 0})
            return
        self._json(200, sentinel.snapshot())

    def _flightrecorder(self, query: dict) -> None:
        rec = flight_recorder.get()
        if query.get("dump", ["0"])[0] == "1":
            path = rec.dump("endpoint")
            self._json(200 if path else 409,
                       {"dumped": path is not None, "path": path,
                        "spans": len(rec.ring)})
            return
        body = "\n".join(json.dumps(d) for d in rec.snapshot())
        self._reply(200, (body + "\n").encode() if body else b"",
                    "application/jsonl")

    def _profile(self, query: dict) -> None:
        try:
            n = int(query.get("n", ["1"])[0])
        except ValueError:
            self._json(400, {"error": "n must be an integer"})
            return
        if not 1 <= n <= 1000:
            self._json(400, {"error": "n must be in [1, 1000]"})
            return
        armed = self.server.core.profiler.arm(n)
        self._json(200, {"armed": armed,
                         "logdir": self.server.core.profiler.logdir})


class TelemetryServer(ThreadingHTTPServer):
    """The side-port HTTP server.  Never binds the wire-protocol port;
    ``SORT_METRICS_PORT=0`` picks an ephemeral port (printed by the
    driver), ``-1`` disables construction entirely (the driver's
    choice, not this class's)."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, core: "ServerCore", host: str, port: int) -> None:
        super().__init__((host, port), _Handler)
        self.core = core

    @property
    def bound_port(self) -> int:
        return int(self.server_address[1])

    def start(self) -> None:
        t = threading.Thread(target=self.serve_forever,
                             name="serve-telemetry", daemon=True)
        t.start()
