"""Dispatch watchdog + circuit breaker: the server degrades loudly.

The serving stack's known worst failure mode is a dispatch that never
returns: the TPU-compiler tunnel hang blocks the single dispatch thread
(sometimes HOLDING THE GIL — see ``utils/topology_probe.py`` for the
startup-time variant), and before this module the server just... sat
there.  Handler threads piled up on completion events, admission stayed
full, ``/healthz`` said 200, and the operator learned about it from
users.  PR 8 made the wedge *visible*; this module (ISSUE 11) makes it
*bounded*:

* :class:`DispatchWatchdog` — a monotonic heartbeat on the dispatch
  thread (``Batcher._guarded`` brackets every executor call).  When a
  dispatch exceeds ``SORT_SERVE_DISPATCH_TIMEOUT_S`` the watchdog
  dumps the flight recorder (the incident artifact, stuck trace_ids
  included), fails every still-queued request typed ``internal``, and
  trips the breaker.  One trip per stuck dispatch — a 10-minute hang
  is one incident, not 600.
* :class:`CircuitBreaker` — while open, ``/healthz`` serves 503 (load
  balancers stop routing) and admission turns into FAST typed
  rejections (``backpressure`` with reason ``breaker``) instead of
  letting clients queue behind a corpse.  After
  ``SORT_SERVE_BREAKER_BACKOFF_S`` the breaker half-opens: the
  watchdog sends ONE tiny probe sort through the ordinary dispatch
  path; success closes the breaker, failure re-opens it with doubled
  backoff (capped).  Recovery is automatic the moment the dispatch
  thread comes back — no operator restart required for a transient
  wedge.

Every transition is a registered ``serve.watchdog`` span event
(trip/probe/recovered/reopen) riding the ordinary trace stream, and
``sort_serve_watchdog_trips_total`` counts trips in ``/metrics`` — the
breaker's whole audit trail is one ``report.py`` run away.
"""

from __future__ import annotations

import threading
import time
from typing import TYPE_CHECKING

import numpy as np

from mpitest_tpu.utils import flight_recorder

if TYPE_CHECKING:
    from mpitest_tpu.serve.server import ServerCore

#: Breaker backoff growth is capped at this multiple of the base — a
#: long outage probes every few minutes, never backs off to "never".
MAX_BACKOFF_FACTOR = 8.0

#: Probe request size: big enough to exercise a real dispatch, small
#: enough to be free (one cached-bucket packed sort).
PROBE_KEYS = 64


class CircuitBreaker:
    """Three-state breaker: ``closed`` (normal) -> ``open`` (fast
    rejections) -> ``half_open`` (one probe in flight) -> closed or
    back to open with doubled backoff.  All transitions under one lock;
    readers (`engaged`, `state`) are lock-cheap."""

    def __init__(self, backoff_s: float) -> None:
        self.base_backoff_s = float(backoff_s)
        self._lock = threading.Lock()
        self._state = "closed"
        self._backoff_s = self.base_backoff_s
        self._open_until = 0.0
        self.trips = 0
        self.recoveries = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def engaged(self) -> bool:
        """True while admission must fast-reject (open OR half-open —
        during a probe, normal traffic stays out)."""
        with self._lock:
            return self._state != "closed"

    def trip(self) -> bool:
        """Open the breaker; returns False when it was already open
        (the caller skips duplicate incident handling)."""
        with self._lock:
            if self._state != "closed":
                return False
            self._state = "open"
            self._backoff_s = self.base_backoff_s
            self._open_until = time.monotonic() + self._backoff_s
            self.trips += 1
            return True

    def ready_to_probe(self) -> bool:
        """True when the open backoff elapsed and a probe should fly;
        flips the state to half_open (one caller wins)."""
        with self._lock:
            if self._state != "open" or time.monotonic() < self._open_until:
                return False
            self._state = "half_open"
            return True

    def probe_succeeded(self) -> None:
        with self._lock:
            self._state = "closed"
            self._backoff_s = self.base_backoff_s
            self.recoveries += 1

    def probe_failed(self) -> None:
        """Back to open with doubled (capped) backoff."""
        with self._lock:
            self._state = "open"
            self._backoff_s = min(self._backoff_s * 2.0,
                                  self.base_backoff_s * MAX_BACKOFF_FACTOR)
            self._open_until = time.monotonic() + self._backoff_s

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self._state, "trips": self.trips,
                    "recoveries": self.recoveries,
                    "backoff_s": self._backoff_s,
                    "open_for_s": (round(self._open_until
                                         - time.monotonic(), 3)
                                   if self._state == "open" else 0.0)}


class DispatchWatchdog:
    """The monitor thread.  Started explicitly (``start()``) by the
    server driver and the tests that want it — ``ServerCore`` alone
    never spawns it, so in-process test cores stay thread-clean."""

    def __init__(self, core: "ServerCore", timeout_s: float,
                 breaker: CircuitBreaker) -> None:
        self.core = core
        self.timeout_s = float(timeout_s)
        self.breaker = breaker
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        #: heartbeat identity (start timestamp) of the dispatch we
        #: already tripped on — one trip per stuck dispatch.
        self._tripped_for: float | None = None
        self._probe_seq = 0

    @property
    def enabled(self) -> bool:
        return self.timeout_s > 0

    def start(self) -> None:
        if not self.enabled or self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop,
                                        name="serve-watchdog", daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    # -- the loop -----------------------------------------------------
    def _poll_interval(self) -> float:
        return max(0.05, min(1.0, self.timeout_s / 4.0))

    def _loop(self) -> None:
        while not self._stop.wait(self._poll_interval()):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 — the watchdog must not die
                pass           # of a telemetry hiccup mid-incident

    def _tick(self) -> None:
        hb = self.core.batcher.inflight_dispatch()
        if hb is not None:
            age_s, kind, trace_ids = hb
            started_key = time.monotonic() - age_s
            # a spill (out-of-core) dispatch is legitimately long — it
            # streams many small device sorts + a disk merge — so it
            # ages against the COMPLETION bound, not the per-dispatch
            # wedge bound (a wedged device inside it still types out
            # through the per-chunk supervisor)
            bound = (max(self.timeout_s,
                         float(self.core.completion_timeout_s))
                     if kind == "spill" else self.timeout_s)
            if age_s >= bound and (
                    self._tripped_for is None
                    or abs(started_key - self._tripped_for) > 0.5):
                self._tripped_for = started_key
                self._trip(age_s, kind, trace_ids)
        else:
            self._tripped_for = None
        if self.breaker.ready_to_probe():
            self._probe()

    def _event(self, event: str, **attrs: object) -> None:
        self.core.tracer.spans.record("serve.watchdog",
                                      time.perf_counter(), 0.0,
                                      event=event, **attrs)

    def _trip(self, age_s: float, kind: str,
              trace_ids: list[str]) -> None:
        """The incident path, gated on the breaker transition: a wedge
        while the breaker is ALREADY open (e.g. the half-open probe's
        own dispatch wedging) is the SAME incident — no second trip
        event, so `sort_serve_watchdog_trips_total`, the report's trip
        count, `breaker.trips` and the driver exit line all agree."""
        if not self.breaker.trip():
            return
        # audit span first (so the flight dump carries it), then the
        # artifact and the queue purge
        self._event("trip", age_s=round(age_s, 3), kind=kind,
                    trace_ids=list(trace_ids),
                    timeout_s=self.timeout_s)
        self.core.tracer.verbose(
            f"watchdog: {kind} dispatch stuck for {age_s:.1f}s "
            f"(> {self.timeout_s:g}s; trace_ids={trace_ids}); tripping "
            "the circuit breaker")
        flight_recorder.dump_on_error("watchdog")
        failed = self.core.batcher.fail_queued(
            "internal",
            f"dispatch watchdog tripped: a {kind} dispatch exceeded "
            f"{self.timeout_s:g}s; queued work cancelled")
        if failed:
            self.core.tracer.verbose(
                f"watchdog: failed {failed} queued request(s) typed "
                "'internal'")

    def _probe(self) -> None:
        """Half-open probe: one tiny sort through the REAL dispatch
        path.  Completion proves the dispatch thread is alive again."""
        from mpitest_tpu.serve.batching import ServeRequest

        self._probe_seq += 1
        tid = f"watchdog-probe-{self._probe_seq}"
        self._event("probe", trace_id=tid)
        req = ServeRequest(
            arr=np.arange(PROBE_KEYS, 0, -1, dtype=np.int32),
            dtype=np.dtype(np.int32), algo=self.core.default_algo,
            batchable=True, trace_id=tid)
        self.core.batcher.submit(req)
        ok = req.done.wait(max(self.timeout_s, 1.0)) and req.error is None
        if ok:
            self.breaker.probe_succeeded()
            self._event("recovered", trace_id=tid)
            self.core.tracer.verbose(
                "watchdog: probe sort completed; breaker closed")
        else:
            self.breaker.probe_failed()
            self._event("reopen", trace_id=tid,
                        detail=(req.error[1] if req.error
                                else "probe timed out"))
            self.core.tracer.verbose(
                "watchdog: probe failed; breaker re-opened "
                f"(backoff {self.breaker.snapshot()['backoff_s']:g}s)")

    def snapshot(self) -> dict:
        return {"enabled": self.enabled, "timeout_s": self.timeout_s,
                "running": self._thread is not None,
                "probes": self._probe_seq,
                **{f"breaker_{k}": v
                   for k, v in self.breaker.snapshot().items()}}
