"""Clients for the sort server's ``sortserve.v1`` wire protocol.

Two tiers (both used by ``bench/serve_load.py``, the tests, and
anything else that wants a remote sort):

* :class:`ServeClient` — one TCP connection, the raw protocol.  May
  issue many requests back to back (the server keeps the connection
  open across requests); a typed error response comes back as a
  :class:`ServeReply` with ``ok=False`` and the server's stable
  ``error`` code — it never raises on a *server-side* rejection, only
  on transport failure.  Connect and read timeouts bound every wire
  wait (ISSUE 11): a half-dead server costs seconds, not forever.
* :class:`ResilientClient` — the production-shaped wrapper (ISSUE 11):
  bounded retry with exponential backoff + jitter on connect errors
  and typed-RETRYABLE responses (``backpressure``, ``draining`` — the
  codes the server emits when asking exactly for that), plus optional
  request **hedging**: when a reply has not landed within
  ``hedge_after_s``, a second attempt races it on a fresh connection
  and the first reply that passes the client-side fingerprint check
  wins (safe because sort is idempotent — both attempts compute the
  same bytes — and the loser is simply discarded).  The measured
  effect is the ROADMAP item-3 promise: injected-tail p99 cut by the
  hedge (``bench/chaos_serve_selftest.py`` gates it).

This module never imports the server stack (jax, the models layer) —
load generators and remote clients need only the wire protocol.
"""

from __future__ import annotations

import json
import os
import queue
import random
import socket
import threading
import time
from dataclasses import dataclass

import numpy as np

#: Must match serve/server.py (kept literal here so the client is
#: importable without the server stack).
WIRE_SCHEMA = "sortserve.v1"

#: Typed error codes the server emits when it WANTS the client to come
#: back later — the retry allowlist.  Anything else (bad_request,
#: integrity, ...) retries would only repeat.
RETRYABLE_ERRORS = ("backpressure", "draining")


@dataclass
class ServeReply:
    """One response: ``ok`` + sorted ``arr``, or a typed error."""

    ok: bool
    header: dict
    arr: np.ndarray | None = None
    #: record requests (ISSUE 15): the payload permuted into key order,
    #: an ``(n, payload_bytes)`` uint8 matrix.
    payload: np.ndarray | None = None

    @property
    def error(self) -> str | None:
        return None if self.ok else str(self.header.get("error"))

    @property
    def spilled(self) -> bool:
        """True when the server served this request from the
        out-of-core spill tier (ISSUE 15)."""
        return bool(self.header.get("spilled"))

    @property
    def detail(self) -> str:
        return str(self.header.get("detail", ""))

    @property
    def trace_id(self) -> str | None:
        """The request's end-to-end trace id, echoed by the server
        (ISSUE 10) — the key ``report.py --trace-id`` reconstructs the
        request from."""
        v = self.header.get("trace_id")
        return str(v) if v is not None else None

    @property
    def plan(self) -> dict | None:
        """Compact decision digest of the dispatch that served this
        request (ISSUE 12): algo, negotiated cap, restage verdict,
        regret — the client-visible decision-drift signal."""
        v = self.header.get("plan")
        return v if isinstance(v, dict) else None


class ServeClient:
    """One persistent connection to a sort server.  ``timeout`` bounds
    every read/write on the socket; ``connect_timeout`` (default: the
    read timeout) bounds the initial connect."""

    def __init__(self, host: str, port: int, timeout: float = 120.0,
                 connect_timeout: float | None = None) -> None:
        self.sock = socket.create_connection(
            (host, port),
            timeout=timeout if connect_timeout is None else connect_timeout)
        self.sock.settimeout(timeout)
        self._rfile = self.sock.makefile("rb")

    def close(self) -> None:
        try:
            self._rfile.close()
        finally:
            self.sock.close()

    def __enter__(self) -> "ServeClient":
        return self

    def __exit__(self, *exc: object) -> None:
        self.close()

    def sort(self, arr: np.ndarray, algo: str | None = None,
             faults: str | None = None,
             trace_id: str | None = None,
             deadline_ms: float | None = None,
             payload: np.ndarray | bytes | None = None,
             dataset_id: str | None = None) -> ServeReply:
        """Send one sort request; block for the reply.  A ``trace_id``
        is minted here when the caller supplies none — the client IS
        the wire layer, so every request carries one end to end (the
        server echoes it in the response header).  ``deadline_ms``
        rides the header (ISSUE 11): the server cancels the request
        typed ``deadline_exceeded`` if the budget expires before
        dispatch.  ``payload`` (ISSUE 15) turns the request into a
        record sort: bytes (``n * width``) or an ``(n, width)`` uint8
        matrix of per-record payloads, returned permuted into key
        order on ``reply.payload``.  ``dataset_id`` (ISSUE 18) is a
        stable client-chosen id keying the spill tier's journaled
        manifest: a retried over-memory request reusing it resumes at
        the merge phase (``resumed: true`` in the reply plan digest)."""
        arr = np.ascontiguousarray(arr).reshape(-1)
        n = int(arr.size)
        hdr: dict = {"v": WIRE_SCHEMA, "dtype": arr.dtype.name,
                     "n": n,
                     "trace_id": trace_id or os.urandom(8).hex()}
        pay_bytes = b""
        if payload is not None:
            if isinstance(payload, (bytes, bytearray, memoryview)):
                pay_bytes = bytes(payload)
            else:
                # raw little-endian BYTES of the array — the same
                # canonical form as the library's as_payload_matrix (a
                # uint64 row-id array is a valid 8-byte payload as-is).
                # A value-cast to uint8 here would silently truncate
                # every payload element above 255.
                pay_bytes = np.ascontiguousarray(
                    np.asarray(payload)).tobytes()
            if n == 0 or len(pay_bytes) % n:
                raise ValueError(
                    f"payload of {len(pay_bytes)} bytes is not a "
                    f"multiple of the key count {n}")
            hdr["payload_bytes"] = len(pay_bytes) // n
        if algo is not None:
            hdr["algo"] = algo
        if faults is not None:
            hdr["faults"] = faults
        if deadline_ms is not None:
            hdr["deadline_ms"] = float(deadline_ms)
        if dataset_id is not None:
            hdr["dataset_id"] = dataset_id
        self.sock.sendall(json.dumps(hdr).encode("utf-8") + b"\n"
                          + arr.tobytes() + pay_bytes)
        line = self._rfile.readline()
        if not line:
            raise ConnectionError("server closed the connection "
                                  "without a response header")
        resp = json.loads(line.decode("utf-8"))
        if not resp.get("ok"):
            return ServeReply(False, resp)
        rn = int(resp["n"])
        dt = np.dtype(str(resp["dtype"]))
        width = int(resp.get("payload_bytes", 0) or 0)
        nbytes = rn * (dt.itemsize + width)
        blob = self._rfile.read(nbytes)
        if len(blob) != nbytes:
            raise ConnectionError(
                f"short response payload ({len(blob)}/{nbytes})")
        out = np.frombuffer(blob[:rn * dt.itemsize], dtype=dt).copy()
        out_pay = None
        if width:
            out_pay = np.frombuffer(
                blob[rn * dt.itemsize:], np.uint8).reshape(rn,
                                                           width).copy()
        return ServeReply(True, resp, out, out_pay)


def reply_fingerprint_ok(request: np.ndarray,
                         reply: ServeReply) -> bool:
    """Client-side verification a hedged reply must pass before it
    wins (ISSUE 11): same element count, non-decreasing order, and —
    for integer keys — the XOR multiset fold of the reply equal to the
    request's (one O(n) pass; a reply carrying someone else's bytes or
    a truncation cannot pass all three).  Floats skip the XOR leg
    (NaN-safe bit games are the server verifier's job) but keep the
    count/order checks."""
    if not reply.ok or reply.arr is None:
        return False
    out = reply.arr
    if out.size != request.size or out.dtype != request.dtype:
        return False
    if out.size == 0:
        return True
    if out.dtype.kind in "iu":
        if not bool(np.all(out[:-1] <= out[1:])):
            return False
        width = f"uint{out.dtype.itemsize * 8}"
        fold_req = np.bitwise_xor.reduce(request.view(width))
        fold_out = np.bitwise_xor.reduce(out.view(width))
        return bool(fold_req == fold_out)
    # floats: total-order sortedness modulo NaNs is the server's
    # verifier domain; check what is cheap and unambiguous here
    finite = out[~np.isnan(out)]
    return bool(np.all(finite[:-1] <= finite[1:])) if finite.size else True


class ResilientClient:
    """Retrying, optionally hedging client (ISSUE 11).  Each attempt
    uses a FRESH connection — a retry must never reuse the socket whose
    peer just stalled, and hedged attempts must not share a stream.

    ``stats`` counts attempts/retries/hedges/hedge_wins; pass
    ``spanlog`` (any object with a ``record(name, t0, dt, **attrs)``
    method — e.g. ``utils.spans.SpanLog``) to record registered
    ``serve.hedge`` events, and ``metrics`` (a
    ``utils.metrics_live.LiveMetrics``) to feed
    ``sort_client_hedges_total``."""

    def __init__(self, host: str, port: int, *,
                 connect_timeout: float = 5.0,
                 read_timeout: float = 120.0,
                 max_attempts: int = 4,
                 backoff_s: float = 0.05,
                 backoff_cap_s: float = 2.0,
                 jitter: float = 0.5,
                 hedge_after_s: float | None = None,
                 seed: int = 0,
                 spanlog: object | None = None,
                 metrics: object | None = None) -> None:
        self.host = host
        self.port = port
        self.connect_timeout = float(connect_timeout)
        self.read_timeout = float(read_timeout)
        self.max_attempts = max(1, int(max_attempts))
        self.backoff_s = float(backoff_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.jitter = float(jitter)
        self.hedge_after_s = hedge_after_s
        self.spanlog = spanlog
        self.metrics = metrics
        self._rng = random.Random(seed)
        #: counters are bumped from the primary AND hedge threads —
        #: a bare += would lose increments under the race
        self._stats_lock = threading.Lock()
        self.stats = {"attempts": 0, "retries": 0, "hedges": 0,
                      "hedge_wins": 0, "transport_errors": 0}

    def _bump(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self.stats[key] += n

    # -- one wire attempt --------------------------------------------
    def _one(self, arr: np.ndarray, algo: str | None,
             trace_id: str | None,
             deadline_ms: float | None) -> ServeReply:
        self._bump("attempts")
        with ServeClient(self.host, self.port,
                         timeout=self.read_timeout,
                         connect_timeout=self.connect_timeout) as c:
            return c.sort(arr, algo=algo, trace_id=trace_id,
                          deadline_ms=deadline_ms)

    def _hedged(self, arr: np.ndarray, algo: str | None,
                trace_id: str | None,
                deadline_ms: float | None) -> ServeReply:
        """Primary attempt; if no reply within ``hedge_after_s``, race
        a second attempt on a fresh connection.  First reply passing
        the fingerprint check wins; the loser is discarded (its daemon
        thread dies on its own closed/answered socket).  Once the
        hedge is in flight the wait BLOCKS until an attempt resolves —
        each attempt is already self-bounded by its connect/per-recv
        socket timeouts, exactly like the non-hedged path, so an
        extra wall budget here would only abandon a legitimate
        long transfer mid-flight."""
        assert self.hedge_after_s is not None
        results: "queue.Queue[tuple[str, ServeReply | None, Exception | None]]" = queue.Queue()

        def attempt(tag: str, tid: str | None) -> None:
            try:
                results.put((tag, self._one(arr, algo, tid, deadline_ms),
                             None))
            except (OSError, ConnectionError,
                    json.JSONDecodeError) as e:
                results.put((tag, None, e))

        t0 = time.perf_counter()
        threading.Thread(target=attempt, args=("primary", trace_id),
                         daemon=True).start()
        hedged = False
        outcomes: list[tuple[str, ServeReply | None, Exception | None]] = []
        expected = 1
        while len(outcomes) < expected:
            try:
                if hedged:
                    outcomes.append(results.get())
                else:
                    outcomes.append(results.get(
                        timeout=self.hedge_after_s))
            except queue.Empty:
                # the tail: fire the hedge
                hedged = True
                expected = 2
                self._bump("hedges")
                if self.metrics is not None:
                    self.metrics.counter(
                        "sort_client_hedges_total").inc(1)
                # the "-h" suffix must stay inside the server's 64-char
                # trace-id grammar; a near-limit caller id gets a fresh
                # mint instead (ServeClient mints when None)
                hedge_tid = (f"{trace_id}-h"
                             if trace_id and len(trace_id) <= 62
                             else None)
                threading.Thread(target=attempt,
                                 args=("hedge", hedge_tid),
                                 daemon=True).start()
                continue
            tag, reply, exc = outcomes[-1]
            if reply is not None and reply_fingerprint_ok(arr, reply):
                if hedged:
                    if tag == "hedge":
                        self._bump("hedge_wins")
                    if self.spanlog is not None:
                        self.spanlog.record(
                            "serve.hedge", t0, time.perf_counter() - t0,
                            winner=tag,
                            waited_ms=round(self.hedge_after_s * 1e3, 3))
                return reply
        # every attempt resolved without a verified success: surface
        # the most informative outcome — a typed server reply beats a
        # transport exception
        for _tag, reply, _exc in outcomes:
            if reply is not None:
                return reply
        for _tag, _reply, exc in outcomes:
            if exc is not None:
                raise exc
        raise ConnectionError("hedged request: no attempt produced a "
                              "reply")

    # -- the public entry --------------------------------------------
    def sort(self, arr: np.ndarray, algo: str | None = None,
             trace_id: str | None = None,
             deadline_ms: float | None = None) -> ServeReply:
        """Sort with bounded retry (+ optional hedging).  Returns the
        first verified-ok or non-retryable typed reply; raises
        ``ConnectionError`` only when every attempt failed at the
        transport level.  ``deadline_ms`` is the caller's END-TO-END
        budget: each attempt sends only the budget still REMAINING
        (elapsed backoff and failed attempts shrink it — a retry must
        never hand the server a fresh full deadline), and once it is
        exhausted the client fails locally with a typed
        ``deadline_exceeded`` reply instead of attempting at all."""
        arr = np.ascontiguousarray(arr).reshape(-1)
        t_start = time.monotonic()
        last_exc: Exception | None = None
        last_reply: ServeReply | None = None
        for attempt in range(self.max_attempts):
            if attempt:
                self._bump("retries")
                delay = min(self.backoff_s * (2 ** (attempt - 1)),
                            self.backoff_cap_s)
                # full jitter fraction: desynchronizes a thundering
                # herd of clients all told to back off at once
                delay *= 1.0 + self.jitter * self._rng.random()
                time.sleep(delay)
            remaining_ms: float | None = None
            if deadline_ms is not None:
                remaining_ms = deadline_ms - (time.monotonic()
                                              - t_start) * 1e3
                if remaining_ms <= 0:
                    return ServeReply(False, {
                        "ok": False, "error": "deadline_exceeded",
                        "detail": f"client-side: {deadline_ms:g} ms "
                                  f"budget exhausted after {attempt} "
                                  "attempt(s)",
                        "trace_id": trace_id})
            try:
                if self.hedge_after_s is not None:
                    reply = self._hedged(arr, algo, trace_id,
                                         remaining_ms)
                else:
                    reply = self._one(arr, algo, trace_id, remaining_ms)
            except (OSError, ConnectionError, json.JSONDecodeError) as e:
                # JSONDecodeError: a truncated/garbled response header
                # (connection died mid-reply) is a transport fault like
                # any other — retry, never escape the documented
                # ConnectionError-only contract
                self._bump("transport_errors")
                last_exc = e
                continue
            if reply.ok and not reply_fingerprint_ok(arr, reply):
                # a reply that fails the client-side fold is treated
                # like a transport fault: never returned as success
                last_exc = ConnectionError(
                    "reply failed the client-side fingerprint check")
                continue
            if not reply.ok and reply.error in RETRYABLE_ERRORS:
                last_reply = reply
                continue
            return reply
        if last_reply is not None:
            return last_reply       # typed + retryable, budget spent
        raise ConnectionError(
            f"sort failed after {self.max_attempts} attempt(s): "
            f"{last_exc}")


def sort_once(host: str, port: int, arr: np.ndarray,
              algo: str | None = None, faults: str | None = None,
              timeout: float = 120.0) -> ServeReply:
    """One-shot convenience: connect, sort, close."""
    with ServeClient(host, port, timeout=timeout) as c:
        return c.sort(arr, algo=algo, faults=faults)
