"""Client for the sort server's ``sortserve.v1`` wire protocol.

Used by ``bench/serve_load.py`` (the closed-loop load generator), the
tests, and anything else that wants a remote sort.  One
:class:`ServeClient` holds one TCP connection and may issue many
requests back to back (the server keeps the connection open across
requests); a typed error response comes back as a :class:`ServeReply`
with ``ok=False`` and the server's stable ``error`` code — the client
never raises on a *server-side* rejection, only on transport failure.
"""

from __future__ import annotations

import json
import os
import socket
from dataclasses import dataclass

import numpy as np

#: Must match serve/server.py (kept literal here so the client is
#: importable without the server stack).
WIRE_SCHEMA = "sortserve.v1"


@dataclass
class ServeReply:
    """One response: ``ok`` + sorted ``arr``, or a typed error."""

    ok: bool
    header: dict
    arr: np.ndarray | None = None

    @property
    def error(self) -> str | None:
        return None if self.ok else str(self.header.get("error"))

    @property
    def detail(self) -> str:
        return str(self.header.get("detail", ""))

    @property
    def trace_id(self) -> str | None:
        """The request's end-to-end trace id, echoed by the server
        (ISSUE 10) — the key ``report.py --trace-id`` reconstructs the
        request from."""
        v = self.header.get("trace_id")
        return str(v) if v is not None else None


class ServeClient:
    """One persistent connection to a sort server."""

    def __init__(self, host: str, port: int, timeout: float = 120.0) -> None:
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self._rfile = self.sock.makefile("rb")

    def close(self) -> None:
        try:
            self._rfile.close()
        finally:
            self.sock.close()

    def __enter__(self) -> "ServeClient":
        return self

    def __exit__(self, *exc: object) -> None:
        self.close()

    def sort(self, arr: np.ndarray, algo: str | None = None,
             faults: str | None = None,
             trace_id: str | None = None) -> ServeReply:
        """Send one sort request; block for the reply.  A ``trace_id``
        is minted here when the caller supplies none — the client IS
        the wire layer, so every request carries one end to end (the
        server echoes it in the response header)."""
        arr = np.ascontiguousarray(arr).reshape(-1)
        hdr: dict = {"v": WIRE_SCHEMA, "dtype": arr.dtype.name,
                     "n": int(arr.size),
                     "trace_id": trace_id or os.urandom(8).hex()}
        if algo is not None:
            hdr["algo"] = algo
        if faults is not None:
            hdr["faults"] = faults
        self.sock.sendall(json.dumps(hdr).encode("utf-8") + b"\n"
                          + arr.tobytes())
        line = self._rfile.readline()
        if not line:
            raise ConnectionError("server closed the connection "
                                  "without a response header")
        resp = json.loads(line.decode("utf-8"))
        if not resp.get("ok"):
            return ServeReply(False, resp)
        nbytes = int(resp["n"]) * np.dtype(str(resp["dtype"])).itemsize
        payload = self._rfile.read(nbytes)
        if len(payload) != nbytes:
            raise ConnectionError(
                f"short response payload ({len(payload)}/{nbytes})")
        out = np.frombuffer(payload,
                            dtype=np.dtype(str(resp["dtype"]))).copy()
        return ServeReply(True, resp, out)


def sort_once(host: str, port: int, arr: np.ndarray,
              algo: str | None = None, faults: str | None = None,
              timeout: float = 120.0) -> ServeReply:
    """One-shot convenience: connect, sort, close."""
    with ServeClient(host, port, timeout=timeout) as c:
        return c.sort(arr, algo=algo, faults=faults)
