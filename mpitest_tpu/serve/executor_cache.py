"""AOT executable cache: compile once per shape bucket, serve warm forever.

The compile is the dominant fixed cost of a small sort — hundreds of
milliseconds against sub-millisecond device work.  The cache removes it
from the request path twice over:

* **Shape bucketing** (:func:`bucket_for`, re-exported from
  ``models/segmented.py``): request/batch sizes round up to powers of
  two, so an unbounded family of request shapes maps to a handful of
  executables.  A 1300-key batch and a 1900-key batch both run the
  2048-lane program; the pad lanes sort to the tail and cost nanoseconds.
* **AOT compilation**: entries are built with
  ``jit(...).lower(...).compile()`` — the executable exists before the
  first request needs it (prewarm) or is built exactly once on first
  miss.  Warm requests call a finished executable; the selftest gate
  asserts a warm window records ZERO compile activity.

Every lookup emits a ``serve.compile_cache`` point event (hit/miss,
bucket, dtype, compile seconds on miss) so cache behavior is observable
in the same span stream as request latency.

Startup prewarm on a TPU backend runs behind the bounded topology probe
(:mod:`mpitest_tpu.utils.topology_probe`): on images where the TPU
compiler rides a tunnel, an unreachable tunnel makes the first compile
block forever HOLDING THE GIL — probing in a killable subprocess first
lets the server degrade to jit-on-first-use and still come up, instead
of wedging before it can accept a request."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any, Callable

from mpitest_tpu.models.segmented import (MIN_BUCKET, bucket_for,
                                          compile_packed_sort,
                                          executable_stats)

if TYPE_CHECKING:
    from mpitest_tpu.utils.spans import SpanLog

__all__ = ["CacheStats", "ExecutorCache", "MIN_BUCKET", "bucket_for"]


@dataclass
class CacheStats:
    hits: int = 0
    misses: int = 0
    compile_s: float = 0.0
    prewarmed: int = 0
    buckets: set = field(default_factory=set)


class ExecutorCache:
    """Memoized AOT executables keyed by (kind, bucket, dtype name,
    total word count, mesh fingerprint).  ``dtype``/``mesh`` ride the
    key for honesty (an entry is only ever reused for the exact
    configuration it was built for) even where the underlying program
    depends on fewer coordinates — the packed sort is shape+word-count
    only, so e.g. int32 and uint32 share a *compile* via the lru-cached
    builder while keeping distinct cache entries and telemetry."""

    def __init__(self, spans: "SpanLog | None" = None) -> None:
        self._entries: dict[tuple, Callable[..., Any]] = {}
        self._lock = threading.Lock()
        self.stats = CacheStats()
        self.spans = spans

    def snapshot(self) -> dict:
        """Consistent point-in-time stats for the live /varz endpoint —
        copied under the cache lock (iterating the live ``buckets`` set
        while a miss mutates it would raise mid-scrape)."""
        with self._lock:
            return {"hits": self.stats.hits,
                    "misses": self.stats.misses,
                    "prewarmed": self.stats.prewarmed,
                    "compile_s": round(self.stats.compile_s, 4),
                    "buckets": sorted(self.stats.buckets)}

    # -- events -------------------------------------------------------
    def _event(self, **attrs: object) -> None:
        if self.spans is not None:
            self.spans.record("serve.compile_cache", time.perf_counter(),
                              0.0, **attrs)

    # -- lookup -------------------------------------------------------
    def get_packed(self, bucket: int, dtype_name: str,
                   n_words_total: int) -> Callable[..., Any]:
        """The compiled packed-batch executable for a shape bucket —
        the batcher's hot path.  First call per key compiles (one
        ``serve.compile_cache`` miss event with the compile seconds);
        every later call is a dict lookup."""
        key = ("packed", bucket, dtype_name, n_words_total)
        with self._lock:
            exe = self._entries.get(key)
            if exe is not None:
                self.stats.hits += 1
                dt = None
            else:
                # compile under the lock: two threads racing on a cold
                # key would otherwise both pay the compile (the dispatch
                # thread is single today, but the contract shouldn't
                # depend on it)
                t0 = time.perf_counter()
                # threadlint: disable=TL003 -- cold-key dogpile guard, reviewed hold
                exe = compile_packed_sort(n_words_total, bucket)
                dt = time.perf_counter() - t0
                self._entries[key] = exe
                self.stats.misses += 1
                self.stats.compile_s += dt
                self.stats.buckets.add(bucket)
        # threadlint TL002: span observers (metrics bridge, sentinel)
        # run on the EMITTING thread — never emit while holding the
        # cache lock, or the sentinel's lock nests under it
        if dt is None:
            self._event(hit=True, bucket=bucket, dtype=dtype_name)
        else:
            # ISSUE 10: stamp the miss event with the XLA cost analysis
            # (flops / bytes accessed / generated code size) so compile
            # cost AND program cost are attributable per shape bucket
            # straight from the span stream.
            self._event(hit=False, bucket=bucket, dtype=dtype_name,
                        compile_s=round(dt, 6), **executable_stats(exe))
        return exe

    def missing_packed(self, buckets: "tuple[int, ...]",
                       dtype_names: "tuple[str, ...]",
                       ) -> "tuple[tuple[int, str], ...]":
        """The (bucket, dtype) pairs no packed executable exists for
        yet (ISSUE 14: the serve tuner checks this before spawning a
        background prewarm — an already-covered recommendation must
        cost a lock acquire, not a thread).  Dtype-aware on purpose:
        executables are keyed per dtype, so an int32 build at a bucket
        does not cover a uint64 mix at the same bucket."""
        import numpy as np

        from mpitest_tpu.ops.keys import codec_for

        out: "list[tuple[int, str]]" = []
        with self._lock:
            for dn in dtype_names:
                nwt = 1 + codec_for(np.dtype(dn)).n_words
                for b in buckets:
                    if ("packed", b, dn, nwt) not in self._entries:
                        out.append((b, dn))
        return tuple(out)

    def _build_detached(self, bucket: int, dtype_name: str,
                        n_words_total: int) -> None:
        """Compile one packed executable WITHOUT holding the cache lock
        for the compile (ISSUE 14: the tuner's mid-traffic background
        prewarm must never stall a live ``get_packed`` — an XLA compile
        under ``self._lock`` would block the dispatch thread even on
        already-cached keys).  The trade is the reverse race:
        ``get_packed`` may compile the same cold key concurrently; both
        pay the compile, the first insert wins, and the dispatch path
        never waits on prewarm."""
        key = ("packed", bucket, dtype_name, n_words_total)
        with self._lock:
            hit = key in self._entries
            if hit:
                self.stats.hits += 1
        if hit:
            # threadlint TL002: emit outside the cache lock (observers
            # run on this thread and take their own locks)
            self._event(hit=True, bucket=bucket, dtype=dtype_name)
            return
        t0 = time.perf_counter()
        exe = compile_packed_sort(n_words_total, bucket)
        dt = time.perf_counter() - t0
        with self._lock:
            fresh = key not in self._entries
            if fresh:
                self._entries[key] = exe
                self.stats.misses += 1
                self.stats.compile_s += dt
                self.stats.buckets.add(bucket)
        if fresh:
            self._event(hit=False, bucket=bucket, dtype=dtype_name,
                        compile_s=round(dt, 6), **executable_stats(exe))

    # -- prewarm ------------------------------------------------------
    def prewarm(self, buckets: "tuple[int, ...]", dtype_names: tuple,
                log: Callable[[str], None] = lambda m: None) -> int:
        """AOT-compile the configured shape buckets before the first
        request (``SORT_SERVE_SHAPE_BUCKETS`` × prewarm dtypes).  On a
        TPU backend the bounded topology probe runs FIRST: if the
        compiler path does not answer, prewarm is skipped with a loud
        log line and the server degrades to jit-on-first-use — it never
        wedges at startup holding the GIL.  Returns the number of
        executables built."""
        import jax

        from mpitest_tpu.ops.keys import codec_for

        if jax.default_backend() == "tpu":
            from mpitest_tpu.utils.topology_probe import probe_tpu_compiler

            reason = probe_tpu_compiler()
            if reason:
                log(f"prewarm skipped ({reason}); executables will "
                    "compile on first use")
                return 0
        import numpy as np

        built = 0
        for dtype_name in dtype_names:
            n_words = codec_for(np.dtype(dtype_name)).n_words
            for b in buckets:
                self._build_detached(b, dtype_name, 1 + n_words)
                built += 1
        # threadlint TL004: prewarm runs on the main thread AND the
        # tuner's background prewarm thread — count under the lock
        with self._lock:
            self.stats.prewarmed += built
        log(f"prewarmed {built} executable(s) "
            f"(buckets {sorted(self.stats.buckets)})")
        return built
