"""Streaming SLO sentinel: live anomaly detection over the span stream.

``report.py --doctor`` diagnoses a trace *after* the run; a fleet
replica needs the same verdicts *while serving* — a router cannot
balance on pathologies an operator finds tomorrow.  The sentinel is
the live half of the ISSUE 16 diagnosis layer: a rolling multi-window
evaluator fed from the existing ``SpanLog.observers`` hook (the exact
pattern of :class:`~mpitest_tpu.utils.metrics_live.SpanMetricsBridge`
— it IS just another observer appended right after the bridge in
``ServerCore.__init__``), tracking

* error-budget **burn rate** over the rolling window (errors vs the
  SLO allowance, the ``report.py`` ``error_budget`` math) plus **p99
  quantile drift** against a long-horizon EWMA — both surface as the
  registered ``deadline_burn`` rule;
* per-exchange **imbalance** (``exchange_balance`` peer ratios) with
  EWMA smoothing → ``skew_imbalance``;
* capacity **regrow accumulation** (``sort.plan`` cap decisions) →
  ``cap_thrash``;
* breaker **flapping** (``serve.watchdog`` trips) → ``breaker_flap``
  (critical).

Every alert is emitted as a registered ``serve.alert`` span — so it
rides the trace stream, the flight-recorder ring, and the bridge
(→ ``sort_alerts_total{rule,severity}``) with zero new plumbing — and
kept in a bounded deque the telemetry server's ``/alerts`` endpoint
snapshots.  Critical alerts dump the flight recorder (rate-limited by
the recorder itself), so the evidence window around the anomaly is on
disk before anyone asks.

Rule names come from :data:`mpitest_tpu.doctor.DOCTOR_RULES` — the
single pathology vocabulary (sortlint SL007 rejects literals outside
it).  Thresholds reuse the doctor's module constants so post-hoc and
live diagnosis can never silently disagree.

Knobs (fail-fast-validated in both drivers): ``SORT_SENTINEL={on,off}``,
``SORT_SENTINEL_WINDOW_S`` (rolling window), ``SORT_ALERT_BURN_RATE``
(burn-rate multiple that alerts).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Deque

from mpitest_tpu import doctor
from mpitest_tpu.utils import span_schema
from mpitest_tpu.utils.metrics_live import LiveMetrics
from mpitest_tpu.utils.spans import Span, SpanLog

#: Minimum ok-latency samples before quantile drift is evaluated.
MIN_DRIFT_SAMPLES = 10
#: p99-vs-EWMA multiple that raises latency drift.
DRIFT_FACTOR = 2.0
#: EWMA smoothing weight (per evaluation, not per second — the window
#: already bounds the horizon).
EWMA_ALPHA = 0.3
#: Imbalance samples before the EWMA is trusted.
MIN_IMBALANCE_SAMPLES = 3
#: Bounded alert history the /alerts endpoint snapshots.
MAX_ALERTS = 256


def _p99(samples: list[float]) -> float:
    s = sorted(samples)
    return s[min(len(s) - 1, int(0.99 * len(s)))]


class SortSentinel:
    """Span-close observer raising registered ``serve.alert`` events.

    Thread-safety: span closes arrive on every handler thread; one
    lock guards the series deques and alert history.  Emitting the
    alert span re-enters ``SpanLog._flush`` (observers run before the
    stream write, no lock is held), so the observer ignores its own
    ``serve.alert`` spans to terminate the recursion at depth one.
    """

    def __init__(self, metrics: LiveMetrics, spans: SpanLog, *,
                 window_s: float, burn_rate: float,
                 slo_target_pct: float = doctor.DEFAULT_SLO_TARGET_PCT,
                 ) -> None:
        self.metrics = metrics
        self.spans = spans
        self.window_s = float(window_s)
        self.burn_threshold = float(burn_rate)
        self.slo_target_pct = float(slo_target_pct)
        self.alerts: Deque[dict[str, Any]] = deque(maxlen=MAX_ALERTS)
        self._lock = threading.Lock()
        # rolling series: (monotonic t, payload)
        self._requests: Deque[tuple[float, bool, float]] = deque()
        self._regrows: Deque[tuple[float, int]] = deque()
        self._trips: Deque[float] = deque()
        self._p99_ewma: float | None = None
        self._imbalance_ewma: float | None = None
        self._imbalance_n = 0
        self._last_alert: dict[str, float] = {}
        self.alerts_total = 0

    # -- observer entry ----------------------------------------------
    def __call__(self, span: Span) -> None:
        name = getattr(span, "name", "")
        if name == span_schema.SERVE_ALERT_SPAN:
            return  # our own emission re-entering the flush hook
        attrs = getattr(span, "attrs", None) or {}
        now = time.monotonic()
        if name == span_schema.SERVE_REQUEST_SPAN:
            ok = str(attrs.get("status", "?")) == "ok"
            self._on_request(now, ok, float(span.dt or 0.0))
        elif name == span_schema.BALANCE_SPAN:
            self._on_balance(now, attrs)
        elif name == span_schema.PLAN_SPAN:
            self._on_plan(now, attrs)
        elif name == span_schema.SERVE_WATCHDOG_SPAN:
            if str(attrs.get("event", "?")) == "trip":
                self._on_trip(now)

    # -- per-signal detectors ----------------------------------------
    def _on_request(self, now: float, ok: bool, dt_s: float) -> None:
        with self._lock:
            self._requests.append((now, ok, dt_s))
            self._gc(self._requests, now)
            win = list(self._requests)
        n = len(win)
        if n < doctor.BURN_MIN_REQUESTS:
            return
        errors = sum(1 for _t, k, _d in win if not k)
        allowance = max(100.0 - self.slo_target_pct, 1e-9)
        burn = (100.0 * errors / n) / allowance
        if errors and burn >= self.burn_threshold:
            sev = ("critical" if burn >= 2 * self.burn_threshold
                   else "warn")
            self._alert(
                "deadline_burn", sev,
                f"burn rate {burn:.1f}x allowance ({errors}/{n} non-ok "
                f"in the last {self.window_s:g}s window)",
                value=round(burn, 4), threshold=self.burn_threshold)
            return
        lats = [d * 1e3 for _t, k, d in win if k]
        if len(lats) < MIN_DRIFT_SAMPLES:
            return
        p99 = _p99(lats)
        with self._lock:
            ewma = self._p99_ewma
            if ewma is None:
                self._p99_ewma = p99
                return
            drifted = p99 > DRIFT_FACTOR * ewma and ewma > 0
            self._p99_ewma = EWMA_ALPHA * p99 + (1 - EWMA_ALPHA) * ewma
        if drifted:
            self._alert(
                "deadline_burn", "warn",
                f"p99 latency drift: {p99:.1f}ms vs {ewma:.1f}ms "
                f"EWMA ({p99 / ewma:.1f}x)",
                value=round(p99 / ewma, 4), threshold=DRIFT_FACTOR)

    def _on_balance(self, now: float, attrs: dict) -> None:
        ratio = attrs.get("peer_ratio", attrs.get("recv_ratio"))
        if not isinstance(ratio, (int, float)) or ratio <= 0:
            return
        with self._lock:
            ewma = self._imbalance_ewma
            self._imbalance_ewma = (
                float(ratio) if ewma is None
                else EWMA_ALPHA * float(ratio) + (1 - EWMA_ALPHA) * ewma)
            self._imbalance_n += 1
            smoothed = self._imbalance_ewma
            samples = self._imbalance_n
        if samples >= MIN_IMBALANCE_SAMPLES and \
                smoothed >= doctor.SKEW_FACTOR_WARN:
            sev = ("critical" if smoothed >= doctor.SKEW_FACTOR_CRITICAL
                   else "warn")
            self._alert(
                "skew_imbalance", sev,
                f"exchange imbalance EWMA {smoothed:.2f}x over "
                f"{samples} exchanges",
                value=round(smoothed, 4),
                threshold=doctor.SKEW_FACTOR_WARN)

    def _on_plan(self, now: float, attrs: dict) -> None:
        decisions = attrs.get("decisions")
        cap = (decisions or {}).get("cap") \
            if isinstance(decisions, dict) else None
        actual = cap.get("actual") if isinstance(cap, dict) else None
        n = actual.get("regrows") if isinstance(actual, dict) else None
        if not isinstance(n, (int, float)) or n <= 0:
            return
        with self._lock:
            self._regrows.append((now, int(n)))
            self._gc(self._regrows, now)
            total = sum(k for _t, k in self._regrows)
        if total >= doctor.CAP_REGROW_GATE:
            self._alert(
                "cap_thrash", "warn",
                f"{total} exchange-cap regrow(s) in the last "
                f"{self.window_s:g}s window",
                value=float(total),
                threshold=float(doctor.CAP_REGROW_GATE))

    def _on_trip(self, now: float) -> None:
        with self._lock:
            self._trips.append(now)
            while self._trips and self._trips[0] < now - self.window_s:
                self._trips.popleft()
            trips = len(self._trips)
        if trips >= doctor.BREAKER_TRIP_GATE:
            self._alert(
                "breaker_flap", "critical",
                f"{trips} breaker trip(s) in the last "
                f"{self.window_s:g}s window",
                value=float(trips),
                threshold=float(doctor.BREAKER_TRIP_GATE))

    def _gc(self, series: Deque, now: float) -> None:
        cutoff = now - self.window_s
        while series and series[0][0] < cutoff:
            series.popleft()

    # -- alert emission ----------------------------------------------
    def _alert(self, rule: str, severity: str, summary: str, *,
               value: float, threshold: float) -> None:
        if rule not in doctor.DOCTOR_RULES:     # computed-name guard
            raise KeyError(f"unregistered doctor rule: {rule!r}")
        now = time.monotonic()
        with self._lock:
            last = self._last_alert.get(rule)
            if last is not None and now - last < self.window_s:
                return  # per-rule cooldown: one alert per window
            self._last_alert[rule] = now
            self.alerts_total += 1
            self.alerts.append({
                "ts": time.time(), "rule": rule, "severity": severity,
                "summary": summary, "value": value,
                "threshold": threshold, "window_s": self.window_s,
            })
        # registered span: rides the trace stream, the flight ring and
        # the bridge (sort_alerts_total) — observers ignore it here
        self.spans.record(
            "serve.alert", time.perf_counter(), 0.0,
            rule=rule, severity=severity, value=value,
            threshold=threshold, window_s=self.window_s,
            summary=summary)
        if severity == "critical":
            # evidence window to disk before anyone asks; the recorder
            # rate-limits and never raises
            from mpitest_tpu.utils.flight_recorder import dump_on_error
            dump_on_error(f"sentinel_{rule}")

    # -- /alerts snapshot --------------------------------------------
    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            win = [r for r in self._requests]
            errors = sum(1 for _t, k, _d in win if not k)
            lats = [d * 1e3 for _t, k, d in win if k]
            return {
                "enabled": True,
                "window_s": self.window_s,
                "burn_threshold": self.burn_threshold,
                "slo_target_pct": self.slo_target_pct,
                "alerts_total": self.alerts_total,
                "alerts": list(self.alerts),
                "series": {
                    "window_requests": len(win),
                    "window_errors": errors,
                    "p99_ms": (round(_p99(lats), 3) if lats else None),
                    "p99_ewma_ms": (round(self._p99_ewma, 3)
                                    if self._p99_ewma is not None
                                    else None),
                    "imbalance_ewma": (round(self._imbalance_ewma, 4)
                                       if self._imbalance_ewma is not None
                                       else None),
                    "window_regrows": sum(k for _t, k in self._regrows),
                    "window_trips": len(self._trips),
                },
            }
