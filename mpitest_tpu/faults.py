"""Deterministic fault injection — the chaos half of the robustness layer.

The reference programs fail *silently*: bucket overflow truncates data
(``mpi_sample_sort.c:140-144``) and a rank that exits early strands its
peers (SURVEY §7.4).  Our port fixed those two instances, but a fix that
is only exercised by the bug it patched proves nothing about the next
fault.  This module makes failure a first-class, reproducible input:

* ``SORT_FAULTS=<spec>`` arms a :class:`FaultRegistry` — a comma list of
  ``site[:count]`` entries (``count`` defaults to 1; ``inf`` = fire on
  every opportunity, the persistent-failure configuration).  The spec is
  consumed deterministically: the k-th opportunity at a site fires iff
  the site still has budget, and corruption values derive from a
  splitmix64 stream over ``SORT_FAULTS_SEED`` — same spec + seed = the
  same faults in the same places, every run.
* Each subsystem polls its own site at its own fault point (the
  supervisor at dispatch, the exchange between all_to_all and the local
  sort/merge, the ingest pipeline after the fingerprint fold, the result
  before verification), so every detection/recovery path in
  ``models/supervisor.py`` is reachable from an env var.
* The native backends mirror this with ``COMM_FAULTS``
  (``comm/comm_faults.h``): ``kill:<rank>@<nth>`` / ``stall:<rank>@<nth>:<ms>``
  at collective entry.

Sites (the chaos grid of ``make fault-selftest`` covers all of them for
both algorithms):

================  ==========================================================
``dispatch_error``  raise a transient ``JaxRuntimeError`` at SPMD dispatch
``dispatch_oom``    raise a ``RESOURCE_EXHAUSTED``-shaped error at dispatch
``exchange_corrupt`` XOR-corrupt one exchange lane between the
                    all_to_all and the local sort (in-program, trace-time)
``exchange_drop``   zero one peer's recv count — drop a whole segment
``cap_squeeze``     force the first exchange cap to the alignment minimum
``ingest_poison``   corrupt an encoded ingest chunk AFTER the input
                    fingerprint folded it (streamed ingest only)
``dispatch_stall``  block the dispatch thread for ``SORT_FAULT_STALL_MS``
                    before launching (the serving watchdog's drill:
                    models the TPU-compiler tunnel hang)
``result_swap``     swap the first/last keys of the sorted result
                    (breaks sortedness — caught by the order check)
``result_dup``      overwrite key[1] with key[0] (stays sorted — caught
                    ONLY by the multiset fingerprint)
``spill_corrupt``   flip bits in a spill run's on-disk keys AFTER the
                    fingerprint sidecar folded them (store/runs.py —
                    the external sort's bad-disk drill)
``merge_drop``      drop one merged output chunk before the output fold
                    (store/merge.py — silent merge truncation)
``spill_torn_write`` chop tail bytes off a run's key file at close —
                    a torn write whose sidecar promises more bytes
                    than disk holds (store/runs.py commit path)
``spill_enospc``    raise ``OSError(ENOSPC)`` at the Nth spill write
                    (``SORT_FAULT_ENOSPC_AT``) — the full-volume shape
                    the typed capacity rejection must absorb
``spill_bitrot``    flip one byte in a run's key body AFTER commit —
                    at-rest decay the merge's read-back fold catches
``spill_block_garbage`` scramble one compressed block's header AFTER
                    commit (SORTRUN2 runs only) — an undecodable block
                    the reader must type as block corruption, never
                    decode into wrong keys
``manifest_torn``   drop the tail of one spill-manifest journal line —
                    the crashed-mid-append shape replay skips loudly
``merge_stall``     block ``SORT_FAULT_STALL_MS`` at merge entry — a
                    merge wedged on a dying disk (the durability
                    drill's deterministic SIGKILL barrier)
================  ==========================================================

Wire-level chaos (ISSUE 11) is a separate family: :data:`WIRE_SITES`
name faults injected OUTSIDE the process by the chaos TCP proxy
(``bench/wire_chaos.py``) between a client and the sort server — torn
headers, stalled payloads, mid-response disconnects, slow-drip writes,
delayed responses, connect-then-silence.  They share this module's
spec grammar (:func:`parse_wire_faults`) so one vocabulary covers the
whole chaos surface, but they never corrupt *data*: they attack the
server's request-lifecycle bounds (read/idle timeouts, admission-byte
reclamation) and the client's retry/hedging policy instead.

Injection never bypasses detection: faults corrupt *data*, and the
always-on verifier (``models/verify.py``) plus the supervisor decide
what the user sees — a retried, fingerprint-verified result or a typed
error with a nonzero exit.  A fault that the system silently absorbs
into a wrong answer is exactly the bug class this module exists to make
impossible to miss.
"""

from __future__ import annotations

import errno
import itertools
import math
import threading
import time
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Callable

from mpitest_tpu.utils import knobs

if TYPE_CHECKING:
    import jax
    import numpy as np

    from mpitest_tpu.models.api import DistributedSortResult

SITES = (
    "dispatch_error",
    "dispatch_oom",
    "dispatch_stall",
    "exchange_corrupt",
    "exchange_drop",
    "cap_squeeze",
    "ingest_poison",
    "result_swap",
    "result_dup",
    # out-of-core external sort (ISSUE 15, mpitest_tpu/store/):
    "spill_corrupt",   # flip bits in a spill run's on-disk keys AFTER
                       # the fingerprint sidecar folded them — a bad
                       # disk / torn write the merge must catch
    "merge_drop",      # drop one merged output chunk before the output
                       # fold — silent truncation in the merge engine
    # crash-durable spill tier (ISSUE 18, store/manifest.py + resume):
    "spill_torn_write",  # chop tail bytes off a run's key file at
                         # close — sidecar/manifest promise more bytes
                         # than disk holds (re-spilled on blame)
    "spill_enospc",      # OSError(ENOSPC) at the Nth spill write
                         # (SORT_FAULT_ENOSPC_AT) — must surface as
                         # the typed capacity rejection, never a 500
    "spill_bitrot",      # flip one byte in a run's key body AFTER
                         # commit — at-rest decay caught by the
                         # merge's read-back fold
    "manifest_torn",     # drop the tail of one manifest journal line
                         # — replay must skip it loudly
    "merge_stall",       # block SORT_FAULT_STALL_MS at merge entry —
                         # the kill-resume drill's SIGKILL barrier
    # compressed spill runs (ISSUE 20, SORTRUN2 framing):
    "spill_block_garbage",  # scramble one compressed block's header
                            # after commit — the reader must raise a
                            # typed block-corruption error naming run
                            # + block, and blame-respill must recover
)

#: Sites applied at trace time inside the compiled SPMD program (the
#: exchange faults) — arming one forces a fresh compile via a unique
#: ``fault_token`` so the poisoned trace can never be served from the
#: jit cache to a clean run.
EXCHANGE_SITES = ("exchange_corrupt", "exchange_drop")

#: Wire-level chaos vocabulary (ISSUE 11): injected by the chaos TCP
#: proxy (``bench/wire_chaos.py``) between client and server.  Each
#: site carries one integer parameter (a byte offset ``k`` or a delay
#: in milliseconds — see ``WIRE_DEFAULT_PARAM``).
WIRE_SITES = (
    "wire_torn_header",         # forward k request bytes, then close
    "wire_stall_payload",       # forward header + k payload bytes, then
                                # go silent (the slow-loris shape)
    "wire_disconnect_response", # forward k response bytes, then close
    "wire_slow_drip",           # drip request bytes with k ms pauses
    "wire_delay_response",      # hold the response back for k ms
    "wire_connect_silence",     # accept the client, forward nothing
)

#: Per-site default parameter (bytes for the offset sites, ms for the
#: delay sites) when the spec names no ``@param``.
WIRE_DEFAULT_PARAM: dict[str, int] = {
    "wire_torn_header": 5,
    "wire_stall_payload": 64,
    "wire_disconnect_response": 16,
    "wire_slow_drip": 200,
    "wire_delay_response": 500,
    "wire_connect_silence": 0,
}


@dataclass(frozen=True)
class WireFault:
    """One parsed wire-fault entry: ``site[@param][:every]``.

    ``param`` is the site's byte offset / delay ms; ``every`` selects
    which proxied connections the fault fires on — every ``every``-th
    (1-based), so ``every=1`` hits all connections and ``every=4``
    hits the 4th, 8th, ... (deterministic tail injection for the
    hedging cells)."""

    site: str
    param: int
    every: int = 1

    def spec(self) -> str:
        """The canonical spec string (``parse_wire_faults`` round-trips
        it)."""
        out = self.site
        if self.param != WIRE_DEFAULT_PARAM[self.site]:
            out += f"@{self.param}"
        if self.every != 1:
            out += f":{self.every}"
        return out

    def fires_on(self, conn_index: int) -> bool:
        """True when this fault applies to the ``conn_index``-th
        (0-based) proxied connection."""
        return (conn_index + 1) % self.every == 0


def parse_wire_faults(spec: str) -> tuple[WireFault, ...]:
    """Parse a comma list of ``site[@param][:every]`` wire-fault
    entries (the ``SORT_FAULTS``-style grammar extended with the wire
    family).  Raises ``ValueError`` naming the vocabulary on garbage —
    the same fail-fast contract as :class:`FaultRegistry`."""
    out: list[WireFault] = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, _, every_s = entry.partition(":")
        name, _, param_s = name.partition("@")
        if name not in WIRE_SITES:
            raise ValueError(
                f"wire faults: unknown site {name!r}; use one of "
                f"{WIRE_SITES}")
        param = WIRE_DEFAULT_PARAM[name]
        if param_s:
            try:
                param = int(param_s)
            except ValueError:
                param = -1
            if param < 0:
                raise ValueError(
                    f"wire faults: bad param {param_s!r} for {name!r}; "
                    "use an integer >= 0 (bytes or ms)")
        every = 1
        if every_s:
            try:
                every = int(every_s)
            except ValueError:
                every = 0
            if every < 1:
                raise ValueError(
                    f"wire faults: bad every-count {every_s!r} for "
                    f"{name!r}; use an integer >= 1")
        out.append(WireFault(name, param, every))
    if not out:
        raise ValueError(
            f"wire faults: empty spec; use a comma list of "
            f"site[@param][:every] over {WIRE_SITES}")
    return tuple(out)


def _splitmix64(state: int) -> tuple[int, int]:
    """One splitmix64 step: (next_state, output) — the same deterministic
    stream family native/comm_fuzz.c uses, so corruption values are
    reproducible from SORT_FAULTS_SEED alone."""
    state = (state + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    z = state
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return state, z ^ (z >> 31)


@dataclass
class _Site:
    name: str
    remaining: float  # math.inf = persistent


@dataclass
class FaultRegistry:
    """Parsed, seedable fault plan for ONE sort run.

    ``fire(site)`` consumes one unit of that site's budget (thread-safe:
    the ingest pool's workers poll ``ingest_poison`` concurrently) and
    records the firing in :attr:`fired`; ``on_fire`` (set by the
    supervisor) forwards each firing into the span/counter pipeline.
    """

    spec: str
    seed: int = 0
    sites: dict[str, _Site] = field(default_factory=dict)
    fired: list[tuple[str, dict[str, object]]] = field(default_factory=list)
    on_fire: Callable[[str, dict[str, object]], None] | None = None

    def __post_init__(self) -> None:
        self._lock = threading.Lock()
        self._rng_state = (self.seed * 0x2545F4914F6CDD1D + 1) & 0xFFFFFFFFFFFFFFFF
        self._seq = 0
        for entry in self.spec.split(","):
            entry = entry.strip()
            if not entry:
                continue
            name, _, cnt = entry.partition(":")
            if name not in SITES:
                raise ValueError(
                    f"SORT_FAULTS: unknown fault site {name!r}; "
                    f"use one of {SITES}"
                )
            if cnt in ("", None):
                count: float = 1
            elif cnt == "inf":
                count = math.inf
            else:
                try:
                    count = int(cnt)
                except ValueError:
                    count = 0
                if count < 1:
                    raise ValueError(
                        f"SORT_FAULTS: bad count {cnt!r} for {name!r}; "
                        "use a positive integer or 'inf'"
                    )
            site = self.sites.setdefault(name, _Site(name, 0))
            site.remaining += count

    # -- firing -------------------------------------------------------
    def would_fire(self, site: str) -> bool:
        """Non-consuming budget peek (lets hooks avoid advancing the
        corruption RNG for sites that are not armed — the RNG stream
        must depend only on the faults that actually fire)."""
        with self._lock:
            s = self.sites.get(site)
            return s is not None and s.remaining > 0

    def fire(self, site: str, **detail: object) -> bool:
        """Consume one unit of ``site``'s budget; True iff the fault
        fires now.  Records the firing and notifies ``on_fire``."""
        with self._lock:
            s = self.sites.get(site)
            if s is None or s.remaining <= 0:
                return False
            s.remaining -= 1
            self._seq += 1
            detail = dict(detail, seq=self._seq)
            self.fired.append((site, detail))
        cb = self.on_fire
        if cb is not None:
            cb(site, detail)
        return True

    def rand_word(self) -> int:
        """Deterministic nonzero uint32 corruption value."""
        with self._lock:
            self._rng_state, out = _splitmix64(self._rng_state)
        return (out & 0xFFFFFFFF) or 0xDEADBEEF

    @property
    def injected(self) -> int:
        return len(self.fired)


# -- run-scoped activation -------------------------------------------------

#: Registry installed explicitly (tests / the chaos driver) — takes
#: precedence over the SORT_FAULTS env spec.
_INSTALLED: FaultRegistry | None = None

#: Stack of registries active for the current run (sort() / ingest
#: pipeline); trace-time and worker-thread hooks read the top.
_ACTIVE: list[FaultRegistry] = []

#: Exchange fault handed from the host (supervisor) to the trace-time
#: hook in collectives.ragged_all_to_all; one-shot, popped at trace.
#: The supervisor drops it if the armed dispatch dies before tracing,
#: and run teardown (``active.__exit__``) clears any stragglers — a
#: stale entry must never leak into a later clean compile.
_PENDING_EXCHANGE: list[dict] = []

#: Process-global token sequence: every armed exchange fault gets a
#: token no earlier compile can have used, so the jit cache can never
#: serve a poisoned trace to a different run (or skip the trace that
#: would consume the pending entry).
_TOKEN_SEQ = itertools.count(1)


def install(reg: FaultRegistry | None) -> None:
    """Install a registry for subsequent runs (None clears).  Tests use
    this instead of mutating os.environ."""
    global _INSTALLED
    _INSTALLED = reg


def for_run() -> FaultRegistry | None:
    """The registry for a new run: the installed one, else a FRESH parse
    of ``SORT_FAULTS`` (counts reset every run — deterministic per run,
    not cumulative across a process)."""
    if _INSTALLED is not None:
        return _INSTALLED
    spec = knobs.get_raw("SORT_FAULTS")
    if not spec:
        return None
    return FaultRegistry(spec, seed=faults_seed())


def faults_seed() -> int:
    """``SORT_FAULTS_SEED`` (default 0): the corruption-stream seed."""
    return knobs.get("SORT_FAULTS_SEED")


def validate_env() -> None:
    """Fail-fast parse of the fault knobs (the CLI's [ERROR] contract)."""
    knobs.validate("SORT_FAULTS", "SORT_FAULTS_SEED")


class active:
    """Context manager scoping ``reg`` to the current run (re-entrant:
    a donated-retry re-ingest inside a sort nests cleanly)."""

    def __init__(self, reg: FaultRegistry | None) -> None:
        self.reg = reg

    def __enter__(self) -> FaultRegistry | None:
        if self.reg is not None:
            _ACTIVE.append(self.reg)
        return self.reg

    def __exit__(self, *exc: object) -> bool:
        if self.reg is not None and _ACTIVE and _ACTIVE[-1] is self.reg:
            _ACTIVE.pop()
        if self.reg is not None and not _ACTIVE:
            drop_pending()  # no armed-but-untraced fault may outlive a run
        return False


def current() -> FaultRegistry | None:
    return _ACTIVE[-1] if _ACTIVE else None


# -- site hooks ------------------------------------------------------------

def arm_exchange(reg: FaultRegistry | None) -> str:
    """Host side of the exchange faults: if one fires for this dispatch,
    queue its parameters for the trace-time hook and return a
    PROCESS-UNIQUE compile token (forces a fresh trace — a reused token
    would let the jit cache serve an old poisoned program AND leave the
    pending entry unconsumed, to be baked into the next clean trace).
    Empty token = clean compile, shared cache."""
    if reg is None:
        return ""
    for site in EXCHANGE_SITES:
        if not reg.would_fire(site):
            continue  # don't advance the RNG for unarmed sites
        word = reg.rand_word()
        if reg.fire(site, word=word):
            _PENDING_EXCHANGE.append({"site": site, "word": word})
            return f"{site}#{next(_TOKEN_SEQ)}"
    return ""


def drop_pending() -> int:
    """Discard any armed-but-unconsumed exchange fault — called when the
    armed dispatch dies before its first trace and at run teardown, so a
    stale entry can never corrupt a later clean compile.  Returns the
    number dropped (the caller records them as ``faults_dropped`` —
    they were counted as injected when armed but never touched data)."""
    n = len(_PENDING_EXCHANGE)
    _PENDING_EXCHANGE.clear()
    return n


def apply_exchange_fault(
    recv_arrays: tuple[jax.Array, ...], recv_cnt: jax.Array,
) -> tuple[tuple[jax.Array, ...], jax.Array]:
    """Trace-time hook (called from collectives.ragged_all_to_all, i.e.
    between the exchange and the local sort/merge): apply the pending
    exchange fault, if any, to the first traced exchange of the armed
    dispatch.  No-op on clean compiles."""
    if not _PENDING_EXCHANGE:
        return recv_arrays, recv_cnt
    import jax.numpy as jnp

    spec = _PENDING_EXCHANGE.pop()
    if spec["site"] == "exchange_drop":
        # drop the segment peer 0 sent to every rank: a truncated
        # exchange, the reference's silent-overflow shape
        recv_cnt = recv_cnt.at[0].set(0)
        return recv_arrays, recv_cnt
    # exchange_corrupt: flip deterministic bits in lane (0, 0) of the
    # first word array — a payload corrupted in flight
    w0 = recv_arrays[0]
    w0 = w0.at[0, 0].set(w0[0, 0] ^ jnp.uint32(spec["word"]))
    return (w0,) + tuple(recv_arrays[1:]), recv_cnt


def maybe_poison_chunk(words: tuple[np.ndarray, ...],
                       chunk_idx: int) -> tuple[np.ndarray, ...]:
    """Ingest-pipeline hook (worker threads): corrupt CHUNK 0's first
    encoded word AFTER the fingerprint fold — the device receives data
    the fingerprint never saw, so the output verifier must flag it.
    Pinned to chunk 0 (one budget unit per stream pass): encode workers
    race on the budget otherwise, and which chunk got poisoned would
    depend on thread scheduling — the registry's same-spec+seed
    determinism contract forbids that."""
    if chunk_idx != 0:
        return words
    reg = current()
    if reg is None or not reg.would_fire("ingest_poison"):
        return words
    word = reg.rand_word()
    if not reg.fire("ingest_poison", chunk=chunk_idx, word=word):
        return words
    w0 = words[0].copy()
    if w0.size:
        w0[0] ^= word & 0xFFFFFFFF
    return (w0,) + tuple(words[1:])


def maybe_corrupt_spill(raw: bytes) -> bytes:
    """Spill-run hook (store/runs.py write path): corrupt the first key
    bytes of a run AFTER its fingerprint sidecar folded the clean words
    — the on-disk bytes then disagree with the sidecar, exactly the
    torn-write/bit-rot shape the merge's read-back fold must flag."""
    reg = current()
    if reg is None or not reg.would_fire("spill_corrupt"):
        return raw
    word = reg.rand_word()
    if not reg.fire("spill_corrupt", word=word):
        return raw
    buf = bytearray(raw)
    if len(buf) >= 4:
        for i in range(4):
            buf[i] ^= (word >> (8 * i)) & 0xFF
    return bytes(buf)


def should_drop_merge_chunk(chunk_idx: int, n: int) -> bool:
    """Merge hook (store/merge.py emit path): True when the armed
    ``merge_drop`` site consumes this output chunk — the chunk vanishes
    from the merged output AND its fold, so the external driver's
    count/fingerprint comparison against the combined run sidecars must
    trip (silent truncation made loud)."""
    reg = current()
    if reg is None or not reg.would_fire("merge_drop"):
        return False
    return reg.fire("merge_drop", chunk=chunk_idx, n=n)


def spill_tear_bytes(body_bytes: int) -> int:
    """Spill-commit hook (store/runs.py close path): number of tail
    bytes to chop off the run's key file (0 = clean).  The sidecar (and
    any manifest line) already promise the full length, so the torn run
    is caught structurally — ``open_run`` / the merge's size check —
    and blamed + re-spilled, or discarded by resume re-validation."""
    reg = current()
    if reg is None or body_bytes <= 0 \
            or not reg.would_fire("spill_torn_write"):
        return 0
    word = reg.rand_word()
    cut = min(1 + (word % 7), body_bytes)
    if not reg.fire("spill_torn_write", cut=cut, body=body_bytes):
        return 0
    return cut


def spill_bitrot_word() -> int | None:
    """Post-commit bit-rot hook (store/runs.py close path): a nonzero
    corruption word to XOR into the middle of the run's key body AFTER
    the durable commit, or None when clean.  The on-disk bytes then
    disagree with the sidecar — at-rest decay the merge's read-back
    fold (and resume's ``verify_run``) must flag."""
    reg = current()
    if reg is None or not reg.would_fire("spill_bitrot"):
        return None
    word = reg.rand_word()
    if not reg.fire("spill_bitrot", word=word):
        return None
    return word


def spill_block_garbage_word() -> int | None:
    """Post-commit block-garbage hook (store/runs.py close path,
    compressed SORTRUN2 runs only): a corruption word used to scramble
    the middle block's header fields after the durable commit, or None
    when clean.  The block becomes undecodable — framing or checksum —
    so the reader's typed :class:`~mpitest_tpu.store.runs.
    BlockIntegrityError` must name the run and block, and the merge's
    blame ladder must re-spill the run, never emit garbage keys."""
    reg = current()
    if reg is None or not reg.would_fire("spill_block_garbage"):
        return None
    word = reg.rand_word()
    if not reg.fire("spill_block_garbage", word=word):
        return None
    return word


def maybe_spill_enospc(nbytes: int) -> None:
    """Spill-write hook (store/runs.py append path): raise a real
    ``OSError(ENOSPC)`` at the Nth write opportunity
    (``SORT_FAULT_ENOSPC_AT``, 1-based) — the volume-full shape the
    external driver must convert to the typed capacity rejection with
    partial outputs deleted, never an untyped 500."""
    reg = current()
    if reg is None or not reg.would_fire("spill_enospc"):
        return
    at = int(knobs.get("SORT_FAULT_ENOSPC_AT"))
    seen = int(getattr(reg, "_enospc_writes", 0)) + 1
    reg._enospc_writes = seen  # type: ignore[attr-defined]
    if seen < at:
        return
    if reg.fire("spill_enospc", write=seen, bytes=nbytes):
        raise OSError(errno.ENOSPC,
                      "No space left on device (injected spill_enospc)")


def manifest_tear_cut(line_len: int) -> int:
    """Manifest-journal hook (store/manifest.py commit path): number of
    tail bytes of this journal line that never reach disk (0 = clean)
    — the crashed-mid-append shape replay must skip loudly without
    losing the committed lines before it."""
    reg = current()
    if reg is None or line_len <= 1 \
            or not reg.would_fire("manifest_torn"):
        return 0
    cut = max(1, line_len // 2)
    if not reg.fire("manifest_torn", cut=cut, line_len=line_len):
        return 0
    return cut


def maybe_merge_stall() -> None:
    """Merge-entry hook (store/external.py): block the merging thread
    for ``SORT_FAULT_STALL_MS`` — a merge wedged on a dying disk.  The
    durability drill arms this as its deterministic barrier: the
    process is SIGKILLed mid-stall with every partition run already
    durably committed, so the restart must resume at the merge."""
    reg = current()
    if reg is None or not reg.would_fire("merge_stall"):
        return
    ms = int(knobs.get("SORT_FAULT_STALL_MS"))
    if reg.fire("merge_stall", ms=ms):
        time.sleep(ms / 1e3)


def maybe_corrupt_result(reg: FaultRegistry | None,
                         res: "DistributedSortResult",
) -> "DistributedSortResult":
    """Result hook (host side, before verification): swap endpoints
    (breaks sortedness) or duplicate a key (multiset change only — the
    fingerprint's job).  Returns a corrupted copy of ``res``'s words."""
    if reg is None:
        return res
    import numpy as np  # noqa: F811 — runtime import (lazy; jax-adjacent)

    from mpitest_tpu.models.ingest import checked_device_put

    for site in ("result_swap", "result_dup"):
        if reg.sites.get(site) and reg.sites[site].remaining > 0:
            if not reg.fire(site):
                continue
            new_words = []
            for w in res.words:
                host = np.asarray(w).copy()
                if host.size >= 2:
                    if site == "result_swap":
                        a, b = 0, min(res.n_valid, host.size) - 1
                        host[a], host[b] = host[b].copy(), host[a].copy()
                    else:
                        host[1] = host[0]
                new_words.append(checked_device_put(host, w.sharding))
            res.words = tuple(new_words)
            break
    return res
