from mpitest_tpu.parallel.mesh import make_mesh, multihost_init  # noqa: F401
from mpitest_tpu.parallel import collectives  # noqa: F401
