"""Device mesh construction — the TPU replacement for ``mpirun -np P``.

The reference acquires its process group from ``MPI_Init`` +
``MPI_Comm_size/rank`` (``mpi_sample_sort.c:225-227``).  Here the "process
group" is a 1-D ``jax.sharding.Mesh`` over ICI; rank/size become
``lax.axis_index`` / the static axis size inside ``shard_map``.  The 1-D
logical mesh is kept topology-agnostic so the same algorithm code compiles
over a multi-host ICI+DCN hybrid mesh (v5e-16 config, SURVEY.md §7.3) —
only this module knows about hosts.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpitest_tpu.utils import knobs

AXIS = "x"  # the single key axis; all sharding is 1-D over it


def _device_order_key(d: "jax.Device") -> tuple:
    """Stable total order over devices: (process, device id).  ``id`` is
    the runtime's stable per-process ordinal (derived from topology
    coords on TPU), so the same hardware always maps to the same mesh
    position regardless of enumeration order."""
    return (getattr(d, "process_index", 0), getattr(d, "id", 0))


def make_mesh(n_devices: int | None = None,
              devices: "list[jax.Device] | None" = None) -> Mesh:
    """Build the 1-D mesh over all (or the first ``n_devices``) devices.

    Device order is made deterministic HERE (sorted by stable device
    id), never taken from enumeration order: the mesh position IS the
    rank, so shard↔rank assignment — and therefore the exact output
    bytes and fingerprints of a sharded run — must be reproducible
    across restarts (ISSUE 7).  ``n_devices=None`` honors the
    ``SORT_DEVICES`` knob (auto = all devices)."""
    if n_devices is None and devices is None:
        # the knob only fills the fully-default case: an explicitly
        # passed device list (multihost local devices, tests) must
        # never be silently truncated by ambient environment
        n_devices = knobs.get("SORT_DEVICES")
    if devices is None:
        devices = jax.devices()
    devices = sorted(devices, key=_device_order_key)
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(f"requested {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (AXIS,))


def key_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a [N]-shaped key-word array: block-split on the key axis
    (the TPU equivalent of the reference's MPI_Scatter block distribution,
    ``mpi_sample_sort.c:72-82`` — minus its P∤N overflow bug)."""
    return NamedSharding(mesh, P(AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_bounds(mesh: Mesh, n_per_shard: int) -> list[tuple]:
    """Per-device ``(device, start, stop)`` over the global padded key
    axis — device d owns ``[d*n, (d+1)*n)``, exactly the block split
    :func:`key_sharding` produces.  This is the placement map the
    streaming ingest pipeline (models/ingest.py) uses to route each
    parsed chunk's slices to their owning devices: a chunk that spans a
    boundary splits into per-device pieces, so the DMA of chunk k can
    land while chunk k+1 is still being parsed/encoded."""
    return [
        (d, i * n_per_shard, (i + 1) * n_per_shard)
        for i, d in enumerate(mesh.devices.flat)
    ]


def assemble_sharded(mesh: Mesh, per_device: "list[jax.Array]",
                     total: int) -> jax.Array:
    """Glue per-device single-device buffers (one per mesh device, in
    mesh order, each already committed to its device) into ONE
    key-axis-sharded global array — zero host copies, the closing step
    of the streamed ingest.  The inverse view of :func:`shard_bounds`."""
    return jax.make_array_from_single_device_arrays(
        (total,), key_sharding(mesh), per_device
    )


def multihost_init(coordinator: str | None = None, num_processes: int | None = None,
                   process_id: int | None = None) -> None:
    """Multi-host runtime bring-up (v5e-16-and-beyond path).

    Thin wrapper over ``jax.distributed.initialize`` — the TPU-native
    equivalent of ``MPI_Init`` across nodes; collectives then ride
    ICI within a slice and DCN across slices with no algorithm changes.
    No-op when running single-process (the common case in tests/bench).

    Arguments are validated HERE, fail-fast: a malformed coordinator
    address or an out-of-range process id used to surface as a deep JAX
    hang or traceback minutes into the handshake — on a 16-host launch
    that is 15 healthy hosts blocked on one typo.  All three arguments
    are required together (partial configuration is always a launcher
    bug, never a valid topology).
    """
    if coordinator is None and num_processes is None and process_id is None:
        return  # single-process: nothing to do
    missing = [name for name, v in (("coordinator", coordinator),
                                    ("num_processes", num_processes),
                                    ("process_id", process_id))
               if v is None]
    if missing:
        raise ValueError(
            "multihost_init needs coordinator, num_processes and "
            f"process_id together; missing: {', '.join(missing)} "
            "(call with no arguments for single-process)")
    host, sep, port = str(coordinator).rpartition(":")
    # host.endswith(':') catches port-less IPv6-style typos ('::1',
    # 'fe80::1'): rpartition would split them into a "host" of colons
    # plus a digit-like "port" and wave through exactly the deep-hang
    # address class this validation exists to stop.  Bracketed IPv6
    # ('[::1]:8476') parses fine.
    if not sep or not host or host.endswith(":"):
        raise ValueError(
            f"multihost_init: coordinator {coordinator!r} is not "
            "'host:port' (e.g. '10.0.0.2:8476'; bracket IPv6 hosts as "
            "'[::1]:8476')")
    try:
        port_n = int(port)
    except ValueError:
        port_n = -1
    if not 1 <= port_n <= 65535:
        raise ValueError(
            f"multihost_init: coordinator port {port!r} is not in "
            "[1, 65535]")
    if not isinstance(num_processes, int) or num_processes < 1:
        raise ValueError(
            f"multihost_init: num_processes={num_processes!r} must be an "
            "integer >= 1")
    if not isinstance(process_id, int) or not 0 <= process_id < num_processes:
        raise ValueError(
            f"multihost_init: process_id={process_id!r} must be an integer "
            f"in [0, {num_processes})")
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
