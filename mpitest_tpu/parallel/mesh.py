"""Device mesh construction — the TPU replacement for ``mpirun -np P``.

The reference acquires its process group from ``MPI_Init`` +
``MPI_Comm_size/rank`` (``mpi_sample_sort.c:225-227``).  Here the "process
group" is a 1-D ``jax.sharding.Mesh`` over ICI; rank/size become
``lax.axis_index`` / the static axis size inside ``shard_map``.  The 1-D
logical mesh is kept topology-agnostic so the same algorithm code compiles
over a multi-host ICI+DCN hybrid mesh (v5e-16 config, SURVEY.md §7.3) —
only this module knows about hosts.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "x"  # the single key axis; all sharding is 1-D over it


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """Build the 1-D mesh over all (or the first ``n_devices``) devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(f"requested {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (AXIS,))


def key_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a [N]-shaped key-word array: block-split on the key axis
    (the TPU equivalent of the reference's MPI_Scatter block distribution,
    ``mpi_sample_sort.c:72-82`` — minus its P∤N overflow bug)."""
    return NamedSharding(mesh, P(AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_bounds(mesh: Mesh, n_per_shard: int) -> list[tuple]:
    """Per-device ``(device, start, stop)`` over the global padded key
    axis — device d owns ``[d*n, (d+1)*n)``, exactly the block split
    :func:`key_sharding` produces.  This is the placement map the
    streaming ingest pipeline (models/ingest.py) uses to route each
    parsed chunk's slices to their owning devices: a chunk that spans a
    boundary splits into per-device pieces, so the DMA of chunk k can
    land while chunk k+1 is still being parsed/encoded."""
    return [
        (d, i * n_per_shard, (i + 1) * n_per_shard)
        for i, d in enumerate(mesh.devices.flat)
    ]


def assemble_sharded(mesh: Mesh, per_device: list, total: int):
    """Glue per-device single-device buffers (one per mesh device, in
    mesh order, each already committed to its device) into ONE
    key-axis-sharded global array — zero host copies, the closing step
    of the streamed ingest.  The inverse view of :func:`shard_bounds`."""
    return jax.make_array_from_single_device_arrays(
        (total,), key_sharding(mesh), per_device
    )


def multihost_init(coordinator: str | None = None, num_processes: int | None = None,
                   process_id: int | None = None) -> None:
    """Multi-host runtime bring-up (v5e-16-and-beyond path).

    Thin wrapper over ``jax.distributed.initialize`` — the TPU-native
    equivalent of ``MPI_Init`` across nodes; collectives then ride
    ICI within a slice and DCN across slices with no algorithm changes.
    No-op when running single-process (the common case in tests/bench).
    """
    if coordinator is None and num_processes is None:
        return  # single-process: nothing to do
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
