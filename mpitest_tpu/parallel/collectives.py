"""The comm layer: XLA collectives shaped like the reference's MPI surface.

This is the Python twin of the native ``comm/comm.h`` shim (SURVEY.md §2.3
maps every ``MPI_*`` call to its TPU-native equivalent).  Everything here is
meant to be called *inside* a ``shard_map``-ed function over the 1-D mesh
axis; all shapes are static, so the whole SPMD program compiles to one XLA
executable with collectives scheduled on ICI.

The centerpiece is :func:`ragged_all_to_all` — the replacement for the
reference's hand-rolled ``MPI_Alltoallv`` (payload length smuggled in the
message tag, ``mpi_sample_sort.c:159-171``; per-peer Isend/Recv loops,
``mpi_radix_sort.c:150-173``).  XLA's ``all_to_all`` is fixed-shape, so
variable buckets ride a static per-peer cap with explicit counts — which
*legitimizes* the reference's own fixed ``max_size_bucket``-plus-length-
in-tag scheme, minus the tag hack and minus the silent overflow
(``mpi_sample_sort.c:140-144``): overflow is detected and reported so the
host can retry with the exact required cap (see models/api.py).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from mpitest_tpu import compat, faults
from mpitest_tpu.parallel.mesh import AXIS
from mpitest_tpu.utils import spans

Words = tuple[jax.Array, ...]


def _emit_collective(name: str, x: jax.Array, axis: str,
                     **attrs: object) -> None:
    """Trace-time telemetry: one point event per collective per compile,
    with the static byte accounting (see utils/spans.py on why device
    collectives are trace-time events, not host-timed spans).  ``bytes``
    is the per-rank payload entering the collective; ``bytes_out`` the
    per-rank result size where the axis size is statically known."""
    log = spans.current_log()
    if log is None:
        return
    b_in = int(x.size) * x.dtype.itemsize
    P_ = compat.axis_size(axis)
    if P_ is not None:
        attrs.setdefault("ranks", P_)
        if name == "all_gather":
            attrs.setdefault("bytes_out", b_in * P_)
    # sortlint: disable=SL003 -- name is each wrapper's literal (all registered)
    log.event(name, bytes=b_in, axis=axis, **attrs)


def rank(axis: str = AXIS) -> jax.Array:
    """``MPI_Comm_rank`` → ``lax.axis_index`` (traced scalar)."""
    return lax.axis_index(axis)


def all_gather(x: jax.Array, axis: str = AXIS) -> jax.Array:
    """``MPI_Allgather`` (and the gather-to-root patterns): every shard gets
    [P, ...] — strictly more than MPI's rooted Gather gives, for free."""
    _emit_collective("all_gather", x, axis)
    return lax.all_gather(x, axis)


def psum(x: jax.Array, axis: str = AXIS) -> jax.Array:
    """``MPI_Allreduce(SUM)``."""
    _emit_collective("psum", x, axis, op="sum")
    return lax.psum(x, axis)


def pmax(x: jax.Array, axis: str = AXIS) -> jax.Array:
    _emit_collective("pmax", x, axis, op="max")
    return lax.pmax(x, axis)


def exclusive_cumsum(x: jax.Array, axis: int = 0) -> jax.Array:
    """Exclusive prefix sum — the root-side displacement computation
    (``mpi_sample_sort.c:188-192``) done replicated on-device."""
    c = jnp.cumsum(x, axis=axis)
    return c - x  # exclusive


def block_send_counts(H: jax.Array, n: int, axis: str = AXIS) -> jax.Array:
    """MY per-destination-block send counts, from the replicated histogram
    alone — the cheap pre-exchange behind capacity negotiation (ISSUE 7).

    Under the "destination = exact global position" repartition (the
    radix pass contract, models/radix_sort.py), my keys of digit ``d``
    occupy global positions ``[base[d], base[d] + H[me, d])`` where
    ``base[d] = digit_base[d] + rank_base[me, d]``.  The number of my
    keys landing in destination block s — ``[s·n, (s+1)·n)`` — is then a
    sum of clipped interval intersections over digits: pure arithmetic
    on the ``[P, bins]`` ``H`` matrix every rank already holds after the
    tiny histogram ``all_gather``.  No key moves; the full per-peer
    requirement of the upcoming ragged exchange is known *before* any
    ``[P, cap]`` buffer is allocated, so the host can compile with the
    exact capacity instead of a worst-case guess.

    Returns int32[P]: exact counts this rank will send to each peer
    (self included — the self block never crosses a link but still
    occupies exchange-buffer lanes).
    """
    me = lax.axis_index(axis)
    n_ranks = H.shape[0]
    tot = H.sum(axis=0)                          # [bins]
    digit_base = exclusive_cumsum(tot)           # [bins]
    rank_base = exclusive_cumsum(H, 0)           # [P, bins]
    base = digit_base + rank_base[me]            # [bins] my global run starts
    bounds = lax.iota(jnp.int32, n_ranks + 1) * n
    # cum[s] = #{my keys with dest < s*n} = Σ_d clip(s*n - base[d], 0, H[me, d])
    cum = jnp.clip(bounds[:, None] - base[None, :], 0, H[me][None, :]).sum(
        axis=1)
    return (cum[1:] - cum[:-1]).astype(jnp.int32)


def block_send_segments(h: jax.Array, base: jax.Array, n: int,
                        n_ranks: int) -> tuple[jax.Array, jax.Array]:
    """Contiguous per-destination send segments of MY digit-sorted
    shard, straight from the histogram + its global bases — the fused
    pallas-pass form of :func:`radix_sort._send_segments` (ISSUE 13).

    Under the dest = exact-global-position contract, my keys of digit
    ``d`` occupy global positions ``[base[d], base[d] + h[d])`` and my
    shard is dest-monotone, so the number of my keys landing before
    block boundary ``s·n`` is ``cum[s] = Σ_d clip(s·n − base[d], 0,
    h[d])`` — the same clipped-interval sum as :func:`block_send_counts`
    but anchored at MY ``base`` (= ``digit_base + rank_base[me]``).
    ``send_start[p] = cum[p]`` equals the lax engine's
    ``searchsorted(dest, p·n)`` bit for bit, with **no n-element dest
    array ever materialized**: the histogram → exclusive scan → segment
    chain is [bins]-sized arithmetic, and the pack kernel reads the key
    planes directly.  Returns ``(send_start, send_cnt)``, both int32[P].
    """
    bounds = lax.iota(jnp.int32, n_ranks + 1) * n
    cum = jnp.clip(bounds[:, None] - base[None, :], 0,
                   h[None, :]).sum(axis=1).astype(jnp.int32)
    return cum[:-1], cum[1:] - cum[:-1]


def exscan_counts(h: jax.Array, axis: str = AXIS) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Global exclusive scan of per-rank count vectors.

    ``h``: int32[B] local histogram.  Returns ``(H, tot, rank_base)`` where
    ``H`` is int32[P, B] (all ranks' histograms, replicated via all_gather),
    ``tot[b] = sum_r H[r, b]``, and ``rank_base[r, b] = sum_{r'<r} H[r', b]``
    — the ``MPI_Exscan`` equivalent, computed replicated because P×B is tiny
    next to the key payload.
    """
    H = all_gather(h, axis)            # [P, B]
    tot = H.sum(axis=0)                # [B]
    rank_base = exclusive_cumsum(H, 0)  # [P, B]
    return H, tot, rank_base


def ragged_all_to_all(
    arrays: Words,
    send_start: jax.Array,  # int32[P] — start offset of the segment for peer p
    send_cnt: jax.Array,    # int32[P] — number of valid elements for peer p
    cap: int,               # static per-peer capacity
    n_ranks: int,           # static mesh axis size
    axis: str = AXIS,
    fill: tuple[int, ...] | None = None,  # per-array fill word for invalid lanes
    pack: str = "xla",      # "xla" | "pallas" | "pallas_interpret"
    engine: str = "lax",    # "lax" | "pallas" | "pallas_interpret" (ISSUE 13)
    pre_exchange: "Callable[[jax.Array], Any] | None" = None,
) -> "tuple[Words, jax.Array, jax.Array] | tuple[Words, jax.Array, jax.Array, Any]":
    """``MPI_Alltoallv`` for contiguous ragged segments, on static shapes.

    Each local array is logically partitioned into P contiguous segments
    (``send_start[p] .. send_start[p]+send_cnt[p]``); segment p is delivered
    to rank p.  Both sort algorithms produce *contiguous* per-destination
    segments by construction (keys are in destination-monotone order before
    the exchange), so one monotone scatter spreads the data into the
    ``[P, cap]`` send matrix without any serial packing loop.

    ``engine`` selects the exchange transport (ISSUE 13): ``"lax"`` is
    the XLA collective with the per-array ``pack`` impl; ``"pallas"`` /
    ``"pallas_interpret"`` route through :mod:`mpitest_tpu.ops.exchange`
    — ONE fused multi-word pack kernel plus the remote-DMA all-to-all
    (``lax.all_to_all`` bit-identically under the interpreter, which
    cannot simulate cross-device DMA).  ``pre_exchange(recv_cnt)`` is
    the compute/DMA overlap hook: it runs between the tiny count
    exchange and the payload transport, so work that depends only on
    the counts and replicated state (the next radix pass's lane-slot
    plane) carries **no data dependence on the payload DMAs** and the
    scheduler is free to run it while the buckets are in flight; its
    result is returned as a fourth element.

    Returns ``(recv_arrays, recv_cnt, max_send_cnt[, pre_result])``:
      * ``recv_arrays[k]``: [P, cap] — lane (s, c) holds element c of the
        segment rank s sent to me (valid iff ``c < recv_cnt[s]``);
      * ``recv_cnt``: int32[P] — the explicit count exchange that replaces
        the reference's tag-as-length trick;
      * ``max_send_cnt``: int32 scalar, globally reduced — ``> cap`` means
        the exchange overflowed and lanes were dropped; the caller retries
        with ``cap = max_send_cnt`` (exact, since the program is
        deterministic);
      * ``pre_result``: only when ``pre_exchange`` was given.
    """
    from mpitest_tpu.ops import exchange as xeng
    from mpitest_tpu.ops import kernels

    n = arrays[0].shape[0]
    use_pallas = xeng.is_pallas(engine)
    interp = engine == "pallas_interpret"
    if use_pallas:
        # the engine owns the pack: one fused multi-word kernel sweep
        pack = engine
    log = spans.current_log()
    if log is not None:
        # Static byte accounting of the padded exchange (trace-time; see
        # utils/spans.py): each array ships a [P, cap] block matrix of
        # which the self-block never crosses a link, plus the explicit
        # int32[P] count exchange that replaces the tag-as-length trick.
        itemsize = sum(a.dtype.itemsize for a in arrays)
        log.event(
            "ragged_all_to_all",
            bytes=n_ranks * cap * itemsize + n_ranks * 4,
            wire_bytes=(n_ranks - 1) * cap * itemsize + (n_ranks - 1) * 4,
            ranks=n_ranks, cap=cap, n=n, arrays=len(arrays), pack=pack,
            engine=engine, axis=axis,
        )
    if pack == "xla":
        j = lax.iota(jnp.int32, n)
        # Destination rank and segment start per element, gather-free: two
        # P-element scatters + cumsums (per-element gathers from even tiny
        # tables are ~10× a full sort's cost on v5e; kernels.piecewise_fill).
        p_j = kernels.piecewise_fill(send_start, lax.iota(jnp.int32, n_ranks), n)
        s_j = kernels.piecewise_fill(send_start, send_start, n)
        c_j = j - s_j                                 # offset within segment
        slot = jnp.where(c_j < cap, p_j * cap + c_j, n_ranks * cap)

    # Explicit count exchange (replaces tag-as-length, mpi_sample_sort.c:161,168).
    recv_cnt = lax.all_to_all(jnp.minimum(send_cnt, cap), axis, 0, 0, tiled=True)
    # Overlap hook: issued before the payload transport — depends only
    # on the counts + replicated state, never on the payload DMAs.
    pre_result = pre_exchange(recv_cnt) if pre_exchange is not None else None

    recv_arrays = []
    if use_pallas:
        sends = xeng.fused_pass_pack(
            tuple(arrays), send_start, send_cnt, cap, n_ranks,
            fills=tuple(fill) if fill is not None else (0,) * len(arrays),
            interpret=interp, vma=(axis,),
        )
        for send in sends:
            recv_arrays.append(xeng.remote_a2a(send, n_ranks, axis,
                                               interpret=interp))
    else:
        for k, a in enumerate(arrays):
            fillv = 0 if fill is None else fill[k]
            if pack == "xla":
                send = (
                    jnp.full((n_ranks * cap,), fillv, a.dtype)
                    .at[slot].set(a, mode="drop")
                    .reshape(n_ranks, cap)
                )
            else:
                # Pallas DMA pack: whole-chunk copies, no per-element
                # scatter (4.7× the XLA spread at 2^26 on v5e;
                # ops/pallas_kernels.py).
                from mpitest_tpu.ops.pallas_kernels import segment_pack

                send = segment_pack(
                    a, send_start, send_cnt, cap, n_ranks, fill=fillv,
                    interpret=(pack == "pallas_interpret"), vma=(axis,),
                )
            recv = lax.all_to_all(send, axis, 0, 0, tiled=True)
            recv_arrays.append(recv)

    # Fault injection (ISSUE 3): the armed exchange fault lands HERE —
    # between the all_to_all and the receiver's local sort/merge — the
    # exact window where the reference's overflow bug corrupted data.
    # No-op (and not even traced) unless the dispatching supervisor
    # armed a fault for this compile (mpitest_tpu/faults.py).
    recv_t, recv_cnt = faults.apply_exchange_fault(tuple(recv_arrays),
                                                   recv_cnt)

    max_send_cnt = lax.pmax(send_cnt.max(), axis)
    if pre_exchange is not None:
        return recv_t, recv_cnt, max_send_cnt, pre_result
    return recv_t, recv_cnt, max_send_cnt
