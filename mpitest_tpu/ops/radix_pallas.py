"""Fused radix-pass and merge-order Pallas kernels (local-sort engine #3).

Two kernels, both gated behind ``SORT_LOCAL_ENGINE=radix_pallas``:

* :func:`fused_radix_sort` — LSD radix sort where **one pass is one
  ``pallas_call``**: the digit histogram, the exclusive prefix (bucket
  bases), the per-element rank and the stable scatter all happen inside
  a single kernel over VMEM-resident word planes, replacing the
  ``lax.sort`` / ``searchsorted`` / ``gather`` chain of HBM round-trips
  the lax engine lowers to.  Pass *count* is planner-driven: the pass
  plan is computed on host from per-word value ranges
  (:func:`pass_plan`), so a range-narrow input (e.g. 20 significant
  bits in an int64) sorts in fewer, narrower passes.

* :func:`merge_order` — the device inner loop of the external sort's
  k-way merge: given the lexicographic key planes of one bounded merge
  round it returns the permutation that sorts them, bit-identical to
  the host ``np.lexsort`` it replaces.  The bounded read-ahead and
  safe-boundary logic stay on host in ``store/merge.py``; only the
  rank-by-comparison inner loop runs on device.

Honesty notes (mirrors ops/exchange.py): this engine has only ever run
under ``interpret=True`` on CPU — Mosaic has never lowered it on a real
TPU, so the first TPU-capable session must re-baseline (see PARITY.md).
The fused kernel keeps every word plane as an (n_pad, 1) VMEM ref and
its scatter loop is serial over each chunk; on real hardware the VMEM
footprint caps n well below :data:`FUSED_MAX_ELEMS` per core and the
scatter wants a DMA formulation — both are flagged TPU follow-ups, the
win this image can certify is pass-count and launch-count reduction.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Words = tuple[jax.Array, ...]

#: Digit width of one fused pass.  Bins per kernel = 2**bits + 1: the
#: extra bin is the *pad bin* — padding rows are binned by index, not
#: value, so compacted pass plans that skip constant high bits can
#: never interleave pads with real keys.
DIGIT_BITS = 8

#: Rows per in-kernel chunk of the histogram / rank / scatter loops.
#: Multiple of the (8, 128) native tile's sublane count.
SORT_CHUNK = 512

#: Fused-engine element cap.  Every word plane lives in VMEM as an
#: (n_pad, 1) ref for the whole pass, so ~16 MiB VMEM bounds n_words *
#: n_pad * 4 B; beyond this the resolver falls back to the lax engine.
FUSED_MAX_ELEMS = 1 << 20

#: Widest key (in u32 words) the fused engine accepts.
FUSED_MAX_WORDS = 4

#: Merge-order element cap per merge round.  The rank kernel is
#: O(n^2) compares; above this the host lexsort is the better engine
#: even on TPU, and under interpret the quadratic cost bites early.
MERGE_MAX_ELEMS = 1 << 12

#: Rows per chunk of the merge-order rank loop.
MERGE_CHUNK = 256

#: Smallest padded size the merge kernel compiles for; sizes bucket up
#: to the next power of two so the jit cache stays small across the
#: varying window sizes merge rounds produce.
_MERGE_MIN_PAD = 256

_PAD_WORD = 0xFFFFFFFF

#: Trace-time launch counter: incremented once per fused-pass
#: ``pallas_call`` *trace*.  The launch-count acceptance gate compiles
#: a fresh shape and asserts the delta equals the pass-plan length —
#: one kernel launch per pass, no hidden sort/gather chain.
_PASS_LAUNCHES = 0


def pass_launches() -> int:
    """Return the number of fused-pass kernels traced so far."""
    return _PASS_LAUNCHES


def pass_plan(diffs: tuple[int, ...] | None,
              n_words: int,
              digit_bits: int = DIGIT_BITS,
              ) -> tuple[tuple[int, int, int], ...]:
    """Plan the fused passes for a key whose per-word value ranges are
    known.

    ``diffs`` is msw-first (``diffs[0]`` is the most significant word),
    each entry the XOR-fold / max-min spread of that word — the same
    shape ``models/api.py`` feeds ``_passes_from_diffs``.  ``None``
    means "unknown": plan full-width passes for every word.

    Returns ``((word_idx, shift, bits), ...)`` in execution order
    (least-significant word first — LSD radix), where ``bits`` may be
    narrower than ``digit_bits`` on the top pass of a word.  Words
    whose range is constant are skipped entirely: that is the
    key-width-compaction win.
    """
    if diffs is None:
        diffs = (_PAD_WORD,) * n_words
    if len(diffs) != n_words:
        raise ValueError(
            f"pass_plan: {len(diffs)} diffs for {n_words} words")
    plan: list[tuple[int, int, int]] = []
    for wi in range(n_words - 1, -1, -1):       # lsw -> msw
        width = int(diffs[wi]).bit_length()
        shift = 0
        while shift < width:
            bits = min(digit_bits, width - shift)
            plan.append((wi, shift, bits))
            shift += bits
    return tuple(plan)


def _pass_kernel(n: int, n_words: int, widx: int, shift: int, bits: int,
                 chunk: int, *refs) -> None:
    """One radix pass: histogram -> exclusive prefix -> stable scatter.

    ``refs`` = n_words input planes, n_words output planes, then one
    (chunk, 1) int32 scratch; every plane is (n_pad, 1) uint32 in VMEM.
    Rows at index >= n are pads and are forced into the extra bin
    ``bins`` regardless of content, so they sit stably at the tail of
    every pass and real rows keep the invariant "reals in [0, n)".
    """
    in_refs = refs[:n_words]
    out_refs = refs[n_words:2 * n_words]
    dest_scr = refs[2 * n_words]
    n_pad = in_refs[0].shape[0]
    nchunks = n_pad // chunk
    bins = 1 << bits
    mask = jnp.uint32(bins - 1)
    bin_iota = lax.broadcasted_iota(jnp.int32, (1, bins + 1), 1)

    def onehot(c):
        w = in_refs[widx][pl.ds(c * chunk, chunk), :]
        d = ((w >> jnp.uint32(shift)) & mask).astype(jnp.int32)
        row = c * chunk + lax.broadcasted_iota(jnp.int32, (chunk, 1), 0)
        d = jnp.where(row < n, d, bins)
        return (d == bin_iota).astype(jnp.int32)        # (chunk, bins+1)

    def hist_body(c, h):
        return h + jnp.sum(onehot(c), axis=0, keepdims=True)

    hist = lax.fori_loop(
        0, nchunks, hist_body, jnp.zeros((1, bins + 1), jnp.int32))
    base = jnp.cumsum(hist, axis=1) - hist              # exclusive

    def scatter_body(c, seen):
        oh = onehot(c)
        # Rank within the chunk among equal digits (stable), then add
        # the bucket base plus the count already scattered by earlier
        # chunks ("seen").
        prior = jnp.cumsum(oh, axis=0) - oh
        dest_scr[...] = jnp.sum(
            oh * (base + seen + prior), axis=1, keepdims=True)

        def store(j, carry):
            dst = dest_scr[j, 0]
            src = c * chunk + j
            for w_in, w_out in zip(in_refs, out_refs):
                w_out[dst, 0] = w_in[src, 0]
            return carry

        lax.fori_loop(0, chunk, store, 0)
        return seen + jnp.sum(oh, axis=0, keepdims=True)

    lax.fori_loop(
        0, nchunks, scatter_body, jnp.zeros((1, bins + 1), jnp.int32))


def _fused_pass(planes: Words, n: int, widx: int, shift: int, bits: int,
                interpret: bool) -> Words:
    """Run ONE radix pass as ONE ``pallas_call`` over padded planes."""
    global _PASS_LAUNCHES
    _PASS_LAUNCHES += 1
    n_words = len(planes)
    n_pad = planes[0].shape[0]
    out = pl.pallas_call(
        functools.partial(
            _pass_kernel, n, n_words, widx, shift, bits, SORT_CHUNK),
        out_shape=[jax.ShapeDtypeStruct((n_pad, 1), jnp.uint32)
                   for _ in range(n_words)],
        scratch_shapes=[pltpu.VMEM((SORT_CHUNK, 1), jnp.int32)],
        interpret=interpret,
    )(*planes)
    return tuple(out)


def fused_radix_sort(words: Words,
                     diffs: tuple[int, ...] | None = None,
                     digit_bits: int = DIGIT_BITS,
                     interpret: bool = False) -> Words:
    """Sort u32 word planes lexicographically (words[0] most
    significant) with one fused kernel launch per radix pass.

    Bit-identical to ``lax.sort(words, num_keys=len(words))`` for any
    ``diffs`` that covers the data (``None`` always does): each pass is
    a stable counting sort by the planned digit, and constant bits
    never discriminate.  ``diffs`` must be host-static — the planner
    derives it from the profiler's per-word min/max.
    """
    n_words = len(words)
    n = int(words[0].shape[0])
    plan = pass_plan(diffs, n_words, digit_bits)
    if n <= 1 or not plan:
        # Zero/one element, or every word constant: already sorted.
        return words
    n_pad = -(-n // SORT_CHUNK) * SORT_CHUNK
    pad = n_pad - n
    if pad:
        fill = jnp.full((pad,), _PAD_WORD, jnp.uint32)
        planes = tuple(jnp.concatenate([w, fill]).reshape(n_pad, 1)
                       for w in words)
    else:
        planes = tuple(w.reshape(n_pad, 1) for w in words)
    for widx, shift, bits in plan:
        planes = _fused_pass(planes, n, widx, shift, bits, interpret)
    return tuple(p.reshape(-1)[:n] for p in planes)


# ---------------------------------------------------------------------
# Device merge-order kernel (external sort / store compaction inner loop)
# ---------------------------------------------------------------------


def _cmp_i32(x: jax.Array) -> jax.Array:
    """Order-preserving u32 -> i32 bijection (sign-flip + bitcast).

    Mosaic has no unsigned vector compare; flipping the sign bit and
    comparing as int32 yields the unsigned order.
    """
    return lax.bitcast_convert_type(x ^ jnp.uint32(0x80000000), jnp.int32)


def _order_kernel(n_planes: int, chunk: int, *refs) -> None:
    """Rank-by-comparison merge order: rank[i] = #{j : key_j < key_i},
    lexicographic over ``n_planes`` planes (plane 0 most significant).

    ``refs`` = n_planes column planes (n_pad, 1), the SAME n_planes
    planes again in row layout (1, n_pad) — passed twice from host to
    avoid an in-kernel transpose — then the (n_pad, 1) int32 order
    output and a (chunk, 1) int32 rank scratch.  Keys must be unique
    (the caller appends run-id and position tie-breaker planes), so
    ranks form a permutation and every output row is written once.
    """
    cols = refs[:n_planes]
    rows = refs[n_planes:2 * n_planes]
    out_ref = refs[2 * n_planes]
    rank_scr = refs[2 * n_planes + 1]
    n_pad = cols[0].shape[0]
    nchunks = n_pad // chunk

    def body(c, carry):
        lt = None
        eq = None
        for colr, rowr in zip(cols, rows):
            a = _cmp_i32(colr[pl.ds(c * chunk, chunk), :])  # (chunk, 1)
            b = _cmp_i32(rowr[...])                         # (1, n_pad)
            p_lt = b < a
            if lt is None:
                lt, eq = p_lt, (b == a)
            else:
                lt = lt | (eq & p_lt)
                eq = eq & (b == a)
        rank_scr[...] = jnp.sum(lt.astype(jnp.int32), axis=1,
                                keepdims=True)

        def store(j, k):
            # order[rank_i] = i : scatter this chunk's global indices.
            out_ref[rank_scr[j, 0], 0] = c * chunk + j
            return k

        lax.fori_loop(0, chunk, store, 0)
        return carry

    lax.fori_loop(0, nchunks, body, 0)


@functools.lru_cache(maxsize=32)
def _compile_merge_order(n_planes: int, n_pad: int, interpret: bool):
    """jit-compiled merge-order entry for one (plane count, padded
    size) bucket; the pallas_call sits behind the literal ``interpret``
    parameter (SL013)."""

    def run(*planes):
        cols = tuple(p.reshape(n_pad, 1) for p in planes)
        rows = tuple(p.reshape(1, n_pad) for p in planes)
        order = pl.pallas_call(
            functools.partial(_order_kernel, n_planes, MERGE_CHUNK),
            out_shape=jax.ShapeDtypeStruct((n_pad, 1), jnp.int32),
            scratch_shapes=[pltpu.VMEM((MERGE_CHUNK, 1), jnp.int32)],
            interpret=interpret,
        )(*cols, *rows)
        return order.reshape(-1)

    return jax.jit(run)


def merge_order(planes: Words, interpret: bool = False) -> jax.Array:
    """Return the int32 permutation that sorts ``planes``
    lexicographically (plane 0 most significant).

    Device twin of ``np.lexsort((planes[-1], ..., planes[0]))`` —
    bit-identical when keys are unique, which ``store/merge.py``
    guarantees by appending (run id, position) tie-breaker planes.
    The LAST plane must never legitimately hold 0xFFFFFFFF (positions
    and run ids are small), because pads claim that value and stay
    unique via an iota in the final plane.
    """
    n_planes = len(planes)
    n = int(planes[0].shape[0])
    if n > MERGE_MAX_ELEMS:
        raise ValueError(
            f"merge_order: n={n} above MERGE_MAX_ELEMS={MERGE_MAX_ELEMS}"
            " — O(n^2) ranking; use the host lexsort")
    if n <= 1:
        return jnp.zeros((n,), jnp.int32)
    n_pad = _MERGE_MIN_PAD
    while n_pad < n:
        n_pad *= 2
    pad = n_pad - n
    if pad:
        hi = jnp.full((pad,), _PAD_WORD, jnp.uint32)
        # Pads outrank every real key on the leading planes; the final
        # plane's iota keeps them mutually distinct so the rank image
        # is a full permutation.
        tie = jnp.arange(pad, dtype=jnp.uint32)
        padded = tuple(
            jnp.concatenate([jnp.asarray(p, jnp.uint32),
                             tie if i == n_planes - 1 else hi])
            for i, p in enumerate(planes))
    else:
        padded = tuple(jnp.asarray(p, jnp.uint32) for p in planes)
    order = _compile_merge_order(n_planes, n_pad, interpret)(*padded)
    return order[:n]
