from mpitest_tpu.ops.keys import KeyCodec, codec_for  # noqa: F401
from mpitest_tpu.ops import kernels  # noqa: F401
