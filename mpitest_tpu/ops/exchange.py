"""Pallas ICI exchange engine — remote-DMA all-to-all + the fused pass pack.

The generic exchange (``parallel/collectives.py ragged_all_to_all`` with
``engine="lax"``) lowers the inter-device hop through ``lax.all_to_all``:
XLA owns the schedule, every pass pays a scatter (or per-array pack
kernel) into the send matrix, and the receive side cannot begin until
the collective op retires.  This module is the second engine
(``SORT_EXCHANGE_ENGINE={auto,lax,pallas,pallas_interpret}``): the
rank-to-rank hop becomes a Pallas kernel that streams each negotiated
per-peer bucket straight into the peer's recv buffer over ICI with
``pltpu.make_async_remote_copy`` + DMA semaphores (SNIPPETS.md [1]/[3]
pattern), and the per-pass pack fuses into ONE multi-word kernel sweep.

Three pieces:

* :func:`fused_pass_pack` — the fused radix-pass pack: ALL key words
  spread into their ``[P, cap]`` send matrices in one kernel over the
  existing pack kernel's (8, 128)/CHUNK tiling (``ops/pallas_kernels``).
  The segment table it prefetches IS the histogram + exclusive-scan
  output (the clip-arithmetic ``block_send_segments`` of
  ``parallel/collectives.py``), so the per-pass chain histogram → scan →
  pack touches the n-element key planes exactly once — the lax engine's
  per-pass ``dest`` materialization (K-element scatter + cumsum + iota +
  searchsorted, three extra n-element HBM round-trips) does not exist
  on this path.  Per output chunk the kernel runs one address/validity
  computation and one 2-chunk DMA **per word**, versus one whole
  ``segment_pack`` launch (scalar prefetch, grid setup, address math)
  per word per pass.
* :func:`remote_a2a` — the rank-to-rank transport: every peer stream is
  started before any is waited on, so all P-1 outgoing buckets are in
  flight concurrently while the local self-block copy (and, upstream,
  the next pass's lane-slot plane — see ``models/radix_sort.py``)
  computes; this is the compute/DMA overlap the XLA collective cannot
  express.  A neighborhood barrier (``get_barrier_semaphore``) keeps a
  fast rank from writing into a peer whose recv buffer is not yet live.
* :func:`digit_histogram_words` is deliberately absent: the per-pass
  histogram stays on the post-sort ``searchsorted`` form
  (``ops/kernels.histogram_sorted``) — counts are order-invariant and
  that form is one log-pass over data the sort just touched; a Mosaic
  scatter histogram would need the per-element cross-tile addressing
  the VPU lacks (see ``ops/pallas_kernels.py`` module docstring).

Interpret-mode contract (this image: CPU-only, jax 0.4.37): the Pallas
interpreter cannot simulate a cross-device DMA (``make_async_remote_copy``
rejects traced ``device_id`` outside a real TPU lowering), so
``interpret=True`` routes the transport through ``lax.all_to_all`` —
**bit-identical semantics** (``recv[s] = the row rank s sent me``) —
while the fused pack kernel, the no-dest segment arithmetic and the
whole engine plumbing run for real under the interpreter.  That is what
the parity gates pin (``bench/multichip_selftest.py`` engine axis,
``tests/test_zz_exchange.py``); the remote-DMA kernel itself lowers
only on a TPU backend, where the supervisor ladder (pallas → lax,
fingerprint-verified) guarantees a kernel bug degrades loudly instead
of shipping a wrong answer.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpitest_tpu import compat
from mpitest_tpu.ops.pallas_kernels import (
    CHUNK, LANES, ROWS, chunk_geometry)

#: Engine names accepted by the exchange dispatch (the knob adds "auto").
ENGINES = ("lax", "pallas", "pallas_interpret")

#: ``collective_id`` of the remote-DMA kernel's barrier semaphore — one
#: exchange kernel class exists, so one id suffices (ids must only be
#: unique across concurrently-running collective Pallas kernels).
_A2A_COLLECTIVE_ID = 7


def is_pallas(engine: str) -> bool:
    """True for both execution forms of the Pallas engine."""
    return engine.startswith("pallas")


def _fused_pack_kernel(n: int, fills: tuple[int, ...], n_arrays: int,
                       starts_ref, cnts_ref, *refs) -> None:
    """Grid (P, cap//CHUNK): instance (p, i) produces output chunk i of
    destination p for EVERY word plane: ``data[starts[p] + i*CHUNK
    ...][:CHUNK]`` where in-segment, the per-word fill beyond
    ``cnts[p]``.  One address/shift/validity computation serves all
    words; the per-word DMAs are all started before any is waited on.
    """
    data_refs = refs[:n_arrays]
    out_refs = refs[n_arrays:2 * n_arrays]
    scratch = refs[2 * n_arrays:3 * n_arrays]
    sems = refs[3 * n_arrays:4 * n_arrays]
    p = pl.program_id(0)
    i = pl.program_id(1)
    # ONE address/shift/validity computation serves every word plane —
    # the geometry itself is shared with the per-array pack kernel
    # (pallas_kernels.chunk_geometry: one home for the invariants).
    arow, shift, valid = chunk_geometry(starts_ref[p], cnts_ref[p], i, n)

    dmas = [
        pltpu.make_async_copy(
            data_refs[a].at[pl.ds(arow, 2 * ROWS), :], scratch[a], sems[a]
        )
        for a in range(n_arrays)
    ]
    for dma in dmas:
        dma.start()

    for a in range(n_arrays):
        dmas[a].wait()
        out_refs[a][0, 0] = jnp.where(valid, shift(scratch[a][...]),
                                      jnp.uint32(fills[a]))


@functools.partial(
    jax.jit,
    static_argnames=("cap", "n_ranks", "fills", "interpret", "vma"),
)
def fused_pass_pack(
    arrays: tuple[jax.Array, ...],  # uint32[n] each; segment p = [starts[p]:+cnts[p]]
    starts: jax.Array,              # int32[P], ascending, starts[0] == 0
    cnts: jax.Array,                # int32[P]
    cap: int,                       # static row capacity, multiple of CHUNK
    n_ranks: int,
    fills: tuple[int, ...] = (),    # per-array fill word (default 0)
    interpret: bool = False,
    vma: tuple[str, ...] = (),
) -> tuple[jax.Array, ...]:         # uint32[P, cap] per array
    """Spread every word plane's ragged segments into its padded send
    matrix in ONE kernel sweep (the fused radix-pass pack)."""
    assert cap % CHUNK == 0, cap
    n_arrays = len(arrays)
    if not fills:
        fills = (0,) * n_arrays
    n = arrays[0].shape[0]
    pad = (-n) % LANES + 2 * CHUNK   # row-shape the data + DMA headroom
    data_2d = tuple(
        jnp.concatenate([a, jnp.zeros((pad,), a.dtype)]).reshape(-1, LANES)
        for a in arrays
    )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_ranks, cap // CHUNK),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * n_arrays,
        out_specs=tuple(
            pl.BlockSpec((1, 1, ROWS, LANES), lambda p, i, *_: (p, i, 0, 0))
            for _ in range(n_arrays)
        ),
        scratch_shapes=(
            [pltpu.VMEM((2 * ROWS, LANES), jnp.uint32)] * n_arrays
            + [pltpu.SemaphoreType.DMA(())] * n_arrays
        ),
    )
    outs = pl.pallas_call(
        functools.partial(_fused_pack_kernel, n, fills, n_arrays),
        grid_spec=grid_spec,
        out_shape=tuple(
            compat.shape_dtype_struct(
                (n_ranks, cap // CHUNK, ROWS, LANES), a.dtype, vma=vma,
            )
            for a in arrays
        ),
        interpret=interpret,
    )(starts.astype(jnp.int32), cnts.astype(jnp.int32), *data_2d)
    if n_arrays == 1 and not isinstance(outs, (tuple, list)):
        outs = (outs,)
    return tuple(o.reshape(n_ranks, cap) for o in outs)


def _remote_a2a_kernel(n_ranks: int, axis: str, x_ref, out_ref,
                       local_sem, send_sems, recv_sems) -> None:
    """All-to-all over ICI: rank r's row ``x[dst]`` lands in dst's
    ``out[r]``.  Balanced permutation schedule (step k: send to
    ``(me+k) % P``, receive from ``(me-k) % P`` on slot k) — every
    link carries one stream per step and no two ranks convoy on the
    same destination.  All remote streams START before anything is
    waited on: the P-1 bucket sends are in flight while the local
    self-block copy runs — the kernel-level half of the engine's
    compute/DMA overlap (the pass-loop half precomputes the next
    pass's lane-slot plane during the same window, models/radix_sort).
    """
    me = lax.axis_index(axis)

    # Ready barrier: a fast rank must not stream into a peer whose
    # output buffer is not yet live in this kernel invocation.
    barrier = pltpu.get_barrier_semaphore()
    for k in range(1, n_ranks):
        peer = (me + k) % n_ranks
        pltpu.semaphore_signal(
            barrier, inc=1, device_id=(peer,),
            device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_wait(barrier, n_ranks - 1)

    # Self block: a local HBM copy, overlapped with the remote streams.
    local = pltpu.make_async_copy(x_ref.at[me], out_ref.at[me], local_sem)
    local.start()

    copies = []
    for k in range(1, n_ranks):
        dst = (me + k) % n_ranks
        # dst_ref is addressed with MY rank: on the receiving core the
        # same SPMD expression denotes row <sender> of ITS buffer.
        rc = pltpu.make_async_remote_copy(
            src_ref=x_ref.at[dst],
            dst_ref=out_ref.at[me],
            send_sem=send_sems.at[k],
            recv_sem=recv_sems.at[k],
            device_id=(dst,),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rc.start()
        copies.append(rc)

    local.wait()
    for rc in copies:
        # wait() = wait_send + wait_recv: slot k's recv is the row from
        # (me-k) % P — its sender also used slot k, and all rows are
        # uniformly shaped, so the descriptor prices the wait exactly.
        rc.wait()


def remote_a2a(
    x: jax.Array,           # [P, cap] — row p is my bucket for rank p
    n_ranks: int,
    axis: str,
    interpret: bool = False,
) -> jax.Array:             # [P, cap] — row s is the bucket rank s sent me
    """Rank-to-rank bucket exchange: remote-DMA kernel on TPU, the
    bit-identical ``lax.all_to_all`` under ``interpret`` (the Pallas
    interpreter cannot simulate cross-device DMA — module docstring).
    """
    if n_ranks == 1:
        return x
    if interpret:
        # Same contract, XLA transport: recv[s] = row sent by rank s.
        return lax.all_to_all(x, axis, 0, 0, tiled=True)
    cap = x.shape[1]
    x3 = x.reshape(n_ranks, cap // LANES, LANES)
    out = pl.pallas_call(
        functools.partial(_remote_a2a_kernel, n_ranks, axis),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=compat.shape_dtype_struct(
            (n_ranks, cap // LANES, LANES), x.dtype, vma=(axis,)),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((n_ranks,)),
            pltpu.SemaphoreType.DMA((n_ranks,)),
        ],
        compiler_params=compat.tpu_compiler_params(
            collective_id=_A2A_COLLECTIVE_ID),
        # never interpreted: interpret=True returned above via the
        # bit-identical lax transport — the interpreter cannot simulate
        # the cross-device DMA this kernel exists for
        interpret=False,
    )(x3)
    return out.reshape(n_ranks, cap)
