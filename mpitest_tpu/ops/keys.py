"""Key codecs: map host integer dtypes to tuples of sortable uint32 words.

TPU-first design decision: JAX on TPU runs with 32-bit integers by default
(no x64), and the MXU/VPU paths are widest for 32-bit lanes.  Rather than
flipping global x64 flags, every key dtype is encoded as a tuple of
**uint32 words, most-significant first**, such that lexicographic unsigned
comparison of the word tuple equals the native comparison of the original
keys.  ``lax.sort`` with ``num_keys=len(words)`` then sorts any supported
dtype, and LSD radix passes simply iterate words from least- to
most-significant.

This fixes a reference bug: ``mpi_radix_sort.c:50,56`` takes ``abs(value)``,
so negative keys sort by magnitude with the sign dropped.  The biased
encoding here (sign-bit flip) makes signed sorts actually correct; the
divergence is documented in SURVEY.md §7.4.

Host-side padding (models/api.py) replicates the maximum *real* key, not a
synthetic sentinel, so pads never widen the key range the radix pass
planner sees.  The all-ones word :data:`MAX_WORD` is still used inside the
SPMD programs as the fill for invalid exchange lanes (sample sort), where
it guarantees fills sort to the tail of the static buffer; validity there
is tracked by explicit counts, so collisions with real all-ones keys are
harmless.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

_SIGN32 = np.uint32(0x80000000)

#: All-ones uint32 word — exchange-lane fill that sorts to the tail.
MAX_WORD = 0xFFFFFFFF


@dataclass(frozen=True)
class KeyCodec:
    """Encode/decode a host numeric dtype to/from uint32 word tuples.

    Floats use the IEEE total-order flip (negative values: all bits
    inverted; non-negative: sign bit set), a bit-preserving bijection, so
    NaNs, infinities, -0.0 < +0.0 and NaN payloads all sort in
    ``totalOrder`` and decode back to their exact input bits.  This is a
    *documented divergence* from ``np.sort`` (which moves every NaN to
    the tail and treats ±0.0 as equal); the sorted multiset of bit
    patterns is identical.
    """

    dtype: np.dtype
    n_words: int
    #: pad with the all-ones sentinel instead of the max real key
    #: (floats: np.max is NaN-poisoned and NaN payloads break max-key
    #: padding; the sentinel is the totalOrder maximum by construction).
    sentinel_pad: bool = False

    def _split64(self, u: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        return (
            (u >> np.uint64(32)).astype(np.uint32),
            (u & np.uint64(0xFFFFFFFF)).astype(np.uint32),
        )

    def encode(self, x: np.ndarray) -> tuple[np.ndarray, ...]:
        """Host array -> tuple of uint32 word arrays, most-significant first."""
        x = np.asarray(x, dtype=self.dtype)
        if self.dtype in (np.dtype(np.int16), np.dtype(np.uint16),
                          np.dtype(np.int8), np.dtype(np.uint8)):
            # narrow ints widen losslessly into the 32-bit paths
            wide = np.int32 if self.dtype.kind == "i" else np.uint32
            return codec_for(wide).encode(x.astype(wide))
        if self.dtype == np.dtype(np.int32):
            return ((x.view(np.uint32) ^ _SIGN32),)
        if self.dtype == np.dtype(np.uint32):
            return (x.copy(),)
        if self.dtype == np.dtype(np.float32):
            u = x.view(np.uint32)
            return (np.where(u & _SIGN32, ~u, u ^ _SIGN32),)
        if self.dtype == np.dtype(np.int64):
            return self._split64(x.view(np.uint64) ^ np.uint64(0x8000000000000000))
        if self.dtype == np.dtype(np.uint64):
            return self._split64(x)
        if self.dtype == np.dtype(np.float64):
            u = x.view(np.uint64)
            s = np.uint64(0x8000000000000000)
            return self._split64(np.where(u & s, ~u, u ^ s))
        raise TypeError(f"unsupported key dtype: {self.dtype}")

    def decode(self, words: tuple[np.ndarray, ...]) -> np.ndarray:
        """Tuple of uint32 word arrays (msw first) -> host array of dtype."""
        words = tuple(np.asarray(w, dtype=np.uint32) for w in words)
        if len(words) != self.n_words:
            raise ValueError(f"expected {self.n_words} words, got {len(words)}")
        if self.dtype in (np.dtype(np.int16), np.dtype(np.uint16),
                          np.dtype(np.int8), np.dtype(np.uint8)):
            wide = np.int32 if self.dtype.kind == "i" else np.uint32
            return codec_for(wide).decode(words).astype(self.dtype)
        if self.dtype == np.dtype(np.int32):
            return (words[0] ^ _SIGN32).view(np.int32)
        if self.dtype == np.dtype(np.uint32):
            return words[0].copy()
        if self.dtype == np.dtype(np.float32):
            e = words[0]
            return np.where(e & _SIGN32, e ^ _SIGN32, ~e).view(np.float32)
        u = (words[0].astype(np.uint64) << np.uint64(32)) | words[1].astype(np.uint64)
        if self.dtype == np.dtype(np.int64):
            return (u ^ np.uint64(0x8000000000000000)).view(np.int64)
        if self.dtype == np.dtype(np.float64):
            s = np.uint64(0x8000000000000000)
            return np.where(u & s, u ^ s, ~u).view(np.float64)
        return u  # uint64

    def encode_jax(self, x):
        """Device-side encode: bitcast + sign-bias XOR, elementwise — XLA
        fuses it into the consumer sort.

        64-bit dtypes (which only exist as device arrays under
        ``jax_enable_x64``) never touch 64-bit arithmetic here:
        ``bitcast_convert_type`` to uint32 appends a trailing word dim
        (minor word = least significant on TPU/x86), so the split into
        (hi, lo) uint32 words is a pure relayout that works with or
        without x64 — device-resident 64-bit keys stay on the mesh with
        no host round-trip (the framework's steady-state contract)."""
        import jax.numpy as jnp
        from jax import lax

        if self.dtype in (np.dtype(np.int16), np.dtype(np.uint16),
                          np.dtype(np.int8), np.dtype(np.uint8)):
            wide = jnp.int32 if self.dtype.kind == "i" else jnp.uint32
            return codec_for(np.dtype(wide)).encode_jax(x.astype(wide))
        if self.dtype == np.dtype(np.int32):
            return (lax.bitcast_convert_type(x, jnp.uint32) ^ jnp.uint32(0x80000000),)
        if self.dtype == np.dtype(np.uint32):
            return (x,)
        if self.dtype == np.dtype(np.float32):
            u = lax.bitcast_convert_type(x, jnp.uint32)
            neg = (u & jnp.uint32(0x80000000)) != 0
            return (jnp.where(neg, ~u, u ^ jnp.uint32(0x80000000)),)
        if self.dtype in (np.dtype(np.int64), np.dtype(np.uint64),
                          np.dtype(np.float64)):
            if x.dtype != self.dtype:
                raise TypeError(
                    f"device array has dtype {x.dtype}, expected {self.dtype} "
                    "(64-bit device-resident keys require jax_enable_x64)"
                )
            w = lax.bitcast_convert_type(x, jnp.uint32)  # [..., 2], minor=lsw
            lo, hi = w[..., 0], w[..., 1]
            if self.dtype == np.dtype(np.int64):
                hi = hi ^ jnp.uint32(0x80000000)
            elif self.dtype == np.dtype(np.float64):
                neg = (hi & jnp.uint32(0x80000000)) != 0
                hi2 = jnp.where(neg, ~hi, hi ^ jnp.uint32(0x80000000))
                lo = jnp.where(neg, ~lo, lo)
                hi = hi2
            return (hi, lo)
        raise TypeError(f"device-side encode unsupported for {self.dtype}")

    def max_sentinel(self) -> tuple[int, ...]:
        """Word values that encode the maximum representable key (sorts
        last); the per-word exchange-lane fill (see :data:`MAX_WORD`)."""
        return (MAX_WORD,) * self.n_words


_CODECS = {
    np.dtype(np.int8): KeyCodec(np.dtype(np.int8), 1),
    np.dtype(np.uint8): KeyCodec(np.dtype(np.uint8), 1),
    np.dtype(np.int16): KeyCodec(np.dtype(np.int16), 1),
    np.dtype(np.uint16): KeyCodec(np.dtype(np.uint16), 1),
    np.dtype(np.int32): KeyCodec(np.dtype(np.int32), 1),
    np.dtype(np.uint32): KeyCodec(np.dtype(np.uint32), 1),
    np.dtype(np.int64): KeyCodec(np.dtype(np.int64), 2),
    np.dtype(np.uint64): KeyCodec(np.dtype(np.uint64), 2),
    np.dtype(np.float32): KeyCodec(np.dtype(np.float32), 1, sentinel_pad=True),
    np.dtype(np.float64): KeyCodec(np.dtype(np.float64), 2, sentinel_pad=True),
}


def codec_for(dtype) -> KeyCodec:
    dt = np.dtype(dtype)
    if dt not in _CODECS:
        raise TypeError(
            f"unsupported key dtype {dt}; supported: {sorted(str(k) for k in _CODECS)}"
        )
    return _CODECS[dt]
