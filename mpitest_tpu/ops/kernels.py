"""Local (per-shard) kernels: sort, histogram, digit extraction, bucketing.

These are the TPU-native equivalents of the reference's local compute
kernels — libc ``qsort`` (``mpi_sample_sort.c:85,174``), the floating-point
digit math (``mpi_radix_sort.c:48-58``), and the O(P)-per-key linear bucket
scan (``mpi_sample_sort.c:148-155``).  All shapes are static; everything
composes under ``jit`` / ``shard_map``.  Digit math is pure integer
shift/mask (the reference's ``pow()``-based digits are a precision hazard).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

Words = tuple[jax.Array, ...]


def local_sort(words: Words, engine: str = "lax") -> Words:
    """Lexicographic stable sort of multi-word keys (msw first).

    ``lax.sort`` with ``num_keys=len(words)`` compares word tuples
    lexicographically — this is how 64-bit keys sort without x64.

    ``engine="bitonic"`` routes one-word keys through the Pallas bitonic
    engine (``ops/bitonic.py``, 1.64x ``lax.sort`` at 2^28 on v5e) —
    including under ``shard_map``, which is how the distributed sample
    sort accelerates its per-shard sorts on real TPU meshes.
    ``engine="bitonic_interpret"`` runs the same kernel through the
    Pallas interpreter (the virtual CPU-mesh tests).  The choice is
    explicit rather than backend-sniffed so that AOT compilation for a
    TPU *topology* from a CPU-pinned process lowers the real Mosaic
    kernels (see tests/test_aot_topology.py).  Multi-word keys always
    use ``lax.sort``.
    """
    if engine.startswith("bitonic") and len(words) == 1:
        from mpitest_tpu.ops import bitonic  # local import: optional path

        interpret = engine == "bitonic_interpret"
        return (bitonic.bitonic_sort_u32(words[0], interpret=interpret),)
    if len(words) == 1:
        return (jnp.sort(words[0]),)
    return tuple(lax.sort(list(words), num_keys=len(words), is_stable=True))


def digit_at(word: jax.Array, shift: int, bits: int) -> jax.Array:
    """Extract the ``bits``-wide digit at bit offset ``shift`` (int32 result)."""
    mask = jnp.uint32((1 << bits) - 1)
    return ((word >> jnp.uint32(shift)) & mask).astype(jnp.int32)


def histogram(digits: jax.Array, n_bins: int) -> jax.Array:
    """Count occurrences of each digit value. Scatter-add; XLA lowers this
    to an efficient on-chip combiner. Returns int32[n_bins]."""
    return jnp.zeros((n_bins,), jnp.int32).at[digits].add(1)


def histogram_sorted(sorted_digits: jax.Array, n_bins: int) -> tuple[jax.Array, jax.Array]:
    """Histogram of an already-sorted digit array via binary search.

    Returns ``(h, lo)`` where ``h[b]`` is the count of digit ``b`` and
    ``lo[b]`` the offset of its first occurrence.  On TPU this replaces the
    scatter-add histogram for the radix pass: scatter lowers to serialized
    updates (measured ~40× slower than the searchsorted form at 2^26 on
    v5e), while ``searchsorted`` over sorted data is a vectorized binary
    search that costs nothing next to the sort we already did.
    """
    edges = jnp.searchsorted(
        sorted_digits, lax.iota(jnp.int32, n_bins + 1)
    ).astype(jnp.int32)
    return jnp.diff(edges), edges[:-1]


def piecewise_fill(starts: jax.Array, values: jax.Array, n: int) -> jax.Array:
    """Materialize a step function: ``out[j] = values[k]`` for
    ``starts[k] <= j < starts[k+1]`` (``starts`` sorted ascending,
    ``starts[0] == 0``; empty segments and ``starts[k] == n`` tails fine).

    This is the gather-free alternative to ``values[segment_id]`` — a
    K-element scatter-add of successive differences followed by a cumsum.
    Per-element gathers from even a 256-entry table measured ~10× the cost
    of a full sort at 2^26 on v5e; K-element scatters and cumsum are cheap.
    """
    delta = jnp.concatenate([values[:1], jnp.diff(values)])
    arr = jnp.zeros((n,), values.dtype).at[starts].add(delta, mode="drop")
    return jnp.cumsum(arr)


def searchsorted_words(sorted_bounds: Words, keys: Words) -> jax.Array:
    """For each key, count how many bounds are < key (lexicographic).

    Multi-word generalization of ``jnp.searchsorted(side='left')`` used for
    splitter bucketing: ``dest[i] = #{j : bounds[j] < key[i]}``.  With B
    bounds this is a vectorized [n, B] comparison — B = P-1 splitters is
    tiny, so this replaces the reference's per-key linear scan
    (``mpi_sample_sort.c:148-155``) with one fused elementwise pass.
    """
    n = keys[0].shape[0]
    lt = None  # bounds[j] < key[i], built msw-first
    eq = None
    for w_k, w_b in zip(keys, sorted_bounds):
        cmp_lt = w_b[None, :] < w_k[:, None]
        cmp_eq = w_b[None, :] == w_k[:, None]
        if lt is None:
            lt, eq = cmp_lt, cmp_eq
        else:
            lt = lt | (eq & cmp_lt)
            eq = eq & cmp_eq
    if lt is None:  # no bounds
        return jnp.zeros((n,), jnp.int32)
    return lt.sum(axis=1, dtype=jnp.int32)


def evenly_spaced_samples(sorted_words: Words, n_samples: int) -> Words:
    """Pick ``n_samples`` evenly spaced elements of a sorted shard.

    Mirrors the reference's sample pick (``mpi_sample_sort.c:88-95``) but
    never runs off the block: indices are spread over [0, n) inclusive of
    both ends, so there is no "no enough sample" abort path
    (``mpi_sample_sort.c:96-99``) for n >= 1.
    """
    n = sorted_words[0].shape[0]
    # Exact integer floor(i*(n-1)/d) without 32-bit overflow: i*q stays
    # below n and i*r below d^2 (d ~ 2P is tiny).  Float index math would
    # lose integer precision for shards beyond 2^24.
    d = max(n_samples - 1, 1)
    if d * (d - 1) >= 2**31:
        raise ValueError(
            f"n_samples={n_samples} overflows the int32 index math "
            "(and a sample that large defeats sampling)"
        )
    q, r = divmod(n - 1, d)
    i = lax.iota(jnp.int32, n_samples)
    idx = jnp.clip(i * q + (i * r) // d, 0, n - 1)
    return tuple(w[idx] for w in sorted_words)
