"""Local (per-shard) kernels: sort, histogram, digit extraction, bucketing.

These are the TPU-native equivalents of the reference's local compute
kernels — libc ``qsort`` (``mpi_sample_sort.c:85,174``), the floating-point
digit math (``mpi_radix_sort.c:48-58``), and the O(P)-per-key linear bucket
scan (``mpi_sample_sort.c:148-155``).  All shapes are static; everything
composes under ``jit`` / ``shard_map``.  Digit math is pure integer
shift/mask (the reference's ``pow()``-based digits are a precision hazard).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

Words = tuple[jax.Array, ...]


def local_sort(words: Words) -> Words:
    """Lexicographic stable sort of multi-word keys (msw first).

    ``lax.sort`` with ``num_keys=len(words)`` compares word tuples
    lexicographically — this is how 64-bit keys sort without x64.
    """
    if len(words) == 1:
        return (jnp.sort(words[0]),)
    return tuple(lax.sort(list(words), num_keys=len(words), is_stable=True))


def local_sort_with_payload(words: Words, payload: Words) -> tuple[Words, Words]:
    """Stable sort of keys, carrying payload words along."""
    ops = list(words) + list(payload)
    out = lax.sort(ops, num_keys=len(words), is_stable=True)
    return tuple(out[: len(words)]), tuple(out[len(words):])


def digit_at(word: jax.Array, shift: int, bits: int) -> jax.Array:
    """Extract the ``bits``-wide digit at bit offset ``shift`` (int32 result)."""
    mask = jnp.uint32((1 << bits) - 1)
    return ((word >> jnp.uint32(shift)) & mask).astype(jnp.int32)


def histogram(digits: jax.Array, n_bins: int) -> jax.Array:
    """Count occurrences of each digit value. Scatter-add; XLA lowers this
    to an efficient on-chip combiner. Returns int32[n_bins]."""
    return jnp.zeros((n_bins,), jnp.int32).at[digits].add(1)


def stable_rank_by_digit(digits: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Stable argsort of digits.

    Returns ``(perm, sorted_digits)`` where ``perm`` lists element indices in
    stable digit order.  This is the TPU replacement for the reference's
    sequential ``bucket_push`` loop (``mpi_radix_sort.c:144-147``): grouping
    by digit while preserving scan order, but as one O(n log n) XLA sort
    instead of a serial O(n) loop that cannot vectorize.
    """
    n = digits.shape[0]
    iota = lax.iota(jnp.int32, n)
    sorted_digits, perm = lax.sort([digits, iota], num_keys=1, is_stable=True)
    return perm, sorted_digits


def searchsorted_words(sorted_bounds: Words, keys: Words) -> jax.Array:
    """For each key, count how many bounds are < key (lexicographic).

    Multi-word generalization of ``jnp.searchsorted(side='left')`` used for
    splitter bucketing: ``dest[i] = #{j : bounds[j] < key[i]}``.  With B
    bounds this is a vectorized [n, B] comparison — B = P-1 splitters is
    tiny, so this replaces the reference's per-key linear scan
    (``mpi_sample_sort.c:148-155``) with one fused elementwise pass.
    """
    n = keys[0].shape[0]
    lt = None  # bounds[j] < key[i], built msw-first
    eq = None
    for w_k, w_b in zip(keys, sorted_bounds):
        cmp_lt = w_b[None, :] < w_k[:, None]
        cmp_eq = w_b[None, :] == w_k[:, None]
        if lt is None:
            lt, eq = cmp_lt, cmp_eq
        else:
            lt = lt | (eq & cmp_lt)
            eq = eq & cmp_eq
    if lt is None:  # no bounds
        return jnp.zeros((n,), jnp.int32)
    return lt.sum(axis=1, dtype=jnp.int32)


def evenly_spaced_samples(sorted_words: Words, n_samples: int) -> Words:
    """Pick ``n_samples`` evenly spaced elements of a sorted shard.

    Mirrors the reference's sample pick (``mpi_sample_sort.c:88-95``) but
    never runs off the block: indices are spread over [0, n) inclusive of
    both ends, so there is no "no enough sample" abort path
    (``mpi_sample_sort.c:96-99``) for n >= 1.
    """
    n = sorted_words[0].shape[0]
    idx = jnp.clip(
        (lax.iota(jnp.int32, n_samples).astype(jnp.float32) * (n - 1) / max(n_samples - 1, 1))
        .astype(jnp.int32),
        0,
        n - 1,
    )
    return tuple(w[idx] for w in sorted_words)
