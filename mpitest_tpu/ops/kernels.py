"""Local (per-shard) kernels: sort, histogram, digit extraction, bucketing.

These are the TPU-native equivalents of the reference's local compute
kernels — libc ``qsort`` (``mpi_sample_sort.c:85,174``), the floating-point
digit math (``mpi_radix_sort.c:48-58``), and the O(P)-per-key linear bucket
scan (``mpi_sample_sort.c:148-155``).  All shapes are static; everything
composes under ``jit`` / ``shard_map``.  Digit math is pure integer
shift/mask (the reference's ``pow()``-based digits are a precision hazard).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

Words = tuple[jax.Array, ...]


def local_sort(words: Words, engine: str = "lax",
               diffs: tuple[int, ...] | None = None) -> Words:
    """Lexicographic stable sort of multi-word keys (msw first).

    ``lax.sort`` with ``num_keys=len(words)`` compares word tuples
    lexicographically — this is how 64-bit keys sort without x64.

    ``engine="bitonic"`` routes one-word keys through the Pallas bitonic
    engine (``ops/bitonic.py``, 2.0-4.2x ``lax.sort`` at 2^26 on v5e
    post-relayout) and two-word keys through the pair engine (+
    on-device residual-cond fallback; 1.54-2.30x the variadic
    ``lax.sort`` at 2^26, clean sessions at the top of the band) —
    including under ``shard_map``, which is how the distributed sample
    sort accelerates its per-shard sorts on real TPU meshes.
    ``engine="bitonic_interpret"`` runs the same kernels through the
    Pallas interpreter (the virtual CPU-mesh tests).  The choice is
    explicit rather than backend-sniffed so that AOT compilation for a
    TPU *topology* from a CPU-pinned process lowers the real Mosaic
    kernels (see tests/test_aot_topology.py).  Wider keys always use
    ``lax.sort``.

    ``engine="radix_pallas"`` routes any key width up to
    ``radix_pallas.FUSED_MAX_WORDS`` through the fused per-pass radix
    kernel (one ``pallas_call`` per pass — no sort/searchsorted/gather
    chain); ``"radix_pallas_interpret"`` is its interpreter twin.
    ``diffs`` (msw-first per-word value spreads, host-static) lets the
    fused engine compact the pass plan for range-narrow keys; it is
    ignored by every other engine.  Bit-identity with the lax form is
    exact: each fused pass is a stable counting sort.

    Stability note: ``words`` is always the FULL key (no payload
    operands), so stability is unobservable in the output — equal key
    tuples are indistinguishable — and the unstable bitonic engines are
    exact drop-ins for the stable ``lax.sort`` form.
    """
    if engine.startswith("radix_pallas"):
        from mpitest_tpu.ops import radix_pallas  # local import: optional path

        return radix_pallas.fused_radix_sort(
            words, diffs=diffs,
            interpret=engine == "radix_pallas_interpret")
    if engine.startswith("bitonic") and len(words) == 1:
        from mpitest_tpu.ops import bitonic  # local import: optional path

        interpret = engine == "bitonic_interpret"
        return (bitonic.bitonic_sort_u32(words[0], interpret=interpret),)
    if engine.startswith("bitonic") and len(words) == 2:
        # 64-bit pair engine with its residual fallback folded in as an
        # on-device cond (usable under shard_map, where host-side
        # fallback orchestration does not exist).  The adaptive sniffs
        # of the single-device path live in models/api.py; here the
        # cond alone guarantees correctness for any duplication.
        interpret = engine == "bitonic_interpret"
        hi, lo = words
        hi_s, lo_s, bad = sort_two_words_bitonic(hi, lo, interpret=interpret)

        def _lax2w(h, l):
            out = lax.sort([h, l], num_keys=2, is_stable=False)
            return out[0], out[1]

        return tuple(lax.cond(bad, _lax2w, lambda h, l: (hi_s, lo_s), hi, lo))
    if len(words) == 1:
        return (jnp.sort(words[0]),)
    return tuple(lax.sort(list(words), num_keys=len(words), is_stable=True))


def _fix_runs_oe(hi: jax.Array, lo: jax.Array, passes: int) -> jax.Array:
    """Segment-masked odd-even transposition: sort ``lo`` within every
    run of equal ``hi`` (already hi-sorted) of length <= ``passes``.

    ``hi`` never moves — exchanges happen only inside equal-hi runs.
    This is the REFERENCE formulation (and the differential oracle for
    the in-VMEM kernel, ``bitonic._fix_runs_pair_kernel``): each pass
    streams the lo plane from HBM (~6 ms/pass at 2^26 measured), so the
    production path runs the same passes in VMEM instead.  Longer runs
    survive either way; the caller detects them via the residual flag
    and falls back (``sort_two_words_bitonic``)."""
    n = hi.shape[0]
    parity = lax.iota(jnp.int32, n) & 1
    nb_hi = jnp.concatenate([hi[1:], hi[-1:]])
    same = hi == nb_hi  # run structure: invariant across passes
    for t in range(passes):
        nb_lo = jnp.concatenate([lo[1:], lo[-1:]])
        # last element pairs with itself: lo > lo is False -> inactive
        act = (parity == (t & 1)) & same & (lo > nb_lo)
        act_prev = jnp.concatenate([jnp.zeros((1,), bool), act[:-1]])
        lo_prev = jnp.concatenate([lo[:1], lo[:-1]])
        lo = jnp.where(act, nb_lo, jnp.where(act_prev, lo_prev, lo))
    return lo


def _fix_boundary(hi: jax.Array, lo: jax.Array, passes: int,
                  bsz: int) -> jax.Array:
    """Finish equal-hi runs that cross block boundaries: the in-VMEM fix
    kernel sorts within blocks only.  A run of length <= ``passes`` that
    crosses boundary k lies entirely inside the 2*passes-wide strip
    around it, so sorting the [nblk-1, 2*passes] strip array (tiny —
    ~32K elements at 2^26) with segment-masked odd-even passes and
    writing it back completes every such run.  Runs already sorted
    in-block stay sorted (a sorted segment is an odd-even fixed point).
    """
    n = hi.shape[0]
    nblk = n // bsz
    if nblk < 2:
        return lo
    W = passes
    hb = hi.reshape(nblk, bsz)
    lb = lo.reshape(nblk, bsz)
    sh = jnp.concatenate([hb[:-1, -W:], hb[1:, :W]], axis=1)
    sl = jnp.concatenate([lb[:-1, -W:], lb[1:, :W]], axis=1)
    n2 = 2 * W
    par = jnp.arange(n2, dtype=jnp.int32) & 1
    nb_h = jnp.concatenate([sh[:, 1:], sh[:, -1:]], axis=1)
    same = sh == nb_h  # last column self-pairs: lo > lo is False anyway
    for t in range(n2):  # odd-even sorts the whole 2W strip — overkill is free
        nb_l = jnp.concatenate([sl[:, 1:], sl[:, -1:]], axis=1)
        act = (par == (t & 1))[None, :] & same & (sl > nb_l)
        pv_a = jnp.concatenate(
            [jnp.zeros((act.shape[0], 1), bool), act[:, :-1]], axis=1)
        pv_l = jnp.concatenate([sl[:, :1], sl[:, :-1]], axis=1)
        sl = jnp.where(act, nb_l, jnp.where(pv_a, pv_l, sl))
    lb = lb.at[:-1, -W:].set(sl[:, :W]).at[1:, :W].set(sl[:, W:])
    return lb.reshape(-1)


def sort_two_words_bitonic(hi: jax.Array, lo: jax.Array,
                           interpret: bool = False, fix_passes: int = 16):
    """64-bit local sort via the pair bitonic engine — the MSD-hybrid
    structure VERDICT r3 #1 asked for, in its measured-optimal form.

    Phase A sorts ``(hi, lo)`` pairs by the hi plane with the key+payload
    network (``ops/bitonic.py``: payload routed by ``out_k == k``,
    measured 1.98x the 1-word layer on v5e — the lexicographic 2-word
    layer measures 4.8x, which is why a full 2-word bitonic engine was
    rejected in round 3).  Equal-hi runs then hold an arbitrary
    permutation of their lo values; phase B sorts them with
    ``fix_passes`` segment-masked odd-even passes.  Runs longer than
    ``fix_passes`` (heavy hi duplication — the caller's sniff makes this
    rare) set the residual flag; output is then NOT fully sorted and the
    caller must fall back to the variadic ``lax.sort``.

    Depth priced on chip at 2^26 (``bench/fixdepth_probe.py``, r5 —
    every phase is oblivious, so the uniform row prices all inputs):
    8 -> 16 passes costs +2.2% always and moves the sniff-evading
    runs-9..16 class from the 279 ms double-sort to the 102 ms in-VMEM
    path (2.7x); 16 -> 32 costs +9% always for the narrower 17..32
    class.  16 is the shipped default (VERDICT r4 weak #3 mid-tier).

    Returns ``(hi_sorted, lo_sorted, residual)``.
    """
    from mpitest_tpu.ops import bitonic  # local import: optional path

    n = hi.shape[0]
    t = max((n - 1).bit_length(), bitonic.MIN_SORT_LOG2)
    n_pow2 = 1 << t
    # same break-even contract as bitonic_sort_u32: tiny or pad-heavy
    # shapes lose to lax.sort's exact-n cost
    if n < (1 << bitonic.MIN_SORT_LOG2) or n * 10 < n_pow2 * 6:
        out = lax.sort([hi, lo], num_keys=2, is_stable=False)
        return out[0], out[1], jnp.zeros((), bool)
    b_log2 = min(bitonic.PAIR_BLOCK_LOG2, t)
    if n_pow2 != n:
        # (max, max) pad pairs sort to the global tail; real elements
        # equal to the pad pair are indistinguishable from it, so the
        # sliced prefix recovers the exact multiset (models/api.py
        # pad-with-max contract).
        pad = jnp.full((n_pow2 - n,), jnp.uint32(0xFFFFFFFF), jnp.uint32)
        hi = jnp.concatenate([hi, pad])
        lo = jnp.concatenate([lo, pad])
    hi_s, lo_r = bitonic.sort_pairs_padded(hi, lo, n_pow2, b_log2,
                                           interpret=interpret)
    lo_s = bitonic.fix_runs_pairs(hi_s, lo_r, fix_passes, b_log2,
                                  interpret=interpret)
    lo_s = _fix_boundary(hi_s, lo_s, fix_passes, 1 << b_log2)
    residual = jnp.any((hi_s[1:] == hi_s[:-1]) & (lo_s[1:] < lo_s[:-1]))
    return hi_s[:n], lo_s[:n], residual


def digit_at(word: jax.Array, shift: int, bits: int) -> jax.Array:
    """Extract the ``bits``-wide digit at bit offset ``shift`` (int32 result)."""
    mask = jnp.uint32((1 << bits) - 1)
    return ((word >> jnp.uint32(shift)) & mask).astype(jnp.int32)


def histogram(digits: jax.Array, n_bins: int) -> jax.Array:
    """Count occurrences of each digit value. Scatter-add; XLA lowers this
    to an efficient on-chip combiner. Returns int32[n_bins]."""
    return jnp.zeros((n_bins,), jnp.int32).at[digits].add(1)


def histogram_sorted(sorted_digits: jax.Array, n_bins: int) -> tuple[jax.Array, jax.Array]:
    """Histogram of an already-sorted digit array via binary search.

    Returns ``(h, lo)`` where ``h[b]`` is the count of digit ``b`` and
    ``lo[b]`` the offset of its first occurrence.  On TPU this replaces the
    scatter-add histogram for the radix pass: scatter lowers to serialized
    updates (measured ~40× slower than the searchsorted form at 2^26 on
    v5e), while ``searchsorted`` over sorted data is a vectorized binary
    search that costs nothing next to the sort we already did.
    """
    edges = jnp.searchsorted(
        sorted_digits, lax.iota(jnp.int32, n_bins + 1)
    ).astype(jnp.int32)
    return jnp.diff(edges), edges[:-1]


def piecewise_fill(starts: jax.Array, values: jax.Array, n: int) -> jax.Array:
    """Materialize a step function: ``out[j] = values[k]`` for
    ``starts[k] <= j < starts[k+1]`` (``starts`` sorted ascending,
    ``starts[0] == 0``; empty segments and ``starts[k] == n`` tails fine).

    This is the gather-free alternative to ``values[segment_id]`` — a
    K-element scatter-add of successive differences followed by a cumsum.
    Per-element gathers from even a 256-entry table measured ~10× the cost
    of a full sort at 2^26 on v5e; K-element scatters and cumsum are cheap.
    """
    delta = jnp.concatenate([values[:1], jnp.diff(values)])
    arr = jnp.zeros((n,), values.dtype).at[starts].add(delta, mode="drop")
    return jnp.cumsum(arr)


def searchsorted_words(sorted_bounds: Words, keys: Words) -> jax.Array:
    """For each key, count how many bounds are < key (lexicographic).

    Multi-word generalization of ``jnp.searchsorted(side='left')`` used for
    splitter bucketing: ``dest[i] = #{j : bounds[j] < key[i]}``.  With B
    bounds this is a vectorized [n, B] comparison — B = P-1 splitters is
    tiny, so this replaces the reference's per-key linear scan
    (``mpi_sample_sort.c:148-155``) with one fused elementwise pass.
    """
    n = keys[0].shape[0]
    lt = None  # bounds[j] < key[i], built msw-first
    eq = None
    for w_k, w_b in zip(keys, sorted_bounds):
        cmp_lt = w_b[None, :] < w_k[:, None]
        cmp_eq = w_b[None, :] == w_k[:, None]
        if lt is None:
            lt, eq = cmp_lt, cmp_eq
        else:
            lt = lt | (eq & cmp_lt)
            eq = eq & cmp_eq
    if lt is None:  # no bounds
        return jnp.zeros((n,), jnp.int32)
    return lt.sum(axis=1, dtype=jnp.int32)


def evenly_spaced_samples(sorted_words: Words, n_samples: int) -> Words:
    """Pick ``n_samples`` evenly spaced elements of a sorted shard.

    Mirrors the reference's sample pick (``mpi_sample_sort.c:88-95``) but
    never runs off the block: indices are spread over [0, n) inclusive of
    both ends, so there is no "no enough sample" abort path
    (``mpi_sample_sort.c:96-99``) for n >= 1.
    """
    n = sorted_words[0].shape[0]
    # Exact integer floor(i*(n-1)/d) without 32-bit overflow: i*q stays
    # below n and i*r below d^2 (d ~ 2P is tiny).  Float index math would
    # lose integer precision for shards beyond 2^24.
    d = max(n_samples - 1, 1)
    if d * (d - 1) >= 2**31:
        raise ValueError(
            f"n_samples={n_samples} overflows the int32 index math "
            "(and a sample that large defeats sampling)"
        )
    q, r = divmod(n - 1, d)
    i = lax.iota(jnp.int32, n_samples)
    idx = jnp.clip(i * q + (i * r) // d, 0, n - 1)
    return tuple(w[idx] for w in sorted_words)
