"""Pallas TPU kernels — escalation for ops XLA lowers poorly.

One hot op qualifies today: the exchange *pack* — spreading contiguous
ragged segments into the fixed ``[P, cap]`` all-to-all send matrix.  XLA
expresses it as an n-element scatter (or row gather), which lowers to
per-element updates (measured 10-40× a fused sort at 2^26 on v5e,
`.claude/skills/verify/SKILL.md`).  The Pallas version moves whole chunks:
per output chunk, one aligned 2-chunk DMA from HBM plus a vectorized
misaligned-copy shift (two row rolls + a lane roll + select) — no
per-element addressing anywhere.

Mosaic constraints that shaped the kernel (discovered on hardware):
sliced-DMA shapes and tile indices must honor the (8, 128) int32 tiling —
hence the row-aligned loads and the in-register shift instead of an
arbitrary-offset DMA; 1-D vector ops are unsupported — hence everything
is [rows, 128].

The local sorts stay on ``lax.sort`` — a measured trade-off, NOT a
memory-bound claim (round 1 asserted "near memory-bound" here; the
arithmetic refutes it — see BASELINE.md "Roofline analysis", which puts
``lax.sort`` at 2^26 roughly 250× the 2-pass HBM bound, as expected of
an O(n log² n) comparison network).  It survives because every measured
alternative is worse on this hardware: XLA scatter/gather permutations
run 3-6× slower than the sort they would replace, batched row sorts
only get cheap below rows of 2^14 while bucketing into rows that small
forces padding blowup and a second sort that eats the gain, and a
Mosaic radix scatter would need per-element cross-tile addressing — the
primitive the VPU lacks.  The realistic escalation path is a fused
in-VMEM bitonic/column-sort kernel (future work, tracked in
BASELINE.md), not a radix scatter.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpitest_tpu import compat

LANES = 128
ROWS = 8                    # (8, 128) = one int32 tile
CHUNK = ROWS * LANES        # 1024 elements = 4 KiB per DMA


def chunk_geometry(start, cnt, i, n: int):
    """The (8, 128)/CHUNK pack-chunk geometry, shared by this module's
    ``_pack_kernel`` and the fused multi-word pack of ``ops/exchange.py``
    (ISSUE 13) — ONE home for the addressing invariants, so a fix to
    the window math can never leave one engine's copy stale.

    For output chunk ``i`` of a segment at ``start`` with ``cnt`` valid
    elements in an ``n``-element (LANES-padded) buffer, returns
    ``(arow, shift, valid)``:

      * ``arow`` — the ROWS-aligned source row to DMA a 2-chunk window
        from (clamped so beyond-count chunks never read past the padded
        buffer);
      * ``shift(x)`` — the in-register misaligned copy: shifts the
        ``[2*ROWS, LANES]`` window left by ``base - arow*LANES``
        elements (= r row rolls + a lane roll + select) and returns the
        ``[ROWS, LANES]`` chunk plane;
      * ``valid`` — the ``[ROWS, LANES]`` in-segment mask (beyond
        ``cnt``, callers write their fill word).
    """
    base = jnp.minimum(start + i * CHUNK, n)
    arow = pl.multiple_of(((base // LANES) // ROWS) * ROWS, ROWS)
    sh = base - arow * LANES
    r, l = sh // LANES, sh % LANES
    lane = jax.lax.broadcasted_iota(jnp.int32, (2 * ROWS, LANES), 1)
    sel = lane < LANES - l

    def shift(x):
        a = pltpu.roll(x, -r, 0)
        b = pltpu.roll(x, -(r + 1), 0)
        return jnp.where(sel, pltpu.roll(a, -l, 1),
                         pltpu.roll(b, -l, 1))[:ROWS, :]

    elem = (jax.lax.broadcasted_iota(jnp.int32, (ROWS, LANES), 0) * LANES
            + jax.lax.broadcasted_iota(jnp.int32, (ROWS, LANES), 1))
    valid = elem < (cnt - i * CHUNK)
    return arow, shift, valid


def _pack_kernel(n: int, fill: int, starts_ref, cnts_ref, data_ref,
                 out_ref, scratch, sem):
    """Grid (P, cap//CHUNK): instance (p, i) produces out chunk i of
    destination p: data[starts[p] + i*CHUNK ...][:CHUNK] where in-segment,
    the fill word beyond ``cnts[p]``."""
    p = pl.program_id(0)
    i = pl.program_id(1)
    arow, shift, valid = chunk_geometry(starts_ref[p], cnts_ref[p], i, n)

    dma = pltpu.make_async_copy(
        data_ref.at[pl.ds(arow, 2 * ROWS), :], scratch, sem
    )
    dma.start()
    dma.wait()

    out_ref[0, 0] = jnp.where(valid, shift(scratch[...]), jnp.uint32(fill))


@functools.partial(
    jax.jit, static_argnames=("cap", "n_ranks", "fill", "interpret", "vma")
)
def segment_pack(
    data: jax.Array,     # uint32[n] — segment p is data[starts[p]:+cnts[p]]
    starts: jax.Array,   # int32[P], ascending, starts[0] == 0
    cnts: jax.Array,     # int32[P]
    cap: int,            # static row capacity, multiple of CHUNK
    n_ranks: int,
    fill: int = 0,
    interpret: bool = False,
    vma: tuple[str, ...] = (),  # mesh axes the output varies over (shard_map)
) -> jax.Array:          # uint32[P, cap]
    """Spread ragged contiguous segments into the padded send matrix."""
    assert cap % CHUNK == 0, cap
    n = data.shape[0]
    pad = (-n) % LANES + 2 * CHUNK   # row-shape the data + DMA headroom
    data_2d = jnp.concatenate(
        [data, jnp.zeros((pad,), data.dtype)]
    ).reshape(-1, LANES)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_ranks, cap // CHUNK),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            (1, 1, ROWS, LANES), lambda p, i, *_: (p, i, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((2 * ROWS, LANES), jnp.uint32),
            pltpu.SemaphoreType.DMA(()),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_pack_kernel, n, fill),
        grid_spec=grid_spec,
        out_shape=compat.shape_dtype_struct(
            (n_ranks, cap // CHUNK, ROWS, LANES), data.dtype, vma=vma,
        ),
        interpret=interpret,
    )(starts.astype(jnp.int32), cnts.astype(jnp.int32), data_2d)
    return out.reshape(n_ranks, cap)
