"""Pallas bitonic sort — the single-chip local sort engine.

Replaces ``lax.sort`` for large one-word (uint32) shards.  XLA's TPU sort
lowers to a comparison network whose per-layer cost is dominated by
generic lowering overhead: measured 84.6 ms for 2^26 uint32 on v5e (see
BASELINE.md "kernel design study"), ~30x the two-pass HBM streaming
floor.  This kernel implements the same O(n log^2 n) bitonic network
with every data movement expressed as *static* circular shifts
(``pltpu.roll``) — the partner of element ``i`` at distance ``d = 2^j``
is ``i ^ d``, reachable by two rolls and one select — so the whole
network compiles to dense VPU code with no data-dependent addressing,
which the TPU does not have (no vectorized gather/scatter; the roofline
study in BASELINE.md prices every alternative).

Design (tpu-first, not a port of any CPU/GPU radix scheme):

- The array lives as ``[nblk, S, 128]`` (row-major flat order), block =
  ``S*128 = 2^B`` elements (256 KiB at B=16 — the largest the unrolled
  layer chain fits in scoped VMEM).
- One **standard bitonic network over the whole padded array**; layers
  are partitioned by compare distance into three kernels:

  * ``block-sort``: all stages with size <= 2^B, unrolled in-VMEM per
    block (grid over blocks, one HBM round-trip total).  Directions come
    from the *global* flat index, so block b ends sorted ascending /
    descending by the parity the merge stages expect.
  * ``cross``: one layer at block distance >= 8, moved as contiguous
    8-block groups — pure elementwise min/max between paired groups;
    the take-min side is constant per group (a block-index bit), so
    there are no per-element masks at all.
  * ``merge``: each stage's tail — its lowest <=3 cross layers (the
    XOR-neighborhood of a contiguous 2^c-block group, paired at the
    Python level) AND the whole trailing in-block sweep — in one VMEM
    visit per block.

- Two measured v5e facts shape the inner loop: **lane rolls cost ~15x
  sublane rolls**, so every distance<128 layer runs on the transposed
  block where it becomes a sublane roll; and direction selects are
  dearer than flip bookkeeping, so descending segments are kept
  bit-flipped (``~x`` reverses int32 order) and every layer is the
  6-op ascending form.
- Compare distances and stage numbers ride in as scalar-prefetch
  operands (``PrefetchScalarGridSpec``), so each kernel compiles
  **once** per array shape, not once per layer.

The network is oblivious (layer sequence depends only on N), so output
is deterministic and bit-identical run to run — the same canonical
sorted bytes ``lax.sort`` or ``qsort`` would produce (reference output
contract: ``mpi_sample_sort.c:203-205``).

Scope: one-word uint32 keys (the encoded form of int32/uint32/float32 —
see ``ops/keys.py``), key-only (no payload): the flagship single-device
path and the per-shard sorts of the distributed sample sort
(``kernels.local_sort(engine="bitonic")``).  Multi-word keys and the
radix per-pass variadic sorts keep ``lax.sort`` — BASELINE.md's design
study shows the measured 2-word margin does not pay for a second kernel
family.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
LANES_LOG2 = 7
#: log2 of elements per block: S = 2^(B-7) sublanes x 128 lanes = 256 KiB
#: u32.  2^18 (the VMEM-optimal choice on paper) OOMs scoped VMEM: Mosaic
#: keeps ~34 copies of the block live across the unrolled 100+-layer chain.
BLOCK_LOG2 = 16
#: below this the padded network does not beat lax.sort's fixed costs.
MIN_SORT_LOG2 = 13
#: blocks per cross-layer transfer group (see ``_cross_kernel``).
_CROSS_GROUP = 8


def _asc_layer(x, lj: int, t_layout: bool = False):
    """Ascending compare-exchange at distance ``2^lj`` — 6 vector ops.

    The partner of element ``i`` is ``i ^ 2^lj``.  Low-side elements
    (bit ``lj`` clear) keep ``min(x, x[i+d])``, high-side keep
    ``max(x, x[i-d])`` — no separate partner select, no direction mask:
    *every* segment compares ascending because the callers bit-flip the
    values of descending segments up front (``~x`` reverses int32
    order), which is what makes the per-layer cost 2 rolls + min + max
    + mask + select instead of the 12-op direct form.

    Layout: in the natural ``[S, 128]`` block, ``lj >= 7`` distances
    are *sublane* rolls and ``lj < 7`` would be lane rolls — which cost
    ~15x a sublane roll on v5e (measured; the cross-lane shift network
    is the scarce resource).  Callers therefore run all ``lj < 7``
    layers on the transposed ``[128, S]`` block (``t_layout=True``),
    where the original lane index is the sublane axis and the same
    distances become sublane rolls; two [S,128] transposes per section
    amortize over seven avoided lane-roll layers.  Both rolls are
    cyclic, but segments of ``2^(lj+1)`` tile the axis exactly, so the
    selected half never reads a wrapped value.
    """
    if t_layout:
        assert lj < LANES_LOG2
        axis, shift, log = 0, 1 << lj, lj
    elif lj < LANES_LOG2:
        axis, shift, log = 1, 1 << lj, lj
    else:
        axis, shift, log = 0, 1 << (lj - LANES_LOG2), lj - LANES_LOG2
    size = x.shape[axis]
    fwd = pltpu.roll(x, size - shift, axis)  # out[i] = in[i + shift]
    bwd = pltpu.roll(x, shift, axis)         # out[i] = in[i - shift]
    idx = lax.broadcasted_iota(jnp.int32, x.shape, axis)
    low = ((idx >> log) & 1) == 0            # bit clear -> partner above
    return jnp.where(low, jnp.minimum(x, fwd), jnp.maximum(x, bwd))


def _sweep(x, b_log2: int):
    """The trailing in-block sweep: layers ``B-1 .. 0`` ascending, with
    the ``lj < 7`` tail run on the transposed block (see
    :func:`_asc_layer` on why lane rolls are banned)."""
    for lj in range(b_log2 - 1, LANES_LOG2 - 1, -1):
        x = _asc_layer(x, lj)
    xt = x.T
    for lj in range(LANES_LOG2 - 1, -1, -1):
        xt = _asc_layer(xt, lj, t_layout=True)
    return xt.T


def _flat_bit(shape, j: int, t_layout: bool):
    """Mask ``bit_j(flat index) == 1`` for a block in either layout.

    flat = r*128 + l; natural layout is [r=S sublanes, l=128 lanes],
    transposed is [l, r]: bit j < 7 lives on the lane index, the rest
    on the row index."""
    if j < LANES_LOG2:
        axis = 0 if t_layout else 1
        bit = j
    else:
        axis = 1 if t_layout else 0
        bit = j - LANES_LOG2
    idx = lax.broadcasted_iota(jnp.int32, shape, axis)
    return ((idx >> bit) & 1) == 1


# ---------------------------------------------------------------- kernels


def _block_sort_kernel(x_ref, o_ref, *, s_rows: int, b_log2: int):
    """Stages 1..B of the network, in-VMEM, one block per grid step.

    Flip-state bookkeeping: before stage ``m`` runs, values in its
    descending segments (bit ``m`` of the flat index set) are held
    bit-flipped, so every layer is the cheap ascending form.  Between
    stages only the *difference* of the two masks re-flips (one xor-mask
    pass per stage vs a direction select per layer); all masks here
    depend on the local index only — block-independent — except the
    final unflip, whose mask degenerates to the block parity.
    """
    blk = pl.program_id(0)
    x = x_ref[0]

    def transition(x, m, t_layout):
        """Re-flip from stage ``m``'s direction mask to stage ``m+1``'s
        (or unflip after the last stage); masks are local-index bits
        except bit B, which is the block parity."""
        delta = _flat_bit(x.shape, m, t_layout)
        if m + 1 < b_log2:
            delta = delta ^ _flat_bit(x.shape, m + 1, t_layout)
        elif m + 1 == b_log2:
            delta = delta ^ ((blk & 1) == 1)
        else:  # after the final stage: unflip from the parity state
            delta = (blk & 1) == 1
            return jnp.where(delta, ~x, x)
        return jnp.where(delta, ~x, x)

    # Stages 1..7 run wholly on the transposed block: every layer there
    # has lane-sized distance, and lane rolls are what we must avoid.
    xt = x.T
    xt = jnp.where(_flat_bit(xt.shape, 1, True), ~xt, xt)
    for m in range(1, LANES_LOG2 + 1):
        for lj in range(m - 1, -1, -1):
            xt = _asc_layer(xt, lj, t_layout=True)
        xt = transition(xt, m, True)
    x = xt.T
    for m in range(LANES_LOG2 + 1, b_log2 + 1):
        for lj in range(m - 1, LANES_LOG2 - 1, -1):
            x = _asc_layer(x, lj)
        xt = x.T
        for lj in range(LANES_LOG2 - 1, -1, -1):
            xt = _asc_layer(xt, lj, t_layout=True)
        x = xt.T
        x = transition(x, m, False)
    o_ref[0] = x


def _cross_kernel(s_ref, xl_ref, xh_ref, o_ref):
    """One distance >= 2^(B+3) layer, one output *group* per grid step.

    The transfer unit is a contiguous group of ``_CROSS_GROUP`` blocks:
    every cross layer handled here has block distance >= 8 (the lowest
    three cross bits belong to the merge kernel), so partner blocks
    have equal low-3 bits and whole groups pair with whole groups —
    the same XOR pairing lifted to group indices, with ~2 MiB DMAs
    instead of 256 KiB ones.

    Scalar prefetch ``s_ref = [sjg, sm]``: the layer's distance in
    *group-index bits* (``sjg = lj - B - 3``) and stage size in
    block-index bits (``sm = lk - B``).  Grid is ``(group_pairs, 2)``:
    step ``(q, r)`` reads both groups of pair ``q`` and writes only the
    ``r``-side one, so one output array receives every group with no
    reconciliation pass (the pair's min/max is computed twice — three
    VPU ops against an HBM-bound layer).  The take-min side is a bit of
    the group id (``sm >= 4`` exceeds the in-group bits): no
    per-element masks at all.
    """
    sjg, sm = s_ref[0], s_ref[1]
    q = pl.program_id(0)
    r = pl.program_id(1)
    mask = (1 << sjg) - 1
    glo = ((q & ~mask) << 1) | (q & mask)
    blo = glo * _CROSS_GROUP  # any block of the low group: shared high bits
    take_min_low = ((blo >> sm) & 1) == 0
    lo = jnp.minimum(xl_ref[:], xh_ref[:])
    hi = jnp.maximum(xl_ref[:], xh_ref[:])
    o_ref[:] = jnp.where(take_min_low ^ (r == 1), lo, hi)


def _merge_kernel(s_ref, x_ref, o_ref, *, n_members: int, s_rows: int,
                  b_log2: int):
    """A stage's trailing chunk: the ``c = log2(G)`` lowest cross layers
    AND the whole in-block sweep, in ONE visit of each block to VMEM.

    Grid step ``g`` owns the *contiguous* member group ``{g*G + i}`` —
    the XOR-neighborhood of the cross layers at block-bit positions
    ``c-1 .. 0``: member ``i`` pairs with ``i ^ 2^k``, a Python-level
    slice pairing with no data movement.  Cross compare directions are
    scalar per member (a block-id bit), so a fused cross layer costs
    three vector ops per element; the fusion is what turns the merge
    tail from one HBM round-trip per layer into one per stage.

    Scalar ``s_ref = [m]``: the stage number, for compare directions.
    """
    m = s_ref[0]
    g = pl.program_id(0)
    sign_shift = m - b_log2
    bids = [g * n_members + i for i in range(n_members)]
    # Stage direction is a block-id bit — one scalar flip per member
    # makes every fused layer the raw ascending form.
    desc = [((bid >> sign_shift) & 1) == 1 for bid in bids]
    xs = [jnp.where(desc[i], ~x_ref[i], x_ref[i]) for i in range(n_members)]

    c = n_members.bit_length() - 1
    for k in range(c - 1, -1, -1):
        for i in range(n_members):
            if (i >> k) & 1:
                continue
            j = i | (1 << k)
            # Members of a pair share the stage-direction bit (they
            # differ only in bit k < sign_shift), so flipped ascending
            # min/max is exact — two vector ops, no selects.
            lo = jnp.minimum(xs[i], xs[j])
            hi = jnp.maximum(xs[i], xs[j])
            xs[i], xs[j] = lo, hi

    for i in range(n_members):
        x = _sweep(xs[i], b_log2)
        o_ref[i] = jnp.where(desc[i], ~x, x)


# ----------------------------------------------------------- host drivers


@functools.lru_cache(maxsize=16)
def _compile_block_sort(nblk: int, s_rows: int, b_log2: int, interpret: bool):
    spec = pl.BlockSpec((1, s_rows, LANES), lambda i: (i, 0, 0),
                        memory_space=pltpu.VMEM)
    return pl.pallas_call(
        functools.partial(_block_sort_kernel, s_rows=s_rows, b_log2=b_log2),
        out_shape=jax.ShapeDtypeStruct((nblk, s_rows, LANES), jnp.int32),
        grid=(nblk,),
        in_specs=[spec],
        out_specs=spec,
        # No aliasing: in-place measured ~1.5x slower (12.9 vs 8.5 ms at
        # 2^26) — same defensive-copy/pipelining penalty as the merge.
        interpret=interpret,
    )


@functools.lru_cache(maxsize=16)
def _compile_cross(nblk: int, s_rows: int, interpret: bool):
    """One call exchanges every 8-block group with its partner group at
    group distance ``2^sjg``.

    The pair layout rides in through the index maps, which receive the
    scalar-prefetch ref: grid step ``(q, r)`` loads groups ``glo`` (bit
    ``sjg`` clear) and ``glo | 2^sjg`` and writes the ``r``-side one.
    One compilation serves every distance.
    """
    def pair_map(side):
        def f(q, r, s_ref):
            sjg = s_ref[0]
            mask = (1 << sjg) - 1
            glo = ((q & ~mask) << 1) | (q & mask)
            pick = side if side is not None else r
            return (glo | (pick << sjg), 0, 0)
        return f

    ngroups = nblk // _CROSS_GROUP
    gspec = lambda m: pl.BlockSpec((_CROSS_GROUP, s_rows, LANES), m,
                                   memory_space=pltpu.VMEM)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(ngroups // 2, 2),
        in_specs=[gspec(pair_map(0)), gspec(pair_map(1))],
        out_specs=gspec(pair_map(None)),
    )
    return pl.pallas_call(
        _cross_kernel,
        out_shape=jax.ShapeDtypeStruct((nblk, s_rows, LANES), jnp.int32),
        grid_spec=grid_spec,
        interpret=interpret,
    )


@functools.lru_cache(maxsize=16)
def _compile_merge(n_members: int, nblk: int, s_rows: int, b_log2: int,
                   interpret: bool):
    spec = pl.BlockSpec((n_members, s_rows, LANES), lambda g, s: (g, 0, 0),
                        memory_space=pltpu.VMEM)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nblk // n_members,),
        in_specs=[spec],
        out_specs=spec,
    )
    return pl.pallas_call(
        functools.partial(_merge_kernel, n_members=n_members, s_rows=s_rows,
                          b_log2=b_log2),
        out_shape=jax.ShapeDtypeStruct((nblk, s_rows, LANES), jnp.int32),
        grid_spec=grid_spec,
        # No input_output_aliases here although each grid step reads only
        # the group it writes: in-place was measured 3.3x SLOWER at 2^30
        # (11.1 s vs 3.4 s end-to-end — XLA inserts defensive copies /
        # the revolving-window pipeline serializes).  The extra buffer is
        # the cheaper trade.  The cross kernel could not alias anyway:
        # both (q, 0) and (q, 1) steps read the pair.
        interpret=interpret,
    )


def sort_padded(x, n_pow2: int, b_log2: int, interpret: bool = False):
    """Bitonic-sort a padded power-of-two uint32 array of ``n_pow2``.

    ``x``: flat uint32 [n_pow2], ``n_pow2 = 2^t``, ``t >= b_log2 >= 10``.
    Returns the sorted flat array.  Pure function of shapes — jittable.

    The network itself runs in the *int32* domain (Mosaic has no
    unsigned vector min/max): the sign bit is flipped on the way in and
    out — an order-preserving bijection uint32 -> int32, two cheap
    elementwise passes against ~100 network layers.
    """
    t = n_pow2.bit_length() - 1
    assert 1 << t == n_pow2 and t >= b_log2
    s_rows = 1 << (b_log2 - LANES_LOG2)
    nblk = n_pow2 >> b_log2
    x = lax.bitcast_convert_type(x ^ jnp.uint32(0x80000000), jnp.int32)
    xb = x.reshape(nblk, s_rows, LANES)

    xb = _compile_block_sort(nblk, s_rows, b_log2, interpret)(xb)

    cross = _compile_cross(nblk, s_rows, interpret) if t > b_log2 + 3 else None

    for m in range(b_log2 + 1, t + 1):
        nbits = m - b_log2  # cross layers at block-bit positions nbits-1..0
        # High cross layers (block distance >= 8) one at a time; the
        # lowest min(nbits, 3) fuse into the merge kernel with the sweep.
        for sj in range(nbits - 1, 2, -1):
            xb = cross(jnp.asarray([sj - 3, nbits], jnp.int32), xb, xb)
        g_final = 1 << min(nbits, 3)
        merge = _compile_merge(g_final, nblk, s_rows, b_log2, interpret)
        xb = merge(jnp.asarray([m], jnp.int32), xb)
    out = xb.reshape(-1)
    return lax.bitcast_convert_type(out, jnp.uint32) ^ jnp.uint32(0x80000000)


def bitonic_sort_u32(x, interpret: bool = False):
    """Sort a flat uint32 array ascending; drop-in for ``jnp.sort``.

    Pads to the next power of two with the max sentinel (pads sort to
    the tail and are sliced off — same contract as the API layer's
    pad-with-max, ``models/api.py``).  Arrays smaller than
    ``2^MIN_SORT_LOG2`` fall back to ``lax.sort`` — below that size the
    network's fixed padding/pass structure costs more than it saves.
    """
    n = x.shape[0]
    if n == 0:
        return x
    t = max((n - 1).bit_length(), MIN_SORT_LOG2) if n else 0
    # Break-even: the network runs on the padded 2^t array (~0.6x
    # lax.sort's per-element cost, measured), so heavily padded sizes
    # lose to sorting the exact n with lax.sort.
    if n < (1 << MIN_SORT_LOG2) or n * 10 < (1 << t) * 6:
        return lax.sort([x], num_keys=1, is_stable=False)[0]
    b_log2 = min(BLOCK_LOG2, t)
    n_pow2 = 1 << t
    if n_pow2 != n:
        pad = jnp.full((n_pow2 - n,), jnp.uint32(0xFFFFFFFF), jnp.uint32)
        xp = jnp.concatenate([x, pad])
    else:
        xp = x
    out = sort_padded(xp, n_pow2, b_log2, interpret=interpret)
    return out[:n] if n_pow2 != n else out
