"""Pallas bitonic sort — the single-chip local sort engine.

Replaces ``lax.sort`` for large one-word (uint32) shards.  XLA's TPU sort
lowers to a comparison network whose per-layer cost is dominated by
generic lowering overhead: measured 84.6 ms for 2^26 uint32 on v5e (see
BASELINE.md "kernel design study"), ~30x the two-pass HBM streaming
floor.  This kernel implements the same O(n log^2 n) bitonic network
with every data movement expressed as *static* circular shifts
(``pltpu.roll``) — the partner of element ``i`` at distance ``d = 2^j``
is ``i ^ d``, reachable by two rolls and one select — so the whole
network compiles to dense VPU code with no data-dependent addressing,
which the TPU does not have (no vectorized gather/scatter; the roofline
study in BASELINE.md prices every alternative).

Design (tpu-first, not a port of any CPU/GPU radix scheme):

- The array lives as ``[nblk, S, 128]`` (row-major flat order), block =
  ``S*128 = 2^B`` elements (256 KiB at B=16 — the largest the unrolled
  layer chain fits in scoped VMEM).
- One **standard bitonic network over the whole padded array**; layers
  are partitioned by compare distance into three kernels:

  * ``block-sort``: all stages with size <= 2^B, unrolled in-VMEM per
    block (grid over blocks, one HBM round-trip total).  Directions come
    from the *global* flat index, so block b ends sorted ascending /
    descending by the parity the merge stages expect.
  * ``cross``: one layer at block distance >= 8, moved as contiguous
    8-block groups — pure elementwise min/max between paired groups;
    the take-min side is constant per group (a block-index bit), so
    there are no per-element masks at all.
  * ``merge``: each stage's tail — its lowest <=3 cross layers (the
    XOR-neighborhood of a contiguous 2^c-block group, paired at the
    Python level) AND the whole trailing in-block sweep — in one VMEM
    visit per block.

- Two measured v5e facts shape the inner loop: **lane rolls cost ~15x
  sublane rolls**, so every distance<128 layer runs on the transposed
  block where it becomes a sublane roll; and direction selects are
  dearer than flip bookkeeping, so descending segments are kept
  bit-flipped (``~x`` reverses int32 order) and every layer is the
  6-op ascending form.
- Compare distances and stage numbers ride in as scalar-prefetch
  operands (``PrefetchScalarGridSpec``), so each kernel compiles
  **once** per array shape, not once per layer.
- Round 5 replaced the single-cross schedule with the **rotation
  relayout** (see the "relayout cross fusion" section below): fused
  XOR-closure visits + rotation-aware merges, 2-3x fewer HBM bytes
  for the cross phase; the r4 schedule stays available as the A/B
  baseline (``relayout=False``).

The network is oblivious (layer sequence depends only on N), so output
is deterministic and bit-identical run to run — the same canonical
sorted bytes ``lax.sort`` or ``qsort`` would produce (reference output
contract: ``mpi_sample_sort.c:203-205``).

Scope: one-word uint32 keys (the encoded form of int32/uint32/float32 —
see ``ops/keys.py``) for the key-only engine, PLUS a key+payload twin
(round 4) that sorts ``(key, payload)`` uint32 pairs by the key plane —
the core of the 64-bit MSD-hybrid local sort (``kernels`` /
``models/api.py``): hi word as key, lo word as payload, equal-hi runs
fixed by a short segmented pass afterwards.  The pair layer routes the
payload from the key *result* (``out_k == k``: low side keeps its
payload iff ``k <= partner``, high iff ``k >= partner``, ties keep own
on both sides — a consistent no-swap), which measures **1.98x** the
1-word layer on v5e where the lexicographic 2-word form measures 4.8x
(``bench/kernel_probes.py`` ``bitonic_layer_kp2``) — the payload plane
costs its bandwidth and nothing else.  The radix per-pass variadic
sorts keep ``lax.sort``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpitest_tpu import compat

LANES = 128
LANES_LOG2 = 7
#: log2 of elements per block: S = 2^(B-7) sublanes x 128 lanes = 256 KiB
#: u32.  2^18 (the VMEM-optimal choice on paper) OOMs scoped VMEM: Mosaic
#: keeps ~34 copies of the block live across the unrolled 100+-layer chain.
BLOCK_LOG2 = 16
#: below this the padded network does not beat lax.sort's fixed costs.
MIN_SORT_LOG2 = 13
#: pair-engine shape: two planes double the in-VMEM footprint.  Keeping
#: the 2^16 block (shrinking it to 2^15 measured 2.2x SLOWER on the
#: whole network — extra stages + HBM visits dwarf everything) and
#: instead halving the merge/cross transfer groups to 4 blocks keeps the
#: 8-member pair merge's 25.6 MiB scoped-vmem demand (measured, over the
#: 16 MiB limit) at ~13 MiB.
PAIR_BLOCK_LOG2 = 16
_PAIR_CROSS_GROUP = 4      # blocks per pair cross-layer transfer group
_PAIR_MERGE_BITS = 2       # cross bits fused into the pair merge tail
#: blocks per cross-layer transfer group (see ``_cross_kernel``).
_CROSS_GROUP = 8
#: Raised scoped-VMEM budget, applied to EVERY kernel in this module.
#: The 16 MiB default is a compiler parameter, not hardware (v5e VMEM
#: is 128 MiB); 48 MiB admits the wide shapes round 4 recorded as
#: walls (2-block member windows, the 25.6 MiB 8-member pair merge,
#: the B=17 block experiment) while leaving ample room for the
#: pipeline's double buffers.
_VMEM_LIMIT = 48 * 1024 * 1024
_COMPILER_PARAMS = compat.tpu_compiler_params(vmem_limit_bytes=_VMEM_LIMIT)

#: Index-map constants pinned to int32: under jax_enable_x64 (the
#: device-resident 64-bit path) Python-int literals in index maps
#: weak-promote to i64, which Mosaic's block-map functions reject.
_Z = np.int32(0)


def _zmap(i, *_):
    return (i, _Z, _Z)


def _asc_layer(x, lj: int, t_layout: bool = False):
    """Ascending compare-exchange at distance ``2^lj`` — 6 vector ops.

    The partner of element ``i`` is ``i ^ 2^lj``.  Low-side elements
    (bit ``lj`` clear) keep ``min(x, x[i+d])``, high-side keep
    ``max(x, x[i-d])`` — no separate partner select, no direction mask:
    *every* segment compares ascending because the callers bit-flip the
    values of descending segments up front (``~x`` reverses int32
    order), which is what makes the per-layer cost 2 rolls + min + max
    + mask + select instead of the 12-op direct form.

    Layout: in the natural ``[S, 128]`` block, ``lj >= 7`` distances
    are *sublane* rolls and ``lj < 7`` would be lane rolls — which cost
    ~15x a sublane roll on v5e (measured; the cross-lane shift network
    is the scarce resource).  Callers therefore run all ``lj < 7``
    layers on the transposed ``[128, S]`` block (``t_layout=True``),
    where the original lane index is the sublane axis and the same
    distances become sublane rolls; two [S,128] transposes per section
    amortize over seven avoided lane-roll layers.  Both rolls are
    cyclic, but segments of ``2^(lj+1)`` tile the axis exactly, so the
    selected half never reads a wrapped value.
    """
    if t_layout:
        assert lj < LANES_LOG2
        axis, shift, log = 0, 1 << lj, lj
    elif lj < LANES_LOG2:
        axis, shift, log = 1, 1 << lj, lj
    else:
        axis, shift, log = 0, 1 << (lj - LANES_LOG2), lj - LANES_LOG2
    size = x.shape[axis]
    fwd = pltpu.roll(x, np.int32(size - shift), axis)  # out[i] = in[i + shift]
    bwd = pltpu.roll(x, np.int32(shift), axis)         # out[i] = in[i - shift]
    idx = lax.broadcasted_iota(jnp.int32, x.shape, axis)
    low = ((idx >> log) & 1) == 0            # bit clear -> partner above
    return jnp.where(low, jnp.minimum(x, fwd), jnp.maximum(x, bwd))


def _sweep(x, b_log2: int):
    """The trailing in-block sweep: layers ``B-1 .. 0`` ascending, with
    the ``lj < 7`` tail run on the transposed block (see
    :func:`_asc_layer` on why lane rolls are banned)."""
    for lj in range(b_log2 - 1, LANES_LOG2 - 1, -1):
        x = _asc_layer(x, lj)
    xt = x.T
    for lj in range(LANES_LOG2 - 1, -1, -1):
        xt = _asc_layer(xt, lj, t_layout=True)
    return xt.T


def _flat_bit(shape, j: int, t_layout: bool):
    """Mask ``bit_j(flat index) == 1`` for a block in either layout.

    flat = r*128 + l; natural layout is [r=S sublanes, l=128 lanes],
    transposed is [l, r]: bit j < 7 lives on the lane index, the rest
    on the row index."""
    if j < LANES_LOG2:
        axis = 0 if t_layout else 1
        bit = j
    else:
        axis = 1 if t_layout else 0
        bit = j - LANES_LOG2
    idx = lax.broadcasted_iota(jnp.int32, shape, axis)
    return ((idx >> bit) & 1) == 1


# ---------------------------------------------------------------- kernels


def _block_sort_kernel(x_ref, o_ref, *, s_rows: int, b_log2: int):
    """Stages 1..B of the network, in-VMEM, one block per grid step.

    Flip-state bookkeeping: before stage ``m`` runs, values in its
    descending segments (bit ``m`` of the flat index set) are held
    bit-flipped, so every layer is the cheap ascending form.  Between
    stages only the *difference* of the two masks re-flips (one xor-mask
    pass per stage vs a direction select per layer); all masks here
    depend on the local index only — block-independent — except the
    final unflip, whose mask degenerates to the block parity.
    """
    blk = pl.program_id(0)
    x = x_ref[0]

    def transition(x, m, t_layout):
        """Re-flip from stage ``m``'s direction mask to stage ``m+1``'s
        (or unflip after the last stage); masks are local-index bits
        except bit B, which is the block parity."""
        delta = _flat_bit(x.shape, m, t_layout)
        if m + 1 < b_log2:
            delta = delta ^ _flat_bit(x.shape, m + 1, t_layout)
        elif m + 1 == b_log2:
            delta = delta ^ ((blk & 1) == 1)
        else:  # after the final stage: unflip from the parity state
            delta = (blk & 1) == 1
            return jnp.where(delta, ~x, x)
        return jnp.where(delta, ~x, x)

    # Stages 1..7 run wholly on the transposed block: every layer there
    # has lane-sized distance, and lane rolls are what we must avoid.
    xt = x.T
    xt = jnp.where(_flat_bit(xt.shape, 1, True), ~xt, xt)
    for m in range(1, LANES_LOG2 + 1):
        for lj in range(m - 1, -1, -1):
            xt = _asc_layer(xt, lj, t_layout=True)
        xt = transition(xt, m, True)
    x = xt.T
    for m in range(LANES_LOG2 + 1, b_log2 + 1):
        for lj in range(m - 1, LANES_LOG2 - 1, -1):
            x = _asc_layer(x, lj)
        xt = x.T
        for lj in range(LANES_LOG2 - 1, -1, -1):
            xt = _asc_layer(xt, lj, t_layout=True)
        x = xt.T
        x = transition(x, m, False)
    o_ref[0] = x


def _cross_kernel(s_ref, xl_ref, xh_ref, o_ref):
    """One distance >= 2^(B+3) layer, one output *group* per grid step.

    The transfer unit is a contiguous group of ``_CROSS_GROUP`` blocks:
    every cross layer handled here has block distance >= 8 (the lowest
    three cross bits belong to the merge kernel), so partner blocks
    have equal low-3 bits and whole groups pair with whole groups —
    the same XOR pairing lifted to group indices, with ~2 MiB DMAs
    instead of 256 KiB ones.

    Scalar prefetch ``s_ref = [sjg, sm]``: the layer's distance in
    *group-index bits* (``sjg = lj - B - 3``) and stage size in
    block-index bits (``sm = lk - B``).  Grid is ``(group_pairs, 2)``:
    step ``(q, r)`` reads both groups of pair ``q`` and writes only the
    ``r``-side one, so one output array receives every group with no
    reconciliation pass (the pair's min/max is computed twice — three
    VPU ops against an HBM-bound layer).  The take-min side is a bit of
    the group id (``sm >= 4`` exceeds the in-group bits): no
    per-element masks at all.
    """
    sjg, sm = s_ref[0], s_ref[1]
    q = pl.program_id(0)
    r = pl.program_id(1)
    mask = (1 << sjg) - 1
    glo = ((q & ~mask) << 1) | (q & mask)
    blo = glo * _CROSS_GROUP  # any block of the low group: shared high bits
    take_min_low = ((blo >> sm) & 1) == 0
    lo = jnp.minimum(xl_ref[:], xh_ref[:])
    hi = jnp.maximum(xl_ref[:], xh_ref[:])
    o_ref[:] = jnp.where(take_min_low ^ (r == 1), lo, hi)


def _merge_kernel(s_ref, x_ref, o_ref, *, n_members: int, s_rows: int,
                  b_log2: int):
    """A stage's trailing chunk: the ``c = log2(G)`` lowest cross layers
    AND the whole in-block sweep, in ONE visit of each block to VMEM.

    Grid step ``g`` owns the *contiguous* member group ``{g*G + i}`` —
    the XOR-neighborhood of the cross layers at block-bit positions
    ``c-1 .. 0``: member ``i`` pairs with ``i ^ 2^k``, a Python-level
    slice pairing with no data movement.  Cross compare directions are
    scalar per member (a block-id bit), so a fused cross layer costs
    three vector ops per element; the fusion is what turns the merge
    tail from one HBM round-trip per layer into one per stage.

    Scalar ``s_ref = [m]``: the stage number, for compare directions.
    """
    m = s_ref[0]
    g = pl.program_id(0)
    sign_shift = m - b_log2
    bids = [g * n_members + i for i in range(n_members)]
    # Stage direction is a block-id bit — one scalar flip per member
    # makes every fused layer the raw ascending form.
    desc = [((bid >> sign_shift) & 1) == 1 for bid in bids]
    xs = [jnp.where(desc[i], ~x_ref[i], x_ref[i]) for i in range(n_members)]
    # Members of a pair share the stage-direction bit (they differ only
    # in bits below sign_shift), so flipped ascending min/max is exact.
    _min_max_ladder(xs, n_members.bit_length() - 1)
    for i in range(n_members):
        x = _sweep(xs[i], b_log2)
        o_ref[i] = jnp.where(desc[i], ~x, x)


# ----------------------------------------------------------- host drivers


@functools.lru_cache(maxsize=16)
def _compile_block_sort(nblk: int, s_rows: int, b_log2: int, interpret: bool):
    spec = pl.BlockSpec((1, s_rows, LANES), _zmap,
                        memory_space=pltpu.VMEM)
    return pl.pallas_call(
        functools.partial(_block_sort_kernel, s_rows=s_rows, b_log2=b_log2),
        out_shape=jax.ShapeDtypeStruct((nblk, s_rows, LANES), jnp.int32),
        grid=(nblk,),
        in_specs=[spec],
        out_specs=spec,
        # No aliasing: in-place measured ~1.5x slower (12.9 vs 8.5 ms at
        # 2^26) — same defensive-copy/pipelining penalty as the merge.
        # Raised budget: admits the B=17 block experiment (the unrolled
        # chain holds ~34 live block copies); no effect at B=16.
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )


@functools.lru_cache(maxsize=16)
def _compile_cross(nblk: int, s_rows: int, interpret: bool):
    """One call exchanges every 8-block group with its partner group at
    group distance ``2^sjg``.

    The pair layout rides in through the index maps, which receive the
    scalar-prefetch ref: grid step ``(q, r)`` loads groups ``glo`` (bit
    ``sjg`` clear) and ``glo | 2^sjg`` and writes the ``r``-side one.
    One compilation serves every distance.
    """
    def pair_map(side):
        def f(q, r, s_ref):
            sjg = s_ref[0]
            mask = (1 << sjg) - 1
            glo = ((q & ~mask) << 1) | (q & mask)
            pick = side if side is not None else r
            return (glo | (pick << sjg), _Z, _Z)
        return f

    ngroups = nblk // _CROSS_GROUP
    gspec = lambda m: pl.BlockSpec((_CROSS_GROUP, s_rows, LANES), m,
                                   memory_space=pltpu.VMEM)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(ngroups // 2, 2),
        in_specs=[gspec(pair_map(0)), gspec(pair_map(1))],
        out_specs=gspec(pair_map(None)),
    )
    return pl.pallas_call(
        _cross_kernel,
        out_shape=jax.ShapeDtypeStruct((nblk, s_rows, LANES), jnp.int32),
        grid_spec=grid_spec,
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )


@functools.lru_cache(maxsize=16)
def _compile_merge(n_members: int, nblk: int, s_rows: int, b_log2: int,
                   interpret: bool):
    spec = pl.BlockSpec((n_members, s_rows, LANES), lambda g, s: (g, _Z, _Z),
                        memory_space=pltpu.VMEM)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nblk // n_members,),
        in_specs=[spec],
        out_specs=spec,
    )
    return pl.pallas_call(
        functools.partial(_merge_kernel, n_members=n_members, s_rows=s_rows,
                          b_log2=b_log2),
        out_shape=jax.ShapeDtypeStruct((nblk, s_rows, LANES), jnp.int32),
        grid_spec=grid_spec,
        # Raised budget (see _VMEM_LIMIT): the 8-member window at B=17
        # needs 28.3 MiB; no effect on the shipped B=16 shapes.
        compiler_params=_COMPILER_PARAMS,
        # No input_output_aliases here although each grid step reads only
        # the group it writes: in-place was measured 3.3x SLOWER at 2^30
        # (11.1 s vs 3.4 s end-to-end — XLA inserts defensive copies /
        # the revolving-window pipeline serializes).  The extra buffer is
        # the cheaper trade.  The cross kernel could not alias anyway:
        # both (q, 0) and (q, 1) steps read the pair.
        interpret=interpret,
    )


def sort_padded(x, n_pow2: int, b_log2: int, interpret: bool = False,
                relayout: bool = True):
    """Bitonic-sort a padded power-of-two uint32 array of ``n_pow2``.

    ``x``: flat uint32 [n_pow2], ``n_pow2 = 2^t``, ``t >= b_log2 >= 10``.
    Returns the sorted flat array.  Pure function of shapes — jittable.

    The network itself runs in the *int32* domain (Mosaic has no
    unsigned vector min/max): the sign bit is flipped on the way in and
    out — an order-preserving bijection uint32 -> int32, two cheap
    elementwise passes against ~100 network layers.

    ``relayout`` (round 5, default): stages with single cross layers
    run the rotation-relayout schedule (fused closure visits of up to
    3 bits at 2-block member windows + the rotation-aware 8-member
    merge) instead of one grouped cross layer at a time; see the
    "relayout cross fusion" section and BASELINE.md round 5.
    """
    t = n_pow2.bit_length() - 1
    assert 1 << t == n_pow2 and t >= b_log2
    s_rows = 1 << (b_log2 - LANES_LOG2)
    nblk = n_pow2 >> b_log2
    x = lax.bitcast_convert_type(x ^ jnp.uint32(0x80000000), jnp.int32)
    xb = x.reshape(nblk, s_rows, LANES)

    xb = _compile_block_sort(nblk, s_rows, b_log2, interpret)(xb)

    cross = (None if relayout else
             (_compile_cross(nblk, s_rows, interpret)
              if t > b_log2 + 3 else None))

    for m in range(b_log2 + 1, t + 1):
        nbits = m - b_log2  # cross layers at block-bit positions nbits-1..0
        if relayout and nbits > 3:
            n_single = nbits - 3
            jarr = jnp.asarray([nbits], jnp.int32)
            if n_single % 3:
                c = n_single % 3
                visit = _compile_relayout_cross(1 << c, nblk, s_rows,
                                                interpret)
                xb = visit(jarr, *([xb] * (1 << c)))
            visit3 = _compile_relayout_cross(8, nblk, s_rows, interpret)
            for _ in range(n_single // 3):
                xb = visit3(jarr, *([xb] * 8))
            xb = _compile_rot_merge(nblk, s_rows, b_log2, 3, interpret)(
                jarr, *([xb] * 8))
            continue
        # High cross layers (block distance >= 8) one at a time; the
        # lowest min(nbits, 3) fuse into the merge kernel with the sweep.
        for sj in range(nbits - 1, 2, -1):
            xb = cross(jnp.asarray([sj - 3, nbits], jnp.int32), xb, xb)
        g_final = 1 << min(nbits, 3)
        merge = _compile_merge(g_final, nblk, s_rows, b_log2, interpret)
        xb = merge(jnp.asarray([m], jnp.int32), xb)
    out = xb.reshape(-1)
    return lax.bitcast_convert_type(out, jnp.uint32) ^ jnp.uint32(0x80000000)


# ------------------------------------------- 1-word relayout cross (r5)
#
# Key-only twins of the rotation-relayout visit / rot-merge pair
# kernels below (see the "relayout cross fusion" section): same
# geometry, no payload plane.  Being single-plane, the 1-word shapes
# afford 8-member closures (c=3) at 2-block member windows inside the
# raised scoped-vmem budget, so each visit retires up to three cross
# layers per n-read + n-write.


def _min_max_ladder(ks, c: int):
    """Key-only XOR-closure ladder: pairwise min/max, highest bit first
    (members of a pair share the stage-direction bit, so the flipped
    ascending form is exact — see :func:`_merge_kernel`)."""
    n_members = len(ks)
    for kbit in range(c - 1, -1, -1):
        for i in range(n_members):
            if (i >> kbit) & 1:
                continue
            jm = i | (1 << kbit)
            ks[i], ks[jm] = jnp.minimum(ks[i], ks[jm]), \
                jnp.maximum(ks[i], ks[jm])


def _relayout_cross_kernel(s_ref, *refs, n_members: int, bpm: int):
    """Key-only :func:`_relayout_cross_pair_kernel`."""
    j_bits = s_ref[0]
    g = pl.program_id(0)
    c = n_members.bit_length() - 1
    lb = bpm.bit_length() - 1
    desc = ((g >> (j_bits - lb - c)) & 1) == 1
    o_ref = refs[n_members]
    for b in range(bpm):
        ks = [jnp.where(desc, ~refs[i][b], refs[i][b])
              for i in range(n_members)]
        _min_max_ladder(ks, c)
        for i in range(n_members):
            o_ref[b * n_members + i] = jnp.where(desc, ~ks[i], ks[i])


@functools.lru_cache(maxsize=16)
def _compile_relayout_cross(n_members: int, nblk: int, s_rows: int,
                            interpret: bool, bpm: int = 2):
    """Key-only :func:`_compile_relayout_cross_pair`."""
    c = n_members.bit_length() - 1
    lb = bpm.bit_length() - 1

    def member_map(s):
        def f(g, s_ref):
            j_w = s_ref[0] - lb
            qbits = j_w - c
            seg = g >> qbits
            w = g & ((1 << qbits) - 1)
            return ((seg << j_w) + (s << qbits) + w, _Z, _Z)
        return f

    mspec = lambda s: pl.BlockSpec((bpm, s_rows, LANES), member_map(s),
                                   memory_space=pltpu.VMEM)
    ospec = pl.BlockSpec((bpm * n_members, s_rows, LANES),
                         lambda g, s: (g, _Z, _Z),
                         memory_space=pltpu.VMEM)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nblk // (bpm * n_members),),
        in_specs=[mspec(s) for s in range(n_members)],
        out_specs=ospec,
    )
    return pl.pallas_call(
        functools.partial(_relayout_cross_kernel, n_members=n_members,
                          bpm=bpm),
        out_shape=jax.ShapeDtypeStruct((nblk, s_rows, LANES), jnp.int32),
        grid_spec=grid_spec,
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )


def _rot_merge_kernel(s_ref, *refs, n_members: int, s_rows: int,
                      b_log2: int, tail: int, bpm: int):
    """Key-only :func:`_rot_merge_pair_kernel`: ``n_members = 2^tail``
    gathered through the stage's accumulated rotation, cross ladder +
    full sweep, natural-order contiguous write."""
    j_bits = s_ref[0]
    lb = bpm.bit_length() - 1
    g = pl.program_id(0)
    desc = ((g >> (j_bits - tail - lb)) & 1) == 1
    o_ref = refs[n_members]
    for b in range(bpm):
        ks = [jnp.where(desc, ~refs[i][b], refs[i][b])
              for i in range(n_members)]
        _min_max_ladder(ks, tail)
        for i in range(n_members):
            k = _sweep(ks[i], b_log2)
            o_ref[b * n_members + i] = jnp.where(desc, ~k, k)


@functools.lru_cache(maxsize=16)
def _compile_rot_merge(nblk: int, s_rows: int, b_log2: int, tail: int,
                       interpret: bool, bpm: int = 2):
    """Key-only :func:`_compile_rot_merge_pair` with a ``2^tail``-member
    group (the 1-word engine fuses three cross bits into its merge)."""
    n_members = 1 << tail
    lb = bpm.bit_length() - 1

    def member_map(s):
        def f(g, s_ref):
            j_w = s_ref[0] - lb
            wbits = j_w - tail
            seg = g >> wbits
            w = g & ((1 << wbits) - 1)
            return ((seg << j_w) + (s << wbits) + w, _Z, _Z)
        return f

    mspec = lambda s: pl.BlockSpec((bpm, s_rows, LANES), member_map(s),
                                   memory_space=pltpu.VMEM)
    ospec = pl.BlockSpec((bpm * n_members, s_rows, LANES),
                         lambda g, s: (g, _Z, _Z),
                         memory_space=pltpu.VMEM)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nblk // (bpm * n_members),),
        in_specs=[mspec(s) for s in range(n_members)],
        out_specs=ospec,
    )
    return pl.pallas_call(
        functools.partial(_rot_merge_kernel, n_members=n_members,
                          s_rows=s_rows, b_log2=b_log2, tail=tail, bpm=bpm),
        out_shape=jax.ShapeDtypeStruct((nblk, s_rows, LANES), jnp.int32),
        grid_spec=grid_spec,
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )


# ------------------------------------------------------- key+payload twin


def _asc_layer_pair(k, p, lj: int, t_layout: bool = False):
    """Pair compare-exchange at distance ``2^lj``: the key plane runs the
    exact 6-op ascending form of :func:`_asc_layer`; the payload plane is
    routed by ``out_k == k`` (see module docstring — measured 1.98x the
    1-word layer, vs 4.8x for a lexicographic 2-word compare)."""
    if t_layout:
        assert lj < LANES_LOG2
        axis, shift, log = 0, 1 << lj, lj
    elif lj < LANES_LOG2:
        axis, shift, log = 1, 1 << lj, lj
    else:
        axis, shift, log = 0, 1 << (lj - LANES_LOG2), lj - LANES_LOG2
    size = k.shape[axis]
    fk = pltpu.roll(k, np.int32(size - shift), axis)
    bk = pltpu.roll(k, np.int32(shift), axis)
    fp = pltpu.roll(p, np.int32(size - shift), axis)
    bp = pltpu.roll(p, np.int32(shift), axis)
    idx = lax.broadcasted_iota(jnp.int32, k.shape, axis)
    low = ((idx >> log) & 1) == 0
    out_k = jnp.where(low, jnp.minimum(k, fk), jnp.maximum(k, bk))
    out_p = jnp.where(out_k == k, p, jnp.where(low, fp, bp))
    return out_k, out_p


def _sweep_pair(k, p, b_log2: int):
    """Pair twin of :func:`_sweep`: the trailing in-block sweep with the
    ``lj < 7`` tail on the transposed planes."""
    for lj in range(b_log2 - 1, LANES_LOG2 - 1, -1):
        k, p = _asc_layer_pair(k, p, lj)
    kt, pt = k.T, p.T
    for lj in range(LANES_LOG2 - 1, -1, -1):
        kt, pt = _asc_layer_pair(kt, pt, lj, t_layout=True)
    return kt.T, pt.T


def _block_sort_pair_kernel(k_ref, p_ref, ok_ref, op_ref, *, s_rows: int,
                            b_log2: int):
    """Pair twin of :func:`_block_sort_kernel`.  Flip bookkeeping touches
    the KEY plane only — the payload is never compared, so descending
    segments keep their payloads as-is and ``out_k == k`` routing stays
    exact on the flipped keys (equality is flip-invariant)."""
    blk = pl.program_id(0)

    def transition(k, m, t_layout):
        delta = _flat_bit(k.shape, m, t_layout)
        if m + 1 < b_log2:
            delta = delta ^ _flat_bit(k.shape, m + 1, t_layout)
        elif m + 1 == b_log2:
            delta = delta ^ ((blk & 1) == 1)
        else:
            delta = (blk & 1) == 1
            return jnp.where(delta, ~k, k)
        return jnp.where(delta, ~k, k)

    kt, pt = k_ref[0].T, p_ref[0].T
    kt = jnp.where(_flat_bit(kt.shape, 1, True), ~kt, kt)
    for m in range(1, LANES_LOG2 + 1):
        for lj in range(m - 1, -1, -1):
            kt, pt = _asc_layer_pair(kt, pt, lj, t_layout=True)
        kt = transition(kt, m, True)
    k, p = kt.T, pt.T
    for m in range(LANES_LOG2 + 1, b_log2 + 1):
        for lj in range(m - 1, LANES_LOG2 - 1, -1):
            k, p = _asc_layer_pair(k, p, lj)
        kt, pt = k.T, p.T
        for lj in range(LANES_LOG2 - 1, -1, -1):
            kt, pt = _asc_layer_pair(kt, pt, lj, t_layout=True)
        k, p = kt.T, pt.T
        k = transition(k, m, False)
    ok_ref[0], op_ref[0] = k, p


def _cross_pair_kernel(s_ref, kl_ref, kh_ref, pl_ref, ph_ref,
                       ok_ref, op_ref):
    """Pair twin of :func:`_cross_kernel` (group = ``_PAIR_CROSS_GROUP``
    blocks): key min/max as before; each side's payload follows its key
    result (``lo == kl`` / ``hi == kh`` — ties route both payloads to
    their own sides, a consistent no-swap, so the pair multiset is
    preserved exactly)."""
    sjg, sm = s_ref[0], s_ref[1]
    q = pl.program_id(0)
    r = pl.program_id(1)
    mask = (1 << sjg) - 1
    glo = ((q & ~mask) << 1) | (q & mask)
    blo = glo * _PAIR_CROSS_GROUP
    take_min_low = ((blo >> sm) & 1) == 0
    kl, kh = kl_ref[:], kh_ref[:]
    lo = jnp.minimum(kl, kh)
    hi = jnp.maximum(kl, kh)
    p_lo = jnp.where(lo == kl, pl_ref[:], ph_ref[:])
    p_hi = jnp.where(hi == kh, ph_ref[:], pl_ref[:])
    side = take_min_low ^ (r == 1)
    ok_ref[:] = jnp.where(side, lo, hi)
    op_ref[:] = jnp.where(side, p_lo, p_hi)


def _merge_pair_kernel(s_ref, k_ref, p_ref, ok_ref, op_ref, *,
                       n_members: int, s_rows: int, b_log2: int):
    """Pair twin of :func:`_merge_kernel` (fused cross tail + sweep)."""
    m = s_ref[0]
    g = pl.program_id(0)
    sign_shift = m - b_log2
    bids = [g * n_members + i for i in range(n_members)]
    desc = [((bid >> sign_shift) & 1) == 1 for bid in bids]
    ks = [jnp.where(desc[i], ~k_ref[i], k_ref[i]) for i in range(n_members)]
    ps = [p_ref[i] for i in range(n_members)]
    _closure_ladder(ks, ps, n_members.bit_length() - 1)
    for i in range(n_members):
        k, p = _sweep_pair(ks[i], ps[i], b_log2)
        ok_ref[i] = jnp.where(desc[i], ~k, k)
        op_ref[i] = p


@functools.lru_cache(maxsize=16)
def _compile_block_sort_pair(nblk: int, s_rows: int, b_log2: int,
                             interpret: bool):
    spec = pl.BlockSpec((1, s_rows, LANES), _zmap,
                        memory_space=pltpu.VMEM)
    shape = jax.ShapeDtypeStruct((nblk, s_rows, LANES), jnp.int32)
    return pl.pallas_call(
        functools.partial(_block_sort_pair_kernel, s_rows=s_rows,
                          b_log2=b_log2),
        out_shape=[shape, shape],
        grid=(nblk,),
        in_specs=[spec, spec],
        out_specs=[spec, spec],
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )


@functools.lru_cache(maxsize=16)
def _compile_cross_pair(nblk: int, s_rows: int, interpret: bool):
    def pair_map(side):
        def f(q, r, s_ref):
            sjg = s_ref[0]
            mask = (1 << sjg) - 1
            glo = ((q & ~mask) << 1) | (q & mask)
            pick = side if side is not None else r
            return (glo | (pick << sjg), _Z, _Z)
        return f

    ngroups = nblk // _PAIR_CROSS_GROUP
    gspec = lambda m: pl.BlockSpec((_PAIR_CROSS_GROUP, s_rows, LANES), m,
                                   memory_space=pltpu.VMEM)
    shape = jax.ShapeDtypeStruct((nblk, s_rows, LANES), jnp.int32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(ngroups // 2, 2),
        in_specs=[gspec(pair_map(0)), gspec(pair_map(1)),
                  gspec(pair_map(0)), gspec(pair_map(1))],
        out_specs=[gspec(pair_map(None)), gspec(pair_map(None))],
    )
    return pl.pallas_call(
        _cross_pair_kernel,
        out_shape=[shape, shape],
        grid_spec=grid_spec,
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )


@functools.lru_cache(maxsize=16)
def _compile_merge_pair(n_members: int, nblk: int, s_rows: int, b_log2: int,
                        interpret: bool):
    spec = pl.BlockSpec((n_members, s_rows, LANES), lambda g, s: (g, _Z, _Z),
                        memory_space=pltpu.VMEM)
    shape = jax.ShapeDtypeStruct((nblk, s_rows, LANES), jnp.int32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nblk // n_members,),
        in_specs=[spec, spec],
        out_specs=[spec, spec],
    )
    return pl.pallas_call(
        functools.partial(_merge_pair_kernel, n_members=n_members,
                          s_rows=s_rows, b_log2=b_log2),
        out_shape=[shape, shape],
        grid_spec=grid_spec,
        # Raised budget: the 8-member shape (tail_bits=3 experiment)
        # needs 25.6 MiB scoped vmem; no effect on the 2/4-member forms.
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )


def sort_pairs_padded(k, p, n_pow2: int, b_log2: int,
                      interpret: bool = False, relayout: bool = True,
                      tail_bits: int | None = None):
    """Bitonic-sort uint32 ``(k, p)`` pairs by the KEY plane only.

    Same network as :func:`sort_padded`; the payload plane rides every
    exchange via ``out_k == k`` routing.  Equal keys keep their own
    payloads at every comparator, so the output payload order within an
    equal-key run is an arbitrary (but deterministic) permutation — the
    64-bit caller fixes runs afterwards (``kernels.sort_two_words``).

    ``relayout`` (round 5, default): stages with >= 1 single cross
    layer run the rotation-relayout schedule — fused 2-bit (odd
    residue: one 1-bit) closure visits at 2n traffic per visit instead
    of 3n per layer, closed by the rotation-aware merge.  ``False``
    keeps the round-4 one-layer-at-a-time cross path (the A/B
    baseline; see BASELINE.md round-5 section).

    ``tail_bits`` (relayout only; 2 or 3): cross bits fused into the
    stage-final merge.  Default 2; 3 trades 4 closure visits for
    8-member merges — measured session-dependent (see the tail
    selection comment below), kept for pricing.

    Returns ``(k_sorted, p_permuted)``, both flat uint32 [n_pow2].
    """
    t = n_pow2.bit_length() - 1
    assert 1 << t == n_pow2 and t >= b_log2
    s_rows = 1 << (b_log2 - LANES_LOG2)
    nblk = n_pow2 >> b_log2
    k = lax.bitcast_convert_type(k ^ jnp.uint32(0x80000000), jnp.int32)
    p = lax.bitcast_convert_type(p, jnp.int32)  # payload: bits only
    kb = k.reshape(nblk, s_rows, LANES)
    pb = p.reshape(nblk, s_rows, LANES)

    kb, pb = _compile_block_sort_pair(nblk, s_rows, b_log2, interpret)(kb, pb)

    # Merge tail width: 2 stays the shipped default.  The 3-bit tail
    # (8-member rot-merge at bpm=1; drops 4 closure visits at 2^26)
    # was priced same-process on chip across three sessions and
    # STRADDLES parity: 1.08x and 1.29x faster through degraded
    # tunnels (fewer kernels -> less per-kernel overhead), 0.97x
    # (slower) in a clean session where the 4-member merge pipelines
    # better.  Clean sessions are the headline regime, so tail=2
    # ships; ``tail_bits=3`` remains available and tested.
    if tail_bits is not None:
        if not relayout:
            raise ValueError("tail_bits applies to the relayout schedule "
                             "only (the r4 path keeps its 2-bit tail)")
        if tail_bits not in (2, 3):
            raise ValueError(f"tail_bits={tail_bits!r}: supported widths "
                             "are 2 and 3 (wider 2^tail-member merges "
                             "exceed the scoped-vmem budget)")
    tail = tail_bits if (relayout and tail_bits is not None) \
        else _PAIR_MERGE_BITS
    cross = (None if relayout else
             (_compile_cross_pair(nblk, s_rows, interpret)
              if t > b_log2 + tail else None))

    for m in range(b_log2 + 1, t + 1):
        nbits = m - b_log2
        if relayout and nbits > tail:
            # Rotation-relayout schedule: highest logical bit first, so
            # an odd single-layer count leads with the 1-bit visit.
            n_single = nbits - tail
            jarr = jnp.asarray([nbits], jnp.int32)
            if n_single % 2:
                kb, pb = _compile_relayout_cross_pair(
                    2, nblk, s_rows, interpret, bpm=2)(jarr, kb, kb, pb, pb)
            visit2 = _compile_relayout_cross_pair(4, nblk, s_rows, interpret,
                                                  bpm=2)
            for _ in range(n_single // 2):
                kb, pb = visit2(jarr, *([kb] * 4), *([pb] * 4))
            nm = 1 << tail
            kb, pb = _compile_rot_merge_pair(
                nblk, s_rows, b_log2, interpret, tail=tail,
                bpm=2 if tail == 2 else 1)(
                jarr, *([kb] * nm), *([pb] * nm))
            continue
        for sj in range(nbits - 1, tail - 1, -1):
            kb, pb = cross(jnp.asarray([sj - tail, nbits], jnp.int32),
                           kb, kb, pb, pb)
        g_final = 1 << min(nbits, tail)
        merge = _compile_merge_pair(g_final, nblk, s_rows, b_log2, interpret)
        kb, pb = merge(jnp.asarray([m], jnp.int32), kb, pb)
    k_out = lax.bitcast_convert_type(kb.reshape(-1), jnp.uint32)
    p_out = lax.bitcast_convert_type(pb.reshape(-1), jnp.uint32)
    return k_out ^ jnp.uint32(0x80000000), p_out


# ------------------------------------------- relayout cross fusion (r5)
#
# The round-4 phase split attributed 56% of the pair network to its 36
# single cross layers (round 5's partial-network attribution corrected
# that to ~44% — BASELINE.md — but they were the biggest addressable
# phase either way): each one reads the whole array TWICE (both sides
# of the pair, so one output array receives every group) and writes it
# once — 3n traffic per layer, measured 1.89 ms against a 0.75 ms
# streaming floor at 2^26.  The wall named in BASELINE.md: consecutive cross
# layers at block bits (j, j-1) form 4-way XOR-closures whose members
# are NOT contiguous, and a pallas grid step cannot write 4 scattered
# windows of one output array.
#
# The fix is a *rotation relayout*: the closure members CAN be read
# scattered (input index maps are arbitrary), so a grid step reads the
# 4 blocks of one closure over the top two unprocessed block bits,
# applies BOTH layers in VMEM, and writes one CONTIGUOUS 4-block group
# — which implicitly rotates the two processed bits to the bottom of
# the physical block index.  The invariant that makes one kernel serve
# every visit: after each visit the next unprocessed logical bits sit
# at the TOP of the physical index again, so every visit is "process
# phys top bits, rotate them down", with the same index maps.  After
# all visits the stage's merge reads its members through the
# accumulated rotation (phys = s*2^(J-2) + h within the segment) and
# writes natural order — the permutation never escapes the stage.
#
# Traffic per 2 layers: n read + n write (vs 6n for two single cross
# layers).  Segment bits (>= J) never move, and every member of a
# closure shares them, so the stage direction stays one scalar flip.


def _closure_ladder(ks, ps, c: int):
    """The pairwise key min/max + ``out_k == k`` payload-routing ladder
    over an XOR-closure of ``2^c`` members, highest bit first — shared
    by the merge tails and the relayout visits (the tie rule — equal
    keys keep their own payloads on both sides — must stay identical
    across every schedule)."""
    n_members = len(ks)
    for kbit in range(c - 1, -1, -1):
        for i in range(n_members):
            if (i >> kbit) & 1:
                continue
            jm = i | (1 << kbit)
            lo = jnp.minimum(ks[i], ks[jm])
            hi = jnp.maximum(ks[i], ks[jm])
            p_lo = jnp.where(lo == ks[i], ps[i], ps[jm])
            p_hi = jnp.where(hi == ks[jm], ps[jm], ps[i])
            ks[i], ks[jm] = lo, hi
            ps[i], ps[jm] = p_lo, p_hi


def _relayout_cross_pair_kernel(s_ref, *refs, n_members: int, bpm: int):
    """Fused visit over the top ``c = log2(n_members)`` physical block
    bits of each 2^J-block segment (J = ``s_ref[0]`` in block bits):
    the c cross layers of one XOR-closure, highest logical bit first,
    in one VMEM visit.  ``refs`` = n_members key refs, n_members
    payload refs, then the key/payload outputs.  ``bpm`` = consecutive
    blocks per member window (``bpm = 2`` halves the grid and doubles
    the DMA size — each grid step carries two whole closures at
    adjacent q; measured: single-block member DMAs ran the visit at
    ~2x the streaming floor).  Sub-window b of member s belongs to
    closure q = bpm*w + b and writes output row ``b*n_members + s``."""
    j_bits = s_ref[0]
    g = pl.program_id(0)
    c = n_members.bit_length() - 1
    lb = bpm.bit_length() - 1
    desc = ((g >> (j_bits - lb - c)) & 1) == 1  # segment bit = flat bit m
    ok_ref, op_ref = refs[2 * n_members], refs[2 * n_members + 1]
    for b in range(bpm):
        ks = [jnp.where(desc, ~refs[i][b], refs[i][b])
              for i in range(n_members)]
        ps = [refs[n_members + i][b] for i in range(n_members)]
        _closure_ladder(ks, ps, c)
        for i in range(n_members):
            ok_ref[b * n_members + i] = jnp.where(desc, ~ks[i], ks[i])
            op_ref[b * n_members + i] = ps[i]


@functools.lru_cache(maxsize=16)
def _compile_relayout_cross_pair(n_members: int, nblk: int, s_rows: int,
                                 interpret: bool, bpm: int = 2):
    """One visit = grid over output groups of ``bpm * n_members``
    contiguous blocks; member ``s`` reads the PHYSICAL ``bpm``-block
    window at ``(seg << J') + (s << (J'-c)) + w`` in window units
    (J' = J - log2(bpm)) — the closures over the segment's top c
    physical bits for ``bpm`` adjacent q — and lands contiguously, so
    the c bits rotate to the bottom of the physical block index."""
    c = n_members.bit_length() - 1
    lb = bpm.bit_length() - 1

    def member_map(s):
        def f(g, s_ref):
            j_w = s_ref[0] - lb       # segment bits in window units
            qbits = j_w - c
            seg = g >> qbits
            w = g & ((1 << qbits) - 1)
            return ((seg << j_w) + (s << qbits) + w, _Z, _Z)
        return f

    mspec = lambda s: pl.BlockSpec((bpm, s_rows, LANES), member_map(s),
                                   memory_space=pltpu.VMEM)
    ospec = pl.BlockSpec((bpm * n_members, s_rows, LANES),
                         lambda g, s: (g, _Z, _Z),
                         memory_space=pltpu.VMEM)
    shape = jax.ShapeDtypeStruct((nblk, s_rows, LANES), jnp.int32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nblk // (bpm * n_members),),
        in_specs=[mspec(s) for s in range(n_members)] * 2,
        out_specs=[ospec, ospec],
    )
    return pl.pallas_call(
        functools.partial(_relayout_cross_pair_kernel, n_members=n_members,
                          bpm=bpm),
        out_shape=[shape, shape],
        grid_spec=grid_spec,
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )


def _rot_merge_pair_kernel(s_ref, *refs, n_members: int, s_rows: int,
                           b_log2: int, tail: int, bpm: int):
    """:func:`_merge_pair_kernel` with gather inputs: member ``s`` was
    read through the stage's accumulated rotation, so the body is the
    identical cross-tail + sweep; the block id used for the stage
    direction is the segment bit, shared by all members.  ``bpm``
    consecutive rotation groups ride per window (same DMA-width trade
    as the visits); ``n_members = 2^tail``."""
    j_bits = s_ref[0]
    lb = bpm.bit_length() - 1
    g = pl.program_id(0)
    desc = ((g >> (j_bits - tail - lb)) & 1) == 1
    ok_ref, op_ref = refs[2 * n_members], refs[2 * n_members + 1]
    for b in range(bpm):
        ks = [jnp.where(desc, ~refs[i][b], refs[i][b])
              for i in range(n_members)]
        ps = [refs[n_members + i][b] for i in range(n_members)]
        _closure_ladder(ks, ps, tail)
        for i in range(n_members):
            k, p = _sweep_pair(ks[i], ps[i], b_log2)
            ok_ref[b * n_members + i] = jnp.where(desc, ~k, k)
            op_ref[b * n_members + i] = p


@functools.lru_cache(maxsize=16)
def _compile_rot_merge_pair(nblk: int, s_rows: int, b_log2: int,
                            interpret: bool, tail: int = 2, bpm: int = 2):
    """Stage-final merge reading through the accumulated rotation: after
    the visits consumed logical bits J-1..tail, the remaining logical
    bits (tail-1..0) sit at the TOP of the physical index — member
    ``s`` of logical group ``h`` lives at phys
    ``(seg << J) + (s << (J-tail)) + h`` (consecutive h adjacent, so
    ``bpm`` groups share one window).  Writes natural logical order
    (contiguous groups of 2^tail), closing the stage's permutation."""
    n_members = 1 << tail
    lb = bpm.bit_length() - 1

    def member_map(s):
        def f(g, s_ref):
            j_w = s_ref[0] - lb
            wbits = j_w - tail
            seg = g >> wbits
            w = g & ((1 << wbits) - 1)
            return ((seg << j_w) + (s << wbits) + w, _Z, _Z)
        return f

    mspec = lambda s: pl.BlockSpec((bpm, s_rows, LANES), member_map(s),
                                   memory_space=pltpu.VMEM)
    ospec = pl.BlockSpec((bpm * n_members, s_rows, LANES),
                         lambda g, s: (g, _Z, _Z),
                         memory_space=pltpu.VMEM)
    shape = jax.ShapeDtypeStruct((nblk, s_rows, LANES), jnp.int32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nblk // (bpm * n_members),),
        in_specs=[mspec(s) for s in range(n_members)] * 2,
        out_specs=[ospec, ospec],
    )
    return pl.pallas_call(
        functools.partial(_rot_merge_pair_kernel, n_members=n_members,
                          s_rows=s_rows, b_log2=b_log2, tail=tail, bpm=bpm),
        out_shape=[shape, shape],
        grid_spec=grid_spec,
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )


def _fix_runs_pair_kernel(k_ref, p_ref, o_ref, *, passes: int, s_rows: int):
    """In-VMEM segment-masked odd-even transposition: ``passes`` passes
    of lo-exchange within equal-hi runs, per block.  The XLA formulation
    of the same passes costs ~6 ms/pass at 2^26 (measured — the
    shift-by-one copies stream the whole plane from HBM every pass);
    here all passes run on one VMEM visit.

    Neighbor construction in the natural ``[S, 128]`` layout: flat
    ``i+1`` is ``lane+1`` with a row carry at lane 127 — one cheap
    sublane roll plus one lane roll for the carry column, selected by
    the lane mask.  The block's last element pairs with nothing (its
    neighbor wraps); runs crossing block boundaries are finished by the
    XLA boundary-strip pass (``kernels._fix_boundary``).

    The lo plane is compared in the sign-flipped int32 domain (unsigned
    order; Mosaic has no unsigned vector compare) and unflipped on the
    way out.  hi is compared for equality only — flip-invariant.
    """
    hi = k_ref[0]
    lo = p_ref[0] ^ jnp.int32(-(2**31))
    lane = lax.broadcasted_iota(jnp.int32, hi.shape, 1)
    row = lax.broadcasted_iota(jnp.int32, hi.shape, 0)
    at_carry = lane == (LANES - 1)
    at_zero = lane == 0
    last = at_carry & (row == s_rows - 1)

    def nxt(v):
        up = pltpu.roll(v, np.int32(LANES - 1), 1)
        upc = pltpu.roll(up, np.int32(s_rows - 1), 0)
        return jnp.where(at_carry, upc, up)

    def prv(v):
        dn = pltpu.roll(v, np.int32(1), 1)
        dnc = pltpu.roll(dn, np.int32(1), 0)
        return jnp.where(at_zero, dnc, dn)

    same = (hi == nxt(hi)) & ~last
    par = lane & 1  # flat parity = lane bit 0
    for t in range(passes):
        nb = nxt(lo)
        act = same & (par == (t & 1)) & (lo > nb)
        a32 = act.astype(jnp.int32)
        # element 0's "previous" wraps to the block's last element,
        # which is always inactive -> act 0 -> safe
        pv_on = prv(a32) == 1
        lo = jnp.where(act, nb, jnp.where(pv_on, prv(lo), lo))
    o_ref[0] = lo ^ jnp.int32(-(2**31))


@functools.lru_cache(maxsize=16)
def _compile_fix_runs(nblk: int, s_rows: int, passes: int, interpret: bool):
    spec = pl.BlockSpec((1, s_rows, LANES), _zmap,
                        memory_space=pltpu.VMEM)
    return pl.pallas_call(
        functools.partial(_fix_runs_pair_kernel, passes=passes,
                          s_rows=s_rows),
        out_shape=jax.ShapeDtypeStruct((nblk, s_rows, LANES), jnp.int32),
        grid=(nblk,),
        in_specs=[spec, spec],
        out_specs=spec,
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )


def fix_runs_pairs(hi, lo, passes: int, b_log2: int,
                   interpret: bool = False):
    """Sort ``lo`` within equal-``hi`` runs of length <= ``passes``
    (both flat uint32, ``hi`` sorted, power-of-two length): the in-VMEM
    per-block kernel above; cross-block runs are the caller's
    boundary-strip job."""
    n = hi.shape[0]
    s_rows = 1 << (b_log2 - LANES_LOG2)
    nblk = n >> b_log2
    kb = lax.bitcast_convert_type(hi, jnp.int32).reshape(nblk, s_rows, LANES)
    pb = lax.bitcast_convert_type(lo, jnp.int32).reshape(nblk, s_rows, LANES)
    out = _compile_fix_runs(nblk, s_rows, passes, interpret)(kb, pb)
    return lax.bitcast_convert_type(out.reshape(-1), jnp.uint32)


def bitonic_sort_u32(x, interpret: bool = False):
    """Sort a flat uint32 array ascending; drop-in for ``jnp.sort``.

    Pads to the next power of two with the max sentinel (pads sort to
    the tail and are sliced off — same contract as the API layer's
    pad-with-max, ``models/api.py``).  Arrays smaller than
    ``2^MIN_SORT_LOG2`` fall back to ``lax.sort`` — below that size the
    network's fixed padding/pass structure costs more than it saves.
    """
    n = x.shape[0]
    if n == 0:
        return x
    t = max((n - 1).bit_length(), MIN_SORT_LOG2) if n else 0
    # Break-even: the network runs on the padded 2^t array (~0.6x
    # lax.sort's per-element cost, measured), so heavily padded sizes
    # lose to sorting the exact n with lax.sort.
    if n < (1 << MIN_SORT_LOG2) or n * 10 < (1 << t) * 6:
        return lax.sort([x], num_keys=1, is_stable=False)[0]
    b_log2 = min(BLOCK_LOG2, t)
    n_pow2 = 1 << t
    if n_pow2 != n:
        pad = jnp.full((n_pow2 - n,), jnp.uint32(0xFFFFFFFF), jnp.uint32)
        xp = jnp.concatenate([x, pad])
    else:
        xp = x
    out = sort_padded(xp, n_pow2, b_log2, interpret=interpret)
    return out[:n] if n_pow2 != n else out
