"""Metrics sidecar + pass-planner regression tests."""

import json

import numpy as np

from mpitest_tpu.models.api import _passes_from_diffs, _word_diffs
from mpitest_tpu.ops.keys import codec_for
from mpitest_tpu.utils.metrics import Metrics


def _needed_passes(words, digit_bits):
    """Pass count for host words — the composition sort() itself uses."""
    return _passes_from_diffs(_word_diffs(words), digit_bits)


def test_metrics_roundtrip(tmp_path):
    m = Metrics(config={"algo": "radix", "n": 1024})
    m.throughput("sort", 1_000_000, 0.5)
    m.bandwidth("all_to_all", 8_000_000_000, 1.0)
    m.record_phases({"sort": 0.25})
    p = tmp_path / "metrics.jsonl"
    m.dump(str(p))
    obj = json.loads(p.read_text().strip())
    assert obj["config"]["algo"] == "radix"
    assert obj["metrics"]["sort"] == {"value": 2.0, "unit": "Mkeys/s"}
    assert obj["metrics"]["all_to_all"] == {"value": 8.0, "unit": "GB/s"}
    assert obj["metrics"]["phase_sort_ms"] == {"value": 250.0, "unit": "ms"}


def test_needed_passes_word_alignment():
    """digit_bits ∤ 32: passes restart at word boundaries, so keys differing
    only in the high word must still cover the full low word (regression:
    contiguous bit-count undercounts and leaves the high word unsorted)."""
    codec = codec_for(np.int64)
    words = codec.encode(np.array([2**32, 0], np.int64))
    per_word = -(-32 // 12)  # 3
    assert _needed_passes(words, 12) == per_word + 1  # low word fully + 1 digit

    # 8-bit digits, int32: small range needs 1 pass (the sign-bias flip
    # cancels in max^min for same-sign keys); mixed signs span bit 31 → 4.
    c32 = codec_for(np.int32)
    assert _needed_passes(c32.encode(np.array([0, 200], np.int32)), 8) == 1
    assert _needed_passes(c32.encode(np.array([-1, 1], np.int32)), 8) == 4
    assert _needed_passes(c32.encode(np.array([5, 5], np.int32)), 8) == 0


def test_needed_passes_digit12_sorts_correctly(mesh8):
    """End-to-end: non-divisor digit width on 64-bit keys (the bug case)."""
    from mpitest_tpu.models.api import sort

    x = np.array([2**32, 0, -(2**40), 7, 2**33 + 1, -1], np.int64)
    got = sort(x, algorithm="radix", mesh=mesh8, digit_bits=12)
    np.testing.assert_array_equal(got, np.sort(x))


def test_bench_canonical_host_provenance_gate(monkeypatch, capsys, mesh8):
    """ADVICE r5 satellite: a pinned CANONICAL_NATIVE_MKEYS row only
    yields vs_canonical_native on the host class it was measured on;
    elsewhere the row carries the skip reason instead of a silently
    cross-host ratio."""
    import bench
    from mpitest_tpu.utils.platform import host_fingerprint

    monkeypatch.setenv("BENCH_LOG2N", "12")
    monkeypatch.setenv("BENCH_REPEATS", "1")
    monkeypatch.setenv("BENCH_NATIVE_RANKS", "0")
    key = ("radix", 12, "int32", 0)

    from mpitest_tpu.utils import knobs

    # bench.main() pins SORT_FALLBACK=0 / SORT_MAX_RETRIES=0 /
    # SORT_EXCHANGE_ENGINE=lax / SORT_PLANNER=off via
    # os.environ.setdefault — correct for its normal subprocess life,
    # but an IN-PROCESS call here would leak the pins into every later
    # test in the suite (observed: the whole supervisor-ladder family
    # failing "retry budget exhausted", and the exchange-engine knob
    # test seeing default "lax", in full runs while passing
    # standalone).  scoped_env restores the pre-call state.
    _BENCH_PINS = dict(SORT_FALLBACK=None, SORT_MAX_RETRIES=None,
                       SORT_EXCHANGE_ENGINE=None, SORT_PLANNER=None)
    monkeypatch.setitem(bench.CANONICAL_NATIVE_MKEYS, key,
                        {"mkeys": 1.0, "host": "someone-elses-box/64c"})
    with knobs.scoped_env(**_BENCH_PINS):
        bench.main()
    row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "vs_canonical_native" not in row
    assert "someone-elses-box/64c" in row["vs_canonical_native_skipped"]

    monkeypatch.setitem(bench.CANONICAL_NATIVE_MKEYS, key,
                        {"mkeys": 1.0, "host": host_fingerprint()})
    with knobs.scoped_env(**_BENCH_PINS):
        bench.main()
    row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert row["vs_canonical_native"] > 0
    assert "vs_canonical_native_skipped" not in row


def test_bench_driver_contract(tmp_path):
    """The driver scrapes exactly ONE JSON line from bench.py stdout with
    the metric/value/unit/vs_baseline fields.  Runs tiny on a 2-device
    virtual CPU mesh (BENCH_PLATFORM hook) so no TPU is needed."""
    import json
    import os
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    env = dict(
        os.environ,
        BENCH_PLATFORM="cpu:2",
        BENCH_LOG2N="14",
        BENCH_REPEATS="1",
        BENCH_NATIVE_RANKS="0",
    )
    r = subprocess.run(
        [sys.executable, str(repo / "bench.py")],
        capture_output=True, text=True, env=env, timeout=600, cwd=str(repo),
    )
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [l for l in r.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line, got {lines}"
    obj = json.loads(lines[0])
    assert {"metric", "value", "unit", "vs_baseline"} <= obj.keys()
    assert obj["unit"] == "Mkeys/s" and obj["value"] > 0
