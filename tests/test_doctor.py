"""Sort doctor + timeline + sentinel unit tests (ISSUE 16).

Three layers, smallest fixtures that pin the math:

* **timeline** — straggler factor (incl. ragged / missing-rank byte
  lists and the median-zero fallback), bytes-proportional rank lanes
  scaled into the anchor span, critical-path phase attribution,
  compute/DMA overlap, and the Chrome enrichment's stable per-rank
  tids (the rank-attribution satellite fix).
* **doctor rules** — one minimal fixture per registered pathology in
  ``DOCTOR_RULES``; each must produce EXACTLY its finding with the
  evidence cited and a knob suggested, and a clean evidence snapshot
  must produce zero findings.
* **sentinel** — the rolling-window math in-process: clean window
  raises nothing, an error burst raises exactly ``deadline_burn`` and
  bridges into ``sort_alerts_total``, p99 drift vs the EWMA raises,
  and the per-rule cooldown holds one alert per window.
"""

import json

import pytest

from mpitest_tpu import doctor
from mpitest_tpu.utils import timeline
from mpitest_tpu.utils.spans import SpanLog


# -- timeline math ----------------------------------------------------

def test_straggler_stats_basic():
    st = timeline.straggler_stats([100.0, 100.0, 100.0, 300.0])
    assert st is not None
    assert st["factor"] == 3.0
    assert st["max"] == 300.0 and st["median"] == 100.0


def test_straggler_stats_degenerate():
    # <2 usable ranks or an all-zero list carries no signal
    assert timeline.straggler_stats([5.0]) is None
    assert timeline.straggler_stats([0.0, 0.0]) is None
    # median 0 (most ranks idle) falls back to the mean: [0,0,0,9]
    # -> mean 2.25 -> factor 4.0
    st = timeline.straggler_stats([0.0, 0.0, 0.0, 9.0])
    assert st is not None and st["factor"] == 4.0


def _rows_fixture():
    """One anchored pass + phases + overlapping compute/DMA, as plain
    dict rows (the duck-typed input report.py feeds the fold)."""
    return [
        {"name": "sort_pass", "id": 1, "parent": None,
         "t0": 0.0, "dt": 2.0, "attrs": {}},
        {"name": "exchange_balance", "id": 2, "parent": 1,
         "t0": 0.5, "dt": 0.0,
         "attrs": {"recv_bytes": [100, 110, 90, 440],
                   "negotiated_cap": 512, "algorithm": "radix"}},
        {"name": "phase:sort", "t0": 0.0, "dt": 2.0, "attrs": {}},
        {"name": "phase:verify", "t0": 2.0, "dt": 0.3, "attrs": {}},
        {"name": "jit_execute", "t0": 0.0, "dt": 1.0, "attrs": {}},
        {"name": "ingest.transfer", "t0": 0.5, "dt": 1.0,
         "attrs": {"bytes": 4096}},
    ]


def test_build_timeline_lanes_straggler_critical_path():
    tl = timeline.build_timeline(_rows_fixture())
    # sorted bytes [90,100,110,440]: median 105, factor 440/105
    assert tl["straggler_factor"] == pytest.approx(440 / 105, abs=1e-3)
    assert tl["ranks"] == [0, 1, 2, 3]
    # lanes scale the ANCHOR's 2.0s budget by bytes/peak
    lane3 = tl["lanes"][3][0]
    assert lane3["dt"] == pytest.approx(2.0) and lane3["estimated"]
    assert tl["lanes"][0][0]["dt"] == pytest.approx(2.0 * 100 / 440)
    assert tl["passes"][0]["anchor"] == "sort_pass"
    assert tl["critical_path_phase"] == "sort"
    assert tl["phases"]["verify"] == pytest.approx(0.3)
    # compute [0,1] vs DMA [0.5,1.5]: 0.5s overlap = 50% of DMA
    assert tl["overlap"]["compute_dma_pct"] == pytest.approx(50.0)
    assert tl["counters"]["exchange_cap"] == [(0.5, 512.0)]
    assert tl["counters"]["inflight_bytes"][0] == (0.5, 4096.0)
    assert tl["counters"]["inflight_bytes"][-1] == (1.5, 0.0)


def test_build_timeline_ragged_and_unanchored():
    rows = [
        # non-numeric entries drop; 2 usable ranks is still a signal
        {"name": "exchange_balance", "id": 7, "parent": None,
         "t0": 0.0, "dt": 0.0,
         "attrs": {"recv_bytes": [100, None, "x", 300]}},
    ]
    tl = timeline.build_timeline(rows)
    p = tl["passes"][0]
    assert p["rank_bytes"] == [100.0, 300.0]
    assert p["straggler"] == 1.5  # max/median of the usable pair
    # no dt>0 ancestor -> no lane estimates, factor still reported
    assert p["anchor"] is None and tl["lanes"] == {}
    assert tl["straggler_factor"] == 1.5
    # a single usable rank carries no imbalance signal at all
    tl2 = timeline.build_timeline(
        [{"name": "exchange_balance", "t0": 0.0, "dt": 0.0,
          "attrs": {"recv_bytes": [100, "?"]}}])
    assert tl2["passes"][0]["straggler"] is None
    assert tl2["straggler_factor"] is None


def test_bench_fold_keys_only_when_signal_present():
    assert timeline.bench_fold([]) == {}
    fold = timeline.bench_fold(_rows_fixture())
    assert fold["straggler_factor"] == pytest.approx(440 / 105, abs=1e-3)
    assert fold["critical_path_phase"] == "sort"


def test_chrome_events_stable_rank_tids():
    events = timeline.chrome_events(_rows_fixture())
    names = {(e.get("tid"), e["args"].get("name")) for e in events
             if e.get("ph") == "M"}
    for rank in range(4):
        assert (timeline.RANK_TID_BASE + rank,
                f"rank {rank} (estimated)") in names
    lanes = [e for e in events if e.get("ph") == "X"]
    assert lanes and all(e["tid"] >= timeline.RANK_TID_BASE
                         and e["args"]["estimated"] for e in lanes)
    counters = {e["name"] for e in events if e.get("ph") == "C"}
    assert {"inflight bytes", "exchange cap"} <= counters


def test_chrome_trace_export_carries_rank_lanes():
    """SpanLog.to_chrome_trace appends the enrichment (the rank-
    attribution satellite): per-rank tids alongside the host lane."""
    log = SpanLog()
    with log.span("sort_pass"):
        log.event("exchange_balance", recv_bytes=[10, 20, 30, 40])
    trace = log.to_chrome_trace()
    events = trace["traceEvents"] if isinstance(trace, dict) else trace
    json.dumps(events)  # must stay valid trace-event JSON
    tids = {e.get("tid") for e in events}
    assert {timeline.RANK_TID_BASE + r for r in range(4)} <= tids


# -- doctor rules: one fixture per registered pathology ---------------

def _only(findings, rule):
    assert [f.rule for f in findings] == [rule], \
        f"expected exactly {rule}, got {[f.rule for f in findings]}"
    return findings[0]


def test_rule_vocabulary_is_fully_registered():
    assert set(doctor.DOCTOR_RULES) == set(doctor._RULES)
    with pytest.raises(KeyError):
        doctor.run_rule("bogus_rule", doctor.empty_evidence())
    with pytest.raises(KeyError):
        doctor.Finding("bogus_rule", "warn", "x")
    with pytest.raises(ValueError):
        doctor.Finding("skew_imbalance", "meh", "x")


def test_clean_evidence_zero_findings():
    ev = doctor.evidence_from_rows(
        [{"name": "serve.request", "dt": 0.01,
          "attrs": {"status": "ok"}}] * 20,
        timeline={"straggler_factor": 1.1,
                  "phases": {"sort": 1.0, "verify": 0.05}})
    assert doctor.diagnose(ev) == []


def test_rule_skew_imbalance():
    ev = doctor.empty_evidence()
    ev["timeline"] = {"straggler_factor": 4.0,
                      "passes": [{"seq": 0, "straggler": 4.0,
                                  "rank_bytes": [100.0, 400.0]}]}
    f = _only(doctor.diagnose(ev), "skew_imbalance")
    assert f.severity == "critical"  # >= SKEW_FACTOR_CRITICAL
    assert f.knob == "SORT_RESTAGE" and f.evidence
    assert f.value == 4.0 and f.threshold == doctor.SKEW_FACTOR_WARN


def test_rule_cap_thrash():
    rows = [{"name": "sort.plan", "attrs": {
        "decisions": {"cap": {"chosen": 512,
                              "actual": {"regrows": 3}}}}}]
    f = _only(doctor.diagnose(doctor.evidence_from_rows(rows)),
              "cap_thrash")
    assert f.knob == "SORT_CAP_FACTOR" and f.value == 3.0
    assert any("regrows=3" in c for c in f.evidence)


def test_rule_compile_storm():
    rows = ([{"name": "serve.compile_cache", "attrs": {"hit": False}}] * 5
            + [{"name": "serve.compile_cache", "attrs": {"hit": True}}])
    f = _only(doctor.diagnose(doctor.evidence_from_rows(rows)),
              "compile_storm")
    assert f.knob == "SORT_SERVE_SHAPE_BUCKETS" and f.value == 5.0


def test_rule_window_misfit_waste_and_occupancy():
    rows = [{"name": "sort.plan", "attrs": {
        "decisions": {"batch": {"actual": {"waste": 0.7}}}}}]
    f = _only(doctor.diagnose(doctor.evidence_from_rows(rows)),
              "window_misfit")
    assert f.severity == "warn" and f.value == 0.7
    # the never-packs shape: N batches, N segments -> occupancy info
    rows = [{"name": "serve.batch", "attrs": {"segments": 1}}] * 4
    f = _only(doctor.diagnose(doctor.evidence_from_rows(rows)),
              "window_misfit")
    assert f.severity == "info" and f.value == 1.0


def test_rule_spill_bound():
    ev = doctor.empty_evidence()
    ev["timeline"] = {"overlap": {"disk_s": 3.5, "compute_s": 0.5,
                                  "compute_disk_pct": 10.0}}
    f = _only(doctor.diagnose(ev), "spill_bound")
    assert f.knob == "SORT_MERGE_FANIN"
    assert f.value == pytest.approx(3.5 / 4.0)


def test_rule_verify_overhead_and_absolute_floor():
    ev = doctor.empty_evidence()
    ev["timeline"] = {"phases": {"sort": 2.0, "verify": 1.0}}
    f = _only(doctor.diagnose(ev), "verify_overhead_regression")
    assert f.knob == "SORT_VERIFY"
    assert f.value == pytest.approx(1.0 / 3.0, abs=1e-3)
    # a tiny run below VERIFY_MIN_SECONDS never fires, whatever the
    # ratio — cold-compile verify on small inputs is not a pathology
    ev["timeline"] = {"phases": {"sort": 0.04,
                                 "verify": doctor.VERIFY_MIN_SECONDS / 2}}
    assert doctor.diagnose(ev) == []


def test_rule_breaker_flap():
    rows = ([{"name": "serve.watchdog", "attrs": {"event": "trip"}}] * 2
            + [{"name": "serve.watchdog", "attrs": {"event": "recovered"}}])
    f = _only(doctor.diagnose(doctor.evidence_from_rows(rows)),
              "breaker_flap")
    assert f.severity == "critical" and f.value == 2.0
    assert any("recovered" in c for c in f.evidence)


def test_rule_spill_churn():
    # one recovery alone is normal operation — below the gate
    rows = [{"name": "external.recover",
             "attrs": {"reason": "fingerprint", "bad_runs": 1}}]
    assert doctor.diagnose(doctor.evidence_from_rows(rows)) == []
    # recovery + crash resume in one trace = churn (warn)
    rows.append({"name": "external.resume",
                 "attrs": {"dataset": "ds1", "committed": 4,
                           "valid": 4}})
    f = _only(doctor.diagnose(doctor.evidence_from_rows(rows)),
              "spill_churn")
    assert f.severity == "warn" and f.value == 2.0
    assert f.knob == "SORT_SPILL_DIR"
    assert any("external.recover" in c for c in f.evidence)
    assert any("external.resume" in c for c in f.evidence)
    # repeated integrity recoveries escalate to critical
    rows = [{"name": "external.recover",
             "attrs": {"reason": "fingerprint", "bad_runs": 1}}] * 2
    f = _only(doctor.diagnose(doctor.evidence_from_rows(rows)),
              "spill_churn")
    assert f.severity == "critical"


def test_rule_deadline_burn():
    rows = ([{"name": "serve.request", "dt": 0.01,
              "attrs": {"status": "ok"}}] * 12
            + [{"name": "serve.request", "dt": 0.0,
                "attrs": {"status": "deadline"}}] * 4
            + [{"name": "serve.deadline", "attrs": {}}] * 4)
    f = _only(doctor.diagnose(doctor.evidence_from_rows(rows)),
              "deadline_burn")
    # 4/16 = 25% vs the 0.1% allowance: way past 2x -> critical
    assert f.severity == "critical"
    assert any("4 expired" in c for c in f.evidence)
    assert f.knob == "SORT_SERVE_MAX_INFLIGHT"


def test_plan_findings_compact_digest_block():
    attrs = {"decisions": {"cap": {"actual": {"regrows": 2}},
                           "batch": {"actual": {"waste": 0.9}}}}
    block = doctor.plan_findings(attrs)
    assert sorted(b["rule"] for b in block) == ["cap_thrash",
                                                "window_misfit"]
    assert all(set(b) == {"rule", "severity", "summary"} for b in block)
    assert doctor.plan_findings({}) == []


def test_render_shapes():
    assert "no findings" in doctor.render([])
    f = doctor.Finding("cap_thrash", "warn", "caps", evidence=["e1"],
                       knob="SORT_CAP_FACTOR", direction="raise")
    out = doctor.render([f])
    assert "[WARN] cap_thrash" in out and "evidence: e1" in out
    assert "SORT_CAP_FACTOR" in out


# -- sentinel math ----------------------------------------------------

def _wired(window_s=60.0, burn_rate=2.0):
    from mpitest_tpu.serve.sentinel import SortSentinel
    from mpitest_tpu.utils.metrics_live import (LiveMetrics,
                                                SpanMetricsBridge)
    log = SpanLog()
    metrics = LiveMetrics()
    log.observers.append(SpanMetricsBridge(metrics))
    sen = SortSentinel(metrics, log, window_s=window_s,
                       burn_rate=burn_rate)
    log.observers.append(sen)
    return log, metrics, sen


def test_sentinel_clean_window_stays_silent():
    log, _metrics, sen = _wired()
    for _ in range(30):
        log.record("serve.request", 0.0, 0.01, status="ok")
    assert sen.alerts_total == 0
    assert not any(s.name == "serve.alert" for s in log.spans)


def test_sentinel_burn_alert_bridges_and_cools_down():
    log, metrics, sen = _wired()
    for _ in range(12):
        log.record("serve.request", 0.0, 0.01, status="ok")
    for _ in range(6):
        log.record("serve.request", 0.0, 0.0, status="deadline")
    assert sen.alerts_total == 1  # cooldown: one alert per window
    alert = sen.alerts[0]
    assert alert["rule"] == "deadline_burn"
    assert alert["severity"] == "critical"  # 33% vs 0.1% allowance
    spans = [s for s in log.spans if s.name == "serve.alert"]
    assert len(spans) == 1 and spans[0].attrs["rule"] == "deadline_burn"
    assert ('sort_alerts_total{rule="deadline_burn",'
            'severity="critical"} 1') in metrics.render_prom()
    snap = sen.snapshot()
    assert snap["alerts_total"] == 1
    assert snap["series"]["window_errors"] == 6


def test_sentinel_p99_drift():
    log, _metrics, sen = _wired()
    # 10 clean samples seed the EWMA at ~10ms ...
    for _ in range(10):
        log.record("serve.request", 0.0, 0.010, status="ok")
    assert sen.alerts_total == 0 and sen._p99_ewma == pytest.approx(10.0)
    # ... then one 100ms sample drives p99 past DRIFT_FACTOR x EWMA
    log.record("serve.request", 0.0, 0.100, status="ok")
    assert sen.alerts_total == 1
    assert sen.alerts[0]["rule"] == "deadline_burn"
    assert sen.alerts[0]["severity"] == "warn"


def test_sentinel_skew_and_cap_rules():
    log, _m, sen = _wired()
    for _ in range(3):  # MIN_IMBALANCE_SAMPLES before the EWMA alerts
        log.record("exchange_balance", 0.0, 0.0, peer_ratio=4.0)
    assert [a["rule"] for a in sen.alerts] == ["skew_imbalance"]
    assert sen.alerts[0]["severity"] == "critical"
    log2, _m2, sen2 = _wired()
    for _ in range(2):
        log2.record("sort.plan", 0.0, 0.0,
                    decisions={"cap": {"actual": {"regrows": 1}}})
    assert [a["rule"] for a in sen2.alerts] == ["cap_thrash"]
