"""Fused local-sort engine tests (ISSUE 17): the per-pass radix kernel,
the device merge-order kernel, planner key-width compaction, ladder
degradation and provenance.

The Mosaic kernels have never lowered on a real TPU (interpret mode is
the oracle — ``ops/radix_pallas.py`` module docstring); on this CPU
mesh the ``radix_pallas`` knob value resolves to the interpreter form,
which runs the histogram/rank/scatter arithmetic for real.  Named
``test_zz_*`` to sort late: the parity cells compile shard_map
programs on the mesh8 fixture.
"""

from __future__ import annotations

import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from mpitest_tpu.models.api import (  # noqa: E402
    _resolve_local_engine, _use_fused, sort)
from mpitest_tpu.ops import radix_pallas as rp  # noqa: E402
from mpitest_tpu.ops.keys import codec_for  # noqa: E402
from mpitest_tpu.utils import knobs  # noqa: E402
from mpitest_tpu.utils.trace import Tracer  # noqa: E402


# ------------------------------------------------------- knob contract

def test_local_engine_knob_validation():
    """SORT_LOCAL_ENGINE is registered, typed, and fail-fast."""
    with knobs.scoped_env(SORT_LOCAL_ENGINE="warp9"):
        with pytest.raises(knobs.KnobError, match="SORT_LOCAL_ENGINE"):
            knobs.get("SORT_LOCAL_ENGINE")
    for ok in ("auto", "bitonic", "lax", "radix_pallas",
               "radix_pallas_interpret"):
        with knobs.scoped_env(SORT_LOCAL_ENGINE=ok):
            assert knobs.get("SORT_LOCAL_ENGINE") == ok
    assert knobs.get("SORT_LOCAL_ENGINE") == "auto"  # default


def test_local_engine_knob_fail_fast_in_cli_and_server():
    """Both drivers validate the knob at startup (same contract as the
    exchange engine: garbage -> one [ERROR] line + rc != 0)."""
    from drivers import sort_cli

    with knobs.scoped_env(SORT_LOCAL_ENGINE="warp9"):
        rc = sort_cli.main(["sort_cli.py", "/nonexistent-but-knobs-first"])
        assert rc != 0
    server_src = (REPO / "drivers" / "sort_server.py").read_text()
    assert '"SORT_LOCAL_ENGINE"' in server_src
    cli_src = (REPO / "drivers" / "sort_cli.py").read_text()
    assert '"SORT_LOCAL_ENGINE"' in cli_src


def test_local_engine_resolution_on_cpu():
    """The fused family resolves to the interpreter off-TPU, falls to
    lax outside the kernel envelope, and auto NEVER chooses it (the
    never-lowered-on-TPU caveat: auto flips only after a real-TPU
    re-baseline)."""
    assert _resolve_local_engine("radix_pallas", 2, 4096) == \
        "radix_pallas_interpret"
    assert _resolve_local_engine("radix_pallas_interpret", 2, 4096) == \
        "radix_pallas_interpret"
    # outside the envelope: too many words / too many elements
    assert _resolve_local_engine(
        "radix_pallas", rp.FUSED_MAX_WORDS + 1, 4096) == "lax"
    assert _resolve_local_engine(
        "radix_pallas", 2, rp.FUSED_MAX_ELEMS + 1) == "lax"
    assert _use_fused("radix_pallas", 2, 4096)
    assert not _use_fused("radix_pallas", 2, rp.FUSED_MAX_ELEMS + 1)
    assert not _use_fused("lax", 2, 4096)
    # auto never resolves into the fused family
    for n in (64, 4096, 1 << 18):
        assert not _resolve_local_engine("auto", 2, n).startswith(
            "radix_pallas")
    assert _resolve_local_engine("lax", 2, 4096) == "lax"


# ------------------------------------------------------ pass-plan units

def test_pass_plan_full_width_and_compaction():
    full = rp.pass_plan(None, 2)
    assert len(full) == 8  # 2 words x 32 bits / 8-bit digits
    # lsw-first: word index 1 (least significant) planned before 0
    assert [w for w, _s, _b in full] == [1, 1, 1, 1, 0, 0, 0, 0]
    assert all(b == rp.DIGIT_BITS for _w, _s, b in full)
    # 20-bit low word, constant high word: 3 passes, high word skipped
    plan = rp.pass_plan((0, (1 << 20) - 1), 2)
    assert len(plan) == 3
    assert all(w == 1 for w, _s, _b in plan)
    assert plan[-1] == (1, 16, 4)  # the top partial digit is narrow
    # all-constant input sorts in zero passes
    assert rp.pass_plan((0, 0), 2) == ()
    with pytest.raises(ValueError, match="diffs"):
        rp.pass_plan((1,), 2)


# ------------------------------------------------------- kernel parity

@pytest.mark.parametrize("dtype", [np.int32, np.uint64, np.float32])
@pytest.mark.parametrize("kind,n", [("uniform", 2048), ("dup", 2048),
                                    ("sorted", 2048), ("tiny", 5),
                                    ("nondiv", 1537)])
def test_fused_kernel_matches_lexsort(dtype, kind, n, rng):
    """fused_radix_sort (interpret) is word-for-word the np.lexsort
    oracle across dtype x input-class cells."""
    if np.dtype(dtype).kind == "f":
        x = rng.normal(size=n).astype(dtype)
    else:
        info = np.iinfo(dtype)
        hi = 5 if kind == "dup" else info.max
        x = rng.integers(info.min if kind != "dup" else 0, hi,
                         size=n, dtype=dtype, endpoint=True)
    if kind == "sorted":
        x = np.sort(x)
    words = codec_for(dtype).encode(x)
    ref = np.lexsort(tuple(reversed(words)))
    got = rp.fused_radix_sort(tuple(np.asarray(w) for w in words),
                              interpret=True)
    for g, w in zip(got, words):
        np.testing.assert_array_equal(np.asarray(g), w[ref])


def test_fused_kernel_compacted_plan_parity(rng):
    """A compacted (range-narrow) plan sorts identically in fewer
    passes, launch-counted: exactly one pallas_call per planned pass."""
    x = rng.integers(0, 1 << 20, size=2048, dtype=np.int64)
    words = tuple(np.asarray(w) for w in codec_for(np.int64).encode(x))
    diffs = tuple(int(w.max()) - int(w.min()) for w in words)
    plan = rp.pass_plan(diffs, len(words))
    assert len(plan) < len(rp.pass_plan(None, len(words)))
    before = rp.pass_launches()
    got = rp.fused_radix_sort(words, diffs=diffs, interpret=True)
    np.asarray(got[0])
    assert rp.pass_launches() - before == len(plan)
    ref = np.lexsort(tuple(reversed(words)))
    for g, w in zip(got, words):
        np.testing.assert_array_equal(np.asarray(g), w[ref])


def test_fused_lowering_has_no_sort_chain(rng):
    """The perf claim in HLO terms: the fused pass lowers with NO
    sort/searchsorted chain — the old per-pass lax.sort is gone from
    the program the engine runs."""
    x = rng.integers(0, 1 << 16, size=1024, dtype=np.int32)
    words = tuple(jnp.asarray(w)
                  for w in codec_for(np.int32).encode(x))

    def run(*ws):
        return rp.fused_radix_sort(ws, interpret=True)

    txt = jax.jit(run).lower(*words).as_text()
    assert " sort(" not in txt


# ------------------------------------------------------- merge kernel

@pytest.mark.parametrize("n", [1, 2, 37, 300, 1000, 4096])
def test_merge_order_matches_lexsort(n, rng):
    """merge_order == np.lexsort on dup-heavy (run, pos)-tied planes —
    the exact planes store/merge.py hands it."""
    kw = rng.integers(0, 7, size=n).astype(np.uint32)  # dup-heavy keys
    rid = rng.integers(0, 4, size=n).astype(np.uint32)
    pos = np.arange(n, dtype=np.uint32)
    rng.shuffle(pos)
    order = np.asarray(rp.merge_order((kw, rid, pos), interpret=True))
    ref = np.lexsort((pos, rid, kw))
    np.testing.assert_array_equal(order, ref)
    # two-word keys through the same path
    kw2 = rng.integers(0, 3, size=n).astype(np.uint32)
    order = np.asarray(rp.merge_order((kw2, kw, rid, pos),
                                      interpret=True))
    np.testing.assert_array_equal(order, np.lexsort((pos, rid, kw, kw2)))


def test_merge_order_envelope_is_typed():
    n = rp.MERGE_MAX_ELEMS + 1
    planes = (np.zeros(n, np.uint32), np.arange(n, dtype=np.uint32))
    with pytest.raises(ValueError, match="merge_order"):
        rp.merge_order(planes, interpret=True)


def test_store_merge_order_for_device_vs_host(rng):
    """store/merge._order_for under the fused knob is bit-identical to
    the host lexsort (and falls back to it above the envelope)."""
    from mpitest_tpu.store.merge import _order_for

    n = 600
    kws = (rng.integers(0, 9, size=n).astype(np.uint32),)
    rid = rng.integers(0, 3, size=n).astype(np.uint32)
    pos = np.arange(n, dtype=np.uint32)
    want = np.lexsort((pos, rid) + tuple(reversed(kws)))
    with knobs.scoped_env(SORT_LOCAL_ENGINE="radix_pallas_interpret"):
        got = _order_for(kws, rid, pos)
    np.testing.assert_array_equal(got, want)
    # above MERGE_MAX_ELEMS: the host path, same bytes
    n = rp.MERGE_MAX_ELEMS + 8
    kws = (rng.integers(0, 9, size=n).astype(np.uint32),)
    rid = np.zeros(n, np.uint32)
    pos = np.arange(n, dtype=np.uint32)
    with knobs.scoped_env(SORT_LOCAL_ENGINE="radix_pallas_interpret"):
        got = _order_for(kws, rid, pos)
    np.testing.assert_array_equal(
        got, np.lexsort((pos, rid) + tuple(reversed(kws))))


# ------------------------------------------------- parity on the mesh

@pytest.mark.parametrize("dtype", [np.int32, np.uint64, np.float32])
@pytest.mark.parametrize("algo", ["radix", "sample"])
def test_lax_vs_fused_parity_mesh8(algo, dtype, mesh8, rng):
    """Bit-identical output across the local-engine knob, both
    algorithms, 1- and 2-word codecs and the float totalOrder codec.
    SORT_FALLBACK=0 pins the engine: a broken fused path would
    silently degrade and the comparison would pass vacuously."""
    if np.dtype(dtype).kind == "f":
        x = rng.normal(size=1 << 12).astype(dtype)
    else:
        info = np.iinfo(dtype)
        x = rng.integers(info.min, info.max, size=1 << 12,
                         dtype=dtype, endpoint=True)
    with knobs.scoped_env(SORT_FALLBACK="0", SORT_LOCAL_ENGINE="lax"):
        a = sort(x, algorithm=algo, mesh=mesh8)
    t = Tracer()
    with knobs.scoped_env(SORT_FALLBACK="0",
                          SORT_LOCAL_ENGINE="radix_pallas"):
        b = sort(x, algorithm=algo, mesh=mesh8, tracer=t)
    assert str(t.counters["local_engine"]).startswith("radix_pallas")
    assert "local_engine_degraded" not in t.counters
    assert a.dtype == b.dtype == np.dtype(dtype)
    assert a.tobytes() == b.tobytes()


def test_fused_single_device_parity(rng):
    """The 1-device dispatch path (no mesh) through the fused engine."""
    x = rng.integers(-(2**62), 2**62, size=3000, dtype=np.int64)
    with knobs.scoped_env(SORT_FALLBACK="0", SORT_LOCAL_ENGINE="lax"):
        a = sort(x)
    t = Tracer()
    with knobs.scoped_env(SORT_FALLBACK="0",
                          SORT_LOCAL_ENGINE="radix_pallas"):
        b = sort(x, tracer=t)
    assert t.counters["local_engine"] == "radix_pallas_interpret"
    assert a.tobytes() == b.tobytes() == np.sort(x).tobytes()


# ------------------------------------------- ladder + plan provenance

def test_ladder_degrades_fused_to_lax_verified(mesh8, rng):
    """A fused-kernel failure re-runs the SAME algorithm and exchange
    engine on the lax LOCAL rung; the result is verified and the
    degrade is a plan decision + counter, never a silent engine swap.

    Odd key count (3311): the injected fault fires at TRACE time, so
    this test must miss every compile-cache entry the parity cells
    populated."""
    x = rng.integers(-(2**31), 2**31 - 1, size=3311, dtype=np.int32)
    orig = rp.fused_radix_sort

    def boom(*a, **kw):
        raise jax.errors.JaxRuntimeError(
            "INTERNAL: injected fused local-sort fault (test)")

    rp.fused_radix_sort = boom
    try:
        with knobs.scoped_env(SORT_MAX_RETRIES="0", SORT_FALLBACK="1",
                              SORT_LOCAL_ENGINE="radix_pallas"):
            t = Tracer()
            out = sort(x, algorithm="radix", mesh=mesh8, tracer=t)
    finally:
        rp.fused_radix_sort = orig
    np.testing.assert_array_equal(out, np.sort(x))
    assert t.counters["local_engine"] == "lax"
    assert t.counters["local_engine_degraded"] == 1
    assert t.counters["verify_runs"] >= 1
    assert "degraded_to" not in t.counters  # same algorithm, local rung
    assert "exchange_engine_degraded" not in t.counters
    d = t.plan.decisions["engine"]
    assert d.trigger == "pallas_fault"
    assert d.regret == 1.0
    assert d.actual.get("local_engine") == "lax"


def test_ladder_pinned_fused_engine_fails_loudly(mesh8, rng):
    """SORT_FALLBACK=0 pins the engine: a fused-kernel failure is a
    typed error, never a silent lax re-run."""
    from mpitest_tpu.models.api import SortRetryExhausted

    x = rng.integers(0, 100, size=997, dtype=np.int32)
    orig = rp.fused_radix_sort

    def boom(*a, **kw):
        raise jax.errors.JaxRuntimeError("INTERNAL: injected (test)")

    rp.fused_radix_sort = boom
    try:
        with knobs.scoped_env(SORT_MAX_RETRIES="0", SORT_FALLBACK="0",
                              SORT_LOCAL_ENGINE="radix_pallas"):
            with pytest.raises(SortRetryExhausted):
                sort(x, algorithm="radix", mesh=mesh8)
    finally:
        rp.fused_radix_sort = orig


def test_plan_actual_carries_local_engine_and_backend(mesh8, rng):
    """The engine decision's actual record names the resolved local
    engine AND the backend — the doctor's local_sort_lax rule keys on
    exactly these two fields."""
    x = rng.integers(0, 1 << 16, size=1 << 12, dtype=np.int32)
    t = Tracer()
    with knobs.scoped_env(SORT_LOCAL_ENGINE="radix_pallas"):
        sort(x, algorithm="radix", mesh=mesh8, tracer=t)
    a = t.plan.decisions["engine"].actual
    assert str(a.get("local_engine")).startswith("radix_pallas")
    assert a.get("backend") == str(jax.default_backend())


# ------------------------------------------------- planner compaction

def test_profile_reports_key_width(rng):
    from mpitest_tpu.models import plan as plan_mod

    narrow = rng.integers(0, 1 << 20, size=4096, dtype=np.int64)
    prof = plan_mod.profile_host_array(narrow)
    assert 0 < prof["key_width"] <= 20
    floats = rng.normal(size=4096).astype(np.float32)
    assert "key_width" not in plan_mod.profile_host_array(floats)


def test_planner_chooses_radix_compact_for_narrow_keys():
    from mpitest_tpu.models import planner

    prof = {"key_width": 20, "sortedness": 0.5, "dup_ratio": 0.1}
    c = planner.choose(prof, "radix", verify_on=True)
    assert c.policy == "radix_compact" and c.trigger == "range_narrow"
    # prediction mirrors the auto digit-width rule: min over 8/16-bit
    assert c.predicted["passes"] == 2  # ceil(20/16) beats ceil(20/8)
    assert c.algo is None  # requested radix: the reroute is a no-op
    c = planner.choose(dict(prof, key_width=9), "sample", verify_on=True)
    assert c.predicted["passes"] == 1 and c.algo == "radix"
    # wide or constant keys never compact
    for w in (0, 21, 64):
        assert planner.choose(dict(prof, key_width=w), "radix",
                              verify_on=True).policy != "radix_compact"
    # earlier policies keep priority: a sorted profile is passthrough
    c = planner.choose({"key_width": 12, "sortedness": 1.0},
                       "radix", verify_on=True)
    assert c.policy == "verify_passthrough"


def test_planner_passes_prediction_regret(mesh8, rng):
    """Honest narrow profile: predicted pass count == ran, regret 0."""
    x = rng.integers(0, 1 << 20, size=1 << 13, dtype=np.int64)
    t = Tracer()
    with knobs.scoped_env(SORT_PLANNER="on"):
        out = sort(x, algorithm="radix", mesh=mesh8, tracer=t)
    assert out.tobytes() == np.sort(x).tobytes()
    d = t.plan.decisions["passes"]
    assert d.trigger == "planner"
    assert int(d.predicted["passes"]) == int(d.chosen)
    assert d.regret == 0.0


def test_planner_lying_profile_stamps_passes_regret(mesh8, rng):
    """A profile that under-reports the key width promises too few
    passes — the 'passes' decision prices the lie as relative regret."""
    from mpitest_tpu.models import plan as plan_mod

    x = rng.integers(-(2**62), 2**62, size=1 << 13, dtype=np.int64)
    orig = plan_mod.profile_host_array

    def lying(arr, *a, **kw):
        out = dict(orig(arr, *a, **kw))
        out["key_width"] = 18  # the lie: true width is ~63 bits
        return out

    plan_mod.profile_host_array = lying
    try:
        t = Tracer()
        with knobs.scoped_env(SORT_PLANNER="on"):
            out = sort(x, algorithm="radix", mesh=mesh8, tracer=t)
    finally:
        plan_mod.profile_host_array = orig
    assert out.tobytes() == np.sort(x).tobytes()
    d = t.plan.decisions["passes"]
    assert d.trigger == "planner" and (d.regret or 0.0) > 0.0


# ------------------------------------------------------ doctor's rule

def test_doctor_rule_local_sort_lax():
    """Sort-dominant timeline + a TPU-backend plan that ran the lax
    local engine -> the SORT_LOCAL_ENGINE suggestion; CPU backends and
    non-sort critical paths stay silent."""
    from mpitest_tpu import doctor

    def ev(backend, phase="sort", engine="lax"):
        e = doctor.empty_evidence()
        e["timeline"] = {"critical_path_phase": phase,
                         "phases": {"sort": 2.0, "decode": 0.5}}
        e["plans"] = [{"decisions": {"engine": {
            "chosen": "xla",
            "actual": {"local_engine": engine, "backend": backend}}}}]
        return e

    fs = [f for f in doctor.diagnose(ev("tpu"))
          if f.rule == "local_sort_lax"]
    assert len(fs) == 1
    f = fs[0]
    assert f.knob == "SORT_LOCAL_ENGINE"
    assert "radix_pallas" in f.direction
    assert f.threshold == doctor.LOCAL_SORT_PHASE_GATE
    assert f.value == pytest.approx(0.8)
    assert any("critical_path_phase=sort" in c for c in f.evidence)
    # cpu backend / fused engine / decode-dominated: silent
    for quiet in (ev("cpu"), ev("tpu", engine="radix_pallas"),
                  ev("tpu", phase="decode")):
        assert not [f for f in doctor.diagnose(quiet)
                    if f.rule == "local_sort_lax"]
