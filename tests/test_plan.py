"""Plan provenance (ISSUE 12): minting coverage across dispatch paths,
predicted-vs-actual stamping under forced faults, regret math, the
EXPLAIN renderers, and the input-distribution profiler invariants.

Uses the session-wide virtual 8-device CPU mesh from conftest.py; the
single-device cells build a 1-device mesh on the same backend.
"""

from __future__ import annotations

import json
import pathlib
import sys

import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from mpitest_tpu import report  # noqa: E402
from mpitest_tpu.models import plan as plan_mod  # noqa: E402
from mpitest_tpu.models.api import ingest_to_mesh, sort  # noqa: E402
from mpitest_tpu.parallel.mesh import make_mesh  # noqa: E402
from mpitest_tpu.utils import knobs  # noqa: E402
from mpitest_tpu.utils.trace import Tracer  # noqa: E402


def run_sort(x, algo="radix", mesh=None, **env):
    """Sort under scoped knobs; returns (output, tracer) with the
    finished plan on tracer.plan."""
    tracer = Tracer()
    with knobs.scoped_env(**env):
        out = sort(x, algorithm=algo, mesh=mesh, tracer=tracer)
    return out, tracer


def the_plan(tracer) -> plan_mod.SortPlan:
    p = tracer.plan
    assert isinstance(p, plan_mod.SortPlan), "no plan minted"
    assert p.finalized
    return p


# ------------------------------------------------- minting: every path

def test_plan_minted_local_host(rng):
    mesh = make_mesh(1)
    x = rng.integers(-2**31, 2**31 - 1, size=4096, dtype=np.int32)
    out, tr = run_sort(x, mesh=mesh)
    assert np.array_equal(out, np.sort(x))
    p = the_plan(tr)
    assert p.ranks == 1
    assert p.decisions["ladder"].chosen == "local"
    assert p.decisions["engine"].chosen  # defaulted from counters
    assert p.decisions["algo"].requested == "radix"
    assert "sortedness" in p.profile


def test_plan_minted_local_device(rng):
    import jax

    mesh = make_mesh(1)
    x = jax.device_put(
        rng.integers(-2**31, 2**31 - 1, size=4096, dtype=np.int32).astype(
            np.int32),
        mesh.devices.flat[0])
    out, tr = run_sort(x, mesh=mesh)
    p = the_plan(tr)
    assert p.decisions["ladder"].chosen == "local"
    # device input: no host sample — the profile may be empty, but the
    # plan itself must still exist with the engine decision
    assert p.decisions["engine"].chosen


def test_plan_minted_local_pair_engine(rng, monkeypatch):
    """The 64-bit adaptive pair path (forced bitonic on CPU runs the
    Pallas interpreter).  Thresholds shrunk like test_pair_engine's
    kernel cells — a full-size interpret-mode network costs ~1 min of
    compile, which the timeout-bound tier-1 run cannot afford."""
    from mpitest_tpu.ops import bitonic

    monkeypatch.setattr(bitonic, "MIN_SORT_LOG2", 8)
    monkeypatch.setattr(bitonic, "PAIR_BLOCK_LOG2", 9)
    mesh = make_mesh(1)
    x = rng.integers(-2**62, 2**62 - 1, size=600, dtype=np.int64)
    out, tr = run_sort(x, mesh=mesh, SORT_LOCAL_ENGINE="bitonic")
    assert np.array_equal(out, np.sort(x))
    p = the_plan(tr)
    assert p.decisions["ladder"].chosen == "local"
    assert p.decisions["engine"].chosen


def test_plan_minted_staged_ingest(rng):
    mesh = make_mesh(1)
    x = rng.integers(-2**31, 2**31 - 1, size=4096, dtype=np.int32)
    staged = ingest_to_mesh(x, mesh=mesh)
    out, tr = run_sort(staged, mesh=mesh)
    assert np.array_equal(out, np.sort(x))
    p = the_plan(tr)
    assert p.decisions["ladder"].chosen == "local"


@pytest.fixture(scope="module")
def spmd_runs(mesh8):
    """ONE radix + ONE sample distributed run, shared by the minting,
    explain and schema assertions below (compiles are the cost here,
    not the assertions — tier-1 is timeout-bound)."""
    runs = {}
    rng = np.random.default_rng(1234)
    for algo in ("radix", "sample"):
        x = rng.integers(-2**31, 2**31 - 1, size=1 << 14, dtype=np.int32)
        out, tr = run_sort(x, algo=algo, mesh=mesh8)
        assert np.array_equal(out, np.sort(x))
        runs[algo] = tr
    return runs


@pytest.mark.parametrize("algo", ["radix", "sample"])
def test_plan_minted_spmd(algo, spmd_runs):
    tr = spmd_runs[algo]
    p = the_plan(tr)
    assert p.ranks == 8
    d = p.decisions
    assert d["algo"].chosen in ("radix", "sample")
    assert d["cap"].trigger in ("exact", "estimate")
    assert d["cap"].predicted["cap"] == d["cap"].chosen
    assert d["cap"].actual["need"] is not None
    assert d["cap"].actual["peer_recv_bytes"] > 0
    assert "restage" in d and "engine" in d and "ladder" in d
    # probe-riding profile fields landed
    assert "skew_factor" in p.profile and "bin_entropy" in p.profile
    # the sort.plan span was emitted and is registered
    names = [s.name for s in tr.spans.spans]
    assert "sort.plan" in names
    from mpitest_tpu.utils import span_schema

    assert span_schema.is_registered("sort.plan")


def test_plan_off_knob(mesh8, rng):
    x = rng.integers(-2**31, 2**31 - 1, size=4096, dtype=np.int32)
    out, tr = run_sort(x, mesh=mesh8, SORT_PLAN="off")
    assert np.array_equal(out, np.sort(x))
    assert tr.plan is None
    assert "sort.plan" not in [s.name for s in tr.spans.spans]
    # fail-fast validation, like every knob
    with knobs.scoped_env(SORT_PLAN="maybe"):
        with pytest.raises(ValueError, match="SORT_PLAN"):
            knobs.get("SORT_PLAN")


# ---------------------------------------- predicted-vs-actual stamping

def test_plan_overflow_regrows_stamped(mesh8, rng):
    """cap_squeeze collapses the initial cap to the alignment floor —
    the regrow loop must run and the supervisor must stamp the regrows
    into the cap decision (regret >= 1 per discarded dispatch)."""
    x = rng.integers(-2**31, 2**31 - 1, size=1 << 14, dtype=np.int32)
    out, tr = run_sort(x, algo="radix", mesh=mesh8,
                       SORT_FAULTS="cap_squeeze", SORT_NEGOTIATE="off")
    assert np.array_equal(out, np.sort(x))
    p = the_plan(tr)
    cap = p.decisions["cap"]
    assert cap.actual.get("regrows", 0) >= 1
    assert cap.regret >= 1.0
    assert p.decisions["cap"].trigger == "off"


def test_plan_reroute_stamped(mesh8):
    """Constant keys degenerate the sample splitters: the up-front
    sniff reroutes to radix, recorded with its trigger and NO
    late-reroute regret."""
    x = np.zeros(1 << 14, dtype=np.int32)
    out, tr = run_sort(x, algo="sample", mesh=mesh8)
    assert np.array_equal(out, x)
    p = the_plan(tr)
    algo = p.decisions["algo"]
    assert algo.requested == "sample"
    assert algo.chosen == "radix"
    assert algo.trigger in ("skew_sniff", "probe_estimate")
    assert algo.regret == 0.0
    # the plan's HEADLINE algo follows the reroute: digest, span head
    # and the by-algo census must report what actually ran
    assert p.algo == "radix"
    assert p.digest()["algo"] == "radix"


def test_plan_restage_stamped(mesh8):
    """Sorted input on a mesh is arrangement-skewed: with a low restage
    ratio the probe triggers the re-stage, and the plan carries the
    predicted vs post-restage peer ratio."""
    x = np.arange(1 << 15, dtype=np.int32)
    out, tr = run_sort(x, algo="sample", mesh=mesh8,
                       SORT_RESTAGE_RATIO="1.5")
    assert np.array_equal(out, x)
    p = the_plan(tr)
    rs = p.decisions["restage"]
    assert rs.chosen is True
    assert rs.trigger in ("probe", "overflow")
    if rs.trigger == "probe":
        assert rs.actual["peer_ratio"] < rs.predicted["peer_ratio"]
        assert rs.regret == 0.0


def test_plan_ladder_rungs_stamped(mesh8, rng):
    """Persistent dispatch faults walk the ladder to the host rung; the
    descents are the ladder decision's regret."""
    x = rng.integers(-2**31, 2**31 - 1, size=1 << 14, dtype=np.int32)
    out, tr = run_sort(x, algo="radix", mesh=mesh8,
                       SORT_FAULTS="dispatch_error:inf",
                       SORT_MAX_RETRIES="0")
    assert np.array_equal(out, np.sort(x))
    p = the_plan(tr)
    ladder = p.decisions["ladder"]
    assert ladder.chosen == "host"
    assert ladder.actual["rungs_descended"] == 2
    assert ladder.regret >= 2.0


def test_plan_negotiate_off_raises_cap_regret(rng):
    """The acceptance comparison: same skewed input, negotiation on vs
    off — off must export strictly more cap regret (the imbalance the
    probe would have seen and the re-stage fixed)."""
    mesh = make_mesh(2)
    x = np.arange(1 << 15, dtype=np.int32)   # arrangement-skewed
    with knobs.scoped_env(SORT_RESTAGE_RATIO="1.5"):
        _, tr_on = run_sort(x, algo="sample", mesh=mesh)
        _, tr_off = run_sort(x, algo="sample", mesh=mesh,
                             SORT_NEGOTIATE="off")
    on = the_plan(tr_on).decisions["cap"].regret
    off = the_plan(tr_off).decisions["cap"].regret
    assert off > on
    assert tr_off.counters["plan_cap_regret"] == off


# ----------------------------------------------------------- regret math

def test_regret_relative():
    assert plan_mod.relative_regret(100, 100) == 0.0
    assert plan_mod.relative_regret(150, 100) == 0.5
    assert plan_mod.relative_regret(0.5, 0.25) == 0.25  # floor at 1


def test_regret_cap_rules():
    p = plan_mod.SortPlan(algo="radix")
    p.decide("cap", chosen=128, trigger="exact", cap=128, need=128,
             fair=64)
    p.actual("cap", need=128)
    assert p.finalize() == 0.0
    # regrows dominate
    p.bump("cap", "regrows")
    p.bump("cap", "regrows")
    p.finalize()
    assert p.decisions["cap"].regret == 2.0
    # negotiation off: the need-above-fair imbalance is charged too
    q = plan_mod.SortPlan(algo="sample")
    q.decide("cap", chosen=200, trigger="off", cap=200, fair=100)
    q.actual("cap", need=200)
    q.finalize()
    assert q.decisions["cap"].regret == pytest.approx(1.0)


def test_regret_restage_and_ladder_rules():
    p = plan_mod.SortPlan()
    p.decide("restage", chosen=True, trigger="probe", peer_ratio=4.0)
    p.actual("restage", peer_ratio=4.5)   # did not improve: wasted pass
    p.decide("ladder", chosen="host")
    p.bump("ladder", "rungs_descended")
    p.bump("ladder", "dispatch_retries", 2)
    p.finalize()
    assert p.decisions["restage"].regret == 1.0
    assert p.decisions["ladder"].regret == 3.0


def test_regret_batch_rule():
    p = plan_mod.SortPlan(algo="packed")
    p.decide("batch", chosen=1024, trigger="window", members=3,
             bucket=1024, waste=0.25)
    p.actual("batch", waste=0.25, keys=768)
    p.finalize()
    assert p.decisions["batch"].regret == pytest.approx(0.25)


def test_digest_shape():
    p = plan_mod.SortPlan(algo="radix", n=100, dtype="int32", ranks=4)
    p.decide("cap", chosen=256, trigger="exact", cap=256, need=250)
    p.actual("cap", need=250)
    p.decide("restage", chosen=False)
    d = p.digest()
    assert d["algo"] == "radix"
    assert d["negotiated_cap"] == 256
    assert d["restaged"] is False
    assert d["regret"] >= 0.0
    json.dumps(d)  # wire-safe


# ------------------------------------------------------------- explain

def test_explain_render_units(spmd_runs):
    tr = spmd_runs["radix"]
    rows = [dict(s.to_dict(), kind="span") for s in tr.spans.spans]
    view = report.explain_view(rows)
    assert view is not None
    assert "plan algo=radix" in view
    for needle in ("cap", "predicted:", "actual:", "regret=",
                   "profile:"):
        assert needle in view, view
    # per-trace filter: nothing carries this id
    assert report.explain_view(rows, "no-such-id") is None


def test_explain_aggregate_table(spmd_runs):
    rows = []
    for tr in spmd_runs.values():
        rows += [dict(s.to_dict(), kind="span") for s in tr.spans.spans]
    view = report.explain_view(rows)
    assert "aggregate regret over 2 plan(s)" in view
    assert "decision" in view


def test_explain_cli_modes(tmp_path, spmd_runs):
    trace = tmp_path / "t.jsonl"
    spmd_runs["radix"].spans.dump(str(trace))
    # file via the --explain value, and via positional args
    assert report.main(["--explain", str(trace)]) == 0
    assert report.main(["--explain", str(trace), "--trace-id", "zz"]) == 1
    # the stream must also pass the registered-schema gate
    assert report.main(["--check", "--require-registered-spans",
                        str(trace)]) == 0


def test_baseline_flags_decision_drift():
    """report.py --baseline compares the pinned plan digest too: same
    throughput from flipped decisions is a DRIFT finding."""
    current = {"metrics": {"radix_sort_mkeys_per_s_2e20_int32_8dev": {
        "value": 100.0, "restaged": 0, "negotiated_cap": 4096,
        "plan_regret": 0.1}}}
    baseline = [{"kind": "bench",
                 "metric": "radix_sort_mkeys_per_s_2e20_int32_8dev",
                 "value": 100.0, "restaged": 1, "negotiated_cap": 1024,
                 "plan_regret": 0.1}]
    findings = report.flag_regressions(current, baseline, 0.9, "h")
    drift = {f["metric"]: f for f in findings
             if f["status"] == "DRIFT"}
    assert any(m.endswith(".restaged") for m in drift)
    assert any(m.endswith(".negotiated_cap") for m in drift)
    # identical digests: no drift
    same = [dict(baseline[0], restaged=0, negotiated_cap=4096)]
    findings2 = report.flag_regressions(current, same, 0.9, "h")
    assert not [f for f in findings2 if f["status"] == "DRIFT"]
    # a CLEAN pin (regret 0.0) must still gate later regret — the
    # absolute floor, not a pin>0 ratio band, drives the check
    cur3 = {"metrics": {"m": {"value": 100.0, "plan_regret": 3.0}}}
    base3 = [{"kind": "bench", "metric": "m", "value": 100.0,
              "plan_regret": 0.0}]
    findings3 = report.flag_regressions(cur3, base3, 0.9, "h")
    assert any(f["status"] == "DRIFT" and f["metric"] == "m.plan_regret"
               for f in findings3)
    # ...while sub-floor jitter from a clean pin never flags
    cur4 = {"metrics": {"m": {"value": 100.0, "plan_regret": 0.01}}}
    findings4 = report.flag_regressions(cur4, base3, 0.9, "h")
    assert not [f for f in findings4 if f["status"] == "DRIFT"]


# ------------------------------------------------- profiler invariants

def test_profiler_sorted_input():
    prof = plan_mod.profile_host_array(np.arange(10_000, dtype=np.int32))
    assert prof["sortedness"] == 1.0
    assert prof["dup_ratio"] == 0.0
    assert prof["run_len"] >= 1024 / 2


def test_profiler_constant_input():
    prof = plan_mod.profile_host_array(np.zeros(10_000, dtype=np.int32))
    assert prof["dup_ratio"] == 1.0
    assert prof["sortedness"] == 1.0


def test_profiler_reverse_and_random():
    rev = plan_mod.profile_host_array(
        np.arange(10_000, 0, -1).astype(np.int32))
    assert rev["sortedness"] <= 0.01
    rnd = plan_mod.profile_host_array(
        np.random.default_rng(0).integers(0, 2**31, 10_000).astype(
            np.int32))
    assert 0.3 < rnd["sortedness"] < 0.7
    assert rnd["dup_ratio"] < 0.05


def test_profiler_counts():
    cnts = np.full((4, 4), 100)
    prof = plan_mod.profile_from_counts(cnts, fair=100)
    assert prof["skew_factor"] == 1.0
    assert prof["bin_entropy"] == 1.0
    hot = np.zeros((4, 4), dtype=int)
    hot[:, 0] = 400   # everything to peer 0
    prof2 = plan_mod.profile_from_counts(hot, fair=100)
    assert prof2["skew_factor"] == 4.0
    assert prof2["bin_entropy"] == 0.0


def test_profiler_empty():
    assert plan_mod.profile_host_array(np.empty(0, np.int32)) == {}
