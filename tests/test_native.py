"""Native (C, comm.h shim) backend tests: build, run, golden parity.

SURVEY.md §4: golden-output parity between backends on identical input
files, multi-"rank" simulation without a cluster (local backend = P
pthread ranks via COMM_RANKS), skew and non-divisible-N cases the
reference gets wrong.
"""

import json
import shutil
import subprocess
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def binaries():
    if shutil.which("cc") is None and shutil.which("gcc") is None:
        pytest.skip("no C compiler")
    for d in ("mpi_sample_sort", "mpi_radix_sort"):
        r = subprocess.run(
            ["make", "-C", str(REPO / d), "BACKEND=local"],
            capture_output=True, text=True,
        )
        assert r.returncode == 0, r.stderr
    return {
        "sample": str(REPO / "mpi_sample_sort" / "sample_sort"),
        "radix": str(REPO / "mpi_radix_sort" / "radix_sort"),
    }


def run_native(binary, path, ranks=4, debug=0, env=None):
    import os

    full_env = dict(os.environ, COMM_RANKS=str(ranks), **(env or {}))
    return subprocess.run(
        [binary, str(path)] + ([str(debug)] if debug else []),
        capture_output=True, text=True, env=full_env, timeout=120,
    )


def write_keys(tmp_path, keys):
    p = tmp_path / "keys.txt"
    p.write_text("\n".join(str(k) for k in keys) + "\n")
    return p


def dump_lines(stdout):
    """Parse the debug>2 full-array dump (rank 0's `index|value` lines)."""
    return [np.uint32(line.split("|")[1]) for line in stdout.splitlines()
            if "|" in line and not line.startswith("[")]


@pytest.mark.parametrize("algo", ["sample", "radix"])
@pytest.mark.parametrize("n,ranks", [(1000, 4), (1003, 7), (64, 8), (5, 8)])
def test_native_median_contract(algo, n, ranks, binaries, tmp_path, rng):
    keys = rng.integers(-(2**31), 2**31 - 1, size=n, dtype=np.int32)
    p = write_keys(tmp_path, keys)
    r = run_native(binaries[algo], p, ranks=ranks)
    assert r.returncode == 0, r.stderr
    ref = np.sort(keys)
    assert f"The n/2-th sorted element: {ref[max(n // 2 - 1, 0)]}" in r.stdout
    assert "Endtime()-Starttime() = " in r.stderr


@pytest.mark.parametrize("algo", ["sample", "radix"])
def test_native_full_output_sorted(algo, binaries, tmp_path, rng):
    """debug>2 dump = the complete sorted array, bit-identical to np.sort."""
    keys = rng.integers(-(2**31), 2**31 - 1, size=777, dtype=np.int32)
    p = write_keys(tmp_path, keys)
    r = run_native(binaries[algo], p, ranks=4, debug=3)
    assert r.returncode == 0, r.stderr
    got = np.array(dump_lines(r.stdout), np.uint32).view(np.int32)
    np.testing.assert_array_equal(got, np.sort(keys))


@pytest.mark.parametrize("algo", ["sample", "radix"])
def test_native_zipf_skew(algo, binaries, tmp_path):
    """Skewed duplicates — the reference's silent bucket overflow config
    (mpi_sample_sort.c:140-144); the shim-based rewrite must be exact."""
    from mpitest_tpu.utils import io

    keys = np.clip(io.generate_zipf(30_000, seed=5), 0, 2**31 - 1).astype(np.int32)
    p = write_keys(tmp_path, keys)
    r = run_native(binaries[algo], p, ranks=8)
    assert r.returncode == 0, r.stderr
    ref = np.sort(keys)
    assert f"The n/2-th sorted element: {ref[15_000 - 1]}" in r.stdout


def test_native_sample_zipf15_radix_fallback(binaries, tmp_path):
    """VERDICT r2 #5: under degenerate splitters (Zipf(1.5): ~38% of the
    mass on one value) the native sample program must reroute to the
    radix core — recv memory stays O(n/P) — matching the TPU path's
    skew-fallback semantics (models/api.py), and still sort exactly."""
    from mpitest_tpu.utils import io

    keys = np.clip(io.generate_zipf(40_000, a=1.5, seed=3), 0, 2**31 - 1).astype(
        np.int32
    )
    p = write_keys(tmp_path, keys)
    r = run_native(binaries["sample"], p, ranks=8, debug=1)
    assert r.returncode == 0, r.stderr
    assert "falling back to radix" in r.stdout
    ref = np.sort(keys)
    assert f"The n/2-th sorted element: {ref[20_000 - 1]}" in r.stdout


def test_native_sample_uniform_no_fallback(binaries, tmp_path, rng):
    """Uniform input stays on the sample path (the fallback is for
    genuinely pathological duplication only)."""
    keys = rng.integers(-(2**31), 2**31 - 1, size=20_000, dtype=np.int32)
    p = write_keys(tmp_path, keys)
    r = run_native(binaries["sample"], p, ranks=8, debug=1)
    assert r.returncode == 0, r.stderr
    assert "falling back to radix" not in r.stdout
    assert "exchange OK" in r.stdout


def parse_pass_dumps(stdout):
    """DUMP: LOOP <k> RADIX <rank> = <value> lines, grouped by (k, rank)."""
    groups = {}
    for line in stdout.splitlines():
        if line.startswith("DUMP: LOOP "):
            p = line.split()
            groups.setdefault((int(p[2]), int(p[4])), []).append(np.uint32(p[6]))
    return groups


def test_native_radix_per_pass_dumps(binaries, tmp_path, rng):
    """VERDICT r2 #6: the reference's last observable behavior — per-pass
    intermediate dumps at debug>2 (DUMP: LOOP %u RADIX %u = %u,
    mpi_radix_sort.c:175-178).  Invariant: pass k's rank-major
    concatenation is the input stably sorted by its low k·8 encoded bits;
    the final pass equals np.sort."""
    keys = rng.integers(-(2**31), 2**31 - 1, size=733, dtype=np.int32)
    p = write_keys(tmp_path, keys)
    r = run_native(binaries["radix"], p, ranks=4, debug=3)
    assert r.returncode == 0, r.stderr
    assert "Scatter OK LOOP" in r.stdout  # per-pass debug>=1 line
    groups = parse_pass_dumps(r.stdout)
    passes = {k for k, _ in groups}
    assert passes == {1, 2, 3, 4}  # full-range int32, 8-bit digits
    enc = keys.view(np.uint32) ^ np.uint32(0x80000000)
    for k in sorted(passes):
        concat = np.concatenate(
            [np.array(groups[(k, rk)], np.uint32) for rk in range(4)]
        )
        nbits = 8 * k
        mask = np.uint32(0xFFFFFFFF) if nbits >= 32 else np.uint32((1 << nbits) - 1)
        want = enc[np.argsort(enc & mask, kind="stable")]
        np.testing.assert_array_equal(concat ^ np.uint32(0x80000000), want)
    final = np.concatenate(
        [np.array(groups[(4, rk)], np.uint32) for rk in range(4)]
    ).view(np.int32)
    np.testing.assert_array_equal(final, np.sort(keys))


@pytest.mark.parametrize("n,ranks", [(1024, 8), (733, 4)])
def test_radix_pass_dump_parity_native_vs_tpu(n, ranks, binaries, tmp_path, rng,
                                              monkeypatch):
    """The TPU driver's per-pass dump (radix_pass_states + sort_cli
    debug>2) must be line-for-line identical to the native core's, same
    input, same digit width, same rank count — including non-divisible N
    (pads dropped, RADIX labels follow the native block contract)."""
    import contextlib
    import importlib.util
    import io as stdio

    spec = importlib.util.spec_from_file_location(
        "sort_cli_dump_parity", str(REPO / "drivers" / "sort_cli.py")
    )
    sort_cli = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sort_cli)

    keys = rng.integers(-(2**31), 2**31 - 1, size=n, dtype=np.int32)
    p = write_keys(tmp_path, keys)
    monkeypatch.setenv("SORT_ALGO", "radix")
    monkeypatch.setenv("SORT_DIGIT_BITS", "8")
    monkeypatch.setenv("SORT_RANKS", str(ranks))
    buf = stdio.StringIO()
    with contextlib.redirect_stdout(buf), contextlib.redirect_stderr(stdio.StringIO()):
        rc = sort_cli.main(["sort_cli.py", str(p), "3"])
    assert rc == 0
    native = run_native(binaries["radix"], p, ranks=ranks, debug=3,
                        env={"RADIX_BITS": "8"})
    assert native.returncode == 0, native.stderr
    tpu_groups = parse_pass_dumps(buf.getvalue())
    native_groups = parse_pass_dumps(native.stdout)
    assert set(tpu_groups) == set(native_groups)
    for k in tpu_groups:
        np.testing.assert_array_equal(
            np.array(tpu_groups[k]), np.array(native_groups[k]), err_msg=str(k)
        )


def test_native_radix_bits_knob(binaries, tmp_path, rng):
    keys = rng.integers(-(2**20), 2**20, size=2000, dtype=np.int32)
    p = write_keys(tmp_path, keys)
    for bits in (4, 11, 16):
        r = run_native(binaries["radix"], p, ranks=4, env={"RADIX_BITS": str(bits)})
        assert r.returncode == 0, r.stderr
        assert f"The n/2-th sorted element: {np.sort(keys)[999]}" in r.stdout


@pytest.mark.parametrize("algo", ["sample", "radix"])
def test_native_bad_file_contract(algo, binaries):
    r = run_native(binaries[algo], "/nonexistent/x.txt")
    assert r.returncode != 0
    assert "is not a valid file for read." in r.stderr


@pytest.mark.parametrize("algo", ["sample", "radix"])
def test_native_usage_contract(algo, binaries):
    r = subprocess.run([binaries[algo]], capture_output=True, text=True)
    assert r.returncode != 0
    assert "Usage:" in r.stderr


@pytest.mark.parametrize("ranks", [1, 4, 8])
def test_comm_stats_selftest_schema(ranks, binaries, tmp_path):
    """COMM_STATS=<path> makes comm_launch append ONE JSON line with the
    shared per-collective schema (comm/comm_stats.h <-> utils/spans.py):
    calls/bytes/seconds per collective, schema-checked by the report CLI
    — ISSUE 1 acceptance: native runs feed the same aggregator as TPU
    span streams."""
    import os

    from mpitest_tpu import report

    r = subprocess.run(
        ["make", "-C", str(REPO / "bench"), "BACKEND=local", "comm_selftest"],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr
    stats = tmp_path / "comm_stats.jsonl"
    r = subprocess.run(
        [str(REPO / "bench" / "comm_selftest")],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, COMM_RANKS=str(ranks), COMM_STATS=str(stats)),
    )
    assert r.returncode == 0, r.stdout + r.stderr
    lines = stats.read_text().splitlines()
    assert len(lines) == 1  # one record per comm_launch
    obj = json.loads(lines[0])
    assert obj["v"] == "comm_stats.v1"
    assert obj["backend"] == "local" and obj["ranks"] == ranks
    # the selftest exercises every collective once per rank
    for coll in ("bcast", "scatter", "scatterv", "gather", "gatherv",
                 "allgather", "allreduce", "exscan", "alltoall",
                 "alltoallv", "barrier"):
        c = obj["collectives"][coll]
        assert c["calls"] >= ranks
        assert c["seconds"] >= 0.0
        if coll not in ("barrier",):
            assert c["bytes"] > 0
    rows = report.load_rows(str(stats))
    assert report.check_rows(rows) == []
    agg = report.aggregate(rows)
    assert agg["collectives"][f"native/localx{ranks}"]["alltoallv"]["calls"] \
        == ranks


def test_comm_stats_sort_parity_local_vs_minimpi(binaries, minimpi_binaries,
                                                 tmp_path, rng):
    """The SAME sort on the pthreads and multi-process MPI backends must
    produce identical per-collective calls/bytes in COMM_STATS (seconds
    are wall time and may differ) — the cross-backend comparability the
    telemetry layer exists for."""
    import os

    keys = rng.integers(-(2**31), 2**31 - 1, size=10_000, dtype=np.int32)
    p = write_keys(tmp_path, keys)
    s_local, s_mpi = tmp_path / "local.jsonl", tmp_path / "mpi.jsonl"
    r = subprocess.run(
        [binaries["radix"], str(p)], capture_output=True, text=True,
        timeout=120,
        env=dict(os.environ, COMM_RANKS="4", COMM_STATS=str(s_local)),
    )
    assert r.returncode == 0, r.stderr
    r = subprocess.run(
        [minimpi_binaries["radix"], str(p)], capture_output=True, text=True,
        timeout=120,
        env=dict(os.environ, MINIMPI_NP="4", COMM_STATS=str(s_mpi)),
    )
    assert r.returncode == 0, r.stderr
    o_local = json.loads(s_local.read_text())
    o_mpi = json.loads(s_mpi.read_text())
    assert o_local["backend"] == "local" and o_mpi["backend"] == "mpi"
    assert set(o_local["collectives"]) == set(o_mpi["collectives"])
    for name, c in o_local["collectives"].items():
        m = o_mpi["collectives"][name]
        assert (c["calls"], c["bytes"]) == (m["calls"], m["bytes"]), name


@pytest.mark.parametrize("ranks", [1, 4, 8])
def test_comm_shim_selftest(ranks, binaries):
    """Each comm.h primitive (incl. the census-completing allreduce and
    exscan) checked in isolation against closed-form expectations."""
    import os

    r = subprocess.run(
        ["make", "-C", str(REPO / "bench"), "BACKEND=local", "comm_selftest"],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr
    r = subprocess.run(
        [str(REPO / "bench" / "comm_selftest")],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, COMM_RANKS=str(ranks)),
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert f"comm_selftest OK ({ranks} ranks)" in r.stdout


def test_mpi_backend_compile_smoke(binaries):
    """comm_mpi.c typechecks against the vendored prototypes-only stub
    <mpi.h> — signature-rot guard for images without an MPI install
    (falls through to the same check under a real mpicc when present)."""
    r = subprocess.run(
        ["make", "-C", str(REPO / "bench"), "mpi-syntax-check"],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr


def test_comm_bench_microbenchmark(binaries, tmp_path):
    """The alltoallv half of BASELINE.md row 7 emits one valid JSON line."""
    import json
    import os

    r = subprocess.run(
        ["make", "-C", str(REPO / "bench"), "BACKEND=local"],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr
    r = subprocess.run(
        [str(REPO / "bench" / "comm_bench"), "65536", "3"],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, COMM_RANKS="4"),
    )
    assert r.returncode == 0, r.stderr
    obj = json.loads(r.stdout.strip())
    assert obj["metric"] == "alltoallv_gb_per_s"
    assert obj["ranks"] == 4 and obj["value"] > 0


def test_native_vs_tpu_golden_parity(binaries, tmp_path, rng):
    """The north-star contract: native and TPU backends, same input file,
    bit-identical sorted output and identical median line."""
    from mpitest_tpu.models.api import sort as tpu_sort
    from mpitest_tpu.parallel.mesh import make_mesh

    keys = rng.integers(-(2**31), 2**31 - 1, size=4096, dtype=np.int32)
    p = write_keys(tmp_path, keys)
    mesh = make_mesh(8)
    tpu_out = tpu_sort(keys, algorithm="radix", mesh=mesh)

    for algo in ("sample", "radix"):
        r = run_native(binaries[algo], p, ranks=8, debug=3)
        native_out = np.array(dump_lines(r.stdout), np.uint32).view(np.int32)
        assert native_out.tobytes() == tpu_out.tobytes()


def require_sanitizer(flags, tmp_path):
    """Skip unless a working C compiler with the given -fsanitize=FLAGS
    runtime exists; returns nothing (the make SANITIZE= build finds the
    compiler itself).  Probes with the discovered compiler and the EXACT
    flag set the build will use — a cc-less gcc image or a toolchain
    missing one runtime must skip, not error."""
    compiler = shutil.which("cc") or shutil.which("gcc")
    if compiler is None:
        pytest.skip("no C compiler")
    probe = subprocess.run(
        [compiler, f"-fsanitize={flags}", "-x", "c", "-", "-o",
         str(tmp_path / "san_probe")],
        input="int main(void){return 0;}", capture_output=True, text=True,
    )
    if probe.returncode != 0:
        pytest.skip(f"toolchain lacks -fsanitize={flags} runtime")


def scratch_tree(tmp_path):
    """Copy the native build tree (sources + Makefiles, relative TOP=..
    layout preserved) into tmp_path so BACKEND/SANITIZE switches never
    mutate the repo's own binaries (ADVICE r2: the in-repo rebuild raced
    with the `binaries` fixture under parallel test execution)."""
    root = tmp_path / "tree"
    # Sources and Makefiles only: the repo's own build outputs may be
    # mid-rewrite by a concurrently running make (binaries fixture).
    skip = shutil.ignore_patterns(".backend-*", "sample_sort", "radix_sort",
                                  "*_mpimock", "comm_bench", "comm_selftest")
    for d in ("comm", "native", "mpi_sample_sort", "mpi_radix_sort"):
        shutil.copytree(REPO / d, root / d, ignore=skip)
    return root


def test_thread_sanitizer_race_check(tmp_path, rng):
    """The pthreads comm backend must be race-clean under TSan — the
    executable race check SURVEY.md §5 prescribes (`make SANITIZE=thread`;
    the reference's hand-rolled collectives carry real races: unwaited
    Isends reusing one request, mpi_sample_sort.c:37,63)."""
    require_sanitizer("thread", tmp_path)
    keys = rng.integers(-(2**31), 2**31 - 1, size=20_000, dtype=np.int32)
    path = write_keys(tmp_path, keys)
    tree = scratch_tree(tmp_path)
    for d, binary in (("mpi_sample_sort", "sample_sort"),
                      ("mpi_radix_sort", "radix_sort")):
        r = subprocess.run(
            ["make", "-C", str(tree / d), "BACKEND=local", "SANITIZE=thread"],
            capture_output=True, text=True,
        )
        assert r.returncode == 0, r.stderr
        run = run_native(str(tree / d / binary), path, ranks=8,
                         env={"TSAN_OPTIONS": "exitcode=66 halt_on_error=1"})
        assert run.returncode == 0, (run.returncode, run.stderr[-2000:])
        assert "WARNING: ThreadSanitizer" not in run.stderr


def test_backend_tpu_wrapper_generation(tmp_path):
    """`make BACKEND=tpu` must produce an executable wrapper over the
    JAX CLI with the same argv contract, and switching BACKEND back must
    rebuild the native binary (the round-1 stale-binary finding)."""
    if shutil.which("make") is None:
        pytest.skip("no make")
    d = scratch_tree(tmp_path) / "mpi_sample_sort"
    r = subprocess.run(["make", "-C", str(d), "BACKEND=tpu"],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    wrapper = d / "sample_sort"
    content = wrapper.read_text()
    assert "sort_cli.py" in content and "SORT_ALGO=sample" in content
    assert wrapper.stat().st_mode & 0o111, "wrapper must be executable"
    # switching back rebuilds a real ELF binary, not the stale wrapper
    r = subprocess.run(["make", "-C", str(d), "BACKEND=local"],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    head = (d / "sample_sort").read_bytes()[:4]
    assert head == b"\x7fELF", "BACKEND=local must rebuild the native binary"


@pytest.fixture(scope="module")
def minimpi_binaries():
    """comm_mpi.c linked against the fork-based multi-process MPI runtime
    (comm/mpi_stub/minimpi.c) — real concurrent ranks, no MPI install."""
    if shutil.which("cc") is None and shutil.which("gcc") is None:
        pytest.skip("no C compiler")
    r = subprocess.run(["make", "-C", str(REPO / "bench"), "mpi-mini"],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    return {
        "sample": str(REPO / "bench" / "sample_sort_minimpi"),
        "radix": str(REPO / "bench" / "radix_sort_minimpi"),
        "selftest": str(REPO / "bench" / "comm_selftest_minimpi"),
        "earlyexit": str(REPO / "bench" / "minimpi_earlyexit"),
    }


def run_minimpi(binary, args, np_ranks, timeout=120, env_extra=None):
    import os

    env = dict(os.environ, MINIMPI_NP=str(np_ranks), **(env_extra or {}))
    return subprocess.run(
        [binary] + [str(a) for a in args], capture_output=True, text=True,
        timeout=timeout, env=env,
    )


@pytest.mark.parametrize("ranks", [1, 2, 4, 8])
def test_minimpi_comm_selftest(ranks, minimpi_binaries):
    """Every comm.h primitive through comm_mpi.c at REAL multi-process
    rank counts — the regime (truncation, Exscan-on-rank-0, per-peer
    count/displacement plumbing) the single-rank mock cannot reach."""
    r = run_minimpi(minimpi_binaries["selftest"], [], ranks)
    assert r.returncode == 0, r.stdout + r.stderr
    assert f"comm_selftest OK ({ranks} ranks)" in r.stdout


@pytest.mark.parametrize("ranks", [4, 8])
def test_minimpi_selftest_tiny_staging(ranks, minimpi_binaries):
    """The whole collective surface with a 1 KiB staging area: every
    ragged collective (scatterv/gatherv/alltoallv) is forced through
    MANY windows and every equal-size one through many chunks (VERDICT
    r3 #5 — exchanges larger than the staging area must work, not
    abort).  The closed-form selftest checks make a torn window visible
    immediately."""
    r = run_minimpi(minimpi_binaries["selftest"], [], ranks,
                    env_extra={"MINIMPI_SHM_BYTES": "1024"})
    assert r.returncode == 0, r.stdout + r.stderr
    assert f"comm_selftest OK ({ranks} ranks)" in r.stdout


def test_minimpi_sort_exceeds_staging(minimpi_binaries, tmp_path, rng):
    """End-to-end BACKEND=mpi sort whose alltoallv/gatherv traffic is
    ~20x the staging area (80 KB of keys through a 4 KiB window): the
    windowed ragged collectives must deliver the exact sorted output,
    not truncate at the old one-shot staging limit."""
    n = 20_001
    keys = rng.integers(-(2**31), 2**31 - 1, size=n, dtype=np.int32)
    path = write_keys(tmp_path, keys)
    r = run_minimpi(minimpi_binaries["radix"], [path, 3], 4,
                    env_extra={"MINIMPI_SHM_BYTES": "4096"})
    assert r.returncode == 0, r.stderr[-1000:]
    got = np.array(dump_lines(r.stdout), np.uint32).view(np.int32)
    np.testing.assert_array_equal(got, np.sort(keys))
    median = f"The n/2-th sorted element: {np.sort(keys)[n // 2 - 1]}"
    assert median in r.stdout


def test_minimpi_early_exit_kills_job(minimpi_binaries):
    """A rank that exits 0 BEFORE MPI_Finalize must bring the job down
    with a nonzero status (ADVICE r3): before the finalized-rank
    tracking, the supervisor saw a clean exit and the remaining ranks
    hung in the process-shared barrier forever."""
    r = run_minimpi(minimpi_binaries["earlyexit"], [], 4, timeout=30)
    assert r.returncode != 0
    assert "exited before MPI_Finalize" in r.stderr


@pytest.mark.parametrize("algo", ["sample", "radix"])
@pytest.mark.parametrize("n,ranks", [(5000, 4), (4099, 7)])
def test_mpi_backend_executes_multirank(algo, n, ranks, minimpi_binaries,
                                        binaries, tmp_path, rng):
    """comm_mpi.c EXECUTED at P>1 (VERDICT r2 #1): both sort programs
    under the multi-process runtime must match the pthreads backend at
    the same rank count — full sorted dump and median line.  (Full
    stdout byte-equality is a P=1-only contract: with real processes
    the per-rank debug lines interleave nondeterministically.)"""
    keys = rng.integers(-(2**31), 2**31 - 1, size=n, dtype=np.int32)
    path = write_keys(tmp_path, keys)
    local = run_native(binaries[algo], path, ranks=ranks, debug=3)
    assert local.returncode == 0, local.stderr[-1000:]
    via_mpi = run_minimpi(minimpi_binaries[algo], [path, 3], ranks)
    assert via_mpi.returncode == 0, via_mpi.stderr[-1000:]
    got = np.array(dump_lines(via_mpi.stdout), np.uint32).view(np.int32)
    want = np.array(dump_lines(local.stdout), np.uint32).view(np.int32)
    np.testing.assert_array_equal(got, want)
    np.testing.assert_array_equal(got, np.sort(keys))
    median = f"The n/2-th sorted element: {np.sort(keys)[n // 2 - 1]}"
    assert median in via_mpi.stdout and median in local.stdout
    assert "Endtime()-Starttime() = " in via_mpi.stderr


@pytest.fixture(scope="module")
def comm_fuzz_binary(minimpi_binaries):
    """Local-backend fuzzer build (the minimpi twin comes from mpi-mini)."""
    r = subprocess.run(["make", "-C", str(REPO / "bench"), "comm_fuzz"],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    return str(REPO / "bench" / "comm_fuzz")


@pytest.mark.parametrize("seed", [1, 42, 1234])
@pytest.mark.parametrize("ranks", [2, 5, 8])
def test_comm_fuzz_differential(seed, ranks, minimpi_binaries, comm_fuzz_binary):
    """Randomized differential test of the full comm.h surface: a seeded
    sequence of collectives (ragged counts, zero segments, random roots,
    mixed reductions) must fold to the IDENTICAL checksum on the
    pthreads backend and the multi-process MPI backend — cross-backend
    protocol bugs the per-primitive selftest can miss show up here."""
    import os

    local = subprocess.run(
        [comm_fuzz_binary, str(seed), "200"],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, COMM_RANKS=str(ranks)),
    )
    assert local.returncode == 0, local.stderr
    via_mpi = run_minimpi(
        str(REPO / "bench" / "comm_fuzz_minimpi"), [seed, 200], ranks)
    assert via_mpi.returncode == 0, via_mpi.stderr
    assert local.stdout.startswith("comm_fuzz OK")
    assert local.stdout == via_mpi.stdout  # includes the checksum


def test_comm_fuzz_tiny_staging(minimpi_binaries, comm_fuzz_binary):
    """Differential fuzz with a 2 KiB staging area: every collective in
    the random sequence is forced through many windows/chunks, and the
    folded checksum must still match the pthreads backend bit-exactly —
    the strongest torn-window detector we have."""
    import os

    local = subprocess.run(
        [comm_fuzz_binary, "7", "120"],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, COMM_RANKS="5"),
    )
    assert local.returncode == 0, local.stderr
    via_mpi = run_minimpi(
        str(REPO / "bench" / "comm_fuzz_minimpi"), [7, 120], 5,
        env_extra={"MINIMPI_SHM_BYTES": "2048"})
    assert via_mpi.returncode == 0, via_mpi.stderr
    assert local.stdout == via_mpi.stdout


def test_comm_fuzz_asan_clean(tmp_path):
    """The full comm stack (comm_local pthreads AND comm_mpi over the
    multi-process minimpi runtime) must run the randomized collective
    sequences clean under AddressSanitizer + UBSan — the memory-safety
    side of the SURVEY §5 sanitizer row (TSan covers the thread side)."""
    require_sanitizer("address,undefined", tmp_path)
    import os

    tree = scratch_tree(tmp_path)
    (tree / "bench").mkdir()
    shutil.copy(REPO / "bench" / "Makefile", tree / "bench" / "Makefile")
    r = subprocess.run(
        ["make", "-C", str(tree / "bench"), "SANITIZE=address,undefined",
         "comm_fuzz", "comm_fuzz_minimpi"],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr
    env = dict(os.environ, ASAN_OPTIONS="abort_on_error=1")
    local = subprocess.run(
        [str(tree / "bench" / "comm_fuzz"), "11", "300"],
        capture_output=True, text=True, timeout=300,
        env=dict(env, COMM_RANKS="6"),
    )
    assert local.returncode == 0, local.stderr[-2000:]
    via_mpi = subprocess.run(
        [str(tree / "bench" / "comm_fuzz_minimpi"), "11", "300"],
        capture_output=True, text=True, timeout=300,
        env=dict(env, MINIMPI_NP="6"),
    )
    assert via_mpi.returncode == 0, via_mpi.stderr[-2000:]
    assert local.stdout == via_mpi.stdout and "OK" in local.stdout


def test_backend_mpi_builds_without_mpicc(tmp_path, rng):
    """`make BACKEND=mpi` must work on machines WITHOUT an MPI toolchain:
    the Makefiles fall back to linking comm_mpi.c against the bundled
    minimpi runtime, runnable via the mpirun-style bench/minirun shim."""
    if shutil.which("cc") is None and shutil.which("gcc") is None:
        pytest.skip("no C compiler")
    if shutil.which("mpicc") is not None:
        pytest.skip("real mpicc present; fallback path not reachable")
    tree = scratch_tree(tmp_path)
    keys = rng.integers(-(2**31), 2**31 - 1, size=3000, dtype=np.int32)
    path = write_keys(tmp_path, keys)
    median = f"The n/2-th sorted element: {np.sort(keys)[1500 - 1]}"
    for d, binary in (("mpi_sample_sort", "sample_sort"),
                      ("mpi_radix_sort", "radix_sort")):
        r = subprocess.run(["make", "-C", str(tree / d), "BACKEND=mpi"],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        run = subprocess.run(
            [str(REPO / "bench" / "minirun"), "-np", "4",
             str(tree / d / binary), str(path)],
            capture_output=True, text=True, timeout=120,
        )
        assert run.returncode == 0, run.stderr[-1000:]
        assert median in run.stdout


def test_comm_faults_stall_is_harmless(binaries, tmp_path, rng):
    """COMM_FAULTS=stall:<rank>@<nth>:<ms> — a slow rank costs wall
    time, never correctness: peers wait in the barrier and the output
    stays byte-exact (ISSUE 3: the native mirror of SORT_FAULTS)."""
    keys = rng.integers(-(2**31), 2**31 - 1, size=10_000, dtype=np.int32)
    p = write_keys(tmp_path, keys)
    r = run_native(binaries["radix"], p, ranks=4,
                   env={"COMM_FAULTS": "stall:1@2:50"})
    assert r.returncode == 0, r.stderr
    assert "[FAULT] rank 1 stalling" in r.stderr
    ref = np.sort(keys)
    assert f"The n/2-th sorted element: {ref[4999]}" in r.stdout


def test_comm_faults_kill_local_fails_loudly(binaries, tmp_path, rng):
    """A rank killed mid-protocol on the pthreads backend takes the
    process down with the fault code and a [FAULT] line — never a
    silent hang (the reference strands peers in this situation)."""
    keys = rng.integers(-(2**31), 2**31 - 1, size=5_000, dtype=np.int32)
    p = write_keys(tmp_path, keys)
    r = run_native(binaries["radix"], p, ranks=4,
                   env={"COMM_FAULTS": "kill:1@3"})
    assert r.returncode == 43, (r.returncode, r.stderr)
    assert "[FAULT] rank 1 killed" in r.stderr


def test_comm_faults_kill_minimpi_kills_job(minimpi_binaries, tmp_path, rng):
    """Under the multi-process runtime the killed rank is a real child
    process: the minimpi supervisor must reap it and bring the WHOLE
    job down with the fault code (mpirun contract) — within the
    timeout, i.e. no stranded-peer hang."""
    keys = rng.integers(-(2**31), 2**31 - 1, size=5_000, dtype=np.int32)
    p = write_keys(tmp_path, keys)
    r = run_minimpi(minimpi_binaries["radix"], [p], 4, timeout=60,
                    env_extra={"COMM_FAULTS": "kill:2@4"})
    assert r.returncode == 43, (r.returncode, r.stderr)
    assert "[FAULT] rank 2 killed" in r.stderr


def test_comm_faults_stall_minimpi_correct(minimpi_binaries, tmp_path, rng):
    keys = rng.integers(-(2**31), 2**31 - 1, size=10_000, dtype=np.int32)
    p = write_keys(tmp_path, keys)
    r = run_minimpi(minimpi_binaries["radix"], [p], 4, timeout=120,
                    env_extra={"COMM_FAULTS": "stall:3@1:40"})
    assert r.returncode == 0, r.stderr
    ref = np.sort(keys)
    assert f"The n/2-th sorted element: {ref[4999]}" in r.stdout


@pytest.mark.parametrize("bad", ["garbage", "kill:1", "stall:1@2",
                                 "kill:-1@3", "kill:1@3:50",
                                 "stall:1@2:50x"])
def test_comm_faults_bad_spec_fails_launch(bad, binaries, tmp_path, rng):
    """A typo'd drill spec must fail the launch loudly — a chaos drill
    that silently runs clean reports false health."""
    keys = rng.integers(-100, 100, size=100, dtype=np.int32)
    p = write_keys(tmp_path, keys)
    r = run_native(binaries["radix"], p, ranks=2,
                   env={"COMM_FAULTS": bad})
    assert r.returncode != 0
    assert "COMM_FAULTS" in r.stderr


def test_minimpi_abort_contract(minimpi_binaries):
    """MPI_Abort terminates ALL ranks with the abort code (mpirun
    contract) — no hang, no signal-exit rewrite."""
    r = run_minimpi(minimpi_binaries["sample"], ["/nonexistent/x.txt"], 4,
                    timeout=30)
    assert r.returncode == 1, (r.returncode, r.stderr)
    assert "is not a valid file for read." in r.stderr


def test_mpi_backend_executes_via_mock(tmp_path, rng):
    """comm_mpi.c EXECUTED end-to-end (not just typechecked): linked
    against the single-rank mock MPI runtime (comm/mpi_stub/mpi_mock.c),
    both sort programs must produce byte-identical stdout — including
    the full debug dump — to the pthreads backend at 1 rank."""
    if shutil.which("cc") is None and shutil.which("gcc") is None:
        pytest.skip("no C compiler")
    r = subprocess.run(["make", "-C", str(REPO / "bench"), "mpi-mock"],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    keys = rng.integers(-(2**31), 2**31 - 1, size=5_000, dtype=np.int32)
    path = write_keys(tmp_path, keys)
    for d, binary, mock in (
        ("mpi_sample_sort", "sample_sort", "sample_sort_mpimock"),
        ("mpi_radix_sort", "radix_sort", "radix_sort_mpimock"),
    ):
        r = subprocess.run(["make", "-C", str(REPO / d), "BACKEND=local"],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        local = run_native(str(REPO / d / binary), path, ranks=1, debug=3)
        via_mpi = subprocess.run(
            [str(REPO / "bench" / mock), str(path), "3"],
            capture_output=True, text=True, timeout=120,
        )
        assert via_mpi.returncode == 0, via_mpi.stderr[-1000:]
        assert via_mpi.stdout == local.stdout
