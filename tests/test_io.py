import numpy as np
import pytest

from mpitest_tpu.utils import io


def test_text_roundtrip(tmp_path):
    x = np.array([5, -3, 2147483647, -2147483648, 0], np.int32)
    p = str(tmp_path / "keys.txt")
    io.write_keys_text(p, x)
    np.testing.assert_array_equal(io.read_keys_text(p), x)


def test_reads_exact_count(tmp_path):
    """No feof overcount (reference bug, mpi_sample_sort.c:50)."""
    p = str(tmp_path / "keys.txt")
    with open(p, "w") as f:
        f.write("1 2 3\n")  # trailing newline: reference would count 4
    got = io.read_keys_text(p)
    assert got.shape == (3,)


def test_binary_roundtrip(tmp_path):
    x = np.arange(-50, 50, dtype=np.int32)
    p = str(tmp_path / "keys.bin")
    io.write_keys_binary(p, x)
    np.testing.assert_array_equal(io.read_keys_binary(p), x)


def test_generators():
    u = io.generate_uniform(1000, np.int32, seed=7)
    assert u.dtype == np.int32 and u.shape == (1000,)
    assert io.generate_uniform(1000, np.int32, seed=7).tolist() == u.tolist()
    z = io.generate_zipf(1000, dtype=np.int64, seed=7)
    assert z.dtype == np.int64 and (z >= 1).all()
    # zipf must actually be skewed: top value should dominate
    vals, counts = np.unique(z, return_counts=True)
    assert counts.max() > 50


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_generate_float(dtype):
    """Float generation lives in io.generate (one generator for bench,
    stress, and tests — VERDICT r2 #7), finite and exponent-spanning."""
    x = io.generate("uniform", 5000, dtype, seed=3)
    assert x.dtype == dtype and x.shape == (5000,)
    assert np.isfinite(x).all()
    assert (x < 0).any() and (x > 0).any()
    mags = np.log10(np.abs(x[x != 0]))
    assert mags.max() - mags.min() > 20  # spans many decades
    assert io.generate("uniform", 5000, dtype, seed=3).tolist() == x.tolist()
    z = io.generate("zipf", 1000, dtype, seed=3)
    assert z.dtype == dtype and (z >= 1).all()


def test_uint64_text_exact(tmp_path):
    """Keys above 2^63-1 must not saturate through an int64 intermediate."""
    p = str(tmp_path / "u64.txt")
    x = np.array([2**64 - 1, 0, 2**63], np.uint64)
    io.write_keys_text(p, x)
    np.testing.assert_array_equal(io.read_keys_text(p, np.uint64), x)


def test_missing_file():
    with pytest.raises(FileNotFoundError):
        io.read_keys_text("/nonexistent/file.txt")


ALL_DTYPES = [np.int8, np.uint8, np.int16, np.uint16, np.int32, np.uint32,
              np.int64, np.uint64, np.float32, np.float64]


@pytest.mark.parametrize("dtype", ALL_DTYPES)
def test_binary_roundtrip_all_dtypes(dtype, tmp_path):
    """SORTBIN1 round-trips bit-exactly for EVERY supported key dtype
    (ISSUE 2 satellite) — including NaN/±0.0 float payloads, which a
    text round-trip can't always carry."""
    x = io.generate("uniform", 257, dtype, seed=5)
    if np.dtype(dtype).kind == "f":
        x[:4] = [np.nan, -0.0, np.inf, -np.inf]
    p = str(tmp_path / "keys.bin")
    io.write_keys_binary(p, x)
    back = io.read_keys_binary(p, dtype)
    assert back.dtype == np.dtype(dtype)
    np.testing.assert_array_equal(back.view(np.uint8), x.view(np.uint8))


def test_read_keys_auto_sniffs_once(tmp_path):
    """read_keys_auto dispatches on the SORTBIN1 magic for both formats,
    and mmap=True returns a zero-copy mmap-backed array for binary."""
    x = np.arange(-500, 500, dtype=np.int32)
    pb = str(tmp_path / "k.bin")
    pt = str(tmp_path / "k.txt")
    io.write_keys_binary(pb, x)
    io.write_keys_text(pt, x)
    np.testing.assert_array_equal(io.read_keys_auto(pb), x)
    np.testing.assert_array_equal(io.read_keys_auto(pt), x)
    mm = io.read_keys_auto(pb, mmap=True)
    assert isinstance(mm, np.memmap)
    np.testing.assert_array_equal(np.asarray(mm), x)
    # dtype mismatch is still a hard error through the auto path
    with pytest.raises(ValueError):
        io.read_keys_auto(pb, np.int64)
    with pytest.raises(FileNotFoundError):
        io.read_keys_auto(str(tmp_path / "absent.bin"))


@pytest.mark.parametrize("dtype", [np.int32, np.uint64, np.float64])
def test_chunked_reader_equivalence_text(dtype, tmp_path, rng):
    """iter_key_chunks over a TEXT file concatenates to exactly the
    monolithic read — with a chunk budget so small that block boundaries
    land mid-token, exercising the carry logic."""
    x = io.generate("uniform", 1000, dtype, seed=11)
    p = str(tmp_path / "keys.txt")
    io.write_keys_text(p, x)
    ref = io.read_keys_text(p, dtype)
    # chunk_elems=3 -> ~36-byte blocks: guaranteed to split tokens
    chunks = list(io.iter_key_chunks(p, dtype, chunk_elems=3))
    assert len(chunks) > 10
    got = np.concatenate(chunks)
    assert got.dtype == np.dtype(dtype)
    np.testing.assert_array_equal(
        got.view(np.uint8), ref.view(np.uint8))


def test_chunked_reader_equivalence_binary(tmp_path, rng):
    """Binary chunks are mmap-backed slices whose concatenation equals
    the monolithic binary read, for divisible and non-divisible chunk
    counts (incl. the 1-chunk case)."""
    x = rng.integers(-(2**31), 2**31 - 1, size=1013, dtype=np.int32)
    p = str(tmp_path / "keys.bin")
    io.write_keys_binary(p, x)
    for ce in (100, 1013, 5000):
        chunks = list(io.iter_key_chunks(p, np.int32, chunk_elems=ce))
        np.testing.assert_array_equal(np.concatenate(chunks), x)
    assert all(isinstance(c.base, np.memmap) or isinstance(c, np.memmap)
               for c in io.iter_key_chunks(p, np.int32, chunk_elems=100))


@pytest.mark.parametrize("dtype", [np.int32, np.uint64, np.float32])
def test_write_keys_text_chunked(dtype, tmp_path):
    """Buffered chunked writes produce the same text (and the same
    bit-exact round-trip) as a whole-array write."""
    x = io.generate("uniform", 777, dtype, seed=2)
    p1, p2 = str(tmp_path / "a.txt"), str(tmp_path / "b.txt")
    io.write_keys_text(p1, x)                    # default chunking
    io.write_keys_text(p2, x, chunk_elems=10)    # forced tiny chunks
    assert open(p1).read() == open(p2).read()
    back = io.read_keys_text(p1, dtype)
    np.testing.assert_array_equal(back.view(np.uint8), x.view(np.uint8))


def test_ingest_knob_validation(monkeypatch):
    """The ingest env knobs fail fast with knob-naming messages."""
    monkeypatch.setenv("SORT_INGEST", "sideways")
    with pytest.raises(ValueError, match="SORT_INGEST="):
        io.ingest_mode()
    monkeypatch.delenv("SORT_INGEST")
    assert io.ingest_mode() == "auto"
    for knob, fn in (("SORT_INGEST_CHUNK", io.ingest_chunk_elems),
                     ("SORT_INGEST_THREADS", io.ingest_threads)):
        for bad in ("0", "-3", "garbage"):
            monkeypatch.setenv(knob, bad)
            with pytest.raises(ValueError, match=knob):
                fn()
        monkeypatch.setenv(knob, "7")
        assert fn() == 7
        monkeypatch.delenv(knob)
