import numpy as np
import pytest

from mpitest_tpu.utils import io


def test_text_roundtrip(tmp_path):
    x = np.array([5, -3, 2147483647, -2147483648, 0], np.int32)
    p = str(tmp_path / "keys.txt")
    io.write_keys_text(p, x)
    np.testing.assert_array_equal(io.read_keys_text(p), x)


def test_reads_exact_count(tmp_path):
    """No feof overcount (reference bug, mpi_sample_sort.c:50)."""
    p = str(tmp_path / "keys.txt")
    with open(p, "w") as f:
        f.write("1 2 3\n")  # trailing newline: reference would count 4
    got = io.read_keys_text(p)
    assert got.shape == (3,)


def test_binary_roundtrip(tmp_path):
    x = np.arange(-50, 50, dtype=np.int32)
    p = str(tmp_path / "keys.bin")
    io.write_keys_binary(p, x)
    np.testing.assert_array_equal(io.read_keys_binary(p), x)


def test_generators():
    u = io.generate_uniform(1000, np.int32, seed=7)
    assert u.dtype == np.int32 and u.shape == (1000,)
    assert io.generate_uniform(1000, np.int32, seed=7).tolist() == u.tolist()
    z = io.generate_zipf(1000, dtype=np.int64, seed=7)
    assert z.dtype == np.int64 and (z >= 1).all()
    # zipf must actually be skewed: top value should dominate
    vals, counts = np.unique(z, return_counts=True)
    assert counts.max() > 50


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_generate_float(dtype):
    """Float generation lives in io.generate (one generator for bench,
    stress, and tests — VERDICT r2 #7), finite and exponent-spanning."""
    x = io.generate("uniform", 5000, dtype, seed=3)
    assert x.dtype == dtype and x.shape == (5000,)
    assert np.isfinite(x).all()
    assert (x < 0).any() and (x > 0).any()
    mags = np.log10(np.abs(x[x != 0]))
    assert mags.max() - mags.min() > 20  # spans many decades
    assert io.generate("uniform", 5000, dtype, seed=3).tolist() == x.tolist()
    z = io.generate("zipf", 1000, dtype, seed=3)
    assert z.dtype == dtype and (z >= 1).all()


def test_uint64_text_exact(tmp_path):
    """Keys above 2^63-1 must not saturate through an int64 intermediate."""
    p = str(tmp_path / "u64.txt")
    x = np.array([2**64 - 1, 0, 2**63], np.uint64)
    io.write_keys_text(p, x)
    np.testing.assert_array_equal(io.read_keys_text(p, np.uint64), x)


def test_missing_file():
    with pytest.raises(FileNotFoundError):
        io.read_keys_text("/nonexistent/file.txt")
