"""Robustness layer tests (ISSUE 3): fault registry, output verifier,
SortSupervisor retry/degradation, and the CLI's typed exit codes.

Every test here follows the one invariant the layer exists for: an
injected fault ends in a fingerprint-verified, bit-exact result or a
typed error — never a silent wrong answer.  The full grid runs in
``make fault-selftest`` (bench/fault_selftest.py); these are the
tier-1-sized probes of each mechanism.

(Named to sort AFTER the core suites: the tier-1 run is timeout-bound,
and the must-stay-green contract of the earlier files wins the race.)
"""

import os

import numpy as np
import pytest

from mpitest_tpu import faults
from mpitest_tpu.models import verify as vfy
from mpitest_tpu.models.api import (SortIntegrityError, SortRetryExhausted,
                                    sort)
from mpitest_tpu.utils.trace import Tracer


@pytest.fixture(autouse=True)
def _no_backoff(monkeypatch):
    monkeypatch.setenv("SORT_RETRY_BACKOFF", "0")


@pytest.fixture
def keys(rng):
    return rng.integers(-(2**31), 2**31 - 1, size=20_000, dtype=np.int32)


def install(spec, seed=7):
    reg = faults.FaultRegistry(spec, seed=seed)
    faults.install(reg)
    return reg


@pytest.fixture(autouse=True)
def _clear_registry():
    yield
    faults.install(None)


# ------------------------------------------------------------- registry

def test_spec_parsing_and_counts():
    reg = faults.FaultRegistry("dispatch_error:2,result_dup", seed=1)
    assert reg.fire("dispatch_error") and reg.fire("dispatch_error")
    assert not reg.fire("dispatch_error")  # budget exhausted
    assert reg.fire("result_dup") and not reg.fire("result_dup")
    assert not reg.fire("cap_squeeze")     # never armed
    assert reg.injected == 3


def test_spec_inf_and_determinism():
    reg = faults.FaultRegistry("dispatch_oom:inf", seed=3)
    assert all(reg.fire("dispatch_oom") for _ in range(50))
    a = faults.FaultRegistry("exchange_corrupt", seed=9)
    b = faults.FaultRegistry("exchange_corrupt", seed=9)
    assert [a.rand_word() for _ in range(4)] == [b.rand_word()
                                                for _ in range(4)]


@pytest.mark.parametrize("bad", ["nosuchsite", "dispatch_error:0",
                                 "dispatch_error:x", "kill:1@2"])
def test_spec_garbage_raises(bad):
    with pytest.raises(ValueError):
        faults.FaultRegistry(bad)


# ------------------------------------------------------------- verifier

def test_fingerprint_catches_each_failure_class(rng):
    w = (rng.integers(0, 2**32, size=1000, dtype=np.uint64)
         .astype(np.uint32),)
    fp = vfy.fingerprint_host(w)
    # truncation: count moves
    assert vfy.fingerprint_host((w[0][:-1],)) != fp
    # duplication: sum moves even when xor collides
    dup = w[0].copy()
    dup[1] = dup[0]
    assert vfy.fingerprint_host((dup,)) != fp
    # corruption: xor moves
    corr = w[0].copy()
    corr[5] ^= np.uint32(0xDEADBEEF)
    assert vfy.fingerprint_host((corr,)) != fp
    # permutation: fingerprint is order-independent (sortedness's job)
    assert vfy.fingerprint_host((w[0][::-1].copy(),)) == fp


def test_streamed_ingest_fingerprint_matches_host_fold(mesh8, rng,
                                                       monkeypatch):
    monkeypatch.setenv("SORT_INGEST", "stream")
    monkeypatch.setenv("SORT_INGEST_CHUNK", "777")
    from mpitest_tpu.models.api import ingest_to_mesh
    from mpitest_tpu.ops.keys import codec_for

    x = rng.integers(-(2**31), 2**31 - 1, size=5000, dtype=np.int32)
    st = ingest_to_mesh(x, mesh=mesh8)
    assert st.fingerprint == vfy.fingerprint_host(
        codec_for(np.dtype(np.int32)).encode(x))


def test_verify_runs_on_every_sort(mesh8, keys):
    tr = Tracer()
    got = sort(keys, algorithm="radix", mesh=mesh8, tracer=tr)
    np.testing.assert_array_equal(got, np.sort(keys))
    assert tr.counters.get("verify_runs", 0) >= 1
    names = [s.name for s in tr.spans.spans]
    assert "verify" in names


def test_verify_disabled_knob(mesh8, keys, monkeypatch):
    monkeypatch.setenv("SORT_VERIFY", "0")
    tr = Tracer()
    got = sort(keys, algorithm="radix", mesh=mesh8, tracer=tr)
    np.testing.assert_array_equal(got, np.sort(keys))
    assert tr.counters.get("verify_runs", 0) == 0
    # the A/B baseline must not silently pay ingest-side fingerprint
    # cost either: staging under SORT_VERIFY=0 folds no fingerprint
    from mpitest_tpu.models.api import ingest_to_mesh

    st = ingest_to_mesh(keys, mesh=mesh8)
    assert st.fingerprint is None
    np.testing.assert_array_equal(sort(st, algorithm="radix", mesh=mesh8),
                                  np.sort(keys))


# ------------------------------------------------- supervisor: transient

@pytest.mark.parametrize("algo", ["radix", "sample"])
def test_transient_dispatch_fault_retried(algo, mesh8, keys):
    reg = install("dispatch_error")
    tr = Tracer()
    got = sort(keys, algorithm=algo, mesh=mesh8, tracer=tr)
    np.testing.assert_array_equal(got, np.sort(keys))
    assert reg.injected == 1
    assert tr.counters.get("sort_retries") == 1
    assert tr.counters.get("faults_injected") == 1
    assert any(s.name == "supervisor_retry" for s in tr.spans.spans)
    assert any(s.name == "fault" for s in tr.spans.spans)


@pytest.mark.parametrize("site,algo", [
    ("exchange_corrupt", "radix"), ("exchange_drop", "sample"),
    ("result_swap", "radix"), ("result_dup", "sample"),
])
def test_corruption_detected_and_recovered(site, algo, mesh8, keys):
    """Corruption between exchange and local sort, or of the final
    result, must be caught by the verifier and retried clean — the
    result_dup case stays SORTED and is caught ONLY by the multiset
    fingerprint."""
    reg = install(site)
    tr = Tracer()
    got = sort(keys, algorithm=algo, mesh=mesh8, tracer=tr)
    np.testing.assert_array_equal(got, np.sort(keys))
    assert reg.injected == 1
    assert tr.counters.get("verify_failures", 0) >= 1


def test_ingest_poison_detected(mesh8, keys, monkeypatch):
    monkeypatch.setenv("SORT_INGEST", "stream")
    monkeypatch.setenv("SORT_INGEST_CHUNK", "4096")
    reg = install("ingest_poison")
    tr = Tracer()
    got = sort(keys, algorithm="radix", mesh=mesh8, tracer=tr)
    np.testing.assert_array_equal(got, np.sort(keys))
    assert reg.injected == 1
    assert tr.counters.get("verify_failures", 0) >= 1


def test_cap_squeeze_exercises_overflow_retry(mesh8, keys):
    reg = install("cap_squeeze")
    tr = Tracer()
    got = sort(keys, algorithm="radix", mesh=mesh8, tracer=tr)
    np.testing.assert_array_equal(got, np.sort(keys))
    assert reg.injected == 1
    assert tr.counters.get("exchange_retries", 0) >= 1


def test_exchange_fault_cannot_poison_jit_cache(mesh8, keys, monkeypatch):
    """Review regression: (a) two env-armed runs in one process must each
    get a FRESH poisoned compile (a reused fault token would hit the jit
    cache, skip the trace, and leave the pending fault to corrupt the
    next clean compile); (b) a clean run of the same shape afterwards
    must stay clean."""
    monkeypatch.setenv("SORT_FAULTS", "exchange_corrupt")
    for _ in range(2):
        tr = Tracer()
        got = sort(keys, algorithm="radix", mesh=mesh8, tracer=tr)
        np.testing.assert_array_equal(got, np.sort(keys))
        assert tr.counters.get("verify_failures", 0) >= 1, \
            "fault was not freshly injected on the second run"
    monkeypatch.delenv("SORT_FAULTS")
    tr = Tracer()
    got = sort(keys, algorithm="radix", mesh=mesh8, tracer=tr)
    np.testing.assert_array_equal(got, np.sort(keys))
    assert tr.counters.get("verify_failures", 0) == 0, \
        "stale pending exchange fault leaked into a clean compile"


def test_armed_exchange_fault_dropped_when_dispatch_dies(mesh8, keys):
    """Review regression: an exchange fault armed for a dispatch that
    dies before tracing (injected dispatch fault) must be DROPPED, not
    left pending to poison a later clean trace."""
    from mpitest_tpu import faults as flt

    install("dispatch_oom:inf,exchange_corrupt")
    tr = Tracer()
    got = sort(keys, algorithm="radix", mesh=mesh8, tracer=tr)
    np.testing.assert_array_equal(got, np.sort(keys))  # host fallback
    assert tr.counters.get("degraded_to") == "host"
    assert not flt._PENDING_EXCHANGE, "stale pending exchange fault"
    faults.install(None)
    # a clean sort at a FRESH shape (forces a new trace) must stay clean
    tr = Tracer()
    fresh = keys[:-7]
    got = sort(fresh, algorithm="radix", mesh=mesh8, tracer=tr)
    np.testing.assert_array_equal(got, np.sort(fresh))
    assert tr.counters.get("verify_failures", 0) == 0


def test_ingest_poison_counted_in_tracer(mesh8, keys, monkeypatch):
    """Review regression: the poison fires inside the streaming pipeline
    BEFORE the dispatch supervisor exists — the fault must still land in
    the tracer's faults_injected counter and the span stream."""
    monkeypatch.setenv("SORT_INGEST", "stream")
    monkeypatch.setenv("SORT_INGEST_CHUNK", "4096")
    install("ingest_poison")
    tr = Tracer()
    got = sort(keys, algorithm="radix", mesh=mesh8, tracer=tr)
    np.testing.assert_array_equal(got, np.sort(keys))
    assert tr.counters.get("faults_injected", 0) >= 1
    assert any(s.name == "fault" for s in tr.spans.spans)


# ------------------------------------------------ supervisor: persistent

def test_device_failure_outside_dispatch_degrades(mesh8, keys, monkeypatch):
    """Review regression: a dead device can surface OUTSIDE the
    supervised sort dispatch (skew sniff, planner reduction, verifier
    program) — the ladder must still degrade instead of leaking an
    untyped JaxRuntimeError past the typed-error contract."""
    import jax

    from mpitest_tpu.models import api

    def boom(*a, **k):
        raise jax.errors.JaxRuntimeError("INTERNAL: injected sniff failure")

    monkeypatch.setattr(api, "_compile_skew_sniff", boom)
    dev = jax.device_put(keys, jax.devices()[0])  # device input → sniff path
    tr = Tracer()
    got = sort(dev, algorithm="sample", mesh=mesh8, tracer=tr)
    np.testing.assert_array_equal(got, np.sort(keys))
    assert tr.counters.get("degraded_to") == "radix", tr.counters


def test_persistent_failure_degrades_to_host(mesh8, keys):
    install("dispatch_oom:inf")
    tr = Tracer()
    got = sort(keys, algorithm="radix", mesh=mesh8, tracer=tr)
    np.testing.assert_array_equal(got, np.sort(keys))
    assert tr.counters.get("degraded_to") == "host"


def test_persistent_failure_fallback_off_typed_error(mesh8, keys,
                                                     monkeypatch):
    monkeypatch.setenv("SORT_FALLBACK", "0")
    install("dispatch_oom:inf")
    with pytest.raises(SortRetryExhausted):
        sort(keys, algorithm="radix", mesh=mesh8)


def test_persistent_corruption_typed_integrity_error(mesh8, keys,
                                                     monkeypatch):
    monkeypatch.setenv("SORT_FALLBACK", "0")
    install("result_dup:inf")
    with pytest.raises(SortIntegrityError):
        sort(keys, algorithm="sample", mesh=mesh8)


def test_host_fallback_result_is_canonical(mesh8, rng):
    """The host rung must produce the same bytes as the device path —
    including float totalOrder (np.sort would misplace NaNs)."""
    x = np.concatenate([
        (rng.standard_normal(997) * 1e3).astype(np.float32),
        np.array([np.nan, -np.nan, 0.0, -0.0], np.float32),
    ])
    clean = sort(x, algorithm="radix", mesh=mesh8)
    install("dispatch_oom:inf")
    tr = Tracer()
    degraded = sort(x, algorithm="radix", mesh=mesh8, tracer=tr)
    assert tr.counters.get("degraded_to") == "host"
    assert degraded.tobytes() == clean.tobytes()


# --------------------------------------------------------- CLI contract

def _cli(tmp_path, keys, monkeypatch, **env):
    import importlib.util
    import os as _os

    spec = importlib.util.spec_from_file_location(
        "sort_cli_faults", _os.path.join(_os.path.dirname(__file__), "..",
                                         "drivers", "sort_cli.py"))
    sort_cli = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sort_cli)
    p = tmp_path / "keys.txt"
    p.write_text("\n".join(str(k) for k in keys) + "\n")
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    return sort_cli, sort_cli.main(["sort_cli.py", str(p)])


def test_cli_integrity_exit_code(tmp_path, keys, monkeypatch, capsys):
    cli, rc = _cli(tmp_path, keys[:2000], monkeypatch,
                   SORT_FAULTS="result_dup:inf", SORT_FALLBACK="0")
    assert rc == cli.EXIT_INTEGRITY == 3
    err = capsys.readouterr().err
    assert err.startswith("[ERROR] ") and "Traceback" not in err


def test_cli_retries_exit_code(tmp_path, keys, monkeypatch, capsys):
    cli, rc = _cli(tmp_path, keys[:2000], monkeypatch,
                   SORT_FAULTS="dispatch_oom:inf", SORT_FALLBACK="0")
    assert rc == cli.EXIT_RETRIES == 4
    err = capsys.readouterr().err
    assert err.startswith("[ERROR] ") and "Traceback" not in err


@pytest.mark.parametrize("knob,value", [
    ("SORT_FAULTS", "garbage_site"),
    ("SORT_FAULTS", "dispatch_error:0"),
    ("SORT_VERIFY", "maybe"),
    ("SORT_MAX_RETRIES", "-1"),
    ("SORT_RETRY_BACKOFF", "fast"),
    ("SORT_FALLBACK", "2"),
])
def test_cli_robustness_knob_garbage(knob, value, tmp_path, keys,
                                     monkeypatch, capsys):
    _, rc = _cli(tmp_path, keys[:100], monkeypatch, **{knob: value})
    assert rc == 1
    err = capsys.readouterr().err
    assert err.startswith("[ERROR] ") and knob in err


def test_cli_recovers_from_transient_fault(tmp_path, keys, monkeypatch,
                                           capsys):
    _, rc = _cli(tmp_path, keys[:2000], monkeypatch,
                 SORT_FAULTS="exchange_corrupt")
    assert rc == 0
    out = capsys.readouterr().out
    ref = np.sort(keys[:2000])
    assert f"The n/2-th sorted element: {ref[999]}" in out


# ------------------------------------------------------------- telemetry

def test_report_aggregates_robustness_events(mesh8, keys, tmp_path):
    from mpitest_tpu import report

    trace = tmp_path / "trace.jsonl"
    install("exchange_corrupt")
    tr = Tracer()
    tr.spans.stream_path = str(trace)
    got = sort(keys, algorithm="radix", mesh=mesh8, tracer=tr)
    np.testing.assert_array_equal(got, np.sort(keys))
    rows = report.load_rows(str(trace))
    assert report.check_rows(rows) == []
    agg = report.aggregate(rows)
    rb = agg["robustness"]
    assert rb["faults"] >= 1 and rb["fault_sites"].get("exchange_corrupt")
    assert rb["verify_runs"] >= 2 and rb["verify_failures"] >= 1
    assert "robustness" in report.render(agg)
