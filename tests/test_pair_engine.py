"""The 64-bit pair-engine local path (VERDICT r3 #1 — the MSD hybrid).

Runs the real orchestration (``models/api.py::_local_pair_sort``) on a
1-device CPU mesh with the engine forced, so the Pallas pair kernels run
through the interpreter.  Every adaptive route is pinned by its tracer
counter: constant-word shortcut, duplication-sniff reroute, the pair
engine itself, and the residual-run fallback — correctness must hold on
all of them (the sniff is a performance heuristic, never a correctness
gate).
"""

import numpy as np
import pytest

from mpitest_tpu.models.api import sort
from mpitest_tpu.parallel.mesh import make_mesh
from mpitest_tpu.utils.trace import Tracer
from mpitest_tpu import compat

N = 15_000  # > MIN_SORT_LOG2 and past the pad break-even (pow2 = 16384)


@pytest.fixture
def mesh1():
    return make_mesh(1)


def _run(x, mesh1, monkeypatch):
    monkeypatch.setenv("SORT_LOCAL_ENGINE", "bitonic")
    tracer = Tracer()
    got = sort(x, algorithm="radix", mesh=mesh1, tracer=tracer)
    np.testing.assert_array_equal(got, np.sort(x))
    return tracer


@pytest.mark.parametrize("dtype", [np.int64, np.uint64])
def test_pair_engine_full_range(dtype, mesh1, rng, monkeypatch):
    info = np.iinfo(np.dtype(dtype))
    x = rng.integers(info.min, info.max, size=N, dtype=dtype, endpoint=True)
    tracer = _run(x, mesh1, monkeypatch)
    assert tracer.counters["local_engine"] == "bitonic_pair"
    assert "pair_residual_fallback" not in tracer.counters


def test_pair_engine_float64_totalorder(mesh1, rng, monkeypatch):
    x = (rng.standard_normal(N) * 10.0 ** rng.integers(-200, 200, N))
    x = x.astype(np.float64)
    x[:4] = [0.0, -0.0, np.inf, -np.inf]
    monkeypatch.setenv("SORT_LOCAL_ENGINE", "bitonic")
    tracer = Tracer()
    got = sort(x, algorithm="radix", mesh=mesh1, tracer=tracer)
    np.testing.assert_array_equal(got, np.sort(x))
    assert tracer.counters["local_engine"] == "bitonic_pair"


def test_narrow_range_collapses_to_one_word(mesh1, rng, monkeypatch):
    """int64 values inside one 32-bit window: the hi word is constant and
    the sort collapses to the 1-word engine on the lo word."""
    x = rng.integers(0, 2**31, size=N, dtype=np.int64)
    tracer = _run(x, mesh1, monkeypatch)
    assert tracer.counters["local_engine"] == "bitonic_1w1"


def test_low_word_constant_collapses(mesh1, rng, monkeypatch):
    """Keys = k * 2^32: lo constant, hi carries all the information."""
    x = rng.integers(0, 2**30, size=N, dtype=np.int64) << 32
    tracer = _run(x, mesh1, monkeypatch)
    assert tracer.counters["local_engine"] == "bitonic_1w0"


def test_all_equal_constant_shortcut(mesh1, monkeypatch):
    x = np.full(N, -(7 << 40), np.int64)
    tracer = _run(x, mesh1, monkeypatch)
    assert tracer.counters["local_engine"] == "constant"


def test_heavy_hi_duplication_reroutes(mesh1, rng, monkeypatch):
    """hi drawn from 8 values: runs ~N/8, the sniff must catch it and
    route straight to lax.sort — no wasted pair phase."""
    hi = rng.integers(0, 8, size=N).astype(np.int64)
    x = (hi << 33) | rng.integers(0, 2**32, size=N).astype(np.int64)
    tracer = _run(x, mesh1, monkeypatch)
    assert tracer.counters["local_engine"] == "lax"
    assert tracer.counters.get("pair_dup_reroute") == 1


def test_mid_runs_in_vmem_fix(mesh1, rng, monkeypatch):
    """Runs of 16 equal-hi keys — the class that used to double-sort via
    the residual fallback now rides the 16-pass in-VMEM fix-up (the
    round-5 mid-tier, priced in bench/fixdepth_probe.py): exact output,
    NO fallback."""
    from mpitest_tpu.models import api

    monkeypatch.setattr(api, "_host_hi_dup_sniff", lambda hi: False)
    n_runs = -(-N // 16)
    hi = np.repeat(np.arange(n_runs, dtype=np.int64) * 37 + 5, 16)[:N]
    x = (hi << 32) | rng.integers(0, 2**32, size=N).astype(np.int64)
    rng.shuffle(x)
    tracer = _run(x, mesh1, monkeypatch)
    assert tracer.counters["local_engine"] == "bitonic_pair"
    assert "pair_residual_fallback" not in tracer.counters


def test_mid_runs_residual_fallback(mesh1, rng, monkeypatch):
    """Runs of 24 equal-hi keys — longer than the 16-pass fix-up covers.
    At test scale the 1024-key sniff could catch this, so the miss is
    forced by stubbing the sniff: the residual flag must fire and the
    fallback must still return exact bytes — correctness must never
    depend on the sniff's sensitivity."""
    from mpitest_tpu.models import api

    monkeypatch.setattr(api, "_host_hi_dup_sniff", lambda hi: False)
    n_runs = -(-N // 24)
    hi = np.repeat(np.arange(n_runs, dtype=np.int64) * 37 + 5, 24)[:N]
    x = (hi << 32) | rng.integers(0, 2**32, size=N).astype(np.int64)
    rng.shuffle(x)  # runs exist in key space, not in input order
    tracer = _run(x, mesh1, monkeypatch)
    assert tracer.counters["local_engine"] == "bitonic_pair"
    assert tracer.counters.get("pair_residual_fallback") == 1


hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402


@settings(max_examples=20, deadline=None)
@given(data=st.data())
def test_property_sort_two_words_contract(data):
    """For ARBITRARY run profiles (run lengths 1..24 — straddling the
    16-pass fix-up threshold both ways — random lo, shuffled input,
    non-power-of-two n): sort_two_words_bitonic either returns the
    exact lexicographic sort with residual=False, or residual=True;
    the pair multiset is preserved in every case, and residual=False
    is GUARANTEED when all runs are <= fix_passes (16).  The
    correctness contract the api fallback relies on."""
    import jax.numpy as jnp

    from mpitest_tpu.ops import bitonic, kernels

    rng = np.random.default_rng(data.draw(st.integers(0, 2**31)))
    n = data.draw(st.integers(300, 3000))
    max_run = data.draw(st.integers(1, 24))
    lens = []
    total = 0
    while total < n:
        l = min(int(rng.integers(1, max_run + 1)), n - total)
        lens.append(l)
        total += l
    hi = np.repeat(
        rng.choice(2**32, size=len(lens), replace=False).astype(np.uint32),
        lens)
    lo = rng.integers(0, 2**32, n, dtype=np.uint64).astype(np.uint32)
    perm = rng.permutation(n)
    hi, lo = hi[perm], lo[perm]
    # shrink the engine constants so these sizes run the REAL network
    # (multi-block: cross + merge + run-fix + boundary strips), not the
    # small-n lax shortcut
    with pytest.MonkeyPatch.context() as mp:
        mp.setattr(bitonic, "MIN_SORT_LOG2", 8)
        mp.setattr(bitonic, "PAIR_BLOCK_LOG2", 9)
        hs, ls, bad = kernels.sort_two_words_bitonic(
            jnp.asarray(hi), jnp.asarray(lo), interpret=True)
    hs, ls, bad = np.asarray(hs), np.asarray(ls), bool(bad)
    key_in = (hi.astype(np.uint64) << 32) | lo
    key_out = (hs.astype(np.uint64) << 32) | ls
    np.testing.assert_array_equal(np.sort(key_out), np.sort(key_in))
    if max(lens) <= 16:  # the round-5 default fix depth
        assert not bad
    if not bad:
        np.testing.assert_array_equal(key_out, np.sort(key_in))


def test_device_resident_pair_engine(mesh1, rng, monkeypatch):
    """Device-resident int64 input goes through the fused on-device
    encode+range+sniff program (no host round-trip of the keys)."""
    import jax

    monkeypatch.setenv("SORT_LOCAL_ENGINE", "bitonic")
    x = rng.integers(-(2**62), 2**62, size=N, dtype=np.int64)
    with compat.enable_x64(True):
        dev = jax.device_put(x, jax.devices()[0])
        tracer = Tracer()
        got = sort(dev, algorithm="radix", mesh=mesh1, tracer=tracer)
    np.testing.assert_array_equal(got, np.sort(x))
    assert tracer.counters["local_engine"] == "bitonic_pair"
