"""Key codec round-trip + order-preservation properties."""

import numpy as np
import pytest

from mpitest_tpu.ops.keys import codec_for


DTYPES = [np.int32, np.uint32, np.int64, np.uint64]


@pytest.mark.parametrize("dtype", DTYPES)
def test_roundtrip(dtype, rng):
    info = np.iinfo(np.dtype(dtype))
    x = rng.integers(info.min, info.max, size=1000, dtype=dtype, endpoint=True)
    x = np.concatenate([x, [info.min, info.max, 0, 1]]).astype(dtype)
    codec = codec_for(dtype)
    words = codec.encode(x)
    assert all(w.dtype == np.uint32 for w in words)
    assert len(words) == codec.n_words
    np.testing.assert_array_equal(codec.decode(words), x)


@pytest.mark.parametrize("dtype", DTYPES)
def test_order_preserved(dtype, rng):
    """Lexicographic unsigned word order == native key order.

    This is the property the reference *breaks* for negatives
    (abs() digit math, mpi_radix_sort.c:50,56)."""
    info = np.iinfo(np.dtype(dtype))
    x = rng.integers(info.min, info.max, size=500, dtype=dtype, endpoint=True)
    codec = codec_for(dtype)
    words = codec.encode(x)
    # sort natively, and lexicographically by words
    native = np.sort(x)
    order = np.lexsort(tuple(reversed(words)))  # lexsort: last key is primary
    lex = x[order]
    np.testing.assert_array_equal(lex, native)


def test_sentinel_is_max():
    for dtype in DTYPES:
        codec = codec_for(dtype)
        sent = np.array(codec.max_sentinel(), dtype=np.uint64)
        assert np.all(sent == 0xFFFFFFFF)
        decoded = codec.decode(tuple(np.full(1, s, np.uint32) for s in codec.max_sentinel()))
        assert decoded[0] == np.iinfo(np.dtype(dtype)).max
