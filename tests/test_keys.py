"""Key codec round-trip + order-preservation properties."""

import numpy as np
import pytest

from mpitest_tpu.ops.keys import codec_for


DTYPES = [np.int32, np.uint32, np.int64, np.uint64]


@pytest.mark.parametrize("dtype", DTYPES)
def test_roundtrip(dtype, rng):
    info = np.iinfo(np.dtype(dtype))
    x = rng.integers(info.min, info.max, size=1000, dtype=dtype, endpoint=True)
    x = np.concatenate([x, [info.min, info.max, 0, 1]]).astype(dtype)
    codec = codec_for(dtype)
    words = codec.encode(x)
    assert all(w.dtype == np.uint32 for w in words)
    assert len(words) == codec.n_words
    np.testing.assert_array_equal(codec.decode(words), x)


@pytest.mark.parametrize("dtype", DTYPES)
def test_order_preserved(dtype, rng):
    """Lexicographic unsigned word order == native key order.

    This is the property the reference *breaks* for negatives
    (abs() digit math, mpi_radix_sort.c:50,56)."""
    info = np.iinfo(np.dtype(dtype))
    x = rng.integers(info.min, info.max, size=500, dtype=dtype, endpoint=True)
    codec = codec_for(dtype)
    words = codec.encode(x)
    # sort natively, and lexicographically by words
    native = np.sort(x)
    order = np.lexsort(tuple(reversed(words)))  # lexsort: last key is primary
    lex = x[order]
    np.testing.assert_array_equal(lex, native)


def test_sentinel_is_max():
    for dtype in DTYPES:
        codec = codec_for(dtype)
        sent = np.array(codec.max_sentinel(), dtype=np.uint64)
        assert np.all(sent == 0xFFFFFFFF)
        decoded = codec.decode(tuple(np.full(1, s, np.uint32) for s in codec.max_sentinel()))
        assert decoded[0] == np.iinfo(np.dtype(dtype)).max


FLOAT_DTYPES = [np.float32, np.float64]


def _float_specials(dtype, rng):
    f = np.dtype(dtype)
    x = rng.standard_normal(1000).astype(f) * 1e10
    specials = np.array(
        [0.0, -0.0, np.inf, -np.inf, np.nan, -np.nan, 1e-40, -1e-40],
        dtype=f,
    )
    return np.concatenate([x, specials])


@pytest.mark.parametrize("dtype", FLOAT_DTYPES)
def test_float_roundtrip_bits(dtype, rng):
    """encode∘decode is the identity on BITS — NaN payloads, -0.0 and
    denormals all survive exactly."""
    x = _float_specials(dtype, rng)
    codec = codec_for(dtype)
    back = codec.decode(codec.encode(x))
    np.testing.assert_array_equal(
        back.view(np.uint32 if dtype == np.float32 else np.uint64),
        x.view(np.uint32 if dtype == np.float32 else np.uint64),
    )


@pytest.mark.parametrize("dtype", FLOAT_DTYPES)
def test_float_total_order(dtype, rng):
    """Word order == IEEE totalOrder: -NaN < -inf < ... < -0.0 < +0.0 <
    ... < +inf < +NaN (documented divergence from np.sort's NaNs-last)."""
    x = _float_specials(dtype, rng)
    codec = codec_for(dtype)
    words = codec.encode(x)
    order = np.lexsort(tuple(reversed(words)))
    s = x[order]
    finite = s[np.isfinite(s)]
    assert (np.diff(finite) >= 0).all()
    # -NaN block at the head, +NaN block at the tail
    sign = np.signbit(s)
    assert np.isnan(s[0]) and sign[0]
    assert np.isnan(s[-1]) and not sign[-1]
    # -0.0 strictly before +0.0
    zeros = np.where(s == 0)[0]
    assert sign[zeros[0]] and not sign[zeros[-1]]
