"""Key codec round-trip + order-preservation properties."""

import numpy as np
import pytest

from mpitest_tpu.ops.keys import codec_for


DTYPES = [np.int32, np.uint32, np.int64, np.uint64]


@pytest.mark.parametrize("dtype", DTYPES)
def test_roundtrip(dtype, rng):
    info = np.iinfo(np.dtype(dtype))
    x = rng.integers(info.min, info.max, size=1000, dtype=dtype, endpoint=True)
    # extremes as a typed array: concatenating a Python list would promote
    # through float64 and round the uint64/int64 extremes off by one
    x = np.concatenate([x, np.array([info.min, info.max, 0, 1], dtype=dtype)])
    codec = codec_for(dtype)
    words = codec.encode(x)
    assert all(w.dtype == np.uint32 for w in words)
    assert len(words) == codec.n_words
    np.testing.assert_array_equal(codec.decode(words), x)


@pytest.mark.parametrize("dtype", DTYPES)
def test_order_preserved(dtype, rng):
    """Lexicographic unsigned word order == native key order.

    This is the property the reference *breaks* for negatives
    (abs() digit math, mpi_radix_sort.c:50,56)."""
    info = np.iinfo(np.dtype(dtype))
    x = rng.integers(info.min, info.max, size=500, dtype=dtype, endpoint=True)
    codec = codec_for(dtype)
    words = codec.encode(x)
    # sort natively, and lexicographically by words
    native = np.sort(x)
    order = np.lexsort(tuple(reversed(words)))  # lexsort: last key is primary
    lex = x[order]
    np.testing.assert_array_equal(lex, native)


def test_sentinel_is_max():
    for dtype in DTYPES:
        codec = codec_for(dtype)
        sent = np.array(codec.max_sentinel(), dtype=np.uint64)
        assert np.all(sent == 0xFFFFFFFF)
        decoded = codec.decode(tuple(np.full(1, s, np.uint32) for s in codec.max_sentinel()))
        assert decoded[0] == np.iinfo(np.dtype(dtype)).max


FLOAT_DTYPES = [np.float32, np.float64]


def _float_specials(dtype, rng):
    f = np.dtype(dtype)
    x = rng.standard_normal(1000).astype(f) * 1e10
    specials = np.array(
        [0.0, -0.0, np.inf, -np.inf, np.nan, -np.nan, 1e-40, -1e-40],
        dtype=f,
    )
    return np.concatenate([x, specials])


@pytest.mark.parametrize("dtype", FLOAT_DTYPES)
def test_float_roundtrip_bits(dtype, rng):
    """encode∘decode is the identity on BITS — NaN payloads, -0.0 and
    denormals all survive exactly."""
    x = _float_specials(dtype, rng)
    codec = codec_for(dtype)
    back = codec.decode(codec.encode(x))
    np.testing.assert_array_equal(
        back.view(np.uint32 if dtype == np.float32 else np.uint64),
        x.view(np.uint32 if dtype == np.float32 else np.uint64),
    )


@pytest.mark.parametrize("dtype", FLOAT_DTYPES)
def test_float_total_order(dtype, rng):
    """Word order == IEEE totalOrder: -NaN < -inf < ... < -0.0 < +0.0 <
    ... < +inf < +NaN (documented divergence from np.sort's NaNs-last)."""
    x = _float_specials(dtype, rng)
    codec = codec_for(dtype)
    words = codec.encode(x)
    order = np.lexsort(tuple(reversed(words)))
    s = x[order]
    finite = s[np.isfinite(s)]
    assert (np.diff(finite) >= 0).all()
    # -NaN block at the head, +NaN block at the tail
    sign = np.signbit(s)
    assert np.isnan(s[0]) and sign[0]
    assert np.isnan(s[-1]) and not sign[-1]
    # -0.0 strictly before +0.0
    zeros = np.where(s == 0)[0]
    assert sign[zeros[0]] and not sign[zeros[-1]]


# ---- property-based (hypothesis): the codec laws hold for ARBITRARY
# values, not just the sampled corpora above.  hypothesis is optional
# (not a declared dependency): absent, only these two tests skip. ------

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

ALL_DTYPES = [np.int8, np.uint8, np.int16, np.uint16,
              np.int32, np.uint32, np.int64, np.uint64]


def _ints_for(dtype):
    info = np.iinfo(np.dtype(dtype))
    return st.integers(min_value=int(info.min), max_value=int(info.max))


@pytest.mark.parametrize("dtype", ALL_DTYPES)
@settings(max_examples=25, deadline=None)
@given(data=st.data())
def test_property_int_roundtrip_and_order(dtype, data):
    """For every integer dtype (each with its own example budget) and ANY
    pair of values: encode∘decode is the identity, and key comparison ==
    lexicographic unsigned word comparison (the law every sort in this
    framework rests on)."""
    a = data.draw(_ints_for(dtype))
    b = data.draw(_ints_for(dtype))
    codec = codec_for(dtype)
    x = np.array([a, b], dtype=dtype)
    words = codec.encode(x)
    np.testing.assert_array_equal(codec.decode(words), x)
    wa = tuple(int(w[0]) for w in words)
    wb = tuple(int(w[1]) for w in words)
    assert (a < b) == (wa < wb) and (a == b) == (wa == wb)


@settings(max_examples=40, deadline=None)
@given(data=st.data())
def test_property_float_roundtrip_and_totalorder(data):
    """For float32/float64 and ANY bit patterns (including NaN payloads,
    infinities, denormals, signed zeros): encode∘decode preserves the
    exact bits, and word order == IEEE-754 totalOrder."""
    wide = data.draw(st.booleans())
    ftype, utype = (np.float64, np.uint64) if wide else (np.float32, np.uint32)
    bits = st.integers(0, 2 ** (64 if wide else 32) - 1)
    a = data.draw(bits)
    b = data.draw(bits)
    x = np.array([a, b], dtype=utype).view(ftype)
    codec = codec_for(ftype)
    words = codec.encode(x)
    np.testing.assert_array_equal(
        codec.decode(words).view(utype), x.view(utype))

    def total_order_key(u):
        # IEEE-754 totalOrder as an unsigned integer: flip all bits of
        # negatives, set the sign bit of non-negatives
        sign = 1 << (63 if wide else 31)
        return (~u) & (2 ** (64 if wide else 32) - 1) if u & sign else u | sign

    wa = tuple(int(w[0]) for w in words)
    wb = tuple(int(w[1]) for w in words)
    assert (total_order_key(a) < total_order_key(b)) == (wa < wb)
