"""Property tests for the distributed sort models on the virtual CPU mesh.

The strategy the reference lacks (SURVEY.md §4): sorted-output equality vs
``np.sort`` (bit-identical, the north-star contract), permutation/multiset
preservation, non-divisible N (the reference's Scatter-overflow case),
negatives (the reference's abs() bug), duplicates, skew, and both
algorithms agreeing byte-for-byte.
"""

import numpy as np
import pytest

from mpitest_tpu.models.api import sort
from mpitest_tpu.utils import io


ALGOS = ["radix", "sample"]


@pytest.mark.parametrize("algo", ALGOS)
@pytest.mark.parametrize("n", [8, 64, 1000, 4096, 100_000])
def test_uniform_int32(algo, n, mesh8, rng):
    x = rng.integers(-(2**31), 2**31 - 1, size=n, dtype=np.int32)
    got = sort(x, algorithm=algo, mesh=mesh8)
    np.testing.assert_array_equal(got, np.sort(x))


@pytest.mark.parametrize("algo", ALGOS)
def test_non_divisible_n(algo, mesh8, rng):
    """P ∤ N — heap-overflow territory in the reference (mpi_sample_sort.c:80-82)."""
    for n in [7, 9, 63, 1001, 12345]:
        x = rng.integers(-1000, 1000, size=n, dtype=np.int32)
        got = sort(x, algorithm=algo, mesh=mesh8)
        np.testing.assert_array_equal(got, np.sort(x))


@pytest.mark.parametrize("algo", ALGOS)
def test_negatives_and_extremes(algo, mesh8):
    """Negative keys sort correctly (reference sorts by |x|, mpi_radix_sort.c:50)."""
    x = np.array(
        [0, -1, 1, -(2**31), 2**31 - 1, 42, -42, -1, 2**31 - 1, -(2**31)],
        np.int32,
    )
    got = sort(x, algorithm=algo, mesh=mesh8)
    np.testing.assert_array_equal(got, np.sort(x))


@pytest.mark.parametrize("algo", ALGOS)
def test_all_duplicates(algo, mesh8):
    x = np.full(1000, 7, np.int32)
    got = sort(x, algorithm=algo, mesh=mesh8)
    np.testing.assert_array_equal(got, x)


@pytest.mark.parametrize("algo", ALGOS)
def test_max_value_keys_vs_sentinel(algo, mesh8, rng):
    """Keys equal to the padding sentinel must survive (canonical multiset)."""
    x = np.concatenate(
        [np.full(50, 2**31 - 1, np.int32), rng.integers(0, 100, 53, dtype=np.int32)]
    )
    got = sort(x, algorithm=algo, mesh=mesh8)
    np.testing.assert_array_equal(got, np.sort(x))


@pytest.mark.parametrize("algo", ALGOS)
@pytest.mark.parametrize("dtype", [np.uint32, np.int64, np.uint64])
def test_other_dtypes(algo, dtype, mesh8, rng):
    info = np.iinfo(np.dtype(dtype))
    x = rng.integers(info.min, info.max, size=2000, dtype=dtype, endpoint=True)
    got = sort(x, algorithm=algo, mesh=mesh8)
    assert got.dtype == np.dtype(dtype)
    np.testing.assert_array_equal(got, np.sort(x))


@pytest.mark.parametrize("algo", ALGOS)
def test_zipf_skew(algo, mesh8):
    """The splitter-imbalance stressor (BASELINE.json configs[4], scaled down).

    Heavy duplication forces exchange-cap overflow → the retry path must
    produce the correct result anyway."""
    x = io.generate_zipf(20_000, dtype=np.int64, seed=3)
    got = sort(x, algorithm=algo, mesh=mesh8)
    np.testing.assert_array_equal(got, np.sort(x))


def test_zipf_sample_routes_to_radix(mesh8):
    """SURVEY.md §7.3 Zipf fallback: under heavy duplication the sample
    path must keep recv memory O(n)/device by rerouting to radix (whose
    dest = exact global position is skew-immune), not by growing the cap
    toward the full shard size."""
    from mpitest_tpu.models.api import SAMPLE_CAP_LIMIT_FACTOR
    from mpitest_tpu.utils.trace import Tracer

    # Zipf(1.5): the top value carries ~38% of the mass (1/zeta(1.5)) —
    # far above the 1/P=12.5% fair share, so splitters degenerate.
    x = io.generate_zipf(1 << 16, a=1.5, dtype=np.int64, seed=3)
    tracer = Tracer()
    got = sort(x, algorithm="sample", mesh=mesh8, tracer=tracer)
    np.testing.assert_array_equal(got, np.sort(x))
    assert tracer.counters.get("sample_skew_fallback", 0) >= 1
    n_shard = -(-x.size // 8)
    assert tracer.counters["exchange_cap"] <= max(
        SAMPLE_CAP_LIMIT_FACTOR * -(-n_shard // 8) + 1024, 1024
    )


def test_zipf11_sample_stays_bounded(mesh8):
    """Zipf(1.1) at 8 ranks is heavy-tailed but NOT degenerate (top value
    ~9.5% < 1/P): the sample path must handle it with bounded cap and no
    fallback — the reroute is for genuinely pathological duplication."""
    from mpitest_tpu.models.api import SAMPLE_CAP_LIMIT_FACTOR
    from mpitest_tpu.utils.trace import Tracer

    x = io.generate_zipf(1 << 15, dtype=np.int64, seed=3)
    tracer = Tracer()
    got = sort(x, algorithm="sample", mesh=mesh8, tracer=tracer)
    np.testing.assert_array_equal(got, np.sort(x))
    assert tracer.counters.get("sample_skew_fallback", 0) == 0
    n_shard = -(-x.size // 8)
    assert tracer.counters["exchange_cap"] <= SAMPLE_CAP_LIMIT_FACTOR * -(-n_shard // 8) + 1024


def test_device_resident_zipf_sniffs_on_device(mesh8):
    """VERDICT r2 #4: a device-resident Zipf(1.5) input must reroute to
    radix via the on-device sniff — zero failed-exchange retries, no
    wasted sample-program round — and still sort correctly."""
    import jax

    from mpitest_tpu.utils.trace import Tracer

    x = np.clip(io.generate_zipf(1 << 16, a=1.5, seed=3), 0, 2**31 - 1).astype(
        np.int32
    )
    dev = jax.device_put(x, jax.devices()[0])
    tracer = Tracer()
    got = sort(dev, algorithm="sample", mesh=mesh8, tracer=tracer)
    np.testing.assert_array_equal(got, np.sort(x))
    assert tracer.counters.get("sample_skew_fallback", 0) == 1
    assert tracer.counters.get("exchange_retries", 0) == 0


def test_device_resident_tail_skew_sniffed(mesh8):
    """Tail-heavy duplication at an awkward N (n_valid mod sample ≈ half
    the data): the on-device sniff's strided sample is anchored to the
    END of the range, so a massively repeated tail value — invisible to
    a head-anchored slice — still degenerates the quantiles and
    reroutes.  Regression for the r4 review finding on the slice
    anchoring."""
    import jax

    from mpitest_tpu.utils.trace import Tracer

    n = (1 << 15) + 255  # forces stride rounding; tail would be unsampled
    rng = np.random.default_rng(9)
    head = rng.permutation(np.arange(n // 2, dtype=np.int32))
    tail = np.full(n - head.size, np.int32(2**31 - 1))
    x = np.concatenate([head, tail])  # second half = one hot value
    dev = jax.device_put(x, jax.devices()[0])
    tracer = Tracer()
    got = sort(dev, algorithm="sample", mesh=mesh8, tracer=tracer)
    np.testing.assert_array_equal(got, np.sort(x))
    assert tracer.counters.get("sample_skew_fallback", 0) == 1
    assert tracer.counters.get("exchange_retries", 0) == 0


def test_device_resident_uniform_no_sniff_fallback(mesh8):
    """The on-device sniff must not fire on uniform device-resident input
    (same threshold semantics as the host sniff)."""
    import jax

    from mpitest_tpu.utils.trace import Tracer

    rng = np.random.default_rng(4)
    x = rng.integers(-(2**31), 2**31 - 1, size=1 << 15, dtype=np.int32)
    dev = jax.device_put(x, jax.devices()[0])
    tracer = Tracer()
    got = sort(dev, algorithm="sample", mesh=mesh8, tracer=tracer)
    np.testing.assert_array_equal(got, np.sort(x))
    assert tracer.counters.get("sample_skew_fallback", 0) == 0


def test_skew_sniff_thresholds():
    """The host-side sniff fires on degenerate quantiles, not on benign
    duplication."""
    from mpitest_tpu.models.api import _sample_skew_sniff
    from mpitest_tpu.ops.keys import codec_for

    rng = np.random.default_rng(0)
    uniform = codec_for(np.dtype(np.int32)).encode(
        rng.integers(-(2**31), 2**31 - 1, size=10_000, dtype=np.int32))
    assert not _sample_skew_sniff(uniform, 8)
    zipf = codec_for(np.dtype(np.int64)).encode(
        io.generate_zipf(10_000, a=1.5, dtype=np.int64, seed=1))
    assert _sample_skew_sniff(zipf, 8)
    # Zipf(1.1) at 8 ranks: heavy-tailed but below the 2/P degeneracy
    # threshold — must NOT fire (it sorts fine with a bounded cap).
    zipf11 = codec_for(np.dtype(np.int64)).encode(
        io.generate_zipf(10_000, a=1.1, dtype=np.int64, seed=1))
    assert not _sample_skew_sniff(zipf11, 8)
    # all-equal keys: maximally degenerate
    const = codec_for(np.dtype(np.int32)).encode(
        np.full(5000, 7, dtype=np.int32))
    assert _sample_skew_sniff(const, 8)


@pytest.mark.parametrize("algo", ALGOS)
def test_sorted_and_reverse_inputs(algo, mesh8):
    x = np.arange(-500, 500, dtype=np.int32)
    np.testing.assert_array_equal(sort(x, algorithm=algo, mesh=mesh8), x)
    np.testing.assert_array_equal(sort(x[::-1].copy(), algorithm=algo, mesh=mesh8), x)


def test_algorithms_agree_bitwise(mesh8, rng):
    """mpi-vs-tpu golden parity analogue: both models, same bytes."""
    x = rng.integers(-(2**31), 2**31 - 1, size=9999, dtype=np.int32)
    a = sort(x, algorithm="radix", mesh=mesh8)
    b = sort(x, algorithm="sample", mesh=mesh8)
    assert a.tobytes() == b.tobytes()


def test_determinism(mesh8, rng):
    x = rng.integers(-(2**31), 2**31 - 1, size=5000, dtype=np.int32)
    runs = [sort(x, algorithm="radix", mesh=mesh8).tobytes() for _ in range(3)]
    assert len(set(runs)) == 1


@pytest.mark.parametrize("algo", ALGOS)
def test_small_meshes(algo, mesh4, rng):
    x = rng.integers(-100, 100, size=1000, dtype=np.int32)
    np.testing.assert_array_equal(sort(x, algorithm=algo, mesh=mesh4), np.sort(x))


@pytest.mark.parametrize("algo", ALGOS)
def test_tiny_inputs(algo, mesh8):
    for n in [1, 2, 3]:
        x = np.arange(n, dtype=np.int32)[::-1].copy()
        np.testing.assert_array_equal(sort(x, algorithm=algo, mesh=mesh8), np.sort(x))
    assert sort(np.array([], np.int32), algorithm=algo, mesh=mesh8).size == 0


def test_empty_return_result(mesh8):
    res = sort(np.array([], np.int32), mesh=mesh8, return_result=True)
    assert res.to_numpy().size == 0


def test_median_probe(mesh8, rng):
    x = rng.integers(-(2**31), 2**31 - 1, size=10_000, dtype=np.int32)
    ref = int(np.sort(x)[10_000 // 2 - 1])
    for algo in ALGOS:
        res = sort(x, algorithm=algo, mesh=mesh8, return_result=True)
        assert res.median_probe() == ref


def test_median_probe_raw_float_bits(mesh8, rng):
    """Float probes compare exact bit patterns via median_probe_raw —
    int truncation (median_probe) collides distinct float medians
    (ADVICE r2)."""
    x = io.generate("uniform", 4001, np.float32, seed=9)
    res = sort(x, algorithm="radix", mesh=mesh8, return_result=True)
    raw = res.median_probe_raw()
    assert raw.dtype == np.float32
    ref = np.sort(x)[4001 // 2 - 1]
    assert np.asarray(raw).view(np.uint32) == np.asarray(ref).view(np.uint32)


def test_auto_digit_width(mesh8, rng):
    """Full-range int32 auto-plans 16-bit digits -> 2 passes; a narrow
    range still collapses to one cheap 8-bit pass (pass count is what a
    pass costs a full fused sort for — BASELINE.md roofline)."""
    from mpitest_tpu.utils.trace import Tracer

    x = rng.integers(-(2**31), 2**31 - 1, size=10_000, dtype=np.int32)
    tr = Tracer()
    got = sort(x, algorithm="radix", mesh=mesh8, tracer=tr)
    np.testing.assert_array_equal(got, np.sort(x))
    assert tr.counters["digit_bits"] == 16
    assert tr.counters["exchange_passes"] == 2

    narrow = rng.integers(0, 200, size=10_000, dtype=np.int32)
    tr2 = Tracer()
    got2 = sort(narrow, algorithm="radix", mesh=mesh8, tracer=tr2)
    np.testing.assert_array_equal(got2, np.sort(narrow))
    assert tr2.counters["digit_bits"] == 8
    assert tr2.counters["exchange_passes"] == 1


def test_explicit_digit_bits_still_work(mesh8, rng):
    x = rng.integers(-(2**31), 2**31 - 1, size=5_000, dtype=np.int32)
    for db in (4, 8, 11, 16):
        got = sort(x, algorithm="radix", mesh=mesh8, digit_bits=db)
        np.testing.assert_array_equal(got, np.sort(x))


def test_sample_spmd_bitonic_engine(mesh8, rng, monkeypatch):
    """The distributed sample sort with its per-shard sorts on the Pallas
    bitonic engine (interpret mode on the CPU mesh) — the multi-chip
    acceleration path — produces the same bytes as np.sort."""
    from mpitest_tpu.ops import bitonic

    monkeypatch.setenv("SORT_LOCAL_ENGINE", "bitonic")
    # keep interpret-mode runtime sane: small blocks, no lax fallback
    monkeypatch.setattr(bitonic, "MIN_SORT_LOG2", 8)
    monkeypatch.setattr(bitonic, "BLOCK_LOG2", 9)
    x = rng.integers(-(2**31), 2**31 - 1, size=4096, dtype=np.int32)
    got = sort(x, algorithm="sample", mesh=mesh8)
    np.testing.assert_array_equal(got, np.sort(x))


def test_sample_spmd_pair_engine_64bit(mesh8, rng, monkeypatch):
    """The distributed sample sort's per-shard sorts on the 64-bit PAIR
    engine under shard_map (interpret mode on the CPU mesh): the
    residual fallback is an on-device cond here — no host orchestration
    exists inside the SPMD program — and the output must still be exact
    bytes."""
    from mpitest_tpu.ops import bitonic

    monkeypatch.setenv("SORT_LOCAL_ENGINE", "bitonic")
    monkeypatch.setattr(bitonic, "MIN_SORT_LOG2", 8)
    monkeypatch.setattr(bitonic, "BLOCK_LOG2", 9)
    monkeypatch.setattr(bitonic, "PAIR_BLOCK_LOG2", 9)
    x = rng.integers(-(2**62), 2**62, size=4096, dtype=np.int64)
    got = sort(x, algorithm="sample", mesh=mesh8)
    np.testing.assert_array_equal(got, np.sort(x))


@pytest.mark.parametrize("algo", ALGOS)
@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_float_keys(algo, dtype, mesh8, rng):
    """Float keys sort in IEEE totalOrder on the full distributed path
    (the reference is int-only; this is framework-level breadth).  On
    NaN-free data with a single zero sign the order equals np.sort."""
    x = (rng.standard_normal(5000) * 1e6).astype(dtype)
    got = sort(x, algorithm=algo, mesh=mesh8)
    np.testing.assert_array_equal(got, np.sort(x))


def test_float_nan_and_zero_totalorder(mesh8, rng):
    """NaNs and signed zeros: multiset of bit patterns preserved, order
    is totalOrder (-NaN first, +NaN last, -0.0 < +0.0) — documented
    divergence from np.sort, including for the n%P != 0 padded case."""
    x = np.concatenate([
        (rng.standard_normal(997) * 1e3).astype(np.float32),
        np.array([np.nan, -np.nan, 0.0, -0.0, np.inf, -np.inf], np.float32),
    ])
    got = sort(x, algorithm="sample", mesh=mesh8)
    assert got.shape == x.shape
    # exact multiset of bit patterns
    np.testing.assert_array_equal(
        np.sort(got.view(np.uint32)), np.sort(x.view(np.uint32)))
    # totalOrder endpoints
    assert np.isnan(got[0]) and np.signbit(got[0])
    assert np.isnan(got[-1]) and not np.signbit(got[-1])
    z = np.where(got == 0)[0]
    assert np.signbit(got[z[0]]) and not np.signbit(got[z[-1]])


@pytest.mark.parametrize("dtype", [np.int8, np.uint8, np.int16, np.uint16])
def test_narrow_int_keys(dtype, mesh8, rng):
    """Narrow integer dtypes widen losslessly into the 32-bit codec paths
    and sort on the full distributed machinery."""
    info = np.iinfo(np.dtype(dtype))
    x = rng.integers(info.min, info.max, size=3000, dtype=dtype, endpoint=True)
    got = sort(x, algorithm="radix", mesh=mesh8)
    assert got.dtype == np.dtype(dtype)
    np.testing.assert_array_equal(got, np.sort(x))


# ------------------------- streaming ingest/egress pipeline (ISSUE 2) ----


@pytest.mark.parametrize("algo", ALGOS)
@pytest.mark.parametrize("n", [5, 999, 12345])
def test_streamed_pipeline_matches_monolithic(algo, n, mesh8, rng,
                                              monkeypatch):
    """The chunked double-buffered ingest (forced on, tiny chunks so
    every input spans many chunks and shard boundaries) produces the
    same bytes as np.sort — including non-divisible N and N < P, where
    padding spans multiple devices."""
    monkeypatch.setenv("SORT_INGEST", "stream")
    monkeypatch.setenv("SORT_INGEST_CHUNK", "100")
    x = rng.integers(-(2**31), 2**31 - 1, size=n, dtype=np.int32)
    got = sort(x, algorithm=algo, mesh=mesh8)
    np.testing.assert_array_equal(got, np.sort(x))


def test_streamed_single_chunk(mesh8, rng, monkeypatch):
    """1-chunk input (chunk larger than N): the pipeline degenerates
    gracefully — same result, no special-casing required."""
    monkeypatch.setenv("SORT_INGEST", "stream")
    monkeypatch.setenv("SORT_INGEST_CHUNK", str(1 << 22))
    x = rng.integers(-(2**31), 2**31 - 1, size=4097, dtype=np.int32)
    got = sort(x, algorithm="radix", mesh=mesh8)
    np.testing.assert_array_equal(got, np.sort(x))


@pytest.mark.parametrize("dtype", [np.int64, np.float64])
def test_streamed_pipeline_two_word_dtypes(dtype, mesh8, monkeypatch):
    """2-word codecs stream chunk-by-chunk too (per-word diffs folded in
    flight feed the radix pass planner; float pads use the totalOrder
    sentinel)."""
    monkeypatch.setenv("SORT_INGEST", "stream")
    monkeypatch.setenv("SORT_INGEST_CHUNK", "500")
    from mpitest_tpu.utils import io as kio

    x = kio.generate("uniform", 7001, dtype, seed=6)
    got = sort(x, algorithm="radix", mesh=mesh8)
    np.testing.assert_array_equal(got.view(np.uint8),
                                  np.sort(x).view(np.uint8))


def test_staged_ingest_entry_and_spans(mesh8, rng, monkeypatch):
    """ingest_to_mesh -> sort(StagedIngest): correct bytes, ingest.*
    stage spans emitted, stats folded (planner diffs mean the sort's
    plan phase touches no data), and streamed egress emits egress.*."""
    from mpitest_tpu.models.api import ingest_to_mesh
    from mpitest_tpu.utils.trace import Tracer

    monkeypatch.setenv("SORT_INGEST", "stream")
    monkeypatch.setenv("SORT_INGEST_CHUNK", "1000")
    x = rng.integers(-(2**31), 2**31 - 1, size=10_000, dtype=np.int32)
    tr = Tracer()
    staged = ingest_to_mesh(x, mesh=mesh8, tracer=tr)
    assert staged.n_valid == x.size and staged.stats.chunks == 10
    # diffs folded chunk-by-chunk == one-shot host diffs
    from mpitest_tpu.models.api import _word_diffs
    from mpitest_tpu.ops.keys import codec_for

    assert staged.word_diffs == _word_diffs(
        codec_for(np.dtype(np.int32)).encode(x))
    got = sort(staged, algorithm="radix", tracer=tr)
    np.testing.assert_array_equal(got, np.sort(x))
    names = {s.name for s in tr.spans.spans}
    assert {"ingest.parse", "ingest.encode", "ingest.transfer",
            "ingest.pipeline", "egress.fetch", "egress.decode"} <= names


def test_streamed_ingest_deterministic(mesh8, rng, monkeypatch):
    """Pipeline output is bit-identical run to run (thread scheduling
    must not leak into results — the transfer thread lands pieces in
    chunk order by construction)."""
    monkeypatch.setenv("SORT_INGEST", "stream")
    monkeypatch.setenv("SORT_INGEST_CHUNK", "333")
    x = rng.integers(-(2**31), 2**31 - 1, size=5000, dtype=np.int32)
    runs = [sort(x, algorithm="radix", mesh=mesh8).tobytes()
            for _ in range(3)]
    assert len(set(runs)) == 1


def test_ingest_dtype_guard(mesh8):
    """ISSUE 2 satellite: the bench.py:171 silent-downcast hazard is a
    hard error at the source.  Without x64, jax.device_put of 64-bit
    host keys lands a 32-bit shadow; checked_device_put must raise, not
    warn — a downcast sort input is wrong data, not lost precision."""
    import jax

    from mpitest_tpu.models.api import checked_device_put

    if jax.config.jax_enable_x64:
        pytest.skip("guard only observable without x64")
    dev = jax.devices()[0]
    # uint32 words (the ingest path's actual traffic) pass untouched
    ok = checked_device_put(np.arange(8, dtype=np.uint32), dev)
    assert ok.dtype == np.uint32
    for dt in (np.int64, np.uint64, np.float64):
        with pytest.raises(TypeError, match="changed dtype"):
            checked_device_put(np.arange(8, dtype=dt), dev)


def test_donated_dispatch_with_overflow_retry(mesh8, rng, monkeypatch):
    """SORT_DONATE=1: the sort donates the staged word buffers to the
    SPMD program; an exchange-overflow retry must re-stage the input
    (the donated buffers are dead) and still produce exact bytes.
    Negotiation pinned off: see test_forced_tiny_cap_overflow_retry."""
    monkeypatch.setenv("SORT_NEGOTIATE", "off")
    monkeypatch.setenv("SORT_DONATE", "1")
    monkeypatch.setenv("SORT_INGEST", "stream")
    monkeypatch.setenv("SORT_INGEST_CHUNK", "4096")
    from mpitest_tpu.utils.trace import Tracer

    x = rng.integers(-(2**31), 2**31 - 1, size=60_000, dtype=np.int32)
    tr = Tracer()
    # cap_factor tiny -> guaranteed overflow -> retry on rebuilt words
    got = sort(x, algorithm="radix", mesh=mesh8, cap_factor=0.01, tracer=tr)
    np.testing.assert_array_equal(got, np.sort(x))
    assert tr.counters.get("exchange_retries", 0) >= 1
    tr2 = Tracer()
    got2 = sort(x, algorithm="sample", mesh=mesh8, cap_factor=0.01,
                tracer=tr2)
    np.testing.assert_array_equal(got2, np.sort(x))


def test_staged_single_use_under_donation(mesh8, rng, monkeypatch):
    """SORT_DONATE=1: a donated dispatch consumes the staged word
    buffers, so reusing the same StagedIngest must raise a clear error
    (not dispatch on deleted arrays), and .rebuild() must produce a
    usable replacement."""
    monkeypatch.setenv("SORT_DONATE", "1")
    from mpitest_tpu.models.api import ingest_to_mesh

    x = rng.integers(-(2**31), 2**31 - 1, size=40_000, dtype=np.int32)
    st = ingest_to_mesh(x, mesh=mesh8)
    np.testing.assert_array_equal(sort(st, algorithm="radix", mesh=mesh8),
                                  np.sort(x))
    assert st.consumed
    with pytest.raises(ValueError, match="already consumed"):
        sort(st, algorithm="radix", mesh=mesh8)
    st2 = st.rebuild()
    np.testing.assert_array_equal(sort(st2, algorithm="radix", mesh=mesh8),
                                  np.sort(x))


@pytest.mark.parametrize("algo", ALGOS)
@pytest.mark.parametrize("donate", ["0", "1"])
def test_forced_tiny_cap_overflow_retry(algo, donate, mesh8, rng,
                                        monkeypatch):
    """ISSUE 3 satellite: SORT_CAP_FACTOR ≈ 0 forces the first exchange
    cap to the alignment floor for BOTH algorithms — the overflow-retry
    path (now the supervisor's ONE shared cap-regrow loop) must recover
    exact bytes, with and without buffer donation (the donated variant
    exercises the PR 2 re-stage path: the failed dispatch consumed the
    input words).  Capacity negotiation (ISSUE 7) is pinned OFF: it
    sizes the cap from the count probe precisely so this overflow never
    happens — these tests exercise the backstop loop it backstops."""
    monkeypatch.setenv("SORT_NEGOTIATE", "off")
    monkeypatch.setenv("SORT_DONATE", donate)
    from mpitest_tpu.utils.trace import Tracer

    x = rng.integers(-(2**31), 2**31 - 1, size=50_000, dtype=np.int32)
    tr = Tracer()
    got = sort(x, algorithm=algo, mesh=mesh8, cap_factor=1e-9, tracer=tr)
    np.testing.assert_array_equal(got, np.sort(x))
    assert (tr.counters.get("exchange_retries", 0) >= 1
            or tr.counters.get("sample_skew_fallback", 0) >= 1), tr.counters
    # the run must also have passed its own verification
    assert tr.counters.get("verify_runs", 0) >= 1
    assert tr.counters.get("verify_failures", 0) == 0


def test_tiny_cap_retry_with_staged_donated_ingest(mesh8, rng, monkeypatch):
    """Tiny cap + donation + streamed StagedIngest input: the overflow
    retry must re-stream from the staged source (PR 2's donated-buffer
    re-stage) and still verify.  Negotiation pinned off: see
    test_forced_tiny_cap_overflow_retry."""
    monkeypatch.setenv("SORT_NEGOTIATE", "off")
    monkeypatch.setenv("SORT_DONATE", "1")
    monkeypatch.setenv("SORT_INGEST", "stream")
    monkeypatch.setenv("SORT_INGEST_CHUNK", "8192")
    from mpitest_tpu.models.api import ingest_to_mesh
    from mpitest_tpu.utils.trace import Tracer

    x = rng.integers(-(2**31), 2**31 - 1, size=50_000, dtype=np.int32)
    st = ingest_to_mesh(x, mesh=mesh8)
    tr = Tracer()
    got = sort(st, algorithm="radix", cap_factor=1e-9, tracer=tr)
    np.testing.assert_array_equal(got, np.sort(x))
    assert tr.counters.get("exchange_retries", 0) >= 1
    assert tr.counters.get("verify_failures", 0) == 0


def test_streamed_egress_matches_legacy(mesh8, rng, monkeypatch):
    """Streamed egress (decode overlapping shard fetches) returns the
    same bytes as the legacy whole-result gather."""
    x = rng.integers(-(2**31), 2**31 - 1, size=30_000, dtype=np.int32)
    res = sort(x, algorithm="radix", mesh=mesh8, return_result=True)
    monkeypatch.setenv("SORT_INGEST", "mono")
    legacy = res.to_numpy()
    monkeypatch.setenv("SORT_INGEST", "stream")
    streamed = res.to_numpy()
    np.testing.assert_array_equal(legacy, streamed)
    np.testing.assert_array_equal(streamed, np.sort(x))
