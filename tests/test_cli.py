"""CLI contract tests: the reference's argv/stdout/stderr interface.

Run in-process (importing drivers/sort_cli) against the virtual CPU mesh —
a subprocess per case would pay the full JAX startup each time.
"""

import importlib.util
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "drivers"))

spec = importlib.util.spec_from_file_location(
    "sort_cli", os.path.join(os.path.dirname(__file__), "..", "drivers", "sort_cli.py")
)
sort_cli = importlib.util.module_from_spec(spec)
spec.loader.exec_module(sort_cli)


@pytest.fixture
def keyfile(tmp_path, rng):
    keys = rng.integers(-(2**31), 2**31 - 1, size=1000, dtype=np.int32)
    p = tmp_path / "keys.txt"
    p.write_text("\n".join(str(k) for k in keys) + "\n")
    return str(p), keys


def test_usage_error(capsys):
    assert sort_cli.main(["sort_cli.py"]) != 0
    assert "Usage:" in capsys.readouterr().err


def test_bad_file(capsys):
    assert sort_cli.main(["sort_cli.py", "/nonexistent/file.txt"]) != 0
    err = capsys.readouterr().err
    assert "is not a valid file for read." in err


@pytest.mark.parametrize("algo", ["sample", "radix"])
def test_output_contract(algo, keyfile, capsys, monkeypatch):
    path, keys = keyfile
    monkeypatch.setenv("SORT_ALGO", algo)
    assert sort_cli.main(["sort_cli.py", path]) == 0
    out = capsys.readouterr()
    ref = np.sort(keys)
    lines = out.out.strip().splitlines()
    if algo == "sample":
        assert lines[0] == f"Each bucket will be put {-(-1000 // 8)} items."
    assert lines[-1] == f"The n/2-th sorted element: {ref[499]}"
    assert "Endtime()-Starttime() = " in out.err
    assert out.err.strip().endswith("sec")


@pytest.mark.parametrize("dtype", ["float32", "float64"])
def test_float_cli_roundtrip(dtype, tmp_path, capsys, monkeypatch, rng):
    """SORT_DTYPE=float32/float64 through the TEXT path end-to-end
    (VERDICT r3 weak #4): tokens parse as floats (not through the int64
    intermediate), the sort is bit-exact, and the median line prints a
    shortest-unique decimal that round-trips to the exact bits — int
    truncation would collide distinct float medians."""
    from mpitest_tpu.utils.io import read_keys_text, write_keys_text

    dt = np.dtype(dtype)
    keys = (rng.standard_normal(1001) * 10.0 **
            rng.integers(-20, 20, size=1001)).astype(dt)
    keys[:3] = [0.0, -0.0, 1e-40]  # signed zero + denormal survive text
    p = tmp_path / "fkeys.txt"
    write_keys_text(str(p), keys)
    # the text round-trip itself is bit-exact for finite keys
    back = read_keys_text(str(p), dtype=dt)
    np.testing.assert_array_equal(back.view(np.uint32 if dt.itemsize == 4
                                            else np.uint64),
                                  keys.view(np.uint32 if dt.itemsize == 4
                                            else np.uint64))
    monkeypatch.setenv("SORT_ALGO", "radix")
    monkeypatch.setenv("SORT_DTYPE", dtype)
    assert sort_cli.main(["sort_cli.py", str(p)]) == 0
    out = capsys.readouterr()
    last = out.out.strip().splitlines()[-1]
    assert last.startswith("The n/2-th sorted element: ")
    printed = last.removeprefix("The n/2-th sorted element: ")
    # expectation in the framework's own totalOrder (np.sort ties
    # -0.0/0.0 arbitrarily; totalOrder does not)
    from mpitest_tpu.ops.keys import codec_for

    order = np.lexsort(tuple(reversed(codec_for(dt).encode(keys))))
    want = keys[order][1001 // 2 - 1]
    # the printed decimal round-trips to the exact median bits
    assert np.array([float(printed)], dtype=dt)[0].tobytes() == want.tobytes()
    assert "Endtime()-Starttime() = " in out.err


def test_debug2_protocol_lines(keyfile, capsys, monkeypatch):
    """debug>=2 per-rank lines match the reference's prefix vocabulary:
    [COMMON] Working r/P for every rank (mpi_sample_sort.c:30), [MASTER]
    read lines (:42,62), [SLAVE] per-rank protocol lines (:68)."""
    path, _ = keyfile
    monkeypatch.setenv("SORT_ALGO", "sample")
    assert sort_cli.main(["sort_cli.py", path, "2"]) == 0
    out = capsys.readouterr().out
    for r in range(8):
        assert f"[COMMON] Working {r}/8" in out
    assert f"[MASTER] Read file: {path}" in out
    assert "[MASTER] File read OK, 1000 numbers " in out
    for r in range(1, 8):
        assert f"[SLAVE] {r} Recv(size_input): 1000" in out


def test_metrics_sidecar_env(keyfile, capsys, monkeypatch, tmp_path):
    """SORT_METRICS=<path> appends one JSON line with phases, throughput,
    exchange bytes and achieved GB/s (SURVEY.md §5 metrics row)."""
    import json

    path, _ = keyfile
    sidecar = tmp_path / "metrics.jsonl"
    monkeypatch.setenv("SORT_ALGO", "radix")
    monkeypatch.setenv("SORT_METRICS", str(sidecar))
    assert sort_cli.main(["sort_cli.py", path]) == 0
    capsys.readouterr()
    lines = sidecar.read_text().strip().splitlines()
    assert len(lines) == 1
    obj = json.loads(lines[0])
    assert obj["config"]["algo"] == "radix" and obj["config"]["ranks"] == 8
    m = obj["metrics"]
    assert m["sort_mkeys_per_s"]["value"] > 0
    assert m["exchange_bytes"]["value"] > 0
    assert m["exchange_gb_per_s"]["unit"] == "GB/s"
    assert any(k.startswith("phase_") for k in m)


def test_cap_factor_oversample_knobs(keyfile, capsys, monkeypatch, tmp_path):
    """SORT_CAP_FACTOR / SORT_OVERSAMPLE reach the sort (visible in the
    metrics sidecar's exchange_cap) and keep the contract intact.
    Negotiation pinned off: with it on, the cap comes from the measured
    count probe and cap_factor is (by design, ISSUE 7) not the driver."""
    import json

    path, keys = keyfile
    sidecar = tmp_path / "m.jsonl"
    monkeypatch.setenv("SORT_NEGOTIATE", "off")
    monkeypatch.setenv("SORT_ALGO", "sample")
    monkeypatch.setenv("SORT_METRICS", str(sidecar))
    monkeypatch.setenv("SORT_CAP_FACTOR", "6.0")
    monkeypatch.setenv("SORT_OVERSAMPLE", "31")
    assert sort_cli.main(["sort_cli.py", path]) == 0
    out = capsys.readouterr()
    assert f"The n/2-th sorted element: {np.sort(keys)[499]}" in out.out
    cap6 = json.loads(sidecar.read_text())["metrics"]["exchange_cap"]["value"]
    # shard n=125, fair share ceil(125/8)=16: factor 6 -> 94+1 -> cap 128
    # either way (alignment floor), so compare against factor 40 instead
    monkeypatch.setenv("SORT_CAP_FACTOR", "40.0")
    sidecar.unlink()
    assert sort_cli.main(["sort_cli.py", path]) == 0
    capsys.readouterr()
    cap40 = json.loads(sidecar.read_text())["metrics"]["exchange_cap"]["value"]
    assert cap40 > cap6


@pytest.mark.parametrize("knob,value", [
    ("SORT_DTYPE", "garbage"),
    ("SORT_DTYPE", "complex64"),
    ("SORT_ALGO", "quicksort"),
    ("SORT_DIGIT_BITS", "garbage"),
    ("SORT_DIGIT_BITS", "0"),
    ("SORT_DIGIT_BITS", "33"),
    ("SORT_RANKS", "zero"),
    ("SORT_RANKS", "-3"),
    ("SORT_CAP_FACTOR", "garbage"),
    ("SORT_CAP_FACTOR", "nan"),
    ("SORT_CAP_FACTOR", "inf"),
    ("SORT_DTYPE", ","),  # np.dtype(',') raises SyntaxError, not TypeError
    ("SORT_OVERSAMPLE", "garbage"),
])
def test_env_knob_garbage_fails_cleanly(knob, value, keyfile, capsys,
                                        monkeypatch):
    """Garbage in ANY env knob is one `[ERROR]` line + nonzero exit —
    the reference's fail-fast stderr contract
    (mpi_sample_sort.c:46-48,230-234), never a traceback (VERDICT r4
    weak #5 reproduced `SORT_DTYPE=garbage` dying in a raw np.dtype
    traceback)."""
    path, _ = keyfile
    monkeypatch.setenv(knob, value)
    assert sort_cli.main(["sort_cli.py", path]) != 0
    out = capsys.readouterr()
    assert out.err.startswith("[ERROR] "), out.err
    assert len(out.err.strip().splitlines()) == 1
    # per-knob contract: the message names the offending knob AND echoes
    # the offending value (the round-5 satellite split the old combined
    # SORT_CAP_FACTOR/SORT_OVERSAMPLE message)
    assert knob in out.err
    assert repr(value) in out.err or value in out.err


def test_sort_trace_and_chrome_export_cli(tmp_path, capsys, monkeypatch, rng):
    """SORT_TRACE streams a schema-clean span JSONL and SORT_TRACE_CHROME
    writes loadable Chrome trace-event JSON from one CLI run — the
    driver end of the ISSUE 1 telemetry layer.  Fresh N so the program
    compiles in-run (collective spans are per-compile trace-time
    records)."""
    import json

    from mpitest_tpu import report

    keys = rng.integers(-(2**31), 2**31 - 1, size=1013, dtype=np.int32)
    p = tmp_path / "keys.txt"
    p.write_text("\n".join(str(k) for k in keys) + "\n")
    trace = tmp_path / "trace.jsonl"
    chrome = tmp_path / "trace_chrome.json"
    monkeypatch.setenv("SORT_ALGO", "radix")
    monkeypatch.setenv("SORT_TRACE", str(trace))
    monkeypatch.setenv("SORT_TRACE_CHROME", str(chrome))
    assert sort_cli.main(["sort_cli.py", str(p)]) == 0
    capsys.readouterr()
    rows = report.load_rows(str(trace))
    assert report.check_rows(rows) == []
    names = {r["name"] for r in rows}
    assert {"sort", "radix_pass", "ragged_all_to_all"} <= names
    ct = json.loads(chrome.read_text())
    assert ct["traceEvents"] and any(e.get("ph") == "X"
                                     for e in ct["traceEvents"])


def test_debug_dump_sorted(keyfile, capsys, monkeypatch):
    path, keys = keyfile
    monkeypatch.setenv("SORT_ALGO", "radix")
    assert sort_cli.main(["sort_cli.py", path, "3"]) == 0
    out = capsys.readouterr().out
    dump = [
        int(line.split("|")[1])
        for line in out.splitlines()
        if "|" in line and not line.startswith("[")
    ]
    expect = [int(v) & 0xFFFFFFFF for v in np.sort(keys)]
    assert dump == expect


def test_profile_hook_produces_artifacts(keyfile, capsys, monkeypatch, tmp_path):
    """SORT_PROFILE=<dir> captures a real jax.profiler trace around the
    sort — verified by artifact presence, not just by the hook running
    (observability row, SURVEY.md §5)."""
    path, keys = keyfile
    logdir = tmp_path / "prof"
    monkeypatch.setenv("SORT_PROFILE", str(logdir))
    monkeypatch.setattr(sys, "argv", ["sort_cli.py", path])
    assert sort_cli.main() == 0
    capsys.readouterr()
    artifacts = list(logdir.rglob("*.xplane.pb"))
    assert artifacts, f"no profiler artifacts under {logdir}"
