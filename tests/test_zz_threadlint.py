"""Tests for the interprocedural concurrency analyzer (ISSUE 19):
threadlint rules on good/bad fixture programs, the thread/lock
vocabulary's contracts, the shared registry loader, the C-side
blocking-under-mutex twin in comm_parity, and the repo-wide dogfood
run.

Named ``test_zz_*`` to sort LAST: tier-1 is timeout-bound, and
everything here is pure ast/text work (no jit compiles, no jax
import), so the whole module stays in low single-digit seconds.
"""

from __future__ import annotations

import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from tools import comm_parity  # noqa: E402
from tools.registry_load import load_registry_module  # noqa: E402
from tools.threadlint import (  # noqa: E402
    DEFAULT_TARGETS, LINT_VERSION, RULES, Lock, Registry, Root,
    lint_files, lint_repo, load_default_registry)
from tools.threadlint.__main__ import selftest  # noqa: E402


def rules_of(findings):
    return sorted({f.rule for f in findings})


def reg(**kw):
    """Synthetic vocabulary builder with fixture-friendly defaults."""
    kw.setdefault("blocking_calls", {"os.fsync": "fsync",
                                     "time.sleep": "sleep"})
    return Registry(**kw)


# --------------------------------------------------------- TL001 fence

JAX_BAD = """\
import threading
import jax

def work(x):
    jax.device_put(x)

def start():
    threading.Thread(target=work).start()
"""


def test_tl001_jax_from_non_jax_ok_root():
    r = reg(roots=[Root("bg", "thread", "app.work", False)])
    fs = lint_files({"app.py": JAX_BAD}, r)
    assert "TL001" in rules_of(fs)
    assert any("bg" in f.msg for f in fs if f.rule == "TL001")


def test_tl001_clean_when_root_is_jax_ok():
    r = reg(roots=[Root("dispatch", "thread", "app.work", True)])
    assert "TL001" not in rules_of(lint_files({"app.py": JAX_BAD}, r))


def test_tl001_interprocedural_and_lambda():
    # the jax touch is two hops away, reached through a helper that
    # runs a lambda — resolution must survive both
    src = (
        "import threading\n"
        "import jax\n"
        "def guarded(thunk):\n"
        "    return thunk()\n"
        "def inner(x):\n"
        "    return jax.device_put(x)\n"
        "def loop():\n"
        "    guarded(lambda: inner(1))\n"
        "def start():\n"
        "    threading.Thread(target=loop).start()\n")
    r = reg(roots=[Root("bg", "thread", "app.loop", False)])
    assert "TL001" in rules_of(lint_files({"app.py": src}, r))


# ---------------------------------------------------- TL002 lock order

CYCLE = """\
import threading

A = threading.Lock()
B = threading.Lock()

def one():
    with A:
        with B:
            pass

def other():
    with B:
        with A:
            pass
"""


def test_tl002_synthetic_cycle_and_rank_inversion():
    r = reg(roots=[Root("r1", "thread", "m.one", False),
                   Root("r2", "thread", "m.other", False)],
            locks=[Lock("a", 10, "m.A"), Lock("b", 20, "m.B")])
    fs = [f for f in lint_files({"m.py": CYCLE}, r) if f.rule == "TL002"]
    assert fs, "cycle must fire TL002"
    msgs = " | ".join(f.msg for f in fs)
    assert "cycle" in msgs and "rank" in msgs


def test_tl002_rank_ordered_nesting_is_clean():
    src = ("import threading\n"
           "A = threading.Lock()\n"
           "B = threading.Lock()\n"
           "def fine():\n"
           "    with A:\n"
           "        with B:\n"
           "            pass\n")
    r = reg(roots=[Root("r", "thread", "m.fine", False)],
            locks=[Lock("a", 10, "m.A"), Lock("b", 20, "m.B")])
    assert "TL002" not in rules_of(lint_files({"m.py": src}, r))


def test_tl002_reacquire_needs_reentrant_registration():
    src = ("import threading\n"
           "L = threading.Lock()\n"
           "def outer():\n"
           "    with L:\n"
           "        inner()\n"
           "def inner():\n"
           "    with L:\n"
           "        pass\n")
    plain = reg(roots=[Root("r", "thread", "m.outer", False)],
                locks=[Lock("l", 10, "m.L")])
    assert "TL002" in rules_of(lint_files({"m.py": src}, plain))
    rlock = reg(roots=[Root("r", "thread", "m.outer", False)],
                locks=[Lock("l", 10, "m.L", reentrant=True)])
    assert "TL002" not in rules_of(lint_files({"m.py": src}, rlock))


def test_tl002_interprocedural_edge():
    # the nesting spans a call: outer holds A, callee takes B, B<A rank
    src = ("import threading\n"
           "A = threading.Lock()\n"
           "B = threading.Lock()\n"
           "def outer():\n"
           "    with A:\n"
           "        helper()\n"
           "def helper():\n"
           "    with B:\n"
           "        pass\n")
    r = reg(roots=[Root("r", "thread", "m.outer", False)],
            locks=[Lock("a", 20, "m.A"), Lock("b", 10, "m.B")])
    fs = [f for f in lint_files({"m.py": src}, r) if f.rule == "TL002"]
    assert fs and "rank inversion" in fs[0].msg


# ---------------------------------------- TL003 blocking under lock

def test_tl003_fsync_under_lock_fires_outside_clean():
    bad = ("import threading\nimport os\n"
           "L = threading.Lock()\n"
           "def flush(fd):\n"
           "    with L:\n"
           "        os.fsync(fd)\n")
    good = ("import threading\nimport os\n"
            "L = threading.Lock()\n"
            "def flush(fd):\n"
            "    with L:\n"
            "        pass\n"
            "    os.fsync(fd)\n")
    r = reg(locks=[Lock("l", 10, "m.L")])
    assert "TL003" in rules_of(lint_files({"m.py": bad}, r))
    assert "TL003" not in rules_of(lint_files({"m.py": good}, r))


def test_tl003_compile_under_lock_interprocedural():
    # compile reached through a call while the caller holds the lock —
    # the PR 15 _build_detached invariant as a fixture
    src = ("import threading\n"
           "L = threading.Lock()\n"
           "def compile_sort(key):\n"
           "    return key\n"
           "def get(key):\n"
           "    with L:\n"
           "        return build(key)\n"
           "def build(key):\n"
           "    return compile_sort(key)\n")
    r = reg(roots=[Root("r", "thread", "m.get", True)],
            locks=[Lock("l", 10, "m.L")],
            compile_funcs=("m.compile_sort",))
    fs = [f for f in lint_files({"m.py": src}, r) if f.rule == "TL003"]
    assert fs and "XLA compile" in fs[0].msg


def test_tl003_reasoned_suppression_severs_propagation():
    # suppressing the reviewed call site must also silence the SAME
    # hazard at interior blocking touches reached through that call
    src = ("import threading\nimport time\n"
           "L = threading.Lock()\n"
           "def get(key):\n"
           "    with L:\n"
           "        # threadlint: disable=TL003 -- reviewed hold\n"
           "        return build(key)\n"
           "def build(key):\n"
           "    time.sleep(0.1)\n")
    r = reg(roots=[Root("r", "thread", "m.get", True)],
            locks=[Lock("l", 10, "m.L")])
    assert "TL003" not in rules_of(lint_files({"m.py": src}, r))


# ------------------------------------------- TL004 shared-write lockset

SHARED = """\
import threading

class Cell:
    def __init__(self):
        self.value = 0
        self.lock = threading.Lock()

    def writer_a(self):
        {a}

    def writer_b(self):
        {b}

def start(c):
    threading.Thread(target=c.writer_a).start()
    threading.Thread(target=c.writer_b).start()
"""


def _shared_reg():
    return reg(roots=[Root("wa", "thread", "m.Cell.writer_a", False),
                      Root("wb", "thread", "m.Cell.writer_b", False)],
               locks=[Lock("cell", 10, "m.Cell.lock")])


def test_tl004_two_roots_no_common_lock():
    src = SHARED.format(a="self.value = 1", b="self.value = 2")
    fs = [f for f in lint_files({"m.py": src}, _shared_reg())
          if f.rule == "TL004"]
    assert fs and "m.Cell.value" in fs[0].msg


def test_tl004_common_lock_on_every_path_is_clean():
    src = SHARED.format(
        a="with self.lock:\n            self.value = 1",
        b="with self.lock:\n            self.value = 2")
    assert "TL004" not in rules_of(
        lint_files({"m.py": src}, _shared_reg()))


def test_tl004_one_unlocked_path_still_fires():
    src = SHARED.format(
        a="with self.lock:\n            self.value = 1",
        b="self.value = 2")
    assert "TL004" in rules_of(lint_files({"m.py": src}, _shared_reg()))


def test_tl004_atomic_ok_exemption():
    src = SHARED.format(a="self.value = 1", b="self.value = 2")
    r = reg(roots=[Root("wa", "thread", "m.Cell.writer_a", False),
                   Root("wb", "thread", "m.Cell.writer_b", False)],
            locks=[Lock("cell", 10, "m.Cell.lock")],
            atomic_ok=("m.Cell.value",))
    assert "TL004" not in rules_of(lint_files({"m.py": src}, r))


def test_tl004_init_and_fresh_locals_are_confined():
    # __init__ writes and writes through a same-function constructor
    # call are thread-confined, not shared state
    src = ("import threading\n"
           "class Box:\n"
           "    def __init__(self):\n"
           "        self.n = 0\n"
           "def parse():\n"
           "    b = Box()\n"
           "    b.n = 41\n"
           "    return b\n"
           "def also_parse():\n"
           "    b = Box()\n"
           "    b.n = 42\n"
           "    return b\n"
           "def start():\n"
           "    threading.Thread(target=parse).start()\n"
           "    threading.Thread(target=also_parse).start()\n")
    r = reg(roots=[Root("p1", "thread", "m.parse", False),
                   Root("p2", "thread", "m.also_parse", False)])
    assert "TL004" not in rules_of(lint_files({"m.py": src}, r))


def test_tl004_module_global_writes():
    src = ("import threading\n"
           "_cache = None\n"
           "def fill_a():\n"
           "    global _cache\n"
           "    _cache = 1\n"
           "def fill_b():\n"
           "    global _cache\n"
           "    _cache = 2\n"
           "def start():\n"
           "    threading.Thread(target=fill_a).start()\n"
           "    threading.Thread(target=fill_b).start()\n")
    r = reg(roots=[Root("a", "thread", "m.fill_a", False),
                   Root("b", "thread", "m.fill_b", False)])
    fs = [f for f in lint_files({"m.py": src}, r) if f.rule == "TL004"]
    assert fs and "m._cache" in fs[0].msg


# ------------------------------------------------- TL005 GIL wedge

def test_tl005_wedge_call_outside_probe_home():
    src = ("def peek(client):\n"
           "    return client.get_topology_desc()\n")
    r = reg(gil_wedge_calls=("get_topology_desc",),
            gil_wedge_home=("pkg/probe.py",))
    assert "TL005" in rules_of(lint_files({"pkg/other.py": src}, r))
    assert "TL005" not in rules_of(lint_files({"pkg/probe.py": src}, r))


# ------------------------------------------- TL010/TL011 vocabulary

def test_tl010_unregistered_thread_and_bare_pool():
    src = ("import threading\n"
           "from concurrent.futures import ThreadPoolExecutor\n"
           "def job():\n"
           "    pass\n"
           "def start():\n"
           "    threading.Thread(target=job).start()\n"
           "    ex = ThreadPoolExecutor(2)\n"
           "    ex.submit(job)\n")
    fs = lint_files({"m.py": src}, reg())
    msgs = [f.msg for f in fs if f.rule == "TL010"]
    assert len(msgs) == 3  # thread target, naked pool, submit target
    assert any("thread_name_prefix" in m for m in msgs)


def test_tl010_registered_sites_are_clean():
    src = ("import threading\n"
           "from concurrent.futures import ThreadPoolExecutor\n"
           "def job():\n"
           "    pass\n"
           "def start():\n"
           "    threading.Thread(target=job).start()\n"
           "    ex = ThreadPoolExecutor(2, thread_name_prefix='w')\n"
           "    ex.submit(job)\n")
    r = reg(roots=[Root("job", "thread", "m.job", False)])
    assert "TL010" not in rules_of(lint_files({"m.py": src}, r))


def test_tl010_handler_and_signal_entries():
    src = ("import signal\n"
           "import socketserver\n"
           "class H(socketserver.StreamRequestHandler):\n"
           "    def handle(self):\n"
           "        pass\n"
           "def on_term(sig, frame):\n"
           "    pass\n"
           "def install():\n"
           "    signal.signal(signal.SIGTERM, on_term)\n")
    fs = lint_files({"m.py": src}, reg())
    assert sum(1 for f in fs if f.rule == "TL010") == 2
    r = reg(roots=[Root("h", "handler", "m.H.handle", False),
                   Root("s", "signal", "m.on_term", False)])
    assert "TL010" not in rules_of(lint_files({"m.py": src}, r))


def test_tl011_unregistered_lock_and_condition_alias():
    bad = "import threading\nSTRAY = threading.Lock()\n"
    assert rules_of(lint_files({"m.py": bad}, reg())) == ["TL011"]
    # a Condition wrapping a registered lock aliases it — no finding
    src = ("import threading\n"
           "class A:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self._idle = threading.Condition(self._lock)\n")
    r = reg(locks=[Lock("a", 10, "m.A._lock")])
    assert "TL011" not in rules_of(lint_files({"m.py": src}, r))


# ------------------------------------------- suppression grammar

def test_suppression_reasoned_works_reasonless_is_tl000():
    reasoned = ("import threading\n"
                "L = threading.Lock()"
                "  # threadlint: disable=TL011 -- fixture lock\n")
    assert rules_of(lint_files({"m.py": reasoned}, reg())) == []
    bare = ("import threading\n"
            "L = threading.Lock()  # threadlint: disable=TL011\n")
    fs = lint_files({"m.py": bare}, reg())
    assert rules_of(fs) == ["TL000", "TL011"], \
        "reasonless directive must not suppress AND must fire TL000"


def test_suppression_line_above_and_wrong_id():
    above = ("import threading\n"
             "# threadlint: disable=TL011 -- fixture lock\n"
             "L = threading.Lock()\n")
    assert rules_of(lint_files({"m.py": above}, reg())) == []
    wrong = ("import threading\n"
             "L = threading.Lock()"
             "  # threadlint: disable=TL003 -- wrong id\n")
    assert "TL011" in rules_of(lint_files({"m.py": wrong}, reg()))


# ------------------------------------------------ vocabulary contracts

def test_vocabulary_pins():
    mod = load_registry_module(
        "_test_thread_registry",
        REPO / "mpitest_tpu" / "utils" / "thread_registry.py",
        register=True)
    names = [r.name for r in mod.THREAD_ROOTS]
    entries = [r.entry for r in mod.THREAD_ROOTS]
    assert len(set(names)) == len(names), "root names must be unique"
    assert len(set(entries)) == len(entries), "entries must be unique"
    for r in mod.THREAD_ROOTS:
        assert r.kind in mod.ROOT_KINDS
        assert r.doc.strip(), f"root {r.name} needs a doc"
    # the jax_ok grant list is closed and audited — additions are a
    # REVIEWED act, so pin the exact set
    assert {r.name for r in mod.THREAD_ROOTS if r.jax_ok} == {
        "serve-dispatch", "serve-tuner-prewarm", "ingest-xfer",
        "egress-fetch", "server-main"}
    ranks = [l.rank for l in mod.LOCKS]
    sites = [l.site for l in mod.LOCKS]
    assert len(set(ranks)) == len(ranks), "lock ranks must be unique"
    assert len(set(sites)) == len(sites), "lock sites must be unique"
    for l in mod.LOCKS:
        assert l.doc.strip(), f"lock {l.name} needs a doc"
    # the only reentrant lock today is the flight ring
    assert [l.name for l in mod.LOCKS if l.reentrant] == ["flight.ring"]
    # alias targets must be registered sites
    for target in mod.LOCK_ALIASES.values():
        assert target in sites


def test_registry_rejects_duplicates():
    with pytest.raises(ValueError):
        Registry(roots=[Root("a", "thread", "m.f", False),
                        Root("b", "thread", "m.f", False)])
    with pytest.raises(ValueError):
        Registry(locks=[Lock("a", 1, "m.L"), Lock("b", 2, "m.L")])


def test_default_targets_exclude_tests_and_tools():
    assert "tests" not in DEFAULT_TARGETS
    assert "tools" not in DEFAULT_TARGETS


# -------------------------------------------------- shared loader

def test_load_registry_module(tmp_path):
    p = tmp_path / "my_registry.py"
    p.write_text("VALUE = 41\n")
    mod = load_registry_module("_test_loader_mod", p)
    assert mod.VALUE == 41
    assert "_test_loader_mod" not in sys.modules
    mod2 = load_registry_module("_test_loader_reg", p, register=True)
    assert sys.modules["_test_loader_reg"] is mod2
    del sys.modules["_test_loader_reg"]
    with pytest.raises(FileNotFoundError):
        load_registry_module("_test_loader_nope", tmp_path / "no.py")


# ---------------------------------------- comm_parity C-side twin

C_BAD = """\
static pthread_mutex_t stats_mu;
void tally(void) {
    pthread_mutex_lock(&stats_mu);
    comm_barrier(world);
    pthread_mutex_unlock(&stats_mu);
}
"""

C_GOOD = """\
static pthread_mutex_t stats_mu;
void tally(void) {
    pthread_mutex_lock(&stats_mu);
    stats.n += 1;
    pthread_mutex_unlock(&stats_mu);
    comm_barrier(world);
}
"""

C_ESCAPED = """\
static pthread_mutex_t stats_mu;
void tally(void) {
    pthread_mutex_lock(&stats_mu);
    /* parity: ok -- bounded: peers already arrived (handshake) */
    comm_barrier(world);
    pthread_mutex_unlock(&stats_mu);
}
"""


def test_c_mutex_blocking_collective():
    bad = comm_parity.check_mutex_blocking_collectives(C_BAD, "x.c")
    assert len(bad) == 1 and "comm_barrier" in bad[0] \
        and "stats_mu" in bad[0]
    assert comm_parity.check_mutex_blocking_collectives(
        C_GOOD, "x.c") == []
    assert comm_parity.check_mutex_blocking_collectives(
        C_ESCAPED, "x.c") == []


def test_c_mutex_twin_covers_mpi_and_barrier_surface():
    src = ("void f(void) {\n"
           "    pthread_mutex_lock(&mu);\n"
           "    MPI_Allreduce(a, b, 1, MPI_INT, MPI_SUM, comm);\n"
           "    pthread_barrier_wait(&bar);\n"
           "    pthread_mutex_unlock(&mu);\n"
           "}\n")
    out = comm_parity.check_mutex_blocking_collectives(src, "x.c")
    assert len(out) == 2


def test_real_backends_have_no_mutex_blocking_findings():
    for backend in ("comm/comm_local.c", "comm/comm_mpi.c"):
        src = (REPO / backend).read_text()
        assert comm_parity.check_mutex_blocking_collectives(
            src, backend) == []


# ------------------------------------------------------- dogfood

def test_repo_lints_clean():
    findings = lint_repo(REPO)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_selftest_every_rule_fires(capsys):
    assert selftest() == 0
    out = capsys.readouterr().out
    assert "all 8 rules fire" in out


def test_rule_table_matches_version():
    assert LINT_VERSION == "threadlint.v1"
    assert set(RULES) == {"TL000", "TL001", "TL002", "TL003", "TL004",
                          "TL005", "TL010", "TL011", "TL999"}


def test_real_registry_loads_and_traverses():
    # the default registry must normalize and every serve-layer root
    # must resolve to a real function in the program
    registry = load_default_registry(REPO)
    assert "mpitest_tpu.serve.batching.Batcher._loop" in registry.roots
    assert registry.roots[
        "mpitest_tpu.serve.batching.Batcher._loop"].jax_ok
