"""Out-of-core store tests (ISSUE 15): run format, k-way merge,
external sort, record sorts, and the serve payload/spill wire path."""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np
import pytest

from mpitest_tpu.models import records
from mpitest_tpu.models.supervisor import SortIntegrityError
from mpitest_tpu.store import aio, external
from mpitest_tpu.store import merge as mergelib
from mpitest_tpu.store import runs as runlib
from mpitest_tpu.utils import knobs

ALL_DTYPES = ("int32", "uint32", "int64", "uint64", "float32", "float64")


def _keys(rng, dtype, n):
    dt = np.dtype(dtype)
    if dt.kind == "f":
        return (rng.standard_normal(n) * 10.0
                ** rng.integers(-10, 10, n)).astype(dt)
    info = np.iinfo(dt)
    return rng.integers(info.min, info.max, n, dtype=dt)


def _merge_to_array(infos, chunk=97):
    codec = runlib.codec_for(infos[0].dtype)
    kparts, pparts = [], []
    for kws, pws in mergelib.merge_runs(infos, chunk):
        kparts.append(codec.decode(kws))
        if pws:
            pparts.append(records.words_to_payload(
                pws, int(kws[0].size), infos[0].payload_width))
    keys = (np.concatenate(kparts) if kparts
            else np.empty(0, infos[0].dtype))
    pay = np.concatenate(pparts) if pparts else None
    return keys, pay


# ---------------------------------------------------------------- runs

@pytest.mark.parametrize("dtype", ALL_DTYPES)
def test_run_roundtrip_and_sidecar(tmp_path, rng, dtype):
    keys = np.sort(_keys(rng, dtype, 5000))
    info = runlib.write_run(str(tmp_path), f"r_{dtype}", keys)
    ri = runlib.open_run(info.path)
    assert ri.n == 5000 and ri.dtype == np.dtype(dtype)
    assert ri.fingerprint == info.fingerprint
    back = np.concatenate([np.array(k) for k, _p in
                           runlib.read_run_chunks(ri, 700)])
    assert np.array_equal(back, keys)
    assert runlib.verify_run(ri, chunk_elems=512)


def test_run_roundtrip_with_payload(tmp_path, rng):
    n = 3000
    keys = _keys(rng, np.int64, n)
    pay = rng.integers(0, 256, (n, 5), dtype=np.uint8)
    order = np.argsort(keys, kind="stable")
    info = runlib.write_run(str(tmp_path), "rp", keys[order], pay[order])
    ri = runlib.open_run(info.path)
    assert ri.payload_width == 5
    ks, ps = [], []
    for k, p in runlib.read_run_chunks(ri, 999):
        ks.append(np.array(k))
        ps.append(np.array(p))
    assert np.array_equal(np.concatenate(ks), keys[order])
    assert np.array_equal(np.concatenate(ps), pay[order])
    assert runlib.verify_run(ri)


def test_truncated_run_is_typed(tmp_path, rng):
    # raw-framing drill: pin compress=False so the open-time body-size
    # check (raw-specific; compressed damage types at READ time as
    # BlockIntegrityError instead) is what trips
    keys = np.sort(_keys(rng, np.int32, 1000))
    info = runlib.write_run(str(tmp_path), "t", keys, compress=False)
    with open(info.path, "r+b") as f:   # sortlint: disable=SL014 -- the test IS the corruption drill
        f.truncate(os.path.getsize(info.path) - 8)
    with pytest.raises(runlib.RunFormatError, match="truncated|bytes"):
        runlib.open_run(info.path)


def test_garbage_sidecar_is_typed(tmp_path, rng):
    keys = np.sort(_keys(rng, np.int32, 100))
    info = runlib.write_run(str(tmp_path), "g", keys)
    with open(info.sidecar_path, "w") as f:  # sortlint: disable=SL014 -- corruption drill
        json.dump({"v": "wrong"}, f)
    with pytest.raises(runlib.RunFormatError, match="schema"):
        runlib.open_run(info.path)


def test_corrupt_run_fails_verify_and_merge(tmp_path, rng):
    # raw-framing drill (fold-vs-sidecar blame); the compressed twin
    # lives in the SORTRUN2 tests below
    keys = np.sort(_keys(rng, np.int32, 4000))
    info = runlib.write_run(str(tmp_path), "c", keys, compress=False)
    with open(info.path, "r+b") as f:  # sortlint: disable=SL014 -- corruption drill
        f.seek(runlib.kio.BIN_HEADER_LEN + 40)
        f.write(b"\xff\xff\xff\xfe")
    ri = runlib.open_run(info.path)
    assert not runlib.verify_run(ri)
    with pytest.raises(mergelib.RunIntegrityError):
        for _ in mergelib.merge_runs([ri], 512):
            pass


# --------------------------------------------------------------- merge

def test_merge_adversarial_shapes(tmp_path, rng):
    cases = {
        "dup_heavy": [rng.integers(0, 5, 4000, dtype=np.int32)
                      for _ in range(3)],
        "presorted": [np.arange(i * 1000, (i + 1) * 1000,
                                dtype=np.int32) for i in range(4)],
        "n_lt_runs": [np.array([i], dtype=np.int32) for i in range(6)],
        "empty_runs": [np.empty(0, np.int32),
                       rng.integers(-50, 50, 300, dtype=np.int32),
                       np.empty(0, np.int32)],
    }
    for name, arrays in cases.items():
        infos = [runlib.write_run(str(tmp_path), f"{name}_{i}",
                                  np.sort(a))
                 for i, a in enumerate(arrays)]
        got, _ = _merge_to_array(infos, chunk=37)
        want = np.sort(np.concatenate(arrays)) if arrays else \
            np.empty(0, np.int32)
        assert np.array_equal(got, want), name


def test_merge_is_stable_across_runs(tmp_path, rng):
    """Equal keys merge in (run, in-run position) order — the exact
    order the in-memory stable sort of the concatenated chunks gives,
    pinned via payloads that tag each record's origin."""
    n, runs_n = 2400, 4
    keys = rng.integers(0, 7, n, dtype=np.int32)   # heavy ties
    pay = np.arange(n, dtype=np.uint64).view(np.uint8).reshape(n, 8)
    infos = []
    per = n // runs_n
    for i in range(runs_n):
        k = keys[i * per:(i + 1) * per]
        p = pay[i * per:(i + 1) * per]
        order = np.argsort(k, kind="stable")
        infos.append(runlib.write_run(str(tmp_path), f"s{i}",
                                      k[order], p[order]))
    got_k, got_p = _merge_to_array(infos, chunk=53)
    order = np.argsort(keys, kind="stable")
    assert np.array_equal(got_k, keys[order])
    assert np.array_equal(got_p, pay[order])


# ------------------------------------------------------------- records

def test_payload_matrix_forms(rng):
    n = 10
    m = rng.integers(0, 256, (n, 3), dtype=np.uint8)
    assert np.array_equal(records.as_payload_matrix(m, n), m)
    assert np.array_equal(
        records.as_payload_matrix(m.tobytes(), n), m)
    ids = np.arange(n, dtype=np.uint64)
    assert records.as_payload_matrix(ids, n).shape == (n, 8)
    with pytest.raises(ValueError, match="multiple"):
        records.as_payload_matrix(b"12345", 2)
    with pytest.raises(ValueError, match="one element per record"):
        records.as_payload_matrix(np.arange(5), 3)


def test_payload_words_roundtrip(rng):
    for width in (1, 3, 4, 7, 8):
        pay = rng.integers(0, 256, (100, width), dtype=np.uint8)
        words = records.payload_to_words(pay)
        assert len(words) == records.payload_width_words(width)
        back = records.words_to_payload(words, 100, width)
        assert np.array_equal(back, pay)


@pytest.mark.parametrize("dtype", ("int32", "uint64", "float64"))
def test_sort_records_matches_stable_argsort(rng, dtype):
    keys = _keys(rng, dtype, 3000)
    keys[100:200] = keys[0]  # force ties: the stability contract
    pay = rng.integers(0, 256, (3000, 6), dtype=np.uint8)
    sk, sp = records.sort_records(keys, pay)
    order = np.argsort(keys, kind="stable")
    assert np.array_equal(sk, keys[order])
    assert np.array_equal(sp, pay[order])


def test_api_sort_payload_entry(rng):
    from mpitest_tpu.models import api

    keys = _keys(rng, np.int32, 1000)
    pay = rng.integers(0, 256, (1000, 4), dtype=np.uint8)
    sk, sp = api.sort(keys, payload=pay)
    order = np.argsort(keys, kind="stable")
    assert np.array_equal(sk, keys[order])
    assert np.array_equal(sp, pay[order])


def test_record_fingerprint_catches_pairing_swap(rng):
    """The binding mix word: swapping two records' payloads preserves
    both per-word multisets but must move the record fingerprint."""
    from mpitest_tpu.models import verify as vfy

    keys = np.arange(100, dtype=np.int32)
    pay = rng.integers(0, 256, (100, 4), dtype=np.uint8)
    kw = runlib.codec_for(np.dtype(np.int32)).encode(keys)
    pw = records.payload_to_words(pay)
    fp = vfy.fingerprint_records(kw, pw)
    swapped = pay.copy()
    swapped[[0, 1]] = swapped[[1, 0]]
    fp2 = vfy.fingerprint_records(
        kw, records.payload_to_words(swapped))
    assert fp != fp2


# ------------------------------------------------------------ external

def test_external_sort_matches_in_memory(tmp_path, rng):
    from mpitest_tpu.models import api

    x = _keys(rng, np.int32, 30_000)
    res = external.external_sort(x, budget=1 << 15,
                                 spill_dir=str(tmp_path))
    assert res.runs >= 4
    assert np.array_equal(res.keys, api.sort(x))
    assert np.array_equal(res.keys, np.sort(x))


def test_external_sort_file_sink(tmp_path, rng):
    x = _keys(rng, np.int32, 20_000)
    res = external.external_sort(x, budget=1 << 15,
                                 spill_dir=str(tmp_path), sink="file",
                                 out_name="out")
    assert res.out_run is not None and res.out_run.n == x.size
    views = runlib.run_body_views(res.out_run, unlink=True)
    got = np.frombuffer(views[0], np.int32)
    assert np.array_equal(got, np.sort(x))
    assert not os.path.exists(res.out_run.path)  # unlinked


def test_external_sort_text_file_streams(tmp_path, rng):
    from mpitest_tpu.utils.io import write_keys_text

    x = _keys(rng, np.int32, 20_000)
    p = tmp_path / "keys.txt"
    write_keys_text(str(p), x)
    res = external.external_sort_file(str(p), np.int32,
                                      budget=1 << 15,
                                      spill_dir=str(tmp_path / "s"))
    assert res.runs >= 2
    assert np.array_equal(res.keys, np.sort(x))


def test_external_recovery_and_typed_failure(tmp_path, rng):
    from mpitest_tpu import faults

    x = _keys(rng, np.int32, 20_000)
    reg = faults.FaultRegistry("merge_drop", seed=3)
    faults.install(reg)
    try:
        res = external.external_sort(x, budget=1 << 15,
                                     spill_dir=str(tmp_path / "a"))
        assert np.array_equal(res.keys, np.sort(x))
        assert reg.injected == 1 and res.recoveries == 1
    finally:
        faults.install(None)
    reg = faults.FaultRegistry("spill_corrupt:inf", seed=3)
    faults.install(reg)
    try:
        with pytest.raises(SortIntegrityError):
            external.external_sort(x, budget=1 << 15,
                                   spill_dir=str(tmp_path / "b"))
    finally:
        faults.install(None)


def test_external_requires_budget(rng):
    with pytest.raises(ValueError, match="budget"):
        external.external_sort(np.arange(10, dtype=np.int32), budget=0)
    with pytest.raises(ValueError, match="fan-in"):
        external.external_sort(np.arange(10, dtype=np.int32),
                               budget=1 << 20, fanin=1)


# ----------------------------------------------------------- serve wire

def test_serve_payload_and_spill_over_the_wire(tmp_path, rng):
    """The acceptance pair over a REAL socket: a payload_bytes record
    request round-trips bit-identical, and an over-admission request
    succeeds through the spill tier with ``spilled: true``."""
    from mpitest_tpu.serve.client import ServeClient
    from mpitest_tpu.serve.server import ServerCore, SortServer

    with knobs.scoped_env(SORT_SERVE_MAX_BYTES=str(1 << 14),
                          SORT_SERVE_BATCH_WINDOW_MS="0",
                          SORT_MEM_BUDGET=str(1 << 13),
                          SORT_SPILL_DIR=str(tmp_path / "spill"),
                          SORT_SERVE_PREWARM="off"):
        core = ServerCore()
        srv = SortServer(core, "127.0.0.1", 0)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            with ServeClient("127.0.0.1", srv.bound_port,
                             timeout=120.0) as c:
                n = 500
                keys = _keys(rng, np.int32, n)
                pay = rng.integers(0, 256, (n, 8), dtype=np.uint8)
                order = np.argsort(keys, kind="stable")
                rep = c.sort(keys, payload=pay)
                assert rep.ok and not rep.spilled
                assert np.array_equal(rep.arr, keys[order])
                assert np.array_equal(rep.payload, pay[order])

                big = _keys(rng, np.int32, 8000)  # 32 KB > 16 KiB
                rep = c.sort(big)
                assert rep.ok and rep.spilled
                assert np.array_equal(rep.arr, np.sort(big))
                assert rep.plan is not None and rep.plan.get("spilled")
        finally:
            srv.shutdown()
            srv.server_close()
            core.drain_and_stop(timeout=10.0)


def test_serve_spill_off_keeps_bytes_rejection(tmp_path, rng):
    from mpitest_tpu.serve.server import ServerCore

    with knobs.scoped_env(SORT_SERVE_MAX_BYTES=str(1 << 12),
                          SORT_SERVE_SPILL="off",
                          SORT_SERVE_BATCH_WINDOW_MS="0"):
        core = ServerCore()
        try:
            big = _keys(rng, np.int32, 4000)
            status, detail, attrs = core.execute(big)
            assert status == "backpressure"
            assert attrs.get("reject") == "bytes"
        finally:
            core.drain_and_stop(timeout=10.0)


def test_wire_bad_payload_bytes_is_typed(rng):
    import io

    from mpitest_tpu.serve.server import ServerCore

    core = ServerCore()
    try:
        hdr = {"v": "sortserve.v1", "dtype": "int32", "n": 4,
               "payload_bytes": -1}
        resp, _pay, keep = core.handle_wire(
            json.dumps(hdr).encode() + b"\n", io.BytesIO(b""))
        assert not resp["ok"] and resp["error"] == "bad_request"
        assert "payload_bytes" in resp["detail"]
    finally:
        core.drain_and_stop(timeout=10.0)


# ---------------------------------------------------- crash durability

def _plant_crash_state(spill_dir, x, budget, dataset, chunks):
    """Simulate a process killed mid-external-sort: the chunk indices
    in ``chunks`` durably committed + journaled (exactly the on-disk
    state the WAL discipline guarantees), everything else absent.
    Returns ``(chunk_elems, manifest_writer_path, run_infos)``."""
    from mpitest_tpu.store import manifest as mfstlib

    chunk = external.spill_chunk_elems(budget, x.dtype, 0)
    mw = mfstlib.ManifestWriter(str(spill_dir), dataset,
                                dtype=x.dtype.name, n=int(x.size),
                                payload_width=0, algorithm="auto",
                                chunk_elems=chunk, budget=budget,
                                fanin=16)
    infos = {}
    for ci in chunks:
        piece = np.sort(x[ci * chunk:(ci + 1) * chunk])
        infos[ci] = runlib.write_run(str(spill_dir), f"rdead_{ci:05d}",
                                     piece, durable=True)
        mw.commit_run(ci, infos[ci])
    mw.close()   # close the handle, NOT delete — the crash shape
    return chunk, mw.path, infos


def _external_span_counts(tracer):
    out = {}
    for line in tracer.spans.to_jsonl().splitlines():
        name = json.loads(line).get("name", "")
        if name.startswith("external."):
            out[name] = out.get(name, 0) + 1
    return out


@pytest.mark.parametrize("state", ("empty", "partial_line",
                                   "all_committed", "torn_run",
                                   "bitrot_run"))
def test_crash_grid_resumes_bit_identical(tmp_path, rng, state):
    """ISSUE 18 simulated-crash grid: each manifest state resumes (or
    degrades to a cold sort) with output bit-identical to an
    uninterrupted sort — and an all-committed journal re-enters at the
    merge phase with ZERO re-sorted chunks."""
    from mpitest_tpu.utils.trace import Tracer

    budget = 1 << 15
    x = _keys(rng, np.int32, 30_000)
    chunk = external.spill_chunk_elems(budget, x.dtype, 0)
    nchunks = -(-x.size // chunk)
    assert nchunks >= 3, "grid needs a multi-run sort"
    committed = {"empty": [], "partial_line": [0],
                 "all_committed": list(range(nchunks)),
                 "torn_run": list(range(nchunks)),
                 "bitrot_run": list(range(nchunks))}[state]
    _, mpath, infos = _plant_crash_state(tmp_path, x, budget, "ds1",
                                         committed)
    if state == "partial_line":
        with open(mpath, "ab") as f:   # torn tail: half a journal line
            f.write(b'{"v": "sortmfst1", "kind": "run", "chu')
    elif state == "torn_run":
        os.truncate(infos[1].path, os.path.getsize(infos[1].path) - 5)
    elif state == "bitrot_run":
        with open(infos[1].path, "r+b") as f:
            f.seek(40)
            b = f.read(1)
            f.seek(40)
            f.write(bytes([b[0] ^ 0x5A]))
    tr = Tracer()
    res = external.external_sort(x, budget=budget,
                                 spill_dir=str(tmp_path),
                                 dataset="ds1", tracer=tr)
    assert np.array_equal(res.keys, np.sort(x))
    spans = _external_span_counts(tr)
    expect_resumed = {"empty": 0, "partial_line": 1,
                      "all_committed": nchunks,
                      "torn_run": nchunks - 1,
                      "bitrot_run": nchunks - 1}[state]
    assert res.resumed_runs == expect_resumed
    # resumed chunks were NOT re-sorted; damaged/missing ones were
    assert spans.get("external.run", 0) == nchunks - expect_resumed
    if expect_resumed:
        assert spans.get("external.resume") == 1
    # success retires the journal — nothing left to GC
    assert not os.path.exists(mpath)
    left = [f for f in os.listdir(tmp_path)
            if f.endswith((".run", ".pay", ".fpr.json", ".tmp"))]
    assert left == []


def test_crash_grid_stale_format_version_is_typed(tmp_path, rng):
    from mpitest_tpu.store import manifest as mfstlib

    x = _keys(rng, np.int32, 20_000)
    mp = mfstlib.manifest_path(str(tmp_path), "ds9")
    begin = {"v": mfstlib.MANIFEST_SCHEMA, "kind": "begin",
             "dataset": "ds9", "dtype": "int32", "n": int(x.size),
             "payload_width": 0, "format_version": 99,
             "chunk_elems": 8192, "algorithm": "auto",
             "budget": 1 << 15, "fanin": 16}
    with open(mp, "w") as f:
        f.write(json.dumps(begin) + "\n")
    with pytest.raises(runlib.RunVersionError, match="format_version 99"):
        external.external_sort(x, budget=1 << 15,
                               spill_dir=str(tmp_path), dataset="ds9")
    # RunVersionError IS a RunFormatError — one except clause catches
    # both disk damage and version skew, but they stay distinguishable
    assert issubclass(runlib.RunVersionError, runlib.RunFormatError)


def test_resume_off_knob_disables_journaling(tmp_path, rng):
    from mpitest_tpu.store import manifest as mfstlib

    x = _keys(rng, np.int32, 20_000)
    with knobs.scoped_env(SORT_RESUME="off"):
        res = external.external_sort(x, budget=1 << 15,
                                     spill_dir=str(tmp_path),
                                     dataset="ds1")
    assert np.array_equal(res.keys, np.sort(x))
    assert res.resumed_runs == 0
    assert mfstlib.live_manifests(str(tmp_path)) == []


def test_stale_run_version_discarded_on_resume(tmp_path, rng):
    """A journaled run whose FILE carries an unknown format_version is
    a typed error at open — the resume path must surface it, not
    silently re-sort around a build-skew problem."""
    x = _keys(rng, np.int32, 30_000)
    budget = 1 << 15
    _, mpath, infos = _plant_crash_state(tmp_path, x, budget, "ds1", [0])
    # stamp an unknown version into the run's SORTBIN1 header
    with open(infos[0].path, "r+b") as f:
        f.seek(runlib.BIN_VERSION_OFF)
        f.write(bytes([99]))
    with pytest.raises(runlib.RunVersionError):
        external.external_sort(x, budget=budget,
                               spill_dir=str(tmp_path), dataset="ds1")


def test_mid_merge_enospc_is_typed_and_partials_deleted(tmp_path, rng):
    from mpitest_tpu import faults

    x = _keys(rng, np.int32, 30_000)
    # fire at the 3rd spill write: the partition phase survives the
    # first writes, then the disk "fills"
    with knobs.scoped_env(SORT_FAULT_ENOSPC_AT="3"):
        reg = faults.FaultRegistry("spill_enospc", seed=3)
        faults.install(reg)
        try:
            with pytest.raises(external.SpillCapacityError) as ei:
                external.external_sort(x, budget=1 << 15,
                                       spill_dir=str(tmp_path),
                                       dataset="ds1")
        finally:
            faults.install(None)
    import errno as errno_mod
    assert ei.value.errno == errno_mod.ENOSPC
    assert isinstance(ei.value, OSError)
    # every partial (runs, tmp files, the journal) deleted
    assert [f for f in os.listdir(tmp_path)] == []


def test_gc_reclaims_orphans_age_gated(tmp_path, rng):
    import time as time_mod

    from mpitest_tpu.store import manifest as mfstlib

    keys = np.sort(_keys(rng, np.int32, 1000))
    orphan = runlib.write_run(str(tmp_path), "orphan_00000", keys)
    live = runlib.write_run(str(tmp_path), "live_00000", keys,
                            durable=True)
    mw = mfstlib.ManifestWriter(str(tmp_path), "liveds", dtype="int32",
                                n=1000, payload_width=0,
                                algorithm="auto", chunk_elems=8192,
                                budget=1 << 15, fanin=16)
    mw.commit_run(0, live)
    mw.close()
    (tmp_path / "stray.run.tmp").write_bytes(b"x")
    # age gate: fresh files are never swept (a concurrent sort's)
    assert external.gc_spill_dir(str(tmp_path), age_s=3600) == 0
    old = time_mod.time() - 7200
    for fn in os.listdir(tmp_path):
        os.utime(tmp_path / fn, (old, old))
    assert external.gc_spill_dir(str(tmp_path), age_s=3600) == 3
    left = sorted(os.listdir(tmp_path))
    # manifest-referenced files and the journal survive; orphans die
    # (suffix-agnostic: the run may be .run or .runz per the knob)
    assert os.path.basename(live.path) in left and "liveds.mfst" in left
    assert not any(f.startswith(("orphan", "stray")) for f in left)


# --------------------------------------------------------------- knobs

def test_external_knob_validation():
    with knobs.scoped_env(SORT_MEM_BUDGET="-3"):
        with pytest.raises(ValueError, match="SORT_MEM_BUDGET"):
            knobs.get("SORT_MEM_BUDGET")
    with knobs.scoped_env(SORT_MERGE_FANIN="1"):
        with pytest.raises(ValueError, match="SORT_MERGE_FANIN"):
            knobs.get("SORT_MERGE_FANIN")
    with knobs.scoped_env(SORT_SERVE_SPILL="yes"):
        with pytest.raises(ValueError, match="SORT_SERVE_SPILL"):
            knobs.get("SORT_SERVE_SPILL")
    assert knobs.get("SORT_MERGE_FANIN") == 16
    assert knobs.get("SORT_SERVE_SPILL") == "auto"
    # ISSUE 18 durability knobs
    with knobs.scoped_env(SORT_RESUME="maybe"):
        with pytest.raises(ValueError, match="SORT_RESUME"):
            knobs.get("SORT_RESUME")
    with knobs.scoped_env(SORT_SPILL_GC_AGE_S="-1"):
        with pytest.raises(ValueError, match="SORT_SPILL_GC_AGE_S"):
            knobs.get("SORT_SPILL_GC_AGE_S")
    with knobs.scoped_env(SORT_FAULT_ENOSPC_AT="0"):
        with pytest.raises(ValueError, match="SORT_FAULT_ENOSPC_AT"):
            knobs.get("SORT_FAULT_ENOSPC_AT")
    assert knobs.get("SORT_RESUME") == "auto"


def test_spill_compress_knob_validation():
    # ISSUE 20 knobs
    with knobs.scoped_env(SORT_SPILL_COMPRESS="zstd"):
        with pytest.raises(ValueError, match="SORT_SPILL_COMPRESS"):
            knobs.get("SORT_SPILL_COMPRESS")
    with knobs.scoped_env(SORT_SPILL_THROTTLE_MBPS="-2"):
        with pytest.raises(ValueError, match="SORT_SPILL_THROTTLE_MBPS"):
            knobs.get("SORT_SPILL_THROTTLE_MBPS")
    with knobs.scoped_env(SORT_SPILL_THROTTLE_MBPS="inf"):
        with pytest.raises(ValueError, match="SORT_SPILL_THROTTLE_MBPS"):
            knobs.get("SORT_SPILL_THROTTLE_MBPS")
    assert knobs.get("SORT_SPILL_COMPRESS") == "auto"
    assert knobs.get("SORT_SPILL_THROTTLE_MBPS") == 0.0


# -------------------------- spill compression + async IO (ISSUE 20)

@pytest.mark.parametrize("dtype", ALL_DTYPES)
def test_compressed_run_roundtrip_all_dtypes(tmp_path, rng, dtype):
    keys = np.sort(_keys(rng, dtype, 5000))
    info = runlib.write_run(str(tmp_path), f"z_{dtype}", keys,
                            compress=True)
    assert info.compressed and info.path.endswith(".runz")
    ri = runlib.open_run(info.path)
    assert ri.compressed and ri.n == 5000
    assert ri.fingerprint == info.fingerprint
    back = np.concatenate([np.array(k) for k, _p in
                           runlib.read_run_chunks(ri, 700)])
    assert np.array_equal(back, keys)
    assert runlib.verify_run(ri, chunk_elems=512)


def test_compressed_run_roundtrip_with_payload(tmp_path, rng):
    n = 3000
    keys = _keys(rng, np.int64, n)
    pay = rng.integers(0, 256, (n, 5), dtype=np.uint8)
    order = np.argsort(keys, kind="stable")
    info = runlib.write_run(str(tmp_path), "zp", keys[order],
                            pay[order], compress=True)
    assert info.compressed and info.payload_width == 5
    ri = runlib.open_run(info.path)
    ks, ps = [], []
    for k, p in runlib.read_run_chunks(ri, 999):
        ks.append(np.array(k))
        ps.append(np.array(p))
    assert np.array_equal(np.concatenate(ks), keys[order])
    assert np.array_equal(np.concatenate(ps), pay[order])
    assert runlib.verify_run(ri)


def test_mixed_raw_and_compressed_runs_merge(tmp_path, rng):
    """Readers dispatch on the file magic, so one merge can consume
    raw (.run) and compressed (.runz) inputs together — the exact
    shape a SORT_SPILL_COMPRESS flip mid-fleet leaves behind."""
    arrays = [rng.integers(-10**6, 10**6, 3000, dtype=np.int32)
              for _ in range(4)]
    infos = [runlib.write_run(str(tmp_path), f"mix{i}", np.sort(a),
                              compress=(i % 2 == 0))
             for i, a in enumerate(arrays)]
    assert {i.compressed for i in infos} == {True, False}
    got, _ = _merge_to_array(infos, chunk=61)
    assert np.array_equal(got, np.sort(np.concatenate(arrays)))


def test_codec_engines_bit_identical(rng):
    """Bytes on disk are engine-independent: the native kernels and
    the pure-Python fallback must produce IDENTICAL packed blocks and
    checksums (cross-decode included), or a .runz written on one image
    would type as corrupt on another."""
    from mpitest_tpu.store import compress as blockz

    cases = [
        np.sort(rng.integers(0, 2**63, 4096, dtype=np.uint64)),
        np.sort(rng.integers(0, 2**20, 1000, dtype=np.uint64)),
        np.zeros(7, dtype=np.uint64),             # width-0 block
        np.array([5], dtype=np.uint64),           # single element
        np.array([0, 2**64 - 1], dtype=np.uint64),  # width-64 delta
    ]
    for vals in cases:
        py = blockz.pack_block(vals, eng="python")
        if blockz.available():
            nat = blockz.pack_block(vals, eng="native")
            assert nat == py
        packed, first, width, chk = py
        for eng in ("python", "native") if blockz.available() \
                else ("python",):
            out, chk2 = blockz.unpack_block(packed, vals.size, first,
                                            width, eng=eng)
            assert np.array_equal(out, vals) and chk2 == chk


def test_compressed_block_garbage_is_typed(tmp_path, rng):
    """Open stays header-only; the damage types at READ time as
    BlockIntegrityError naming run + block, and the merge layer
    translates it to RunIntegrityError so blame-respill recovery
    covers compressed corruption too."""
    keys = np.sort(_keys(rng, np.int32, 20_000))   # several blocks
    info = runlib.write_run(str(tmp_path), "zc", keys, compress=True)
    # flip a byte of block 0's `first` field: guaranteed checksum
    # mismatch regardless of how the deltas land
    off = runlib.RUNZ_HEADER_LEN + 8
    with open(info.path, "r+b") as f:  # sortlint: disable=SL014 -- corruption drill
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0xFF]))
    ri = runlib.open_run(info.path)    # header-only: still opens
    with pytest.raises(runlib.BlockIntegrityError) as ei:
        for _ in runlib.read_run_chunks(ri, 700):
            pass
    assert ei.value.block == 0 and ei.value.path == info.path
    # verify_run surfaces the typed error (BlockIntegrityError IS a
    # RunFormatError — the driver's blame step catches that supertype
    # and treats it as "bad run, re-spill")
    with pytest.raises(runlib.RunFormatError):
        runlib.verify_run(ri)
    with pytest.raises(mergelib.RunIntegrityError):
        for _ in mergelib.merge_runs([ri], 512):
            pass


def test_crash_resume_over_compressed_runs(tmp_path, rng):
    """The ISSUE 18 all-committed resume shape over .runz journals:
    re-enter at the merge phase with ZERO re-sorted chunks."""
    from mpitest_tpu.utils.trace import Tracer

    budget = 1 << 15
    x = _keys(rng, np.int32, 30_000)
    with knobs.scoped_env(SORT_SPILL_COMPRESS="on"):
        chunk = external.spill_chunk_elems(budget, x.dtype, 0)
        nchunks = -(-x.size // chunk)
        _, mpath, infos = _plant_crash_state(tmp_path, x, budget, "dz",
                                             list(range(nchunks)))
        assert all(i.path.endswith(".runz") for i in infos.values())
        tr = Tracer()
        res = external.external_sort(x, budget=budget,
                                     spill_dir=str(tmp_path),
                                     dataset="dz", tracer=tr)
    assert np.array_equal(res.keys, np.sort(x))
    assert res.resumed_runs == nchunks
    assert _external_span_counts(tr).get("external.run", 0) == 0
    assert not os.path.exists(mpath)


def test_subtract_intervals():
    sub = aio.subtract_intervals
    assert sub((0.0, 10.0), []) == [(0.0, 10.0)]
    assert sub((0.0, 10.0), [(2.0, 3.0), (5.0, 7.0)]) == \
        [(0.0, 2.0), (3.0, 5.0), (7.0, 10.0)]
    assert sub((0.0, 10.0), [(0.0, 10.0)]) == []
    assert sub((2.0, 4.0), [(0.0, 1.0), (5.0, 6.0)]) == [(2.0, 4.0)]
    assert sub((2.0, 4.0), [(0.0, 3.0)]) == [(3.0, 4.0)]
    assert sub((2.0, 4.0), [(3.0, 9.0)]) == [(2.0, 3.0)]


def test_readahead_matches_sync_and_is_bounded(tmp_path, rng):
    keys = np.sort(_keys(rng, np.int32, 50_000))
    info = runlib.write_run(str(tmp_path), "ra", keys, compress=True)
    sync = [np.array(k) for k, _p in
            runlib.read_run_chunks(runlib.open_run(info.path), 1000)]
    ra = aio.ReadAhead(runlib.open_run(info.path), 1000)
    try:
        # bounded double buffering: with the consumer idle, the
        # producer parks at the queue cap instead of decoding the
        # whole run into memory
        deadline = time.monotonic() + 5.0
        while ra._q.qsize() < aio.QUEUE_DEPTH and \
                time.monotonic() < deadline:
            time.sleep(0.01)
        assert ra._q.qsize() <= aio.QUEUE_DEPTH
        got = [np.array(k) for k, _p in ra]
    finally:
        ra.close()
    assert len(got) == len(sync)
    assert all(np.array_equal(a, b) for a, b in zip(got, sync))
    io_iv, _stalls = ra.snapshot()
    assert len(io_iv) == len(sync) and all(b >= a for a, b in io_iv)


def test_readahead_close_midstream_joins(tmp_path, rng):
    keys = np.sort(_keys(rng, np.int32, 50_000))
    info = runlib.write_run(str(tmp_path), "rc", keys)
    ra = aio.ReadAhead(runlib.open_run(info.path), 500)
    next(ra)
    ra.close()
    ra.close()   # idempotent
    assert not ra._thread.is_alive()
    with pytest.raises(StopIteration):
        next(ra)


def test_readahead_propagates_block_corruption(tmp_path, rng):
    """The worker thread's typed exception surfaces at the consumer's
    next() with the original type — same contract as the sync path."""
    keys = np.sort(_keys(rng, np.int32, 20_000))
    info = runlib.write_run(str(tmp_path), "rx", keys, compress=True)
    off = runlib.RUNZ_HEADER_LEN + 8
    with open(info.path, "r+b") as f:  # sortlint: disable=SL014 -- corruption drill
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0xFF]))
    ra = aio.ReadAhead(runlib.open_run(info.path), 700)
    try:
        with pytest.raises(runlib.BlockIntegrityError):
            for _ in ra:
                pass
    finally:
        ra.close()


def test_writebehind_writes_identical_run(tmp_path, rng):
    keys = np.sort(_keys(rng, np.int32, 20_000))
    w = runlib.RunStreamWriter(str(tmp_path), "wb", keys.dtype, 0,
                               compress=True)
    wb = aio.WriteBehind(w)
    for i in range(0, keys.size, 3000):
        wb.append(keys[i:i + 3000])
    info = wb.close()
    ri = runlib.open_run(info.path)
    back = np.concatenate([np.array(k) for k, _p in
                           runlib.read_run_chunks(ri, 777)])
    assert np.array_equal(back, keys)
    assert runlib.verify_run(ri)


def test_writebehind_reraises_writer_error(tmp_path):
    class BoomWriter:
        aborted = False

        def append(self, keys, payload=None):
            raise OSError(28, "disk full (drill)")

        def append_words(self, kw, pw):
            raise OSError(28, "disk full (drill)")

        def abort(self):
            self.aborted = True

    boom = BoomWriter()
    wb = aio.WriteBehind(boom)
    wb.append(np.arange(3, dtype=np.int32))
    # the worker parks the error and sets abort; wait for it, then the
    # NEXT append must re-raise the ORIGINAL exception type
    deadline = time.monotonic() + 5.0
    while not wb._abort.is_set() and time.monotonic() < deadline:
        time.sleep(0.01)
    with pytest.raises(OSError, match="disk full"):
        wb.append(np.arange(3, dtype=np.int32))
    wb.abort()
    assert boom.aborted and not wb._thread.is_alive()


def test_merge_with_async_io_bit_identical(tmp_path, rng):
    arrays = [rng.integers(-10**6, 10**6, 5000, dtype=np.int32)
              for _ in range(5)]
    infos = [runlib.write_run(str(tmp_path), f"aio{i}", np.sort(a),
                              compress=(i % 2 == 0))
             for i, a in enumerate(arrays)]
    io = aio.MergeIO()
    codec = runlib.codec_for(infos[0].dtype)
    t0 = time.perf_counter()
    parts = [codec.decode(kws)
             for kws, _p in mergelib.merge_runs(infos, 611, io=io)]
    stats = io.stats(t0, time.perf_counter())
    assert np.array_equal(np.concatenate(parts),
                          np.sort(np.concatenate(arrays)))
    assert 0.0 <= stats["disk_overlap"] <= 1.0
    assert stats["disk_busy_s"] >= 0.0 and stats["overlap_s"] >= 0.0
    # merge_runs' cursor cleanup closed every reader thread
    assert all(not ra._thread.is_alive() for ra in io.readers)
    assert knobs.get("SORT_SPILL_GC_AGE_S") == 3600
