"""Test harness: simulate the device mesh on CPU, no TPU required.

Multi-"node" simulation without a cluster (SURVEY.md §4): the reference was
exercised via ``mpirun -np P`` on one host; the TPU-native equivalent is a
virtual P-device CPU mesh via ``--xla_force_host_platform_device_count``,
so all ``shard_map``/collective code runs unmodified.

The env/config overrides MUST happen before the first JAX backend query
(this image's sitecustomize pins an experimental TPU platform).
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from mpitest_tpu.utils.platform import ensure_virtual_cpu_devices  # noqa: E402

ensure_virtual_cpu_devices(8)

import jax  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from mpitest_tpu.parallel.mesh import make_mesh

    assert len(jax.devices()) == 8, "virtual CPU mesh not active"
    return make_mesh(8)


@pytest.fixture(scope="session")
def mesh4():
    from mpitest_tpu.parallel.mesh import make_mesh

    return make_mesh(4)


@pytest.fixture
def rng():
    return np.random.default_rng(1234)
