"""Exchange-engine tests (ISSUE 13): the Pallas ICI engine's plumbing,
parity, overlap-loop invariants, ladder degradation and provenance.

The remote-DMA kernel itself lowers only on a TPU backend (the Pallas
interpreter cannot simulate cross-device DMA — ``ops/exchange.py``
module docstring); on this CPU mesh the ``pallas_interpret`` engine
runs the fused multi-word pack kernel + the no-dest segment arithmetic
+ all engine plumbing for real, with the rank-to-rank hop on the
bit-identical ``lax.all_to_all``.  Named ``test_zz_*`` to sort late:
the parity cells compile shard_map programs on the mesh8 fixture.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from mpitest_tpu.models.api import (  # noqa: E402
    _resolve_exchange_engine, sort)
from mpitest_tpu.ops import exchange as xeng  # noqa: E402
from mpitest_tpu.utils import knobs  # noqa: E402
from mpitest_tpu.utils.trace import Tracer  # noqa: E402


def _spans(tracer, name):
    return [s for s in tracer.spans.spans if s.name == name]


# ------------------------------------------------------- knob contract

def test_engine_knob_validation():
    """SORT_EXCHANGE_ENGINE is registered, typed, and fail-fast."""
    with knobs.scoped_env(SORT_EXCHANGE_ENGINE="warp9"):
        with pytest.raises(knobs.KnobError, match="SORT_EXCHANGE_ENGINE"):
            knobs.get("SORT_EXCHANGE_ENGINE")
    for ok in ("auto", "lax", "pallas", "pallas_interpret"):
        with knobs.scoped_env(SORT_EXCHANGE_ENGINE=ok):
            assert knobs.get("SORT_EXCHANGE_ENGINE") == ok
    assert knobs.get("SORT_EXCHANGE_ENGINE") == "auto"  # default


def test_engine_knob_fail_fast_in_cli_and_server():
    """Both drivers validate the knob at startup: the CLI maps garbage
    to one [ERROR] line + rc != 0 (in-process, like test_cli), and the
    server's validate() sweep names the knob (test_zz_serve contract —
    the sweep raises the same KnobError before any socket binds)."""
    from drivers import sort_cli

    with knobs.scoped_env(SORT_EXCHANGE_ENGINE="warp9"):
        rc = sort_cli.main(["sort_cli.py", "/nonexistent-but-knobs-first"])
        assert rc != 0
    # the server's startup sweep covers the knob (source-level pin: the
    # sweep is a literal validate() list; spawning a server per knob
    # would pay seconds for the same evidence)
    server_src = (REPO / "drivers" / "sort_server.py").read_text()
    assert '"SORT_EXCHANGE_ENGINE"' in server_src
    cli_src = (REPO / "drivers" / "sort_cli.py").read_text()
    assert '"SORT_EXCHANGE_ENGINE"' in cli_src


def test_engine_resolution_on_cpu():
    """auto = lax off-TPU; a forced pallas runs the interpreter form
    (same convention as the bitonic local engine)."""
    assert _resolve_exchange_engine(None) == "lax"  # auto default, CPU
    assert _resolve_exchange_engine("lax") == "lax"
    assert _resolve_exchange_engine("pallas") == "pallas_interpret"
    assert _resolve_exchange_engine("pallas_interpret") == "pallas_interpret"
    with knobs.scoped_env(SORT_EXCHANGE_ENGINE="pallas"):
        assert _resolve_exchange_engine(None) == "pallas_interpret"
    with pytest.raises(ValueError, match="exchange engine"):
        _resolve_exchange_engine("warp9")


# ------------------------------------------------------- kernel units

def test_block_send_segments_matches_searchsorted():
    """The no-dest clip-arithmetic segments equal the lax engine's
    searchsorted-over-dest form, bit for bit, on random histograms."""
    from mpitest_tpu.parallel.collectives import block_send_segments

    rng = np.random.default_rng(5)
    for _ in range(20):
        P, bins = int(rng.integers(2, 9)), int(rng.integers(2, 33))
        n = int(rng.integers(1, 257))
        h = rng.multinomial(n, np.ones(bins) / bins).astype(np.int32)
        # a valid global arrangement: base[d] = my run start for digit d
        # (any non-decreasing assignment with room for h works)
        gaps = rng.integers(0, 4, size=bins)
        base = np.cumsum(np.concatenate([[0], (h + gaps)[:-1]])).astype(
            np.int32)
        n_total = int(base[-1] + h[-1] + rng.integers(0, 4))
        # reference: materialize dest per element, searchsorted
        dest = np.concatenate(
            [base[d] + np.arange(h[d]) for d in range(bins)]).astype(
                np.int64)
        dest.sort()
        shard = max(1, -(-n_total // P))
        bounds = np.arange(P + 1) * shard
        cum_ref = np.searchsorted(dest, bounds, side="left")
        start, cnt = block_send_segments(
            jnp.asarray(h), jnp.asarray(base), shard, P)
        np.testing.assert_array_equal(np.asarray(start), cum_ref[:-1])
        np.testing.assert_array_equal(np.asarray(cnt), np.diff(cum_ref))


def test_fused_pass_pack_matches_xla_spread():
    """The fused multi-word pack kernel (interpret) produces the exact
    send matrices the XLA scatter spread builds — both word planes,
    fills included."""
    rng = np.random.default_rng(9)
    P, cap, n = 4, 2048, 1500
    hi = rng.integers(0, 2**32, n, dtype=np.uint32)
    lo = rng.integers(0, 2**32, n, dtype=np.uint32)
    cuts = np.sort(rng.integers(0, n, size=P - 1))
    starts = np.concatenate([[0], cuts]).astype(np.int32)
    ends = np.concatenate([cuts, [n]]).astype(np.int32)
    cnts = (ends - starts).astype(np.int32)
    fills = (0xFFFFFFFF, 0)

    outs = xeng.fused_pass_pack(
        (jnp.asarray(hi), jnp.asarray(lo)), jnp.asarray(starts),
        jnp.asarray(cnts), cap, P, fills=fills, interpret=True)
    for a, fill, out in zip((hi, lo), fills, outs):
        want = np.full((P, cap), fill, np.uint32)
        for p in range(P):
            c = min(int(cnts[p]), cap)
            want[p, :c] = a[starts[p]:starts[p] + c]
        np.testing.assert_array_equal(np.asarray(out), want)


def test_remote_a2a_interpret_contract(mesh8):
    """Under interpret the transport is lax.all_to_all — pin the
    recv[s] = row-sent-by-s contract on the virtual mesh."""
    from jax.sharding import PartitionSpec as P

    from mpitest_tpu import compat
    from mpitest_tpu.parallel.mesh import AXIS

    n_ranks, cap = 8, 1024
    x = jnp.arange(n_ranks * n_ranks * cap, dtype=jnp.uint32).reshape(
        n_ranks * n_ranks, cap)

    def f(block):
        return xeng.remote_a2a(block, n_ranks, AXIS, interpret=True)

    out = jax.jit(compat.shard_map(
        f, mesh=mesh8, in_specs=P(AXIS), out_specs=P(AXIS),
        check_vma=False))(x)
    got = np.asarray(out).reshape(n_ranks, n_ranks, cap)
    ref = np.asarray(x).reshape(n_ranks, n_ranks, cap)
    for me in range(n_ranks):
        for s in range(n_ranks):
            np.testing.assert_array_equal(got[me, s], ref[s, me])


# ------------------------------------------------- parity on the mesh

@pytest.mark.parametrize("dtype", [np.int32, np.uint64, np.float32])
@pytest.mark.parametrize("algo", ["radix", "sample"])
def test_lax_vs_interpret_parity_mesh8(algo, dtype, mesh8, rng):
    """Bit-identical output across the engine knob, both algorithms,
    1- and 2-word codecs and the float totalOrder codec."""
    if np.dtype(dtype).kind == "f":
        x = rng.normal(size=1 << 12).astype(dtype)
    else:
        info = np.iinfo(dtype)
        x = rng.integers(info.min, info.max, size=1 << 12,
                         dtype=dtype, endpoint=True)
    # SORT_FALLBACK=0 pins each engine: without it a broken pallas path
    # would silently degrade to lax and the byte comparison would pass
    # vacuously (lax vs lax).
    with knobs.scoped_env(SORT_FALLBACK="0"):
        a = sort(x, algorithm=algo, mesh=mesh8, exchange_engine="lax")
        t = Tracer()
        b = sort(x, algorithm=algo, mesh=mesh8,
                 exchange_engine="pallas_interpret", tracer=t)
    assert t.counters["exchange_engine"] == "pallas_interpret"
    assert "exchange_engine_degraded" not in t.counters
    assert a.dtype == b.dtype == np.dtype(dtype)
    assert a.tobytes() == b.tobytes()


def test_overlap_loop_pass_count_invariants(mesh8, rng):
    """The pallas overlap loop runs EXACTLY the lax engine's pass
    structure: same pass count, one exchange per pass, one overlap-hook
    slot plane per exchange — and the trace carries the engine."""
    x = rng.integers(-2**31, 2**31 - 1, size=1 << 12, dtype=np.int32)
    results = {}
    for eng in ("lax", "pallas_interpret"):
        t = Tracer()
        with knobs.scoped_env(SORT_FALLBACK="0"):  # pin: no silent degrade
            results[eng] = sort(x, algorithm="radix", mesh=mesh8,
                                digit_bits=8, exchange_engine=eng,
                                tracer=t)
        assert "exchange_engine_degraded" not in t.counters
        passes = _spans(t, "radix_pass")
        a2a = _spans(t, "ragged_all_to_all")
        assert len(passes) == 4  # full-range int32 at 8-bit digits
        assert len(a2a) == len(passes)  # one exchange per pass, no extras
        for e in a2a:
            assert e.attrs["engine"] == eng
        if eng != "lax":
            # the engine owns the pack on the pallas path
            assert all(e.attrs["pack"] == eng for e in a2a)
    assert results["lax"].tobytes() == results["pallas_interpret"].tobytes()


# ------------------------------------------- ladder + plan provenance

def test_ladder_degrades_pallas_to_lax_verified(mesh8, rng):
    """A pallas engine failure re-runs the SAME algorithm on the lax
    rung; the result is fingerprint-verified and the degrade is a plan
    decision + counters, never a silent engine swap.

    The key count is deliberately odd (3333): the injected fault fires
    at TRACE time, so this test must miss every compile-cache entry the
    other cells populated — a cached executable never re-traces and the
    patched transport would never be reached."""
    x = rng.integers(-2**31, 2**31 - 1, size=3333, dtype=np.int32)

    orig = xeng.remote_a2a

    def boom(*a, **kw):
        raise jax.errors.JaxRuntimeError(
            "INTERNAL: injected pallas exchange fault (test)")

    xeng.remote_a2a = boom
    try:
        with knobs.scoped_env(SORT_MAX_RETRIES="0", SORT_FALLBACK="1"):
            t = Tracer()
            out = sort(x, algorithm="radix", mesh=mesh8,
                       exchange_engine="pallas_interpret", tracer=t)
    finally:
        xeng.remote_a2a = orig
    np.testing.assert_array_equal(out, np.sort(x))
    assert t.counters["exchange_engine"] == "lax"
    assert t.counters["exchange_engine_degraded"] == 1
    assert t.counters["verify_runs"] >= 1
    assert "degraded_to" not in t.counters  # same algorithm, engine rung
    d = t.plan.decisions["exchange_engine"]
    assert d.chosen == "lax" and d.trigger == "pallas_fault"
    assert d.regret == 1.0
    assert t.plan.digest()["exchange_engine"] == "lax"


def test_ladder_engine_descent_blames_actual_cause(mesh8, rng):
    """A descent off the pallas rung caused by VERIFICATION failure
    (e.g. a result fault that equally implicates the data) is recorded
    as trigger=verify_failure, not blamed on the kernel."""
    from mpitest_tpu import faults

    x = rng.integers(-2**31, 2**31 - 1, size=4321, dtype=np.int32)
    # result_swap:2 corrupts both verification tries of rung 1, then
    # exhausts — the lax rung runs clean and the ladder ends verified.
    reg = faults.FaultRegistry("result_swap:2")
    faults.install(reg)
    try:
        with knobs.scoped_env(SORT_MAX_RETRIES="0", SORT_FALLBACK="1"):
            t = Tracer()
            out = sort(x, algorithm="radix", mesh=mesh8,
                       exchange_engine="pallas_interpret", tracer=t)
    finally:
        faults.install(None)
    np.testing.assert_array_equal(out, np.sort(x))
    d = t.plan.decisions["exchange_engine"]
    assert d.chosen == "lax" and d.trigger == "verify_failure"
    assert d.regret == 1.0


def test_ladder_pinned_engine_fails_loudly(mesh8, rng):
    """SORT_FALLBACK=0 pins the engine: a pallas failure is a typed
    error, never a silent lax re-run (the bench/selftest contract)."""
    from mpitest_tpu.models.api import SortRetryExhausted

    # odd size: must miss the compile caches (see the test above)
    x = rng.integers(0, 100, size=999, dtype=np.int32)
    orig = xeng.remote_a2a

    def boom(*a, **kw):
        raise jax.errors.JaxRuntimeError("INTERNAL: injected (test)")

    xeng.remote_a2a = boom
    try:
        with knobs.scoped_env(SORT_MAX_RETRIES="0", SORT_FALLBACK="0"):
            with pytest.raises(SortRetryExhausted):
                sort(x, algorithm="radix", mesh=mesh8,
                     exchange_engine="pallas_interpret")
    finally:
        xeng.remote_a2a = orig


def test_balance_event_carries_engine(mesh8, rng):
    """The exchange_balance event (the scale-out table's source) names
    the engine that sized the capacity."""
    x = np.sort(rng.integers(0, 1 << 16, size=1 << 12).astype(np.int32))
    for eng in ("lax", "pallas_interpret"):
        t = Tracer()
        sort(x, algorithm="radix", mesh=mesh8, exchange_engine=eng,
             tracer=t)
        events = _spans(t, "exchange_balance")
        assert events, "negotiated run must emit exchange_balance"
        assert all(e.attrs["exchange_engine"] == eng for e in events)
        assert t.counters["exchange_engine"] == eng


def test_explain_shows_engine_decision(mesh8, rng, tmp_path):
    """`report.py --explain` renders the exchange_engine decision from
    the sort.plan span stream."""
    from mpitest_tpu import report

    trace = tmp_path / "trace.jsonl"
    x = rng.integers(-2**31, 2**31 - 1, size=1 << 12, dtype=np.int32)
    with knobs.scoped_env(SORT_TRACE=str(trace)):
        sort(x, algorithm="radix", mesh=mesh8,
             exchange_engine="pallas_interpret")
    rows = report.load_rows(str(trace))
    view = report.explain_view(rows)
    assert view is not None
    assert "exchange_engine" in view
    assert "chosen=pallas_interpret" in view
