"""Unit tests for the gather-free kernel building blocks + 1-device path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpitest_tpu.models.api import sort
from mpitest_tpu.ops import kernels
from mpitest_tpu.parallel.mesh import make_mesh
from mpitest_tpu import compat


def test_piecewise_fill_basic():
    starts = jnp.asarray([0, 3, 3, 7], jnp.int32)   # empty segment at k=1→2
    values = jnp.asarray([5, 2, 9, -4], jnp.int32)
    out = np.asarray(jax.jit(kernels.piecewise_fill, static_argnums=2)(starts, values, 10))
    #           j: 0  1  2  3  4  5  6   7   8   9
    expect = np.array([5, 5, 5, 9, 9, 9, 9, -4, -4, -4], np.int32)
    np.testing.assert_array_equal(out, expect)


def test_piecewise_fill_tail_at_n():
    starts = jnp.asarray([0, 4, 4], jnp.int32)      # start == n tail segments
    values = jnp.asarray([1, 7, 8], jnp.int32)
    out = np.asarray(jax.jit(kernels.piecewise_fill, static_argnums=2)(starts, values, 4))
    np.testing.assert_array_equal(out, np.array([1, 1, 1, 1], np.int32))


def test_histogram_sorted_matches_scatter():
    rng = np.random.default_rng(0)
    d = np.sort(rng.integers(0, 256, 5000).astype(np.int32))
    h, lo = jax.jit(kernels.histogram_sorted, static_argnums=1)(jnp.asarray(d), 256)
    expect = np.bincount(d, minlength=256)
    np.testing.assert_array_equal(np.asarray(h), expect)
    np.testing.assert_array_equal(np.asarray(lo), np.concatenate([[0], np.cumsum(expect)[:-1]]))


@pytest.mark.parametrize("algo", ["radix", "sample"])
def test_device_resident_input_multi_device(algo, mesh8, rng):
    """Device-resident jax.Array input on a multi-device mesh: sharded,
    committed-to-one-device, and non-divisible-N variants."""
    from mpitest_tpu.parallel.mesh import key_sharding

    x = rng.integers(-(2**31), 2**31 - 1, size=8 * 512, dtype=np.int32)
    ref = np.sort(x)

    x_sharded = jax.device_put(x, key_sharding(mesh8))
    np.testing.assert_array_equal(sort(x_sharded, algorithm=algo, mesh=mesh8), ref)

    x_committed = jax.device_put(x, jax.devices("cpu")[0])
    np.testing.assert_array_equal(sort(x_committed, algorithm=algo, mesh=mesh8), ref)

    y = rng.integers(0, 2**32, size=1003, dtype=np.uint32)
    y_dev = jax.device_put(y, jax.devices("cpu")[0])
    np.testing.assert_array_equal(sort(y_dev, algorithm=algo, mesh=mesh8), np.sort(y))


@pytest.mark.parametrize("algo", ["radix", "sample"])
@pytest.mark.parametrize("dtype", [np.int64, np.uint64])
def test_device_resident_64bit_input(algo, dtype, mesh8, rng):
    """Device-resident 64-bit keys use the on-device 2-word codec (no host
    round-trip) — requires x64 only to *hold* the input array; the sort
    itself runs entirely on uint32 words."""
    info = np.iinfo(np.dtype(dtype))
    x = rng.integers(info.min, info.max, size=8 * 256 + 5, dtype=dtype,
                     endpoint=True)
    with compat.enable_x64(True):
        x_dev = jnp.asarray(x)
        assert x_dev.dtype == np.dtype(dtype)
        got = sort(x_dev, algorithm=algo, mesh=mesh8)
    np.testing.assert_array_equal(got, np.sort(x))


@pytest.mark.parametrize("n_mesh", [1, 8])
def test_device_resident_float64_host_fallback(n_mesh, rng, monkeypatch):
    """Some TPU stacks cannot lower the f64→u32 bitcast (XLA's x64
    rewrite lacks the rule — observed on v5e via this image's AOT
    service); a device-resident float64 input must then degrade to ONE
    documented host round-trip and still sort exactly, not surface an
    internal compiler error.  The failure is injected here (the CPU
    backend lowers the bitcast fine)."""
    import jax.errors

    from mpitest_tpu.models import api
    from mpitest_tpu.utils.trace import Tracer

    calls = {"n": 0}

    def boom(*a, **k):
        calls["n"] += 1

        def f(*args):
            raise jax.errors.JaxRuntimeError(
                "While rewriting computation to not contain X64 element "
                "types: %bitcast-convert injected")
        return f

    monkeypatch.setattr(api, "_f64_encode_broken_platforms", set())
    monkeypatch.setattr(api, "_compile_encode_pad", boom)
    monkeypatch.setattr(api, "_compile_local_device", boom)
    x = (rng.standard_normal(8 * 200 + 3) * 1e9).astype(np.float64)
    with compat.enable_x64(True):
        x_dev = jnp.asarray(x)
        tracer = Tracer()
        got = sort(x_dev, algorithm="radix", mesh=make_mesh(n_mesh),
                   tracer=tracer)
        np.testing.assert_array_equal(got, np.sort(x))
        assert tracer.counters.get("f64_host_fallback") == 1
        # the verdict memoizes: the second call must route straight to the
        # host path without re-attempting the doomed compile
        first_calls = calls["n"]
        tracer2 = Tracer()
        got2 = sort(x_dev, algorithm="radix", mesh=make_mesh(n_mesh),
                    tracer=tracer2)
        np.testing.assert_array_equal(got2, np.sort(x))
        assert tracer2.counters.get("f64_host_fallback") == 1
        assert calls["n"] == first_calls
        # int64 must NOT be silently degraded by the same path...
        y = rng.integers(-(2**62), 2**62, size=1000, dtype=np.int64)
        y_dev = jnp.asarray(y)
        with pytest.raises(jax.errors.JaxRuntimeError, match="bitcast"):
            sort(y_dev, algorithm="radix", mesh=make_mesh(n_mesh))
    # ...and any OTHER runtime error on f64 must re-raise, never
    # masquerade as the lowering gap: plain OOM/preemption, and errors
    # carrying only ONE of the gap's message fragments (a different
    # x64-rewrite failure, an unrelated bitcast error).
    for msg in ("RESOURCE_EXHAUSTED: injected",
                "some other bitcast-convert failure",
                "X64 element types trouble elsewhere"):
        monkeypatch.setattr(api, "_f64_encode_broken_platforms", set())

        def other(*a, _msg=msg, **k):
            def f(*args):
                raise jax.errors.JaxRuntimeError(_msg)
            return f

        monkeypatch.setattr(api, "_compile_encode_pad", other)
        monkeypatch.setattr(api, "_compile_local_device", other)
        with compat.enable_x64(True):
            with pytest.raises(jax.errors.JaxRuntimeError,
                               match=msg.split()[0].split(":")[0]):
                sort(jnp.asarray(x), algorithm="radix", mesh=make_mesh(n_mesh))
        assert not api._f64_encode_broken_platforms


@pytest.mark.parametrize("algo", ["radix", "sample"])
@pytest.mark.parametrize("dtype", [np.int32, np.int64])
def test_single_device_mesh_fast_path(algo, dtype, rng):
    """1-device mesh: both algorithms specialize to the local fused sort."""
    mesh1 = make_mesh(1)
    info = np.iinfo(np.dtype(dtype))
    x = rng.integers(info.min, info.max, size=10_001, dtype=dtype, endpoint=True)
    got = sort(x, algorithm=algo, mesh=mesh1)
    np.testing.assert_array_equal(got, np.sort(x))
    res = sort(x, algorithm=algo, mesh=mesh1, return_result=True)
    assert res.median_probe() == int(np.sort(x)[x.size // 2 - 1])


@pytest.mark.parametrize("algo", ["radix", "sample"])
def test_device_resident_float32(algo, mesh8, rng):
    """Device-resident float32 keys: the on-device totalOrder encode
    (keys.py encode_jax) keeps them off the host; NaN-free data matches
    np.sort byte-for-byte."""
    x = (rng.standard_normal(8 * 300 + 7) * 1e6).astype(np.float32)
    x_dev = jax.device_put(x, jax.devices("cpu")[0])
    got = sort(x_dev, algorithm=algo, mesh=mesh8)
    np.testing.assert_array_equal(got, np.sort(x))
