"""Report CLI tests: mixed-schema aggregation, --check validation, and
regression flagging with host-provenance gating (ISSUE 1 satellite)."""

import json

import pytest

from mpitest_tpu import report


def write_jsonl(path, rows):
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    return str(path)


SPAN_ROWS = [
    {"v": "span.v1", "name": "sort", "id": 0, "parent": None,
     "t0": 0.0, "dt": 2.0, "attrs": {"algorithm": "radix"}},
    {"v": "span.v1", "name": "phase:sort", "id": 1, "parent": 0,
     "t0": 0.1, "dt": 1.5, "attrs": {}},
    {"v": "span.v1", "name": "ragged_all_to_all", "id": 2, "parent": 1,
     "t0": 0.2, "dt": 0.0, "attrs": {"bytes": 4096, "ranks": 4}},
    {"v": "span.v1", "name": "all_gather", "id": 3, "parent": 1,
     "t0": 0.3, "dt": 0.0, "attrs": {"bytes": 1024}},
]

COMM_ROW = {"v": "comm_stats.v1", "backend": "local", "ranks": 4,
            "collectives": {"alltoallv": {"calls": 16, "bytes": 320000,
                                          "seconds": 0.001}}}

METRICS_ROW = {"ts": 1.0, "config": {"algo": "radix"},
               "metrics": {"phase_sort_ms": {"value": 250.0, "unit": "ms"},
                           "sort_mkeys_per_s": {"value": 700.0,
                                                "unit": "Mkeys/s"}}}

BENCH_ROW = {"metric": "radix_sort_mkeys_per_s_2e28_int32", "value": 766.7,
             "unit": "Mkeys/s", "vs_baseline": 60.7}


def test_load_classifies_all_schemas(tmp_path):
    p = write_jsonl(tmp_path / "mixed.jsonl",
                    SPAN_ROWS + [COMM_ROW, METRICS_ROW, BENCH_ROW])
    kinds = [r["kind"] for r in report.load_rows(p)]
    assert kinds == ["span"] * 4 + ["comm_stats", "metrics", "bench"]


def test_aggregate_lines_up_tpu_and_native(tmp_path):
    p = write_jsonl(tmp_path / "mixed.jsonl",
                    SPAN_ROWS + [COMM_ROW, METRICS_ROW, BENCH_ROW])
    agg = report.aggregate(report.load_rows(p))
    # phases fold spans AND metrics sidecar rows (ms)
    assert agg["phases"]["sort"]["count"] == 2
    assert agg["phases"]["sort"]["ms"] == pytest.approx(1750.0)
    # the TPU span events land on the comm.h vocabulary next to native
    assert agg["collectives"]["tpu"]["alltoallv"]["bytes"] == 4096
    assert agg["collectives"]["tpu"]["allgather"]["calls"] == 1
    assert agg["collectives"]["native/localx4"]["alltoallv"]["calls"] == 16
    assert agg["metrics"]["sort_mkeys_per_s"]["value"] == 700.0
    assert agg["metrics"][BENCH_ROW["metric"]]["value"] == 766.7
    # renders without error
    text = report.render(agg)
    assert "alltoallv" in text and "native/localx4" in text


def test_check_clean_and_violations(tmp_path):
    clean = write_jsonl(tmp_path / "clean.jsonl", SPAN_ROWS + [COMM_ROW])
    assert report.check_rows(report.load_rows(clean)) == []

    bad_rows = [
        {"v": "span.v1", "name": "x", "id": 0, "parent": 7,   # dangling
         "t0": 0.0, "dt": 0.1, "attrs": {}},
        {"v": "span.v1", "name": "y", "id": 1, "parent": None,
         "t0": 0.0, "dt": 0.1},                               # no attrs
        {"v": "comm_stats.v1", "backend": "local", "ranks": 4,
         "collectives": {"bcast": {"calls": 1, "bytes": 2}}},  # no seconds
        {"weird": True},                                       # unknown
    ]
    bad = write_jsonl(tmp_path / "bad.jsonl", bad_rows)
    errors = report.check_rows(report.load_rows(bad))
    assert len(errors) == 4
    joined = "\n".join(errors)
    assert "dangling parent" in joined
    assert "missing 'attrs'" in joined
    assert "missing 'seconds'" in joined
    assert "unrecognized record shape" in joined

    # invalid JSON is a check error too, with file:line
    garbled = tmp_path / "garbled.jsonl"
    garbled.write_text('{"metric": "m", "value": 1}\nnot json\n')
    errors = report.check_rows(report.load_rows(str(garbled)))
    assert len(errors) == 1 and "not valid JSON" in errors[0]


def test_main_check_exit_codes(tmp_path, capsys):
    clean = write_jsonl(tmp_path / "clean.jsonl", SPAN_ROWS)
    assert report.main(["--check", clean]) == 0
    assert "telemetry check OK" in capsys.readouterr().out
    bad = tmp_path / "bad.jsonl"
    bad.write_text("nope\n")
    assert report.main(["--check", str(bad)]) == 1


def test_regression_flagging(tmp_path):
    current = report.aggregate(report.load_rows(
        write_jsonl(tmp_path / "cur.jsonl", [BENCH_ROW])))
    host = "this-host/8c"
    baseline = [
        # clear regression: 766.7 < 0.9 * 900
        {"metric": BENCH_ROW["metric"], "value": 900.0, "host": host},
        # other-host pin must be SKIPPED, not compared
        {"metric": BENCH_ROW["metric"], "value": 9999.0,
         "host": "other-host/1c"},
        # unpinned-host row compares everywhere; passes at 700 pinned
        {"metric": BENCH_ROW["metric"], "value": 700.0},
        # pinned metric with no current row
        {"metric": "absent_metric", "value": 1.0, "host": host},
    ]
    for row in baseline:
        row.update(unit="Mkeys/s")
    rows = report.load_rows(write_jsonl(tmp_path / "base.jsonl", baseline))
    findings = report.flag_regressions(current, rows, 0.9, host)
    status = [f["status"] for f in findings]
    assert status == ["REGRESSION", "skipped", "ok", "missing"]
    assert findings[0]["ratio"] == pytest.approx(766.7 / 900.0, abs=1e-3)
    assert "host mismatch" in findings[1]["reason"]


def test_main_regression_exit_code(tmp_path, capsys):
    cur = write_jsonl(tmp_path / "cur.jsonl", [BENCH_ROW])
    base = write_jsonl(tmp_path / "base.jsonl",
                       [{"metric": BENCH_ROW["metric"], "value": 9000.0}])
    rc = report.main([cur, "--baseline", base])
    assert rc == 2
    out = capsys.readouterr().out
    assert "REGRESSION" in out
    # threshold loose enough -> ok, exit 0
    ok_base = write_jsonl(tmp_path / "ok.jsonl",
                          [{"metric": BENCH_ROW["metric"], "value": 766.0}])
    assert report.main([cur, "--baseline", ok_base]) == 0


def test_main_aggregates_baseline_results_default(tmp_path, capsys,
                                                  monkeypatch):
    """With no files, the CLI reads bench/BASELINE_RESULTS.jsonl — the
    pinned measurement history rides the same report path."""
    bench_dir = tmp_path / "bench"
    bench_dir.mkdir()
    write_jsonl(bench_dir / "BASELINE_RESULTS.jsonl", [BENCH_ROW, COMM_ROW])
    monkeypatch.chdir(tmp_path)
    assert report.main([]) == 0
    out = capsys.readouterr().out
    assert BENCH_ROW["metric"] in out and "alltoallv" in out


INGEST_ROWS = [
    {"v": "span.v1", "name": "ingest.parse", "id": 10, "parent": None,
     "t0": 0.0, "dt": 0.4, "attrs": {"chunk": 0, "bytes": 1000}},
    {"v": "span.v1", "name": "ingest.encode", "id": 11, "parent": None,
     "t0": 0.5, "dt": 0.4, "attrs": {"chunk": 0, "bytes": 1000}},
    # transfer [0.7, 1.2) overlaps encode [0.5, 0.9) by 0.2s and the
    # second parse [1.0, 1.3) by 0.2s -> 0.4s total host∩transfer
    {"v": "span.v1", "name": "ingest.transfer", "id": 12, "parent": None,
     "t0": 0.7, "dt": 0.5, "attrs": {"chunk": 0, "bytes": 1000}},
    {"v": "span.v1", "name": "ingest.parse", "id": 13, "parent": None,
     "t0": 1.0, "dt": 0.3, "attrs": {"chunk": 1, "bytes": 500}},
]


def test_ingest_overlap_aggregation(tmp_path):
    """ISSUE 2: the ingest table sums per-stage seconds/bytes and the
    overlap row measures host-stage ∩ transfer wall-clock concurrency
    from span intervals."""
    p = write_jsonl(tmp_path / "ingest.jsonl", INGEST_ROWS)
    agg = report.aggregate(report.load_rows(p))
    assert agg["ingest"]["ingest.parse"]["count"] == 2
    assert agg["ingest"]["ingest.parse"]["seconds"] == pytest.approx(0.7)
    assert agg["ingest"]["ingest.parse"]["bytes"] == 1500
    ov = agg["ingest_overlap"]
    assert ov["overlap_s"] == pytest.approx(0.4)
    assert ov["transfer_s"] == pytest.approx(0.5)
    assert ov["pct"] == pytest.approx(80.0)
    rendered = report.render(agg)
    assert "ingest/egress pipeline" in rendered
    assert "overlap" in rendered


def test_main_require_ingest_overlap_exit_codes(tmp_path, capsys):
    """--require-ingest-overlap: 0 with genuine overlap, 1 when the
    stages ran serially (or no ingest spans exist)."""
    good = write_jsonl(tmp_path / "good.jsonl", INGEST_ROWS)
    assert report.main(["--check", "--require-ingest-overlap", good]) == 0
    out = capsys.readouterr().out
    assert "ingest overlap OK" in out
    serial = [dict(r) for r in INGEST_ROWS]
    for i, r in enumerate(serial):  # push every span onto its own second
        r = dict(r)
        r["t0"] = float(10 * i)
        serial[i] = r
    bad = write_jsonl(tmp_path / "serial.jsonl", serial)
    assert report.main(["--check", "--require-ingest-overlap", bad]) == 1
    assert "NO parse/encode" in capsys.readouterr().err


def test_require_ingest_overlap_ignores_egress(tmp_path, capsys):
    """Egress-only overlap must NOT satisfy the ingest gate: a change
    that serializes stream_to_mesh has to fail `make ingest-selftest`
    even while the egress side still overlaps."""
    rows = [  # serial ingest...
        {"v": "span.v1", "name": "ingest.parse", "id": 1, "parent": None,
         "t0": 0.0, "dt": 0.3, "pid": 7, "attrs": {"bytes": 10}},
        {"v": "span.v1", "name": "ingest.transfer", "id": 2, "parent": None,
         "t0": 0.4, "dt": 0.3, "pid": 7, "attrs": {"bytes": 10}},
        # ...but genuinely overlapped egress
        {"v": "span.v1", "name": "egress.fetch", "id": 3, "parent": None,
         "t0": 1.0, "dt": 0.4, "pid": 7, "attrs": {"bytes": 10}},
        {"v": "span.v1", "name": "egress.decode", "id": 4, "parent": None,
         "t0": 1.2, "dt": 0.4, "pid": 7, "attrs": {"bytes": 10}},
    ]
    p = write_jsonl(tmp_path / "egress_only.jsonl", rows)
    agg = report.aggregate(report.load_rows(p))
    assert agg["ingest_overlap"]["overlap_s"] == 0.0
    assert agg["egress_overlap"]["overlap_s"] == pytest.approx(0.2)
    assert report.main(["--require-ingest-overlap", p]) == 1
    assert "NO parse/encode" in capsys.readouterr().err


def test_ingest_overlap_groups_runs_by_pid(tmp_path):
    """Two serial runs appended to ONE trace file must not manufacture
    overlap: t0 is a process-relative perf_counter clock, so run A's
    host spans and run B's transfers live on unrelated timelines.  The
    aggregator groups intervals per (file, pid)."""
    run_a = [  # fully serial pipeline: parse then transfer, no overlap
        {"v": "span.v1", "name": "ingest.parse", "id": 1, "parent": None,
         "t0": 0.0, "dt": 0.4, "pid": 100, "attrs": {"bytes": 10}},
        {"v": "span.v1", "name": "ingest.transfer", "id": 2, "parent": None,
         "t0": 0.5, "dt": 0.4, "pid": 100, "attrs": {"bytes": 10}},
    ]
    run_b = [  # second run, also serial, clock restarted near zero
        {"v": "span.v1", "name": "ingest.parse", "id": 1, "parent": None,
         "t0": 0.45, "dt": 0.4, "pid": 200, "attrs": {"bytes": 10}},
        {"v": "span.v1", "name": "ingest.transfer", "id": 2, "parent": None,
         "t0": 0.9, "dt": 0.4, "pid": 200, "attrs": {"bytes": 10}},
    ]
    p = write_jsonl(tmp_path / "two_runs.jsonl", run_a + run_b)
    ov = report.aggregate(report.load_rows(p))["ingest_overlap"]
    # cross-run phantom overlap (A.transfer [0.5,0.9) ∩ B.parse
    # [0.45,0.85)) must NOT count — both runs were serial
    assert ov["overlap_s"] == 0.0
    assert ov["transfer_s"] == pytest.approx(0.8)


# ------------------------------------------------- serve SLO table (ISSUE 8)

def _serve_req(i, dt, status="ok", batched=True, n=512):
    return {"v": "span.v1", "name": "serve.request", "id": 100 + i,
            "parent": None, "t0": float(i), "dt": dt, "pid": 1,
            "attrs": {"n": n, "dtype": "int32", "status": status,
                      "batched": batched}}


SERVE_ROWS = (
    [_serve_req(i, dt) for i, dt in
     enumerate([0.010, 0.020, 0.030, 0.040, 0.200])]
    + [_serve_req(9, 0.005, status="backpressure", batched=False),
       _serve_req(10, 0.004, status="integrity", batched=False),
       {"v": "span.v1", "name": "serve.batch", "id": 200, "parent": None,
        "t0": 0.0, "dt": 0.003, "pid": 1,
        "attrs": {"segments": 5, "keys": 2560, "bucket": 4096}},
       {"v": "span.v1", "name": "serve.compile_cache", "id": 201,
        "parent": None, "t0": 0.0, "dt": 0.0, "pid": 1,
        "attrs": {"hit": False, "bucket": 4096, "dtype": "int32",
                  "compile_s": 0.25}},
       {"v": "span.v1", "name": "serve.compile_cache", "id": 202,
        "parent": None, "t0": 0.1, "dt": 0.0, "pid": 1,
        "attrs": {"hit": True, "bucket": 4096, "dtype": "int32"}}])


def test_percentile_nearest_rank():
    vals = sorted([1.0, 2.0, 3.0, 4.0, 100.0])
    assert report.percentile(vals, 50) == 3.0
    assert report.percentile(vals, 99) == 100.0
    assert report.percentile([], 99) == 0.0
    assert report.percentile([7.0], 50) == 7.0


def test_serve_slo_aggregation(tmp_path):
    p = write_jsonl(tmp_path / "serve.jsonl", SERVE_ROWS)
    agg = report.aggregate(report.load_rows(p))
    slo = report.serve_slo(agg["serve"])
    # errors are error-budget lines, never latency samples
    assert slo["requests"] == 7 and slo["ok"] == 5
    assert slo["errors"] == {"backpressure": 1, "integrity": 1}
    assert slo["batched"] == 5
    assert slo["p50_ms"] == pytest.approx(30.0)
    assert slo["p99_ms"] == pytest.approx(200.0)
    assert slo["batches"] == 1 and slo["batch_segments"] == 5
    assert slo["cache_hits"] == 1 and slo["cache_misses"] == 1
    assert slo["compile_s"] == pytest.approx(0.25)
    rendered = report.render(agg)
    assert "sort-as-a-service" in rendered
    assert "p99 200.0 ms" in rendered
    assert "backpressure=1" in rendered


def test_serve_slo_absent_without_serve_spans(tmp_path):
    p = write_jsonl(tmp_path / "plain.jsonl", SPAN_ROWS)
    agg = report.aggregate(report.load_rows(p))
    assert report.serve_slo(agg["serve"]) is None
    assert "sort-as-a-service" not in report.render(agg)
