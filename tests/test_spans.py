"""Span-tracing layer tests: nesting/ordering, Chrome trace golden,
SORT_TRACE stream, and the per-pass/per-collective acceptance contract
(ISSUE 1): a radix run must emit >= one span per radix pass and per
collective, each collective with byte counts, and the Chrome export must
be valid trace-event JSON."""

import json

import numpy as np
import pytest

from mpitest_tpu.utils import spans
from mpitest_tpu.utils.spans import MPI_EQUIV, SpanLog
from mpitest_tpu.utils.trace import Tracer


def test_span_nesting_and_ordering():
    log = SpanLog()
    with log.span("outer", kind="test"):
        log.event("point", bytes=7)
        with log.span("inner"):
            pass
        with log.span("inner"):  # second occurrence keeps its own id
            pass
    names = [s.name for s in log.spans]
    assert names == ["outer", "point", "inner", "inner"]
    outer, point, in1, in2 = log.spans
    assert outer.parent is None
    assert point.parent == outer.id and point.dt == 0.0
    assert in1.parent == outer.id and in2.parent == outer.id
    assert in1.id != in2.id
    # ids are allocated in creation order; dt only set on close
    assert [s.id for s in log.spans] == sorted(s.id for s in log.spans)
    assert outer.dt >= in1.dt >= 0.0


def test_active_log_registry():
    """Module-level emit() reaches the log whose outermost span is open,
    and is a no-op outside one — the hook collectives.py relies on."""
    spans.emit("orphan", bytes=1)  # no active log: silently dropped
    log = SpanLog()
    assert spans.current_log() is None
    with log.span("outer"):
        assert spans.current_log() is log
        spans.emit("collected", bytes=2)
        with log.span("inner"):   # nested spans don't re-register
            assert spans.current_log() is log
    assert spans.current_log() is None
    assert [s.name for s in log.spans] == ["outer", "collected", "inner"]


def test_chrome_trace_golden(monkeypatch):
    """Deterministic clock -> byte-exact Chrome trace-event export."""
    ticks = iter([1.0, 1.25, 2.0, 3.5])  # open, event, open, closes...
    monkeypatch.setattr(spans.time, "perf_counter",
                        lambda: next(ticks, 4.0))
    log = SpanLog()
    with log.span("run", n=8):
        log.event("coll", bytes=64)
        with log.span("step"):
            pass
    got = log.to_chrome_trace()
    assert got == {
        "traceEvents": [
            {"name": "process_name", "ph": "M", "pid": 1, "tid": 1,
             "args": {"name": "mpitest_tpu"}},
            {"name": "run", "ph": "X", "pid": 1, "tid": 1,
             "ts": 1.0e6, "dur": 3.0e6,
             "args": {"n": 8, "span_id": 0}},
            {"name": "coll", "ph": "i", "s": "t", "pid": 1, "tid": 1,
             "ts": 1.25e6, "args": {"bytes": 64, "span_id": 1,
                                    "parent_id": 0}},
            {"name": "step", "ph": "X", "pid": 1, "tid": 1,
             "ts": 2.0e6, "dur": 1.5e6,
             "args": {"span_id": 2, "parent_id": 0}},
        ],
        "displayTimeUnit": "ms",
    }
    # and it is serializable JSON (what a .json file for Perfetto needs)
    json.loads(json.dumps(got))


def test_jsonl_roundtrip_and_stream(tmp_path):
    stream = tmp_path / "stream.jsonl"
    log = SpanLog(stream_path=str(stream))
    with log.span("outer"):
        log.event("e", bytes=3)
    lines = [json.loads(line) for line in stream.read_text().splitlines()]
    # streamed in COMPLETION order: the event closes before the outer span
    assert [o["name"] for o in lines] == ["e", "outer"]
    assert all(o["v"] == spans.SCHEMA for o in lines)
    # dump() appends the same records in creation order
    full = tmp_path / "full.jsonl"
    log.dump(str(full))
    lines2 = [json.loads(line) for line in full.read_text().splitlines()]
    assert [o["name"] for o in lines2] == ["outer", "e"]


@pytest.fixture
def radix_traced(mesh8, rng):
    """One traced radix sort on the 8-device mesh with a FRESH program
    (unique n so the jit cache can't have it), returning the tracer."""
    from mpitest_tpu.models.api import sort

    x = rng.integers(-(2**31), 2**31 - 1, size=8 * 1096, dtype=np.int32)
    tracer = Tracer()
    out = sort(x, algorithm="radix", mesh=mesh8, digit_bits=16,
               tracer=tracer)
    np.testing.assert_array_equal(out, np.sort(x))
    return tracer


def test_radix_run_span_contract(radix_traced):
    """The ISSUE 1 acceptance criterion: >= one span per radix pass and
    per collective, byte counts on every collective span."""
    sp = radix_traced.spans.spans
    passes = [s for s in sp if s.name == "radix_pass"]
    # full-range int32 at 16-bit digits = 2 passes
    assert [p.attrs["pass_index"] for p in passes] == [1, 2]
    colls = [s for s in sp if s.name in MPI_EQUIV]
    assert len(colls) >= 4  # exscan all_gather + exchange, per pass
    for c in colls:
        assert c.attrs["bytes"] > 0
    a2a = [s for s in sp if s.name == "ragged_all_to_all"]
    assert len(a2a) == len(passes)  # one exchange per pass
    for s in a2a:
        assert s.attrs["ranks"] == 8 and s.attrs["wire_bytes"] > 0
    # every collective nests under a pass span; passes under the jit
    # span.  Capacity-negotiation probe collectives (ISSUE 7) are the
    # registered exception: they nest under negotiate_probe, which has
    # no pass (the probe runs before any pass exists).
    byid = {s.id: s for s in sp}
    for c in colls:
        chain = []
        p = c.parent
        while p is not None:
            chain.append(byid[p].name)
            p = byid[p].parent
        assert "sort" in chain
        if "negotiate_probe" in chain:
            continue
        assert "radix_pass" in chain
    # the totals aggregate on the shared comm.h vocabulary
    totals = radix_traced.spans.collective_totals()
    assert totals["alltoallv"]["calls"] == len(a2a)
    assert totals["allgather"]["bytes"] > 0


def test_compile_vs_execute_split(mesh8, rng):
    """First call of a program records jit_compile_execute; a warm rerun
    of the SAME program records jit_execute and re-emits no trace-time
    collective spans (they are per-compile records)."""
    from mpitest_tpu.models.api import sort

    x = rng.integers(-(2**31), 2**31 - 1, size=8 * 1097, dtype=np.int32)
    t1, t2 = Tracer(), Tracer()
    sort(x, algorithm="radix", mesh=mesh8, digit_bits=16, tracer=t1)
    sort(x, algorithm="radix", mesh=mesh8, digit_bits=16, tracer=t2)
    names1 = {s.name for s in t1.spans.spans}
    names2 = {s.name for s in t2.spans.spans}
    assert "jit_compile_execute" in names1
    assert "jit_execute" in names2 and "jit_compile_execute" not in names2
    assert "ragged_all_to_all" in names1
    assert "ragged_all_to_all" not in names2
    assert t1.counters.get("jit_first_calls", 0) >= 1
    assert "jit_first_calls" not in t2.counters


def test_sort_trace_env_streams_jsonl(tmp_path, mesh8, rng, monkeypatch):
    """SORT_TRACE=<path> streams a schema-clean JSONL file from a plain
    library sort() call — no CLI needed (the acceptance's 'SORT_TRACE
    run')."""
    from mpitest_tpu import report
    from mpitest_tpu.models.api import sort

    path = tmp_path / "trace.jsonl"
    monkeypatch.setenv("SORT_TRACE", str(path))
    x = rng.integers(-(2**31), 2**31 - 1, size=8 * 1098, dtype=np.int32)
    sort(x, algorithm="sample", mesh=mesh8)
    rows = report.load_rows(str(path))
    assert rows and all(r["kind"] == "span" for r in rows)
    assert report.check_rows(rows) == []
    names = {r["name"] for r in rows}
    assert "sort" in names and "splitter_round" in names
    assert "ragged_all_to_all" in names  # the sample exchange


def test_tracer_phase_spans():
    t = Tracer()
    with t.phase("alpha"):
        with t.phase("beta"):
            pass
    assert "alpha" in t.phases and "beta" in t.phases
    names = [s.name for s in t.spans.spans]
    assert names == ["phase:alpha", "phase:beta"]
    assert t.spans.spans[1].parent == t.spans.spans[0].id
